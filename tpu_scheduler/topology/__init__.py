"""Topology-aware gang placement — interconnect distance model + locality
scoring (ROADMAP "Topology- and gang-aware placement").

``model.py`` declares the TPU slice / rack interconnect hierarchy (from node
labels or a ``--topology-file`` spec) and compiles it per node set;
``locality.py`` packs the per-cycle tensors and provides the fused
rank-aware co-placement score term both batched backends share.
"""

from .locality import (
    SCORING_KNOBS,
    TopologySet,
    gang_placement_stats,
    gang_state_update,
    gang_topology_term,
    pack_topology,
)
from .model import (
    DEFAULT_LEVEL_KEYS,
    CompiledTopology,
    TopologyLevel,
    TopologyModel,
    load_topology_file,
)

__all__ = [
    "CompiledTopology",
    "DEFAULT_LEVEL_KEYS",
    "SCORING_KNOBS",
    "TopologyLevel",
    "TopologyModel",
    "TopologySet",
    "gang_placement_stats",
    "gang_state_update",
    "gang_topology_term",
    "load_topology_file",
    "pack_topology",
]
