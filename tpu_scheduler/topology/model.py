"""Declarative interconnect-topology model.

A cluster's interconnect hierarchy is a sequence of *levels*, finest first
(e.g. TPU slice → rack): two nodes in the same slice communicate over ICI,
two slices in one rack over the rack fabric, anything further over the pod
spine.  The model is data — loadable from node labels (the kube-native way:
every node advertises its domain per level) or from a ``--topology-file``
JSON spec for clusters whose labels don't carry it — and compiles per node
set into the arrays the scoring path consumes:

  • per-level membership: ``dom_id[l][N]`` int32 domain ids (masks via
    one-hot, built in locality.pack_topology), and
  • a symmetric ``[N, N]`` node-distance tensor (``distance_matrix()``):
    ``dist(a, b) = Σ_l d_l · [dom_l(a) ≠ dom_l(b)]`` — the number of
    hierarchy levels two nodes do NOT share, weighted by each level's
    ``distance`` contribution.  Same slice → 0; same rack, different
    slice → d_slice; different rack → d_slice + d_rack.

The solve path never materializes the [N, N] tensor on device: the
distance-to-placed-ranks sum factors through the per-level membership
one-hots (see locality.gang_topology_term), which is algebraically identical
and keeps device memory O(G·N + D·N) instead of O(N²) at flagship node
counts.  ``distance_matrix()`` serves the host-side consumers — scorecard
locality verdicts, the debug API, and bench reporting — where N is small or
the cost is off the cycle clock.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

import numpy as np

__all__ = [
    "DEFAULT_LEVEL_KEYS",
    "CompiledTopology",
    "TopologyLevel",
    "TopologyModel",
    "load_topology_file",
]

# Default node-label keys per hierarchy level, finest first.  A cluster
# advertising either key topology-enables itself (TopologyModel.detect);
# levels whose key no node carries are dropped from the compiled model.
DEFAULT_LEVEL_KEYS = (
    ("slice", "topology.tpu-scheduler/slice"),
    ("rack", "topology.tpu-scheduler/rack"),
)


@dataclass(frozen=True)
class TopologyLevel:
    """One hierarchy level: its name, the node-label key that carries the
    node's domain at this level (None for spec-file-only models), and the
    distance contributed when two nodes differ at this level."""

    name: str
    key: str | None = None
    distance: float = 1.0


@dataclass(frozen=True)
class TopologyModel:
    """The declarative model: ordered levels (finest first) plus an optional
    explicit node → {level name → domain} map (spec files).  Labels win for
    levels with a ``key``; the explicit map covers the rest."""

    levels: tuple[TopologyLevel, ...]
    node_domains: dict = field(default_factory=dict)

    # shape: (level_keys: obj) -> obj
    @staticmethod
    def from_node_labels(level_keys=DEFAULT_LEVEL_KEYS) -> "TopologyModel":
        """Model whose domains come entirely from node labels."""
        return TopologyModel(levels=tuple(TopologyLevel(name=n, key=k) for n, k in level_keys))

    # shape: (nodes: obj, level_keys: obj) -> obj
    @staticmethod
    def detect(nodes, level_keys=DEFAULT_LEVEL_KEYS) -> "TopologyModel | None":
        """Auto-detection for ``--topology auto``: a model over the default
        label keys, or None when NO node advertises any of them — an
        unlabeled cluster stays topology-blind instead of degenerating to
        per-node singleton domains."""
        present = set()
        for node in nodes:
            labels = node.metadata.labels or {}
            for name, key in level_keys:
                if key in labels:
                    present.add(name)
        if not present:
            return None
        return TopologyModel(
            levels=tuple(TopologyLevel(name=n, key=k) for n, k in level_keys if n in present)
        )

    # shape: (spec: dict) -> obj
    @staticmethod
    def from_spec(spec: dict) -> "TopologyModel":
        """Build from a parsed ``--topology-file`` spec::

            {"levels": [{"name": "slice", "key": "...", "distance": 1.0}, ...],
             "nodes": {"node-1": {"slice": "s0", "rack": "r0"}, ...}}

        ``key`` and ``distance`` are optional per level; ``nodes`` is
        optional (label-only specs just pin the level order/weights)."""
        levels = tuple(
            TopologyLevel(
                name=entry["name"],
                key=entry.get("key"),
                distance=float(entry.get("distance", 1.0)),
            )
            for entry in spec.get("levels", ())
        )
        if not levels:
            raise ValueError("topology spec declares no levels")
        return TopologyModel(levels=levels, node_domains=dict(spec.get("nodes", {})))

    # shape: (nodes: obj) -> obj
    def compile(self, nodes) -> "CompiledTopology":
        """Resolve every node's domain per level against this node set.

        Resolution order: explicit spec map, then the level's label key.  A
        node with neither gets a singleton domain (``~<node>``): it is
        maximally far from everything at that level — conservative for
        locality (never accidentally co-located), and visible in the stats
        rather than silently dropped."""
        names = tuple(n.metadata.name for n in nodes)
        dom_names: list[tuple[str, ...]] = []
        dom_ids: list[np.ndarray] = []
        dom_counts: list[int] = []
        for lv in self.levels:
            vocab: dict[str, int] = {}
            ids = np.zeros((len(names),), dtype=np.int32)
            per_node: list[str] = []
            for i, node in enumerate(nodes):
                spec_doms = self.node_domains.get(node.metadata.name)
                dom = spec_doms.get(lv.name) if spec_doms else None
                if dom is None and lv.key is not None:
                    dom = (node.metadata.labels or {}).get(lv.key)
                if dom is None:
                    dom = f"~{node.metadata.name}"
                if dom not in vocab:
                    vocab[dom] = len(vocab)
                ids[i] = vocab[dom]
                per_node.append(dom)
            dom_ids.append(ids)
            dom_counts.append(len(vocab))
            dom_names.append(tuple(per_node))
        return CompiledTopology(
            model=self,
            node_names=names,
            dom_ids=tuple(dom_ids),
            dom_counts=tuple(dom_counts),
            node_domain_names=tuple(dom_names),
        )


@dataclass(frozen=True)
class CompiledTopology:
    """One model resolved against one node set (order = snapshot order)."""

    model: TopologyModel
    node_names: tuple[str, ...]
    # Per level: [N] int32 domain id, domain count, and the per-node domain
    # NAME tuple (host-side consumers key on names, not ids).
    dom_ids: tuple
    dom_counts: tuple
    node_domain_names: tuple
    _dist: object = field(default=None, compare=False, repr=False)
    _row: object = field(default=None, compare=False, repr=False)

    @property
    def n_levels(self) -> int:
        return len(self.model.levels)

    # shape: (self: obj) -> obj
    def level_distances(self) -> np.ndarray:
        """[Lv] float32 distance contribution per level."""
        return np.asarray([lv.distance for lv in self.model.levels], dtype=np.float32)

    # shape: (name: str) -> obj
    def domains_of(self, name: str) -> tuple | None:
        """The node's (finest → coarsest) domain names, or None if unknown."""
        if self._row is None:
            object.__setattr__(self, "_row", {n: i for i, n in enumerate(self.node_names)})
        i = self._row.get(name)
        if i is None:
            return None
        return tuple(doms[i] for doms in self.node_domain_names)

    # shape: (self: obj) -> [N, N] f32
    def distance_matrix(self) -> np.ndarray:
        """The symmetric [N, N] node-distance tensor (lazy, memoized):
        ``Σ_l d_l · [dom_l(a) ≠ dom_l(b)]``.  Host-side consumers only —
        the device solve path uses the factored per-level form
        (locality.gang_topology_term), which is algebraically identical."""
        if self._dist is None:
            n = len(self.node_names)
            dist = np.zeros((n, n), dtype=np.float32)
            for ids, lv in zip(self.dom_ids, self.model.levels):
                dist += np.float32(lv.distance) * (ids[:, None] != ids[None, :])
            object.__setattr__(self, "_dist", dist)
        return self._dist


# shape: (path: str) -> obj
def load_topology_file(path: str) -> TopologyModel:
    """Parse a ``--topology-file`` JSON spec into a model."""
    with open(path) as f:
        return TopologyModel.from_spec(json.load(f))
