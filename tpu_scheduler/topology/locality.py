"""Rank-aware gang co-placement scoring — the fused locality term.

A gang (an MPI-style training job's workers) is only as fast as its slowest
link, so placement quality IS communication performance: every pair of
members split across racks pays the spine.  This module turns the compiled
topology (model.py) plus the cycle's gang membership into ONE per-round
additive score tensor ``T[G+1, N]`` shared by every member of a gang, so the
whole term costs a per-block row gather inside the existing pods×nodes score
path (ops/score.py) — batched over ALL ranks at once, no per-rank Python
loop on either backend.

Three components, all per (gang, node), recomputed each auction round from
the loop-carried placement state:

  anchor   −w·Σ_l d_l·(placed_total_g − same_l[g, n]) — the distance from
           node n to every already-placed member of g, factored through the
           per-level membership one-hots (identical to multiplying by the
           [N, N] distance matrix, without materializing it on device);
  fit      +w·Σ_l d_l·fits_l[g, dom_l(n)] — the gang's remaining demand
           fits the node's level-l domain whole.  Because a finer domain's
           free capacity is a subset of its parent's, a node whose SLICE
           fits the gang collects the slice AND rack bonuses — automatic
           preference for the finest domain that can take the whole gang;
  herd     +w·Σ_l d_l·tb_l[g, dom_l(n)] — a deterministic per-(gang,
           domain) tie-break in [0, 1) (crc32, no PYTHONHASHSEED exposure)
           shared by every member, so on the FIRST round — before any
           anchor exists — all members rank fitting domains identically and
           converge on one, instead of scattering across near-ties by the
           per-pod jitter hash.

``w`` is the profile's ``gang_locality_weight`` (weights[6]); at its
default the term dominates the packing score for gang members — intended:
for tightly-coupled workloads, locality outranks bin-packing aesthetics.
Pods outside any gang ride row 0 of T, which is pinned to zero: the term is
score-neutral for everything else.

Demand/capacity fit uses cpu+memory in float32 — a scoring heuristic, never
a validity decision (the feasibility mask and the accept prefix-sum stay
exact int32), so float rounding can only nudge a bonus, not oversubscribe.
"""

from __future__ import annotations

import zlib
from dataclasses import dataclass

import numpy as np

__all__ = [
    "SCORING_KNOBS",
    "TopologySet",
    "gang_placement_stats",
    "gang_state_update",
    "gang_topology_term",
    "pack_topology",
]

# The profile knobs this subsystem reads (drift-gated into the README
# "Topology & gang placement" catalogue by the TOPO analyze rule).
SCORING_KNOBS = ("gang_locality_weight",)

# Component scales inside the term (all further multiplied by the profile's
# gang_locality_weight).  The ordering invariant that makes convergence
# robust: ANCHOR > max herd spread > fit > per-pod jitter — once any member
# is placed, no herd tie-break can pull the rest of the gang to a different
# domain (a demand that shrank mid-admission may open a "better-hashed"
# rack; the anchor must still win), while before any placement the herd
# spread dominates base-score differences between near-tied fitting
# domains.  With 2 levels and w=64: anchor ≥ 64·16·1 = 1024 per placed
# member per level crossed vs max herd+fit = 64·(1+4)·2 = 640.
ANCHOR_SCALE = 16.0
HERD_SCALE = 4.0


@dataclass(frozen=True)
class TopologySet:
    """Per-cycle topology tensors for one packed cluster (the topology twin
    of ops/constraints.ConstraintSet).  Pod rows align with PackedCluster's
    pending order (padded to P); node columns with its node order (padded to
    N, padding nodes in per-level sentinel domains that never fit)."""

    pod_gang_id: np.ndarray  # [P] int32 — 0 = no gang, 1..G
    # meta (static per cycle): per level l in 0..Lv-1:
    #   dom_id_l   [N]        int32   node's domain id (D_l = padding sentinel)
    #   dom_onehot_l [D_l+1, N] f32   domain membership rows
    #   gang_tb_l  [G+1, D_l+1] f32  per-(gang, domain) herd tie-break [0,1)
    # plus level_dist [Lv] f32.
    meta: dict
    n_gangs: int
    gang_names: tuple[str, ...]  # 1-based: gang_names[g-1] is gang id g
    compiled: object  # the CompiledTopology (host-side consumers)

    # shape: (self: obj) -> dict
    def meta_arrays(self) -> dict:
        return self.meta

    # shape: (self: obj) -> dict
    def pod_arrays(self) -> dict:
        return {"pod_gang_id": self.pod_gang_id}

    # shape: (self: obj) -> dict
    def state_arrays(self) -> dict:
        """Round-start loop-carry state: per-(gang, node) placed-member
        counts.  Column N is the non-claimant sentinel (ops/assign.py uses
        node index n for pods with no accepted choice), row 0 the no-gang
        dump — both never read back."""
        n = self.meta["dom_id_0"].shape[0]
        return {"gang_nodes": np.zeros((self.n_gangs + 1, n + 1), dtype=np.float32)}


# shape: (gang: str, level: int, dom: int) -> float
def _herd_tb(gang: str, level: int, dom: int) -> float:
    """Deterministic per-(gang, level, domain) tie-break in [0, 1) — crc32,
    so it is stable across processes, backends, and replays."""
    return zlib.crc32(f"{gang}|{level}|{dom}".encode()) / 4294967296.0


# shape: (compiled: obj, pending: obj, p_pad: int, node_names: obj, n_pad: int) -> obj
def pack_topology(compiled, pending, p_pad: int, node_names: tuple[str, ...], n_pad: int) -> TopologySet | None:
    """Build the cycle's TopologySet, or None when no pending pod declares a
    gang (the term would be all-zero; skipping keeps gangless cycles free).

    ``compiled`` node order must cover ``node_names`` (same snapshot);
    padding rows/columns get gang 0 / per-level sentinel domains."""
    gang_ids = np.zeros((p_pad,), dtype=np.int32)
    gang_names: list[str] = []
    by_name: dict[str, int] = {}
    for i, pod in enumerate(pending):
        g = pod.spec.gang if pod.spec is not None else None
        if not g:
            continue
        gid = by_name.get(g)
        if gid is None:
            gang_names.append(g)
            by_name[g] = gid = len(gang_names)  # 1-based
        gang_ids[i] = gid
    if not gang_names:
        return None

    row = {n: i for i, n in enumerate(compiled.node_names)}
    gather = np.asarray([row[n] for n in node_names], dtype=np.intp)
    n_real = len(node_names)
    g1 = len(gang_names) + 1
    meta: dict[str, np.ndarray] = {"level_dist": compiled.level_distances()}
    for l_idx in range(compiled.n_levels):
        d = int(compiled.dom_counts[l_idx])
        dom_id = np.full((n_pad,), d, dtype=np.int32)  # padding → sentinel
        dom_id[:n_real] = compiled.dom_ids[l_idx][gather]
        onehot = np.zeros((d + 1, n_pad), dtype=np.float32)
        onehot[dom_id, np.arange(n_pad)] = 1.0
        tb = np.zeros((g1, d + 1), dtype=np.float32)
        for g, name in enumerate(gang_names, start=1):
            for dom in range(d):  # sentinel column stays 0 (never fits anyway)
                tb[g, dom] = _herd_tb(name, l_idx, dom)
        meta[f"dom_id_{l_idx}"] = dom_id
        meta[f"dom_onehot_{l_idx}"] = onehot
        meta[f"gang_tb_{l_idx}"] = tb
    return TopologySet(
        pod_gang_id=gang_ids,
        meta=meta,
        n_gangs=len(gang_names),
        gang_names=tuple(gang_names),
        compiled=compiled,
    )


# shape: (gang_nodes: [G, M] f32, meta: dict, avail: [N, R] i32,
#   pod_gang_id: [P] i32, pod_req: [P, R] i32, active: [P] bool,
#   weight: scalar f32) -> [G, N] f32
def gang_topology_term(xp, gang_nodes, meta, avail, pod_gang_id, pod_req, active, weight):
    """The per-round [G+1, N] additive score tensor (module docstring).

    ``gang_nodes`` is the loop-carried [G+1, N+1] placed-member count (its
    sentinel column is sliced off here); ``avail``/``pod_req``/``active``
    are the round's live capacity and pod state — remaining gang demand is
    derived from them, so nothing else needs to ride the loop carry.
    xp-generic (numpy / jax.numpy): one expression tree for both backends,
    and jit-pure (no host syncs) for the device path.
    """
    f32 = xp.float32
    n = avail.shape[0]
    placed = gang_nodes[:, :n]  # [G+1, N] — drop the sentinel column
    g1 = placed.shape[0]
    level_dist = meta["level_dist"]
    n_levels = level_dist.shape[0]
    # Remaining demand of each gang's still-active members (cpu, mem) —
    # float32 on purpose: a scoring heuristic, never a validity decision.
    live_req = xp.where(active[:, None], pod_req[:, :2], 0).astype(f32)  # [P, 2]
    rem = xp.zeros((g1, 2), f32)
    if xp is np:
        np.add.at(rem, pod_gang_id, live_req)
    else:
        rem = rem.at[pod_gang_id].add(live_req)
    free = xp.maximum(avail[:, :2], 0).astype(f32)  # [N, 2]
    total = placed.sum(axis=1, keepdims=True)  # [G+1, 1]

    t = xp.zeros((g1, n), f32)
    for l_idx in range(n_levels):
        d_l = level_dist[l_idx]
        dom_id = meta[f"dom_id_{l_idx}"]  # [N] i32
        onehot = meta[f"dom_onehot_{l_idx}"]  # [D+1, N] f32
        # anchor: same-level placed count per (gang, node) via the one-hot
        # factoring of the [N, N] distance matrix.
        same = (placed @ onehot.T)[:, dom_id]  # [G+1, N]
        t = t - (f32(ANCHOR_SCALE) * d_l) * (total - same)
        # fit: remaining demand vs the node's level-l domain free capacity.
        dom_free = onehot @ free  # [D+1, 2]
        fits = (rem[:, None, :] <= dom_free[None, :, :]).all(-1).astype(f32)  # [G+1, D+1]
        # herd: the per-(gang, domain) shared tie-break rides only on
        # FITTING domains — a domain that cannot take the gang whole must
        # not attract it.
        t = t + d_l * ((fits * (f32(1.0) + f32(HERD_SCALE) * meta[f"gang_tb_{l_idx}"]))[:, dom_id])
    # Row 0 (no gang) pinned to zero: score-neutral for gangless pods.
    t = xp.where((xp.arange(g1) > 0)[:, None], weight * t, f32(0.0))
    return t.astype(f32)


# shape: (gang_nodes: [G, M] f32, accepted: [P] bool, choice: [P] i32,
#   pod_gang_id: [P] i32) -> [G, M] f32
def gang_state_update(xp, gang_nodes, accepted, choice, pod_gang_id):
    """Commit a round's accepted placements into the [G+1, N+1] per-(gang,
    node) count state.  ``choice`` may carry the non-claimant sentinel N
    (lands in the sentinel column, never read back); gangless pods land in
    row 0 (same).  xp-generic and jit-pure."""
    acc = accepted.astype(xp.float32)
    if xp is np:
        out = gang_nodes.copy()
        np.add.at(out, (pod_gang_id, choice), acc)
        return out
    return gang_nodes.at[pod_gang_id, choice].add(acc)


# shape: (member_domains: obj, level_dists: obj) -> dict
def gang_placement_stats(member_domains, level_dists) -> dict:
    """Pairwise placement-distance statistics for ONE gang's placed members.

    ``member_domains``: per member, the (finest → coarsest) domain-name
    tuple of its node (CompiledTopology.domains_of); ``level_dists`` the
    matching per-level distance contributions.  Returns max/mean pairwise
    distance plus ``cross_edges`` — the pair count differing at the
    COARSEST level (the "cross-rack edge" count the scorecard gates on).
    Host-side only (scorecard, debug API, bench, controller metrics)."""
    k = len(member_domains)
    pairs = 0
    dist_sum = 0.0
    dist_max = 0.0
    cross = 0
    for i in range(k):
        for j in range(i + 1, k):
            pairs += 1
            d = 0.0
            for lvl, w in enumerate(level_dists):
                if member_domains[i][lvl] != member_domains[j][lvl]:
                    d += float(w)
            dist_sum += d
            dist_max = max(dist_max, d)
            if member_domains[i][-1] != member_domains[j][-1]:
                cross += 1
    return {
        "members": k,
        "pairs": pairs,
        "max_distance": round(dist_max, 6),
        "mean_distance": round(dist_sum / pairs, 6) if pairs else 0.0,
        "cross_edges": cross,
    }
