"""Deterministic cluster simulator + chaos harness.

The reference scheduler was built to be DRIVEN by a cluster simulator
(acrlabs wrote it as the reference pod scheduler for SimKube-style
experiments); this package delivers that substrate in-process: a seeded
discrete-event simulation layered on the injectable-clock seams the runtime
already carries (``FakeApiServer(clock=...)``, ``Scheduler(clock=...)``).

Modules:
  • ``clock``     — ``VirtualClock``: virtual time that advances to the next
                    scheduled event instead of sleeping
  • ``workload``  — seeded workload generator: Poisson/burst pod arrivals,
                    gang jobs, priority tiers, pod lifetimes, node churn
                    (add / drain / fail / flap), all from ONE rng seed
  • ``chaos``     — ``ChaosApiServer``: a programmable fault layer wrapping
                    ``FakeApiServer`` (binding 500s, binding latency, API
                    errors, watch drops, 410 Gone storms, timed fault
                    windows) — the generalization of the one-off
                    ``fail_next_bindings`` hook and the tests' ``FlakyWatch``
  • ``trace``     — JSONL record/replay of the applied event stream plus the
                    chaos decision schedule (bit-identical replays)
  • ``scorecard`` — the global invariants I1–I4 (tests/test_stress.py) plus
                    virtual-time SLOs, emitted as one JSON verdict
  • ``scenarios`` — the named scenario registry (steady-state, burst-storm,
                    node-flap, api-brownout, gang-heavy, sim-smoke)
  • ``harness``   — the discrete-event loop wiring all of the above around a
                    real ``Scheduler``
  • ``cli``       — ``python -m tpu_scheduler.cli sim --scenario X --seed N``
"""

from .chaos import ChaosApiServer, ChaosConfig, ChaosWindow
from .clock import VirtualClock
from .harness import run_scenario
from .scenarios import SCENARIOS, Scenario
from .workload import WorkloadSpec

__all__ = [
    "ChaosApiServer",
    "ChaosConfig",
    "ChaosWindow",
    "VirtualClock",
    "run_scenario",
    "SCENARIOS",
    "Scenario",
    "WorkloadSpec",
]
