"""Virtual time — the simulator's clock.

A ``VirtualClock`` is a drop-in replacement for ``time.monotonic`` at every
injectable-clock seam the runtime carries (``Scheduler(clock=...)``,
``FakeApiServer(clock=...)``, ``Reflector(clock=...)``): calling it returns
the current VIRTUAL time, and ``sleep``/``advance`` move that time forward
instantly instead of blocking — a simulated hour of watch backoff and
requeue waits costs microseconds of wall clock.

It is also a minimal discrete-event engine: callbacks scheduled with
``schedule``/``schedule_in`` fire IN TIMESTAMP ORDER while the clock
advances past them (ties break by scheduling order), with ``now`` set to
each callback's own due time while it runs — the invariant every
discrete-event simulation rests on.
"""

from __future__ import annotations

import heapq

__all__ = ["VirtualClock"]


class VirtualClock:
    """Deterministic virtual time source + event queue (single-threaded)."""

    def __init__(self, start: float = 0.0):
        self._now = float(start)
        self._heap: list[tuple[float, int, object]] = []
        self._seq = 0  # FIFO tie-break for equal timestamps

    # -- the time.monotonic surface ----------------------------------------

    def __call__(self) -> float:
        return self._now

    @property
    def now(self) -> float:
        return self._now

    def sleep(self, seconds: float) -> None:
        """``time.sleep`` twin: advance virtual time (firing due events)."""
        self.advance(seconds)

    # -- event scheduling ---------------------------------------------------

    def schedule(self, at: float, fn) -> None:
        """Run ``fn()`` when the clock advances to/past virtual time ``at``.
        An ``at`` in the past fires on the next advance (at current time)."""
        self._seq += 1
        heapq.heappush(self._heap, (max(at, self._now), self._seq, fn))

    def schedule_in(self, delay: float, fn) -> None:
        self.schedule(self._now + delay, fn)

    def next_event_at(self) -> float | None:
        """Due time of the earliest scheduled event (None when idle)."""
        return self._heap[0][0] if self._heap else None

    # -- advancing ----------------------------------------------------------

    def advance(self, seconds: float) -> None:
        if seconds < 0:
            raise ValueError(f"cannot advance virtual time by {seconds}")
        self.advance_to(self._now + seconds)

    def advance_to(self, t: float) -> None:
        """Move time forward to ``t``, firing every event due on the way (in
        timestamp order, ``now`` pinned to each event's due time while its
        callback runs — callbacks may schedule further events, including
        ones due before ``t``)."""
        if t < self._now:
            raise ValueError(f"virtual time cannot move backwards ({t} < {self._now})")
        while self._heap and self._heap[0][0] <= t:
            at, _seq, fn = heapq.heappop(self._heap)
            self._now = at
            fn()
        self._now = t
