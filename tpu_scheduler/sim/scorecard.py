"""Scenario scorecard — one JSON verdict per simulated run.

Runs the framework's global invariants I1–I4 (the same checks
``tests/test_stress.py`` pins, made churn-aware) against the final cluster
state, plus the virtual-time SLOs a placement system is judged on:
time-to-bind percentiles, binding throughput per virtual second, the
pending backlog, and preemption/eviction churn.

Churn-awareness: a placement that was valid when made can look invalid
against the FINAL state after the node was re-tainted/cordoned or the pod's
gang was partially killed by a node failure — those placements are
verifiably disturbed, so I2/I4 skip them (counted, never silent) and I3
skips gangs with a churn-disturbed member.  Capacity (I1) has no such
escape: an oversubscribed node is a scheduler bug under any history.

``SCORECARD_FIELDS`` is the closed top-level schema; ``build_scorecard``
enforces it, and the README "Simulation & chaos" catalogue is drift-gated
against it by ``scripts/lint.py`` (the METR-gate pattern).
"""

from __future__ import annotations

import hashlib
import json

import tpu_scheduler.core.predicates as P
from ..core.snapshot import ClusterSnapshot, node_allocatable, node_used_resources
from ..learn.objective import policy_block

__all__ = [
    "SCORECARD_FIELDS",
    "INCREMENTAL_FIELDS",
    "REBALANCE_FIELDS",
    "ELASTICITY_FIELDS",
    "LATENCY_FIELDS",
    "CONVERGENCE_FIELDS",
    "COMPILE_FIELDS",
    "check_invariants",
    "build_scorecard",
    "build_latency_block",
    "fingerprint",
]

# The closed top-level schema of a scorecard (drift-gated against README.md).
SCORECARD_FIELDS = (
    "scenario",
    "seed",
    "mode",
    "pass",
    "virtual_seconds",
    "cycles",
    "pods",
    "slo",
    "invariants",
    "chaos_injected",
    "resilience",
    "availability",
    "convergence",
    "locality",
    "profile",
    "compile",
    "incremental",
    "rebalance",
    "elasticity",
    "policy",
    "latency",
    "flight_recorder",
    "fingerprint",
)

# The closed schema of the ``incremental`` block (drift-gated against the
# README "Incremental scheduling" catalogue by the DLTA analyze rule).
# Strictly virtual/control-flow quantities: cycle counts, escalation-reason
# counts, dirty-set size percentiles, and the shadow-solve parity verdicts —
# never wall clock, so byte-identity and record→replay hold.
INCREMENTAL_FIELDS = (
    "enabled",
    "required",
    "delta_cycles",
    "full_solves",
    "full_solve_fraction",
    "escalations",
    "dirty_p50",
    "dirty_p95",
    "dirty_max",
    "skipped_pods",
    "standing_verdicts",
    "shadow_checks",
    "shadow_mismatches",
    "shadow_skipped",
    "shadow_parity_ok",
    "ok",
)

# The closed schema of the ``rebalance`` block (drift-gated against the
# README "Rebalancing & defragmentation" catalogue by the REBL analyze
# rule).  Strictly deterministic quantities: lifetime counts from the
# Rebalancer ledger, exact-integer packing stats over the FINAL cluster
# state, and the orphan evidence derived from the chaos unbind log — never
# wall clock, so byte-identity and record→replay hold.
REBALANCE_FIELDS = (
    "enabled",
    "required",
    "solves",
    "migrations",
    "completed",
    "skips",
    "nodes_drained",
    "pressure_releases",
    "unbinds_while_open",
    "orphaned_migrations",
    "packing_efficiency",
    "efficiency_gate",
    "stranded_frac",
    "occupied_nodes",
    "empty_nodes",
    "migration_budget",
    "preemption_churn",
    "whatif",
    "ok",
)


# The closed schema of the ``compile`` block (drift-gated against the
# README "Simulation & chaos" catalogue like every scorecard field).  The
# runtime twin of the JITC static pass (scripts/analyze/jitc.py): bucket
# discipline statically proven bounded must also be DYNAMICALLY flat — the
# XLA compile count (the PR-8 jax.monitoring listener,
# utils/profiler.compile_stats) may grow only during the warmup window
# while shape buckets are first traced; a single post-warmup compile is a
# retrace leak and fails compile-required scenarios.  Deliberately
# environment-robust: the block carries the warmup-window LENGTH (scenario
# config) and the POST-warmup count (0 in any healthy run, warm or cold
# cache) but never the warmup compile count itself — that number differs
# between a cold record and a warm same-process replay, and the scorecard
# must stay bit-identical across record→replay (the same reasoning that
# keeps ``compile`` spans out of the profile block's census).
COMPILE_FIELDS = (
    "enabled",
    "required",
    "warmup_cycles",
    "post_warmup_compiles",
    "steady_flat",
    "ok",
)


# The closed schema of the ``elasticity`` block (drift-gated against the
# README "Autoscaling & elasticity" catalogue by the ELAS analyze rule).
# Strictly deterministic quantities: lifetime counts from the Autoscaler
# and SimCloudProvider ledgers, virtual provisioning lag, the node-hour
# cost integral of elastic capacity, and a joint cost+SLO objective whose
# SLO term charges still-pending pods their unmet age — so the static
# baseline fails the gate on merit.  The reclaim-orphan count (provider
# reclaim unbinds ∪ scale-down drain unbinds that ended pending or lost)
# is REQUIRED zero whenever the block gates at all.
ELASTICITY_FIELDS = (
    "enabled",
    "required",
    "scale_ups",
    "scale_downs",
    "skus",
    "pending_provisions",
    "provision_lag_p99_s",
    "reclaims",
    "reclaim_orphans",
    "quota_errors",
    "stockout_errors",
    "skips",
    "cost_node_hours",
    "joint_objective",
    "objective_gate",
    "ok",
)


# The closed schema of the ``convergence`` block (drift-gated against the
# README "Chaos fuzzing" catalogue by the FUZZ analyze rule).  The fuzzer's
# end-state quiescence oracle: after the last scheduled fault
# (``last_fault_t`` — the latest chaos-window end, replica kill, or rack
# failure) the backlog must drain (``pending_final`` == 0), every LIVE
# replica's deferred-bind buffer must flush (``deferred_residue`` == 0),
# no unexpired shard/replica/gang-reservation lease may be held by a dead
# replica (``stale_leases`` == 0), and the overtime the run spent settling
# past max(duration, last fault) must stay within ``settle_bound_s``.
# Strictly virtual-time quantities — byte-identity and record→replay hold.
CONVERGENCE_FIELDS = (
    "enabled",
    "required",
    "last_fault_t",
    "settle_overtime_s",
    "settle_bound_s",
    "pending_final",
    "deferred_residue",
    "stale_leases",
    "ok",
)


# The closed schema of the ``latency`` block (drift-gated against the README
# "Latency & time-to-bind" catalogue by the LATN analyze rule).  Strictly
# virtual-time quantities: every number derives from scheduler-clock ``t``
# stamps on flight-recorder events plus the harness's arrival ledger — never
# wall clock, so byte-identity and record→replay hold.
LATENCY_FIELDS = (
    "required",
    "ok",
    "measured",
    "coverage",
    "sum_to_ttb_ok",
    "max_sum_error_s",
    "cadence_wait_fraction",
    "segments",
    "tiers",
)


# shape: (samples: obj, bound_total: obj, required: obj, tol: obj) -> obj
def build_latency_block(
    samples: list[tuple[str, dict]],
    bound_total: int | None = None,
    required: bool = False,
    tol: float = 1e-6,
) -> dict:
    """Fold per-pod waterfalls (``utils/events.waterfall`` outputs, paired
    with their SLO tier) into the closed ``latency`` scorecard block.

    The audit that catches attribution leaks: every sample's segments +
    unattributed must sum to its TTB within ``tol`` — a timeline whose
    interval fell through the segment taxonomy fails ``sum_to_ttb_ok`` and,
    on latency-required scenarios, the run.  ``coverage`` (measured /
    bound_total) is reported for the latency-smoke gate but never fails the
    scorecard itself: a pod bound on the final cycle legitimately misses its
    confirm."""
    per_seg: dict[str, list[float]] = {}
    per_tier: dict[str, list[dict]] = {}
    ttbs: list[float] = []
    cadence_sum = 0.0
    max_err = 0.0
    for tier, wf in samples:
        err = abs(sum(wf["segments"].values()) + wf["unattributed"] - wf["ttb"])
        max_err = max(max_err, err)
        ttbs.append(wf["ttb"])
        cadence_sum += wf["segments"].get("cadence-wait", 0.0)
        per_tier.setdefault(tier, []).append(wf)
        for seg, v in wf["segments"].items():
            per_seg.setdefault(seg, []).append(v)

    def pcts(vals: list[float]) -> dict:
        s = sorted(vals)
        return {"p50_s": round(_percentile(s, 0.50), 6), "p99_s": round(_percentile(s, 0.99), 6)}

    measured = len(samples)
    ttb_total = sum(ttbs)
    sum_ok = max_err <= tol
    tiers = {
        tier: {
            "count": len(wfs),
            "ttb": pcts([w["ttb"] for w in wfs]),
            "segments": {seg: pcts([w["segments"][seg] for w in wfs]) for seg in sorted(per_seg)},
        }
        for tier, wfs in sorted(per_tier.items())
    }
    block = {
        "required": bool(required),
        "ok": sum_ok and (measured > 0 or not required),
        "measured": measured,
        "coverage": round(measured / bound_total, 6) if bound_total else None,
        "sum_to_ttb_ok": sum_ok,
        "max_sum_error_s": round(max_err, 9),
        "cadence_wait_fraction": round(cadence_sum / ttb_total, 6) if ttb_total > 0 else 0.0,
        "segments": {seg: pcts(vals) for seg, vals in sorted(per_seg.items())},
        "tiers": tiers,
    }
    assert tuple(block) == LATENCY_FIELDS, "latency block schema drifted from LATENCY_FIELDS"
    return block


def fingerprint(bind_log: list[tuple[float, str, str]], placements: list[tuple[str, str]]) -> str:
    """Determinism fingerprint: sha256 over the confirmed binding sequence
    (virtual time, pod, node — in POST order) and the final placement set.
    Two runs agree on this iff they made identical decisions."""
    h = hashlib.sha256()
    h.update(json.dumps(bind_log, sort_keys=False).encode())
    h.update(json.dumps(sorted(placements)).encode())
    return h.hexdigest()


def _percentile(sorted_vals: list[float], q: float) -> float:
    """Nearest-rank percentile on a pre-sorted list (deterministic, no
    interpolation-mode ambiguity)."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def check_invariants(
    api,
    scheduled_names: set[str],
    disturbed_pods: set[str],
    disturbed_nodes: set[str],
    gangs: dict[str, set[str]],
) -> dict:
    """I1–I4 against the final API state.

    ``scheduled_names`` — pods the SCHEDULER placed (arrivals, not pre-bound
    seeds); ``disturbed_pods``/``disturbed_nodes`` — churn-touched objects
    whose placements are excluded from the order-dependent re-checks;
    ``gangs`` — gang name -> member pod names (full membership ever seen).
    """
    final = ClusterSnapshot.build(api.list_nodes(), api.list_pods())
    node_by = {n.name: n for n in final.nodes}
    out: dict = {}

    # I1 capacity — exact scalar arithmetic, no exclusions ever.
    over = [
        n.name
        for n in final.nodes
        if (lambda used, alloc: used.cpu > alloc.cpu or used.memory > alloc.memory)(
            node_used_resources(final, n.name), node_allocatable(n)
        )
    ]
    out["capacity"] = {"ok": not over, "oversubscribed_nodes": over}

    # I2 predicates — every undisturbed placement passes the order-free
    # scalar chain vs the final state minus itself (spread excluded: it is
    # order-dependent by construction; see tests/test_stress.py).
    order_free = [(r, pred) for r, pred in P.PREDICATE_CHAIN if r != P.InvalidNodeReason.TOPOLOGY_SPREAD_VIOLATION]
    checked = skipped = 0
    violations: list[str] = []
    for pod, node in final.placed_pods():
        name = pod.metadata.name
        if name not in scheduled_names:
            continue
        if name in disturbed_pods or node.name in disturbed_nodes:
            skipped += 1
            continue
        checked += 1
        others = ClusterSnapshot.build(final.nodes, [q for q in final.pods if q is not pod])
        for reason, pred in order_free:
            if not pred(pod, node_by[node.name], others):
                violations.append(f"{name} on {node.name}: {reason.name}")
    out["predicates"] = {"ok": not violations, "checked": checked, "skipped_churned": skipped, "violations": violations[:20]}

    # I3 gang atomicity — an undisturbed gang is never partially ADMITTED:
    # no mix of bound and still-pending members.  Members that already
    # COMPLETED (bound, ran their lifetime, deleted) don't break atomicity —
    # admission was whole; they just finished at different times.
    placed_names = {p.metadata.name for p in final.pods if p.spec is not None and p.spec.node_name}
    pending_names = {p.metadata.name for p in final.pods if p.spec is None or not p.spec.node_name}
    g_checked = g_skipped = 0
    partial: list[str] = []
    for g, members in sorted(gangs.items()):
        if members & disturbed_pods:
            g_skipped += 1
            continue
        g_checked += 1
        n_placed = len(members & placed_names)
        n_pending = len(members & pending_names)
        if n_placed and n_pending:
            partial.append(f"{g}: {n_placed} bound / {n_pending} pending of {len(members)}")
    out["gangs"] = {"ok": not partial, "checked": g_checked, "skipped_churned": g_skipped, "partial": partial}

    # I4 selectors — nodeSelector / hard taints / required node affinity /
    # cordon on undisturbed placements (subsumed by I2; cheap triage).
    sel_bad: list[str] = []
    for pod, node in final.placed_pods():
        name = pod.metadata.name
        if name not in scheduled_names or name in disturbed_pods or node.name in disturbed_nodes:
            continue
        for reason, pred in P.NODE_LOCAL_PREDICATES:
            if not pred(pod, node_by[node.name], final):
                sel_bad.append(f"{name} on {node.name}: {reason.name}")
                break
    out["selectors"] = {"ok": not sel_bad, "violations": sel_bad[:20]}

    out["ok"] = all(out[k]["ok"] for k in ("capacity", "predicates", "gangs", "selectors"))
    return out


def build_scorecard(
    *,
    scenario: str,
    seed: int,
    mode: str,
    virtual_seconds: float,
    cycles: int,
    pod_counts: dict,
    ttb: list[float],
    backlog_pod_seconds: float,
    metrics_snapshot: dict,
    invariants: dict,
    chaos_injected: dict,
    resilience: dict,
    availability: dict,
    convergence: dict,
    locality: dict,
    profile: dict,
    compile: dict,
    incremental: dict,
    rebalance: dict,
    elasticity: dict,
    latency: dict,
    recorder_stats: dict,
    fp: str,
    policy_required: bool = False,
    policy_floor: float = 0.0,
) -> dict:
    """Assemble the one-JSON verdict.  Strictly virtual-time quantities —
    wall clock never appears, so the scorecard is bit-identical across runs
    and machines (the determinism acceptance criterion)."""
    ttb_sorted = sorted(ttb)
    slo = {
        "p50_time_to_bind_s": round(_percentile(ttb_sorted, 0.50), 6),
        "p99_time_to_bind_s": round(_percentile(ttb_sorted, 0.99), 6),
        "max_time_to_bind_s": round(ttb_sorted[-1], 6) if ttb_sorted else 0.0,
        "bound_per_virtual_second": round(len(ttb) / virtual_seconds, 4) if virtual_seconds > 0 else 0.0,
        "pending_backlog_pod_seconds": round(backlog_pod_seconds, 4),
        "preemption_churn": int(metrics_snapshot.get("scheduler_preemption_victims_total", 0))
        + int(metrics_snapshot.get("scheduler_noexecute_evictions_total", 0)),
        "requeues": int(metrics_snapshot.get("scheduler_requeues_total", 0)),
        "watch_errors": int(metrics_snapshot.get("scheduler_watch_errors_total", 0)),
    }
    # The policy objective (learn/objective.py): one scalar folded from the
    # blocks already computed above — nothing new is measured, so the
    # record→replay byte-identity contract is untouched.
    policy = policy_block(
        slo=slo,
        pod_counts=pod_counts,
        locality=locality,
        rebalance=rebalance,
        required=policy_required,
        floor=policy_floor,
    )
    card = {
        "scenario": scenario,
        "seed": seed,
        "mode": mode,
        # The degraded-mode invariant rides the verdict: a binding POST
        # through an OPEN circuit breaker is a resilience-layer bug even
        # when every placement invariant holds.  Locality-required scenarios
        # additionally gate on ZERO cross-rack gangs — a communication-
        # locality regression fails the run like an SLO regression does.
        # Multi-replica scenarios additionally gate on the availability
        # block's ok: zero double-binds, zero orphaned pods, and every
        # replica-kill's shard takeover within 2 x lease_duration.
        # Profile-required scenarios additionally gate on attribution
        # coverage ≥ 0.9 (the profile block): an unattributed cycle region
        # is an observability regression and fails the run.
        # Incremental-required scenarios additionally gate on the
        # incremental block's ok: shadow-solve parity on EVERY sampled
        # cycle and full_solve_fraction <= 0.10 — the delta path must stay
        # the default and provably equivalent.
        "pass": bool(
            invariants.get("ok")
            and pod_counts.get("lost", 1) == 0
            and pod_counts.get("double_bound", 1) == 0
            and resilience.get("binds_while_open", 0) == 0
            and not (locality.get("required") and locality.get("cross_rack_gangs", 0) != 0)
            and not (availability.get("enabled") and not availability.get("ok"))
            and not (profile.get("required") and not profile.get("coverage_ok"))
            # Compile-required scenarios additionally gate on the compile
            # block's ok: the XLA compile count must go FLAT after the
            # warmup window — one post-warmup compile is a shape-bucket
            # retrace leak (the runtime twin of the JITC static pass) and
            # fails the run like an SLO regression does.
            and not (compile.get("required") and not compile.get("ok"))
            and not (incremental.get("required") and not incremental.get("ok"))
            # Rebalance-required scenarios additionally gate on the
            # rebalance block's ok: final packing efficiency past the
            # scenario's gate within the migration budget, zero orphaned
            # migrations, zero deschedules through an open breaker, and a
            # consistent autoscaler what-if — a fragmentation regression
            # fails the run like an SLO regression does.
            and not (rebalance.get("required") and not rebalance.get("ok"))
            # Elasticity-required scenarios additionally gate on the
            # elasticity block's ok: the joint cost+SLO objective must
            # clear the scenario's gate AND the reclaim-orphan count must
            # be zero — a static fleet (or an autoscaler that buys its way
            # to the SLO at unbounded cost, or orphans a reclaimed pod)
            # fails the run like an SLO regression does.
            and not (elasticity.get("required") and not elasticity.get("ok"))
            # Policy-required scenarios additionally gate on the policy
            # block's ok: the learned-objective scalar must clear the
            # scenario's floor — a tuning run that wins one component by
            # wrecking another fails the run like an SLO regression does.
            and not (policy.get("required") and not policy.get("ok"))
            # Latency-required scenarios additionally gate on the latency
            # block's ok: waterfall segments must sum to TTB within
            # rounding on EVERY measured pod — an attribution leak is an
            # observability regression and fails the run.
            and not (latency.get("required") and not latency.get("ok"))
            # Convergence-required scenarios (the fuzzer's generated plans
            # and the lease-brownout scenario) additionally gate on the
            # convergence block's ok: after the last fault the backlog
            # drains, live deferred buffers flush, and no dead replica
            # holds an unexpired lease — a wedged end state fails the run.
            and not (convergence.get("required") and not convergence.get("ok"))
        ),
        "virtual_seconds": round(virtual_seconds, 6),
        "cycles": cycles,
        "pods": pod_counts,
        "slo": slo,
        "invariants": invariants,
        "chaos_injected": dict(sorted(chaos_injected.items())),
        "resilience": resilience,
        "availability": availability,
        "convergence": convergence,
        "locality": locality,
        "profile": profile,
        "compile": compile,
        "incremental": incremental,
        "rebalance": rebalance,
        "elasticity": elasticity,
        "policy": policy,
        "latency": latency,
        "flight_recorder": recorder_stats,
        "fingerprint": fp,
    }
    assert tuple(card) == SCORECARD_FIELDS, "scorecard schema drifted from SCORECARD_FIELDS"
    return card
