"""Named scenario registry — the seed-addressable robustness surface.

Every scenario is a complete experiment definition: workload shape, chaos
schedule, cadence, and policy knobs.  ``--scenario NAME --seed N`` fully
determines a run; the registry below is drift-gated against the README
"Simulation & chaos" catalogue by ``scripts/lint.py`` (SIMC, the METR-gate
pattern), so a scenario cannot ship undocumented.

All durations/rates are VIRTUAL seconds — a 2-minute scenario costs wall
clock proportional to the scheduling work, not the simulated time.
"""

from __future__ import annotations

from dataclasses import dataclass

from .chaos import ChaosConfig, ChaosWindow
from .workload import WorkloadSpec

__all__ = ["Scenario", "SCENARIOS", "arrival_rate_variant"]


@dataclass(frozen=True)
class Scenario:
    name: str
    description: str
    duration: float  # virtual seconds of workload generation
    workload: WorkloadSpec
    chaos: ChaosConfig = ChaosConfig()
    cycle_interval: float = 1.0  # virtual seconds between scheduler cycles
    requeue_seconds: float = 3.0  # failed-pod retry delay (virtual)
    watch_history: int = 1 << 18  # FakeApiServer retained watch events
    preemption: bool = False
    drain_grace_cycles: int = 12  # no-progress cycles after duration before stopping
    # Gate the scorecard pass on ZERO cross-rack gangs (the locality block):
    # for topology-labeled workloads where a single-rack fit always exists,
    # any cross-rack admission is a placement-quality regression.
    locality_required: bool = False
    # Multi-replica control plane (sim/multi.py): run this many controller
    # replicas against the one chaos apiserver, the pending set partitioned
    # into ``shards`` lease-owned shards (0 = 2 x replicas).  ``replica_kills``
    # lists (virtual time, replica index) crash points — the replica dies
    # between solve and flush of its next cycle (zero binds POSTed) and
    # NEVER releases its leases; survivors must absorb its shards within
    # 2 x lease_duration (the scorecard ``availability`` pass gate).
    replicas: int = 1
    shards: int = 0
    lease_duration: float = 5.0
    replica_kills: tuple[tuple[float, int], ...] = ()
    # Gate the scorecard pass on attribution coverage (the ``profile``
    # block, utils/profiler.py): steady-state-family scenarios must explain
    # ≥ 90% of their cycle wall through the span tree — an instrumentation
    # regression (a new unattributed cycle region) fails the run like an
    # SLO regression does.
    profile_required: bool = False
    # Compile-cache flatness (the scorecard ``compile`` block — the runtime
    # twin of the JITC static pass): ``compile_required`` gates the
    # scorecard pass on ZERO XLA compiles after the first
    # ``compile_warmup_cycles`` cycles.  Shape buckets are all traced
    # during warmup; a later compile means a raw per-cycle dim leaked into
    # a jit signature (a retrace leak the static pass missed).  Vacuously
    # green under the pure-numpy NativeBackend (the block's ``enabled`` bit
    # says so) — the jit-stability smoke drives the TpuBackend on CPU to
    # make the gate bite.
    compile_required: bool = False
    compile_warmup_cycles: int = 24
    # Incremental delta engine (tpu_scheduler/delta): ``delta_shadow_every``
    # > 0 runs the full-wave shadow solve beside every Nth delta cycle and
    # records placed-set parity; ``incremental_required`` gates the
    # scorecard pass on the ``incremental`` block's ok (shadow parity on
    # every sampled cycle AND full_solve_fraction <= 0.10).
    delta_shadow_every: int = 0
    incremental_required: bool = False
    # Background rebalancer (tpu_scheduler/rebalance): ``rebalance`` runs
    # the defrag tier inline on the cycle cadence (``rebalance_every``
    # cycles between ticks, ``rebalance_batch`` migrations per tick);
    # ``rebalance_required`` gates the scorecard pass on the ``rebalance``
    # block's ok — final packing efficiency >= ``rebalance_efficiency_gate``
    # (0 disables the efficiency gate), migrations within
    # ``rebalance_migration_budget`` (0 = unbounded), and ZERO orphaned
    # migrations.  ``rebalance_whatif`` computes the autoscaler what-if
    # block (node-add need for the final backlog, scale-down headroom).
    rebalance: bool = False
    rebalance_every: int = 4
    rebalance_batch: int = 8
    rebalance_required: bool = False
    rebalance_efficiency_gate: float = 0.0
    rebalance_migration_budget: int = 0
    rebalance_whatif: bool = False
    # Policy objective (tpu_scheduler/learn): every scorecard carries the
    # ``policy`` block (the learned-objective scalar + component breakdown);
    # ``policy_required`` additionally gates the pass on
    # ``objective >= policy_objective_floor`` — the floor a tuned profile
    # must clear WITHOUT breaking any other gate.
    policy_required: bool = False
    policy_objective_floor: float = 0.0
    # Time-to-bind waterfall (utils/events.py + the scorecard ``latency``
    # block): ``latency_required`` gates the scorecard pass on the latency
    # block's ok — at least one measured pod AND every measured pod's
    # segment decomposition summing to its TTB within rounding (the
    # attribution-leak audit).
    latency_required: bool = False
    # Closed-loop autoscaler (tpu_scheduler/autoscale): ``autoscale`` runs
    # the elastic-capacity tier inline after the rebalancer's tick
    # (``autoscale_every`` cycles between decisions) against a shared
    # seeded SimCloudProvider; ``autoscale_required`` gates the scorecard
    # pass on the ``elasticity`` block's ok — the joint cost+SLO objective
    # (effective p99 TTB + ``autoscale_cost_weight`` × elastic node-hours)
    # <= ``autoscale_objective_gate`` (0 disables the gate) AND zero
    # reclaim orphans.  ``autoscale_skus`` restricts the DEFAULT_CATALOG
    # by name (empty = full catalog); ``autoscale_quota`` caps the
    # account-wide concurrent elastic node count (0 = unbounded);
    # ``autoscale_reclaim_rate`` is the spot-reclaim hazard (reclaims per
    # virtual second per spot node, 0 = never) with
    # ``autoscale_reclaim_grace_s`` of notice; ``autoscale_burn_trigger``,
    # ``autoscale_max_per_tick``, ``autoscale_reserve``, and
    # ``autoscale_cooldown`` are the AutoscaleConfig knobs.
    # End-state convergence (the fuzzer's quiescence oracle, sim/fuzz):
    # ``convergence_required`` gates the scorecard pass on the
    # ``convergence`` block's ok — after the last scheduled fault the
    # backlog must drain, live replicas' deferred buffers must flush, and
    # no unexpired shard/replica/reservation lease may be held by a dead
    # replica, all within the settle bound.  Off by default: scenarios with
    # a standing backlog by design (autoscaler-backlog-whatif) judge
    # convergence informationally, never as a gate.
    convergence_required: bool = False
    autoscale: bool = False
    autoscale_every: int = 2
    autoscale_required: bool = False
    autoscale_burn_trigger: float = 0.02
    autoscale_cost_weight: float = 0.0
    autoscale_objective_gate: float = 0.0
    autoscale_quota: int = 0
    autoscale_reclaim_rate: float = 0.0
    autoscale_reclaim_grace_s: float = 5.0
    autoscale_max_per_tick: int = 8
    autoscale_reserve: int = 1
    autoscale_cooldown: int = 4
    autoscale_skus: tuple[str, ...] = ()


SCENARIOS: dict[str, Scenario] = {}


def _register(sc: Scenario) -> Scenario:
    SCENARIOS[sc.name] = sc
    return sc


_register(
    Scenario(
        name="steady-state",
        description="Poisson arrivals with pod completions at ~70% utilization; the healthy-daemon baseline every other scenario deviates from",
        duration=120.0,
        workload=WorkloadSpec(
            initial_nodes=60,
            arrival_rate=15.0,
            lifetime_mean_s=25.0,
            gang_fraction=0.05,
            selector_fraction=0.2,
            priority_tiers=(0, 0, 0, 5, 50),
        ),
        profile_required=True,
        latency_required=True,
        compile_required=True,
    )
)

_register(
    Scenario(
        name="burst-storm",
        description="Quiet background load punctured by 500-pod storms every 20 virtual seconds — tests backlog drain and time-to-bind tails",
        duration=100.0,
        workload=WorkloadSpec(
            initial_nodes=100,
            arrival_rate=2.0,
            bursts=((10.0, 500), (30.0, 500), (50.0, 500), (70.0, 500)),
            lifetime_mean_s=15.0,
            gang_fraction=0.1,
            priority_tiers=(0, 0, 5),
        ),
        latency_required=True,
    )
)

_register(
    Scenario(
        name="arrival-rate-sweep",
        description="The latency bench's scenario family: steady-state's cluster shape at a parameterized Poisson rate (arrival_rate_variant), pass-gated on the time-to-bind waterfall summing to TTB — bench.py latency_row sweeps the rate to put the TTB-vs-load curve on the record",
        duration=45.0,
        workload=WorkloadSpec(
            initial_nodes=60,
            arrival_rate=12.0,
            lifetime_mean_s=20.0,
            gang_fraction=0.05,
            selector_fraction=0.2,
            priority_tiers=(0, 0, 0, 5, 50),
        ),
        latency_required=True,
        # An oversubscribing rate variant drains only as lifetimes expire.
        drain_grace_cycles=25,
    )
)


# shape: (rate: obj) -> obj
def arrival_rate_variant(rate: float) -> Scenario:
    """The ``arrival-rate-sweep`` family member at a given Poisson rate —
    the parameterization bench.py's latency_row sweeps.  Variants are NOT
    registered (the registry stays the closed, README-documented set); the
    harness accepts Scenario objects directly."""
    from dataclasses import replace

    base = SCENARIOS["arrival-rate-sweep"]
    return replace(
        base,
        name=f"arrival-rate-{rate:g}",
        description=f"arrival-rate-sweep variant at {rate:g} pods/s",
        workload=replace(base.workload, arrival_rate=float(rate)),
    )


_register(
    Scenario(
        name="node-flap",
        description="Nodes repeatedly vanish and return (NotReady flaps) plus drains and permanent failures; bound pods re-arrive as Pending",
        duration=90.0,
        workload=WorkloadSpec(
            initial_nodes=40,
            arrival_rate=8.0,
            lifetime_mean_s=30.0,
            node_flap_rate=0.25,
            node_fail_rate=0.05,
            node_drain_rate=0.05,
            node_add_rate=0.05,
            flap_down_s=4.0,
        ),
        # Flapping clusters also stress the watch path: drops force backoff
        # + queued-event catch-up on top of the object churn.
        chaos=ChaosConfig(watch_drop_rate=0.05),
    )
)

_register(
    Scenario(
        name="api-brownout",
        description="The apiserver browns out mid-run: binding 500s, added binding latency, watch drops and a 410 Gone storm inside timed windows",
        duration=90.0,
        workload=WorkloadSpec(initial_nodes=50, arrival_rate=12.0, lifetime_mean_s=25.0),
        chaos=ChaosConfig(
            binding_latency_s=0.002,
            windows=(
                ChaosWindow(start=20.0, end=45.0, binding_error_rate=0.3, watch_drop_rate=0.3, binding_latency_s=0.02),
                ChaosWindow(start=55.0, end=65.0, watch_gone_rate=0.5, api_error_rate=0.2),
            ),
        ),
    )
)

_register(
    Scenario(
        name="api-brownout-recovery",
        description="A hard 20s API blackout (every binding POST 500s, watches drop): the circuit breaker must open, defer binds with ZERO POSTs while open, then probe half-open, flush the buffer, and drain the backlog after the window closes",
        duration=90.0,
        workload=WorkloadSpec(initial_nodes=50, arrival_rate=10.0, lifetime_mean_s=30.0),
        chaos=ChaosConfig(
            windows=(
                ChaosWindow(start=20.0, end=40.0, binding_error_rate=1.0, watch_drop_rate=0.5, api_error_rate=0.3),
            ),
        ),
        # The open window escalates (5 -> 10 -> 20s virtual) while probes
        # keep failing inside the blackout; give the post-window drain
        # enough grace to cover one full escalated re-open.
        drain_grace_cycles=25,
    )
)

_register(
    Scenario(
        name="gang-heavy",
        description="40% of arrivals are 2-8 member gangs across priority tiers on an OVERSUBSCRIBED cluster with preemption on — all-or-nothing admission under real contention",
        duration=80.0,
        workload=WorkloadSpec(
            initial_nodes=10,
            arrival_rate=8.0,
            lifetime_mean_s=45.0,
            gang_fraction=0.4,
            gang_size_max=8,
            priority_tiers=(0, 1, 5, 50, 100),
        ),
        preemption=True,
        # Oversubscribed by design: the backlog drains only as lifetimes
        # expire, so give the post-duration drain a longer leash.
        drain_grace_cycles=25,
    )
)

_register(
    Scenario(
        name="slice-fragmented-cluster",
        description="Topology-labeled fleet (8 racks x 2 slices) under mixed single-pod + gang load: fillers fragment free capacity while single-rack fits still exist everywhere — topology-aware scoring must admit EVERY gang with zero cross-rack edges (pass-gated), where blind scoring scatters them",
        duration=40.0,
        workload=WorkloadSpec(
            initial_nodes=48,
            slice_size=3,
            rack_size=6,
            arrival_rate=5.0,
            bursts=((2.0, 40),),  # the fragmenting filler/gang wave
            gang_fraction=0.45,
            gang_size_max=4,
            lifetime_mean_s=35.0,
        ),
        locality_required=True,
        drain_grace_cycles=20,
    )
)

_register(
    Scenario(
        name="rack-failure-during-gang-admission",
        description="A whole rack dies mid-run while gangs are being admitted: every node in the picked rack vanishes, its pods re-arrive Pending, and admission must continue whole-gang on the surviving racks (invariants + replay bit-identity under rack-scale churn)",
        duration=40.0,
        workload=WorkloadSpec(
            initial_nodes=30,
            slice_size=0,
            rack_size=5,
            arrival_rate=6.0,
            gang_fraction=0.4,
            gang_size_max=5,
            lifetime_mean_s=25.0,
            rack_fail_times=(12.0,),
        ),
        drain_grace_cycles=25,
    )
)

_register(
    Scenario(
        name="replica-kill-mid-cycle",
        description="Active-active sharded control plane: two replicas split four lease-owned shards; the busier replica is crash-killed between solve and flush (zero binds POSTed, leases never released) — the survivor must absorb the orphaned shards within 2x lease_duration with zero double-binds and zero orphaned pods (availability pass gate)",
        duration=60.0,
        workload=WorkloadSpec(
            initial_nodes=30,
            arrival_rate=6.0,
            lifetime_mean_s=25.0,
            gang_fraction=0.1,
            priority_tiers=(0, 0, 5),
        ),
        replicas=2,
        shards=4,
        lease_duration=5.0,
        replica_kills=((15.0, 0),),
        drain_grace_cycles=20,
    )
)

_register(
    Scenario(
        name="mesh-rebind-on-takeover",
        description="Multi-mesh fleet failover (tpu_scheduler/fleet): a topology-labeled 4-rack fleet keys its four shards to contiguous rack slices (one device-mesh binding per owned shard); killing a replica mid-cycle forces the survivor to absorb the orphaned shards AND rebind them onto its own mesh — the delta engine must escalate exactly one mesh-rebind full wave, takeover within 2x lease_duration, zero double-binds, zero orphaned reservations",
        duration=40.0,
        workload=WorkloadSpec(
            initial_nodes=32,
            rack_size=8,
            arrival_rate=6.0,
            lifetime_mean_s=25.0,
            gang_fraction=0.1,
            priority_tiers=(0, 0, 5),
        ),
        replicas=2,
        shards=4,
        lease_duration=5.0,
        replica_kills=((15.0, 0),),
        drain_grace_cycles=20,
    )
)

_register(
    Scenario(
        name="cross-shard-gang-admission",
        description="Cross-replica gang admission (tpu_scheduler/fleet): four replicas each own ONE rack-keyed shard (8 nodes) while gangs of up to 12 members arrive — wider than any single slice under the one-member-per-node proxy, so the owner must two-phase RESERVE peer shards, solve the gang against the widened slice, and COMMIT the reservation on admission; a lease brownout window exercises the all-or-nothing abort path, and the run must settle with zero double-binds and zero orphaned reservations",
        duration=40.0,
        workload=WorkloadSpec(
            initial_nodes=32,
            rack_size=8,
            arrival_rate=4.0,
            lifetime_mean_s=30.0,
            gang_fraction=0.35,
            gang_size_max=12,
            pod_cpu_m=(2000, 4000),
            pod_mem_mi=(512, 1024),
        ),
        chaos=ChaosConfig(
            windows=(ChaosWindow(start=18.0, end=24.0, api_error_rate=0.2, watch_drop_rate=0.1),),
        ),
        replicas=4,
        shards=4,
        lease_duration=5.0,
        drain_grace_cycles=25,
    )
)

_register(
    Scenario(
        name="replica-kill-during-brownout",
        description="The replica-kill composed with the PR-4 circuit breaker: a hard binding blackout opens the owner's breaker (binds defer in memory), then the owner is crash-killed mid-brownout — its deferred buffer dies with it, the survivor re-places those pods through its OWN degraded mode, and the run must still end with zero double-binds and zero binds through an open breaker",
        duration=80.0,
        workload=WorkloadSpec(
            initial_nodes=30,
            arrival_rate=6.0,
            lifetime_mean_s=30.0,
        ),
        chaos=ChaosConfig(
            windows=(ChaosWindow(start=12.0, end=30.0, binding_error_rate=1.0, watch_drop_rate=0.3),),
        ),
        replicas=2,
        shards=4,
        lease_duration=5.0,
        replica_kills=((18.0, 0),),
        drain_grace_cycles=30,
    )
)

_register(
    Scenario(
        name="lease-brownout-during-takeover",
        description="The lease-fault surface composed with failover: the coordination plane browns out (lease CAS 500s, refused acquires, virtual lease latency) in a window spanning a replica crash-kill — the survivor's takeover CAS calls fail and retry through the hardened refuse-don't-raise path, and the run must still absorb the orphaned shards within 2x lease_duration with zero double-binds and a converged end state (pass-gated availability + convergence blocks)",
        duration=60.0,
        workload=WorkloadSpec(
            initial_nodes=30,
            arrival_rate=6.0,
            lifetime_mean_s=25.0,
            gang_fraction=0.1,
            priority_tiers=(0, 0, 5),
        ),
        chaos=ChaosConfig(
            windows=(
                ChaosWindow(start=12.0, end=28.0, lease_error_rate=0.3, lease_refused_rate=0.15, lease_latency_s=0.005),
            ),
        ),
        replicas=2,
        shards=4,
        lease_duration=5.0,
        replica_kills=((15.0, 0),),
        convergence_required=True,
        drain_grace_cycles=25,
    )
)

_register(
    Scenario(
        name="churn-steady-state",
        description="The incremental engine's home turf: Poisson arrivals + completions at moderate utilization with NO node churn — the delta cycle must stay the default (full_solve_fraction <= 0.10) while the sampled full-wave shadow solve proves placed-set parity on every check (pass-gated incremental block)",
        duration=120.0,
        workload=WorkloadSpec(
            initial_nodes=60,
            arrival_rate=15.0,
            lifetime_mean_s=25.0,
            gang_fraction=0.05,
            selector_fraction=0.2,
            priority_tiers=(0, 0, 0, 5, 50),
        ),
        delta_shadow_every=8,
        incremental_required=True,
        compile_required=True,
    )
)

_register(
    Scenario(
        name="fragmentation-long-horizon",
        description="Long-horizon fragmentation: arrival waves place-and-spread across 24 nodes, completions thin the cluster to a sparse scatter, and the quiet tail belongs to the background rebalancer — the scorecard rebalance block must recover the packing-efficiency gate within the migration budget (pass-gated), where the rebalancer-off baseline stays fragmented and fails it",
        duration=120.0,
        workload=WorkloadSpec(
            initial_nodes=24,
            arrival_rate=0.0,
            bursts=((1.0, 90), (8.0, 70), (16.0, 60)),
            pod_cpu_m=(500, 1000, 2000),
            pod_mem_mi=(512, 1024, 2048),
            lifetime_mean_s=45.0,
        ),
        rebalance=True,
        rebalance_every=4,
        rebalance_batch=12,
        rebalance_required=True,
        rebalance_efficiency_gate=0.35,
        rebalance_migration_budget=160,
        drain_grace_cycles=20,
    )
)

_register(
    Scenario(
        name="defrag-smoke",
        description="The defrag tier-1 gate: a 12-node single-wave fragmentation run sized to finish on CPU in seconds — the rebalancer must consolidate the surviving scatter past the efficiency gate within the migration budget while the rebalancer-off baseline fails the same gate (make defrag-smoke)",
        duration=60.0,
        workload=WorkloadSpec(
            initial_nodes=12,
            arrival_rate=0.0,
            bursts=((1.0, 90),),
            pod_cpu_m=(500, 1000, 2000),
            pod_mem_mi=(512, 1024, 2048),
            lifetime_mean_s=30.0,
        ),
        rebalance=True,
        rebalance_every=3,
        rebalance_batch=12,
        rebalance_required=True,
        rebalance_efficiency_gate=0.35,
        rebalance_migration_budget=120,
        drain_grace_cycles=20,
    )
)

_register(
    Scenario(
        name="rebalance-under-chaos",
        description="Migrations composed with the chaos stack: a hard binding blackout opens the breaker mid-defrag (unbinds must defer — zero deschedules through an open breaker), then the shard-0 owner carrying the rebalancer is crash-killed — the survivor absorbs shard 0 and the background tier with it, and the run must end with zero double-binds and ZERO orphaned migrations (pass-gated rebalance + availability blocks)",
        duration=110.0,
        workload=WorkloadSpec(
            initial_nodes=20,
            arrival_rate=0.0,
            bursts=((1.0, 70), (10.0, 50)),
            pod_cpu_m=(500, 1000, 2000),
            pod_mem_mi=(512, 1024, 2048),
            lifetime_mean_s=40.0,
        ),
        chaos=ChaosConfig(
            windows=(
                # Mid-defrag blackout: every binding POST 500s AND the
                # deschedule endpoint itself faults — the breaker must
                # open, the rebalancer must stand down (breaker-open
                # skips), and zero unbinds may land inside the open spans.
                ChaosWindow(start=8.0, end=22.0, binding_error_rate=1.0, api_error_rate=0.4, watch_drop_rate=0.3),
            ),
        ),
        replicas=2,
        shards=4,
        lease_duration=5.0,
        replica_kills=((40.0, 0),),
        rebalance=True,
        rebalance_every=4,
        rebalance_batch=10,
        rebalance_required=True,
        rebalance_efficiency_gate=0.0,
        rebalance_migration_budget=200,
        drain_grace_cycles=30,
    )
)

_register(
    Scenario(
        name="autoscaler-backlog-whatif",
        description="The autoscaler what-if the packing tier makes answerable: an 8-node cluster buried under a forever-lived burst holds a standing pending backlog — the rebalancer must stand DOWN (backlog/SLO-burn throttle, counted skips), and the scorecard rebalance block's whatif must recommend a concrete node-add count that would clear the backlog (pass-gated consistency)",
        duration=30.0,
        workload=WorkloadSpec(
            initial_nodes=8,
            arrival_rate=0.0,
            bursts=((1.0, 140),),
            pod_cpu_m=(1000, 2000),
            pod_mem_mi=(1024, 2048),
            lifetime_mean_s=0.0,
        ),
        rebalance=True,
        rebalance_every=2,
        rebalance_batch=8,
        rebalance_required=True,
        rebalance_whatif=True,
        drain_grace_cycles=10,
    )
)

_register(
    Scenario(
        name="diurnal-traffic",
        description="The autoscaling steady-state gate: a 4-node base fleet sized for the trough rides two full diurnal waves (rate 2/s ± 100%, 60 s period) of chunky pods — the closed loop must buy capacity into each crest and retire it in each trough, and the pass gates on the joint cost+SLO objective the static fleet cannot reach (elasticity block, autoscale=False must FAIL)",
        duration=120.0,
        workload=WorkloadSpec(
            initial_nodes=4,
            arrival_rate=2.0,
            diurnal_period=60.0,
            diurnal_amplitude=1.0,
            pod_cpu_m=(1000, 2000, 4000),
            pod_mem_mi=(1024, 2048, 4096),
            lifetime_mean_s=12.0,
        ),
        autoscale=True,
        autoscale_required=True,
        autoscale_burn_trigger=0.01,
        autoscale_cost_weight=10.0,
        autoscale_objective_gate=30.0,
        autoscale_cooldown=2,
        drain_grace_cycles=20,
    )
)

_register(
    Scenario(
        name="flash-crowd-provisioning-lag",
        description="The provisioning-lag gate: a 4-node fleet takes a 90-pod flash crowd at t=8 — capacity bought at the crest lands only after the SKU's seeded provisioning latency, so the p99 time-to-bind is lag-exposed by construction; the pass gates on the joint cost+SLO objective (elasticity block, autoscale=False must FAIL)",
        duration=60.0,
        workload=WorkloadSpec(
            initial_nodes=4,
            arrival_rate=0.5,
            bursts=((8.0, 90),),
            pod_cpu_m=(1000, 2000),
            pod_mem_mi=(1024, 2048),
            lifetime_mean_s=25.0,
        ),
        autoscale=True,
        autoscale_required=True,
        autoscale_cost_weight=10.0,
        autoscale_objective_gate=30.0,
        drain_grace_cycles=25,
    )
)

_register(
    Scenario(
        name="spot-reclaim-storm",
        description="The reclaim-safety gate: the catalog is restricted to the cheap preemptible SKU and the provider reclaims spot nodes at hazard 0.02/s with 4 s of notice — every reclaimed node's pods must be force-unbound through the faultable unbind path and re-placed by the delta engine; the pass gates on ZERO reclaim orphans plus the joint objective (elasticity block, autoscale=False must FAIL)",
        duration=90.0,
        workload=WorkloadSpec(
            initial_nodes=3,
            arrival_rate=1.0,
            bursts=((5.0, 80),),
            pod_cpu_m=(1000, 2000),
            pod_mem_mi=(1024, 2048),
            lifetime_mean_s=20.0,
        ),
        autoscale=True,
        autoscale_required=True,
        autoscale_burn_trigger=0.01,
        autoscale_cost_weight=10.0,
        autoscale_objective_gate=35.0,
        autoscale_reclaim_rate=0.02,
        autoscale_reclaim_grace_s=4.0,
        autoscale_cooldown=2,
        autoscale_skus=("spot-16",),
        drain_grace_cycles=25,
    )
)

_register(
    Scenario(
        name="quota-capped-surge",
        description="The quota-pressure gate: a 100-pod surge against an account-wide quota of TWO elastic nodes — the cost-aware plan buys to the cap, further asks are refused live (quota-exceeded provider errors + counted `quota` skips), and the two nodes it did win must still clear the joint objective a static fleet cannot (elasticity block, autoscale=False must FAIL)",
        duration=60.0,
        workload=WorkloadSpec(
            initial_nodes=3,
            arrival_rate=0.5,
            bursts=((5.0, 100),),
            pod_cpu_m=(1000, 2000),
            pod_mem_mi=(1024, 2048),
            lifetime_mean_s=20.0,
        ),
        autoscale=True,
        autoscale_required=True,
        autoscale_cost_weight=10.0,
        autoscale_objective_gate=35.0,
        autoscale_quota=2,
        drain_grace_cycles=25,
    )
)

_register(
    Scenario(
        name="train-smoke",
        description="The policy-training gate: a topology-labeled 12-node cluster (2 racks x 2 slices) under mixed single-pod + gang load, sized so one episode costs well under a second on CPU — `sim train` climbs the scorecard policy objective here, and the pass gates on the objective floor the default profile clears (make train-smoke)",
        duration=24.0,
        workload=WorkloadSpec(
            initial_nodes=12,
            slice_size=3,
            rack_size=6,
            arrival_rate=4.0,
            bursts=((2.0, 24),),
            gang_fraction=0.3,
            gang_size_max=3,
            lifetime_mean_s=15.0,
            priority_tiers=(0, 0, 5, 50),
        ),
        drain_grace_cycles=15,
        policy_required=True,
        policy_objective_floor=1.0,
    )
)

_register(
    Scenario(
        name="sim-smoke",
        description="The tier-1 gate: ~2k pods over 200 nodes with node churn AND an api-brownout window, sized to finish green on CPU in seconds",
        duration=60.0,
        workload=WorkloadSpec(
            initial_nodes=200,
            arrival_rate=30.0,
            bursts=((5.0, 200),),
            lifetime_mean_s=20.0,
            gang_fraction=0.08,
            selector_fraction=0.15,
            priority_tiers=(0, 0, 5, 50),
            node_flap_rate=0.1,
            node_fail_rate=0.03,
            node_add_rate=0.03,
        ),
        chaos=ChaosConfig(
            watch_drop_rate=0.02,
            windows=(ChaosWindow(start=15.0, end=35.0, binding_error_rate=0.2, watch_drop_rate=0.2, binding_latency_s=0.005),),
        ),
    )
)
