"""``python -m tpu_scheduler.cli sim`` — the simulator's command surface.

Runs one named scenario to its scorecard JSON (stdout, one line).  Exit
codes: 0 = verdict passed, 1 = verdict failed (invariant violation, lost or
double-bound pods), 3 = a ``--replay`` run diverged from its recorded
fingerprint.
"""

from __future__ import annotations

import argparse
import json
import sys

from ..utils.tracing import configure_logging
from .harness import ReplayMismatchError, run_scenario
from .scenarios import SCENARIOS

__all__ = ["main"]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="tpu-scheduler sim", description=__doc__)
    p.add_argument("--scenario", default="sim-smoke", choices=sorted(SCENARIOS), help="named scenario (see --list)")
    p.add_argument("--seed", type=int, default=0, help="the ONE seed every random choice derives from")
    p.add_argument("--record", default=None, metavar="PATH", help="persist the run as a JSONL trace")
    p.add_argument("--replay", default=None, metavar="PATH", help="re-run a recorded trace and verify bit-identity")
    p.add_argument("--backend", choices=["native", "tpu"], default="native", help="scheduling backend under test")
    p.add_argument(
        "--profile-file",
        default=None,
        metavar="PATH",
        help="schedule with a tuned-profile JSON artifact (learn/profiles schema) instead of the default profile",
    )
    p.add_argument("--events-buffer", type=int, default=4096, help="flight recorder capacity during the run")
    p.add_argument(
        "--profile-check",
        action="store_true",
        help="after the run, enforce the profiler gates: attribution coverage >= 0.9 and "
        "estimated span+ring overhead < 2%% of the cycle wall (exit 1 on breach) — the "
        "`make profile-smoke` engine",
    )
    p.add_argument("--log-level", default="WARNING")
    p.add_argument("--list", action="store_true", help="list scenarios and exit")
    return p


def main(argv: list[str] | None = None) -> int:
    if argv and argv[0] == "fuzz":
        # Coverage-guided chaos fuzzing (tpu_scheduler/sim/fuzz): seeded
        # fault-plan search + corpus replay, byte-identical per seed:
        #   python -m tpu_scheduler.cli sim fuzz --budget 200 --seed 0
        from .fuzz.cli import main as fuzz_main

        return fuzz_main(argv[1:])
    if argv and argv[0] == "train":
        # Policy training (tpu_scheduler/learn): seeded CEM over the
        # profile weight surface, distilled to a JSON artifact:
        #   python -m tpu_scheduler.cli sim train --scenario-set train-smoke --seed 0 --out profile.json
        from ..learn.cli import main as train_main

        return train_main(argv[1:])
    args = build_parser().parse_args(argv)
    configure_logging(args.log_level, "text")
    if args.list:
        for name in sorted(SCENARIOS):
            sc = SCENARIOS[name]
            print(json.dumps({"scenario": name, "duration_s": sc.duration, "description": sc.description}))
        return 0
    if args.record and args.replay:
        print("--record and --replay are mutually exclusive", file=sys.stderr)
        return 2
    if args.backend == "tpu":
        from ..backends.tpu import TpuBackend

        backend = TpuBackend()
    else:
        from ..backends.native import NativeBackend

        backend = NativeBackend()
    profile = None
    if args.profile_file:
        from ..models.profiles import SchedulingProfile

        profile = SchedulingProfile.from_file(args.profile_file)
    gates: dict | None = {} if args.profile_check else None
    try:
        card = run_scenario(
            args.scenario,
            seed=args.seed,
            backend=backend,
            record=args.record,
            replay=args.replay,
            events_buffer=args.events_buffer,
            profile_gates=gates,
            profile=profile,
        )
    except ReplayMismatchError as e:
        print(json.dumps({"replay_mismatch": True, "expected": e.expected, "got": e.got}))
        return 3
    print(json.dumps(card, sort_keys=True))
    if gates is not None:
        # Wall-derived gate inputs stay OFF the (byte-identical) scorecard;
        # this line is diagnostics, the exit code is the verdict.
        verdict = {
            "profile_check": True,
            "coverage": round(gates["coverage"], 4),
            "overhead_frac": round(gates["overhead_frac"], 5),
            "spans_per_cycle": round(gates["spans_per_cycle"], 1),
            "coverage_ok": gates["coverage"] >= 0.9,
            "overhead_ok": gates["overhead_frac"] < 0.02,
        }
        print(json.dumps(verdict), file=sys.stderr)
        if not (verdict["coverage_ok"] and verdict["overhead_ok"]):
            return 1
    return 0 if card["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
