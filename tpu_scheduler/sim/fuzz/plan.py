"""Fault plans — the fuzzer's closed, serializable chaos vocabulary.

A :class:`FaultPlan` is a small program: a base workload name, a virtual
duration, and up to :data:`MAX_OPS` :class:`FaultOp` instructions drawn from
the closed :data:`FAULT_OPS` vocabulary.  ``compile_plan`` lowers a plan onto
one of the :data:`BASE_WORKLOADS` — producing an ordinary (unregistered)
``Scenario`` that runs through the same harness as every scripted scenario,
so a plan inherits the whole invariant battery for free.  Plans serialize to
canonical JSON (sorted keys, rounded floats) and any run reproduces
bit-identically from (plan, seed) alone.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, replace

from ..chaos import ChaosConfig, ChaosWindow
from ..scenarios import Scenario
from ..workload import WorkloadSpec

__all__ = [
    "BASE_WORKLOADS",
    "FAULT_OPS",
    "MAX_OPS",
    "OP_FIELDS",
    "PLAN_FIELDS",
    "FaultOp",
    "FaultPlan",
    "compile_plan",
    "op_valid_for_base",
    "plan_from_json",
    "plan_to_json",
]

# The closed fault-op vocabulary.  Window ops ("brownout".."lease-latency")
# lower to a ChaosWindow over [t0, t1); event ops ("replica-kill",
# "rack-fail") fire once at t0; hazard ops ("node-flap", "spot-reclaim")
# raise a whole-run rate.  Adding a kind here without a README catalogue row
# trips the FUZZ analyze rule.
FAULT_OPS = (
    "brownout",  # binding 500s + binding latency over the window
    "bind-500",  # binding_error_rate window
    "unbind-500",  # api_error_rate window (unbind/list paths)
    "watch-drop",  # watch events silently dropped
    "watch-gone",  # 410 Gone storm — forced relists
    "lease-500",  # lease CAS endpoints raise apiserver 500s
    "lease-refused",  # lease acquire loses the CAS without raising
    "lease-latency",  # lease round-trips slow down
    "replica-kill",  # crash-kill one scheduler replica at t0
    "rack-fail",  # whole-rack outage at t0 (gang-rack base only)
    "node-flap",  # nodes blink out and return all run long
    "spot-reclaim",  # provider reclaims autoscaled capacity (elastic base only)
)

# Event/hazard kinds (no [t0, t1) window semantics).
EVENT_OPS = ("replica-kill", "rack-fail")
HAZARD_OPS = ("node-flap", "spot-reclaim")

# Plans are capped small by construction: the corpus promise is that every
# checked-in reproducer has at most MAX_OPS fault ops.
MAX_OPS = 6

# Closed serialization schemas — the FUZZ analyze rule pins these to the
# README plan-JSON table, and the serde below asserts against drift.
PLAN_FIELDS = ("plan_id", "base", "duration", "ops")
OP_FIELDS = ("kind", "t0", "t1", "magnitude")


# protocol: machine fuzz-plan field=- init=generated
# protocol: states: generated | judged | passed | violated | minimal
# protocol: generated -> judged
# protocol: judged -> passed | violated
# protocol: violated -> minimal
# protocol: var ops: 0..6 = 2
# protocol: action judge: generated -> judged
# protocol: action clear: judged -> passed
# protocol: action flag: judged -> violated requires ops >= 1
# protocol: action drop_op: violated -> violated requires ops >= 2 effect ops -= 1
# protocol: action settle: violated -> minimal requires ops >= 1
# protocol: invariant capped: ops <= 6
# protocol: invariant minimal_nonempty: state == minimal implies ops >= 1
# protocol: progress shrink_terminates: state == violated
@dataclass(frozen=True)
class FaultOp:
    """One fault instruction: ``kind`` at ``[t0, t1)`` with ``magnitude``.

    ``magnitude`` is a 0..1 severity knob whose meaning is per-kind (error
    rate for window ops, replica index selector for kills, hazard scale for
    flap/reclaim).  Event kinds ignore ``t1``.
    """

    kind: str
    t0: float
    t1: float
    magnitude: float

    def to_json(self) -> dict:
        out = {
            "kind": self.kind,
            "t0": round(float(self.t0), 3),
            "t1": round(float(self.t1), 3),
            "magnitude": round(float(self.magnitude), 3),
        }
        assert tuple(out) == OP_FIELDS, "FaultOp serde drifted from OP_FIELDS"
        return out


@dataclass(frozen=True)
class FaultPlan:
    """A complete fault schedule: base workload + duration + ops."""

    plan_id: str
    base: str
    duration: float
    ops: tuple[FaultOp, ...]

    def to_json(self) -> dict:
        out = {
            "plan_id": self.plan_id,
            "base": self.base,
            "duration": round(float(self.duration), 3),
            "ops": [op.to_json() for op in self.ops],
        }
        assert tuple(out) == PLAN_FIELDS, "FaultPlan serde drifted from PLAN_FIELDS"
        return out


# shape: (plan: obj) -> str
def plan_to_json(plan: FaultPlan) -> str:
    """Canonical JSON: sorted keys, no whitespace variance — diff- and
    fingerprint-stable across machines."""
    return json.dumps(plan.to_json(), sort_keys=True, separators=(",", ":"))


# shape: (text: str) -> obj
def plan_from_json(text: str) -> FaultPlan:
    raw = json.loads(text)
    ops = []
    for op in raw["ops"]:
        if op["kind"] not in FAULT_OPS:
            raise ValueError(f"unknown fault op kind: {op['kind']!r}")
        ops.append(FaultOp(kind=op["kind"], t0=float(op["t0"]), t1=float(op["t1"]), magnitude=float(op["magnitude"])))
    if len(ops) > MAX_OPS:
        raise ValueError(f"plan has {len(ops)} ops, cap is {MAX_OPS}")
    if raw["base"] not in BASE_WORKLOADS:
        raise ValueError(f"unknown base workload: {raw['base']!r}")
    return FaultPlan(plan_id=str(raw["plan_id"]), base=str(raw["base"]), duration=float(raw["duration"]), ops=tuple(ops))


# Base workloads the generator composes over.  All run 2 replicas × 4 shards
# (the interesting lease/takeover machinery is always live) with finite pod
# lifetimes so the convergence gate is meaningful.  Durations here are
# defaults; each plan carries its own.
_MIXED = Scenario(
    name="fuzz-base-mixed",
    description="General mixed workload: steady arrivals, some gangs, three priority tiers.",
    duration=26.0,
    workload=WorkloadSpec(
        initial_nodes=16,
        arrival_rate=4.0,
        gang_fraction=0.15,
        gang_size_max=4,
        priority_tiers=(0, 0, 5),
        lifetime_mean_s=16.0,
    ),
    replicas=2,
    shards=4,
    lease_duration=5.0,
    drain_grace_cycles=25,
    convergence_required=True,
)

_GANG_RACK = Scenario(
    name="fuzz-base-gang-rack",
    description="Gang-heavy workload on racked topology — rack failures are in vocabulary here.",
    duration=26.0,
    workload=WorkloadSpec(
        initial_nodes=20,
        arrival_rate=3.0,
        gang_fraction=0.35,
        gang_size_max=5,
        priority_tiers=(0, 5),
        lifetime_mean_s=18.0,
        rack_size=5,
    ),
    replicas=2,
    shards=4,
    lease_duration=5.0,
    drain_grace_cycles=25,
    convergence_required=True,
)

_ELASTIC = Scenario(
    name="fuzz-base-elastic",
    description="Small fleet + burst with the autoscaler live — spot reclaims are in vocabulary here.",
    duration=26.0,
    workload=WorkloadSpec(
        initial_nodes=5,
        arrival_rate=1.5,
        bursts=((4.0, 25),),
        priority_tiers=(0, 5),
        pod_cpu_m=(500, 1000, 2000),
        pod_mem_mi=(512, 1024, 2048),
        lifetime_mean_s=13.0,
    ),
    replicas=2,
    shards=4,
    lease_duration=5.0,
    drain_grace_cycles=25,
    convergence_required=True,
    autoscale=True,
    autoscale_burn_trigger=0.01,
    autoscale_cooldown=2,
)

BASE_WORKLOADS = {
    "mixed": _MIXED,
    "gang-rack": _GANG_RACK,
    "elastic": _ELASTIC,
}


# shape: (kind: str, base: str) -> bool
def op_valid_for_base(kind: str, base: str) -> bool:
    """Rack failures need racks; spot reclaims need the autoscaler."""
    if kind == "rack-fail":
        return BASE_WORKLOADS[base].workload.rack_size > 0
    if kind == "spot-reclaim":
        return BASE_WORKLOADS[base].autoscale
    return True


def _window_for(op: FaultOp) -> ChaosWindow:
    mag = float(op.magnitude)
    kw: dict = {"start": float(op.t0), "end": float(op.t1)}
    if op.kind == "brownout":
        kw["binding_error_rate"] = mag
        kw["binding_latency_s"] = 0.01 * mag
    elif op.kind == "bind-500":
        kw["binding_error_rate"] = mag
    elif op.kind == "unbind-500":
        kw["api_error_rate"] = mag
    elif op.kind == "watch-drop":
        kw["watch_drop_rate"] = mag
    elif op.kind == "watch-gone":
        kw["watch_gone_rate"] = mag
    elif op.kind == "lease-500":
        kw["lease_error_rate"] = mag
    elif op.kind == "lease-refused":
        kw["lease_refused_rate"] = mag
    elif op.kind == "lease-latency":
        kw["lease_latency_s"] = 0.02 * mag
    else:  # pragma: no cover - generator never routes event/hazard ops here
        raise ValueError(f"not a window op: {op.kind}")
    return ChaosWindow(**kw)


# shape: (plan: obj) -> obj
def compile_plan(plan: FaultPlan) -> Scenario:
    """Lower a plan onto its base workload, yielding an unregistered
    Scenario with ``convergence_required`` inherited from the base."""
    base = BASE_WORKLOADS[plan.base]
    windows = list(base.chaos.windows)
    kills = list(base.replica_kills)
    wl = base.workload
    rack_fails = list(wl.rack_fail_times)
    flap = wl.node_flap_rate
    reclaim = base.autoscale_reclaim_rate
    for op in plan.ops:
        if op.kind in EVENT_OPS:
            if op.kind == "replica-kill":
                kills.append((float(op.t0), int(op.magnitude * 10.0) % max(1, base.replicas)))
            else:
                rack_fails.append(float(op.t0))
        elif op.kind == "node-flap":
            flap = max(flap, 0.3 * float(op.magnitude))
        elif op.kind == "spot-reclaim":
            reclaim = max(reclaim, 0.04 * float(op.magnitude))
        else:
            windows.append(_window_for(op))
    # Never crash the whole fleet: a plan that kills every replica wedges by
    # construction, which would be a false "violation".  Keep the earliest
    # kill per replica index and drop kills past replicas-1.
    kills.sort()
    kept: list[tuple[float, int]] = []
    seen_idx: list[int] = []
    for t, idx in kills:
        if idx not in seen_idx and len(seen_idx) < base.replicas - 1:
            kept.append((t, idx))
            seen_idx.append(idx)
    new_wl = replace(wl, rack_fail_times=tuple(sorted(rack_fails)), node_flap_rate=flap)
    return replace(
        base,
        name=f"fuzz-{plan.base}-{plan.plan_id}",
        description=f"Compiled fault plan {plan.plan_id} on base '{plan.base}'.",
        duration=float(plan.duration),
        workload=new_wl,
        chaos=ChaosConfig(windows=tuple(windows)),
        replica_kills=tuple(kept),
        autoscale_reclaim_rate=reclaim,
    )
