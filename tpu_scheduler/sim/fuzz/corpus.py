"""The reproducer corpus: shrunk plans checked into ``tests/fuzz_corpus/``.

Each ``*.json`` entry is a complete, self-verifying replay: the plan, the
seed, and the expected outcome (fingerprint, pass verdict, violation names,
and optional dotted-path ``pins`` into the scorecard).  Tier-1 replays every
entry on every test run — a corpus entry is a bug (or a near-miss) pinned
forever, bit-identically, with at most :data:`~.plan.MAX_OPS` fault ops.
"""

from __future__ import annotations

import json
import os

from .oracle import card_value, run_plan
from .plan import MAX_OPS, FaultPlan, plan_from_json

__all__ = ["ENTRY_FIELDS", "load_corpus", "replay_entry"]

# Closed corpus-entry schema (FUZZ analyze rule pins it to the README).
ENTRY_FIELDS = ("name", "note", "seed", "expect", "plan")


# shape: (path: str) -> obj
def load_entry(path: str) -> dict:
    with open(path, encoding="utf-8") as fh:
        raw = json.load(fh)
    for field in ENTRY_FIELDS:
        if field not in raw:
            raise ValueError(f"corpus entry {path} missing field {field!r}")
    plan = plan_from_json(json.dumps(raw["plan"]))
    if len(plan.ops) > MAX_OPS:
        raise ValueError(f"corpus entry {path} has {len(plan.ops)} ops, cap is {MAX_OPS}")
    return {
        "name": str(raw["name"]),
        "note": str(raw["note"]),
        "seed": int(raw["seed"]),
        "plan": plan,
        "expect": dict(raw["expect"]),
    }


# shape: (corpus_dir: str) -> obj
def load_corpus(corpus_dir: str) -> list[dict]:
    """All entries, sorted by filename for a deterministic replay order."""
    if not os.path.isdir(corpus_dir):
        return []
    out = []
    for fname in sorted(os.listdir(corpus_dir)):
        if fname.endswith(".json"):
            out.append(load_entry(os.path.join(corpus_dir, fname)))
    return out


# shape: (entry: obj) -> (bool, obj, obj)
def replay_entry(entry: dict) -> tuple[bool, list[str], dict]:
    """Re-run one corpus entry from (plan, seed) and check every
    expectation: fingerprint equality IS the bit-identity assertion."""
    card, violations = run_plan(entry["plan"], entry["seed"])
    expect = entry["expect"]
    problems: list[str] = []
    if card["fingerprint"] != expect["fingerprint"]:
        problems.append(f"fingerprint drifted: {card['fingerprint']} != {expect['fingerprint']}")
    if bool(card["pass"]) != bool(expect["pass"]):
        problems.append(f"pass verdict drifted: {card['pass']} != {expect['pass']}")
    if list(violations) != list(expect.get("violations", [])):
        problems.append(f"violations drifted: {violations} != {expect.get('violations')}")
    for path, want in sorted(expect.get("pins", {}).items()):
        got = card_value(card, path)
        if got != want:
            problems.append(f"pin {path} drifted: {got!r} != {want!r}")
    return (not problems), problems, card


# shape: (entry_name: str, note: str, plan: obj, seed: int, card: obj, violations: obj) -> obj
def entry_for(entry_name: str, note: str, plan: FaultPlan, seed: int, card: dict, violations: list, pins: dict | None = None) -> dict:
    """Build the JSON body for a new corpus entry from a finished run."""
    out = {
        "name": entry_name,
        "note": note,
        "seed": int(seed),
        "expect": {
            "fingerprint": card["fingerprint"],
            "pass": bool(card["pass"]),
            "violations": list(violations),
        },
        "plan": plan.to_json(),
    }
    if pins:
        out["expect"]["pins"] = dict(sorted(pins.items()))
    assert tuple(out) == ENTRY_FIELDS, "corpus entry drifted from ENTRY_FIELDS"
    return out
