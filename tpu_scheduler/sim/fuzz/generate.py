"""Seeded, coverage-biased fault-plan generator.

All randomness flows from one labeled stream (``f"{seed}:fuzz-gen"``), so a
campaign is a pure function of (seed, budget): re-running it replays the
same plans in the same order.  Coverage feedback is deterministic too — the
runs that update the map are themselves seeded — so the guided search stays
bit-reproducible end to end.
"""

from __future__ import annotations

import random

from .coverage import CoverageMap
from .plan import FAULT_OPS, HAZARD_OPS, MAX_OPS, EVENT_OPS, FaultOp, FaultPlan, BASE_WORKLOADS, op_valid_for_base

__all__ = ["PlanGenerator"]

# Severity ladder: quantized so shrunk magnitudes stay on round, diffable
# values and the search space stays small.
_MAGNITUDES = (0.25, 0.5, 0.75, 1.0)

# Plan durations (virtual seconds) the generator samples from.
_DURATIONS = (22.0, 26.0, 30.0)


class PlanGenerator:
    """Generates :class:`FaultPlan` instances, biased toward fault-op kinds
    with unseen (kind × facet) coverage pairs."""

    def __init__(self, seed: int, coverage: CoverageMap | None = None, max_ops: int = MAX_OPS) -> None:
        self.seed = int(seed)
        self.rng = random.Random(f"{seed}:fuzz-gen")
        self.coverage = coverage if coverage is not None else CoverageMap()
        self.max_ops = min(int(max_ops), MAX_OPS)
        self._bases = tuple(sorted(BASE_WORKLOADS))

    def _pick_kind(self, base: str, have_kill: bool) -> str:
        """Weighted pick: 1 + unseen-facet count per kind, so kinds that
        have already been injected under every subsystem state decay to
        baseline weight instead of dominating the schedule."""
        kinds = [k for k in FAULT_OPS if op_valid_for_base(k, base)]
        if have_kill:
            kinds = [k for k in kinds if k != "replica-kill"]
        weights = [1 + self.coverage.unseen(k) for k in kinds]
        total = sum(weights)
        roll = self.rng.random() * total
        acc = 0.0
        for kind, w in zip(kinds, weights):
            acc += w
            if roll < acc:
                return kind
        return kinds[-1]

    def _make_op(self, kind: str, duration: float) -> FaultOp:
        mag = self.rng.choice(_MAGNITUDES)
        if kind == "replica-kill":
            # Kills land mid-run: late enough that shards settled, early
            # enough that takeover + drain fit inside the settle bound.
            t0 = round(self.rng.uniform(8.0, 0.6 * duration), 1)
            return FaultOp(kind=kind, t0=t0, t1=t0, magnitude=mag)
        if kind in EVENT_OPS or kind in HAZARD_OPS:
            t0 = round(self.rng.uniform(4.0, 0.7 * duration), 1)
            return FaultOp(kind=kind, t0=t0, t1=t0, magnitude=mag)
        t0 = round(self.rng.uniform(3.0, 0.7 * duration), 1)
        t1 = round(t0 + self.rng.uniform(3.0, 10.0), 1)
        return FaultOp(kind=kind, t0=t0, t1=t1, magnitude=mag)

    # shape: (index: int) -> obj
    def next_plan(self, index: int) -> FaultPlan:
        """Generate campaign plan number ``index`` (round-robin bases, so
        rack and autoscale vocabularies are all exercised)."""
        base = self._bases[index % len(self._bases)]
        duration = self.rng.choice(_DURATIONS)
        n_ops = self.rng.randint(2, self.max_ops)
        ops: list[FaultOp] = []
        for _ in range(n_ops):
            have_kill = any(op.kind == "replica-kill" for op in ops)
            kind = self._pick_kind(base, have_kill)
            ops.append(self._make_op(kind, duration))
        ops.sort(key=lambda op: (op.t0, op.kind, op.t1, op.magnitude))
        return FaultPlan(
            plan_id=f"plan-{self.seed}-{index:04d}",
            base=base,
            duration=duration,
            ops=tuple(ops),
        )
