"""``python -m tpu_scheduler.cli sim fuzz`` — the chaos-fuzzing campaign.

One invocation = corpus replay + a seeded generation campaign:

  sim fuzz --budget 200 --seed 0 --runlog out.jsonl

First every checked-in reproducer in ``--corpus`` replays (fingerprint,
verdict, violations, pins — all must match); then ``--budget`` fresh plans
are generated coverage-guided, run, and judged.  Any new violation is
shrunk to a minimal plan and (with ``--write-corpus``) written into the
corpus.  The run log contains only virtual-time quantities, so the same
(budget, seed) pair produces a byte-identical log anywhere — the sim's
determinism contract extended to the search.

Exit codes: 0 = corpus green and no new violations, 1 = a corpus entry
drifted or the campaign found a violation.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .corpus import entry_for, load_corpus, replay_entry
from .coverage import CoverageMap
from .generate import PlanGenerator
from .oracle import run_plan
from .plan import MAX_OPS, plan_to_json
from .shrink import shrink_plan

__all__ = ["main"]

DEFAULT_CORPUS = os.path.join("tests", "fuzz_corpus")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="tpu-scheduler sim fuzz", description=__doc__)
    p.add_argument("--budget", type=int, default=50, help="number of fresh plans to generate and judge")
    p.add_argument("--seed", type=int, default=0, help="the ONE campaign seed (plans, workloads, chaos all derive)")
    p.add_argument("--corpus", default=DEFAULT_CORPUS, metavar="DIR", help="reproducer corpus to replay first")
    p.add_argument("--no-corpus", action="store_true", help="skip the corpus replay phase")
    p.add_argument("--runlog", default=None, metavar="PATH", help="write the per-plan JSONL log here (deterministic)")
    p.add_argument("--write-corpus", action="store_true", help="write shrunk reproducers for new violations into --corpus")
    p.add_argument("--max-ops", type=int, default=MAX_OPS, help=f"ops per generated plan, capped at {MAX_OPS}")
    p.add_argument("--shrink", dest="shrink", action="store_true", default=True, help="shrink new violations (default)")
    p.add_argument("--no-shrink", dest="shrink", action="store_false", help="report violations unshrunk")
    p.add_argument("--log-level", default="ERROR", help="scheduler log level (campaign noise is off by default)")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    from ...utils.tracing import configure_logging

    configure_logging(args.log_level, "text")
    log_lines: list[str] = []

    def log(obj: dict) -> None:
        line = json.dumps(obj, sort_keys=True)
        log_lines.append(line)
        print(line)

    corpus_ok = True
    corpus_n = 0
    if not args.no_corpus:
        for entry in load_corpus(args.corpus):
            ok, problems, card = replay_entry(entry)
            corpus_n += 1
            corpus_ok = corpus_ok and ok
            log(
                {
                    "corpus": entry["name"],
                    "ok": ok,
                    "problems": problems,
                    "fingerprint": card["fingerprint"],
                    "ops": len(entry["plan"].ops),
                }
            )

    coverage = CoverageMap()
    gen = PlanGenerator(args.seed, coverage, max_ops=args.max_ops)
    found: list[dict] = []
    for i in range(args.budget):
        plan = gen.next_plan(i)
        card, violations = run_plan(plan, args.seed, coverage)
        log(
            {
                "plan": plan.plan_id,
                "base": plan.base,
                "ops": len(plan.ops),
                "pass": card["pass"],
                "violations": violations,
                "fingerprint": card["fingerprint"],
                "coverage_pairs": coverage.distinct(),
            }
        )
        if violations:
            minimal = shrink_plan(plan, args.seed) if args.shrink else plan
            mcard, mviol = run_plan(minimal, args.seed)
            found.append({"plan": minimal, "card": mcard, "violations": mviol})
            log(
                {
                    "violation": minimal.plan_id,
                    "shrunk_ops": len(minimal.ops),
                    "violations": mviol,
                    "plan_json": plan_to_json(minimal),
                }
            )
            if args.write_corpus:
                body = entry_for(
                    entry_name=f"{minimal.plan_id}-min",
                    note=f"Shrunk reproducer found by sim fuzz --seed {args.seed}; violates: {', '.join(mviol)}.",
                    plan=minimal,
                    seed=args.seed,
                    card=mcard,
                    violations=mviol,
                )
                os.makedirs(args.corpus, exist_ok=True)
                path = os.path.join(args.corpus, f"{minimal.plan_id}-min.json")
                with open(path, "w", encoding="utf-8") as fh:
                    json.dump(body, fh, indent=2, sort_keys=True)
                    fh.write("\n")
    summary = {
        "fuzz": True,
        "seed": args.seed,
        "budget": args.budget,
        "corpus_replayed": corpus_n,
        "corpus_ok": corpus_ok,
        "violations_found": len(found),
        "coverage_pairs": coverage.distinct(),
        "lease_pairs": coverage.lease_pairs(),
        "coverage": coverage.to_json(),
    }
    log(summary)
    if args.runlog:
        with open(args.runlog, "w", encoding="utf-8") as fh:
            fh.write("\n".join(log_lines) + "\n")
    return 0 if corpus_ok and not found else 1


if __name__ == "__main__":
    sys.exit(main())
