"""Coverage map: (fault-op × subsystem-state-at-injection) pairs.

A fault op is only interesting relative to what the scheduler was *doing*
when it landed: a lease 500 during a takeover is a different test than the
same 500 against an idle fleet.  The oracle samples a small closed set of
subsystem facets (:data:`STATE_FACETS`) at the cycle each op first becomes
active and records one (kind, facet) pair per facet.  The generator then
biases kind selection toward ops with unseen facets, steering random search
into the interleavings the scripted scenarios never pinned.
"""

from __future__ import annotations

__all__ = ["STATE_FACETS", "CoverageMap", "sample_facets"]

# Closed facet vocabulary — one axis per subsystem whose in-flight state
# changes what a fault can break.  Gated by the FUZZ analyze rule.
STATE_FACETS = (
    "breaker-closed",  # every live replica's circuit breaker is closed
    "breaker-open",  # some live breaker is open or half-open
    "shards-stable",  # shard ownership unchanged since the previous cycle
    "shards-churning",  # ownership moved (takeover / rebalance of shards)
    "rebalance-idle",  # no drain migrations in flight
    "rebalance-active",  # drain migrations in flight on a live replica
    "autoscale-idle",  # no provider provisions pending
    "autoscale-active",  # provider provisions pending
    "fleet-full",  # every replica alive
    "fleet-degraded",  # at least one replica crashed/killed
)


class CoverageMap:
    """Counting map of (fault-op kind, state facet) pairs."""

    def __init__(self) -> None:
        self.pairs: dict[tuple[str, str], int] = {}

    # shape: (kind: str, facets: obj) -> obj
    def record(self, kind: str, facets: tuple[str, ...]) -> None:
        for facet in facets:
            key = (kind, facet)
            self.pairs[key] = self.pairs.get(key, 0) + 1

    def distinct(self) -> int:
        return len(self.pairs)

    def lease_pairs(self) -> int:
        """Distinct pairs whose op kind is one of the lease faults."""
        return sum(1 for kind, _facet in self.pairs if kind.startswith("lease-"))

    # shape: (kind: str) -> int
    def unseen(self, kind: str) -> int:
        """How many facets this kind has never been injected under —
        the generator's bias weight."""
        seen = sum(1 for k, _facet in self.pairs if k == kind)
        return len(STATE_FACETS) - seen

    def to_json(self) -> list:
        """Deterministic listing: sorted (kind, facet, count) triples."""
        return [[k, f, self.pairs[(k, f)]] for k, f in sorted(self.pairs)]


# shape: (ctx: obj, prev_owned: obj) -> (obj, obj)
def sample_facets(ctx, prev_owned) -> tuple[tuple[str, ...], tuple]:
    """Read the subsystem facets out of an EpisodeContext at cycle start.

    ``prev_owned`` is the previous cycle's ownership snapshot (or None on
    the first sample); churn is ownership delta between the two.  Reads are
    strictly side-effect free: breaker state comes from the ``.state``
    attribute (``mode()`` would promote open → half-open as a side effect).
    """
    fleet = ctx.fleet
    live = [r for i, r in enumerate(fleet.scheds) if fleet.alive[i]]
    breaker_open = any(r.breaker.state != "closed" for r in live)
    owned = tuple(
        tuple(sorted(r.shard_set.owned)) if getattr(r, "shard_set", None) is not None else ()
        for i, r in enumerate(fleet.scheds)
        if fleet.alive[i]
    )
    churning = prev_owned is not None and owned != prev_owned
    rebalance_active = any(getattr(r, "rebalancer", None) is not None and r.rebalancer.inflight for r in live)
    provider = getattr(fleet, "provider", None)
    autoscale_active = provider is not None and provider.pending_provisions() > 0
    degraded = not all(fleet.alive)
    facets = (
        "breaker-open" if breaker_open else "breaker-closed",
        "shards-churning" if churning else "shards-stable",
        "rebalance-active" if rebalance_active else "rebalance-idle",
        "autoscale-active" if autoscale_active else "autoscale-idle",
        "fleet-degraded" if degraded else "fleet-full",
    )
    return facets, owned
