"""Delta-debugging shrinker: failing plan → minimal reproducer.

Greedy fixed-point reduction: repeatedly try dropping whole ops, then
halving window lengths and magnitudes, keeping any candidate that still
trips the ORIGINAL primary violation under a deterministic re-run.  Every
probe is a full seeded simulation, so the shrink trajectory itself is
reproducible.  The result is what lands in ``tests/fuzz_corpus/`` — small
enough to read, strong enough to pin the bug forever.
"""

from __future__ import annotations

from dataclasses import replace

from .oracle import run_plan
from .plan import EVENT_OPS, HAZARD_OPS, FaultOp, FaultPlan

__all__ = ["shrink_plan"]

# Stop shrinking a window below this many virtual seconds / a magnitude
# below this rung — probes get meaninglessly weak past these floors.
_MIN_WINDOW_S = 2.0
_MIN_MAGNITUDE = 0.25


def _op_shrink_candidates(op: FaultOp) -> list[FaultOp]:
    out: list[FaultOp] = []
    if op.kind not in EVENT_OPS and op.kind not in HAZARD_OPS:
        span = op.t1 - op.t0
        if span > _MIN_WINDOW_S:
            out.append(replace(op, t1=round(op.t0 + max(_MIN_WINDOW_S, span / 2.0), 1)))
    if op.magnitude > _MIN_MAGNITUDE:
        out.append(replace(op, magnitude=round(max(_MIN_MAGNITUDE, op.magnitude / 2.0), 3)))
    return out


# shape: (plan: obj, seed: int) -> obj
def shrink_plan(plan: FaultPlan, seed: int, run=None) -> FaultPlan:
    """Reduce ``plan`` to a local minimum that still reproduces its primary
    (first-listed) violation at ``seed``.

    ``run`` is injectable for tests: a callable (plan) -> list of violation
    names; defaults to the real oracle.
    """
    if run is None:

        def run(p, _seed=seed):
            return run_plan(p, _seed)[1]

    violations = run(plan)
    if not violations:
        return plan
    primary = violations[0]
    changed = True
    while changed:
        changed = False
        # Pass 1: drop whole ops (never below one — an empty plan can't
        # reproduce anything).
        for i in range(len(plan.ops)):
            if len(plan.ops) <= 1:
                break
            cand = replace(plan, ops=plan.ops[:i] + plan.ops[i + 1 :])
            if primary in run(cand):
                plan = cand
                changed = True
                break
        if changed:
            continue
        # Pass 2: weaken surviving ops (shorter windows, lower magnitudes).
        for i, op in enumerate(plan.ops):
            for cand_op in _op_shrink_candidates(op):
                cand = replace(plan, ops=plan.ops[:i] + (cand_op,) + plan.ops[i + 1 :])
                if primary in run(cand):
                    plan = cand
                    changed = True
                    break
            if changed:
                break
    return plan
