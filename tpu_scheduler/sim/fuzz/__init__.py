"""Coverage-guided chaos fuzzer (``sim fuzz``) — randomized fault-schedule
search over the deterministic simulator.

The pipeline: a seeded :class:`PlanGenerator` composes :data:`FAULT_OPS`
into serializable :class:`FaultPlan` schedules over parameterized base
workloads; :func:`run_plan` executes each plan through the ordinary
``scenario_episode`` loop and judges it on the union of every scorecard
pass gate plus the end-state convergence check; a :class:`CoverageMap` of
(fault-op × subsystem-state-at-injection) pairs biases generation toward
unseen interleavings; :func:`shrink_plan` delta-debugs a failing plan to a
minimal reproducer for ``tests/fuzz_corpus/``, replayed forever by tier-1.

Everything is derived from ONE campaign seed — the same ``--budget --seed``
pair produces a byte-identical run log on every machine (the sim's
record→replay determinism contract, extended to the search itself).
"""

from .coverage import STATE_FACETS, CoverageMap
from .generate import PlanGenerator
from .oracle import judge_card, run_plan
from .plan import BASE_WORKLOADS, FAULT_OPS, FaultOp, FaultPlan, compile_plan, plan_from_json, plan_to_json
from .shrink import shrink_plan

__all__ = [
    "BASE_WORKLOADS",
    "FAULT_OPS",
    "STATE_FACETS",
    "CoverageMap",
    "FaultOp",
    "FaultPlan",
    "PlanGenerator",
    "compile_plan",
    "judge_card",
    "plan_from_json",
    "plan_to_json",
    "run_plan",
    "shrink_plan",
]
