"""Invariant oracle: run one fault plan, judge it on the full battery.

``run_plan`` compiles a plan, drives it through ``scenario_episode`` (the
same loop every scripted scenario uses), samples the subsystem-state facets
at each op's activation cycle into the campaign :class:`CoverageMap`, and
returns the scorecard plus the list of violated gates.  ``judge_card``
mirrors the scorecard's composite ``pass`` gate clause by clause, so a
violation name points straight at the failed subsystem instead of a bare
``pass: false``.
"""

from __future__ import annotations

from ..harness import scenario_episode
from .coverage import CoverageMap, sample_facets
from .plan import FaultPlan, compile_plan

__all__ = ["VIOLATIONS", "card_value", "judge_card", "run_plan"]

# Closed violation vocabulary — one name per scorecard pass-gate clause the
# fuzzer can trip (locality/profile/incremental/policy/latency gates are
# never required by fuzz bases, so they cannot appear).
VIOLATIONS = (
    "invariants",  # capacity/selector/gang placement invariants broke
    "lost-pods",  # a pod vanished without bind or terminal state
    "double-binds",  # one pod bound twice
    "binds-while-open",  # a bind POST went through an OPEN breaker
    "availability",  # double-bind/orphan/slow takeover in the replica set
    "rebalance",  # orphaned migration or deschedule through open breaker
    "elasticity",  # autoscaler objective gate or reclaim orphans
    "convergence",  # end state failed to quiesce after the last fault
)


# shape: (card: obj) -> obj
def judge_card(card: dict) -> list[str]:
    """Names of every violated pass gate, in VIOLATIONS order."""
    out: list[str] = []
    if not card["invariants"].get("ok"):
        out.append("invariants")
    if card["pods"].get("lost", 0) != 0:
        out.append("lost-pods")
    if card["pods"].get("double_bound", 0) != 0:
        out.append("double-binds")
    if card["resilience"].get("binds_while_open", 0) != 0:
        out.append("binds-while-open")
    av = card["availability"]
    if av.get("enabled") and not av.get("ok"):
        out.append("availability")
    rb = card["rebalance"]
    if rb.get("enabled") and (rb.get("orphaned_migrations", 0) != 0 or rb.get("unbinds_while_open", 0) != 0):
        out.append("rebalance")
    el = card["elasticity"]
    if el.get("enabled") and el.get("reclaim_orphans", 0) != 0:
        out.append("elasticity")
    cv = card["convergence"]
    if cv.get("required") and not cv.get("ok"):
        out.append("convergence")
    for v in out:
        assert v in VIOLATIONS, f"judge emitted unknown violation {v!r}"
    return out


# shape: (card: obj, path: str) -> obj
def card_value(card: dict, path: str):
    """Resolve a dotted path ("availability.max_takeover_latency_s") into a
    scorecard — the corpus pin mechanism for near-miss plans."""
    node = card
    for part in path.split("."):
        node = node[part]
    return node


# shape: (plan: obj, seed: int) -> (obj, obj)
def run_plan(
    plan: FaultPlan,
    seed: int,
    coverage: CoverageMap | None = None,
    record: str | None = None,
) -> tuple[dict, list[str]]:
    """Execute one plan deterministically; optionally record the underlying
    JSONL trace.  (Trace *replay* resolves scenarios by registry name, which
    compiled fuzz scenarios deliberately don't have — bit-identity for plans
    is asserted by re-running from (plan, seed) and comparing
    fingerprints.)"""
    sc = compile_plan(plan)
    gen = scenario_episode(sc, seed=seed, record=record)
    activated = [False] * len(plan.ops)
    prev_owned = None
    card: dict
    try:
        ctx = next(gen)
        while True:
            now = ctx.clock.now
            facets, prev_owned = sample_facets(ctx, prev_owned)
            if coverage is not None:
                for i, op in enumerate(plan.ops):
                    if not activated[i] and op.t0 <= now:
                        activated[i] = True
                        coverage.record(op.kind, facets)
            ctx = gen.send(None)
    except StopIteration as stop:
        card = stop.value
    return card, judge_card(card)
