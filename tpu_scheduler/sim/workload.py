"""Seeded workload generator — everything a scenario throws at the cluster,
parameterized by ONE rng seed.

The generator emits a time-ordered list of high-level ``SimEvent``s:

  • ``pods``       — a Poisson arrival or a burst: fully-sampled pod specs
                     (cpu/mem tier, priority tier, optional nodeSelector,
                     optional gang of 2..k members, a sampled lifetime)
  • ``node-add``   — a new node joins (fleet growth)
  • ``node-drain`` — cordon a node and evict its pods (they re-arrive as
                     fresh Pending pods — the ReplicaSet stand-in)
  • ``node-fail``  — the node vanishes outright, pods re-arrive Pending
  • ``node-flap``  — fail + automatic return of the SAME node after
                     ``down_s`` virtual seconds (the NotReady flap)

Node-targeting events carry a ``pick`` float in [0, 1) instead of a node
name: the harness resolves it against the sorted live node list at apply
time, so generation never needs to simulate cluster state — and the
RESOLVED op stream is what the trace records, keeping replays bit-identical
regardless of resolution logic.

All sampling comes from the single ``random.Random`` the caller passes;
each process (arrivals, each churn kind) draws from its own derived seed so
event streams merge deterministically by (time, stream, index).
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field

__all__ = ["WorkloadSpec", "SimEvent", "generate_events", "initial_nodes"]

# Heterogeneous fleet shapes (cpu cores, memory GiB) — testing.py's tiers.
NODE_SHAPES = ((8, 32), (16, 64), (32, 128))
ZONES = ("zone-a", "zone-b", "zone-c", "zone-d")


@dataclass(frozen=True)
class WorkloadSpec:
    """One scenario's workload shape (all times/rates in VIRTUAL seconds)."""

    initial_nodes: int = 50
    arrival_rate: float = 10.0  # Poisson pod arrivals per virtual second
    bursts: tuple[tuple[float, int], ...] = ()  # (t, n_pods) storms
    gang_fraction: float = 0.0  # fraction of arrivals opening a gang
    gang_size_max: int = 4  # gangs are 2..gang_size_max members
    priority_tiers: tuple[int, ...] = (0,)  # sampled uniformly per pod
    selector_fraction: float = 0.0  # fraction pinning a zone nodeSelector
    pod_cpu_m: tuple[int, ...] = (100, 250, 500, 1000)
    pod_mem_mi: tuple[int, ...] = (128, 256, 512, 1024)
    lifetime_mean_s: float = 0.0  # Exp(mean) run time after bind; 0 = forever
    # Diurnal traffic: when ``diurnal_period`` > 0 the Poisson arrival rate
    # becomes rate(t) = arrival_rate * (1 + amplitude * sin(2πt/period)) —
    # sampled by thinning at the peak rate, so the elastic-capacity wave
    # the autoscaler must ride is itself seeded and deterministic.
    diurnal_period: float = 0.0  # virtual seconds per wave (0 = flat rate)
    diurnal_amplitude: float = 0.0  # fractional swing around arrival_rate
    node_add_rate: float = 0.0  # churn processes, events per virtual second
    node_drain_rate: float = 0.0
    node_fail_rate: float = 0.0
    node_flap_rate: float = 0.0
    flap_down_s: float = 4.0  # how long a flapping node stays gone
    # Interconnect topology (topology/): consecutive node indices group into
    # slices of ``slice_size`` and racks of ``rack_size`` nodes (0 = level
    # absent).  The harness turns these into the default topology node
    # labels, which topology-enables the scheduler under test.
    slice_size: int = 0
    rack_size: int = 0
    # Whole-rack outages: at each listed virtual time, one rack (picked by a
    # seeded draw against the live rack list) fails outright — every node in
    # it vanishes and its pods re-arrive Pending (the rack-power-loss /
    # spine-failure event gangs must survive).
    rack_fail_times: tuple[float, ...] = ()


@dataclass(frozen=True)
class SimEvent:
    t: float
    kind: str  # pods | node-add | node-drain | node-fail | node-flap
    payload: dict = field(default_factory=dict)


def _pod_spec(rng: random.Random, spec: WorkloadSpec, name: str, gang: str | None) -> dict:
    """One pod as a primitives-only dict (trace/JSONL-safe)."""
    p: dict = {
        "name": name,
        "cpu_m": rng.choice(spec.pod_cpu_m),
        "mem_mi": rng.choice(spec.pod_mem_mi),
        "priority": rng.choice(spec.priority_tiers),
        "app": f"app-{rng.randrange(24)}",
    }
    if gang:
        p["gang"] = gang
    if spec.selector_fraction and rng.random() < spec.selector_fraction:
        p["zone"] = rng.choice(ZONES)
    if spec.lifetime_mean_s > 0:
        p["lifetime_s"] = round(rng.expovariate(1.0 / spec.lifetime_mean_s), 6)
    return p


def _arrival_group(rng: random.Random, spec: WorkloadSpec, seq_start: int) -> tuple[list[dict], int]:
    """One arrival: a single pod, or a whole gang of 2..gang_size_max."""
    seq = seq_start
    if spec.gang_fraction and rng.random() < spec.gang_fraction:
        size = rng.randrange(2, spec.gang_size_max + 1)
        gang = f"gang-{seq}"
        pods = []
        for _ in range(size):
            pods.append(_pod_spec(rng, spec, f"sim-p{seq}", gang))
            seq += 1
        # Gang members share one priority — mixed-priority gangs would split
        # across segments and be refused forever by design.
        prio = pods[0]["priority"]
        for p in pods:
            p["priority"] = prio
        return pods, seq
    pod = _pod_spec(rng, spec, f"sim-p{seq}", None)
    return [pod], seq + 1


def generate_events(spec: WorkloadSpec, duration: float, rng: random.Random) -> list[SimEvent]:
    """The full timed event stream for one run — deterministic in (spec,
    duration, rng seed).  Sorted by (t, stream priority, index)."""
    streams: list[tuple[float, int, int, SimEvent]] = []

    # Poisson arrivals (stream 0).
    arr_rng = random.Random(rng.randrange(1 << 62))
    t, seq, idx = 0.0, 0, 0
    if spec.arrival_rate > 0 and spec.diurnal_period > 0:
        # Thinning (Lewis–Shedler): draw at the peak rate, accept with
        # probability rate(t)/peak.  Gated on diurnal_period so the flat
        # path below stays draw-for-draw identical to every older trace.
        import math

        peak = spec.arrival_rate * (1.0 + abs(spec.diurnal_amplitude))
        while True:
            t += arr_rng.expovariate(peak)
            if t >= duration:
                break
            rate_t = spec.arrival_rate * (
                1.0 + spec.diurnal_amplitude * math.sin(2.0 * math.pi * t / spec.diurnal_period)
            )
            if arr_rng.random() * peak > rate_t:
                continue
            pods, seq = _arrival_group(arr_rng, spec, seq)
            streams.append((t, 0, idx, SimEvent(round(t, 6), "pods", {"pods": pods})))
            idx += 1
    elif spec.arrival_rate > 0:
        while True:
            t += arr_rng.expovariate(spec.arrival_rate)
            if t >= duration:
                break
            pods, seq = _arrival_group(arr_rng, spec, seq)
            streams.append((t, 0, idx, SimEvent(round(t, 6), "pods", {"pods": pods})))
            idx += 1

    # Bursts (stream 1) — a storm is one event with n fully-sampled pods.
    burst_rng = random.Random(rng.randrange(1 << 62))
    for i, (bt, n) in enumerate(spec.bursts):
        pods = []
        while len(pods) < n:
            group, seq = _arrival_group(burst_rng, spec, seq)
            pods.extend(group)
        streams.append((float(bt), 1, i, SimEvent(round(float(bt), 6), "pods", {"pods": pods})))

    # Node churn processes (streams 2..5), each an independent Poisson.
    for stream, (kind, rate) in enumerate(
        (
            ("node-add", spec.node_add_rate),
            ("node-drain", spec.node_drain_rate),
            ("node-fail", spec.node_fail_rate),
            ("node-flap", spec.node_flap_rate),
        ),
        start=2,
    ):
        churn_rng = random.Random(rng.randrange(1 << 62))
        if rate <= 0:
            continue
        ct, i = 0.0, 0
        node_seq = spec.initial_nodes
        while True:
            ct += churn_rng.expovariate(rate)
            if ct >= duration:
                break
            if kind == "node-add":
                payload = _node_payload(node_seq, churn_rng, spec)
                node_seq += 1
            elif kind == "node-flap":
                payload = {"pick": churn_rng.random(), "down_s": spec.flap_down_s}
            else:
                payload = {"pick": churn_rng.random()}
            streams.append((ct, stream, i, SimEvent(round(ct, 6), kind, payload)))
            i += 1

    # Whole-rack outages (stream 6) — fixed times from the spec, the rack
    # picked by a seeded draw resolved against the live rack list at apply
    # time (same ``pick`` convention as the node-targeting events).
    rack_rng = random.Random(rng.randrange(1 << 62))
    for i, rt in enumerate(spec.rack_fail_times):
        streams.append(
            (float(rt), 6, i, SimEvent(round(float(rt), 6), "rack-fail", {"pick": rack_rng.random()}))
        )

    streams.sort(key=lambda e: (e[0], e[1], e[2]))
    return [ev for _, _, _, ev in streams]


def _topology_fields(i: int, spec: WorkloadSpec) -> dict:
    """Per-node slice/rack assignment from the consecutive-index grouping."""
    out: dict = {}
    if spec.slice_size > 0:
        out["slice"] = f"slice-{i // spec.slice_size}"
    if spec.rack_size > 0:
        out["rack"] = f"rack-{i // spec.rack_size}"
    return out


def _node_payload(i: int, rng: random.Random, spec: WorkloadSpec) -> dict:
    cores, gib = NODE_SHAPES[rng.randrange(len(NODE_SHAPES))]
    return {"name": f"sim-n{i}", "cpu": cores, "mem_gi": gib, "zone": ZONES[i % len(ZONES)], **_topology_fields(i, spec)}


def initial_nodes(spec: WorkloadSpec) -> list[dict]:
    """The t=0 fleet — shapes round-robin over the tiers (no rng: the
    starting cluster is part of the scenario, not the sample)."""
    out = []
    for i in range(spec.initial_nodes):
        cores, gib = NODE_SHAPES[i % len(NODE_SHAPES)]
        out.append(
            {"name": f"sim-n{i}", "cpu": cores, "mem_gi": gib, "zone": ZONES[i % len(ZONES)], **_topology_fields(i, spec)}
        )
    return out
