"""Trace record/replay — the simulator's persistence layer.

A trace is one JSONL file carrying everything a run consumed that was not
pure computation:

  ``header``  — scenario name, seed, backend, schema version
  ``action``  — every RESOLVED cluster op the harness applied, with its
                virtual timestamp (pod creations with full specs, node
                add/remove/cordon, completions, flap returns).  This is the
                persisted WatchEvent stream: applying the ops reproduces the
                exact ADDED/MODIFIED/DELETED sequence the reflectors saw.
  ``chaos``   — the chaos layer's decision schedule, in call order
                (sim/chaos.py replays it verbatim instead of re-drawing).
  ``cycle``   — one line per scheduler cycle (virtual time, bound count) —
                the cross-link into the PR-1 flight recorder's cycle ring.
  ``footer``  — the run's determinism fingerprint and scorecard, so a
                replay can verify bit-identity without a second artifact.

Replaying feeds the recorded actions and chaos decisions back through the
same harness; with the clock, workload, and faults all reproduced, the
scheduler's binding sequence — and therefore the fingerprint — must match
bit-for-bit.
"""

from __future__ import annotations

import json

__all__ = ["TraceWriter", "load_trace", "TRACE_VERSION"]

TRACE_VERSION = 1


class TraceWriter:
    """Streaming JSONL writer (one object per line, written in run order)."""

    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "w")

    def header(self, scenario: str, seed: int, backend: str) -> None:
        self._line({"type": "header", "version": TRACE_VERSION, "scenario": scenario, "seed": seed, "backend": backend})

    def action(self, t: float, op: dict) -> None:
        # Exact float, NOT rounded: replay gates ops on ``t <= clock.now``
        # against the bit-identical replayed clock, and rounding up past the
        # true boundary would defer the op a whole cycle (JSON round-trips
        # Python floats losslessly, so exactness costs nothing).
        self._line({"type": "action", "t": t, "op": op})

    def chaos(self, endpoint: str, injected: bool, latency: float) -> None:
        self._line({"type": "chaos", "ep": endpoint, "inject": injected, "lat": latency})

    def cycle(self, t: float, cycle: int, bound: int, pending: int) -> None:
        self._line({"type": "cycle", "t": t, "cycle": cycle, "bound": bound, "pending": pending})

    def footer(self, fingerprint: str, scorecard: dict) -> None:
        self._line({"type": "footer", "fingerprint": fingerprint, "scorecard": scorecard})

    def _line(self, obj: dict) -> None:
        self._f.write(json.dumps(obj, sort_keys=True) + "\n")

    def close(self) -> None:
        self._f.close()


def load_trace(path: str) -> dict:
    """Parse a trace into {header, actions, chaos, footer}.

    ``actions`` is ``[(t, op), ...]`` in recorded order; ``chaos`` is the
    decision list shaped for ``ChaosApiServer(replay_decisions=...)``."""
    header = footer = None
    actions: list[tuple[float, dict]] = []
    chaos: list[tuple[str, bool, float]] = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            obj = json.loads(line)
            kind = obj.get("type")
            if kind == "header":
                if obj.get("version") != TRACE_VERSION:
                    raise ValueError(f"{path}:{lineno}: unsupported trace version {obj.get('version')}")
                header = obj
            elif kind == "action":
                actions.append((float(obj["t"]), obj["op"]))
            elif kind == "chaos":
                chaos.append((obj["ep"], bool(obj["inject"]), float(obj.get("lat", 0.0))))
            elif kind == "footer":
                footer = obj
            # "cycle" lines are observability breadcrumbs, not replay input.
    if header is None:
        raise ValueError(f"{path}: not a sim trace (no header line)")
    return {"header": header, "actions": actions, "chaos": chaos, "footer": footer}
