"""Programmable chaos layer over ``FakeApiServer``.

Generalizes the runtime's one-off fault hooks — ``fail_next_bindings``
(runtime/fake_api.py) and the tests' hand-rolled ``FlakyWatch`` — into one
declarative, SEEDED fault surface the simulator (and any test) can drive:

  • binding 500s (``CreateBindingFailed``) at a configurable rate
  • virtual binding latency (advances a ``VirtualClock`` per POST — the
    in-process twin of a slow apiserver)
  • generic API errors on the scheduler-facing mutation/read endpoints
    (``delete_pod`` evictions, ``list_pdbs``)
  • watch drops (``ConnectionError``) and 410 Gone storms (``ApiError(410)``)
    raised from ``poll()`` — events stay queued, exactly the FlakyWatch
    contract, so the reflector's backoff-and-retry path is what recovers
  • lease-op faults on the coordination surface every control-plane
    protocol rides (shard leases, replica presence, gang reservations, the
    shard map): CAS 500s (``lease_error_rate``), refused acquires
    (``lease_refused_rate`` — the CAS loses as if a conflicting writer
    won), and virtual lease latency (``lease_latency_s``)
  • timed fault WINDOWS overriding any base rate over a virtual interval
    (an api-brownout is one window; a flap storm is several)

Every injection decision is drawn from one dedicated RNG in call order, and
every decision is exposed through ``decision_log`` so a trace can replay
the exact fault schedule bit-identically (sim/trace.py).  The wrapper is a
transparent proxy (``__getattr__``) for everything it does not fault, so it
drops into ``Scheduler(api=...)`` unchanged.
"""

from __future__ import annotations

import random
from dataclasses import dataclass

from ..errors import CreateBindingFailed
from ..runtime.fake_api import ApiError, FakeApiServer

__all__ = ["ChaosConfig", "ChaosWindow", "ChaosApiServer", "ChaosWatch"]


@dataclass(frozen=True)
class ChaosWindow:
    """Rate overrides active during ``[start, end)`` virtual seconds; a
    ``None`` field inherits the base ``ChaosConfig`` rate.  Later windows in
    the tuple win where they overlap."""

    start: float
    end: float
    binding_error_rate: float | None = None
    binding_latency_s: float | None = None
    api_error_rate: float | None = None
    watch_drop_rate: float | None = None
    watch_gone_rate: float | None = None
    lease_error_rate: float | None = None
    lease_refused_rate: float | None = None
    lease_latency_s: float | None = None


@dataclass(frozen=True)
class ChaosConfig:
    """Base fault rates (probability per call; latency in virtual seconds)."""

    binding_error_rate: float = 0.0  # CreateBindingFailed per binding POST
    binding_latency_s: float = 0.0  # virtual seconds added per successful POST
    api_error_rate: float = 0.0  # ApiError(500) on delete_pod / list_pdbs
    watch_drop_rate: float = 0.0  # poll() raises ConnectionError
    watch_gone_rate: float = 0.0  # poll() raises ApiError(410) — Gone storm
    lease_error_rate: float = 0.0  # ApiError(500) on acquire/release/get lease
    lease_refused_rate: float = 0.0  # acquire_lease CAS refused (returns False)
    lease_latency_s: float = 0.0  # virtual seconds added per lease mutation
    windows: tuple[ChaosWindow, ...] = ()

    def rate(self, name: str, t: float) -> float:
        value = getattr(self, name)
        for w in self.windows:
            if w.start <= t < w.end:
                override = getattr(w, name)
                if override is not None:
                    value = override
        return value

    @property
    def any_faults(self) -> bool:
        base = any(
            getattr(self, f) > 0
            for f in (
                "binding_error_rate",
                "binding_latency_s",
                "api_error_rate",
                "watch_drop_rate",
                "watch_gone_rate",
                "lease_error_rate",
                "lease_refused_rate",
                "lease_latency_s",
            )
        )
        return base or bool(self.windows)


class ChaosWatch:
    """Watch proxy whose ``poll()`` may raise per the chaos schedule.  A
    faulted poll leaves the underlying queue untouched (events are delayed,
    never lost) — the same contract as the resilience tests' FlakyWatch,
    which is what makes the reflector's backoff the recovery path."""

    def __init__(self, chaos: "ChaosApiServer", inner, kind: str):
        self._chaos = chaos
        self._inner = inner
        self._kind = kind

    def poll(self):
        if self._chaos._decide("watch_drop_rate", f"watch-drop:{self._kind}"):
            raise ConnectionError(f"chaos: {self._kind} watch dropped")
        if self._chaos._decide("watch_gone_rate", f"watch-gone:{self._kind}"):
            raise ApiError(410, f"chaos: {self._kind} watch resourceVersion too old")
        return self._inner.poll()

    def close(self):
        return self._inner.close()


class ChaosApiServer:
    """Fault-injecting proxy around a ``FakeApiServer`` (or compatible).

    ``replay_decisions`` switches the layer from drawing its RNG to replaying
    a recorded decision sequence verbatim (sim/trace.py) — the schedule is
    then part of the trace, not a function of the config."""

    def __init__(
        self,
        inner: FakeApiServer,
        config: ChaosConfig | None = None,
        rng: random.Random | None = None,
        clock=None,
        replay_decisions: list | None = None,
    ):
        self.inner = inner
        self.config = config or ChaosConfig()
        self.rng = rng or random.Random(0)
        self.clock = clock or getattr(inner, "_clock", None) or (lambda: 0.0)
        # Injection counters by kind — the scorecard's chaos evidence.
        self.injected: dict[str, int] = {}
        # Every rate draw, in call order: (endpoint, injected, latency).
        self.decision_log: list[tuple[str, bool, float]] = []
        self._replay = list(replay_decisions) if replay_decisions is not None else None
        self._replay_pos = 0
        # Deterministic observation stream: (virtual t, pod_full, node) per
        # CONFIRMED binding — the harness's time-to-bind source and the
        # run's determinism fingerprint material.
        self.bind_log: list[tuple[float, str, str]] = []
        # Which replica POSTed each bind_log entry (parallel list, same
        # length): the multi-replica harness sets ``actor`` before each
        # replica's cycle so the scorecard can judge binds-while-open
        # against the POSTING replica's breaker, not every replica's.
        # Deliberately OUTSIDE bind_log so single-replica fingerprints are
        # byte-identical with pre-sharding traces.
        self.actor = 0
        self.bind_actors: list[int] = []
        # Scheduler-driven pod deletions that succeeded (preemption victims,
        # NoExecute evictions) — sanctioned removals, not lost pods.
        self.evict_log: list[tuple[float, str]] = []
        # Rebalancer deschedules that succeeded (rebalance/executor.py):
        # the pod returned to Pending for a delta-engine re-place.  The
        # harness drains this to keep its bound-pod bookkeeping exact (a
        # migrated pod's re-bind is a migration completing, never a
        # double-bind) and the scorecard derives orphaned-migration
        # evidence from it.  ``unbind_actors`` mirrors ``bind_actors``:
        # which replica issued each deschedule, so unbinds-while-open is
        # judged against the POSTING replica's breaker.
        self.unbind_log: list[tuple[float, str]] = []
        self.unbind_actors: list[int] = []

    def __getattr__(self, name):
        return getattr(self.inner, name)

    # -- decisions ----------------------------------------------------------

    def _decide(self, rate_name: str, endpoint: str) -> bool:
        rate = self.config.rate(rate_name, self.clock())
        if self._replay is not None:
            if rate <= 0:
                return False  # no draw happened at record time either
            if self._replay_pos >= len(self._replay):
                raise RuntimeError(f"chaos replay exhausted at {endpoint} (trace/config mismatch)")
            ep, inject, _lat = self._replay[self._replay_pos]
            if ep != endpoint:
                raise RuntimeError(f"chaos replay diverged: expected {ep!r}, got {endpoint!r}")
            self._replay_pos += 1
            if inject:
                self.injected[endpoint] = self.injected.get(endpoint, 0) + 1
            return inject
        if rate <= 0:
            return False
        inject = self.rng.random() < rate
        self.decision_log.append((endpoint, inject, 0.0))
        if inject:
            self.injected[endpoint] = self.injected.get(endpoint, 0) + 1
        return inject

    def _latency(self) -> float:
        return self.config.rate("binding_latency_s", self.clock())

    # -- faulted endpoints --------------------------------------------------

    def watch_nodes(self, *args, **kwargs) -> ChaosWatch:
        return ChaosWatch(self, self.inner.watch_nodes(*args, **kwargs), "Node")

    def watch_pods(self, *args, **kwargs) -> ChaosWatch:
        return ChaosWatch(self, self.inner.watch_pods(*args, **kwargs), "Pod")

    def create_binding(self, namespace: str, pod_name: str, target) -> None:
        if self._decide("binding_error_rate", "bind-500"):
            raise CreateBindingFailed(f"chaos: injected apiserver 500 binding {namespace}/{pod_name}")
        lat = self._latency()
        if lat > 0 and hasattr(self.clock, "advance"):
            # Virtual POST latency: the cycle's own clock moves, so requeue
            # deadlines and workload arrivals feel the slow apiserver.
            self.clock.advance(lat)
            self.injected["bind-latency"] = self.injected.get("bind-latency", 0) + 1
        self.inner.create_binding(namespace, pod_name, target)
        self.bind_log.append((round(self.clock(), 9), f"{namespace}/{pod_name}", target.name))
        self.bind_actors.append(self.actor)

    def delete_pod(self, namespace: str, name: str) -> None:
        if self._decide("api_error_rate", "delete-500"):
            raise ApiError(500, f"chaos: injected apiserver 500 deleting {namespace}/{name}")
        self.inner.delete_pod(namespace, name)
        self.evict_log.append((round(self.clock(), 9), f"{namespace}/{name}"))

    def unbind_pod(self, namespace: str, pod_name: str, expect_node: str | None = None) -> None:
        if self._decide("api_error_rate", "unbind-500"):
            raise ApiError(500, f"chaos: injected apiserver 500 descheduling {namespace}/{pod_name}")
        self.inner.unbind_pod(namespace, pod_name, expect_node)
        self.unbind_log.append((round(self.clock(), 9), f"{namespace}/{pod_name}"))
        self.unbind_actors.append(self.actor)

    def list_pdbs(self) -> list:
        if self._decide("api_error_rate", "list-pdbs-500"):
            raise ApiError(500, "chaos: injected apiserver 500 listing PDBs")
        return self.inner.list_pdbs()

    # -- lease endpoints (the coordination surface every control-plane
    # -- protocol rides: shard/replica/gang-reservation/shard-map leases) ----

    def _lease_latency(self) -> None:
        lat = self.config.rate("lease_latency_s", self.clock())
        if lat > 0 and hasattr(self.clock, "advance"):
            # Virtual CAS latency: the cycle's own clock moves, so lease
            # TTL deadlines feel the slow coordination plane.
            self.clock.advance(lat)
            self.injected["lease-latency"] = self.injected.get("lease-latency", 0) + 1

    def acquire_lease(self, name: str, holder: str, duration_seconds: float) -> bool:
        if self._decide("lease_error_rate", "lease-acquire-500"):
            raise ApiError(500, f"chaos: injected apiserver 500 acquiring lease {name}")
        if self._decide("lease_refused_rate", "lease-refused"):
            return False  # CAS lost — indistinguishable from a conflicting writer winning
        self._lease_latency()
        return self.inner.acquire_lease(name, holder, duration_seconds)

    def release_lease(self, name: str, holder: str) -> None:
        if self._decide("lease_error_rate", "lease-release-500"):
            raise ApiError(500, f"chaos: injected apiserver 500 releasing lease {name}")
        self._lease_latency()
        return self.inner.release_lease(name, holder)

    def get_lease(self, name: str) -> dict | None:
        if self._decide("lease_error_rate", "lease-get-500"):
            raise ApiError(500, f"chaos: injected apiserver 500 reading lease {name}")
        return self.inner.get_lease(name)
