"""The simulation harness — a real ``Scheduler`` driven by virtual time.

One ``run_scenario`` call wires together:

  ``VirtualClock``  →  ``FakeApiServer(clock=...)``  →  ``ChaosApiServer``
                    →  ``Scheduler(clock=..., rng=seeded)``

and runs the discrete-event loop: apply every workload op due at the
current virtual time, run one scheduling cycle, fold the confirmed bindings
(time-to-bind, completion scheduling), advance the clock one cycle
interval.  Nothing sleeps; a 2-minute scenario with thousands of pods costs
seconds of wall clock.

Determinism: every randomness source is derived from the ONE scenario seed
(workload, chaos, scheduler/reflector jitter), all bookkeeping iterates in
sorted or insertion order, and the scorecard contains only virtual-time
quantities — the same ``--scenario --seed`` pair produces an identical
binding sequence and byte-identical scorecard JSON on every run.  With
``record=...`` the resolved op stream + chaos decision schedule persist to
JSONL (sim/trace.py); ``replay=...`` feeds them back and verifies the
fingerprint bit-matches the recorded footer.
"""

from __future__ import annotations

import heapq
import random

from ..api.objects import is_pod_bound
from ..backends.native import NativeBackend
from ..models.profiles import DEFAULT_PROFILE
from ..runtime.fake_api import FakeApiServer
from ..testing import make_node, make_pod
from ..topology.locality import gang_placement_stats
from ..topology.model import DEFAULT_LEVEL_KEYS
from ..utils.events import waterfall
from ..utils.profiler import compile_listener_active, compile_stats, tier_of
from ..utils.tracing import base_name
from .chaos import ChaosApiServer
from .clock import VirtualClock
from .multi import MultiReplicaHarness
from .scenarios import SCENARIOS, Scenario
from .scorecard import (
    COMPILE_FIELDS,
    CONVERGENCE_FIELDS,
    ELASTICITY_FIELDS,
    _percentile,
    build_latency_block,
    build_scorecard,
    check_invariants,
    fingerprint,
)
from .trace import TraceWriter, load_trace
from .workload import generate_events, initial_nodes

__all__ = ["run_scenario", "scenario_episode", "EpisodeContext", "ReplayMismatchError"]


class ReplayMismatchError(RuntimeError):
    """A --replay run's fingerprint differs from the recorded footer."""

    def __init__(self, expected: str, got: str):
        super().__init__(f"replay fingerprint mismatch: recorded {expected[:16]}…, replayed {got[:16]}…")
        self.expected = expected
        self.got = got


class EpisodeContext:
    """What ``scenario_episode`` yields once per cycle, BEFORE the fleet
    steps: live references into the run (never copies — one episode, one
    world).  ``learn/env.py`` derives its observation from these; the plain
    ``run_scenario`` driver never looks at them, so ordinary runs pay
    nothing for the episode surface."""

    __slots__ = ("clock", "api", "chaos", "fleet", "state", "cycle")

    def __init__(self, clock, api, chaos, fleet, state, cycle: int):
        self.clock = clock
        self.api = api  # the inner FakeApiServer (truth, not the chaos shim)
        self.chaos = chaos
        self.fleet = fleet
        self.state = state
        self.cycle = cycle  # completed cycles so far (0 on the first yield)


class _SimState:
    """Run bookkeeping shared by record and replay paths."""

    def __init__(self):
        self.nodes: dict[str, dict] = {}  # live node name -> payload
        self.arrival_t: dict[str, float] = {}
        self.lifetime: dict[str, float] = {}
        self.live: set[str] = set()  # created, not deleted
        self.bound_live: set[str] = set()
        self.bind_epoch: dict[str, int] = {}
        self.gangs: dict[str, set[str]] = {}
        self.disturbed_pods: set[str] = set()
        self.disturbed_nodes: set[str] = set()
        self.scheduled_names: set[str] = set()
        # Topology bookkeeping: every node's domains (kept after delete —
        # a failed rack's placements still need scoring) and each pod's
        # FIRST bound node (bind-time locality; churn re-binds are the
        # disturbed set's business, not a locality verdict's).
        self.node_domains: dict[str, dict] = {}
        self.first_bind: dict[str, str] = {}
        self.counts = {"arrived": 0, "churn_recreated": 0, "completed": 0, "evicted": 0, "migrated": 0}
        self.ttb: list[float] = []
        self.tier: dict[str, str] = {}  # pod name -> SLO tier (from priority at arrival)
        self.double_bound = 0


def _resolve_scenario(scenario: Scenario | str) -> Scenario:
    if isinstance(scenario, Scenario):
        return scenario
    try:
        return SCENARIOS[scenario]
    except KeyError:
        raise ValueError(f"unknown scenario {scenario!r} (known: {', '.join(sorted(SCENARIOS))})") from None


_LEVEL_LABEL = dict(DEFAULT_LEVEL_KEYS)  # level name -> node label key


def _node_obj(payload: dict, unschedulable: bool = False):
    labels = {"zone": payload["zone"], "name": payload["name"]}
    for level, key in DEFAULT_LEVEL_KEYS:
        if payload.get(level):
            # Topology-labeled fleets (WorkloadSpec slice_size/rack_size)
            # advertise their domains the kube-native way, which
            # topology-enables the scheduler under test (controller "auto").
            labels[key] = payload[level]
    return make_node(
        payload["name"],
        cpu=payload["cpu"],
        memory=f"{payload['mem_gi']}Gi",
        labels=labels,
        unschedulable=unschedulable,
    )


def _pod_obj(payload: dict):
    return make_pod(
        payload["name"],
        cpu=f"{payload['cpu_m']}m",
        memory=f"{payload['mem_mi']}Mi",
        priority=payload.get("priority", 0),
        labels={"app": payload.get("app", "app-0")},
        node_selector={"zone": payload["zone"]} if payload.get("zone") else None,
        gang=payload.get("gang"),
    )


def _profile_block(sc: Scenario, fleet: MultiReplicaHarness) -> dict:
    """The scorecard ``profile`` verdict: attribution coverage across the
    fleet's continuous profile rings (utils/profiler.py) plus the span
    CENSUS — per-path span counts with indexed segments collapsed to their
    base (``solve/round[03]`` → ``solve/round``).

    Deterministic by construction: span presence/counts are pure control
    flow (bit-identical under record/replay) and ``coverage_ok`` is a
    wide-margin boolean; raw durations never enter the scorecard (the
    byte-identity contract).  ``compile`` spans are excluded — XLA
    compile-cache state is environment, not scheduling."""
    census: dict[str, int] = {}
    cycles = 0
    wall = 0.0
    other = 0.0
    for r in fleet.scheds:
        snap = r.profile_ring.snapshot()
        cycles += snap["cycles"]
        wall += snap["wall_total_s"]
        other += snap["other_total_s"]
        for path, count in r.profile_ring.span_census().items():
            if "compile" in path:
                continue
            base = "/".join(base_name(seg) for seg in path.split("/"))
            census[base] = census.get(base, 0) + count
    return {
        "enabled": True,
        "required": bool(sc.profile_required),
        "coverage_ok": bool(wall <= 0 or (1.0 - other / wall) >= 0.9),
        "cycles": cycles,
        "span_census": dict(sorted(census.items())),
    }


def _compile_block(sc: Scenario, post_warmup_compiles: int) -> dict:
    """The scorecard ``compile`` verdict — the runtime twin of the JITC
    static pass (scripts/analyze/jitc.py): after ``compile_warmup_cycles``
    cycles every shape bucket must already be traced, so a later XLA
    compile means a raw per-cycle dimension leaked into a jit signature.

    Deterministic by construction: the block carries only the warmup-window
    LENGTH and the POST-warmup compile count — never the warmup compile
    count itself, which differs between a cold record (every bucket traces)
    and a warm replay (the in-process cache is already primed).  A PASSING
    run has ``post_warmup_compiles == 0`` in both, so the gate preserves
    record→replay bit-identity.  Under the pure-numpy NativeBackend the
    listener never installs and the count is vacuously zero; ``enabled``
    says so and ``ok`` stays green — the jit-stability smoke drives the
    TpuBackend to make this gate bite."""
    enabled = compile_listener_active()
    flat = int(post_warmup_compiles) == 0
    out = {
        "enabled": bool(enabled),
        "required": bool(sc.compile_required),
        "warmup_cycles": int(sc.compile_warmup_cycles),
        "post_warmup_compiles": int(post_warmup_compiles),
        "steady_flat": flat,
        "ok": flat or not enabled,
    }
    assert tuple(out) == COMPILE_FIELDS, "compile block drifted from COMPILE_FIELDS"
    return out


# shape: (sc: obj, fleet: obj, st: obj) -> obj
def _latency_block(sc: Scenario, fleet: MultiReplicaHarness, st: "_SimState") -> dict:
    """The scorecard ``latency`` verdict: every undisturbed bound pod's
    flight-recorder timeline reduced to its waterfall (utils/events.py),
    anchored at the harness's nominal arrival time, folded by SLO tier.

    Deterministic by construction: the recorder stamps every event with the
    scheduler clock (``t``, virtual here) and ``waterfall`` reads only those
    stamps, so the whole block is bit-identical under record/replay.
    Multi-replica runs concatenate per-replica timelines for the same pod
    (a migrated pod's history lives on two recorders) in replica order and
    stably sort by ``t``."""
    timelines: dict[str, list[dict]] = {}
    for r in fleet.scheds:
        for pf in r.recorder.tracked_pods():
            timelines.setdefault(pf, []).extend(r.recorder.timeline(pf))
    samples: list[tuple[str, dict]] = []
    for pf in sorted(timelines):
        name = pf.rpartition("/")[2]
        if name in st.disturbed_pods or name not in st.arrival_t:
            continue
        tl = sorted(timelines[pf], key=lambda ev: float(ev.get("t", ev.get("ts", 0.0))))
        wf = waterfall(tl, arrival_t=st.arrival_t[name])
        if wf is None:
            continue
        samples.append((st.tier.get(name, "default"), wf))
    return build_latency_block(
        samples,
        bound_total=len(st.ttb),
        required=bool(sc.latency_required),
    )


def _incremental_block(sc: Scenario, fleet: MultiReplicaHarness) -> dict:
    """The scorecard ``incremental`` verdict (tpu_scheduler/delta):
    delta-vs-full cycle counts, escalation-reason tallies, dirty-set size
    percentiles, and the shadow-solve parity record, aggregated across the
    fleet.  Deterministic by construction — every quantity is control flow
    (cycle counts, set sizes, parity booleans), never wall clock.

    ``ok`` holds the contract the ISSUE's acceptance criterion names: zero
    parity mismatches (with at least one check when sampling is on) and the
    full-wave solve staying the RARE path (fraction <= 0.10)."""
    engines = [r.delta for r in fleet.scheds if r.delta is not None]
    out = {
        "enabled": bool(engines),
        "required": bool(sc.incremental_required),
        "delta_cycles": 0,
        "full_solves": 0,
        "full_solve_fraction": 0.0,
        "escalations": {},
        "dirty_p50": 0,
        "dirty_p95": 0,
        "dirty_max": 0,
        "skipped_pods": 0,
        "standing_verdicts": 0,
        "shadow_checks": 0,
        "shadow_mismatches": 0,
        "shadow_skipped": 0,
        "shadow_parity_ok": True,
        "ok": True,
    }
    if not engines:
        out["ok"] = not sc.incremental_required
        return out
    sizes: list[int] = []
    escalations: dict[str, int] = {}
    for eng in engines:
        s = eng.stats()
        out["delta_cycles"] += s["delta_cycles"]
        out["full_solves"] += s["full_solves"]
        out["skipped_pods"] += s["skipped_total"]
        out["standing_verdicts"] += s["standing_verdicts"]
        out["shadow_checks"] += s["shadow_checks"]
        out["shadow_mismatches"] += s["shadow_mismatches"]
        out["shadow_skipped"] += s["shadow_skipped"]
        sizes.extend(s["dirty_sizes"])
        for reason, n in s["full_solve_reasons"].items():
            escalations[reason] = escalations.get(reason, 0) + n
    out["escalations"] = dict(sorted(escalations.items()))
    total = out["delta_cycles"] + out["full_solves"]
    if total:
        out["full_solve_fraction"] = round(out["full_solves"] / total, 6)
    sizes.sort()
    out["dirty_p50"] = int(_percentile(sizes, 0.50))
    out["dirty_p95"] = int(_percentile(sizes, 0.95))
    out["dirty_max"] = sizes[-1] if sizes else 0
    out["shadow_parity_ok"] = out["shadow_mismatches"] == 0
    out["ok"] = bool(
        out["shadow_parity_ok"]
        and out["full_solve_fraction"] <= 0.10
        and (out["shadow_checks"] > 0 or sc.delta_shadow_every <= 0)
    )
    return out


def _rebalance_block(
    sc: Scenario,
    fleet: MultiReplicaHarness,
    inner,
    chaos,
    pending_final,
    lost_names,
    open_iv_by_replica,
    enabled: bool,
    slo_churn: int,
) -> dict:
    """The scorecard ``rebalance`` verdict (tpu_scheduler/rebalance).

    Packing efficiency / stranded capacity are computed from the FINAL API
    state with the same exact-integer capacity math the rebalancer itself
    packs with — so the rebalancer-OFF baseline gets the identical verdict
    surface and must fail the same gate.  Orphan evidence comes from the
    chaos unbind log vs the final state: a pod ever descheduled that ends
    the run neither bound nor legitimately gone is an orphaned migration
    (the acceptance quantity chaos variants hold at zero), and a
    deschedule POSTed inside its OWN replica's breaker-open interval is a
    degraded-mode bug counted in ``unbinds_while_open``."""
    from ..core.snapshot import ClusterSnapshot
    from ..rebalance import REBALANCE_CORDON_LABEL, RebalanceSnapshot, packing_stats

    rebs = [r.rebalancer for r in fleet.scheds if r.rebalancer is not None]
    out = {
        "enabled": bool(rebs),
        "required": bool(sc.rebalance_required),
        "solves": 0,
        "migrations": 0,
        "completed": 0,
        "skips": {},
        "nodes_drained": 0,
        "pressure_releases": 0,
        "unbinds_while_open": 0,
        "orphaned_migrations": 0,
        "packing_efficiency": 1.0,
        "efficiency_gate": round(float(sc.rebalance_efficiency_gate), 6),
        "stranded_frac": 0.0,
        "occupied_nodes": 0,
        "empty_nodes": 0,
        "migration_budget": int(sc.rebalance_migration_budget),
        "preemption_churn": int(slo_churn),
        "whatif": None,
        "ok": True,
    }
    skips: dict[str, int] = {}
    for reb in rebs:
        s = reb.stats()
        out["solves"] += s["solves"]
        out["migrations"] += s["executed"]
        out["completed"] += s["completed"]
        out["nodes_drained"] += s["nodes_drained"]
        out["pressure_releases"] += s["pressure_releases"]
        for k, v in s["skips"].items():
            skips[k] = skips.get(k, 0) + v
    out["skips"] = dict(sorted(skips.items()))
    final = ClusterSnapshot.build(inner.list_nodes(), inner.list_pods())
    rs = RebalanceSnapshot.build(final)
    stats = packing_stats(rs.alloc, rs.used)
    out["packing_efficiency"] = stats["efficiency"]
    out["stranded_frac"] = stats["stranded_frac"]
    out["occupied_nodes"] = stats["occupied_nodes"]
    out["empty_nodes"] = stats["empty_nodes"]
    unbound_names = {pf.rpartition("/")[2] for _t, pf in chaos.unbind_log}
    pending_names = {p.metadata.name for p in pending_final}
    out["orphaned_migrations"] = len(unbound_names & (pending_names | set(lost_names)))
    out["unbinds_while_open"] = sum(
        1
        for (t, _pf), actor in zip(chaos.unbind_log, chaos.unbind_actors)
        if any(s < t < e for s, e in open_iv_by_replica[actor])
    )
    if sc.rebalance_whatif:
        from ..rebalance import autoscaler_whatif

        drained_labeled = sum(
            1 for n in final.nodes if (n.metadata.labels or {}).get(REBALANCE_CORDON_LABEL)
        )
        out["whatif"] = autoscaler_whatif(final, pending_final, drained_labeled=drained_labeled)
    gate = out["efficiency_gate"]
    budget = out["migration_budget"]
    whatif = out["whatif"]
    out["ok"] = bool(
        (gate <= 0 or out["packing_efficiency"] >= gate)
        and (budget <= 0 or out["migrations"] <= budget)
        and out["orphaned_migrations"] == 0
        and out["unbinds_while_open"] == 0
        and (
            whatif is None
            or whatif["pending_unplaceable"] == 0
            or whatif["nodes_needed"] >= 1
        )
    )
    if not enabled and not sc.rebalance_required:
        out["ok"] = True  # a scenario without the tier has nothing to judge
    return out


def _locality_block(sc: Scenario, st: "_SimState") -> dict:
    """The scorecard ``locality`` verdict: per-gang placement-distance
    statistics over FIRST-bind placements (bind-time locality — churn
    re-binds belong to the disturbed set, which is skipped here exactly like
    I2/I3 skip it: counted, never silent).  ``cross_rack_gangs`` is the
    number the pass gate holds at zero for ``locality_required`` scenarios —
    a locality regression fails a run the same way an SLO regression does."""
    levels = [level for level, _k in DEFAULT_LEVEL_KEYS if any(level in d for d in st.node_domains.values())]
    out = {
        "enabled": bool(levels),
        "required": bool(sc.locality_required),
        "levels": levels,
        "gangs_scored": 0,
        "gangs_skipped_churned": 0,
        "gangs_unscored": 0,
        "max_distance": 0.0,
        "mean_distance": 0.0,
        "cross_rack_edges": 0,
        "cross_rack_gangs": 0,
        "single_domain_gangs": 0,
    }
    if not levels:
        return out
    level_dists = [1.0] * len(levels)
    means: list[float] = []
    for g, members in sorted(st.gangs.items()):
        if members & st.disturbed_pods:
            out["gangs_skipped_churned"] += 1
            continue
        doms = []
        for m in sorted(members):
            node = st.first_bind.get(m)
            nd = st.node_domains.get(node) if node is not None else None
            if nd is None:
                doms = None
                break
            doms.append(tuple(nd.get(level, f"~{node}") for level in levels))
        if doms is None or len(doms) < 2:
            # Never admitted (or a 1-member tail) — nothing to score; the
            # SLO/backlog numbers already account for unplaced demand.
            out["gangs_unscored"] += 1
            continue
        stats = gang_placement_stats(doms, level_dists)
        out["gangs_scored"] += 1
        out["max_distance"] = max(out["max_distance"], stats["max_distance"])
        means.append(stats["mean_distance"])
        out["cross_rack_edges"] += stats["cross_edges"]
        if stats["cross_edges"]:
            out["cross_rack_gangs"] += 1
        elif stats["max_distance"] == 0.0:
            out["single_domain_gangs"] += 1
    if means:
        out["mean_distance"] = round(sum(means) / len(means), 6)
    return out


def _elasticity_block(
    sc: Scenario,
    fleet: MultiReplicaHarness,
    pending_final,
    lost_names,
    end_t: float,
    st: "_SimState",
    enabled: bool,
) -> dict:
    """The scorecard ``elasticity`` verdict (tpu_scheduler/autoscale).

    The joint objective is computed from the SAME surface whether the
    autoscaler ran or not: effective p99 time-to-bind — every bound pod's
    TTB plus every still-pending pod charged its unmet age at episode end —
    plus ``autoscale_cost_weight`` × the provider's elastic node-hour cost
    integral (zero for the static fleet).  So the ``autoscale=False``
    baseline gets the identical verdict surface and must fail the same
    gate on merit: it pays no cost but its unserved backlog's effective
    p99 blows the objective.  Reclaim-orphan evidence: any pod the
    provider force-unbound at a reclaim deadline (or the autoscaler
    unbound while draining a scale-down candidate) that ends the run
    neither bound nor legitimately gone — REQUIRED zero whenever the
    block gates at all."""
    autos = [r.autoscaler for r in fleet.scheds if r.autoscaler is not None]
    provider = fleet.provider
    out = {
        "enabled": bool(enabled and provider is not None),
        "required": bool(sc.autoscale_required),
        "scale_ups": {},
        "scale_downs": {},
        "skus": {},
        "pending_provisions": 0,
        "provision_lag_p99_s": 0.0,
        "reclaims": 0,
        "reclaim_orphans": 0,
        "quota_errors": 0,
        "stockout_errors": 0,
        "skips": {},
        "cost_node_hours": 0.0,
        "joint_objective": 0.0,
        "objective_gate": round(float(sc.autoscale_objective_gate), 6),
        "ok": True,
    }
    ups: dict[str, int] = {}
    downs: dict[str, int] = {}
    skips: dict[str, int] = {}
    unbound_short: set[str] = set()
    for auto in autos:
        s = auto.stats()
        for k, v in s["scale_ups"].items():
            ups[k] = ups.get(k, 0) + v
        for k, v in s["scale_downs"].items():
            downs[k] = downs.get(k, 0) + v
        for k, v in s["skips"].items():
            skips[k] = skips.get(k, 0) + v
        unbound_short.update(pf.rpartition("/")[2] for pf in auto.drain_unbound)
    out["scale_ups"] = dict(sorted(ups.items()))
    out["scale_downs"] = dict(sorted(downs.items()))
    out["skips"] = dict(sorted(skips.items()))
    if provider is not None:
        pstats = provider.stats()
        out["skus"] = pstats["skus"]
        out["pending_provisions"] = pstats["pending_provisions"]
        out["reclaims"] = pstats["reclaim_notices"]
        out["quota_errors"] = pstats["quota_errors"]
        out["stockout_errors"] = pstats["stockout_errors"]
        lags = sorted(provider.provision_lags())
        out["provision_lag_p99_s"] = round(_percentile(lags, 0.99), 6)
        out["cost_node_hours"] = round(provider.cost_node_hours(end_t), 6)
        unbound_short.update(pf.rpartition("/")[2] for pf in provider.reclaim_unbound)
    pending_names = {p.metadata.name for p in pending_final}
    out["reclaim_orphans"] = len(unbound_short & (pending_names | set(lost_names)))
    # Effective p99 TTB: the SLO term no fleet can game by refusing to
    # bind — unserved demand is charged its full unmet age.
    eff = sorted(
        st.ttb
        + [end_t - st.arrival_t[p.metadata.name] for p in pending_final if p.metadata.name in st.arrival_t]
    )
    joint = _percentile(eff, 0.99) + float(sc.autoscale_cost_weight) * out["cost_node_hours"]
    out["joint_objective"] = round(joint, 6)
    gate = out["objective_gate"]
    out["ok"] = bool((gate <= 0 or out["joint_objective"] <= gate) and out["reclaim_orphans"] == 0)
    assert tuple(out) == ELASTICITY_FIELDS, "elasticity block drifted from ELASTICITY_FIELDS"
    return out


# shape: (sc: obj, fleet: obj, inner: obj, pending_final: obj, end_t: float) -> obj
def _convergence_block(sc: Scenario, fleet: MultiReplicaHarness, inner, pending_final, end_t: float) -> dict:
    """The scorecard ``convergence`` verdict — the fuzzer's end-state
    quiescence oracle (sim/fuzz).  After the last scheduled fault
    (latest chaos-window end, replica kill, or rack failure) the run must
    settle: backlog drained, every LIVE replica's deferred-bind buffer
    flushed, and no unexpired shard/replica/gang-reservation lease held by
    a dead replica (a crashed owner's leases stop renewing and expire
    within one TTL, so a settled fleet counts zero).  The shard-map lease
    is excluded — its holder is the map payload, not a replica identity.
    ``settle_overtime_s`` is the virtual time spent past
    max(duration, last fault); the loop's drain-grace exit bounds it, and
    the bound here re-derives that cap so a wedged run is named, not
    silently truncated.  Deterministic by construction: every quantity is
    virtual time or control flow."""
    from ..fleet.reservation import GANG_RESERVATION_PREFIX
    from ..fleet.resize import SHARD_MAP_LEASE
    from ..runtime.shards import REPLICA_LEASE_PREFIX, SHARD_LEASE_PREFIX

    last_fault = 0.0
    for w in sc.chaos.windows:
        last_fault = max(last_fault, float(w.end))
    for t, _idx in sc.replica_kills:
        last_fault = max(last_fault, float(t))
    for t in sc.workload.rack_fail_times:
        last_fault = max(last_fault, float(t))
    # The settle bound the loop itself enforces: one drain-grace stretch of
    # no-progress cycles plus two lease TTLs for takeover/expiry tails.
    settle_bound = 2.0 * float(sc.lease_duration) + float(sc.drain_grace_cycles) * float(sc.cycle_interval)
    overtime = max(0.0, end_t - max(float(sc.duration), last_fault))
    deferred = sum(len(r.deferred_binds) for i, r in enumerate(fleet.scheds) if fleet.alive[i])
    live = {r.identity for i, r in enumerate(fleet.scheds) if fleet.alive[i] and getattr(r, "identity", None)}
    stale = 0
    lister = getattr(inner, "list_lease_summaries", None)
    if lister is not None:
        for info in lister():
            name = info["name"]
            if name == SHARD_MAP_LEASE:
                continue
            if not name.startswith((SHARD_LEASE_PREFIX, REPLICA_LEASE_PREFIX, GANG_RESERVATION_PREFIX)):
                continue
            if info.get("holder") and info["holder"] not in live and end_t < float(info.get("expires", 0.0)):
                stale += 1
    out = {
        "enabled": True,
        "required": bool(sc.convergence_required),
        "last_fault_t": round(last_fault, 6),
        "settle_overtime_s": round(overtime, 6),
        "settle_bound_s": round(settle_bound, 6),
        "pending_final": len(pending_final),
        "deferred_residue": int(deferred),
        "stale_leases": stale,
        "ok": bool(
            len(pending_final) == 0 and deferred == 0 and stale == 0 and overtime <= settle_bound + 1e-9
        ),
    }
    assert tuple(out) == CONVERGENCE_FIELDS, "convergence block drifted from CONVERGENCE_FIELDS"
    return out


def run_scenario(
    scenario: Scenario | str,
    seed: int = 0,
    backend=None,
    record: str | None = None,
    replay: str | None = None,
    events_buffer: int = 4096,
    topology="auto",
    profile_gates: dict | None = None,
    rebalance="auto",
    autoscale="auto",
    profile=None,
) -> dict:
    """Run one scenario to its verdict; returns the scorecard dict.

    ``record`` persists the run as a JSONL trace; ``replay`` re-runs a trace
    (its header names the scenario) and raises ``ReplayMismatchError`` if
    the replayed fingerprint differs from the recorded one.  ``topology``
    passes through to the Scheduler: "auto" (default) detects the workload's
    slice/rack node labels, None runs the topology-BLIND baseline the
    locality scorecard block quantifies against.  ``profile_gates`` (a dict,
    filled in place) receives the WALL-derived profiler gate inputs —
    aggregate attribution coverage and the measured overhead estimate —
    which are deliberately kept OFF the scorecard (it must stay
    byte-identical across runs); `sim --profile-check` consumes them.
    ``rebalance`` mirrors the topology switch for the background defrag
    tier: "auto" (default) follows the scenario's ``rebalance`` knob,
    False forces the rebalancer-OFF baseline the fragmentation scorecard
    block quantifies against (and must FAIL the efficiency gate).
    ``autoscale`` is the same switch for the elastic-capacity tier:
    "auto" follows the scenario's ``autoscale`` knob, False forces the
    static-fleet baseline the elasticity scorecard block quantifies
    against (and must FAIL the joint cost+SLO objective gate).
    ``profile`` overrides the ``SchedulingProfile`` the fleet schedules
    with (None = the default, exactly as before — fingerprints hold); a
    scenario's ``preemption`` knob still applies on top."""
    gen = scenario_episode(
        scenario,
        seed=seed,
        backend=backend,
        record=record,
        replay=replay,
        events_buffer=events_buffer,
        topology=topology,
        profile_gates=profile_gates,
        rebalance=rebalance,
        autoscale=autoscale,
        profile=profile,
    )
    # Drive the episode with no per-cycle actions — byte-identical to the
    # pre-generator loop; the gym-style surface (learn/env.py) is the only
    # caller that ever sends one.
    try:
        next(gen)
        while True:
            gen.send(None)
    except StopIteration as stop:
        return stop.value


def scenario_episode(
    scenario: Scenario | str,
    seed: int = 0,
    backend=None,
    record: str | None = None,
    replay: str | None = None,
    events_buffer: int = 4096,
    topology="auto",
    profile_gates: dict | None = None,
    rebalance="auto",
    autoscale="auto",
    profile=None,
):
    """The discrete-event loop as a generator: yields an ``EpisodeContext``
    once per cycle (after due ops apply, BEFORE the fleet steps) and accepts
    an optional ``SchedulingProfile`` in return, installed fleet-wide for
    the next cycle window (the controller reads its profile fresh every
    cycle).  Returns the scorecard via ``StopIteration.value``.  Same
    determinism contract as ``run_scenario`` — the yield exchanges no
    randomness, so a None-action drive is bit-identical to the plain run."""
    replay_data = load_trace(replay) if replay else None
    if replay_data is not None:
        sc = _resolve_scenario(replay_data["header"]["scenario"])
        seed = int(replay_data["header"]["seed"])
    else:
        sc = _resolve_scenario(scenario)

    clock = VirtualClock()
    inner = FakeApiServer(watch_history=sc.watch_history, clock=clock)
    chaos = ChaosApiServer(
        inner,
        sc.chaos,
        rng=random.Random(f"{seed}:chaos"),
        clock=clock,
        replay_decisions=replay_data["chaos"] if replay_data else None,
    )
    backend = backend or NativeBackend()
    profile = profile if profile is not None else DEFAULT_PROFILE
    if sc.preemption and not profile.preemption:
        profile = profile.with_(preemption=True)
    # One harness regardless of replica count: replicas == 1 constructs the
    # scheduler exactly as the single-replica path always did (same rng
    # label, no shard machinery), so pre-sharding fingerprints hold.
    rebalance_on = bool(getattr(sc, "rebalance", False)) and rebalance is not False
    autoscale_on = bool(getattr(sc, "autoscale", False)) and autoscale is not False
    fleet = MultiReplicaHarness(
        sc,
        seed,
        clock,
        chaos,
        backend,
        profile,
        events_buffer,
        topology,
        rebalance_on=rebalance_on,
        autoscale_on=autoscale_on,
    )

    writer = TraceWriter(record) if record else None
    if writer:
        writer.header(sc.name, seed, backend.name)

    st = _SimState()
    # Timed internal ops (record mode only): completions + flap returns.
    future: list[tuple[float, int, dict]] = []
    fseq = 0

    def push_future(t: float, op: dict) -> None:
        nonlocal fseq
        fseq += 1
        heapq.heappush(future, (t, fseq, op))

    # -- op application (the ONE mutation path; every applied op is traced) --

    def apply_op(op: dict) -> None:
        kind = op["op"]
        now = clock.now
        if kind == "create_pod":
            p = op["pod"]
            name = p["name"]
            inner.create_pod(_pod_obj(p))
            st.live.add(name)
            st.scheduled_names.add(name)
            # SLO clock starts at the event's nominal arrival ("at"), not at
            # application: a pod arriving between cycles queues until the
            # next one, and that queueing delay is real time-to-bind.
            st.arrival_t[name] = float(op.get("at", now))
            st.tier[name] = tier_of(int(p.get("priority", 0)))
            if p.get("lifetime_s"):
                st.lifetime[name] = float(p["lifetime_s"])
            if p.get("gang"):
                st.gangs.setdefault(p["gang"], set()).add(name)
            if op.get("churned"):
                st.counts["churn_recreated"] += 1
                st.disturbed_pods.add(name)
            else:
                st.counts["arrived"] += 1
        elif kind == "delete_pod":
            name = op["name"]
            inner.delete_pod("default", name)
            st.live.discard(name)
            st.bound_live.discard(name)
            if op.get("reason") == "completed":
                st.counts["completed"] += 1
        elif kind == "create_node":
            payload = op["node"]
            inner.create_node(_node_obj(payload))
            st.nodes[payload["name"]] = payload
            doms = {level: payload[level] for level, _k in DEFAULT_LEVEL_KEYS if payload.get(level)}
            if doms:
                st.node_domains[payload["name"]] = doms
        elif kind == "delete_node":
            inner.delete_node(op["name"])
            st.nodes.pop(op["name"], None)
            st.disturbed_nodes.add(op["name"])
        elif kind == "cordon":
            payload = st.nodes[op["name"]]
            inner.update_node(_node_obj(payload, unschedulable=True))
            st.disturbed_nodes.add(op["name"])
        else:
            raise ValueError(f"unknown sim op {kind!r}")
        if writer:
            writer.action(now, op)

    def evict_node_pods(node_name: str, recreate: bool) -> None:
        """Delete every pod bound to the node; optionally re-arrive them as
        fresh Pending pods (the ReplicaSet stand-in).  Sorted for
        determinism; bindings in flight are impossible (single-threaded)."""
        from ..api.objects import total_pod_resources

        for pod in sorted(inner.list_pods(f"spec.nodeName={node_name}"), key=lambda p: p.metadata.name):
            name = pod.metadata.name
            req = total_pod_resources(pod)
            spec = {
                "name": name,
                "cpu_m": int(req.cpu),  # PodResources carries millicores
                "mem_mi": int(req.memory // (1 << 20)),
                "priority": pod.spec.priority if pod.spec else 0,
                "app": (pod.metadata.labels or {}).get("app", "app-0"),
            }
            if pod.spec is not None and pod.spec.gang:
                spec["gang"] = pod.spec.gang
            if pod.spec is not None and pod.spec.node_selector:
                spec["zone"] = pod.spec.node_selector.get("zone")
            if name in st.lifetime:
                spec["lifetime_s"] = st.lifetime[name]
            apply_op({"op": "delete_pod", "name": name, "reason": "churn"})
            st.disturbed_pods.add(name)
            if recreate:
                apply_op({"op": "create_pod", "pod": spec, "churned": True})

    def resolve_event(ev) -> None:
        """Turn one generated workload event into concrete ops (record mode)."""
        if ev.kind == "pods":
            for p in ev.payload["pods"]:
                apply_op({"op": "create_pod", "pod": p, "at": ev.t})
            return
        if ev.kind == "node-add":
            if ev.payload["name"] not in st.nodes:
                apply_op({"op": "create_node", "node": dict(ev.payload)})
            return
        if ev.kind == "rack-fail":
            # Whole-rack outage: resolve "pick" against the sorted live rack
            # list, then fail every node in it (each op recorded
            # individually, so replay stays bit-identical).
            rack_nodes: dict[str, list[str]] = {}
            for name in sorted(st.nodes):
                rack = st.nodes[name].get("rack")
                if rack:
                    rack_nodes.setdefault(rack, []).append(name)
            racks = sorted(rack_nodes)
            if not racks:
                return
            target = racks[int(ev.payload["pick"] * len(racks)) % len(racks)]
            for name in rack_nodes[target]:
                evict_node_pods(name, recreate=True)
                apply_op({"op": "delete_node", "name": name})
            return
        # Node-targeting events resolve "pick" against the sorted live fleet.
        names = sorted(st.nodes)
        if not names:
            return
        target = names[int(ev.payload["pick"] * len(names)) % len(names)]
        if ev.kind == "node-drain":
            evict_node_pods(target, recreate=True)
            apply_op({"op": "cordon", "name": target})
        elif ev.kind == "node-fail":
            evict_node_pods(target, recreate=True)
            apply_op({"op": "delete_node", "name": target})
        elif ev.kind == "node-flap":
            payload = st.nodes[target]
            evict_node_pods(target, recreate=True)
            apply_op({"op": "delete_node", "name": target})
            push_future(clock.now + float(ev.payload["down_s"]), {"op": "create_node", "node": payload})
        else:
            raise ValueError(f"unknown workload event {ev.kind!r}")

    # -- initial fleet + event stream ---------------------------------------

    if replay_data is not None:
        actions = replay_data["actions"]
        events = []
    else:
        actions = []
        events = generate_events(sc.workload, sc.duration, random.Random(f"{seed}:workload"))
        for payload in initial_nodes(sc.workload):
            apply_op({"op": "create_node", "node": payload})
    ai = ei = 0  # replay: actions (incl. the t=0 fleet) apply in the loop

    # -- bind folding --------------------------------------------------------

    bind_cursor = 0
    evict_cursor = 0
    unbind_cursor = 0

    def fold_outcomes() -> int:
        """Fold chaos logs since the last cycle: time-to-bind, completion
        scheduling, double-bind detection, sanctioned evictions, and
        rebalancer deschedules (a migrated pod leaves the bound set so its
        re-bind is a migration completing, never a double-bind)."""
        nonlocal bind_cursor, evict_cursor, unbind_cursor
        new_binds = 0
        for t, pod_full, _node in chaos.bind_log[bind_cursor:]:
            name = pod_full.rpartition("/")[2]
            if name in st.bound_live:
                st.double_bound += 1
            st.bound_live.add(name)
            st.bind_epoch[name] = st.bind_epoch.get(name, 0) + 1
            st.first_bind.setdefault(name, _node)
            if name in st.arrival_t:
                st.ttb.append(round(t - st.arrival_t[name], 9))
            if replay_data is None and name in st.lifetime:
                epoch = st.bind_epoch[name]
                push_future(t + st.lifetime[name], {"op": "delete_pod", "name": name, "reason": "completed", "_epoch": epoch})
            new_binds += 1
        bind_cursor = len(chaos.bind_log)
        for _t, pod_full in chaos.evict_log[evict_cursor:]:
            name = pod_full.rpartition("/")[2]
            if name in st.live:
                st.live.discard(name)
                st.bound_live.discard(name)
                st.disturbed_pods.add(name)
                st.counts["evicted"] += 1
        evict_cursor = len(chaos.evict_log)
        # Rebalancer deschedules happen AFTER the cycle's binds (the tick
        # runs at cycle end), so draining them after the bind fold keeps
        # intra-cycle order: unbound pods re-enter pending and their next
        # bind re-adds them above.
        restarts = _forced_restarts()
        for _t, pod_full in chaos.unbind_log[unbind_cursor:]:
            name = pod_full.rpartition("/")[2]
            st.bound_live.discard(name)
            st.counts["migrated"] += 1
            # A spot reclaim or autoscale drain is a forced RESTART, not a
            # scheduling decision: the TTB clock restarts at eviction, so
            # the scorecard judges how fast the fleet re-places the pod —
            # not the cloud's choice of when to take the node away.
            # (Rebalancer migrations keep the original clock: the
            # scheduler chose those.)
            if pod_full in restarts and name in st.arrival_t:
                st.arrival_t[name] = _t
                st.disturbed_pods.add(name)
        unbind_cursor = len(chaos.unbind_log)
        return new_binds

    def _forced_restarts() -> set[str]:
        # shape: () -> set[str]  (full pod names force-unbound by the
        # provider's reclaim kill path or an autoscaler scale-down drain)
        out: set[str] = set()
        if getattr(fleet, "provider", None) is not None:
            out.update(fleet.provider.reclaim_unbound)
        for sched in fleet.scheds:
            if sched.autoscaler is not None:
                out.update(sched.autoscaler.drain_unbound)
        return out

    # -- the discrete-event loop --------------------------------------------

    cycles = 0
    no_progress = 0
    max_pending = 0
    # Compile-flatness bookkeeping: the process-global compile count at the
    # warmup-cycle boundary.  None until the run crosses it (a run shorter
    # than the warmup window is all-warmup: post-warmup count 0).
    warmup_compile_mark: int | None = None
    hard_cap = int(3 * sc.duration / sc.cycle_interval) + 400
    while True:
        now = clock.now
        if replay_data is not None:
            while ai < len(actions) and actions[ai][0] <= now:
                try:
                    apply_op(actions[ai][1])
                except Exception as e:
                    # A recorded op that no longer applies means the trace is
                    # corrupt or the world diverged — name it, don't 404.
                    raise RuntimeError(
                        f"trace replay diverged applying action {ai} ({actions[ai][1].get('op')!r}): {e}"
                    ) from e
                ai += 1
        else:
            while future and future[0][0] <= now:
                _t, _s, op = heapq.heappop(future)
                epoch = op.pop("_epoch", None)
                if op["op"] == "delete_pod":
                    name = op["name"]
                    # A completion for an earlier life of the pod (churn
                    # recreated it since) or a pod evicted meanwhile: skip.
                    if name not in st.bound_live or (epoch is not None and st.bind_epoch.get(name) != epoch):
                        continue
                elif op["op"] == "create_node" and op["node"]["name"] in st.nodes:
                    continue  # flap return raced a node-add; keep the live one
                apply_op(op)
            while ei < len(events) and events[ei].t <= now:
                resolve_event(events[ei])
                ei += 1

        # The episode surface: hand the cycle to the driver; a returned
        # profile applies fleet-wide from this cycle on (the controller
        # reads ``self.profile`` fresh each cycle, so installation is just
        # attribute assignment — zero cost on the None-action path).
        action = yield EpisodeContext(clock, inner, chaos, fleet, st, cycles)
        if action is not None:
            for sched in fleet.scheds:
                sched.profile = action

        fleet.step()
        cycles += 1
        if warmup_compile_mark is None and cycles >= sc.compile_warmup_cycles:
            warmup_compile_mark = int(compile_stats()["compiles"])
        new_binds = fold_outcomes()
        pending = len(inner.list_pods("status.phase=Pending"))
        max_pending = max(max_pending, pending)
        if writer:
            writer.cycle(clock.now, cycles, new_binds, pending)
        no_progress = 0 if (new_binds or pending == 0) else no_progress + 1
        if clock.now >= sc.duration:
            events_done = (ai >= len(actions)) if replay_data is not None else (ei >= len(events))
            if events_done and (pending == 0 or no_progress >= sc.drain_grace_cycles):
                break
        if cycles >= hard_cap:
            break
        clock.advance(sc.cycle_interval)

    # -- verdict -------------------------------------------------------------

    end_t = clock.now
    api_pods = {p.metadata.name: p for p in inner.list_pods()}
    lost = sorted(name for name in st.live if name not in api_pods)
    pending_final = [p for p in api_pods.values() if p.status.phase == "Pending" and not is_pod_bound(p)]
    backlog = sum(end_t - st.arrival_t[p.metadata.name] for p in pending_final if p.metadata.name in st.arrival_t)
    pod_counts = {
        **st.counts,
        "bound_total": len(st.ttb),
        "pending_final": len(pending_final),
        "running_final": sum(1 for p in api_pods.values() if is_pod_bound(p)),
        "lost": len(lost),
        "lost_names": lost[:20],
        "double_bound": st.double_bound,
    }
    if getattr(fleet, "provider", None) is not None:
        # A reclaim notice is cluster churn: the run can end inside the
        # notice→kill grace window with pods still bound on the cordoned
        # node — the provider took it, the scheduler didn't misplace them.
        for rec in fleet.provider.records:
            if rec["state"] == "reclaiming":
                st.disturbed_nodes.add(rec["name"])
    invariants = check_invariants(inner, st.scheduled_names, st.disturbed_pods, st.disturbed_nodes, st.gangs)
    placements = [
        (p.metadata.name, p.spec.node_name) for p in api_pods.values() if p.spec is not None and p.spec.node_name
    ]
    fp = fingerprint(chaos.bind_log, placements)
    # Resilience verdict inputs: each replica's breaker open spans vs the
    # binds THAT replica POSTed (chaos.bind_actors attributes every bind_log
    # entry to its posting replica — a survivor binding while a dead
    # replica's breaker log still reads open is healthy failover, not a
    # degraded-mode bug), recovery time after the last chaos window, and the
    # worst backlog the run ever held.
    # Strictly interior, on 9-decimal-rounded bounds (bind_log timestamps
    # are rounded the same way): virtual time is discrete, so the POST that
    # tripped the breaker (or a success completing in the same instant)
    # shares the open-start timestamp, and a half-open probe shares the
    # open-end one — both happened through a not-yet/no-longer open breaker.
    open_iv_by_replica = [
        [(round(s, 9), round(e, 9)) for s, e in r.breaker.open_intervals(end_t)] for r in fleet.scheds
    ]
    open_iv = [span for per_replica in open_iv_by_replica for span in per_replica]
    binds_while_open = sum(
        1
        for (t, _pf, _n), actor in zip(chaos.bind_log, chaos.bind_actors)
        if any(s < t < e for s, e in open_iv_by_replica[actor])
    )
    last_window_end = max((w.end for w in sc.chaos.windows), default=None)
    recovery_s = None
    if last_window_end is not None:
        after = [t for t, _pf, _n in chaos.bind_log if t >= last_window_end]
        recovery_s = round(after[0] - last_window_end, 6) if after else None
    metrics_snapshot = fleet.merged_metrics()
    resilience = {
        "breaker_transitions": sum(len(r.breaker.transitions) for r in fleet.scheds),
        "breaker_opened": sum(r.breaker.opened_total for r in fleet.scheds),
        "breaker_open_seconds": round(sum(e - s for s, e in open_iv), 6),
        "binds_while_open": binds_while_open,
        "recovery_seconds_after_brownout": recovery_s,
        "max_pending_backlog": max_pending,
        "deferred_binds": int(metrics_snapshot.get("scheduler_deferred_binds_total", 0)),
        "flushed_binds": int(metrics_snapshot.get("scheduler_flushed_binds_total", 0)),
        "backoff_pruned": int(metrics_snapshot.get("scheduler_backoff_pruned_total", 0)),
    }
    card = build_scorecard(
        scenario=sc.name,
        seed=seed,
        mode="replay" if replay_data is not None else "live",
        virtual_seconds=end_t,
        cycles=cycles,
        pod_counts=pod_counts,
        ttb=st.ttb,
        backlog_pod_seconds=backlog,
        metrics_snapshot=metrics_snapshot,
        invariants=invariants,
        chaos_injected=chaos.injected,
        resilience=resilience,
        availability=fleet.availability_block(pending_final, st.double_bound),
        convergence=_convergence_block(sc, fleet, inner, pending_final, end_t),
        locality=_locality_block(sc, st),
        profile=_profile_block(sc, fleet),
        compile=_compile_block(
            sc,
            0 if warmup_compile_mark is None else int(compile_stats()["compiles"]) - warmup_compile_mark,
        ),
        incremental=_incremental_block(sc, fleet),
        rebalance=_rebalance_block(
            sc,
            fleet,
            inner,
            chaos,
            pending_final,
            lost,
            open_iv_by_replica,
            rebalance_on,
            int(metrics_snapshot.get("scheduler_preemption_victims_total", 0))
            + int(metrics_snapshot.get("scheduler_noexecute_evictions_total", 0)),
        ),
        elasticity=_elasticity_block(sc, fleet, pending_final, lost, end_t, st, autoscale_on),
        latency=_latency_block(sc, fleet, st),
        recorder_stats={
            "tracked_pods": sum(len(r.recorder.tracked_pods()) for r in fleet.scheds),
            "evicted_timelines": sum(r.recorder.evicted_timelines for r in fleet.scheds),
            "recorded_cycles": sum(len(r.recorder.cycles()) for r in fleet.scheds),
        },
        fp=fp,
        policy_required=bool(sc.policy_required),
        policy_floor=float(sc.policy_objective_floor),
    )
    if writer:
        for ep, inject, lat in chaos.decision_log:
            writer.chaos(ep, inject, lat)
        writer.footer(fp, card)
        writer.close()
    if replay_data is not None and replay_data.get("footer"):
        expected = replay_data["footer"]["fingerprint"]
        if expected != fp:
            raise ReplayMismatchError(expected, fp)
    if profile_gates is not None:
        walls = [r.profile_ring.snapshot() for r in fleet.scheds]
        wall_total = sum(s["wall_total_s"] for s in walls)
        other_total = sum(s["other_total_s"] for s in walls)
        ests = [r.profile_ring.overhead_estimate() for r in fleet.scheds]
        profile_gates["coverage"] = (1.0 - other_total / wall_total) if wall_total > 0 else 1.0
        profile_gates["overhead_frac"] = max((e["overhead_frac"] for e in ests), default=0.0)
        profile_gates["per_span_s"] = max((e["per_span_s"] for e in ests), default=0.0)
        profile_gates["spans_per_cycle"] = max((e["spans_per_cycle"] for e in ests), default=0.0)
    return card
