"""Multi-replica sim harness — N controllers, one chaos apiserver, one clock.

``MultiReplicaHarness`` runs N real ``Scheduler`` instances (each with its
own reflectors, breaker, and backoff ledgers) against ONE ``ChaosApiServer``
on ONE ``VirtualClock``, the pending set partitioned across lease-owned
shards (runtime/shards.py).  Each discrete-event step cycles every live
replica in index order — the fixed order is what keeps the shared chaos
rng's draw sequence, and therefore the whole run, bit-identical under
record/replay.

Replica kills are the chaos this harness adds: at each scheduled
``(virtual time, replica)`` point the replica's next cycle is interrupted
between solve and flush (a hook raises on the first binding POST decision,
so placements were computed but ZERO binds left the process) and the
replica is never cycled again — its leases are NOT released, exactly like a
crash.  Survivors must absorb the orphaned shards within
``2 × lease_duration``; the scorecard ``availability`` block holds that
bound, plus double-binds = 0 and orphaned-pods = 0, as a pass gate.

A 1-replica harness constructs the scheduler exactly as the single-replica
path always did (same rng label, no shard machinery), so every pre-existing
scenario's fingerprint is unchanged.
"""

from __future__ import annotations

import random

from ..backends.base import SchedulingBackend
from ..runtime.controller import Scheduler

__all__ = ["AVAILABILITY_FIELDS", "ReplicaKilled", "MultiReplicaHarness"]

# The closed schema of the scorecard ``availability`` block (drift-gated
# against the README "Multi-replica & failover" catalogue by the REPL rule).
AVAILABILITY_FIELDS = (
    "enabled",
    "replicas",
    "shards",
    "lease_duration_s",
    "kills",
    "max_takeover_latency_s",
    "takeover_bound_s",
    "lease_outage_credit_s",
    "orphaned_pods",
    "orphaned_reservations",
    "double_binds",
    "ok",
)


class ReplicaKilled(Exception):
    """Raised from the pre-bind hook to crash a replica between solve and
    flush — placements decided, zero POSTs issued."""

    def __init__(self, replica: int):
        super().__init__(f"replica {replica} killed mid-cycle")
        self.replica = replica


class MultiReplicaHarness:
    """The replica fleet + kill schedule + takeover bookkeeping."""

    def __init__(
        self,
        sc,
        seed: int,
        clock,
        chaos,
        backend: SchedulingBackend,
        profile,
        events_buffer: int,
        topology,
        rebalance_on: bool = False,
        autoscale_on: bool = False,
    ):
        self.sc = sc
        self.clock = clock
        self.chaos = chaos
        self.replicas = max(1, int(sc.replicas))
        self.shards = int(sc.shards) if sc.shards > 0 else 2 * self.replicas
        # ONE provider per cluster (the cloud account), shared by every
        # replica: the shard-0 owner's autoscaler drives it, and a shard-0
        # takeover inherits the in-flight provisions and reclaim deadlines
        # because the ledger lives here, not in the dead replica.  Its rng
        # label ("provider") is its own stream — scheduler/chaos/workload
        # draw sequences are untouched, so old fingerprints hold.
        self.provider = None
        if autoscale_on:
            from ..autoscale import DEFAULT_CATALOG, SimCloudProvider

            catalog = tuple(
                s for s in DEFAULT_CATALOG if not sc.autoscale_skus or s.name in sc.autoscale_skus
            )
            self.provider = SimCloudProvider(
                chaos,
                clock=clock,
                rng=random.Random(f"{seed}:provider"),
                catalog=catalog,
                total_quota=int(sc.autoscale_quota),
                reclaim_rate=float(sc.autoscale_reclaim_rate),
                reclaim_grace_s=float(sc.autoscale_reclaim_grace_s),
            )
        self.scheds: list[Scheduler] = []
        for i in range(self.replicas):
            kwargs = dict(
                profile=profile,
                requeue_seconds=sc.requeue_seconds,
                clock=clock,
                # Replica 0 keeps the historic rng label so single-replica
                # scenarios stay fingerprint-identical with old traces.
                rng=random.Random(f"{seed}:sched" if i == 0 else f"{seed}:sched{i}"),
                events_buffer=events_buffer,
                topology=topology,
                # Incremental engine shadow sampling (tpu_scheduler/delta):
                # deterministic — span presence and parity verdicts are pure
                # control flow, so record/replay bit-identity holds.
                delta_shadow_every=getattr(sc, "delta_shadow_every", 0),
            )
            if rebalance_on:
                # Background rebalancer (tpu_scheduler/rebalance), INLINE
                # solve mode: a worker thread would race the VirtualClock,
                # so the sim runs the packing solve synchronously inside
                # the cadence-gated tick — every decision is control flow
                # and record/replay bit-identity holds.
                from ..rebalance import RebalanceConfig

                kwargs.update(
                    rebalance=RebalanceConfig(
                        every=int(sc.rebalance_every),
                        batch=int(sc.rebalance_batch),
                        max_migrations=int(sc.rebalance_migration_budget),
                    )
                )
            if autoscale_on:
                # Closed-loop autoscaler (tpu_scheduler/autoscale), INLINE
                # plan mode for the same VirtualClock reason as above.
                # Every replica gets an Autoscaler but only the shard-0
                # owner ticks (runtime/controller.py gates), so the shared
                # provider sees exactly one decision stream.
                from ..autoscale import AutoscaleConfig

                kwargs.update(
                    autoscale=AutoscaleConfig(
                        every=int(sc.autoscale_every),
                        burn_trigger=float(sc.autoscale_burn_trigger),
                        max_per_tick=int(sc.autoscale_max_per_tick),
                        cooldown=int(sc.autoscale_cooldown),
                        reserve=int(sc.autoscale_reserve),
                    ),
                    autoscale_provider=self.provider,
                )
            if self.replicas > 1:
                kwargs.update(shards=self.shards, identity=f"replica-{i}", lease_duration=sc.lease_duration)
            self.scheds.append(Scheduler(chaos, backend, **kwargs))
        self.alive = [True] * self.replicas
        self._kills = sorted((float(t), int(idx)) for t, idx in sc.replica_kills)
        self._kill_cursor = 0
        # One record per executed kill; takeover_latency_s fills in when
        # every orphaned shard is re-owned by a live replica.
        self.kills: list[dict] = []
        self._awaiting_takeover: list[dict] = []

    @property
    def primary(self) -> Scheduler:
        return self.scheds[0]

    # -- one discrete-event step --------------------------------------------

    def step(self) -> None:
        """Cycle every live replica in index order, executing kills due at
        the current virtual time during the victim's own cycle."""
        now = self.clock.now
        due: set[int] = set()
        while self._kill_cursor < len(self._kills) and self._kills[self._kill_cursor][0] <= now:
            due.add(self._kills[self._kill_cursor][1])
            self._kill_cursor += 1
        for i, sched in enumerate(self.scheds):
            if not self.alive[i]:
                continue
            self.chaos.actor = i
            if i in due:
                self._kill_during_cycle(i, sched)
            else:
                sched.run_cycle()
        self._resolve_takeovers()

    def _kill_during_cycle(self, i: int, sched: Scheduler) -> None:
        """Crash the replica between solve and flush: the hook fires on its
        first binding POST decision of the cycle.  A cycle with nothing to
        bind dies at cycle end instead — either way the replica never
        cycles again and never releases a lease."""

        def die(_ns, _name, _node):
            raise ReplicaKilled(i)

        sched.pre_bind_hook = die
        try:
            sched.run_cycle()
        except ReplicaKilled:
            pass
        finally:
            sched.pre_bind_hook = None
        self.alive[i] = False
        orphans = sorted(sched.shard_set.owned) if sched.shard_set is not None else []
        rec = {
            "replica": i,
            "at": round(self.clock.now, 6),
            "orphan_shards": orphans,
            "takeover_latency_s": None,
        }
        self.kills.append(rec)
        if orphans:
            self._awaiting_takeover.append(rec)
        else:
            rec["takeover_latency_s"] = 0.0

    def _live_owned(self) -> set[int]:
        owned: set[int] = set()
        for i, sched in enumerate(self.scheds):
            if self.alive[i] and sched.shard_set is not None:
                owned.update(sched.shard_set.owned)
        return owned

    def _resolve_takeovers(self) -> None:
        if not self._awaiting_takeover:
            return
        owned_now = self._live_owned()
        resolved = []
        for rec in self._awaiting_takeover:
            if all(s in owned_now for s in rec["orphan_shards"]):
                rec["takeover_latency_s"] = round(self.clock.now - rec["at"], 6)
                resolved.append(rec)
        for rec in resolved:
            self._awaiting_takeover.remove(rec)

    # -- verdict inputs -----------------------------------------------------

    def merged_metrics(self) -> dict:
        """Counter snapshots summed across replicas (numeric values only;
        single-replica runs reduce to the one snapshot unchanged)."""
        out: dict = {}
        for sched in self.scheds:
            for k, v in sched.metrics.snapshot().items():
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    out[k] = out.get(k, 0) + v
                elif k not in out:
                    out[k] = v
        return out

    def _shard_of(self, pod) -> int:
        """Keyer-consistent pod→shard: a live replica's installed ShardKeyer
        (the fleet's topology keying) judges ownership exactly as the
        controllers do; the flat module hash is the fallback for fleets that
        never compiled one."""
        for i, sched in enumerate(self.scheds):
            if self.alive[i] and sched.shard_set is not None:
                return sched.shard_set.shard_of(pod)
        from ..runtime.shards import shard_of_pod

        return shard_of_pod(pod, self.shards)

    def _lease_outage_overlap(self, t0: float, t1: float) -> float:
        """Virtual seconds within [t0, t1] during which the lease CAS
        endpoints were HARD down (an injected error or refusal rate >= 1.0).
        No scheduler can complete a takeover through a dead CAS, so the
        takeover bound credits exactly this overlap — found by the chaos
        fuzzer (a replica kill composed with a total lease-500 window made
        the physically-optimal takeover miss the fixed bound by the outage
        length).  Partial brownouts (< 1.0) leave retries a way through and
        still count against the budget."""
        cfg = getattr(self.chaos, "config", None)
        if cfg is None:
            return 0.0
        total = 0.0
        for w in cfg.windows:
            hard = max(w.lease_error_rate or 0.0, w.lease_refused_rate or 0.0)
            if hard >= 1.0:
                total += max(0.0, min(t1, float(w.end)) - max(t0, float(w.start)))
        return total

    def availability_block(self, pending_final, double_binds: int) -> dict:
        """The scorecard ``availability`` verdict.  ``ok`` requires zero
        double-binds, zero orphaned pods (a final-pending pod whose shard no
        live replica owns has no controller responsible for it), zero
        orphaned gang reservations (an unexpired reservation lease held by a
        dead replica would wedge peer capacity past the settle), and every
        kill's takeover resolved within 2 × lease_duration of virtual
        time — plus, per kill, the hard-lease-outage credit above."""
        enabled = self.replicas > 1
        out = {
            "enabled": enabled,
            "replicas": self.replicas,
            "shards": self.shards if enabled else 0,
            "lease_duration_s": round(float(self.sc.lease_duration), 6) if enabled else None,
            "kills": self.kills,
            "max_takeover_latency_s": None,
            "takeover_bound_s": round(2.0 * float(self.sc.lease_duration), 6) if enabled else None,
            "lease_outage_credit_s": 0.0 if enabled else None,
            "orphaned_pods": 0,
            "orphaned_reservations": 0,
            "double_binds": int(double_binds),
            "ok": True,
        }
        if not enabled:
            return out
        owned_now = self._live_owned()
        out["orphaned_pods"] = sum(1 for p in pending_final if self._shard_of(p) not in owned_now)
        from ..fleet.reservation import count_orphaned_reservations

        live = {sched.identity for i, sched in enumerate(self.scheds) if self.alive[i]}
        out["orphaned_reservations"] = count_orphaned_reservations(self.chaos, self.clock.now, live)
        latencies = [rec["takeover_latency_s"] for rec in self.kills]
        resolved = [lat for lat in latencies if lat is not None]
        if resolved:
            out["max_takeover_latency_s"] = round(max(resolved), 6)
        takeovers_ok = True
        max_credit = 0.0
        for rec in self.kills:
            lat = rec["takeover_latency_s"]
            if lat is None:
                takeovers_ok = False
                continue
            credit = self._lease_outage_overlap(rec["at"], rec["at"] + lat)
            max_credit = max(max_credit, credit)
            if lat > out["takeover_bound_s"] + credit:
                takeovers_ok = False
        out["lease_outage_credit_s"] = round(max_credit, 6)
        out["ok"] = bool(
            double_binds == 0
            and out["orphaned_pods"] == 0
            and out["orphaned_reservations"] == 0
            and takeovers_ok
        )
        return out
