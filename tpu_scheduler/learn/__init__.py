"""Policy learning — gym-style sim episodes, seeded black-box search, and
zero-cost distillation into the fused score path.

The subsystem has four layers:

  ``objective.py``  — the scalar reward surface: one number per scorecard,
                      computed from existing blocks only (SLO attainment,
                      packing efficiency, gang locality, churn penalty)
                      with a closed, documented weight schema.
  ``env.py``        — ``SchedulerEnv``: step/observe/act episodes over
                      ``sim/harness.py`` on the existing ``VirtualClock``;
                      every episode reproducible from one seed.
  ``search.py``     — dependency-free seeded cross-entropy search over the
                      ``SchedulingProfile`` weight vector, train seeds for
                      climbing and a held-out seed set for selection.
  ``distill.py``    — the winning vector exported as a versioned JSON
                      artifact (``learn/profiles/``), loadable via
                      ``SchedulingProfile.from_file`` / ``--profile-file``
                      and riding the existing fused choose path at ZERO
                      inference cost.

This ``__init__`` stays import-light on purpose: ``sim/scorecard.py``
imports ``learn.objective`` for every verdict, and must not drag the env
or search machinery (or jax, via the backends) into that path.
"""

from __future__ import annotations

__all__: list[str] = []
