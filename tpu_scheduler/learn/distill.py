"""Distillation — the winning vector becomes a runtime artifact.

The whole point of the subsystem: "learned" must cost NOTHING at inference.
A training run's output is just a ``SchedulingProfile`` serialized as the
versioned JSON artifact (``models/profiles.py`` schema), loadable via
``SchedulingProfile.from_file`` / CLI ``--profile-file`` — the tuned
weights ride the existing fused choose path (native, jit, and Pallas
variants) exactly like the defaults did, so the steady-state delta-cycle
bench is unchanged by construction (bench.py ``policy_row`` holds that).

``provenance`` makes every artifact auditable: the full ``SearchConfig``
echo (one seed reproduces the run), the objective version it was trained
against, the per-generation history, and the held-out table tuned-vs-
default — the numbers the PR reports.
"""

from __future__ import annotations

from dataclasses import asdict

from ..models.profiles import SchedulingProfile
from .objective import OBJECTIVE_VERSION

__all__ = ["distill", "load_profile"]


def distill(result, out_path: str) -> dict:
    """Write the tuned-profile artifact for a finished ``TrainResult``;
    returns the provenance block that went into it."""
    # shape: (result: obj, out_path: str) -> obj
    cfg = result.config
    provenance = {
        "objective_version": OBJECTIVE_VERSION,
        "search": asdict(cfg) if cfg is not None else {},
        "vector": list(result.vector),
        "improved": bool(result.improved),
        "train_objective": result.train_objective,
        "default_train_objective": result.default_train_objective,
        "held_out": dict(result.held_out),
        "default_held_out": dict(result.default_held_out),
        "history": list(result.history),
    }
    result.profile.to_file(out_path, provenance)
    return provenance


def load_profile(path: str) -> SchedulingProfile:
    """Load any profile artifact (tuned or the checked-in default) —
    strict: schema-version and unknown-key violations raise."""
    # shape: (path: str) -> obj
    return SchedulingProfile.from_file(path)
