"""``SchedulerEnv`` — gym-style episodes over the deterministic simulator.

Wraps ``sim/harness.py scenario_episode`` (the discrete-event loop as a
generator) into the step/observe/act interface black-box search climbs:

  observe — a per-cycle feature summary (``OBSERVATION_FIELDS``): pending
            census by SLO tier and gangness, residual-capacity percentiles
            across the node fleet, backlog age, and topology fragmentation.
  act     — a policy vector over ``ACTION_KNOBS`` (the ``SchedulingProfile``
            score-weight surface), installed fleet-wide for the next cycle
            window; ``None`` keeps the current profile (a None-only episode
            is bit-identical to a plain ``run_scenario``).
  reward  — episodic: 0.0 every non-terminal step, and the scorecard
            ``policy`` objective (learn/objective.py) on the terminal one.

Determinism: the env adds NO randomness — every draw still derives from
the one scenario seed inside the harness, observations are pure functions
of the yielded ``EpisodeContext``, and all floats are rounded to 6 decimals
— so the same (scenario, seed, action sequence) produces a byte-identical
observation/reward trajectory in any process.
"""

from __future__ import annotations

from ..api.objects import is_pod_bound
from ..core.snapshot import ClusterSnapshot, node_allocatable, node_used_resources
from ..sim.harness import scenario_episode
from ..sim.scorecard import _percentile
from ..utils.profiler import tier_of

__all__ = ["OBSERVATION_FIELDS", "ACTION_KNOBS", "SchedulerEnv", "action_profile", "observe"]

# The closed observation schema: field name -> position in every
# observation dict (drift-gated against the README "Learned policy &
# tuning" catalogue by the LERN analyze rule).  Strictly virtual-time /
# control-flow quantities — wall clock never appears.
OBSERVATION_FIELDS = (
    "virtual_time",        # clock.now (virtual seconds)
    "pending_total",       # pods awaiting placement
    "pending_gang_pods",   # pending pods that belong to a gang
    "pending_gangs",       # distinct gangs with a pending member
    "pending_critical",    # pending census by SLO tier (utils/profiler.py)
    "pending_high",
    "pending_default",
    "pending_best_effort",
    "backlog_age_mean",    # mean pending age vs nominal arrival (virtual s)
    "backlog_age_max",     # oldest pending pod's age
    "free_cpu_frac_p10",   # residual-capacity percentiles across nodes
    "free_cpu_frac_p50",
    "free_cpu_frac_p90",
    "frag_free_cpu",       # 1 - largest single rack's share of free CPU
)

# The closed action surface: (SchedulingProfile field, lower, upper).  A
# policy vector indexes this tuple; ``action_profile`` clips each entry
# into its bounds, so every action is a VALID profile — validity and
# capacity stay exact no matter what the optimizer proposes.  The bounds
# are the solver's operating envelope, not just sanity limits:
# ``least_requested_weight`` is floored at 0.25 because a negative weight
# (most-requested packing) makes every pod bid the same fullest node and
# the auction's round budget explodes at flagship scale (measured: 97
# rounds vs 8 on 4000x400, half the wave unplaced at max_rounds=32), and
# a zero weight leaves equally-free nodes score-tied, which costs rounds
# the same way (1.35x delta-cycle at the bench shape) — packing density
# belongs to the background rebalancer tier, off the hot path, not to
# the per-cycle score vector.
ACTION_KNOBS = (
    ("least_requested_weight", 0.25, 4.0),
    ("balanced_allocation_weight", 0.0, 4.0),
    ("spread_jitter", 0.0, 64.0),
    ("preferred_affinity_weight", 0.0, 4.0),
    ("soft_taint_weight", 0.0, 40.0),
    ("topology_weight", 0.0, 8.0),
    ("gang_locality_weight", 0.0, 256.0),
)


def action_profile(base, vec):
    """Clip a raw policy vector into ``ACTION_KNOBS`` bounds and graft it
    onto ``base`` (preemption/driver/blocks untouched)."""
    # shape: (base: obj, vec: obj) -> obj
    if len(vec) != len(ACTION_KNOBS):
        raise ValueError(f"action vector has {len(vec)} entries, expected {len(ACTION_KNOBS)}")
    kw = {}
    for (name, lo, hi), raw in zip(ACTION_KNOBS, vec):
        kw[name] = round(min(hi, max(lo, float(raw))), 6)
    return base.with_(**kw)


def observe(ctx) -> dict:
    """The per-cycle feature summary, computed lazily from the yielded
    ``EpisodeContext`` — plain ``run_scenario`` drives never call this, so
    ordinary runs pay nothing for the observation surface."""
    # shape: (ctx: obj) -> obj
    now = ctx.clock.now
    pods = ctx.api.list_pods()
    nodes = ctx.api.list_nodes()
    pending = [p for p in pods if p.status.phase == "Pending" and not is_pod_bound(p)]

    tiers = {"critical": 0, "high": 0, "default": 0, "best-effort": 0}
    gang_pods = 0
    gangs: set[str] = set()
    ages: list[float] = []
    for p in pending:
        prio = p.spec.priority if p.spec is not None and p.spec.priority else 0
        tiers[tier_of(prio)] += 1
        if p.spec is not None and p.spec.gang:
            gang_pods += 1
            gangs.add(p.spec.gang)
        at = ctx.state.arrival_t.get(p.metadata.name)
        if at is not None:
            ages.append(max(0.0, now - at))

    # Residual capacity: free-CPU fraction per node, plus how concentrated
    # the free pool is across racks (a fragmented fleet has plenty of free
    # CPU but no single rack that fits a gang).
    snap = ClusterSnapshot.build(nodes, pods)
    fracs: list[float] = []
    rack_free: dict[str, float] = {}
    total_free = 0.0
    for n in snap.nodes:
        alloc = node_allocatable(n)
        if alloc.cpu <= 0:
            continue
        free = max(0.0, float(alloc.cpu) - float(node_used_resources(snap, n.name).cpu))
        fracs.append(free / float(alloc.cpu))
        total_free += free
        rack = ctx.state.node_domains.get(n.name, {}).get("rack")
        if rack is not None:
            rack_free[rack] = rack_free.get(rack, 0.0) + free
    fracs.sort()
    frag = 0.0
    if total_free > 0 and rack_free:
        frag = 1.0 - max(rack_free.values()) / total_free

    obs = {
        "virtual_time": round(now, 6),
        "pending_total": len(pending),
        "pending_gang_pods": gang_pods,
        "pending_gangs": len(gangs),
        "pending_critical": tiers["critical"],
        "pending_high": tiers["high"],
        "pending_default": tiers["default"],
        "pending_best_effort": tiers["best-effort"],
        "backlog_age_mean": round(sum(ages) / len(ages), 6) if ages else 0.0,
        "backlog_age_max": round(max(ages), 6) if ages else 0.0,
        "free_cpu_frac_p10": round(_percentile(fracs, 0.10), 6),
        "free_cpu_frac_p50": round(_percentile(fracs, 0.50), 6),
        "free_cpu_frac_p90": round(_percentile(fracs, 0.90), 6),
        "frag_free_cpu": round(frag, 6),
    }
    assert tuple(obs) == OBSERVATION_FIELDS, "observation drifted from OBSERVATION_FIELDS"
    return obs


class SchedulerEnv:
    """One episode = one seeded scenario run.  ``reset`` starts the
    generator and returns the first observation; ``step(action)`` installs
    the (optional) policy vector for the next ``window`` cycles and returns
    ``(obs, reward, done, info)``.  After the terminal step ``info`` holds
    the full scorecard under ``"scorecard"``."""

    def __init__(self, scenario, seed: int = 0, backend=None, window: int = 1, topology="auto", rebalance="auto"):
        # shape: (self: obj, scenario: obj, seed: int, backend: obj, window: int, topology: obj, rebalance: obj) -> obj
        if window < 1:
            raise ValueError("window must be >= 1")
        self.scenario = scenario
        self.seed = seed
        self.backend = backend
        self.window = window
        self.topology = topology
        self.rebalance = rebalance
        self._gen = None
        self._ctx = None
        self.card: dict | None = None

    def reset(self) -> dict:
        """Start (or restart) the episode; returns the first observation."""
        # shape: (self: obj) -> obj
        self.card = None
        self._gen = scenario_episode(
            self.scenario,
            seed=self.seed,
            backend=self.backend,
            topology=self.topology,
            rebalance=self.rebalance,
        )
        self._ctx = next(self._gen)
        return observe(self._ctx)

    def base_profile(self):
        """The profile the fleet is currently scheduling with (the action
        vector grafts onto this, preserving preemption/driver)."""
        # shape: (self: obj) -> obj
        if self._ctx is None:
            raise RuntimeError("call reset() first")
        return self._ctx.fleet.scheds[0].profile

    def step(self, action=None):
        """Advance ``window`` cycles under ``action`` (a policy vector over
        ``ACTION_KNOBS``, or None to keep the current profile)."""
        # shape: (self: obj, action: obj) -> obj
        if self._gen is None:
            raise RuntimeError("call reset() first")
        send = action_profile(self.base_profile(), action) if action is not None else None
        try:
            for _ in range(self.window):
                self._ctx = self._gen.send(send)
                send = None  # the profile persists; install once per step
        except StopIteration as stop:
            self.card = stop.value
            self._gen = None
            reward = round(float(self.card["policy"]["objective"]), 6)
            return observe_terminal(self.card), reward, True, {"scorecard": self.card}
        return observe(self._ctx), 0.0, False, {}


def observe_terminal(card: dict) -> dict:
    """The terminal observation: all-zero pending census at the final
    virtual time (the episode is drained or out of budget either way)."""
    # shape: (card: obj) -> obj
    obs = dict.fromkeys(OBSERVATION_FIELDS, 0.0)
    obs["virtual_time"] = round(float(card["virtual_seconds"]), 6)
    obs["pending_total"] = int(card["pods"]["pending_final"])
    return obs
