"""``python -m tpu_scheduler.cli sim train`` — the training command surface.

Runs the seeded CEM search (learn/search.py) over registered scenarios and
writes the winning profile as a versioned JSON artifact (learn/distill.py).
Stdout is one JSON report line: the held-out tuned-vs-default table, the
chosen vector, and whether the tuned profile actually won (``improved``;
on a loss the artifact falls back to the default profile's weights, so the
output is never worse than what it replaces).  Exit 0 on a written
artifact, 2 on bad arguments — "tuned lost to default" is a reported
outcome, not an error.
"""

from __future__ import annotations

import argparse
import json
import sys

from ..utils.tracing import configure_logging
from .distill import distill
from .search import SearchConfig, train_profile

__all__ = ["main", "build_parser"]


def _csv_ints(text: str) -> tuple:
    # shape: (text: str) -> obj
    return tuple(int(tok) for tok in text.split(",") if tok.strip() != "")


def build_parser() -> argparse.ArgumentParser:
    # shape: () -> obj
    from ..sim.scenarios import SCENARIOS

    p = argparse.ArgumentParser(prog="tpu-scheduler sim train", description=__doc__)
    p.add_argument(
        "--scenario-set",
        default="train-smoke",
        help=f"comma-separated registered scenarios to climb (known: {', '.join(sorted(SCENARIOS))})",
    )
    p.add_argument("--seed", type=int, default=0, help="the ONE seed the CEM sampler derives from")
    p.add_argument("--train-seeds", default="0,1", help="comma-separated episode seeds the optimizer sees")
    p.add_argument("--held-out-seeds", default="101,102", help="disjoint seeds for final tuned-vs-default selection")
    p.add_argument("--generations", type=int, default=3, help="CEM iterations")
    p.add_argument("--population", type=int, default=8, help="candidates per generation")
    p.add_argument("--elite-frac", type=float, default=0.25, help="elite refit fraction")
    p.add_argument("--workers", type=int, default=0, help="thread-pool width for episode evaluation (0 = serial)")
    p.add_argument("--out", default="profile.json", metavar="PATH", help="where the tuned-profile artifact lands")
    p.add_argument("--log-level", default="WARNING")
    return p


def main(argv=None) -> int:
    # shape: (argv: obj) -> int
    from ..sim.scenarios import SCENARIOS

    args = build_parser().parse_args(argv)
    configure_logging(args.log_level, "text")
    scenarios = tuple(tok.strip() for tok in args.scenario_set.split(",") if tok.strip())
    unknown = sorted(set(scenarios) - set(SCENARIOS))
    if unknown:
        print(f"unknown scenarios: {', '.join(unknown)}", file=sys.stderr)
        return 2
    train_seeds = _csv_ints(args.train_seeds)
    held_out = _csv_ints(args.held_out_seeds)
    if set(train_seeds) & set(held_out):
        print("--train-seeds and --held-out-seeds must be disjoint", file=sys.stderr)
        return 2
    cfg = SearchConfig(
        scenarios=scenarios,
        train_seeds=train_seeds,
        held_out_seeds=held_out,
        generations=args.generations,
        population=args.population,
        elite_frac=args.elite_frac,
        seed=args.seed,
        workers=args.workers,
    )
    result = train_profile(cfg, log=lambda msg: print(msg, file=sys.stderr))
    provenance = distill(result, args.out)
    print(
        json.dumps(
            {
                "out": args.out,
                "improved": result.improved,
                "profile": result.profile.name,
                "vector": result.vector,
                "train_objective": result.train_objective,
                "default_train_objective": result.default_train_objective,
                "held_out": result.held_out,
                "default_held_out": result.default_held_out,
                "objective_version": provenance["objective_version"],
            },
            sort_keys=True,
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
