"""Seeded black-box search over the scheduling-policy weight surface.

Dependency-free cross-entropy method (CEM): sample a Gaussian population
over the ``ACTION_KNOBS`` box, evaluate each candidate as full sim episodes
(mean scorecard objective over training scenarios × seeds), refit the
Gaussian to the elite fraction, repeat.  The current mean is always
injected as candidate 0 of every generation, so the best-seen value is
monotone and generation 0 provably contains the default profile — the
``make train-smoke`` floor.

Discipline the rest of the repo already enforces:

  * ONE seed: every draw comes from ``random.Random(f"{seed}:cem")``; the
    same ``SearchConfig`` reproduces the identical history in any process.
  * Pass gates are HARD constraints: an episode whose scorecard fails ANY
    gate (invariants, SLO, locality, availability, incremental, rebalance,
    policy floor) scores ``PASS_PENALTY``, so the optimizer cannot buy
    objective points with a broken run.
  * Held-out selection: the winning vector must beat the default profile
    on a DISJOINT seed set, else ``train_profile`` falls back to the
    default — a tuned artifact is never worse than what it replaces.
  * ``workers`` fans episode evaluation out over a thread pool (each
    episode is an independent single-threaded sim, the multi-replica
    harness pattern); results are keyed by candidate index, so the
    history is identical to the serial run.
"""

from __future__ import annotations

import random
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field

from ..models.profiles import DEFAULT_PROFILE
from .env import ACTION_KNOBS, action_profile
from .objective import objective_from_card

__all__ = [
    "PASS_PENALTY",
    "SearchConfig",
    "TrainResult",
    "cem_optimize",
    "default_vector",
    "episode_objective",
    "evaluate_vectors",
    "held_out_table",
    "train_profile",
]

# Objective assigned to a candidate whose episode FAILS its scorecard pass
# gate — far below any reachable objective (components are bounded), so a
# gate-breaking vector can never enter the elite set.
PASS_PENALTY = -10.0


@dataclass(frozen=True)
class SearchConfig:
    """Everything a training run derives from — one config, one result."""

    scenarios: tuple = ("train-smoke",)  # registered scenario names to climb
    train_seeds: tuple = (0, 1)          # episode seeds the optimizer sees
    held_out_seeds: tuple = (101, 102)   # disjoint seeds for final selection
    generations: int = 3                 # CEM iterations
    population: int = 8                  # candidates per generation
    elite_frac: float = 0.25             # refit fraction (>= 1 candidate)
    init_sigma_frac: float = 0.25        # sigma0 as a fraction of each knob's span
    sigma_floor: float = 1e-3            # sigma never collapses below this
    seed: int = 0                        # the ONE seed (rng label "{seed}:cem")
    workers: int = 0                     # thread-pool width (0/1 = serial)


@dataclass
class TrainResult:
    """What ``train_profile`` hands to ``distill``: the chosen profile plus
    the full audit trail (history, train/held-out tables, fallback flag)."""

    profile: object = None               # the chosen SchedulingProfile
    vector: list = field(default_factory=list)
    improved: bool = False               # tuned beat default on held-out
    train_objective: float = 0.0         # best train-set mean objective
    default_train_objective: float = 0.0
    held_out: dict = field(default_factory=dict)    # scenario -> tuned mean
    default_held_out: dict = field(default_factory=dict)
    history: list = field(default_factory=list)     # per-generation stats
    config: SearchConfig = None


def cem_optimize(fn, lo, hi, mean0, sigma0, *, generations, population, elite_frac, rng, sigma_floor=1e-3):
    """Generic seeded CEM over a box (MAXIMIZATION).  ``fn`` takes the whole
    population (a list of vectors) and returns one value per candidate —
    batch-shaped so the caller owns any parallelism.  Returns
    ``(best_vec, best_val, history)``; candidate 0 of every generation is
    the current mean, so ``best_val`` is monotone in the mean's value."""
    # shape: (fn: obj, lo: obj, hi: obj, mean0: obj, sigma0: obj, generations: int, population: int, elite_frac: float, rng: obj, sigma_floor: float) -> obj
    dims = len(lo)
    mean = [float(m) for m in mean0]
    sigma = [max(sigma_floor, float(s)) for s in sigma0]
    n_elite = max(1, int(round(elite_frac * population)))
    best_vec: list | None = None
    best_val = float("-inf")
    history: list[dict] = []
    for g in range(generations):
        pop = [list(mean)]
        while len(pop) < population:
            pop.append([min(hi[d], max(lo[d], rng.gauss(mean[d], sigma[d]))) for d in range(dims)])
        vals = [float(v) for v in fn(pop)]
        # Ties break on candidate index — deterministic elite membership.
        ranked = sorted(range(len(pop)), key=lambda i: (-vals[i], i))
        elite = [pop[i] for i in ranked[:n_elite]]
        if vals[ranked[0]] > best_val:
            best_val = vals[ranked[0]]
            best_vec = list(pop[ranked[0]])
        mean = [sum(e[d] for e in elite) / n_elite for d in range(dims)]
        # Decaying extra noise on top of the elite std (Szita & Lorincz):
        # without it the elite variance collapses a sqrt-factor per
        # generation and the search freezes short of the optimum.  Linear
        # decay to zero at 70% of the run leaves the tail for fine refit.
        decay = max(0.0, 1.0 - (g + 1) / max(1.0, generations * 0.7))
        sigma = [
            max(
                sigma_floor,
                (sum((e[d] - mean[d]) ** 2 for e in elite) / n_elite) ** 0.5 + float(sigma0[d]) * decay,
            )
            for d in range(dims)
        ]
        history.append(
            {
                "generation": g,
                "best": round(vals[ranked[0]], 6),
                "elite_mean": round(sum(vals[i] for i in ranked[:n_elite]) / n_elite, 6),
                "mean": [round(m, 6) for m in mean],
                "sigma": [round(s, 6) for s in sigma],
            }
        )
    return best_vec, best_val, history


def default_vector() -> list:
    """The default profile's coordinates in ``ACTION_KNOBS`` order — the
    search's starting mean and the held-out baseline."""
    # shape: () -> obj
    return [float(getattr(DEFAULT_PROFILE, name)) for name, _lo, _hi in ACTION_KNOBS]


def episode_objective(vec, scenario, seed: int) -> float:
    """One full episode under the candidate vector; the scorecard policy
    objective, or ``PASS_PENALTY`` when ANY pass gate fails."""
    # shape: (vec: obj, scenario: obj, seed: int) -> float
    from ..sim.harness import run_scenario

    profile = action_profile(DEFAULT_PROFILE, vec)
    card = run_scenario(scenario, seed=seed, profile=profile)
    if not card["pass"]:
        return PASS_PENALTY
    return objective_from_card(card)


def evaluate_vectors(vectors, scenarios, seeds, workers: int = 0) -> list:
    """Mean episode objective per candidate over scenarios × seeds.
    ``workers > 1`` fans the independent episodes over a thread pool;
    results are folded by (candidate, scenario, seed) index, so the output
    is identical to the serial evaluation."""
    # shape: (vectors: obj, scenarios: obj, seeds: obj, workers: int) -> obj
    jobs = [
        (i, sc, seed)
        for i, _vec in enumerate(vectors)
        for sc in scenarios
        for seed in seeds
    ]
    if workers and workers > 1:
        with ThreadPoolExecutor(max_workers=int(workers)) as pool:
            scores = list(pool.map(lambda j: episode_objective(vectors[j[0]], j[1], j[2]), jobs))
    else:
        scores = [episode_objective(vectors[i], sc, seed) for i, sc, seed in jobs]
    per = len(scenarios) * len(seeds)
    return [round(sum(scores[i * per : (i + 1) * per]) / per, 6) for i in range(len(vectors))]


def held_out_table(vec, scenarios, seeds, workers: int = 0) -> dict:
    """Per-scenario mean objective on the held-out seed set (the numbers
    the PR/bench report)."""
    # shape: (vec: obj, scenarios: obj, seeds: obj, workers: int) -> obj
    out = {}
    for sc in scenarios:
        vals = [episode_objective(vec, sc, seed) for seed in seeds]
        out[sc] = round(sum(vals) / len(vals), 6) if vals else 0.0
    return out


def train_profile(cfg: SearchConfig, log=None) -> TrainResult:
    """The full training run: CEM on the train seeds, selection on the
    held-out seeds, fall back to the default profile if the tuned vector
    does not beat it there.  Reproducible from ``cfg`` alone."""
    # shape: (cfg: obj, log: obj) -> obj
    say = log or (lambda _msg: None)
    lo = [k[1] for k in ACTION_KNOBS]
    hi = [k[2] for k in ACTION_KNOBS]
    mean0 = default_vector()
    sigma0 = [cfg.init_sigma_frac * (hi_v - lo_v) for lo_v, hi_v in zip(lo, hi)]
    rng = random.Random(f"{cfg.seed}:cem")

    def fn(pop):
        return evaluate_vectors(pop, cfg.scenarios, cfg.train_seeds, workers=cfg.workers)

    default_train = evaluate_vectors([mean0], cfg.scenarios, cfg.train_seeds, workers=cfg.workers)[0]
    say(f"generation-0 default objective (train): {default_train}")
    best_vec, best_val, history = cem_optimize(
        fn,
        lo,
        hi,
        mean0,
        sigma0,
        generations=cfg.generations,
        population=cfg.population,
        elite_frac=cfg.elite_frac,
        rng=rng,
        sigma_floor=cfg.sigma_floor,
    )
    say(f"best train objective after {cfg.generations} generations: {round(best_val, 6)}")

    tuned_held = held_out_table(best_vec, cfg.scenarios, cfg.held_out_seeds, workers=cfg.workers)
    default_held = held_out_table(mean0, cfg.scenarios, cfg.held_out_seeds, workers=cfg.workers)
    tuned_mean = sum(tuned_held.values()) / len(tuned_held)
    default_mean = sum(default_held.values()) / len(default_held)
    improved = tuned_mean > default_mean
    chosen = best_vec if improved else mean0
    say(f"held-out: tuned {round(tuned_mean, 6)} vs default {round(default_mean, 6)} -> {'tuned' if improved else 'default (fallback)'}")
    profile = action_profile(DEFAULT_PROFILE.with_(name="tuned" if improved else "default"), chosen)
    return TrainResult(
        profile=profile,
        vector=[round(float(x), 6) for x in chosen],
        improved=improved,
        train_objective=round(best_val, 6),
        default_train_objective=round(default_train, 6),
        held_out=tuned_held,
        default_held_out=default_held,
        history=history,
        config=cfg,
    )
