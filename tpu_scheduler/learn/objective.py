"""The policy objective — one scalar per scorecard, from existing blocks only.

``policy_block`` folds the scorecard's already-computed verdict blocks into
the single number the optimizer climbs (``search.py``) and every scenario
reports (the scorecard ``policy`` block).  Nothing here measures anything
new: every input is a deterministic quantity an existing block carries, so
record→replay byte-identity holds unchanged.

The weight schema is CLOSED and documented (``OBJECTIVE_COMPONENTS``,
drift-gated against the README "Learned policy & tuning" catalogue by the
LERN analyze rule):

  ``slo``       (+1.0)  bind completeness × latency factor — the fraction of
                        demand that ever bound, discounted by the p99
                        time-to-bind against the ``P99_SCALE_S`` horizon.
  ``packing``   (+0.5)  final-state packing efficiency (the rebalance
                        block's exact-integer dominant-axis fill).
  ``locality``  (+0.5)  fraction of scored gangs with zero cross-rack
                        edges (1.0 for topology-blind scenarios — no
                        locality surface to judge).
  ``churn``     (-0.25) preemption victims + NoExecute evictions +
                        rebalancer migrations per successful bind —
                        placement-quality wins must not be bought with
                        disruption.

Every component lands in [0, 1], so the objective lives in [-0.25, 2.0]
with the default weights.  Components are rounded to 6 decimals before the
weighted sum — the scorecard byte-identity contract.
"""

from __future__ import annotations

__all__ = [
    "OBJECTIVE_VERSION",
    "OBJECTIVE_COMPONENTS",
    "POLICY_FIELDS",
    "P99_SCALE_S",
    "policy_block",
    "objective_from_card",
]

# Bump on any formula or weight change: tuned artifacts record the version
# they were trained against, and bench.py refuses cross-version comparison.
OBJECTIVE_VERSION = 1

# The closed (component name -> weight) schema.  Order is the reporting
# order in the scorecard ``policy.components`` block.
OBJECTIVE_COMPONENTS = (
    ("slo", 1.0),
    ("packing", 0.5),
    ("locality", 0.5),
    ("churn", -0.25),
)

# The closed schema of the scorecard ``policy`` block (drift-gated against
# the README "Learned policy & tuning" catalogue by the LERN analyze rule).
POLICY_FIELDS = (
    "enabled",
    "required",
    "version",
    "objective",
    "components",
    "floor",
    "ok",
)

# p99 time-to-bind horizon: a run whose p99 equals this many virtual
# seconds keeps half its bind-completeness credit.
P99_SCALE_S = 30.0


def _clamp01(x: float) -> float:
    # shape: (x: float) -> float
    return 0.0 if x < 0.0 else (1.0 if x > 1.0 else x)


def policy_block(
    *,
    slo: dict,
    pod_counts: dict,
    locality: dict,
    rebalance: dict,
    required: bool,
    floor: float,
) -> dict:
    """The scorecard ``policy`` verdict: per-component breakdown plus the
    weighted scalar.  ``required``/``floor`` come from the scenario —
    policy-required scenarios gate the pass on ``objective >= floor``, so
    a tuned profile that wins the objective by breaking a workload fails
    the run instead of shipping."""
    # shape: (slo: obj, pod_counts: obj, locality: obj, rebalance: obj, required: bool, floor: float) -> obj
    demand = int(pod_counts.get("arrived", 0)) + int(pod_counts.get("churn_recreated", 0))
    bound = int(pod_counts.get("bound_total", 0))
    bound_frac = _clamp01(bound / demand) if demand > 0 else 1.0
    latency_factor = 1.0 / (1.0 + float(slo.get("p99_time_to_bind_s", 0.0)) / P99_SCALE_S)

    scored = int(locality.get("gangs_scored", 0))
    if not locality.get("enabled") or scored == 0:
        local_frac = 1.0  # no locality surface to judge
    else:
        local_frac = _clamp01(1.0 - int(locality.get("cross_rack_gangs", 0)) / scored)

    disruptions = (
        int(slo.get("preemption_churn", 0))
        + int(pod_counts.get("migrated", 0))
    )
    churn_frac = _clamp01(disruptions / bound) if bound > 0 else (1.0 if disruptions else 0.0)

    components = {
        "slo": round(bound_frac * latency_factor, 6),
        "packing": round(_clamp01(float(rebalance.get("packing_efficiency", 0.0))), 6),
        "locality": round(local_frac, 6),
        "churn": round(churn_frac, 6),
    }
    objective = round(sum(w * components[name] for name, w in OBJECTIVE_COMPONENTS), 6)
    out = {
        "enabled": True,
        "required": bool(required),
        "version": OBJECTIVE_VERSION,
        "objective": objective,
        "components": components,
        "floor": round(float(floor), 6),
        "ok": bool(floor <= 0 or objective >= floor),
    }
    assert tuple(out) == POLICY_FIELDS, "policy block schema drifted from POLICY_FIELDS"
    return out


def objective_from_card(card: dict) -> float:
    """The scalar the optimizer climbs, read back off a finished scorecard."""
    # shape: (card: obj) -> float
    return float(card["policy"]["objective"])
