"""Scheduling-backend interface — the trait boundary of the north star.

The reference gates everything behind ``check_node_validity``
(``src/predicates.rs:63``); here the boundary is one cycle-level call: packed
tensors in, per-pod node assignments out.  Two implementations with identical
semantics: ``native`` (NumPy, the recovery/parity path) and ``tpu``
(JAX/XLA).  Selected by the ``--backend={native,tpu}`` flag (runtime/cli).
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field

import numpy as np

from ..models.profiles import DEFAULT_PROFILE, SchedulingProfile
from ..ops.pack import PackedCluster

__all__ = ["CycleResult", "SchedulingBackend"]


@dataclass
class CycleResult:
    """Outcome of one scheduling cycle."""

    assigned: np.ndarray  # [num_pods] int32 — node index into packed.node_names, or −1
    bindings: list[tuple[str, str]]  # (pod full name, node name) for assigned pods
    unschedulable: list[str]  # pod full names with no feasible node this cycle
    rounds: int
    stats: dict = field(default_factory=dict)


class SchedulingBackend(abc.ABC):
    name: str = "abstract"

    @abc.abstractmethod
    def assign(self, packed: PackedCluster, profile: SchedulingProfile) -> tuple:
        """Run the cycle over padded tensors; return (assigned [padded_pods],
        rounds) or (assigned, rounds, extras) where ``extras`` carries
        per-pod diagnostics (acceptance round, priority rank) into
        ``CycleResult.stats``."""

    # Whether a routed cycle may solve shards from concurrent threads.
    # Mesh backends whose assign issues cross-host collectives must say
    # False: a multi-controller runtime requires identical collective launch
    # order on every process, which a thread pool cannot guarantee.
    supports_concurrent_shards: bool = True

    # Whether assign() consumes PackedCluster.topology (the rank-aware gang
    # locality term, topology/locality.py).  The controller only attaches
    # the tensors — and only then arms the cross-rack quality backstop —
    # for backends that say True: a topology-BLIND backend judged by the
    # locality gate would have its gangs deferred every cycle (starvation).
    supports_topology: bool = False

    def shard_for(self, index: int) -> "SchedulingBackend":
        """Backend instance for the ``index``-th parallel shard of a routed
        cycle (parallel/routing.py).  Default: this backend (serialized on
        one device); device-owning backends override to spread shards over
        the device set — the expert-parallel dispatch."""
        return self

    def schedule(self, packed: PackedCluster, profile: SchedulingProfile = DEFAULT_PROFILE) -> CycleResult:
        result = self.assign(packed, profile)
        assigned_padded, rounds = result[0], result[1]
        extras = result[2] if len(result) > 2 else {}
        assigned = np.asarray(assigned_padded)[: packed.num_pods]
        # Vectorized binding construction: at 100k pods a Python loop with
        # per-element int() casts costs ~0.2 s — a third of the whole cycle.
        pod_arr = np.asarray(packed.pod_names, dtype=object)
        node_arr = np.asarray(packed.node_names, dtype=object)
        placed = np.flatnonzero(assigned >= 0)
        bindings = list(zip(pod_arr[placed].tolist(), node_arr[assigned[placed]].tolist()))
        unschedulable = pod_arr[np.flatnonzero(assigned < 0)].tolist()
        stats = {"backend": self.name}
        for k, v in extras.items():
            stats[k] = np.asarray(v)[: packed.num_pods]
        return CycleResult(
            assigned=assigned,
            bindings=bindings,
            unschedulable=unschedulable,
            rounds=int(rounds),
            stats=stats,
        )
