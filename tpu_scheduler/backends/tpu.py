"""TPU (JAX/XLA) batched backend — the ``--backend=tpu`` path.

Ships the packed tensors to device once per cycle and runs the whole
filter+score+commit auction under one jit (ops/assign.py).  Works on any JAX
platform (tests run it on CPU; the benchmark on a real v5e chip); the class
is named for its design target.
"""

from __future__ import annotations

import numpy as np

from ..errors import BackendUnavailable
from ..models.profiles import SchedulingProfile
from ..ops.assign import assign_cycle, split_device_arrays
from ..ops.pack import PackedCluster
from .base import SchedulingBackend

__all__ = ["TpuBackend"]


class TpuBackend(SchedulingBackend):
    name = "tpu"

    def __init__(self, device=None, use_pallas: bool | None = None):
        try:
            import jax
        except Exception as e:  # pragma: no cover - jax is baked into the image
            raise BackendUnavailable(f"jax unavailable: {e}") from e
        self._jax = jax
        if device is None:
            devices = jax.devices()
            if not devices:
                raise BackendUnavailable("no jax devices")
            device = devices[0]
        self.device = device
        # The fused Pallas choose kernel (ops/pallas_choose.py) is
        # Mosaic/TPU-only; every other platform runs the jnp path (tests
        # exercise the kernel itself in interpreter mode).
        self.use_pallas = (device.platform == "tpu") if use_pallas is None else use_pallas

    def assign(self, packed: PackedCluster, profile: SchedulingProfile) -> tuple[np.ndarray, int]:
        jax = self._jax
        try:
            a = packed.device_arrays()
            put = {k: jax.device_put(v, self.device) for k, v in a.items()}
            weights = jax.device_put(profile.weights(), self.device)
            nodes, pods = split_device_arrays(put)
            assigned, rounds, _avail = assign_cycle(
                nodes,
                pods,
                weights,
                max_rounds=profile.max_rounds,
                block=profile.pod_block,
                use_pallas=self.use_pallas,
            )
            return np.asarray(jax.device_get(assigned)), int(rounds)
        except jax.errors.JaxRuntimeError as e:
            # Device-runtime failure (OOM, device lost, …) — the recovery
            # scenario the native fallback exists for (SURVEY.md §5).  Python
            # programming errors deliberately propagate instead.
            raise BackendUnavailable(f"tpu backend runtime failure: {e}") from e


def make_backend(name: str, **kw) -> SchedulingBackend:
    """Factory for the --backend flag."""
    from .native import NativeBackend

    if name == "native":
        return NativeBackend()
    if name == "tpu":
        return TpuBackend(**kw)
    raise ValueError(f"unknown backend {name!r} (expected 'native' or 'tpu')")
