"""TPU (JAX/XLA) batched backend — the ``--backend=tpu`` path.

Ships the packed tensors to device once per cycle and runs the whole
filter+score+commit auction under one jit (ops/assign.py).  Works on any JAX
platform (tests run it on CPU; the benchmark on a real v5e chip); the class
is named for its design target.
"""

from __future__ import annotations

import threading
import weakref

import numpy as np

from ..errors import BackendUnavailable
from ..models.profiles import SchedulingProfile
from ..ops.assign import assign_cycle, assign_cycle_epochs, split_device_arrays
from ..ops.pack import PackedCluster
from ..utils.profiler import install_jax_profile_hooks, record_transfer
from .base import SchedulingBackend

__all__ = ["TpuBackend"]


# shape: (assigned: [P] i32, acc_round: [P] i32, rank_of: [P] i32,
#   rounds: scalar i32) -> [4, P] i32
def _stack_results(assigned, acc_round, rank_of, rounds):
    """[4, P] i32: rows assigned / acc_round / rank_of / broadcast rounds —
    the single-fetch result layout (see _assign_once).  Module-level jit so
    the compiled stack is cached across cycles."""
    global _STACK_FN
    if _STACK_FN is None:
        import jax
        import jax.numpy as jnp

        @jax.jit
        def stack(a, b, c, r):
            return jnp.stack([a, b, c, jnp.full_like(a, r)])

        _STACK_FN = stack
    return _STACK_FN(assigned, acc_round, rank_of, rounds)


_STACK_FN = None


class TpuBackend(SchedulingBackend):
    name = "tpu"
    supports_topology = True

    def __init__(self, device=None, use_pallas: bool | None = None):
        try:
            import jax
        except Exception as e:  # pragma: no cover - jax is baked into the image
            raise BackendUnavailable(f"jax unavailable: {e}") from e
        self._jax = jax
        # Compile-vs-execute attribution: XLA compiles observed via
        # jax.monitoring land in the active cycle trace as ``compile`` spans
        # (best-effort, idempotent, never raises — utils/profiler.py).
        install_jax_profile_hooks()
        if device is None:
            devices = jax.devices()
            if not devices:
                raise BackendUnavailable("no jax devices")
            device = devices[0]
        self.device = device
        # The fused Pallas choose kernel (ops/pallas_choose.py) is
        # Mosaic/TPU-only; every other platform runs the jnp path (tests
        # exercise the kernel itself in interpreter mode).
        self.use_pallas = (device.platform == "tpu") if use_pallas is None else use_pallas
        # Until the fused kernel survives one real Mosaic compile+run on this
        # device, a pallas failure downgrades to the jnp path instead of
        # killing the cycle: Mosaic lowering errors are *not*
        # JaxRuntimeError subclasses, so they would otherwise bypass the
        # BackendUnavailable→native fallback on the flagship platform.
        # Proving, strikes and disablement are per KERNEL VARIANT
        # (unconstrained / constrained): the two cycles compile different
        # Pallas programs, so a proven flagship kernel says nothing about the
        # constrained one's Mosaic fate — and a constrained-variant failure
        # must not take down a proven flagship kernel.
        self._pallas_proven = False  # guarded-by: _guard_lock — any variant proven (bench honesty flag)
        self._proven_variants: set[bool] = set()  # guarded-by: _guard_lock — {False: plain, True: constrained}
        self._disabled_variants: set[bool] = set()  # guarded-by: _guard_lock
        self._pallas_strikes: dict[bool, int] = {False: 0, True: 0}  # guarded-by: _guard_lock
        # Serializes the first-use proving attempt: concurrent routed-shard
        # threads must not double-count strikes on one transient fault (the
        # guard tolerates exactly one) or race the unproven kernel.
        self._guard_lock = threading.Lock()
        # Written only by shard_for (main-thread-only by routing.py's
        # contract); read from worker threads by _drop_dev_cache — the two
        # unlocked touches are pinned in scripts/analyze/baseline.json.
        self._shards: dict = {}  # guarded-by: _put_lock — device id -> shard backend (see shard_for)
        # Host→device upload cache: the tunnel moves ~100 MB/s, so re-putting
        # an unchanged 21 MB pack costs ~0.25 s/cycle.  Keyed by host-array
        # identity (weakref-validated); safe because pack.py never mutates an
        # array it has handed out (repack_* replace, _grow_columns copies).
        # Locked: routed cycles call _assign_once from a thread pool on this
        # one instance.  Eviction is immediate via weakref.finalize — a dead
        # host array must release its device buffer within the cycle, not
        # after a size threshold (at flagship scale each stale pod pack pins
        # tens of MB of HBM).
        # Entry: (weakref, device_buf, finalizer).  The finalizer handle
        # lives IN the entry (not a separate id-keyed set): ids recycle the
        # moment an array dies, so a set would let a stale finalizer both
        # block registration for the id's new owner and — firing later —
        # leave the new owner's buffer pinned until _drop_dev_cache
        # (round-3 advisor finding).  Eviction compares the stored weakref
        # object itself, which is unambiguous across id reuse.
        #
        # Size-capped, oldest-insertion-first: on platforms where
        # device_put ALIASES the host buffer (CPU is zero-copy), the cached
        # device array keeps its host array alive, so weakref eviction
        # alone never fires and a long daemon's cache grows with every
        # repack (found by a 800-cycle churn soak).  A flagship cycle
        # touches a few dozen arrays; evicting a live entry is always safe
        # (worst case: one re-upload).
        self._dev_cache: dict[int, tuple[weakref.ref, object, object]] = {}  # guarded-by: _put_lock
        self._dev_cache_cap = 512
        self._put_lock = threading.Lock()
        # Fleet mesh-per-replica bindings (parallel/mesh.MeshBinding), keyed
        # by shard id.  Main-thread state: bound/released from the
        # controller's shard-refresh path only.
        self._mesh_bindings: dict[int, object] = {}

    # -- fleet mesh bindings (tpu_scheduler/fleet) --------------------------

    # shape: (self: obj, shard: int, num_shards: int) -> obj
    def bind_shard_mesh(self, shard: int, num_shards: int):
        """Bind one owned shard to this replica's contiguous device-slice
        mesh (parallel/mesh.mesh_binding).  Idempotent per (shard, K); a
        resize (new K) rebuilds the binding — the old slice geometry is
        meaningless under the new shard map."""
        ent = self._mesh_bindings.get(int(shard))
        if ent is not None and ent.num_shards == int(num_shards):
            return ent
        from ..parallel.mesh import mesh_binding

        ent = mesh_binding(int(shard), int(num_shards), devices=[self.device] if self.device else None)
        self._mesh_bindings[int(shard)] = ent
        return ent

    # shape: (self: obj, shard: int) -> bool
    def release_shard_mesh(self, shard: int) -> bool:
        """Forget a lost shard's binding (the new owner builds its own)."""
        return self._mesh_bindings.pop(int(shard), None) is not None

    # shape: (self: obj) -> obj
    def mesh_bindings_info(self) -> dict:
        """/debug/shards payload: per-shard device ids + mesh shape + the
        node-axis partition spec the slice's tensors are laid out over
        (parallel/mesh.node_sharding)."""
        from ..parallel.mesh import node_sharding

        return {
            str(s): {
                "devices": list(b.device_ids),
                "mesh_shape": [int(b.mesh.shape["dp"]), int(b.mesh.shape["tp"])],
                "dedicated": bool(b.dedicated),
                "node_sharding": str(node_sharding(b)),
            }
            for s, b in sorted(self._mesh_bindings.items())
        }

    def _drop_dev_cache(self) -> None:
        """Forget every cached upload — after a device-runtime failure the
        buffers may belong to a dead device session (tunnel drop, device
        reset); recovery must re-upload, not reuse corpses.  A tunnel drop
        kills the whole session, so sibling per-device shard backends
        (shard_for) drop theirs too."""
        with self._put_lock:
            for ent in self._dev_cache.values():
                ent[2].detach()  # a re-upload registers a fresh finalizer
            self._dev_cache.clear()
        for sh in list(self._shards.values()):
            if sh is not self:
                sh._drop_dev_cache()

    def _evict(self, key: int, wr: weakref.ref) -> None:
        with self._put_lock:
            ent = self._dev_cache.get(key)
            # Drop only OUR entry: by the time a finalizer runs, the id may
            # already belong to a NEW cached array (CPython reuses ids) —
            # the stored weakref's identity disambiguates.
            if ent is not None and ent[0] is wr:
                del self._dev_cache[key]

    def _put(self, arr):
        """device_put with identity-keyed reuse of prior uploads."""
        key = id(arr)
        with self._put_lock:
            ent = self._dev_cache.get(key)
            if ent is not None and ent[0]() is arr:
                # Refresh recency (insertion order is the eviction order):
                # hot node tensors must outlive churned pod tensors.
                del self._dev_cache[key]
                self._dev_cache[key] = ent
                return ent[1]
        # Cache MISSES are real host->device traffic: count the bytes so the
        # profiler's compile/execute split (utils/profiler.py) can name
        # transfer-bound cycles (scheduler_device_transfer_bytes_total).
        nbytes = getattr(arr, "nbytes", None)
        if nbytes:
            record_transfer(int(nbytes))
        buf = self._jax.device_put(arr, self.device)
        try:
            wr = weakref.ref(arr)
        except TypeError:  # non-weakref-able input (e.g. a jax array): skip caching
            return buf
        fin = weakref.finalize(arr, self._evict, key, wr)
        fin.atexit = False  # interpreter teardown needs no cache hygiene
        with self._put_lock:
            old = self._dev_cache.pop(key, None)  # pop: the fresh entry must land at the MRU end
            if old is not None and old[0] is not wr:
                # The id's previous owner died (or this is a re-upload after
                # a cache drop): detach its finalizer so a late fire cannot
                # touch the new entry.
                old[2].detach()
            self._dev_cache[key] = (wr, buf, fin)
            while len(self._dev_cache) > self._dev_cache_cap:
                oldest = next(iter(self._dev_cache))
                if oldest == key:  # never evict the entry just inserted
                    break
                self._dev_cache.pop(oldest)[2].detach()
        return buf

    # shape: (packed: obj, profile: obj, use_pallas: bool) -> ([P] i32, scalar i32, dict)
    # hotpath: tpu-solve
    def _assign_once(self, packed: PackedCluster, profile: SchedulingProfile, use_pallas: bool):
        jax = self._jax
        a = packed.device_arrays()
        put = {k: self._put(v) for k, v in a.items()}
        weights = jax.device_put(profile.weights(), self.device)
        nodes, pods = split_device_arrays(put)
        cmeta = cstate = None
        cons = packed.constraints
        if cons is not None:
            pods.update({k: self._put(v) for k, v in cons.pod_arrays().items()})
            cmeta = {k: self._put(v) for k, v in cons.meta_arrays().items()}
            # Constraint STATE is mutated by the cycle only on device (the
            # loop carry); the host arrays are per-cycle fresh — still cheap
            # (domain-granular, "a rounding error" next to the pod tensors).
            cstate = {k: jax.device_put(v, self.device) for k, v in cons.state_arrays().items()}
        tmeta = tstate = None
        topo = packed.topology
        if topo is not None:
            # Topology tensors (topology/locality.py): the gang-id column
            # rides the pod dict (permuted/compacted/sliced with the rest);
            # meta is node/domain-side (cacheable uploads); the gang-count
            # STATE is loop-carried on device, per-cycle fresh like cstate.
            pods.update({k: self._put(v) for k, v in topo.pod_arrays().items()})
            tmeta = {k: self._put(v) for k, v in topo.meta_arrays().items()}
            tstate = {k: jax.device_put(v, self.device) for k, v in topo.state_arrays().items()}
        # Driver choice (profiles.py `driver`): monolithic keeps the whole
        # auction in one jit program — one host sync per cycle, no jit-
        # boundary relayouts — and since the in-jit static size chain
        # (assign_cycle) it also shrinks the per-round cost with the active
        # count, so it beats the host-driven epoch driver on BOTH cycle
        # shapes (measurements in profiles.py).  Both drivers are
        # bit-identical in results (tests/test_assign.py).
        drive = assign_cycle_epochs if profile.driver == "epochs" else assign_cycle
        assigned, rounds, _avail, acc_round, rank_of = drive(
            nodes,
            pods,
            weights,
            max_rounds=profile.max_rounds,
            block=profile.pod_block,
            use_pallas=use_pallas,
            cmeta=cmeta,
            cstate=cstate,
            soft_spread=cons is not None and cons.n_spread_soft > 0,
            soft_pa=cons is not None and cons.n_ppa_terms > 0,
            hard_pa=cons is not None and cons.n_pa_terms > 0,
            tmeta=tmeta,
            tstate=tstate,
        )
        # ONE device→host fetch for the whole result.  Each fresh fetch
        # costs ~80 ms of tunnel latency regardless of size (measured on the
        # real chip), so assigned/acc_round/rank_of/rounds ride home stacked
        # in a single [4, P] transfer instead of four round-trips.
        combined = np.asarray(jax.device_get(_stack_results(assigned, acc_round, rank_of, rounds)))  # host-sync: the designed single [4, P] result fetch
        extras = {"acc_round": combined[1], "rank": combined[2]}
        return combined[0], int(combined[3, 0]), extras

    def _variant_enabled(self, variant: bool) -> bool:  # holds-lock: _guard_lock
        return self.use_pallas and variant not in self._disabled_variants

    # shape: (packed: obj, profile: obj) -> ([P] i32, scalar i32, dict)
    def assign(self, packed: PackedCluster, profile: SchedulingProfile) -> tuple[np.ndarray, int]:
        jax = self._jax
        # Constraint cycles ride the kernel too: the per-round blocked/
        # penalty masks enter as extra node-side operands (ops/pallas_choose
        # ``cons_pod``/``cons_node``); accept/commit stay jnp.
        variant = packed.constraints is not None
        # Eligibility flags are read under the guard lock, atomically with
        # the proving/strike state they pair with — a concurrent routed
        # shard disabling the variant must not be seen half-applied (the old
        # unlocked reads were a benign-looking race the THRD pass flags).
        with self._guard_lock:
            if self._variant_enabled(variant) and variant not in self._proven_variants:
                return self._assign_proving(packed, profile, variant)
            use_pallas = self._variant_enabled(variant)
        try:
            return self._assign_once(packed, profile, use_pallas=use_pallas)
        except jax.errors.JaxRuntimeError as e:
            # Device-runtime failure (OOM, device lost, …) — the recovery
            # scenario the native fallback exists for (SURVEY.md §5).  Python
            # programming errors deliberately propagate instead.
            self._drop_dev_cache()
            raise BackendUnavailable(f"tpu backend runtime failure: {e}") from e

    def _assign_proving(self, packed: PackedCluster, profile: SchedulingProfile, variant: bool):  # holds-lock: _guard_lock
        """First-use pallas attempt for one kernel ``variant`` under the
        guard lock (a second thread re-checks the flags it may have just
        changed).  Failures strike/disable only THIS variant: a constrained-
        kernel rejection must not take down a proven flagship kernel."""
        jax = self._jax
        if self._variant_enabled(variant) and variant not in self._proven_variants:
            try:
                result = self._assign_once(packed, profile, use_pallas=True)
                self._proven_variants.add(variant)
                self._pallas_proven = True
                return result
            except Exception as e:  # noqa: BLE001 — first-compile guard, see __init__
                import logging

                log = logging.getLogger("tpu_scheduler.backend")
                if isinstance(e, jax.errors.JaxRuntimeError):
                    # Could be either a Mosaic compile rejection or a
                    # transient device fault — indistinguishable without
                    # parsing messages.  Strike-based: fall back to native
                    # for this cycle (BackendUnavailable), keep the variant
                    # armed; a deterministic compile failure strikes again
                    # next cycle and is then disabled, while a transient
                    # device fault clears and the variant proves itself.
                    self._pallas_strikes[variant] += 1
                    if self._pallas_strikes[variant] >= 2:
                        log.warning(
                            "pallas %s kernel failed %d first-use attempts; disabling that variant",
                            "constrained" if variant else "plain",
                            self._pallas_strikes[variant],
                        )
                        self._disabled_variants.add(variant)
                    self._drop_dev_cache()
                    raise BackendUnavailable(f"tpu backend runtime failure: {e}") from e
                # Non-runtime exceptions (tracing/lowering errors) are
                # deterministic kernel bugs — disable this variant
                # immediately and serve the cycle via the jnp path on the
                # same device.
                log.warning(
                    "pallas %s choose kernel failed on first use (%s: %s); disabling that variant, retrying jnp path",
                    "constrained" if variant else "plain",
                    type(e).__name__,
                    e,
                )
                self._disabled_variants.add(variant)
        try:
            return self._assign_once(packed, profile, use_pallas=self._variant_enabled(variant))
        except jax.errors.JaxRuntimeError as e:
            # Device-runtime failure (OOM, device lost, …) — the recovery
            # scenario the native fallback exists for (SURVEY.md §5).  Python
            # programming errors deliberately propagate instead.
            self._drop_dev_cache()
            raise BackendUnavailable(f"tpu backend runtime failure: {e}") from e

    def shard_for(self, index: int) -> "TpuBackend":
        """Per-pool shard backend (parallel/routing.py): round-robin the pool
        shards over the visible device set so their solves overlap — the EP
        dispatch.  On one device every shard is this backend."""
        devices = self._jax.devices()
        if len(devices) <= 1:
            return self
        dev = devices[index % len(devices)]
        if dev == self.device:
            return self
        if dev.id not in self._shards:
            self._shards[dev.id] = TpuBackend(device=dev, use_pallas=self.use_pallas)
        return self._shards[dev.id]


# shape: (name: str) -> obj
def make_backend(name: str, **kw) -> SchedulingBackend:
    """Factory for the --backend flag."""
    from .native import NativeBackend

    if name == "native":
        return NativeBackend()
    if name == "tpu":
        return TpuBackend(**kw)
    raise ValueError(f"unknown backend {name!r} (expected 'native' or 'tpu')")
