"""Native (NumPy) batched backend — the ``--backend=native`` path.

Same auction-round algorithm as ops/assign.py, expressed in NumPy.  It shares
the mask/score expression trees (ops/masks.py, ops/score.py, xp-generic) so
float behaviour is identical; the segmented prefix-sum is exact int64 clamped
to INT32_MAX, which equals the TPU path's saturating scan (see
ops/assign.py overflow note).  Serves three roles from SURVEY.md:
  • parity oracle for the TPU backend (binding-for-binding equality),
  • recovery path when the TPU backend is unavailable (§5 failure handling),
  • the "native" side of the north star's --backend flag.
"""

from __future__ import annotations

import contextlib

import numpy as np

from ..models.profiles import SchedulingProfile
from ..ops.masks import feasibility_block
from ..ops.pack import INT32_MAX, STALL_ROUNDS, PackedCluster
from ..ops.score import score_block
from ..topology.locality import gang_state_update, gang_topology_term
from ..utils.tracing import span
from .base import SchedulingBackend

__all__ = ["NativeBackend"]

# Stateless reusable no-op context: the mask/score/choose sub-spans only
# open on constrained/topology rounds (where the split carries signal);
# plain rounds pay one span, not four — the <2% profiler-overhead budget.
_NULL = contextlib.nullcontext()


class NativeBackend(SchedulingBackend):
    name = "native"
    supports_topology = True

    # shape: (packed: obj, profile: obj) -> ([P] i32, scalar i32, dict)
    def assign(self, packed: PackedCluster, profile: SchedulingProfile) -> tuple[np.ndarray, int]:
        node_alloc, node_avail = packed.node_alloc, packed.node_avail
        node_labels, node_valid = packed.node_labels, packed.node_valid
        node_taints = packed.node_taints
        node_aff = packed.node_aff
        weights = profile.weights()
        p = packed.padded_pods
        n = packed.padded_nodes
        block = profile.pod_block

        perm = np.argsort(-packed.pod_prio, kind="stable")
        req = packed.pod_req[perm]
        sel = packed.pod_sel[perm]
        selc = packed.pod_sel_count[perm]
        ntol = packed.pod_ntol[perm]
        aff = packed.pod_aff[perm]
        has_aff = packed.pod_has_aff[perm]
        valid = packed.pod_valid[perm]
        pref_w = packed.pod_pref_w[perm]
        ntol_soft = packed.pod_ntol_soft[perm]
        node_pref, node_taints_soft = packed.node_pref, packed.node_taints_soft

        cons = packed.constraints
        cmeta = cstate = cpods = None
        soft_spread = cons is not None and cons.n_spread_soft > 0
        soft_pa = cons is not None and cons.n_ppa_terms > 0
        hard_pa = cons is not None and cons.n_pa_terms > 0
        if cons is not None:
            from ..ops.constraints import (
                augment_round_state,
                blocked_block,
                constraint_commit,
                constraint_filter,
                round_blocked_masks,
            )

            cmeta = cons.meta_arrays()
            # Round-carried conflict state (spread water line, per-cell
            # counts, PA bootstrap flags) — derived once, then updated
            # incrementally by constraint_commit (ops/assign.py twin).
            cstate = augment_round_state(np, {k: v.copy() for k, v in cons.state_arrays().items()}, cmeta)
            cpods = {k: v[perm] for k, v in cons.pod_arrays().items()}
        topo = packed.topology
        tmeta = gang_nodes = pod_gang = None
        if topo is not None:
            # Rank-aware gang co-placement (topology/locality.py) — the
            # exact NumPy twin of the jnp round-body path in ops/assign.py.
            tmeta = topo.meta_arrays()
            gang_nodes = topo.state_arrays()["gang_nodes"].copy()
            pod_gang = topo.pod_gang_id[perm]

        avail = node_avail.copy()
        assigned = np.full((p,), -1, dtype=np.int32)
        acc_round = np.full((p,), -1, dtype=np.int32)
        active = valid.copy()
        ranks = np.arange(p, dtype=np.uint32)  # already in priority-rank order
        rounds = 0
        stall = 0  # consecutive zero-acceptance rounds (ops/assign.py STALL_ROUNDS)

        while rounds < profile.max_rounds and active.any() and stall < STALL_ROUNDS:
            # Per-round attribution (utils/profiler.py): each round nests a
            # mask/score/choose split under ``round[NN]`` so a constrained
            # cycle's cost names the round that ate it.  Spans are inert
            # (two clock reads) without an active trace — bench and parity
            # tests calling assign() directly pay nothing.
            detail = cons is not None or topo is not None
            with span(f"round[{rounds:02d}]"):
                with span("mask") if detail else _NULL:
                    round_masks = (
                        round_blocked_masks(np, cstate, cmeta, soft_spread=soft_spread, soft_pa=soft_pa, hard_pa=hard_pa)
                        if cons is not None
                        else None
                    )
                    topo_t = None
                    if topo is not None:
                        topo_t = gang_topology_term(np, gang_nodes, tmeta, avail, pod_gang, req, active, weights[6])
                choice = np.zeros((p,), dtype=np.int32)
                has = np.zeros((p,), dtype=bool)
                node_idx = np.arange(n, dtype=np.uint32)
                with span("score") if detail else _NULL:
                    for lo in range(0, p, block):
                        hi = min(lo + block, p)
                        m = feasibility_block(
                            np, req[lo:hi], sel[lo:hi], selc[lo:hi], active[lo:hi], avail, node_labels, node_valid,
                            ntol[lo:hi], node_taints, aff[lo:hi], has_aff[lo:hi], node_aff,
                        )
                        if round_masks is not None:
                            blk = {k: v[lo:hi] for k, v in cpods.items()}
                            m = m & ~blocked_block(np, blk, round_masks)
                        pod_idx = np.arange(lo, hi, dtype=np.uint32)
                        sc = score_block(
                            np, req[lo:hi], node_alloc, avail, weights, pod_idx, node_idx,
                            pod_pref_w=pref_w[lo:hi], node_pref=node_pref,
                            pod_ntol_soft=ntol_soft[lo:hi], node_taints_soft=node_taints_soft,
                            pod_sps_declares=cpods["pod_sps_declares"][lo:hi] if soft_spread else None,
                            sp_penalty_node=round_masks["sp_penalty_node"] if soft_spread else None,
                            pod_sp_declares=cpods["pod_sp_declares"][lo:hi] if round_masks is not None else None,
                            sp_level_node=round_masks["sp_level_node"] if round_masks is not None else None,
                            pod_ppa_w=cpods["pod_ppa_w"][lo:hi] if soft_pa else None,
                            ppa_cnt_node=round_masks["ppa_cnt_node"] if soft_pa else None,
                            salt=rounds,
                            pod_gang_id=pod_gang[lo:hi] if topo is not None else None,
                            topo_gang_node=topo_t,
                        )
                        sc = np.where(m, sc, -np.inf)
                        choice[lo:hi] = sc.argmax(axis=1).astype(np.int32)
                        has[lo:hi] = m.any(axis=1)

                with span("choose") if detail else _NULL:
                    cand = active & has
                    ch = np.where(cand, choice, n).astype(np.int32)
                    claim = np.where(cand[:, None], req, 0)

                    order = np.argsort(ch, kind="stable")
                    ch_s = ch[order]
                    claim_s = claim[order].astype(np.int64)
                    cum = claim_s.cumsum(axis=0)
                    is_start = np.concatenate([[True], ch_s[1:] != ch_s[:-1]])
                    start_idx = np.maximum.accumulate(np.where(is_start, np.arange(p), 0))
                    base = (cum - claim_s)[start_idx]
                    within = np.minimum(cum - base, INT32_MAX)

                    avail_ext = np.concatenate([avail, np.zeros((1, avail.shape[1]), avail.dtype)], axis=0)
                    fits_prefix = (within <= avail_ext[ch_s]).all(-1)
                    acc_s = fits_prefix & (ch_s < n)
                    accepted = np.zeros((p,), dtype=bool)
                    accepted[order] = acc_s

                    if cons is not None:
                        # Named separately under choose: measured (PERF.md
                        # "Reading an attribution profile") the within-round
                        # conflict filter dominated constrained rounds at
                        # ~99% of round wall before the round-7 active-set
                        # fusion; ``spans=span`` opens the filter/aa|pa|
                        # spread sub-spans so the attribution names WHICH
                        # constraint family dominates, not just the filter.
                        with span("filter"):
                            accepted = constraint_filter(
                                np, accepted, choice, ranks, cpods, cstate, cmeta, hard_pa=hard_pa, spans=span
                            )
                        stall = 0 if accepted.any() else stall + 1
                        with span("commit"):
                            cstate = constraint_commit(
                                np, accepted, choice, cpods, cstate, cmeta, soft_spread=soft_spread, soft_pa=soft_pa, hard_pa=hard_pa
                            )

                    if topo is not None:
                        gang_nodes = gang_state_update(np, gang_nodes, accepted, ch, pod_gang)
                    assigned = np.where(accepted, choice, assigned)
                    acc_round = np.where(accepted, rounds, acc_round)
                    dec = np.zeros((n + 1, avail.shape[1]), dtype=np.int64)
                    np.add.at(dec, ch, np.where(accepted[:, None], req, 0).astype(np.int64))
                    avail = (avail.astype(np.int64) - dec[:n]).astype(np.int32)
                    was_active = active
                    active = cand & ~accepted
                    if cons is not None and hard_pa:
                        # Positive-affinity declarers blocked everywhere stay
                        # active while ANY pending PA term gained a match this
                        # round (mirrors ops/assign.py exactly — see its
                        # rationale).
                        new_match = (cpods["pod_pa_matched"] * accepted[:, None].astype(np.float32)).sum(axis=0) > 0
                        pa_hope = (cpods["pod_pa_declares"].sum(axis=1) > 0) & new_match.any()
                        active = active | (was_active & ~has & pa_hope)
            rounds += 1

        out = np.full((p,), -1, dtype=np.int32)
        out[perm] = assigned
        out_acc = np.full((p,), -1, dtype=np.int32)
        out_acc[perm] = acc_round
        rank_of = np.zeros((p,), dtype=np.int32)
        rank_of[perm] = np.arange(p, dtype=np.int32)
        return out, rounds, {"acc_round": out_acc, "rank": rank_of}
