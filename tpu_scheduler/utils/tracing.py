"""Structured tracing — the build-side answer to the reference's flat
``tracing_subscriber::fmt()`` INFO logging (``src/main.rs:129``; SURVEY.md §5
calls for per-cycle spans + optional device profiler traces).

``span("name")`` times a block, logs it, and records the duration AND the
wall-clock interval into the active ``Trace`` (if any) — the intervals feed
the flight recorder's Chrome trace export (utils/events.py).
``device_profile(dir)`` wraps ``jax.profiler`` for TPU traces of the scoring
step; it is a no-op if profiling can't start.  ``configure_logging`` grows a
``--log-format json`` path: one JSON object per line (ts, level, logger,
msg, cycle) so the daemon's logs are machine-parseable; ``set_log_cycle``
tags every line emitted during a cycle with its number.
"""

from __future__ import annotations

import contextlib
import json
import logging
import time
from collections import defaultdict

logger = logging.getLogger("tpu_scheduler")

__all__ = [
    "span",
    "Trace",
    "current_trace",
    "device_profile",
    "configure_logging",
    "JsonLogFormatter",
    "set_log_cycle",
]

_active: list["Trace"] = []

# The cycle number logs emitted "now" belong to — set by the controller at
# the top of each cycle so the JSON formatter can stamp every line without
# threading `extra=` through every logging call site.  A plain mutable cell:
# one scheduler loop per process owns the write side.
_log_cycle: list[int | None] = [None]


def set_log_cycle(cycle: int | None) -> None:
    _log_cycle[0] = cycle


class JsonLogFormatter(logging.Formatter):
    """One JSON object per line: ts (epoch seconds), level, logger, msg,
    and the current scheduling cycle when one is active (``set_log_cycle``).
    A record carrying its own ``cycle`` attribute (``extra={"cycle": n}``)
    wins over the ambient one."""

    def format(self, record: logging.LogRecord) -> str:
        obj: dict = {
            "ts": round(record.created, 6),
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        cycle = getattr(record, "cycle", None)
        if cycle is None:
            cycle = _log_cycle[0]
        if cycle is not None:
            obj["cycle"] = cycle
        if record.exc_info:
            obj["exc"] = self.formatException(record.exc_info)
        return json.dumps(obj)


def configure_logging(level: str = "INFO", fmt: str = "text") -> None:
    """Process-wide log init (the main.rs:129 equivalent), level configurable
    — the reference hard-codes both level and format.  ``fmt="json"`` emits
    one JSON object per line for log pipelines; ``"text"`` keeps the
    human-readable default."""
    lvl = getattr(logging, level.upper(), logging.INFO)
    if fmt == "json":
        handler = logging.StreamHandler()
        handler.setFormatter(JsonLogFormatter())
        logging.basicConfig(level=lvl, handlers=[handler], force=True)
    elif fmt == "text":
        logging.basicConfig(
            level=lvl,
            format="%(asctime)s %(levelname)s %(name)s %(message)s",
        )
    else:
        raise ValueError(f"unknown log format {fmt!r} (expected 'text' or 'json')")


class Trace:
    """Accumulates named span durations (seconds) for one scope (e.g. one
    scheduling cycle), plus the span INTERVALS in wall-clock time — the
    flight recorder's Chrome-trace source.  Intervals are derived from
    perf_counter deltas re-anchored to wall time at construction, so they
    are monotonic within the trace and meaningful across cycles."""

    def __init__(self):
        self.durations: dict[str, float] = defaultdict(float)
        self.counts: dict[str, int] = defaultdict(int)
        self.events: list[tuple[str, float, float]] = []  # (name, wall_start, wall_end)
        self._wall0 = time.time()
        self._perf0 = time.perf_counter()

    def _wall(self, perf_t: float) -> float:
        return self._wall0 + (perf_t - self._perf0)

    def record(self, name: str, seconds: float, perf_start: float | None = None) -> None:
        """Record a span.  ``perf_start`` (a perf_counter stamp) gives the
        exact interval; without it the interval is synthesized as ending now
        — the overlapped-bind drain knows only its duration, and an
        approximate box in the trace beats an invisible one."""
        self.durations[name] += seconds
        self.counts[name] += 1
        end = time.perf_counter() if perf_start is None else perf_start + seconds
        start = end - seconds
        self.events.append((name, self._wall(start), self._wall(end)))

    def __enter__(self) -> "Trace":
        _active.append(self)
        return self

    def __exit__(self, *exc) -> None:
        _active.remove(self)

    def summary(self) -> dict[str, float]:
        return dict(self.durations)


def current_trace() -> Trace | None:
    return _active[-1] if _active else None


@contextlib.contextmanager
def span(name: str):
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        tr = current_trace()
        if tr is not None:
            tr.record(name, dt, perf_start=t0)
        logger.debug("span %s took %.6fs", name, dt)


@contextlib.contextmanager
def device_profile(log_dir: str | None):
    """jax.profiler trace around a block; inert when log_dir is None."""
    if not log_dir:
        yield
        return
    try:
        import jax

        jax.profiler.start_trace(log_dir)
        started = True
    except Exception as e:  # pragma: no cover - profiler availability varies
        logger.warning("device profiling unavailable: %s", e)
        started = False
    try:
        yield
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception:  # pragma: no cover
                pass
