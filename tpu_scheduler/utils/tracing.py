"""Structured tracing — the build-side answer to the reference's flat
``tracing_subscriber::fmt()`` INFO logging (``src/main.rs:129``; SURVEY.md §5
calls for per-cycle spans + optional device profiler traces).

``span("name")`` times a block, logs it, and records the duration into the
active ``Trace`` (if any).  ``device_profile(dir)`` wraps ``jax.profiler`` for
TPU traces of the scoring step; it is a no-op if profiling can't start.
"""

from __future__ import annotations

import contextlib
import logging
import time
from collections import defaultdict

logger = logging.getLogger("tpu_scheduler")

__all__ = ["span", "Trace", "current_trace", "device_profile", "configure_logging"]

_active: list["Trace"] = []


def configure_logging(level: str = "INFO") -> None:
    """Process-wide log init (the main.rs:129 equivalent), level configurable
    — the reference hard-codes INFO."""
    logging.basicConfig(
        level=getattr(logging, level.upper(), logging.INFO),
        format="%(asctime)s %(levelname)s %(name)s %(message)s",
    )


class Trace:
    """Accumulates named span durations (seconds) for one scope (e.g. one
    scheduling cycle)."""

    def __init__(self):
        self.durations: dict[str, float] = defaultdict(float)
        self.counts: dict[str, int] = defaultdict(int)

    def record(self, name: str, seconds: float) -> None:
        self.durations[name] += seconds
        self.counts[name] += 1

    def __enter__(self) -> "Trace":
        _active.append(self)
        return self

    def __exit__(self, *exc) -> None:
        _active.remove(self)

    def summary(self) -> dict[str, float]:
        return dict(self.durations)


def current_trace() -> Trace | None:
    return _active[-1] if _active else None


@contextlib.contextmanager
def span(name: str):
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        tr = current_trace()
        if tr is not None:
            tr.record(name, dt)
        logger.debug("span %s took %.6fs", name, dt)


@contextlib.contextmanager
def device_profile(log_dir: str | None):
    """jax.profiler trace around a block; inert when log_dir is None."""
    if not log_dir:
        yield
        return
    try:
        import jax

        jax.profiler.start_trace(log_dir)
        started = True
    except Exception as e:  # pragma: no cover - profiler availability varies
        logger.warning("device profiling unavailable: %s", e)
        started = False
    try:
        yield
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception:  # pragma: no cover
                pass
