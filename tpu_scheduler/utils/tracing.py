"""Structured tracing — the build-side answer to the reference's flat
``tracing_subscriber::fmt()`` INFO logging (``src/main.rs:129``; SURVEY.md §5
calls for per-cycle spans + optional device profiler traces).

``span("name")`` times a block and records it into the active ``Trace`` (if
any) as a node of a HIERARCHICAL attribution tree: spans nest, and every
recorded duration is keyed by its full ``parent/child`` path (e.g.
``solve/round[03]/score``), so a cycle decomposes into a tree whose leaves
are the real cost centers (utils/profiler.py aggregates the trees; the
flight recorder's Chrome trace renders them as nested slices).  Depth-0
paths are the cycle PHASES the ``CycleMetrics`` breakdown is built from —
anything the phases don't cover is exactly ``other_seconds``.

The active-trace stack is THREAD-LOCAL: a worker thread (routed per-pool
solves, the pipelined bind worker) sees no active trace and its spans
degrade to two clock reads — never a concurrent mutation of the main
thread's tree (the THRD stance: no shared mutable state, no lock needed).

``device_profile(dir)`` wraps ``jax.profiler`` for TPU traces of the scoring
step; it is a no-op if profiling can't start.  ``configure_logging`` grows a
``--log-format json`` path: one JSON object per line (ts, level, logger,
msg, cycle) so the daemon's logs are machine-parseable; ``set_log_cycle``
tags every line emitted during a cycle with its number.
"""

from __future__ import annotations

import contextlib
import json
import logging
import threading
import time

logger = logging.getLogger("tpu_scheduler")

__all__ = [
    "span",
    "Trace",
    "current_trace",
    "base_name",
    "device_profile",
    "configure_logging",
    "JsonLogFormatter",
    "set_log_cycle",
]

# Per-THREAD active-trace stack.  Only the thread that entered a Trace sees
# it; spans on other threads no-op (two perf_counter reads) instead of racing
# the owner's tree.
_tls = threading.local()


def _stack() -> list:
    s = getattr(_tls, "stack", None)
    if s is None:
        s = _tls.stack = []
    return s


def base_name(segment: str) -> str:
    """A path segment's catalogue name: indexed spans (``round[03]``,
    ``epoch[1]``) aggregate under their base (``round``, ``epoch``)."""
    i = segment.find("[")
    return segment if i < 0 else segment[:i]


# The cycle number logs emitted "now" belong to — set by the controller at
# the top of each cycle so the JSON formatter can stamp every line without
# threading `extra=` through every logging call site.  A plain mutable cell:
# one scheduler loop per process owns the write side.
_log_cycle: list[int | None] = [None]


def set_log_cycle(cycle: int | None) -> None:
    _log_cycle[0] = cycle


class JsonLogFormatter(logging.Formatter):
    """One JSON object per line: ts (epoch seconds), level, logger, msg,
    and the current scheduling cycle when one is active (``set_log_cycle``).
    A record carrying its own ``cycle`` attribute (``extra={"cycle": n}``)
    wins over the ambient one."""

    def format(self, record: logging.LogRecord) -> str:
        obj: dict = {
            "ts": round(record.created, 6),
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        cycle = getattr(record, "cycle", None)
        if cycle is None:
            cycle = _log_cycle[0]
        if cycle is not None:
            obj["cycle"] = cycle
        if record.exc_info:
            obj["exc"] = self.formatException(record.exc_info)
        return json.dumps(obj)


def configure_logging(level: str = "INFO", fmt: str = "text") -> None:
    """Process-wide log init (the main.rs:129 equivalent), level configurable
    — the reference hard-codes both level and format.  ``fmt="json"`` emits
    one JSON object per line for log pipelines; ``"text"`` keeps the
    human-readable default."""
    lvl = getattr(logging, level.upper(), logging.INFO)
    if fmt == "json":
        handler = logging.StreamHandler()
        handler.setFormatter(JsonLogFormatter())
        logging.basicConfig(level=lvl, handlers=[handler], force=True)
    elif fmt == "text":
        logging.basicConfig(
            level=lvl,
            format="%(asctime)s %(levelname)s %(name)s %(message)s",
        )
    else:
        raise ValueError(f"unknown log format {fmt!r} (expected 'text' or 'json')")


class Trace:
    """Accumulates named span durations (seconds) for one scope (e.g. one
    scheduling cycle), plus the span INTERVALS in wall-clock time — the
    flight recorder's Chrome-trace source.  Intervals are derived from
    perf_counter deltas re-anchored to wall time at construction, so they
    are monotonic within the trace and meaningful across cycles.

    Spans NEST: while a span is open, spans (and ``record`` calls) inside it
    key under ``parent/child`` paths.  ``durations``/``counts``/``events``
    are therefore PATH-keyed; depth-0 paths (no ``/``) are the cycle phases.
    Single-threaded by design — only the entering thread's spans land here
    (see the module docstring)."""

    __slots__ = ("durations", "counts", "events", "_wall0", "_perf0", "_path")

    def __init__(self):
        self.durations: dict[str, float] = {}
        self.counts: dict[str, int] = {}
        self.events: list[tuple[str, float, float]] = []  # (path, wall_start, wall_end)
        self._wall0 = time.time()
        self._perf0 = time.perf_counter()
        self._path = ""  # the currently open span path ("" = top level)

    def _wall(self, perf_t: float) -> float:
        return self._wall0 + (perf_t - self._perf0)

    def record(self, name: str, seconds: float, perf_start: float | None = None) -> None:
        """Record a span as a child of the currently open path.  ``perf_start``
        (a perf_counter stamp) gives the exact interval; without it the
        interval is synthesized as ending now — the overlapped-bind drain
        knows only its duration, and an approximate box in the trace beats
        an invisible one."""
        path = f"{self._path}/{name}" if self._path else name
        self._record_path(path, seconds, perf_start)

    def _record_path(self, path: str, seconds: float, perf_start: float | None) -> None:
        self.durations[path] = self.durations.get(path, 0.0) + seconds
        self.counts[path] = self.counts.get(path, 0) + 1
        end = time.perf_counter() if perf_start is None else perf_start + seconds
        self.events.append((path, self._wall(end - seconds), self._wall(end)))

    def __enter__(self) -> "Trace":
        _stack().append(self)
        return self

    def __exit__(self, *exc) -> None:
        s = _stack()
        if self in s:
            s.remove(self)

    def summary(self) -> dict[str, float]:
        """Path -> accumulated seconds (depth-0 paths are plain names)."""
        return dict(self.durations)

    def top_level(self) -> dict[str, float]:
        """Depth-0 durations only — the disjoint cycle phases whose sum is
        the attributed share of the cycle wall."""
        return {p: s for p, s in self.durations.items() if "/" not in p}


def current_trace() -> Trace | None:
    s = getattr(_tls, "stack", None)
    return s[-1] if s else None


class span:
    """Time a block into the active trace (hierarchically).  A plain class
    context manager, not @contextmanager: this sits on the per-round hot
    path and the generator protocol costs ~2 µs per use that a __slots__
    class does not (the <2 % profiler-overhead budget is built from this)."""

    __slots__ = ("name", "_t0", "_tr", "_prev")

    def __init__(self, name: str):
        self.name = name

    def __enter__(self) -> "span":
        s = getattr(_tls, "stack", None)
        tr = self._tr = s[-1] if s else None
        if tr is not None:
            prev = tr._path
            self._prev = prev
            tr._path = f"{prev}/{self.name}" if prev else self.name
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        dt = time.perf_counter() - self._t0
        tr = self._tr
        if tr is not None:
            path = tr._path
            tr._path = self._prev
            tr._record_path(path, dt, self._t0)


@contextlib.contextmanager
def device_profile(log_dir: str | None):
    """jax.profiler trace around a block; inert when log_dir is None."""
    if not log_dir:
        yield
        return
    try:
        import jax

        jax.profiler.start_trace(log_dir)
        started = True
    except Exception as e:  # pragma: no cover - profiler availability varies
        logger.warning("device profiling unavailable: %s", e)
        started = False
    try:
        yield
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception:  # pragma: no cover
                pass
