"""Cycle cost-attribution profiler — the instrument behind the two headline
ROADMAP perf items ("profile the cycle" is where both the constrained-scale
and the incremental-cycle work start).

Four pieces:

  • **Attribution trees** — ``build_tree`` folds one cycle's hierarchical
    ``Trace`` (utils/tracing.py path-keyed spans) into a nested node tree
    with per-node total and SELF time (total minus children — the disjoint
    quantity that sums to the attributed wall).  ``coverage`` is
    1 − other/wall: the share of the cycle wall the tree explains.  The
    closed span vocabulary is ``SPAN_CATALOGUE`` (drift-gated against the
    README "Profiling" catalogue by the PROF analyze rule).
  • **Continuous profile ring** (``ProfileRing``) — an always-on, bounded,
    lock-disciplined aggregator: per-path count + total plus a bounded
    sample window for p50/p99, fed one trace per cycle, served at
    ``/debug/profile`` and summarized into ``/debug/shards``.
  • **Replica registry** (``ReplicaProfileRegistry``) — multi-replica
    aggregation: each replica registers its snapshot callable; the merged
    view sums totals/counts per path, ``/debug/profile?replica=`` selects
    one replica.
  • **Compile/execute split** — ``install_jax_profile_hooks`` registers
    ``jax.monitoring`` listeners so XLA compiles land in the active trace as
    ``compile`` spans (and in global counters); ``record_transfer`` counts
    host→device bytes at the TpuBackend's device_put seam.  Together with
    the epoch driver's ``dispatch``/``host-sync`` spans, "solve time"
    decomposes into compile / device-execute / host-sync / Python.

SLO burn: ``tier_of`` maps pod priority to a closed tier set with per-tier
time-to-bind targets (``SLO_TIERS``); the controller's pending-age tracker
feeds ``scheduler_pending_age_seconds{tier=,gang=}`` and the per-tier
burn-rate gauges from it.

Determinism contract (sim): the profiler draws no randomness and influences
no scheduling decision — span *presence and counts* are pure functions of
control flow (bit-identical under record/replay), only durations vary, and
the scorecard ``profile`` block carries exclusively the deterministic parts
(span census + the coverage verdict, which holds with wide margin).
"""

from __future__ import annotations

import threading
import time

from .tracing import Trace, current_trace

__all__ = [
    "SPAN_CATALOGUE",
    "SLO_TIERS",
    "tier_of",
    "build_tree",
    "coverage",
    "ProfileRing",
    "ReplicaProfileRegistry",
    "ReplicaLatencyRegistry",
    "install_jax_profile_hooks",
    "record_transfer",
    "transfer_bytes_total",
    "span_cost_estimate",
]

# The closed vocabulary of span base names (indexed spans like ``round[03]``
# catalogue under their base).  Every span the package opens must use a name
# from this tuple — enforced by tests/test_profiler.py against live cycles
# and drift-gated against the README "Profiling" catalogue (PROF rule).
SPAN_CATALOGUE = (
    # cycle phases (depth 0 — the CycleMetrics breakdown fields)
    "sync",        # reflector watch fold -> fresh snapshot
    "overlay",     # ledger prune, shard/lease refresh, deferred flush/overlay, pipeline fold
    "noexecute",   # NoExecute taint eviction scan
    "queue",       # eligibility filter, backoff prune, cycle-snapshot rebuild, gang census
    "pack",        # snapshot -> device tensors (full or incremental)
    "solve",       # backend auction (rounds/epochs nest under it)
    "constrained", # host sequential phase (untensorizable constraint fallback)
    "mopup",       # stall-residue sequential completeness pass
    "bind",        # binding POSTs / deferred-bind bookkeeping
    "preempt",     # preemption pass
    "gang",        # per-gang admission accounting + locality stats
    "slo",         # pending-age tracker + burn-rate gauges
    "delta",       # incremental engine: classification/closure/commit (tpu_scheduler/delta)
    "rebalance",   # background defrag tier: reconcile/solve/plan/migrate (tpu_scheduler/rebalance)
    "autoscale",   # elastic-capacity tier: pump/plan/scale (tpu_scheduler/autoscale)
    # nested cost centers
    "index",       # delta sub-span: watch-event fold into the SolveState
    "close",       # delta sub-span: invalidation closure over standing verdicts
    "repack",      # delta sub-span: carried residual-capacity materialization
    "shadow",      # delta sub-span: sim-only full-solve parity check
    "round",       # one auction round (native backend round loop)
    "mask",        # per-round constraint/topology mask build
    "score",       # per-round feasibility + scoring sweep
    "choose",      # per-round claim/accept/commit
    "filter",      # choose sub-span: within-round constraint conflict filter
    "aa",          # filter sub-span: fused anti-affinity predecessor check
    "pa",          # filter sub-span: positive-affinity bootstrap min-rank
    "spread",      # filter sub-span: spread rank-prefix admission + cascade
    "commit",      # choose sub-span: domain-state commit of accepted claims
    "snapshot",    # rebalance sub-span: consistent packing-view build
    "plan",        # rebalance sub-span: batch selection / autoscale sub-span: catalog what-if
    "migrate",     # rebalance sub-span: breaker-gated unbinds + cordons
    "pump",        # autoscale sub-span: provider lifecycle pump (joins, reclaims, kills)
    "scale",       # autoscale sub-span: scale-up requests / scale-down drains
    "epoch",       # one epoch of the host-driven size-shrinking driver
    "dispatch",    # epoch dispatch (async jit call; Python + trace time)
    "host-sync",   # the one per-epoch device fetch (device execute + transfer)
    "compile",     # XLA compile time observed via jax.monitoring
)

# Priority tier -> (floor priority, time-to-bind SLO target seconds).  The
# tier of a pod is the first row whose floor its priority reaches; the burn
# rate of a tier is oldest-pending-age / target (>1 = the SLO is burning).
SLO_TIERS = (
    ("critical", 1000, 30.0),
    ("high", 100, 60.0),
    ("default", 0, 300.0),
    ("best-effort", None, 1200.0),  # None floor = everything below "default"
)


def tier_of(priority: int) -> str:
    for name, floor, _target in SLO_TIERS:
        if floor is not None and priority >= floor:
            return name
    return SLO_TIERS[-1][0]


def tier_target(tier: str) -> float:
    for name, _floor, target in SLO_TIERS:
        if name == tier:
            return target
    return SLO_TIERS[-1][2]


# -- attribution trees --------------------------------------------------------


def build_tree(trace: Trace, wall: float) -> dict:
    """Fold a path-keyed trace into a nested attribution tree.

    Returns ``{"wall_s", "attributed_s", "other_s", "coverage", "children"}``
    where children maps span name -> ``{"count", "total_s", "self_s",
    "children"}``.  ``self_s`` (total minus direct children) is disjoint by
    construction: summed over the whole tree it equals the attributed wall.
    """
    root: dict = {"children": {}}
    for path, seconds in trace.durations.items():
        node = root
        for seg in path.split("/"):
            node = node["children"].setdefault(seg, {"count": 0, "total_s": 0.0, "self_s": 0.0, "children": {}})
        node["count"] = trace.counts.get(path, 0)
        node["total_s"] += seconds

    def finish(node: dict) -> None:
        kids = sum(c["total_s"] for c in node["children"].values())
        node["self_s"] = max(0.0, node["total_s"] - kids)
        for c in node["children"].values():
            finish(c)

    for c in root["children"].values():
        finish(c)
    attributed = sum(c["total_s"] for c in root["children"].values())
    other = max(0.0, wall - attributed)
    return {
        "wall_s": wall,
        "attributed_s": attributed,
        "other_s": other,
        "coverage": (attributed / wall) if wall > 0 else 1.0,
        "children": root["children"],
    }


def coverage(trace: Trace, wall: float) -> float:
    """1 − other/wall for one cycle (attributed = depth-0 span total)."""
    if wall <= 0:
        return 1.0
    return min(1.0, sum(trace.top_level().values()) / wall)


def _quantile(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


class ProfileRing:
    """Always-on bounded aggregator of per-cycle attribution trees.

    Per path: lifetime count/total plus a bounded window of recent per-cycle
    totals for p50/p99.  Ingest is one lock hold per cycle; snapshots are
    derived from one locked copy (the metrics-registry stance) because the
    HTTP debug thread reads while the cycle loop writes."""

    def __init__(self, window: int = 512):
        self.window = max(16, int(window))
        self._lock = threading.Lock()
        self._paths: dict[str, dict] = {}  # guarded-by: _lock — path -> {count,total_s,recent:[...]}
        self._cycles = 0  # guarded-by: _lock
        self._wall_total = 0.0  # guarded-by: _lock
        self._other_total = 0.0  # guarded-by: _lock
        self._recent_wall: list[float] = []  # guarded-by: _lock
        self._recent_spans: list[int] = []  # guarded-by: _lock — span events per cycle
        self._span_events_total = 0  # guarded-by: _lock

    def ingest(self, trace: Trace, wall: float) -> None:
        """Fold one cycle's trace.  Bounded: per-path windows and the
        cycle-level windows each trim to ``window`` entries."""
        other = max(0.0, wall - sum(trace.top_level().values()))
        with self._lock:
            self._cycles += 1
            self._wall_total += wall
            self._other_total += other
            self._recent_wall.append(wall)
            if len(self._recent_wall) > self.window:
                del self._recent_wall[0]
            self._recent_spans.append(len(trace.events))
            self._span_events_total += len(trace.events)
            if len(self._recent_spans) > self.window:
                del self._recent_spans[0]
            for path, seconds in trace.durations.items():
                ent = self._paths.get(path)
                if ent is None:
                    ent = self._paths[path] = {"count": 0, "total_s": 0.0, "recent": []}
                ent["count"] += trace.counts.get(path, 0)
                ent["total_s"] += seconds
                ent["recent"].append(seconds)
                if len(ent["recent"]) > self.window:
                    del ent["recent"][0]

    def _copy(self) -> tuple[dict, int, float, float, list[float], list[int]]:  # holds-lock: _lock
        paths = {
            p: {"count": e["count"], "total_s": e["total_s"], "recent": list(e["recent"])}
            for p, e in self._paths.items()
        }
        return paths, self._cycles, self._wall_total, self._other_total, list(self._recent_wall), list(self._recent_spans)

    def snapshot(self) -> dict:
        """The /debug/profile payload: aggregate coverage + a nested tree
        with per-node count, total, p50/p99 of per-cycle totals."""
        with self._lock:
            paths, cycles, wall_total, other_total, recent_wall, recent_spans = self._copy()
        tree: dict = {}
        for path in sorted(paths):
            ent = paths[path]
            node_children = tree
            segs = path.split("/")
            for seg in segs[:-1]:
                node_children = node_children.setdefault(seg, {"children": {}})["children"]
            rec = sorted(ent["recent"])
            node = node_children.setdefault(segs[-1], {"children": {}})
            node.update(
                count=ent["count"],
                total_s=round(ent["total_s"], 6),
                p50_s=round(_quantile(rec, 0.50), 6),
                p99_s=round(_quantile(rec, 0.99), 6),
            )
        rw = sorted(recent_wall)
        return {
            "cycles": cycles,
            "wall_total_s": round(wall_total, 6),
            "attributed_total_s": round(wall_total - other_total, 6),
            "other_total_s": round(other_total, 6),
            "coverage": round(1.0 - other_total / wall_total, 6) if wall_total > 0 else 1.0,
            "cycle_p50_s": round(_quantile(rw, 0.50), 6),
            "cycle_p99_s": round(_quantile(rw, 0.99), 6),
            "spans_per_cycle": round(sum(recent_spans) / len(recent_spans), 1) if recent_spans else 0.0,
            "tree": tree,
        }

    def brief(self) -> dict:
        """The /debug/shards perf block: cycle quantiles + coverage + the
        costliest top-level phases by lifetime total."""
        with self._lock:
            paths, cycles, wall_total, other_total, recent_wall, _ = self._copy()
        top = sorted(
            ((p, e["total_s"]) for p, e in paths.items() if "/" not in p),
            key=lambda kv: -kv[1],
        )[:5]
        rw = sorted(recent_wall)
        return {
            "cycles": cycles,
            "coverage": round(1.0 - other_total / wall_total, 6) if wall_total > 0 else 1.0,
            "cycle_p50_s": round(_quantile(rw, 0.50), 6),
            "cycle_p99_s": round(_quantile(rw, 0.99), 6),
            "top_phases": [{"phase": p, "total_s": round(s, 6)} for p, s in top],
        }

    def span_census(self) -> dict[str, int]:
        """Path -> lifetime count.  Counts are pure control-flow facts (no
        wall clock), so this is the deterministic face of the ring — the
        part the sim scorecard may carry."""
        with self._lock:
            return {p: e["count"] for p, e in sorted(self._paths.items())}

    def aggregate_coverage(self) -> float:
        with self._lock:
            if self._wall_total <= 0:
                return 1.0
            return 1.0 - self._other_total / self._wall_total

    def overhead_estimate(self) -> dict:
        """Measured profiler overhead over the run: (lifetime span events ×
        a freshly microbenched per-span cost + one ring-ingest pass per
        cycle, costed as ~one span per event) over the lifetime cycle wall.
        A model, not a subtraction of two noisy walls — the quantity the
        <2 % gate holds.  Aggregate on purpose: an idle no-op cycle costs a
        handful of spans against microseconds of wall, and judging overhead
        against idle cycles would indict the instrument for the workload's
        silence."""
        with self._lock:
            spans_total = self._span_events_total
            cycles = self._cycles
            wall_total = self._wall_total
            recent_spans = list(self._recent_spans)
        per_span = span_cost_estimate()
        spans_per_cycle = (sum(recent_spans) / len(recent_spans)) if recent_spans else 0.0
        overhead_total = spans_total * 2.0 * per_span  # span itself + its ingest pass
        return {
            "per_span_s": per_span,
            "spans_per_cycle": spans_per_cycle,
            "span_events_total": spans_total,
            "cycles": cycles,
            "overhead_total_s": overhead_total,
            "wall_total_s": wall_total,
            "overhead_frac": (overhead_total / wall_total) if wall_total > 0 else 0.0,
        }


def span_cost_estimate(n: int = 4000) -> float:
    """Median-of-3 microbench of one span enter/exit against a live Trace —
    the calibration input of the overhead gate."""
    from .tracing import span as _span

    best = []
    for _ in range(3):
        tr = Trace()
        with tr:
            t0 = time.perf_counter()
            for _i in range(n):
                with _span("probe"):
                    pass
            best.append((time.perf_counter() - t0) / n)
    best.sort()
    return best[1]


# -- multi-replica aggregation ------------------------------------------------


class ReplicaProfileRegistry:
    """Replica id -> snapshot callable; the /debug/profile route's source in
    multi-replica deployments (and the single-replica CLI registers its one
    scheduler).  ``snapshot(replica=...)`` selects one replica; without it,
    per-replica blocks plus a merged per-path sum."""

    def __init__(self):
        self._lock = threading.Lock()
        self._replicas: dict[str, object] = {}  # guarded-by: _lock — id -> () -> dict

    def register(self, replica_id: str, snapshot_fn) -> None:
        with self._lock:
            self._replicas[replica_id] = snapshot_fn

    def replica_ids(self) -> list[str]:
        with self._lock:
            return sorted(self._replicas)

    def snapshot(self, replica: str | None = None) -> dict:
        with self._lock:
            fns = dict(self._replicas)
        if replica is not None:
            fn = fns.get(replica)
            if fn is None:
                return {"error": f"unknown replica {replica!r}", "replicas": sorted(fns)}
            return {"replica": replica, **fn()}
        per = {rid: fn() for rid, fn in sorted(fns.items())}
        merged: dict = {"cycles": 0, "wall_total_s": 0.0, "other_total_s": 0.0}
        for snap in per.values():
            prof = snap.get("profile", snap)
            merged["cycles"] += prof.get("cycles", 0)
            merged["wall_total_s"] += prof.get("wall_total_s", 0.0)
            merged["other_total_s"] += prof.get("other_total_s", 0.0)
        wt = merged["wall_total_s"]
        merged["coverage"] = round(1.0 - merged["other_total_s"] / wt, 6) if wt > 0 else 1.0
        merged["wall_total_s"] = round(merged["wall_total_s"], 6)
        merged["other_total_s"] = round(merged["other_total_s"], 6)
        return {"replicas": per, "merged": merged}


class ReplicaLatencyRegistry:
    """Replica id -> latency_snapshot callable; the /debug/latency route's
    source in multi-replica deployments (same registration pattern as
    ReplicaProfileRegistry).  ``snapshot(replica=...)`` selects one replica;
    without it, per-replica blocks plus a fleet-merged per-tier sum of the
    time-to-bind decomposition."""

    def __init__(self):
        self._lock = threading.Lock()
        self._replicas: dict[str, object] = {}  # guarded-by: _lock — id -> () -> dict

    def register(self, replica_id: str, snapshot_fn) -> None:
        with self._lock:
            self._replicas[replica_id] = snapshot_fn

    def replica_ids(self) -> list[str]:
        with self._lock:
            return sorted(self._replicas)

    # shape: (self: obj, replica: obj) -> obj
    def snapshot(self, replica: str | None = None) -> dict:
        with self._lock:
            fns = dict(self._replicas)
        if replica is not None:
            fn = fns.get(replica)
            if fn is None:
                return {"error": f"unknown replica {replica!r}", "replicas": sorted(fns)}
            return {"replica": replica, **fn()}
        per = {rid: fn() for rid, fn in sorted(fns.items())}
        merged_tiers: dict[str, dict] = {}
        confirmed = 0
        awaiting = 0
        for snap in per.values():
            confirmed += snap.get("confirmed", 0)
            awaiting += snap.get("awaiting_confirm", 0)
            for tier, blk in snap.get("tiers", {}).items():
                acc = merged_tiers.setdefault(
                    tier, {"count": 0, "ttb_sum_s": 0.0, "unattributed_sum_s": 0.0, "segments_sum_s": {}}
                )
                acc["count"] += blk.get("count", 0)
                acc["ttb_sum_s"] += blk.get("ttb_sum_s", 0.0)
                acc["unattributed_sum_s"] += blk.get("unattributed_sum_s", 0.0)
                for seg, v in blk.get("segments_sum_s", {}).items():
                    acc["segments_sum_s"][seg] = acc["segments_sum_s"].get(seg, 0.0) + v
        for acc in merged_tiers.values():
            acc["mean_ttb_s"] = round(acc["ttb_sum_s"] / acc["count"], 9) if acc["count"] else 0.0
            acc["ttb_sum_s"] = round(acc["ttb_sum_s"], 9)
            acc["unattributed_sum_s"] = round(acc["unattributed_sum_s"], 9)
            acc["segments_sum_s"] = {seg: round(v, 9) for seg, v in acc["segments_sum_s"].items()}
        merged = {"confirmed": confirmed, "awaiting_confirm": awaiting, "tiers": merged_tiers}
        return {"replicas": per, "merged": merged}


# -- compile/transfer accounting ----------------------------------------------

_xfer_lock = threading.Lock()
_xfer_bytes = [0]  # guarded-by: _xfer_lock — lifetime host->device bytes
_compile_lock = threading.Lock()
_compile_stats = {"compiles": 0, "compile_s": 0.0, "cache_hits": 0, "cache_misses": 0}  # guarded-by: _compile_lock
_hooks_installed = [False]


def record_transfer(nbytes: int) -> None:
    """Count host→device bytes (the TpuBackend device_put seam)."""
    with _xfer_lock:
        _xfer_bytes[0] += int(nbytes)


def transfer_bytes_total() -> int:
    with _xfer_lock:
        return _xfer_bytes[0]


def compile_stats() -> dict:
    with _compile_lock:
        return dict(_compile_stats)


def compile_listener_active() -> bool:
    """Whether the jax.monitoring compile listener is counting — the
    scorecard ``compile`` block's ``enabled`` bit (False means the counts
    are vacuously zero, e.g. a pure-numpy NativeBackend run)."""
    return bool(_hooks_installed[0])


def _on_event_duration(event: str, duration: float, **_kw) -> None:
    """jax.monitoring duration listener: XLA backend compiles become
    ``compile`` spans of the active trace (attributed wherever the trace was
    — inside ``solve`` for a cycle's first constrained shape) and lifetime
    counters for /debug/profile."""
    if "compile" not in event:
        return
    with _compile_lock:
        _compile_stats["compiles"] += 1
        _compile_stats["compile_s"] += float(duration)
    tr = current_trace()
    if tr is not None:
        tr.record("compile", float(duration))


def _on_event(event: str, **_kw) -> None:
    if "compilation_cache" not in event:
        return
    key = "cache_hits" if ("hit" in event or "persistent_cache_hit" in event) else "cache_misses" if "miss" in event else None
    if key is None:
        return
    with _compile_lock:
        _compile_stats[key] += 1


def install_jax_profile_hooks() -> bool:
    """Best-effort ``jax.monitoring`` listener registration (idempotent).
    Returns whether hooks are active; never raises — profiling must not be
    able to take the scheduler down."""
    if _hooks_installed[0]:
        return True
    try:
        from jax import monitoring

        monitoring.register_event_duration_secs_listener(_on_event_duration)
        if hasattr(monitoring, "register_event_listener"):
            monitoring.register_event_listener(_on_event)
        _hooks_installed[0] = True
        return True
    except Exception:  # noqa: BLE001 — jax absent/old: profiling degrades, never crashes
        return False
