"""Persistent XLA compilation cache.

The flagship cycle's first compile costs ~15-20 s (Mosaic kernel + the full
auction while_loop); the disk cache cuts a fresh process's warmup to ~4 s
(measured on the real chip — the residual is device init and sub-threshold
compiles).  Opt-in from entry points (bench.py, cli.py) rather than at import
so library users keep control of jax config.
"""

from __future__ import annotations

import os

__all__ = ["enable_compilation_cache", "DEFAULT_CACHE_DIR"]

DEFAULT_CACHE_DIR = os.path.join(os.path.expanduser("~"), ".cache", "tpu_scheduler", "jax")


def enable_compilation_cache(cache_dir: str | None = None) -> str | None:
    """Point jax at a persistent on-disk compilation cache.  Returns the
    directory used, or None if jax is unavailable or the config rejects it
    (old jax); never raises — warmup speed is never worth a crash."""
    path = cache_dir or os.environ.get("TPU_SCHEDULER_COMPILE_CACHE", DEFAULT_CACHE_DIR)
    try:
        import jax

        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        return path
    except Exception:  # noqa: BLE001 — best-effort: cache or nothing changes
        return None
