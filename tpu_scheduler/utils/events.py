"""Scheduling flight recorder — bounded per-pod decision timelines plus a
ring buffer of recent cycles, the in-process answer to "why is this pod
Pending?" and "what did cycle N spend its time on?" without re-running
bench.py (VERDICT round 5: classify and surface unschedulable pods as a
product feature, not a bench field).

Every verdict the controller reaches about a pod — seen-pending, packed,
gang-admitted/refused, bound, requeued, unschedulable (with its typed
``InvalidNodeReason`` and per-reason candidate-node counts) — lands here as
one timeline entry.  The recorder is strictly bounded in three dimensions
(tracked pods, events per pod, retained cycles) so a daemon observing
unbounded churn holds constant memory; overflow evicts the least-recently
updated timeline (the pods an operator debugs are the ones still acting)
and is counted, never silent.

``chrome_trace`` renders the recorded per-cycle span intervals as Chrome
trace-event JSON (the ``{"traceEvents": [...]}`` object form) loadable in
Perfetto / chrome://tracing, with the device-trace directory linked when
``--profile-dir`` is set — plus one track per tracked pod (pid 2) showing
its admission waterfall as segment slices.  Served by
``runtime/http_api.py`` under ``/debug/pods/<ns>/<name>``, ``/debug/cycles``
and ``/debug/trace``.

``waterfall`` is the latency reducer on top of the timelines: it attributes
one bound pod's time-to-bind to the closed ``SEGMENTS`` taxonomy (each
inter-event interval belongs to the segment named by the EARLIER event's
kind via ``SEGMENT_OF_KIND``), with anything unmapped surfaced as
``unattributed`` — the attribution leak the scorecard's sum-to-TTB audit
catches.  Latency math reads the ``t`` stamp (the injected scheduler clock:
virtual seconds in the sim, monotonic in the daemon), never wall ``ts``.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque

__all__ = ["FlightRecorder", "EVENT_KINDS", "SEGMENTS", "SEGMENT_OF_KIND", "waterfall"]

# The closed vocabulary of per-pod verdicts (one place, so the debug API and
# tests can validate timelines against it).
EVENT_KINDS = (
    "seen-pending",
    "packed",
    "gang-admitted",
    "gang-refused",
    "backend-fallback",
    "bound",
    "requeued",
    "unschedulable",
    "preempted",
    "evicted",
    # degraded-mode verdicts (runtime/resilience.py): the bind was computed
    # but POSTing waited out an open circuit breaker / flushed on recovery.
    "bind-deferred",
    "bind-flushed",
    # admission-latency waterfall: the cross-shard two-phase gang hold
    # opened (tpu_scheduler/fleet) and the binding POST confirmed by the
    # watch stream — the ``reservation-wait`` and ``confirm`` segment edges.
    "reservation-opened",
    "bind-confirmed",
)

# The closed admission-latency segment taxonomy (drift-gated against the
# README "Latency & time-to-bind" catalogue by the LATN analyze rule):
# every bound pod's time-to-bind decomposes into exactly these segments.
SEGMENTS = (
    "cadence-wait",  # arrival -> the first cycle that saw the pod
    "solve",  # cycle entry -> placement chosen
    "gang-wait",  # placed-but-gang-incomplete residency
    "reservation-wait",  # cross-shard gang two-phase hold
    "backoff",  # requeue intervals, by failure class
    "breaker-deferred",  # open-circuit flush-buffer residency
    "bind-post",  # placement committed -> binding POSTed
    "confirm",  # POST accepted -> watch-confirmed bound
)

# Interval attribution: the span between two consecutive timeline events
# belongs to the segment named by the EARLIER event's kind (what the pod
# was waiting on when that interval started).  Kinds absent here (preempted,
# evicted, migration churn) make the interval ``unattributed`` — a leak the
# scorecard's sum-to-TTB audit fails loudly instead of absorbing.
SEGMENT_OF_KIND = {
    "seen-pending": "solve",
    "packed": "solve",
    "backend-fallback": "solve",
    "gang-admitted": "bind-post",
    "gang-refused": "gang-wait",
    "reservation-opened": "reservation-wait",
    "requeued": "backoff",
    "unschedulable": "backoff",
    "bind-deferred": "breaker-deferred",
    "bind-flushed": "bind-post",
    "bound": "confirm",
}


# shape: (timeline: obj, arrival_t: obj) -> obj
def waterfall(timeline: list[dict], arrival_t: float | None = None) -> dict | None:
    """Decompose one pod's timeline into its admission-latency waterfall.

    The terminal event is the last ``bind-confirmed`` (falling back to the
    last ``bound`` when the watch-confirm was never recorded); a pod that
    never bound has no waterfall (returns None).  ``cadence-wait`` is the
    gap from ``arrival_t`` (defaulting to the first event's stamp — zero
    cadence wait) to the first event; every later inter-event interval is
    attributed via ``SEGMENT_OF_KIND``.  Segments + ``unattributed`` sum to
    ``ttb`` exactly by construction (to the 9-decimal rounding), so a
    nonzero ``unattributed`` IS the attribution leak the scorecard audit
    gates on.  Pure function of the ``t`` stamps — deterministic under the
    sim's virtual clock."""
    if not timeline:
        return None
    term = None
    for i in range(len(timeline) - 1, -1, -1):
        if timeline[i].get("kind") == "bind-confirmed":
            term = i
            break
    if term is None:
        for i in range(len(timeline) - 1, -1, -1):
            if timeline[i].get("kind") == "bound":
                term = i
                break
    if term is None:
        return None

    def t_of(ev: dict) -> float:
        return float(ev.get("t", ev.get("ts", 0.0)))

    t_first = t_of(timeline[0])
    t0 = t_first if arrival_t is None else float(arrival_t)
    segments = {seg: 0.0 for seg in SEGMENTS}
    segments["cadence-wait"] = max(0.0, t_first - t0)
    unattributed = 0.0
    for i in range(term):
        dt = max(0.0, t_of(timeline[i + 1]) - t_of(timeline[i]))
        seg = SEGMENT_OF_KIND.get(timeline[i].get("kind"))
        if seg is None:
            unattributed += dt
        else:
            segments[seg] += dt
    return {
        "ttb": round(max(0.0, t_of(timeline[term]) - t0), 9),
        "segments": {seg: round(v, 9) for seg, v in segments.items()},
        "unattributed": round(unattributed, 9),
    }


class FlightRecorder:
    """Bounded in-memory recorder of scheduling decisions.

    ``max_pods`` timelines of at most ``per_pod`` events each, plus
    ``max_cycles`` cycle records (CycleMetrics + span intervals).  All
    methods are thread-safe: the pipelined bind worker records bound/requeue
    outcomes while the HTTP debug routes read concurrently.  ``max_pods=0``
    disables recording entirely (every call is a cheap no-op) — the
    ``--events-buffer 0`` escape hatch for benchmark runs.

    ``clock`` (the scheduler's own clock callable) adds a second stamp ``t``
    to every event beside wall ``ts``: the latency-math time base —
    VIRTUAL seconds in the sim (so ``waterfall`` is deterministic under
    record/replay), monotonic in the daemon.  Without it ``t`` equals
    ``ts``."""

    def __init__(self, max_pods: int = 4096, per_pod: int = 64, max_cycles: int = 256, clock=None):
        self.max_pods = max_pods
        self.per_pod = per_pod
        self.max_cycles = max_cycles
        self.clock = clock
        self._lock = threading.Lock()
        self._timelines: OrderedDict[str, deque] = OrderedDict()  # guarded-by: _lock
        self._cycles: deque = deque(maxlen=max(1, max_cycles))  # guarded-by: _lock
        self.evicted_timelines = 0  # guarded-by: _lock — LRU overflow count; visible, never silent
        # Set by the CLI when --profile-dir is active so chrome_trace can
        # link the device trace next to the host spans.
        self.device_trace_dir: str | None = None

    @property
    def enabled(self) -> bool:
        return self.max_pods > 0

    def _now(self) -> tuple[float, float]:
        """(wall ``ts``, scheduler-clock ``t``) for one event stamp."""
        ts = time.time()
        return ts, (float(self.clock()) if self.clock is not None else ts)

    # -- per-pod timelines --------------------------------------------------

    def record(
        self,
        pod_full: str,
        kind: str,
        cycle: int,
        *,
        node: str | None = None,
        reason: str | None = None,
        counts: dict[str, int] | None = None,
        detail: str | None = None,
    ) -> None:
        """Append one verdict to a pod's timeline (creating it if needed,
        evicting the least-recently-updated timeline at capacity)."""
        if not self.enabled:
            return
        ts, t = self._now()
        ev: dict = {"ts": ts, "t": t, "cycle": cycle, "kind": kind}
        if node is not None:
            ev["node"] = node
        if reason is not None:
            ev["reason"] = reason
        if counts:
            ev["candidate_counts"] = dict(counts)
        if detail is not None:
            ev["detail"] = detail
        with self._lock:
            tl = self._timelines.get(pod_full)
            if tl is None:
                while len(self._timelines) >= self.max_pods:
                    self._timelines.popitem(last=False)
                    self.evicted_timelines += 1
                tl = self._timelines[pod_full] = deque(maxlen=self.per_pod)
            else:
                self._timelines.move_to_end(pod_full)
            tl.append(ev)

    def seen(self, pod_full: str, cycle: int) -> None:
        """Record ``seen-pending`` once — only for pods with no timeline yet
        (O(1) dict probe; called for every pending pod every cycle).

        One lock hold for probe AND append: the old probe-unlock-record
        shape was a TOCTOU — two threads racing the same new pod could both
        miss the probe and double-record ``seen-pending`` (surfaced by the
        THRD lock-discipline review; regression-pinned in test_analyze)."""
        if not self.enabled:
            return
        with self._lock:
            if pod_full in self._timelines:
                return
            while len(self._timelines) >= self.max_pods:
                self._timelines.popitem(last=False)
                self.evicted_timelines += 1
            tl = self._timelines[pod_full] = deque(maxlen=self.per_pod)
            ts, t = self._now()
            tl.append({"ts": ts, "t": t, "cycle": cycle, "kind": "seen-pending"})

    def seen_many(self, pod_fulls, cycle: int) -> None:
        """Batch ``seen``: ONE lock hold for a whole cycle's pending set —
        the controller calls this with up to 100k names per cycle, and a
        per-name lock acquisition would tax the hot loop measurably."""
        if not self.enabled:
            return
        ts, t = self._now()
        with self._lock:
            for pf in pod_fulls:
                if pf in self._timelines:
                    continue
                while len(self._timelines) >= self.max_pods:
                    self._timelines.popitem(last=False)
                    self.evicted_timelines += 1
                tl = self._timelines[pf] = deque(maxlen=self.per_pod)
                tl.append({"ts": ts, "t": t, "cycle": cycle, "kind": "seen-pending"})

    def record_packed(self, pod_fulls, cycle: int, backend: str) -> None:
        """Record ``packed`` for ALREADY-TRACKED pods only — the batch path
        packs 100k pods per cycle, and growing timelines here would churn
        the LRU; a pod enters via ``seen`` and keeps its batch membership
        from then on."""
        if not self.enabled:
            return
        ts, t = self._now()
        ev_base = {"ts": ts, "t": t, "cycle": cycle, "kind": "packed", "detail": backend}
        with self._lock:
            for pf in pod_fulls:
                tl = self._timelines.get(pf)
                if tl is not None:
                    tl.append(dict(ev_base))

    def timeline(self, pod_full: str) -> list[dict]:
        with self._lock:
            tl = self._timelines.get(pod_full)
            return [dict(ev) for ev in tl] if tl is not None else []

    def tracked_pods(self) -> list[str]:
        with self._lock:
            return list(self._timelines)

    # -- per-cycle records ---------------------------------------------------

    def record_cycle(self, metrics: dict, spans: list[tuple[str, float, float]], notes: list[str] | None = None) -> None:
        """Retain one cycle: its CycleMetrics dict, its span INTERVALS
        (name, wall_start, wall_end — the chrome_trace source), and any
        cycle-level annotations (backend-fallback etc.)."""
        if not self.enabled:
            return
        rec = {
            "wall_end": time.time(),
            "metrics": dict(metrics),
            "spans": [(name, t0, t1) for name, t0, t1 in spans],
        }
        if notes:
            rec["notes"] = list(notes)
        with self._lock:
            self._cycles.append(rec)

    def cycles(self, n: int | None = None) -> list[dict]:
        with self._lock:
            out = list(self._cycles)
        if n is not None:
            out = out[-n:]
        return [
            {**rec, "spans": [{"name": s[0], "start": s[1], "end": s[2]} for s in rec["spans"]]}
            for rec in out
        ]

    # -- Chrome trace-event export ------------------------------------------

    def chrome_trace(self, n_cycles: int | None = None) -> dict:
        """The recorded cycles as a Chrome trace-event JSON object
        (https://docs.google.com/document/d/1CvAClvFfyA5R-PhYUmn5OOQtYMH4h6I0nSsKchNAySU)
        — ``ph: "X"`` complete events in microseconds, one per recorded span,
        loadable in Perfetto or chrome://tracing.  When a device trace was
        captured (``--profile-dir``), its directory is linked in
        ``otherData`` so the host and device timelines can be opened side by
        side.  Tracked pods get their own process (pid 2, one thread per
        pod — the most recently updated 64): each timeline renders as its
        admission-waterfall segments, so a pod's journey reads as a lane of
        named slices under the cycle spans."""
        with self._lock:
            recs = list(self._cycles)
            pod_tls = [(pf, list(tl)) for pf, tl in list(self._timelines.items())[-64:]]
        if n_cycles is not None:
            recs = recs[-n_cycles:]
        events: list[dict] = []
        for rec in recs:
            cycle = rec["metrics"].get("cycle")
            for name, t0, t1 in rec["spans"]:
                # Span names are hierarchical PATHS (utils/tracing.py:
                # ``solve/round[03]/score``); Perfetto nests ``X`` slices on
                # one tid by time containment, so the slice carries the leaf
                # name and the full path rides in args.  Endpoint rounding
                # is monotone, so child slices never overhang their parent.
                ev = {
                    "name": name.rsplit("/", 1)[-1],
                    "cat": "scheduler",
                    "ph": "X",
                    "ts": round(t0 * 1e6, 3),
                    "dur": round(max(0.0, t1 - t0) * 1e6, 3),
                    "pid": 1,
                    "tid": 1,
                    "args": {"cycle": cycle},
                }
                if "/" in name:
                    ev["args"]["path"] = name
                events.append(ev)
            # One instant event marking the cycle boundary keeps cycles
            # countable even when a cycle recorded no spans (idle standby).
            events.append(
                {
                    "name": f"cycle {cycle}",
                    "cat": "scheduler",
                    "ph": "i",
                    "ts": round(rec.get("wall_end", 0.0) * 1e6, 3),
                    "pid": 1,
                    "tid": 1,
                    "s": "g",
                }
            )
        # Per-pod waterfall tracks (pid 2): each inter-event interval that
        # maps to a segment becomes one X slice on the pod's own tid.  Wall
        # ``ts`` keeps the pod lanes aligned with the cycle spans above;
        # unmapped intervals (eviction churn) are simply not drawn.
        if pod_tls:
            events.append({"name": "process_name", "ph": "M", "pid": 2, "args": {"name": "pod admission waterfall"}})
            for tid, (pf, tl) in enumerate(pod_tls, start=1):
                events.append({"name": "thread_name", "ph": "M", "pid": 2, "tid": tid, "args": {"name": pf}})
                for i in range(len(tl) - 1):
                    seg = SEGMENT_OF_KIND.get(tl[i].get("kind"))
                    if seg is None:
                        continue
                    t0 = tl[i].get("ts", 0.0)
                    events.append(
                        {
                            "name": seg,
                            "cat": "pod",
                            "ph": "X",
                            "ts": round(t0 * 1e6, 3),
                            "dur": round(max(0.0, tl[i + 1].get("ts", 0.0) - t0) * 1e6, 3),
                            "pid": 2,
                            "tid": tid,
                            "args": {"pod": pf, "kind": tl[i].get("kind")},
                        }
                    )
        trace = {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {"recorded_cycles": len(recs)},
        }
        if self.device_trace_dir:
            trace["otherData"]["device_trace_dir"] = self.device_trace_dir
        return trace
