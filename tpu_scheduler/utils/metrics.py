"""Cycle metrics — pods-bound/sec and cycle wall-clock are the north-star
numbers (BASELINE.md); the reference exposes no metrics at all (SURVEY.md §5).
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, field

__all__ = ["CycleMetrics", "MetricsRegistry"]


@dataclass
class CycleMetrics:
    cycle: int
    backend: str
    pending: int
    bound: int
    unschedulable: int
    rounds: int
    wall_seconds: float
    pack_seconds: float = 0.0
    solve_seconds: float = 0.0
    bind_seconds: float = 0.0
    # Host-side phases that can dominate constrained cycles at scale —
    # surfaced so a slow cycle is attributable from the JSON line alone.
    sync_seconds: float = 0.0
    mopup_seconds: float = 0.0
    other_seconds: float = 0.0  # wall minus every attributed phase

    @property
    def pods_per_second(self) -> float:
        return self.bound / self.wall_seconds if self.wall_seconds > 0 else 0.0

    def to_json(self) -> str:
        d = self.__dict__.copy()
        d["pods_per_second"] = round(self.pods_per_second, 2)
        return json.dumps(d)


@dataclass
class MetricsRegistry:
    """Process counters (Prometheus-style names, in-memory registry).
    ``inc`` is locked: the routed cycle's pool shards (and backend
    fallbacks inside them) increment from worker threads, and the /metrics
    HTTP server reads concurrently."""

    counters: dict[str, int] = field(default_factory=dict)
    cycles: list[CycleMetrics] = field(default_factory=list)
    started_at: float = field(default_factory=time.time)
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    def inc(self, name: str, value: int = 1) -> None:
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + value

    def observe_cycle(self, m: CycleMetrics) -> None:
        self.cycles.append(m)
        if len(self.cycles) > 1024:
            del self.cycles[0]  # bounded — a daemon observes unbounded cycles
        self.inc("scheduler_cycles_total")
        self.inc("scheduler_pods_bound_total", m.bound)
        self.inc("scheduler_pods_unschedulable_total", m.unschedulable)

    def snapshot(self) -> dict:
        with self._lock:  # /metrics reader vs worker-thread inc (dict-resize race)
            out = dict(self.counters)
        if self.cycles:
            last = self.cycles[-1]
            out["scheduler_last_cycle_seconds"] = last.wall_seconds
            out["scheduler_last_pods_per_second"] = last.pods_per_second
        return out

    def to_prometheus(self) -> str:
        """Prometheus text exposition (version 0.0.4) of the registry —
        counters, last-cycle gauges, and process uptime.  The reference has
        no metrics endpoint at all (SURVEY.md §5); this feeds the
        /metrics route of runtime/http_api.py.  Derived from ``snapshot()``
        so there is one source of truth for exported values."""
        snap = self.snapshot()
        gauges = {k: v for k, v in snap.items() if k not in self.counters}
        gauges["scheduler_uptime_seconds"] = time.time() - self.started_at
        if self.cycles:
            last = self.cycles[-1]
            gauges["scheduler_last_cycle_pending"] = float(last.pending)
            gauges["scheduler_last_cycle_rounds"] = float(last.rounds)
        lines = []
        for name in sorted(self.counters):
            lines.append(f"# TYPE {name} counter")
            lines.append(f"{name} {self.counters[name]}")
        for name in sorted(gauges):
            lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name} {gauges[name]}")
        return "\n".join(lines) + "\n"
