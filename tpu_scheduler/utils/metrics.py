"""Cycle metrics — pods-bound/sec and cycle wall-clock are the north-star
numbers (BASELINE.md); the reference exposes no metrics at all (SURVEY.md §5).

The registry is a real (if minimal) Prometheus-style registry: unlabeled and
LABELED counters, bucketed histograms (phase latencies, binding latency,
rounds-per-cycle), and last-cycle gauges, exported in valid text exposition
(version 0.0.4) by ``to_prometheus``.  Labeled counters live in the same
``counters`` dict as unlabeled ones under pre-formatted
``name{label="value"}`` keys — one flat dict keeps the checkpoint format
(runtime/checkpoint.py persists ``counters`` verbatim) and the CLI summary
line unchanged while the exposition groups series into families.

Thread-safety contract: every mutation AND every read path goes through
``_lock``; ``to_prometheus`` is derived strictly from one locked
``_snapshot_full()`` so a worker-thread ``inc`` can never race the /metrics
scrape mid-iteration (dict-resize under iteration was a real crash class).
"""

from __future__ import annotations

import bisect
import json
import threading
import time
from dataclasses import dataclass, field, fields

__all__ = ["CycleMetrics", "MetricsRegistry", "cycle_phases", "format_labels", "escape_label_value"]

# Latency buckets (seconds): sub-ms host phases through multi-second
# constrained cycles at flagship shapes.
LATENCY_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)
# Auction rounds per cycle: the round-5 work holds the flagship at 2.
ROUNDS_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0)
# Requeue backoff delays (seconds): sub-second fast-class retries through
# the reference's 5-minute flat delay and the long no-node escalation cap.
BACKOFF_BUCKETS = (0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 150.0, 300.0, 600.0, 1200.0)
# Worst pairwise interconnect distance of an admitted gang's placement
# (topology/ levels differing, weighted): 0 = one slice, through a few
# hierarchy levels — fractional bounds cover non-unit level weights.
DISTANCE_BUCKETS = (0.0, 0.5, 1.0, 1.5, 2.0, 3.0, 4.0, 8.0)
# Final pending age (first-seen to bind/exit) per SLO tier: sub-second
# same-cycle binds through multi-minute backlog pain past every tier target
# (utils/profiler.SLO_TIERS tops out at 1200 s).
PENDING_AGE_BUCKETS = (0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0, 600.0, 1200.0, 3600.0)
# Dirty-set size per delta cycle (tpu_scheduler/delta): single-pod watch
# ripples through flagship-scale churn waves.
DIRTY_BUCKETS = (1.0, 4.0, 16.0, 64.0, 256.0, 1024.0, 4096.0, 16384.0)
# Per-segment time-to-bind attribution (utils/events.SEGMENTS): zero-width
# same-cycle segments through multi-minute backoff/backlog residency — the
# low end needs sub-cadence resolution (one cycle interval ~ 1 s).
TTB_SEGMENT_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0, 600.0)

# Histogram name -> bucket bounds; the one registration point the README
# drift gate (scripts/lint.py) and to_prometheus share.
HISTOGRAM_BUCKETS = {
    "scheduler_cycle_seconds": LATENCY_BUCKETS,
    "scheduler_phase_seconds": LATENCY_BUCKETS,
    "scheduler_binding_seconds": LATENCY_BUCKETS,
    "scheduler_cycle_rounds": ROUNDS_BUCKETS,
    "scheduler_backoff_seconds": BACKOFF_BUCKETS,
    "scheduler_gang_placement_distance": DISTANCE_BUCKETS,
    "scheduler_pending_age_seconds": PENDING_AGE_BUCKETS,
    "scheduler_delta_dirty_pods": DIRTY_BUCKETS,
    "scheduler_ttb_segment_seconds": TTB_SEGMENT_BUCKETS,
}


def escape_label_value(v: str) -> str:
    """Prometheus label-value escaping: backslash, double-quote, newline."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def format_labels(labels: dict[str, str] | None) -> str:
    """``{a="x",b="y"}`` (sorted, escaped) — "" for no labels."""
    if not labels:
        return ""
    inner = ",".join(f'{k}="{escape_label_value(v)}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class _Histogram:
    """One histogram series: cumulative-at-export bucket counts, sum, count."""

    __slots__ = ("bounds", "counts", "sum")

    def __init__(self, bounds: tuple[float, ...]):
        self.bounds = bounds
        self.counts = [0] * (len(bounds) + 1)  # last slot = +Inf
        self.sum = 0.0

    def observe(self, value: float) -> None:
        self.counts[bisect.bisect_left(self.bounds, value)] += 1
        self.sum += value

    @property
    def count(self) -> int:
        return sum(self.counts)


@dataclass
class CycleMetrics:
    cycle: int
    backend: str
    pending: int
    bound: int
    unschedulable: int
    rounds: int
    wall_seconds: float
    pack_seconds: float = 0.0
    solve_seconds: float = 0.0
    bind_seconds: float = 0.0
    # Host-side phases that can dominate constrained cycles at scale —
    # surfaced so a slow cycle is attributable from the JSON line alone.
    sync_seconds: float = 0.0
    mopup_seconds: float = 0.0
    # The remaining cycle regions, each its own phase so the attribution
    # coverage gate (1 − other/wall ≥ 0.9, utils/profiler.py) holds on
    # steady-state cycles where loop glue rivals the solve: overlay (ledger
    # prune + shard refresh + deferred flush), noexecute (taint eviction
    # scan), queue (eligibility + snapshot rebuild + gang census),
    # constrained (host sequential fallback), preempt, gang (admission
    # accounting), slo (pending-age tracker).  Every field here except
    # wall/other MUST correspond to a depth-0 span name — cycle_phases()
    # derives the set, observe_cycle and the controller's breakdown both
    # consume it, and tests/test_profiler.py pins the exact match so a new
    # phase cannot silently land in `other`.
    overlay_seconds: float = 0.0
    noexecute_seconds: float = 0.0
    queue_seconds: float = 0.0
    # Incremental engine bookkeeping (tpu_scheduler/delta): watch-delta
    # classification, invalidation closure, residual repack, commit, and the
    # sim-only shadow parity solve.
    delta_seconds: float = 0.0
    constrained_seconds: float = 0.0
    preempt_seconds: float = 0.0
    gang_seconds: float = 0.0
    slo_seconds: float = 0.0
    # Background rebalancer tick (tpu_scheduler/rebalance): reconcile,
    # packing snapshot/solve (inline mode), batch planning, migrations —
    # its own phase so background-tier cost can never hide in `other`.
    rebalance_seconds: float = 0.0
    # Autoscaler tick (tpu_scheduler/autoscale): provider pump, catalog
    # what-if plan, scale-up requests / scale-down drains — its own phase
    # so elastic-capacity cost can never hide in `other`.
    autoscale_seconds: float = 0.0
    other_seconds: float = 0.0  # wall minus every attributed phase

    @property
    def pods_per_second(self) -> float:
        return self.bound / self.wall_seconds if self.wall_seconds > 0 else 0.0

    def to_json(self) -> str:
        d = self.__dict__.copy()
        d["pods_per_second"] = round(self.pods_per_second, 2)
        return json.dumps(d)


def cycle_phases() -> tuple[str, ...]:
    """The closed cycle-phase set, DERIVED from the CycleMetrics fields
    (every ``*_seconds`` field except ``wall``): the one source the
    ``scheduler_phase_seconds{phase=}`` series, the controller's breakdown
    construction, and the drift test all share — adding a phase field wires
    the metric and the other-subtraction automatically."""
    return tuple(
        f.name[: -len("_seconds")]
        for f in fields(CycleMetrics)
        if f.name.endswith("_seconds") and f.name != "wall_seconds"
    )


@dataclass
class MetricsRegistry:
    """Process counters + histograms (Prometheus-style, in-memory).
    Everything is locked: the routed cycle's pool shards (and backend
    fallbacks inside them) increment from worker threads, and the /metrics
    HTTP server reads concurrently."""

    counters: dict[str, int] = field(default_factory=dict)  # guarded-by: _lock
    cycles: list[CycleMetrics] = field(default_factory=list)  # guarded-by: _lock
    started_at: float = field(default_factory=time.time)
    _histograms: dict[str, dict[str, _Histogram]] = field(default_factory=dict, repr=False)  # guarded-by: _lock
    _gauges: dict[str, float] = field(default_factory=dict, repr=False)  # guarded-by: _lock
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    # -- writes (all under _lock) -----------------------------------------

    def _inc(self, name: str, value: int, labels: dict[str, str] | None) -> None:  # holds-lock: _lock
        key = name + format_labels(labels)
        self.counters[key] = self.counters.get(key, 0) + value

    def inc(self, name: str, value: int = 1, labels: dict[str, str] | None = None) -> None:
        with self._lock:
            self._inc(name, value, labels)

    def _observe(self, name: str, value: float, labels: dict[str, str] | None) -> None:  # holds-lock: _lock
        per = self._histograms.setdefault(name, {})
        ls = format_labels(labels)
        h = per.get(ls)
        if h is None:
            h = per[ls] = _Histogram(HISTOGRAM_BUCKETS.get(name, LATENCY_BUCKETS))
        h.observe(value)

    def observe(self, name: str, value: float, labels: dict[str, str] | None = None) -> None:
        """Record one histogram observation (bucket bounds come from
        HISTOGRAM_BUCKETS, defaulting to the latency bounds)."""
        with self._lock:
            self._observe(name, value, labels)

    def set_gauge(self, name: str, value: float, labels: dict[str, str] | None = None) -> None:
        """Set an explicit gauge (e.g. ``scheduler_circuit_state``, or the
        per-tier ``scheduler_slo_burn_rate{tier=}`` series) —
        last-write-wins, exported beside the derived last-cycle gauges."""
        with self._lock:
            self._gauges[name + format_labels(labels)] = float(value)

    def observe_cycle(self, m: CycleMetrics) -> None:
        with self._lock:
            self.cycles.append(m)
            if len(self.cycles) > 1024:
                del self.cycles[0]  # bounded — a daemon observes unbounded cycles
            self._inc("scheduler_cycles_total", 1, None)
            self._inc("scheduler_pods_bound_total", m.bound, None)
            self._inc("scheduler_pods_unschedulable_total", m.unschedulable, None)
            self._observe("scheduler_cycle_seconds", m.wall_seconds, None)
            self._observe("scheduler_cycle_rounds", float(m.rounds), None)
            # The phase list is DERIVED from the CycleMetrics fields
            # (cycle_phases): a new breakdown field is a new {phase=} series
            # by construction, never a silent addition to `other`.
            for phase in cycle_phases():
                seconds = getattr(m, f"{phase}_seconds")
                if seconds > 0:
                    self._observe("scheduler_phase_seconds", seconds, {"phase": phase})
            if m.bind_seconds > 0:
                self._observe("scheduler_binding_seconds", m.bind_seconds, None)

    # -- reads (one locked snapshot; no iteration over live state) ---------

    def _snapshot_full(self) -> dict:
        """Everything the exposition needs, copied under ONE lock hold."""
        with self._lock:
            counters = dict(self.counters)
            hists = {
                name: {ls: (h.bounds, list(h.counts), h.sum) for ls, h in per.items()}
                for name, per in self._histograms.items()
            }
            last = self.cycles[-1] if self.cycles else None
            gauges: dict[str, float] = dict(self._gauges)
        if last is not None:
            gauges["scheduler_last_cycle_seconds"] = last.wall_seconds
            gauges["scheduler_last_pods_per_second"] = last.pods_per_second
            gauges["scheduler_last_cycle_pending"] = float(last.pending)
            gauges["scheduler_last_cycle_rounds"] = float(last.rounds)
        return {"counters": counters, "histograms": hists, "gauges": gauges}

    def snapshot(self) -> dict:
        """Flat name -> value view (labeled counters under their formatted
        keys) — the CLI summary / checkpoint-delta surface."""
        full = self._snapshot_full()
        out = dict(full["counters"])
        for k in ("scheduler_last_cycle_seconds", "scheduler_last_pods_per_second"):
            if k in full["gauges"]:
                out[k] = full["gauges"][k]
        return out

    def to_prometheus(self) -> str:
        """Prometheus text exposition (version 0.0.4) — counters (series
        grouped into families, one TYPE line each), histograms with
        cumulative ``_bucket``/``_sum``/``_count``, last-cycle gauges, and
        process uptime.  Derived strictly from one locked
        ``_snapshot_full()`` so a concurrent ``inc`` can never race the
        scrape (SURVEY.md §5: the reference has no metrics endpoint at
        all; this feeds the /metrics route of runtime/http_api.py)."""
        full = self._snapshot_full()
        gauges = dict(full["gauges"])
        gauges["scheduler_uptime_seconds"] = time.time() - self.started_at

        # Group counter series into families: "name{...}" -> family "name".
        families: dict[str, list[tuple[str, int]]] = {}
        for key in sorted(full["counters"]):
            fam = key.split("{", 1)[0]
            families.setdefault(fam, []).append((key, full["counters"][key]))
        lines: list[str] = []
        for fam in sorted(families):
            lines.append(f"# TYPE {fam} counter")
            for key, value in families[fam]:
                lines.append(f"{key} {value}")
        for name in sorted(full["histograms"]):
            lines.append(f"# TYPE {name} histogram")
            for ls in sorted(full["histograms"][name]):
                bounds, counts, total = full["histograms"][name][ls]
                # ls is "" or '{a="b"}'; merge the le label into it.
                base = ls[1:-1] if ls else ""
                cum = 0
                for bound, c in zip(bounds, counts):
                    cum += c
                    merged = ",".join(x for x in (base, f'le="{bound:g}"') if x)
                    lines.append(f"{name}_bucket{{{merged}}} {cum}")
                cum += counts[-1]
                merged = ",".join(x for x in (base, 'le="+Inf"') if x)
                lines.append(f"{name}_bucket{{{merged}}} {cum}")
                lines.append(f"{name}_sum{ls} {total}")
                lines.append(f"{name}_count{ls} {cum}")
        # Gauges group into families exactly like counters — set_gauge keys
        # labeled series as pre-formatted 'name{label="value"}' strings.
        gauge_families: dict[str, list[tuple[str, float]]] = {}
        for key in sorted(gauges):
            gauge_families.setdefault(key.split("{", 1)[0], []).append((key, gauges[key]))
        for fam in sorted(gauge_families):
            lines.append(f"# TYPE {fam} gauge")
            for key, value in gauge_families[fam]:
                lines.append(f"{key} {value}")
        return "\n".join(lines) + "\n"
