"""CPython GC tuning for daemon workloads.

The scheduler's steady state holds millions of long-lived objects (pods,
nodes, packed-tensor host buffers) while each cycle allocates hundreds of
thousands of short-lived ones (evolved API objects, watch events, bindings).
CPython's default gen-0 threshold of 700 allocations makes every ~700
allocations scan the young generation and periodically walk the WHOLE heap
(gen-2), which measured ~2x on the binding hot path at flagship scale
(90 µs -> 48 µs per FakeApiServer.create_binding with tuning; the same
effect Go servers get from GOGC tuning).  ``enable_daemon_gc_tuning``
raises the thresholds so collections amortize over real work; reference
counting still reclaims the non-cyclic majority immediately, and the API
objects are plain dataclasses with no reference cycles, so the delayed
cycle detection affects only genuinely cyclic garbage (rare here).

Opt out with TPU_SCHED_NO_GC_TUNING=1 (e.g. when embedding the scheduler
in a process whose GC cadence is owned elsewhere).
"""

from __future__ import annotations

import gc
import os

__all__ = ["enable_daemon_gc_tuning"]

_THRESHOLDS = (50_000, 20, 20)


def enable_daemon_gc_tuning() -> bool:
    """Raise the GC thresholds for daemon/throughput workloads; returns
    whether tuning was applied (False under the env opt-out)."""
    if os.environ.get("TPU_SCHED_NO_GC_TUNING"):
        return False
    gc.set_threshold(*_THRESHOLDS)
    return True
