"""DeltaEngine — plan/commit orchestration of the incremental cycle.

Every owned cycle the controller asks for a plan.  The answer is either a
``DeltaPlan`` — solve ONLY the dirty pods against the carried residual
tensors — or ``None``, which escalates to the classic full-wave cycle
(fresh capacity sweep, every eligible pod re-solved) followed by a state
rebuild.  Escalation happens only on the closed ``ESCALATION_REASONS``:

  cold              no SolveState yet (first owned cycle of a process)
  restore           checkpoint restore — never trust restored residuals
  takeover          leadership/shard-ownership change (another replica's
                    commits may predate our watch view of them)
  breaker-recovery  the API circuit breaker re-closed — the brownout may
                    have dropped watch evidence on the floor
  node-change       node set/order/content signature drift (capacity rows
                    cannot be remapped safely)
  vocab-change      a request names a resource column the packed vocabulary
                    lacks (full pack re-derives scales)
  closure-overflow  the invalidation closure grew past the threshold —
                    a full sweep is cheaper than incremental bookkeeping
  epoch-refresh     periodic paranoia full-wave (bounds the lifetime of any
                    undetected bookkeeping drift)
  mesh-rebind       a fleet takeover/resize rebound absorbed shards onto
                    this replica's device mesh — the carried residuals were
                    laid out for the old node slice, so the widened slice
                    re-solves from scratch (tpu_scheduler/fleet)

The shadow-solve parity gate (sim): on sampled delta cycles the controller
solves the FULL eligible set beside the delta path and the engine records
whether both placed exactly the same pod set — the
invariant-equivalence contract (placements may differ only within the
score tie-break freedom; the PLACED SET and the unschedulable set may not).
"""

from __future__ import annotations

import logging

from ..api.objects import full_name
from ..utils.tracing import span
from .index import DeltaIndex, blocking_nodes, verdict_constrained
from .state import SolveState, req64_of

logger = logging.getLogger("tpu_scheduler.delta")

__all__ = ["ESCALATION_REASONS", "DeltaPlan", "DeltaEngine"]

# The closed escalation vocabulary (drift-gated against the README
# "Incremental scheduling" catalogue by the DLTA analyze rule; producer
# coverage gated both directions by the PROT taxonomy below).
# protocol: taxonomy ESCALATION_REASONS producers=_escalate,invalidate scope=tpu_scheduler
ESCALATION_REASONS = (
    "cold",
    "restore",
    "takeover",
    "breaker-recovery",
    "node-change",
    "vocab-change",
    "closure-overflow",
    "epoch-refresh",
    "mesh-rebind",
)


class DeltaPlan:
    """One delta cycle's work order: the dirty pods to solve, the count of
    standing verdicts skipped, and the carried capacity pair the repack
    consumes instead of the O(bound-pods) sweep."""

    __slots__ = ("pods", "skipped", "alloc_used64", "retired")

    def __init__(self, pods: list, skipped: int, alloc_used64, retired: int):
        self.pods = pods
        self.skipped = skipped
        self.alloc_used64 = alloc_used64  # ([N_pad, R] i64, [N_pad, R] i64) or None
        self.retired = retired


class DeltaEngine:
    """Owns the SolveState + DeltaIndex and the escalation policy.  Written
    only by the controller's cycle loop (single-threaded); the HTTP debug
    thread reads GIL-atomic copies via ``stats()``."""

    # Closure-overflow threshold: a dirty set above max(OVERFLOW_MIN,
    # OVERFLOW_FRAC · total pods) means incremental bookkeeping is no longer
    # buying anything — rebuild wholesale.
    OVERFLOW_MIN = 512
    OVERFLOW_FRAC = 0.5
    # Per-verdict blocking-set budget (pod × node predicate probes per
    # commit): a mass-unschedulable cycle falls back to blocked=None (the
    # coarse any-free rule) instead of stalling the loop classifying it.
    BLOCKING_BUDGET = 200_000

    def __init__(self, metrics=None, epoch_refresh: int = 64):
        self.metrics = metrics
        self.epoch_refresh = int(epoch_refresh)
        self.index = DeltaIndex()
        self.state: SolveState | None = None
        self._invalid_reason: str | None = None  # forces the next plan full
        self._full_reason: str | None = None  # the reason the CURRENT cycle went full
        self._placements_since_plan = False
        self.generation = 0
        # Lifetime stats (served to the sim scorecard / bench / tests).
        self.delta_cycles = 0
        self.full_solve_reasons: dict[str, int] = {}
        self.skipped_total = 0
        self.dirty_sizes: list[int] = []
        self.shadow_checks = 0
        self.shadow_mismatches = 0
        self.shadow_skipped = 0

    # -- lifecycle ----------------------------------------------------------

    def attach(self, reflector) -> None:
        """Subscribe to the reflector's pod event stream (the watch-delta
        feed the DeltaIndex classifies).  Prefers the BATCH feed (one call
        per sync with the drained event list) over per-event dispatch; the
        per-event path survives for reflectors without the batch hook."""
        batch = getattr(reflector, "add_pod_batch_listener", None)
        if batch is not None:
            batch(self.index.on_pod_events)
        else:
            reflector.add_pod_listener(self.index.on_pod_event)

    def invalidate(self, reason: str) -> None:
        """Force the next plan to escalate (takeover, restore, breaker
        recovery).  The strongest pending reason wins nothing — first set
        sticks, which is enough: any escalation rebuilds everything."""
        if reason not in ESCALATION_REASONS:
            raise ValueError(f"unknown escalation reason {reason!r}")
        if self._invalid_reason is None:
            self._invalid_reason = reason

    def uncommit(self, pod_full: str) -> None:
        """A committed placement did not stick (requeue after an async bind
        failure, deferred-flush overflow): release its capacity so the
        ledger matches the API truth again."""
        if self.state is not None:
            self.state.release(pod_full)

    # -- plan ---------------------------------------------------------------

    def _escalate(self, reason: str):
        self._full_reason = reason
        self._invalid_reason = None
        return None

    # shape: (self: obj, snapshot: obj, pending: obj, pending_all: obj,
    #   packed: obj, node_sig: obj, preempting: bool) -> obj
    # hotpath: delta-plan
    def plan(self, snapshot, pending: list, pending_all: list, packed, node_sig, preempting: bool = False):
        """Classify this cycle: a DeltaPlan (solve the dirty set against
        carried residuals) or None (escalate to the full-wave path; the
        reason is recorded and counted at commit).

        ``preempting`` disables the verdict skip (every eligible pod stays
        dirty): the preemption pass retries exactly the pods the cycle
        marked unschedulable, and a PDB-blocked preemption must re-attempt
        as budgets thaw — a standing verdict would silently starve it.  The
        carried-capacity fast path still applies."""
        self._full_reason = None
        st = self.state
        if st is None:
            return self._escalate(self._invalid_reason or "cold")
        if self._invalid_reason is not None:
            return self._escalate(self._invalid_reason)
        if (
            packed is None
            or tuple(packed.node_names) != st.node_names
            or node_sig != st.node_sig
        ):
            return self._escalate("node-change")
        if packed.res_vocab != st.res_vocab or packed.res_scales != st.res_scales:
            return self._escalate("vocab-change")
        if st.delta_cycles_since_full >= self.epoch_refresh:
            return self._escalate("epoch-refresh")
        with span("index"):
            fold = self.index.fold(st, self.index.take())
        if not fold.ok:
            return self._escalate("vocab-change")
        with span("close"):
            retired = self.index.close(st, fold, self._placements_since_plan, pending_all)
            self._placements_since_plan = False
            standing = st.unsched
            if preempting:
                dirty = list(pending)
                skipped = 0
            else:
                dirty = [p for p in pending if full_name(p) not in standing]
                skipped = len(pending) - len(dirty)
        if len(dirty) > max(self.OVERFLOW_MIN, int(self.OVERFLOW_FRAC * len(snapshot.pods))):
            return self._escalate("closure-overflow")
        alloc_used = None
        if dirty:
            with span("repack"):
                # A dirty pod naming a resource column outside the carried
                # vocabulary is a full-pack event (the full path re-derives
                # scales); detect it here, where the padded sweep is skipped.
                for p in dirty:
                    if req64_of(p, st.res_vocab) is None:
                        return self._escalate("vocab-change")
                alloc_used = (st.alloc64, st.used64)
        return DeltaPlan(dirty, skipped, alloc_used, retired)

    # -- commit -------------------------------------------------------------

    # hotpath: delta-commit
    def commit(self, plan, snapshot, packed, node_sig, placed: list, unschedulable: list, pending_all: list, res_memo=None) -> None:
        """Fold the cycle's outcome back into the SolveState.  ``plan`` is
        the object this cycle ran under (None = the full-wave path ran, so
        the state rebuilds wholesale from the solved snapshot)."""
        if plan is None:
            reason = self._full_reason or "cold"
            self.full_solve_reasons[reason] = self.full_solve_reasons.get(reason, 0) + 1
            if self.metrics is not None:
                self.metrics.inc("scheduler_full_solves_total", labels={"reason": reason})
            self._rebuild(snapshot, packed, node_sig, placed, unschedulable, pending_all, res_memo)
            return
        st = self.state
        for pod, node in placed:
            req = req64_of(pod, st.res_vocab, res_memo)
            if req is None:
                # Should be unreachable (plan pre-checked the dirty set);
                # never poison the ledger — escalate instead.
                self.invalidate("vocab-change")
                continue
            st.commit(full_name(pod), node.name, req)
        if placed:
            self._placements_since_plan = True
        self._record_verdicts(st, snapshot, unschedulable, pending_all)
        st.delta_cycles_since_full += 1
        self.delta_cycles += 1
        self.skipped_total += plan.skipped
        self.dirty_sizes.append(len(plan.pods))
        if self.metrics is not None:
            self.metrics.inc("scheduler_delta_cycles_total")
            if plan.skipped:
                self.metrics.inc("scheduler_delta_skipped_pods_total", plan.skipped)
            self.metrics.observe("scheduler_delta_dirty_pods", float(len(plan.pods)))

    def _record_verdicts(self, st: SolveState, snapshot, unschedulable: list, pending_all: list) -> None:
        """Write this cycle's unschedulable verdicts into the ledger, each
        with its per-node blocking set (budgeted — beyond
        ``BLOCKING_BUDGET`` pod×node probes the rest record blocked=None
        and retire coarsely) and its constraint-entanglement flag."""
        if not unschedulable:
            return
        by_full = {full_name(p): p for p in pending_all}
        budget = self.BLOCKING_BUDGET
        n_nodes = len(snapshot.nodes)
        for pf in unschedulable:
            p = by_full.get(pf)
            if p is None or p.spec is None:
                continue  # vanished mid-cycle; the DELETE event owns it
            constrained = verdict_constrained(p)
            blocked = None
            if not constrained and n_nodes and budget >= n_nodes:
                budget -= n_nodes
                blocked = blocking_nodes(p, snapshot)
            st.unsched[pf] = (bool(p.spec.pod_affinity), p.spec.gang or None, blocked, constrained)

    def _rebuild(self, snapshot, packed, node_sig, placed: list, unschedulable: list, pending_all: list, res_memo) -> None:
        """Reset the SolveState from a freshly solved full-wave cycle: the
        capacity pair comes from the SAME exact sweep the pack ran
        (ops/pack._alloc_and_used64), placements re-enumerate from the
        snapshot plus this cycle's commits, and the verdict ledger restarts
        from this cycle's unschedulable set."""
        self.index.take()  # buffered events are already reflected in the snapshot
        self.generation += 1
        if packed is None or tuple(n.name for n in snapshot.nodes) != tuple(packed.node_names):
            # No packed axis to align to (an empty-pending escalation
            # cycle, or the cached pack predates node churn): stay cold —
            # the next packing cycle rebuilds against a fresh axis.
            self.state = None
            return
        from ..ops.pack import _alloc_and_used64

        alloc64, used64, row = _alloc_and_used64(
            snapshot, packed.padded_nodes, res_memo, packed.res_vocab
        )
        st = SolveState(
            node_names=tuple(packed.node_names),
            node_sig=node_sig,
            res_vocab=packed.res_vocab,
            res_scales=packed.res_scales,
            alloc64=alloc64,
            used64=used64,
            row=row,
            generation=self.generation,
        )
        for pod, node in snapshot.placed_pods():
            req = req64_of(pod, st.res_vocab, res_memo)
            if req is None:
                self.state = None  # resource outside the vocab: stay cold
                return
            # Capacity is already in used64 (the sweep above); ledger only.
            st.placements[full_name(pod)] = (st.row.get(node.name, -1), node.name, req)
        for pod, node in placed:
            req = req64_of(pod, st.res_vocab, res_memo)
            if req is not None:
                st.commit(full_name(pod), node.name, req)
        self._record_verdicts(st, snapshot, unschedulable, pending_all)
        self.state = st
        self._placements_since_plan = False

    # -- shadow parity ------------------------------------------------------

    def record_shadow(self, ok: bool | None, detail: str = "") -> None:
        """Record one shadow-solve comparison: True = parity held, False =
        the full solve placed a different pod set (a closure bug), None =
        the cycle was not comparable (bind failures / open breaker)."""
        if ok is None:
            self.shadow_skipped += 1
            return
        self.shadow_checks += 1
        if not ok:
            self.shadow_mismatches += 1
            if self.metrics is not None:
                self.metrics.inc("scheduler_delta_shadow_mismatches_total")
            logger.warning("delta shadow-solve parity MISMATCH: %s", detail)

    # -- introspection ------------------------------------------------------

    def stats(self) -> dict:
        """Lifetime engine stats (GIL-atomic copies — safe from any thread;
        consumed by the sim scorecard, bench, and tests)."""
        sizes = list(self.dirty_sizes)
        return {
            "enabled": True,
            "generation": self.generation,
            "valid": self.state is not None and self._invalid_reason is None,
            "delta_cycles": self.delta_cycles,
            "full_solves": sum(self.full_solve_reasons.values()),
            "full_solve_reasons": dict(sorted(self.full_solve_reasons.items())),
            "skipped_total": self.skipped_total,
            "standing_verdicts": len(self.state.unsched) if self.state is not None else 0,
            "dirty_sizes": sizes,
            "shadow_checks": self.shadow_checks,
            "shadow_mismatches": self.shadow_mismatches,
            "shadow_skipped": self.shadow_skipped,
        }
