"""SolveState — the solve's memory between cycles.

A full-wave cycle derives node residual capacity from scratch (an O(bound
pods) sweep in ``ops/pack._alloc_and_used64``) and re-solves every eligible
pending pod.  The SolveState keeps both across cycles:

  • ``alloc64``/``used64`` — the exact int64 (allocatable, committed-usage)
    tensors over the packed node axis, updated by O(deltas) scatter work per
    cycle; ``residual_avail`` turns them into the same conservative int32
    ``node_avail`` a fresh pack would compute (identical math —
    ``ops/pack._avail_i32`` — so delta and full cycles see the same
    capacities).
  • ``placements`` — every committed placement (bound, dispatched, or
    breaker-deferred) with its exact request vector, so a later watch DELETE
    frees precisely what the commit consumed and a flushed deferred bind can
    never commit twice.
  • ``unsched`` — the skipped-verdict ledger: pods the solve proved
    unschedulable, skipped on later cycles until the invalidation closure
    (delta/index.py) retires the proof.

Capacity semantics mirror ``_alloc_and_used64`` exactly: requests are raw
int64 (cpu millicores, memory bytes, extended raw), pods bound to unknown
nodes consume nothing we track, and a request naming a resource outside the
packed vocabulary is a full-pack event (the engine escalates).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..api.objects import Pod, total_pod_resources
from ..ops.pack import _avail_i32

__all__ = ["SolveState", "req64_of"]


# shape: (pod: obj, res_vocab: obj, res_memo: dict) -> obj
def req64_of(pod: Pod, res_vocab: tuple[str, ...], res_memo: dict | None = None):
    """The pod's exact request vector over ``res_vocab`` as [R] i64, or
    ``None`` when the pod names an extended resource outside the vocabulary
    (the caller must escalate — a new resource column is a full-pack
    event).  ``res_memo`` is the controller's id-keyed request memo
    (ops/pack semantics: identity-keyed with the object held)."""
    if res_memo is not None:
        hit = res_memo.get(id(pod))
        if hit is not None and hit[0] is pod:
            res = hit[1]
        else:
            res = total_pod_resources(pod)
            res_memo[id(pod)] = (pod, res)
    else:
        res = total_pod_resources(pod)
    out = np.zeros((len(res_vocab),), dtype=np.int64)
    out[0] = res.cpu
    out[1] = res.memory
    if res.extended:
        for name, v in res.extended.items():
            if not v:
                continue  # zero entries are vacuous, exactly as in fits_in
            try:
                out[res_vocab.index(name)] = v
            except ValueError:
                return None
    return out


# protocol: machine placement-ledger field=- init=absent
# protocol: states: absent | committed
# protocol: absent -> committed
# protocol: committed -> absent
# protocol: var used: 0..2 = 0
# protocol: action commit: absent -> committed effect used += 1
# protocol: env dup-commit: committed -> committed
# protocol: action release: committed -> absent effect used -= 1
# protocol: env dup-release: absent -> absent
# protocol: invariant flush-at-most-once: used <= 1
# protocol: invariant exact-accounting: state == absent implies used == 0
# protocol: invariant committed-counted: state == committed implies used == 1
@dataclass
class SolveState:
    """Persisted solve state, aligned to one packed node axis.

    Valid only while the node set/order (and therefore ``node_sig``) holds;
    any node-set change escalates to a full-wave rebuild rather than trying
    to remap rows.

    The ``# protocol:`` contract above models one pod's row in the
    ``placements`` ledger against duplicated deliveries (model-only: the
    state is ledger membership, not a field).  A deferred-bind flush and
    the watch event confirming our own POST both re-deliver ``commit``;
    the membership guard makes the duplicate a no-op (``dup-commit`` has
    no capacity effect), so MODL proves ``flush-at-most-once`` — capacity
    is consumed exactly once per committed pod and returned exactly once
    on release, whatever the delivery interleaving.
    """

    node_names: tuple[str, ...]
    node_sig: tuple
    res_vocab: tuple[str, ...]
    res_scales: tuple[int, ...]
    # Exact int64 capacity pair over the PADDED node axis ([N_pad, R]) —
    # the same layout ops/pack._alloc_and_used64 produces.
    alloc64: np.ndarray
    used64: np.ndarray
    # node name -> row in the padded axis.
    row: dict[str, int]
    # pod full name -> (node row or -1 for untracked nodes, node name,
    # [R] i64 request) for every committed placement.
    placements: dict[str, tuple[int, str, np.ndarray]] = field(default_factory=dict)
    # pod full name -> (has_pod_affinity, gang name or None, blocking node
    # set or None, constrained): the skipped-verdict ledger.  Membership
    # means "proven unschedulable and the proof still stands";
    # delta/index.py retires entries.  The BLOCKING SET is the pod's
    # node-locally-feasible node names — the only nodes where freed
    # capacity could cure a plain pod's verdict (None = unknown, treated
    # coarsely: any free retires).  ``constrained`` marks verdicts whose
    # feasibility entangles cross-node state (anti-affinity / pod-affinity
    # / spread / gang): a placed-pod deletion ANYWHERE can shift their
    # domain counts, so they always retire on any freed capacity.
    unsched: dict[str, tuple[bool, str | None, frozenset | None, bool]] = field(default_factory=dict)
    generation: int = 0
    delta_cycles_since_full: int = 0

    # shape: (self: obj) -> [N, R] i32
    def residual_avail(self) -> np.ndarray:
        """The carried ``node_avail`` tensor — identical to what a fresh
        ``_alloc_and_used64`` + ``_avail_i32`` pass over the same committed
        state would produce (same floor-divide conservatism)."""
        return _avail_i32(self.alloc64, self.used64, self.res_scales)

    # shape: (self: obj, pod_full: obj, node_name: obj, req64: [R] i64) -> bool
    def commit(self, pod_full: str, node_name: str, req64: np.ndarray) -> bool:
        """Record one placement and consume its capacity EXACTLY ONCE: a pod
        already in the ledger (e.g. a deferred bind being flushed, or a
        watch event confirming our own POST) is a no-op.  Returns True when
        the entry was new."""
        if pod_full in self.placements:
            return False
        r = self.row.get(node_name, -1)
        if r >= 0:
            self.used64[r] += req64
        self.placements[pod_full] = (r, node_name, req64)
        self.unsched.pop(pod_full, None)
        return True

    # shape: (self: obj, pod_full: obj) -> obj
    def release(self, pod_full: str):
        """Retire one placement, freeing its capacity (watch DELETE, requeue
        after a failed async bind, out-of-band rebind adjustments).  Returns
        the freed NODE NAME (the invalidation closure's per-node blocking
        key), "" for a placement on an untracked node (freed, but outside
        the packed axis — callers treat it coarsely), or None when there
        was nothing to free."""
        ent = self.placements.pop(pod_full, None)
        if ent is None:
            return None
        r, node, req64 = ent
        if r >= 0:
            self.used64[r] -= req64
            return node
        return ""
