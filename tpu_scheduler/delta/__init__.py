"""Incremental delta-scheduling engine — the steady-state fast path.

The controller's full cycle rebuilds and re-solves the whole pods×nodes
problem every tick even when only a handful of watch deltas arrived.  This
package makes the DELTA cycle the default and the full-wave solve the rare
escalation:

  • ``state.SolveState`` — solve state persisted ACROSS cycles: committed
    placements, per-node residual-capacity tensors (the exact int64
    alloc/used pair ``ops/pack._avail_i32`` consumes), and the
    skipped-verdict ledger (pods proven unschedulable whose proof still
    stands).
  • ``index.DeltaIndex`` — the watch-delta invalidation closure: raw
    reflector events classify into dirty pods, then the set CLOSES (freed
    capacity re-dirties capacity-blocked verdicts, gang membership keeps
    gangs all-or-nothing, constraint-carrier churn re-dirties constrained
    verdicts, fresh placements re-dirty positive pod-affinity seekers).
  • ``engine.DeltaEngine`` — plan/commit orchestration in the controller:
    packs only the dirty set against the carried residual tensors,
    escalates to a full-wave solve only on the closed
    ``ESCALATION_REASONS`` triggers, and (in the sim) shadow-solves sampled
    cycles to hold the delta path to invariant-equivalence with the full
    solve.
"""

from .engine import ESCALATION_REASONS, DeltaEngine, DeltaPlan
from .index import DeltaIndex
from .state import SolveState

__all__ = ["DeltaEngine", "DeltaPlan", "DeltaIndex", "SolveState", "ESCALATION_REASONS"]
