"""Candidate-node workspace compaction for delta cycles.

The PR-9 conflict filter gathers a round's accepted claimants into a
compact ``[A]`` workspace so the cell passes scale with the accepted count,
not the padded pod axis.  This is the node-axis analogue for the delta
cycle: nodes that cannot host even the SMALLEST dirty request on some axis
are infeasible for every dirty pod, so the solve's ``[P, N]`` sweeps can
drop their columns wholesale.

Soundness: the exclusion test is per-axis against the per-axis MINIMUM of
the dirty requests (a node below the cpu minimum fails ``req <= avail`` for
every dirty pod; a zero minimum excludes nothing on that axis), so the
feasible (pod, node) set is unchanged and the solve places the identical
POD SET — only the tie-break jitter (a function of the node column index)
may pick different winners among equal-score candidates, which is inside
the delta contract's documented tie-break freedom.

Applied only when it pays and cannot interact with cross-node state:
  • plain batches only (no packed constraints, no topology state — their
    domain tensors aggregate over the full node axis);
  • at least half the nodes must drop (a mostly-free cluster keeps the
    full axis and the solver's warm compile);
  • the compacted axis pads to a power-of-two bucket (>= node_block) so
    repeated saturated cycles reuse a handful of compiled shapes instead
    of recompiling per cycle.
"""

from __future__ import annotations

from dataclasses import replace

import numpy as np

from ..ops.pack import round_up

__all__ = ["compact_candidate_nodes"]


# shape: (n: int, node_block: int) -> int
# bucket: return
def _bucket(n: int, node_block: int) -> int:
    """Quantized padding for the compacted axis: next power of two at or
    above ``n``, floored at one node block — few distinct jit shapes."""
    size = max(int(node_block), 1)
    while size < n:
        size *= 2
    return round_up(size, node_block)


# shape: (avail: [N, R] i32, min_req: [R] i32, valid: [N] bool) -> [N] bool
def _candidate_mask(avail: np.ndarray, min_req: np.ndarray, valid: np.ndarray) -> np.ndarray:
    """Nodes that can host at least the smallest dirty request on BOTH
    fixed axes (cpu, memory) — a node below either minimum fails
    ``req <= avail`` for every dirty pod, so its column is dead weight."""
    return valid & (avail[:, 0] >= min_req[0]) & (avail[:, 1] >= min_req[1])


# shape: (packed: obj, node_block: int) -> obj
# bucket: n_pad
def compact_candidate_nodes(packed, node_block: int = 128):
    """Gather the candidate-node rows of every node-side tensor into a
    compact workspace (or return ``packed`` unchanged when compaction does
    not pay).  Candidates = valid nodes whose available cpu AND memory meet
    the per-axis minimum of the dirty requests."""
    if packed.constraints is not None or packed.topology is not None:
        return packed
    n_real = len(packed.node_names)
    if n_real == 0:
        return packed
    valid_req = packed.pod_req[packed.pod_valid]
    if valid_req.shape[0] == 0:
        return packed
    min_req = valid_req.min(axis=0)  # [R] i32, per-axis smallest dirty ask
    keep = _candidate_mask(
        packed.node_avail[:n_real], min_req, np.asarray(packed.node_valid[:n_real], dtype=bool)
    )
    idx = np.flatnonzero(keep)
    if len(idx) == 0 or len(idx) > n_real // 2:
        return packed  # nothing to drop, or not enough to pay for new shapes
    n_pad = _bucket(len(idx), node_block)
    out = {}
    for field in (
        "node_alloc",
        "node_avail",
        "node_labels",
        "node_taints",
        "node_aff",
        "node_valid",
        "node_taints_soft",
        "node_pref",
    ):
        arr = getattr(packed, field)
        gathered = arr[idx]
        pad_rows = n_pad - len(idx)
        if pad_rows:
            gathered = np.concatenate(
                [gathered, np.zeros((pad_rows,) + arr.shape[1:], dtype=arr.dtype)], axis=0
            )
        out[field] = gathered
    out["node_names"] = tuple(packed.node_names[i] for i in idx)
    return replace(packed, **out)
