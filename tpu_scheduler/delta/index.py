"""DeltaIndex — watch events → dirty pods, then the invalidation closure.

The reflector already streams copy-on-write by-node indexes and DELETE keys;
this module is the missing classification layer: each cycle's raw pod events
fold into the SolveState's capacity tensors and produce the DIRTY set — the
pods whose last verdict can no longer be trusted — which then CLOSES:

  • **capacity closure** — a deleted/retired placement frees capacity, so
    every skipped unschedulable verdict is retired (the freed room may fit
    them now).  Deliberately conservative: per-(pod, node) blocking sets
    would be a [P, N] bitmap; retiring all verdicts on any free is O(skipped)
    and can only cause extra re-solves, never a missed placement.
  • **constraint closure** — a deleted PENDING pod frees no capacity but may
    have been the anti-affinity carrier (or spread-domain occupant, via the
    ``sp_dom_sel``-projected cells) whose term blocked someone; verdicts
    retire the same way.
  • **gang closure** — gangs admit all-or-nothing, so a dirty member dirties
    the whole gang's verdicts (membership from the full pending set).
  • **pod-affinity closure** (engine commit) — fresh placements can SATISFY
    a positive pod-affinity seeker, the one way new placements ADD
    feasibility; verdicts flagged has_pod_affinity retire when anything
    placed.

Soundness argument (the shadow-solve parity gate holds it): with the node
set unchanged, a skipped pod's infeasibility can only be cured by freed
capacity, a removed constraint carrier, or a new positive-affinity match —
each of which retires the verdict above.  Everything else (new placements,
new pods) only ever REMOVES feasibility, which keeps an unschedulable
verdict true.
"""

from __future__ import annotations

from ..api.objects import full_name
from .state import SolveState, req64_of

__all__ = ["DeltaIndex", "FoldResult"]


class FoldResult:
    """One cycle's classification verdict."""

    __slots__ = ("ok", "freed", "carrier_deleted", "dirty")

    def __init__(self):
        self.ok = True  # False => escalate (vocabulary drift)
        self.freed = False  # any committed capacity was released
        self.carrier_deleted = False  # a pending pod (potential AA/spread carrier) vanished
        self.dirty: set[str] = set()  # pod full names whose verdict retired


def _pod_full(key) -> str:
    ns, name = key
    return f"{ns or 'default'}/{name}"


def _node_of(pod) -> str | None:
    return pod.spec.node_name if pod is not None and pod.spec is not None else None


class DeltaIndex:
    """Buffers raw reflector pod events between plans and folds them into a
    SolveState + dirty classification.  Registered as a reflector pod
    listener once per scheduler; the buffer drains at plan time (or is
    discarded by a full-wave rebuild, whose snapshot already reflects every
    buffered event)."""

    def __init__(self):
        self._events: list[tuple] = []

    def on_pod_event(self, key, prev, new) -> None:
        self._events.append((key, prev, new))

    def pending_events(self) -> int:
        return len(self._events)

    def take(self) -> list[tuple]:
        out, self._events = self._events, []
        return out

    # shape: (self: obj, state: obj, events: obj) -> obj
    def fold(self, state: SolveState, events: list[tuple]) -> FoldResult:
        """Fold one cycle's events into ``state`` (capacity bookkeeping) and
        classify the raw dirty set.  Exact-once accounting: confirmations of
        our own commits are no-ops; out-of-band binds and rebinds adjust by
        the difference; deletes free exactly what was committed."""
        out = FoldResult()
        for key, prev, new in events:
            pf = _pod_full(key)
            if new is None:  # DELETED
                if state.release(pf):
                    out.freed = True
                elif _node_of(prev) is None:
                    # A pending pod vanished: zero capacity change, but it
                    # may have carried the term/domain cell blocking a
                    # constrained verdict.
                    out.carrier_deleted = True
                state.unsched.pop(pf, None)
                continue
            node = _node_of(new)
            if node is not None:  # bound (created bound, or confirmed/out-of-band)
                req = req64_of(new, state.res_vocab)
                if req is None:
                    out.ok = False  # new resource column: full-pack event
                    return out
                ent = state.placements.get(pf)
                if ent is None:
                    state.commit(pf, node, req)
                elif ent[1] != node or (ent[2] != req).any():
                    # Re-bound elsewhere (409 winner) or request drift: move
                    # the mass; the old node's room frees.
                    state.release(pf)
                    state.commit(pf, node, req)
                    out.freed = True
                else:
                    state.unsched.pop(pf, None)  # confirmed; verdict moot
                continue
            # Pending (created or modified): its spec may have changed —
            # any standing verdict retires and the pod re-solves.
            out.dirty.add(pf)
            if state.release(pf):
                out.freed = True  # bound -> pending regression (defensive)
            state.unsched.pop(pf, None)
        return out

    # shape: (self: obj, state: obj, fold: obj, placements_made: bool,
    #   pending_all: obj) -> int
    def close(self, state: SolveState, fold: FoldResult, placements_made: bool, pending_all: list) -> int:
        """Close the dirty set over the SolveState's standing verdicts;
        returns the number of verdicts retired.  After this, "dirty" is
        simply "pending and without a standing verdict" — the engine picks
        the cycle's work straight off ``state.unsched`` membership."""
        retired = 0
        if fold.freed or fold.carrier_deleted:
            retired += len(state.unsched)
            state.unsched.clear()
        elif placements_made:
            # New placements only ADD feasibility through positive
            # pod-affinity — retire exactly those verdicts.
            for pf in [pf for pf, (has_pa, _g) in state.unsched.items() if has_pa]:
                del state.unsched[pf]
                retired += 1
        if not state.unsched:
            return retired
        # Gang closure: a dirty member (fresh pod, retired verdict) dirties
        # the whole gang — membership over the FULL pending set, so a member
        # in backoff still drags its gang-mates' verdicts with it when it
        # re-dirties.
        dirty_gangs: set[str] = set()
        standing = state.unsched
        for p in pending_all:
            g = p.spec.gang if p.spec is not None else None
            if g and full_name(p) not in standing:
                dirty_gangs.add(g)
        if dirty_gangs:
            for pf in [pf for pf, (_pa, g) in standing.items() if g in dirty_gangs]:
                del standing[pf]
                retired += 1
        return retired
