"""DeltaIndex — watch events → dirty pods, then the invalidation closure.

The reflector already streams copy-on-write by-node indexes and DELETE keys;
this module is the missing classification layer: each cycle's raw pod events
fold into the SolveState's capacity tensors and produce the DIRTY set — the
pods whose last verdict can no longer be trusted — which then CLOSES:

  • **capacity closure** — a deleted/retired placement frees capacity on a
    KNOWN node, so exactly the verdicts that node was blocking retire: a
    plain (constraint-free) pod's infeasibility is per-node-local
    predicates + capacity, so freed room on node X can only cure verdicts
    whose BLOCKING SET (node-locally-feasible nodes, computed at verdict
    time) contains X — churn on an unrelated node leaves them standing.
    Constrained verdicts (anti-affinity / pod-affinity / spread / gang)
    and verdicts without a blocking set (the budget ran out) keep the old
    coarse rule — any free retires — because a placed-pod deletion
    anywhere can shift their cross-node domain state.  Conservative
    either way: extra re-solves possible, missed placements never.
  • **constraint closure** — a deleted PENDING pod frees no capacity but may
    have been the anti-affinity carrier (or spread-domain occupant, via the
    ``sp_dom_sel``-projected cells) whose term blocked someone; verdicts
    retire the same way.
  • **gang closure** — gangs admit all-or-nothing, so a dirty member dirties
    the whole gang's verdicts (membership from the full pending set).
  • **pod-affinity closure** (engine commit) — fresh placements can SATISFY
    a positive pod-affinity seeker, the one way new placements ADD
    feasibility; verdicts flagged has_pod_affinity retire when anything
    placed.

Soundness argument (the shadow-solve parity gate holds it): with the node
set unchanged, a skipped pod's infeasibility can only be cured by freed
capacity, a removed constraint carrier, or a new positive-affinity match —
each of which retires the verdict above.  Everything else (new placements,
new pods) only ever REMOVES feasibility, which keeps an unschedulable
verdict true.
"""

from __future__ import annotations

import numpy as np

from ..api.objects import full_name
from .state import SolveState, req64_of

__all__ = ["DeltaIndex", "FoldResult"]


class FoldResult:
    """One cycle's classification verdict."""

    __slots__ = ("ok", "freed_nodes", "freed_unknown", "carrier_deleted", "dirty")

    def __init__(self):
        self.ok = True  # False => escalate (vocabulary drift)
        self.freed_nodes: set[str] = set()  # nodes where committed capacity released
        self.freed_unknown = False  # capacity freed on an untracked node (coarse)
        self.carrier_deleted = False  # a pending pod (potential AA/spread carrier) vanished
        self.dirty: set[str] = set()  # pod full names whose verdict retired

    @property
    def freed(self) -> bool:
        return self.freed_unknown or bool(self.freed_nodes)

    def note_release(self, node) -> None:
        """Fold one SolveState.release result: a node name is a per-node
        free, "" an untracked (coarse) free, None a no-op."""
        if node is None:
            return
        if node:
            self.freed_nodes.add(node)
        else:
            self.freed_unknown = True


def _pod_full(key) -> str:
    ns, name = key
    return f"{ns or 'default'}/{name}"


def _node_of(pod) -> str | None:
    return pod.spec.node_name if pod is not None and pod.spec is not None else None


# shape: (pod: obj, snapshot: obj) -> obj
def blocking_nodes(pod, snapshot) -> frozenset:
    """The pod's node-locally-feasible node names — the per-verdict
    BLOCKING SET: selector / taint / required-node-affinity / cordon
    exclusions are static for the SolveState's node signature (any node
    content change escalates to a full wave), so freed capacity on a node
    OUTSIDE this set can never cure the verdict."""
    from ..core.predicates import NODE_LOCAL_PREDICATES

    return frozenset(
        node.name
        for node in snapshot.nodes
        if all(pred(pod, node, snapshot) for _r, pred in NODE_LOCAL_PREDICATES)
    )


# shape: (pod: obj) -> bool
def verdict_constrained(pod) -> bool:
    """Cross-node-entangled verdicts (anti-affinity / pod-affinity /
    topology-spread / gang) always retire on any freed capacity — a
    placed-pod deletion anywhere can shift their domain counts."""
    s = pod.spec
    return s is not None and bool(s.anti_affinity or s.pod_affinity or s.topology_spread or s.gang)


class DeltaIndex:
    """Buffers raw reflector pod events between plans and folds them into a
    SolveState + dirty classification.  Registered as a reflector pod
    listener once per scheduler; the buffer drains at plan time (or is
    discarded by a full-wave rebuild, whose snapshot already reflects every
    buffered event)."""

    def __init__(self):
        self._events: list[tuple] = []

    def on_pod_event(self, key, prev, new) -> None:
        self._events.append((key, prev, new))

    def on_pod_events(self, events: list[tuple]) -> None:
        """Batch feed (reflector add_pod_batch_listener): one call per sync
        with the drained event list — replaces per-event dispatch cost with
        one list extend."""
        self._events.extend(events)

    def pending_events(self) -> int:
        return len(self._events)

    def take(self) -> list[tuple]:
        out, self._events = self._events, []
        return out

    # shape: (self: obj, state: obj, events: obj) -> obj
    def fold(self, state: SolveState, events: list[tuple]) -> FoldResult:
        """Fold one cycle's events into ``state`` — the VECTORIZED fast
        path.  When every event key is unique the per-key outcomes are
        independent, so the loop partitions once (deletes / binds /
        re-pendings), batches the set bookkeeping, and applies ALL capacity
        movement as two unbuffered scatters (``np.add.at``/``subtract.at``)
        instead of one tiny ndarray op per event — int64 adds are exact and
        order-free, so the result is bit-identical to the scalar fold
        (tests/test_fleet.py pins the parity).  Duplicate keys (several
        events for one pod in a cycle) and vocabulary misses fall back to
        the order-dependent scalar loop."""
        if len(events) < 8 or len({k for k, _p, _n in events}) != len(events):
            return self._fold_scalar(state, events)
        out = FoldResult()
        deletes: list[tuple] = []
        bounds: list[tuple] = []
        repend: list[str] = []
        for key, prev, new in events:
            pf = _pod_full(key)
            if new is None:
                deletes.append((pf, prev))
            else:
                node = _node_of(new)
                if node is None:
                    repend.append(pf)
                else:
                    req = req64_of(new, state.res_vocab)
                    if req is None:
                        # Vocabulary miss: the scalar loop owns the exact
                        # stop-at-first-miss semantics (no state touched yet).
                        return self._fold_scalar(state, events)
                    bounds.append((pf, node, req))
        placements = state.placements
        unsched = state.unsched
        sub_rows: list[int] = []
        sub_reqs: list = []
        add_rows: list[int] = []
        add_reqs: list = []
        for pf, prev in deletes:
            ent = placements.pop(pf, None)
            if ent is None:
                if _node_of(prev) is None:
                    out.carrier_deleted = True
            elif ent[0] >= 0:
                sub_rows.append(ent[0])
                sub_reqs.append(ent[2])
                out.freed_nodes.add(ent[1])
            else:
                out.freed_unknown = True
            unsched.pop(pf, None)
        out.dirty.update(repend)
        for pf in repend:
            ent = placements.pop(pf, None)
            if ent is not None:
                if ent[0] >= 0:
                    sub_rows.append(ent[0])
                    sub_reqs.append(ent[2])
                    out.freed_nodes.add(ent[1])
                else:
                    out.freed_unknown = True
            unsched.pop(pf, None)
        row_of = state.row
        for pf, node, req in bounds:
            ent = placements.get(pf)
            if ent is not None and ent[1] == node and not (ent[2] != req).any():
                unsched.pop(pf, None)  # confirmation of our own commit
                continue
            if ent is not None:  # rebind / request drift: move the mass
                placements.pop(pf)
                if ent[0] >= 0:
                    sub_rows.append(ent[0])
                    sub_reqs.append(ent[2])
                    out.freed_nodes.add(ent[1])
                else:
                    out.freed_unknown = True
            r = row_of.get(node, -1)
            if r >= 0:
                add_rows.append(r)
                add_reqs.append(req)
            placements[pf] = (r, node, req)
            unsched.pop(pf, None)
        if sub_rows:
            np.subtract.at(state.used64, np.asarray(sub_rows), np.stack(sub_reqs))
        if add_rows:
            np.add.at(state.used64, np.asarray(add_rows), np.stack(add_reqs))
        return out

    # shape: (self: obj, state: obj, events: obj) -> obj
    def _fold_scalar(self, state: SolveState, events: list[tuple]) -> FoldResult:
        """The original one-event-at-a-time fold.  Exact-once accounting:
        confirmations of our own commits are no-ops; out-of-band binds and
        rebinds adjust by the difference; deletes free exactly what was
        committed.  Order-dependent, so it also serves duplicate-key event
        runs (bind→delete→re-create of one pod in a single cycle)."""
        out = FoldResult()
        for key, prev, new in events:
            pf = _pod_full(key)
            if new is None:  # DELETED
                released = state.release(pf)
                if released is not None:
                    out.note_release(released)
                elif _node_of(prev) is None:
                    # A pending pod vanished: zero capacity change, but it
                    # may have carried the term/domain cell blocking a
                    # constrained verdict.
                    out.carrier_deleted = True
                state.unsched.pop(pf, None)
                continue
            node = _node_of(new)
            if node is not None:  # bound (created bound, or confirmed/out-of-band)
                req = req64_of(new, state.res_vocab)
                if req is None:
                    out.ok = False  # new resource column: full-pack event
                    return out
                ent = state.placements.get(pf)
                if ent is None:
                    state.commit(pf, node, req)
                elif ent[1] != node or (ent[2] != req).any():
                    # Re-bound elsewhere (409 winner) or request drift: move
                    # the mass; the old node's room frees.
                    out.note_release(state.release(pf))
                    state.commit(pf, node, req)
                else:
                    state.unsched.pop(pf, None)  # confirmed; verdict moot
                continue
            # Pending (created or modified): its spec may have changed —
            # any standing verdict retires and the pod re-solves.  A
            # bound -> pending transition (a rebalancer deschedule, or a
            # defensive regression) frees its node's room.
            out.dirty.add(pf)
            out.note_release(state.release(pf))
            state.unsched.pop(pf, None)
        return out

    # shape: (self: obj, state: obj, fold: obj, placements_made: bool,
    #   pending_all: obj) -> int
    def close(self, state: SolveState, fold: FoldResult, placements_made: bool, pending_all: list) -> int:
        """Close the dirty set over the SolveState's standing verdicts;
        returns the number of verdicts retired.  After this, "dirty" is
        simply "pending and without a standing verdict" — the engine picks
        the cycle's work straight off ``state.unsched`` membership."""
        retired = 0
        standing = state.unsched
        if fold.freed_unknown or fold.carrier_deleted:
            # Coarse path: capacity freed outside the packed axis, or a
            # potential constraint carrier vanished — retire everything.
            retired += len(standing)
            standing.clear()
        elif fold.freed_nodes:
            # Per-node capacity closure: freed room on node X retires a
            # PLAIN verdict only when X is in its blocking set (node-
            # locally feasible — a selector/taint-excluded node's churn
            # cannot cure it).  Constrained verdicts and budget-elided
            # blocking sets keep the coarse any-free rule.
            freed = fold.freed_nodes
            for pf in [
                pf
                for pf, (_pa, _g, blocked, constrained) in standing.items()
                if constrained or blocked is None or (blocked & freed)
            ]:
                del standing[pf]
                retired += 1
        if placements_made:
            # New placements only ADD feasibility through positive
            # pod-affinity — retire exactly those verdicts.
            for pf in [pf for pf, ent in standing.items() if ent[0]]:
                del standing[pf]
                retired += 1
        if not standing:
            return retired
        # Gang closure: a dirty member (fresh pod, retired verdict) dirties
        # the whole gang — membership over the FULL pending set, so a member
        # in backoff still drags its gang-mates' verdicts with it when it
        # re-dirties.
        dirty_gangs: set[str] = set()
        for p in pending_all:
            g = p.spec.gang if p.spec is not None else None
            if g and full_name(p) not in standing:
                dirty_gangs.add(g)
        if dirty_gangs:
            for pf in [pf for pf, ent in standing.items() if ent[1] in dirty_gangs]:
                del standing[pf]
                retired += 1
        return retired
