"""Scheduling profiles — named policy configurations.

The reference has a single hard-coded policy (random candidate, first-fit,
``src/main.rs:49-71``).  Here policy is data: score weights, commit-round
budget, block sizes.  Profiles are the "models" of this framework — the
flagship profile drives the benchmark cycle.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, fields, replace

import numpy as np

__all__ = ["PROFILE_SCHEMA_VERSION", "SchedulingProfile", "DEFAULT_PROFILE", "PROFILES"]

# Version of the tuned-profile JSON artifact (learn/profiles/*.json).
# ``from_file`` rejects any other version — a schema change must bump this
# and ship a migration, never silently reinterpret old artifacts.
PROFILE_SCHEMA_VERSION = 1

# The closed top-level schema of a profile artifact.  ``provenance`` is
# free-form (training config echo, held-out scores) and never read back
# into the profile; unknown top-level or profile keys are rejected.
ARTIFACT_FIELDS = ("schema_version", "profile", "provenance")


@dataclass(frozen=True)
class SchedulingProfile:
    name: str = "default"
    # Score weights (kube-scheduler defaults both at 1).
    least_requested_weight: float = 1.0
    balanced_allocation_weight: float = 1.0
    # Deterministic tie-spreading jitter (score points); spreads identical-
    # request pods across near-tied nodes so auction rounds don't herd.
    spread_jitter: float = 0.5
    # Auction-round safety cap (rounds needed ≈ max per-node contention).
    max_rounds: int = 32
    # Pods per choose-block (caps peak [block, N] tile memory on device).
    pod_block: int = 4096
    # Soft-term weights (ops/score.py):
    #   preferred_affinity_weight — scale of preferredDuringScheduling node-
    #     affinity points (pods declare 1-100 per term, kube-style);
    #   soft_taint_weight — score subtracted per untolerated PreferNoSchedule
    #     taint;
    #   topology_weight — penalty per matching pod already in the node's
    #     domain for ScheduleAnyway spread constraints (0 = off).
    preferred_affinity_weight: float = 1.0
    soft_taint_weight: float = 10.0
    topology_weight: float = 1.0
    # Rank-aware gang co-placement (topology/locality.py): score points per
    # interconnect-distance unit between a candidate node and the gang's
    # already-placed members, and the scale of the whole-gang-fits domain
    # bonus.  DELIBERATELY dominant over the ~200-point packing score at its
    # default: for tightly-coupled TPU workloads placement locality IS
    # communication performance, so a gang member prefers a worse-packed
    # node in the right slice over a better-packed node a rack away.
    # 0 disables the term (topology-blind gang scoring).
    gang_locality_weight: float = 64.0
    # Auction driver (backends/tpu.py): "monolithic" (and "auto", the
    # default) runs the whole auction as ONE jit program containing a
    # static size chain — the round body at quartering array sizes with
    # on-device result folding (ops/assign.py assign_cycle) — so the
    # per-round accept/compact/constraint cost shrinks with the active
    # count at zero host syncs.  "epochs" is the host-driven size-shrinking
    # driver (assign_cycle_epochs), kept for environments with cheap jit
    # boundaries; on the tunnelled chip each of its re-entries pays a
    # narrow-operand relayout (~200 ms at 100k pods) plus ~70 ms sync.
    # Measured on chip at 100k x 10k (scripts/bench_constrained.py +
    # /tmp experiments, round 4): unconstrained 0.25 s staged-monolithic
    # (epochs 2.35 s back in round 3); constrained 1.39 s staged-monolithic
    # vs 2.13 s epochs (and 15.7 s for the round-3 unstaged monolithic).
    driver: str = "auto"
    # Expert-parallel routing (parallel/routing.py): node label whose values
    # partition the cluster into per-pool scheduling shards; None = off.
    pool_key: str | None = None
    # Priority preemption (runtime/controller.py): pods the cycle could not
    # place for lack of RESOURCES may evict strictly-lower-priority victims
    # (kube PostFilter semantics).  Off by default: the synthetic cluster
    # has no controllers to recreate evicted pods.
    preemption: bool = False

    def __post_init__(self):
        if self.driver not in ("auto", "monolithic", "epochs"):
            raise ValueError(f"unknown driver {self.driver!r} (expected 'auto', 'monolithic' or 'epochs')")

    def weights(self) -> np.ndarray:
        return np.array(
            [
                self.least_requested_weight,
                self.balanced_allocation_weight,
                self.spread_jitter,
                self.preferred_affinity_weight,
                self.soft_taint_weight,
                self.topology_weight,
                self.gang_locality_weight,
            ],
            dtype=np.float32,
        )

    def with_(self, **kw) -> "SchedulingProfile":
        return replace(self, **kw)

    # -- JSON artifact round-trip (learn/profiles/*.json) -------------------

    def to_file(self, path: str, provenance: dict | None = None) -> None:
        """Write the versioned tuned-profile artifact.  Every dataclass
        field serializes (the artifact is the FULL policy, not a weight
        diff); ``provenance`` carries the training config echo and scores
        and is never read back into the profile."""
        # shape: (self: obj, path: str, provenance: obj) -> obj
        doc = {
            "schema_version": PROFILE_SCHEMA_VERSION,
            "profile": {f.name: getattr(self, f.name) for f in fields(self)},
            "provenance": provenance or {},
        }
        with open(path, "w") as fh:
            json.dump(doc, fh, indent=2, sort_keys=False)
            fh.write("\n")

    @classmethod
    def from_file(cls, path: str) -> "SchedulingProfile":
        """Load a tuned-profile artifact, strictly: wrong schema version,
        unknown top-level keys, or unknown profile keys all raise — a typo'd
        weight name must never silently fall back to the default."""
        # shape: (cls: obj, path: str) -> obj
        with open(path) as fh:
            doc = json.load(fh)
        if not isinstance(doc, dict):
            raise ValueError(f"profile artifact {path!r}: expected a JSON object")
        version = doc.get("schema_version")
        if version != PROFILE_SCHEMA_VERSION:
            raise ValueError(
                f"profile artifact {path!r}: schema_version {version!r} "
                f"(this build reads version {PROFILE_SCHEMA_VERSION})"
            )
        unknown = sorted(set(doc) - set(ARTIFACT_FIELDS))
        if unknown:
            raise ValueError(f"profile artifact {path!r}: unknown top-level keys {unknown}")
        payload = doc.get("profile")
        if not isinstance(payload, dict):
            raise ValueError(f"profile artifact {path!r}: missing 'profile' object")
        known = {f.name for f in fields(cls)}
        bad = sorted(set(payload) - known)
        if bad:
            raise ValueError(f"profile artifact {path!r}: unknown profile keys {bad}")
        return cls(**payload)


DEFAULT_PROFILE = SchedulingProfile()

PROFILES: dict[str, SchedulingProfile] = {
    "default": DEFAULT_PROFILE,
    # Bin-packing flavour: prefer fuller nodes (negative least-requested).
    "most-requested": SchedulingProfile(name="most-requested", least_requested_weight=-1.0),
    # Pure spread on balanced allocation.
    "balanced-only": SchedulingProfile(name="balanced-only", least_requested_weight=0.0),
    # Mass-admission flavour — the flagship benchmark profile: a wider
    # tie-break jitter spreads each auction round's claims across many more
    # near-tied nodes, cutting rounds (measured 13 -> 9 at 100k x 10k going
    # 8 -> 32) at the cost of ±32 points of scoring noise on the ~200-point
    # LeastRequested+Balanced scale.  Validity and capacity are exact
    # regardless (jitter only reorders feasible choices); for score-faithful
    # placement use the default profile (jitter 0.5).
    "throughput": SchedulingProfile(name="throughput", spread_jitter=32.0),
}
