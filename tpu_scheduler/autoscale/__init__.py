"""Closed-loop autoscaler — elastic capacity as a first-class actor.

PR 11's what-if (nodes_needed / nodes_removable) recommended; nothing
acted.  This package closes the loop from signal to capacity against a
deterministic simulated cloud provider: heterogeneous SKU catalogs with
hourly cost and per-SKU quotas, seeded provisioning latency (nodes join
through the ordinary FakeApiServer create-node path so the reflector and
delta engine see them organically), quota/stockout refusals, and
spot/preemptible reclaim with a short grace window.

Scale-up picks WHICH SKU by cost-aware FFD of the pending backlog over the
catalog, driven by the SLO-burn signal; scale-down routes through the
rebalancer's drain protocol (unbind → cordon → provider delete) with
reserve hysteresis against the rebalancer's drained-node parking so the
two subsystems never fight.  The sim scores it all on a pass-gated
"elasticity" scorecard block: a joint cost+SLO objective, scale decisions
and provisioning lag, and a reclaim-orphan count that is REQUIRED zero.

Modules:
  provider.py   — SimCloudProvider: the deterministic cloud (catalog,
                  quotas, provisioning queue, reclaim schedule, cost ledger)
  policy.py     — AutoscaleConfig, the closed skip taxonomy, the
                  cost-aware catalog FFD (pack_catalog), the throttle
  controller.py — Autoscaler: cadence + breaker/cooldown throttles, the
                  scale-up / scale-down tick, inline and background modes
"""

from .controller import Autoscaler
from .policy import SKIP_REASONS, AutoscaleConfig, pack_catalog
from .provider import (
    DEFAULT_CATALOG,
    PROVIDER_SKU_LABEL,
    InstanceSKU,
    ProviderError,
    QuotaExceeded,
    SimCloudProvider,
    Stockout,
    load_catalog,
)

__all__ = [
    "SKIP_REASONS",
    "DEFAULT_CATALOG",
    "PROVIDER_SKU_LABEL",
    "Autoscaler",
    "AutoscaleConfig",
    "InstanceSKU",
    "ProviderError",
    "QuotaExceeded",
    "SimCloudProvider",
    "Stockout",
    "load_catalog",
    "pack_catalog",
]
