"""Deterministic simulated cloud provider — the capacity side of the loop.

The same seeded-rng + VirtualClock discipline as ``sim/chaos.py``: every
draw (provision jitter, stockout, spot reclaim time) comes from ONE
dedicated rng in call order, calls happen only from the autoscaler's
cadence-gated tick (deterministic control flow), so a record→replay re-run
re-derives the identical provisioning schedule bit-identically — provider
node adds/deletes are deliberately NOT in the trace.

The lifecycle per node: ``request`` (quota + stockout checked, jittered
ready time drawn) → provisioning → ready (the node joins via the ordinary
``FakeApiServer`` create-node path, so the reflector/delta engine see it
organically) → optionally reclaiming (spot notice cordons the node, a
short grace later the provider force-unbinds survivors and deletes it) →
deleted.  Force-unbinds go through the chaos shim's faultable
``unbind_pod`` — a failed POST is retried next pump and a node is NEVER
deleted while a pod is still bound to it (the zero-orphan guarantee).

The cost ledger prices every node-interval (virtual seconds, per-SKU
hourly cost) — the cost integral of the ELASTIC capacity, the cost half of
the scorecard's joint objective.
"""

from __future__ import annotations

import http.client
import json
import random
from dataclasses import dataclass, replace as dc_replace

from ..runtime.fake_api import ApiError
from ..testing import make_node

__all__ = [
    "PROVIDER_SKU_LABEL",
    "InstanceSKU",
    "DEFAULT_CATALOG",
    "ProviderError",
    "QuotaExceeded",
    "Stockout",
    "SimCloudProvider",
    "load_catalog",
]

# Node-label marker on provider-provisioned nodes: names the SKU, survives
# crashes, and distinguishes elastic capacity from the scenario's base
# fleet — only labeled nodes are ever scale-down candidates.
PROVIDER_SKU_LABEL = "autoscale.tpu-scheduler/sku"


class ProviderError(Exception):
    """Base class for simulated provider failures."""


class QuotaExceeded(ProviderError):
    """The SKU's (or the account's) concurrent-node quota is exhausted."""


class Stockout(ProviderError):
    """The provider had no capacity for the SKU right now (seeded draw)."""


@dataclass(frozen=True)
class InstanceSKU:
    """One catalog entry: a purchasable node shape (catalogued in the
    README "Autoscaling & elasticity" section, drift-gated by ELAS)."""

    name: str
    cpu: int  # cores
    mem_gi: int  # GiB
    hourly_cost: float  # $ per node-hour (virtual hours)
    quota: int = 0  # max concurrent nodes of this SKU (0 = unbounded)
    provision_s: float = 8.0  # base provisioning latency (virtual seconds)
    provision_jitter_s: float = 4.0  # + uniform(0, jitter) per request
    stockout_rate: float = 0.0  # probability a request stockouts (per draw)
    spot: bool = False  # preemptible: eligible for provider reclaim
    ext: tuple[tuple[str, int], ...] = ()  # extended resources (key, count)


# The default catalog mirrors the workload generator's NODE_SHAPES plus one
# cheap preemptible shape — cost-aware FFD picks spot first when the
# scenario lets it (reclaim risk is the scenario's knob, not the SKU's).
DEFAULT_CATALOG = (
    InstanceSKU(name="std-8", cpu=8, mem_gi=32, hourly_cost=2.4, provision_s=6.0, provision_jitter_s=3.0),
    InstanceSKU(name="std-16", cpu=16, mem_gi=64, hourly_cost=4.8, provision_s=8.0, provision_jitter_s=4.0),
    InstanceSKU(name="std-32", cpu=32, mem_gi=128, hourly_cost=9.6, provision_s=12.0, provision_jitter_s=5.0),
    InstanceSKU(name="spot-16", cpu=16, mem_gi=64, hourly_cost=1.4, spot=True, provision_s=5.0, provision_jitter_s=2.0),
)

_ZONES = ("zone-a", "zone-b", "zone-c", "zone-d")  # workload.py's zones


# shape: (path: str) -> obj
def load_catalog(path: str) -> tuple[InstanceSKU, ...]:
    """Parse a ``--catalog-file`` JSON list of SKU dicts (field names match
    ``InstanceSKU``; ``ext`` may be a {resource: count} object)."""
    with open(path, encoding="utf-8") as f:
        raw = json.load(f)
    if not isinstance(raw, list) or not raw:
        raise ValueError(f"catalog file {path!r} must hold a non-empty JSON list of SKU objects")
    skus = []
    for entry in raw:
        ext = entry.pop("ext", None)
        if isinstance(ext, dict):
            entry["ext"] = tuple(sorted((k, int(v)) for k, v in ext.items()))
        elif ext is not None:
            entry["ext"] = tuple(tuple(e) for e in ext)
        skus.append(InstanceSKU(**entry))
    names = [s.name for s in skus]
    if len(set(names)) != len(names):
        raise ValueError(f"catalog file {path!r} repeats a SKU name")
    return tuple(skus)


# protocol: machine provider-node field=state init=provisioning
# protocol: states: provisioning | ready | reclaiming | deleted
# protocol: provisioning -> ready | deleted
# protocol: ready -> reclaiming | deleted
# protocol: reclaiming -> deleted
# protocol: var pods: 0..2 = 0
# protocol: action join: provisioning -> ready
# protocol: env bind: ready -> ready effect pods += 1
# protocol: env notice: ready -> reclaiming
# protocol: env bind-raced: reclaiming -> reclaiming effect pods += 1
# protocol: action unbind: reclaiming -> reclaiming requires pods >= 1 effect pods -= 1
# protocol: action kill: reclaiming -> deleted requires pods == 0
# protocol: action delete: ready -> deleted requires pods == 0
# protocol: action delete-pending: provisioning -> deleted
# protocol: invariant delete-only-when-empty: state == deleted implies pods == 0
# protocol: progress reclaim-completes: state == reclaiming
class SimCloudProvider:
    """The deterministic cloud: catalog, quotas, provisioning queue, spot
    reclaim schedule, and the node-hour cost ledger.

    The ``# protocol:`` contract above models one provider node's
    lifecycle composed with the scheduler environment: ``bind`` is a pod
    landing on the node (it keeps landing right through the reclaim grace
    — ``bind-raced`` is the bind that slips in under ``_kill``'s unbind
    loop), ``notice`` is the spot reclaim condemning the node, and both
    delete paths (``kill`` at the reclaim deadline, ``delete`` at
    scale-down) carry the structural guard the docstrings promise: a node
    is deleted only when verifiably empty — MODL proves
    ``delete-only-when-empty`` holds in every reachable composite state,
    and that a reclaiming node can always make progress (unbind until
    empty, then kill).

    ONE instance per cluster (shared across sharded replicas — a shard-0
    takeover inherits in-flight provisions and reclaim deadlines).  All
    mutation happens from the owning tick's thread; debug readers take
    GIL-atomic copies via ``stats()``."""

    def __init__(
        self,
        api,
        clock,
        rng: random.Random | None = None,
        catalog: tuple[InstanceSKU, ...] = DEFAULT_CATALOG,
        total_quota: int = 0,
        reclaim_rate: float = 0.0,
        reclaim_grace_s: float = 5.0,
    ):
        if not catalog:
            raise ValueError("SimCloudProvider needs a non-empty SKU catalog")
        self.api = api  # the chaos shim in the sim — unbinds stay faultable
        self.clock = clock
        self.rng = rng or random.Random(0)
        self.catalog = tuple(catalog)
        self.by_name = {s.name: s for s in self.catalog}
        if len(self.by_name) != len(self.catalog):
            raise ValueError("catalog repeats a SKU name")
        self.total_quota = int(total_quota)  # account-wide cap (0 = unbounded)
        self.reclaim_rate = float(reclaim_rate)  # spot reclaims per virtual second
        self.reclaim_grace_s = float(reclaim_grace_s)
        # One dict per requested node, in request order (the deterministic
        # iteration order of every pump): name, sku, requested_at, ready_at,
        # joined_at, reclaim_at, kill_at, deleted_at, state.
        self.records: list[dict] = []
        self._by_node: dict[str, dict] = {}
        self._seq = 0
        self.quota_errors = 0
        self.stockout_errors = 0
        self.reclaim_notices = 0
        self.reclaimed = 0
        # Pod full names the provider force-unbound at reclaim deadlines —
        # the scorecard's reclaim-orphan evidence (ordered, append-only).
        self.reclaim_unbound: list[str] = []

    # -- accounting ---------------------------------------------------------

    def _active(self, sku_name: str | None = None) -> int:
        return sum(
            1
            for rec in self.records
            if rec["state"] != "deleted" and (sku_name is None or rec["sku"] == sku_name)
        )

    # shape: (self: obj) -> dict
    def quota_left(self) -> dict:
        """Remaining request headroom per SKU (None = unbounded) — what the
        catalog FFD plans against so a plan never asks past a quota."""
        account = None if self.total_quota <= 0 else max(0, self.total_quota - self._active())
        out: dict = {}
        for sku in self.catalog:
            per = None if sku.quota <= 0 else max(0, sku.quota - self._active(sku.name))
            if per is None:
                out[sku.name] = account
            elif account is None:
                out[sku.name] = per
            else:
                out[sku.name] = min(per, account)
        return out

    # shape: (self: obj) -> int
    def pending_provisions(self) -> int:
        return sum(1 for rec in self.records if rec["state"] == "provisioning")

    # shape: (self: obj) -> dict
    def ready_nodes(self) -> dict:
        """Live provider-owned nodes (name -> SKU name), excluding ones a
        reclaim notice already condemned — the scale-down candidate set."""
        return {rec["name"]: rec["sku"] for rec in self.records if rec["state"] == "ready"}

    # -- the provider API ---------------------------------------------------

    # shape: (self: obj, sku_name: str, now: float) -> str
    def request(self, sku_name: str, now: float) -> str:
        """Ask for one node of the SKU.  Raises ``QuotaExceeded`` (checked
        first, no draw) or ``Stockout`` (one seeded draw); otherwise draws
        the jittered ready time (+ the reclaim time for spot shapes under a
        reclaim regime) and queues the provision."""
        sku = self.by_name.get(sku_name)
        if sku is None:
            raise ProviderError(f"unknown SKU {sku_name!r}")
        if sku.quota > 0 and self._active(sku_name) >= sku.quota:
            self.quota_errors += 1
            raise QuotaExceeded(f"SKU {sku_name} quota ({sku.quota}) exhausted")
        if self.total_quota > 0 and self._active() >= self.total_quota:
            self.quota_errors += 1
            raise QuotaExceeded(f"account quota ({self.total_quota}) exhausted")
        if sku.stockout_rate > 0 and self.rng.random() < sku.stockout_rate:
            self.stockout_errors += 1
            raise Stockout(f"SKU {sku_name} out of capacity")
        name = f"auto-{sku_name}-{self._seq}"
        zone = _ZONES[self._seq % len(_ZONES)]
        self._seq += 1
        ready_at = now + sku.provision_s
        if sku.provision_jitter_s > 0:
            ready_at += self.rng.uniform(0.0, sku.provision_jitter_s)
        reclaim_at = None
        if sku.spot and self.reclaim_rate > 0:
            reclaim_at = ready_at + self.rng.expovariate(self.reclaim_rate)
        rec = {
            "name": name,
            "sku": sku_name,
            "zone": zone,
            "requested_at": round(now, 9),
            "ready_at": round(ready_at, 9),
            "joined_at": None,
            "reclaim_at": round(reclaim_at, 9) if reclaim_at is not None else None,
            "kill_at": None,
            "deleted_at": None,
            "state": "provisioning",
        }
        self.records.append(rec)
        self._by_node[name] = rec
        return name

    def _live_node(self, name: str):
        for n in self.api.list_nodes():
            if n.name == name:
                return n
        return None

    def _cordon(self, name: str) -> bool:
        """Mark the node unschedulable in place (the reclaim NOTICE) so the
        scheduler stops placing onto capacity the provider condemned."""
        node = self._live_node(name)
        if node is None:
            return False
        from ..api.objects import NodeSpec

        spec = node.spec if node.spec is not None else NodeSpec()
        try:
            self.api.update_node(dc_replace(node, spec=dc_replace(spec, unschedulable=True)))
        except (ApiError, OSError, http.client.HTTPException):
            return False  # retried next pump — the deadline still stands
        return True

    # shape: (self: obj, now: float) -> dict
    def pump(self, now: float) -> dict:
        """Advance every in-flight lifecycle to ``now`` (called every tick,
        cadence or not): join ready provisions via the ordinary create-node
        path, issue due reclaim notices (cordon), and past each grace
        deadline force-unbind survivors then delete the empty node."""
        out = {"joined": 0, "reclaim_notices": 0, "reclaim_kills": 0, "reclaim_unbinds": 0}
        for rec in self.records:
            if rec["state"] == "provisioning" and rec["ready_at"] <= now:
                sku = self.by_name[rec["sku"]]
                self.api.create_node(
                    make_node(
                        rec["name"],
                        cpu=sku.cpu,
                        memory=f"{sku.mem_gi}Gi",
                        labels={"zone": rec["zone"], "name": rec["name"], PROVIDER_SKU_LABEL: sku.name},
                        extended=dict(sku.ext) if sku.ext else None,
                    )
                )
                rec["state"] = "ready"
                rec["joined_at"] = round(now, 9)
                out["joined"] += 1
            if rec["state"] == "ready" and rec["reclaim_at"] is not None and now >= rec["reclaim_at"]:
                self._cordon(rec["name"])  # best effort — the deadline rules
                rec["state"] = "reclaiming"
                rec["kill_at"] = round(now + self.reclaim_grace_s, 9)
                self.reclaim_notices += 1
                out["reclaim_notices"] += 1
            if rec["state"] == "reclaiming" and now >= rec["kill_at"]:
                if self._kill(rec, out):
                    rec["state"] = "deleted"
                    rec["deleted_at"] = round(now, 9)
                    self.reclaimed += 1
                    out["reclaim_kills"] += 1
        return out

    def _kill(self, rec: dict, out: dict) -> bool:
        """The reclaim deadline: force-unbind every surviving pod through
        the (faultable) unbind path, then delete the node ONLY once it is
        verifiably empty.  A failed unbind aborts — retried next pump, so a
        chaos-injected 500 can delay a reclaim but never orphan a pod."""
        from ..api.objects import full_name

        name = rec["name"]
        for pod in sorted(self.api.list_pods(f"spec.nodeName={name}"), key=lambda p: p.metadata.name):
            try:
                self.api.unbind_pod(pod.metadata.namespace or "default", pod.metadata.name, expect_node=name)
            except (ApiError, OSError, http.client.HTTPException):
                return False
            self.reclaim_unbound.append(full_name(pod))
            out["reclaim_unbinds"] += 1
        if self.api.list_pods(f"spec.nodeName={name}"):
            return False  # a bind landed under us — never delete over a pod
        self.api.delete_node(name)
        return True

    # shape: (self: obj, name: str, now: float) -> bool
    def delete(self, name: str, now: float) -> bool:
        """Scale-down delete of one provider-owned node.  Refuses (False)
        unless the node is verifiably empty — the drain protocol must have
        emptied it first; the zero-orphan guarantee is structural."""
        rec = self._by_node.get(name)
        if rec is None or rec["state"] == "deleted":
            return False
        if self.api.list_pods(f"spec.nodeName={name}"):
            return False
        if rec["state"] != "provisioning":
            self.api.delete_node(name)
        rec["state"] = "deleted"
        rec["deleted_at"] = round(now, 9)
        return True

    # -- evidence -----------------------------------------------------------

    # shape: (self: obj) -> obj
    def provision_lags(self) -> list:
        """Virtual request→join latency per landed node, in join order."""
        return [
            round(rec["joined_at"] - rec["requested_at"], 9)
            for rec in self.records
            if rec["joined_at"] is not None
        ]

    # shape: (self: obj, end_t: float) -> float
    def cost_node_hours(self, end_t: float) -> float:
        """The cost integral: Σ hourly_cost × (lifetime virtual hours) over
        every node that ever joined (still-live nodes price to ``end_t``)."""
        total = 0.0
        for rec in self.records:
            if rec["joined_at"] is None:
                continue
            until = rec["deleted_at"] if rec["deleted_at"] is not None else end_t
            total += self.by_name[rec["sku"]].hourly_cost * max(0.0, until - rec["joined_at"]) / 3600.0
        return round(total, 9)

    # shape: (self: obj) -> dict
    def stats(self) -> dict:
        """Lifetime counters + per-SKU landed census (strictly virtual-time
        / control-flow quantities — scorecard-safe)."""
        skus: dict[str, int] = {}
        for rec in self.records:
            if rec["joined_at"] is not None:
                skus[rec["sku"]] = skus.get(rec["sku"], 0) + 1
        return {
            "requested": len(self.records),
            "pending_provisions": self.pending_provisions(),
            "ready": sum(1 for r in self.records if r["state"] in ("ready", "reclaiming")),
            "deleted": sum(1 for r in self.records if r["state"] == "deleted"),
            "skus": dict(sorted(skus.items())),
            "quota_errors": self.quota_errors,
            "stockout_errors": self.stockout_errors,
            "reclaim_notices": self.reclaim_notices,
            "reclaimed": self.reclaimed,
            "reclaim_unbound": len(self.reclaim_unbound),
        }
