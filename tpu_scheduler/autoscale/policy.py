"""Autoscale policy — recommendation → action, with a closed skip taxonomy.

Scale-up picks WHICH SKU by cost-aware first-fit-decreasing of the pending
backlog's overflow over the provider catalog (``pack_catalog`` — the
whatif overflow-packing generalized to shape choice): open one
hypothetical node at a time, each time choosing the SKU that minimizes
hourly cost per overflow pod absorbed (ties broken by absolute cost, then
name), bounded by the provider's remaining quota.  The trigger is the PR 8
SLO-burn signal: overflow alone waits; overflow past ``burn_trigger``
buys.

Scale-down routes through the PR 11 drain protocol and ONLY ever deletes
provider-owned (elastic) nodes; the base fleet is never shrunk.  The
``reserve`` knob is the hysteresis against the rebalancer: the
rebalancer's drained-and-parked nodes count toward the same warm-headroom
reserve, so when the defragmenter is already holding capacity aside the
autoscaler skips (``reserve``) instead of deleting its own empties — the
two subsystems never fight over the same headroom.

Every tick that declines to act reports exactly one reason from
``SKIP_REASONS`` (rebalancer-style closed taxonomy, README-catalogued,
drift-gated by ELAS):

- ``breaker-open``: the API breaker is not closed; provider calls stand down.
- ``cooldown``: the hysteresis window from a recent scale action is open.
- ``inflight``: requested provisions are still landing; buying more would
  double-count the backlog.
- ``quota``: the provider refused every useful SKU on quota.
- ``stockout``: the provider had no capacity for the chosen SKU.
- ``no-demand``: no unplaceable backlog past the burn trigger and no
  scale-down candidate — the steady state.
- ``reserve``: removable empties are retained as warm headroom (counting
  the rebalancer's drained reserve — the anti-thrash hysteresis).
- ``not-empty``: the best scale-down candidate still hosts more pods than
  the drain limit, or its pods fit nowhere else.
- ``unbind-failed``: a drain unbind POST failed; the candidate survives
  untouched.
- ``api-error``: an unexpected provider/API failure; the tick stands down.
"""

from __future__ import annotations

from dataclasses import dataclass

__all__ = ["SKIP_REASONS", "AutoscaleConfig", "pack_catalog", "throttle_reason"]

# protocol: taxonomy SKIP_REASONS producers=_skip,throttle_reason scope=tpu_scheduler/autoscale
SKIP_REASONS = (
    "breaker-open",
    "cooldown",
    "inflight",
    "quota",
    "stockout",
    "no-demand",
    "reserve",
    "not-empty",
    "unbind-failed",
    "api-error",
)


@dataclass(frozen=True)
class AutoscaleConfig:
    """Autoscaler knobs (README-catalogued, drift-gated by ELAS)."""

    every: int = 2  # cadence: act every Nth scheduler cycle
    burn_trigger: float = 0.02  # min SLO-burn before overflow buys capacity
    max_per_tick: int = 8  # provision requests / deletes issued per tick
    cooldown: int = 4  # ticks of hysteresis after any scale action
    reserve: int = 1  # warm nodes retained (drained + empty elastic count)
    drain_max_pods: int = 4  # max pods unbound to free a scale-down candidate
    background: bool = False  # plan on a worker thread (daemon mode)


# shape: (breaker_mode: str, cooldown_left: int) -> obj
def throttle_reason(breaker_mode: str, cooldown_left: int):
    """The most-urgent stand-down reason before any planning happens, or
    None when the tick may proceed (mirrors the rebalancer's throttle)."""
    if breaker_mode != "closed":
        return "breaker-open"
    if cooldown_left > 0:
        return "cooldown"
    return None


# shape: (overflow: obj, catalog: obj, quota_left: obj) -> obj
def pack_catalog(overflow, catalog, quota_left=None) -> tuple:
    """Cost-aware FFD of the overflow backlog over a heterogeneous catalog.

    ``overflow`` is a list of ``(cpu_millicores, memory_bytes)`` requests
    (the whatif overflow, any order); ``quota_left`` maps SKU name to
    remaining request headroom (None = unbounded).  Opens one hypothetical
    node per round, picking the SKU minimizing hourly_cost per pod it
    absorbs (ties by cost, then name).  Returns ``(plan, unplaceable)``:
    a {sku_name: count} dict and the count of requests no SKU can hold.
    Deterministic: exact ints, sorted orders, no rng."""
    plan: dict[str, int] = {}
    remaining = sorted(overflow, key=lambda r: (-max(r[0], r[1]), r))
    skus = sorted(catalog, key=lambda s: (s.hourly_cost, s.name))
    while remaining:
        best = None
        for sku in skus:
            left = None if quota_left is None else quota_left.get(sku.name)
            if left is not None and plan.get(sku.name, 0) >= left:
                continue
            cap_cpu = sku.cpu * 1000
            cap_mem = sku.mem_gi << 30
            take = []
            for i, (cpu, mem) in enumerate(remaining):
                if cap_cpu >= cpu and cap_mem >= mem:
                    cap_cpu -= cpu
                    cap_mem -= mem
                    take.append(i)
            if not take:
                continue
            key = (sku.hourly_cost / len(take), sku.hourly_cost, sku.name)
            if best is None or key < best[0]:
                best = (key, sku.name, take)
        if best is None:
            break  # nothing left fits any purchasable SKU
        _key, name, take = best
        plan[name] = plan.get(name, 0) + 1
        taken = set(take)
        remaining = [r for i, r in enumerate(remaining) if i not in taken]
    return dict(sorted(plan.items())), len(remaining)
