"""Autoscaler — the elastic-capacity control loop.

One ``tick`` per scheduler cycle, run AFTER the rebalancer's so the
defragmenter's drains are visible before any capacity decision.  The
provider ``pump`` (provision joins, reclaim notices, grace-deadline kills)
runs EVERY tick — lifecycle latency must not quantize to the decision
cadence — while decisions themselves are cadence-gated, breaker-gated, and
cooldown-damped.  In sharded mode only the shard-0 owner ticks (the caller
gates), and in daemon mode (``AutoscaleConfig.background``) the catalog
what-if plans on a worker thread against the immutable snapshot view.

The scale-up path: whatif overflow → cost-aware SKU FFD (``pack_catalog``)
→ provider requests, at most ``max_per_tick``, only past the SLO-burn
trigger, and never while earlier provisions are still landing
(``inflight`` — buying again would double-count the same backlog).

The scale-down path (PR 11 drain protocol, elastic nodes ONLY): prefer
empty provider nodes beyond the warm ``reserve`` (the rebalancer's
drained-and-parked base nodes count toward the same reserve — the
hysteresis that keeps the two subsystems from fighting); a lightly-loaded
candidate is drained first — per-pod breaker-gated CAS unbinds, then
cordon, then the provider delete — and only when its pods provably fit
elsewhere, so a scale-down can never strand demand or orphan a pod.

Crash safety mirrors the rebalancer: no autoscaler-private durable state.
A crash between unbinds leaves pods Pending (the normal scheduling path
owns them); a crash between request and join loses nothing — the provider
record is the ledger and the next owner's pump joins the node.
"""

from __future__ import annotations

import threading
import time

from ..utils.tracing import span
from .policy import SKIP_REASONS, AutoscaleConfig, pack_catalog, throttle_reason
from .provider import ProviderError, QuotaExceeded, Stockout

__all__ = ["Autoscaler"]


class Autoscaler:
    """Owns the cadence, throttles, cooldown, and lifetime stats.  Written
    only by the owning scheduler's cycle loop; the HTTP debug thread reads
    GIL-atomic copies via ``stats()``."""

    def __init__(self, config: AutoscaleConfig | None = None, provider=None, metrics=None):
        if provider is None:
            raise ValueError("Autoscaler needs a provider")
        self.config = config or AutoscaleConfig()
        self.provider = provider
        self.metrics = metrics
        self.scale_ups: dict[str, int] = {}  # SKU -> provision requests issued
        self.scale_downs: dict[str, int] = {}  # SKU -> scale-down deletes
        self.skips: dict[str, int] = {}
        self.reclaim_notices_seen = 0
        self.reclaim_kills_seen = 0
        # Pod full names unbound by the scale-down drain protocol — the
        # scorecard's drain-orphan evidence (ordered, append-only).
        self.drain_unbound: list[str] = []
        self.last_decision: dict = {}
        self._tick = 0
        self._cooldown_left = 0
        # Wall-clock plan times (bench / debug evidence only — NEVER on
        # the scorecard, which must stay byte-identical).
        self.plan_walls: list[float] = []
        # Background mode: one worker, one request slot, one finished plan.
        self._bg_lock = threading.Lock()
        self._bg_request = None  # guarded-by: _bg_lock
        self._bg_plan = None  # guarded-by: _bg_lock
        self._bg_event = threading.Event()
        self._bg_thread: threading.Thread | None = None
        self._bg_stop = False

    # -- bookkeeping --------------------------------------------------------

    def _skip(self, reason: str) -> None:
        assert reason in SKIP_REASONS, reason
        self.skips[reason] = self.skips.get(reason, 0) + 1
        if self.metrics is not None:
            self.metrics.inc("scheduler_autoscale_skips_total", labels={"reason": reason})

    def _decision(self, action: str, **detail) -> None:
        self.last_decision = {"tick": self._tick, "action": action, **detail}

    # -- the background plan seam ------------------------------------------

    def _bg_loop(self) -> None:
        while True:
            self._bg_event.wait()
            self._bg_event.clear()
            with self._bg_lock:
                if self._bg_stop:
                    return
                req, self._bg_request = self._bg_request, None
            if req is None:
                continue
            snapshot, pending, drained_labeled, topo = req
            t0 = time.perf_counter()
            plan = self._whatif(snapshot, pending, drained_labeled, topo)
            wall = time.perf_counter() - t0
            with self._bg_lock:
                self._bg_plan = plan
                self.plan_walls.append(wall)

    def _whatif(self, snapshot, pending, drained_labeled: int, topo) -> dict:
        from ..rebalance.whatif import autoscaler_whatif

        return autoscaler_whatif(
            snapshot,
            pending,
            drained_labeled=drained_labeled,
            topo=topo,
            catalog=self.provider.catalog,
            quota_left=self.provider.quota_left(),
        )

    def _plan(self, snapshot, pending, drained_labeled: int, topo):
        """Inline mode: plan now.  Background mode: hand the request to the
        worker and return a previously finished plan if one is ready (None
        otherwise — this tick stands down and a later tick consumes it)."""
        if not self.config.background:
            t0 = time.perf_counter()
            plan = self._whatif(snapshot, pending, drained_labeled, topo)
            self.plan_walls.append(time.perf_counter() - t0)
            return plan
        if self._bg_thread is None:
            self._bg_thread = threading.Thread(target=self._bg_loop, daemon=True)
            self._bg_thread.start()
        with self._bg_lock:
            ready, self._bg_plan = self._bg_plan, None
            if ready is None and self._bg_request is None:
                self._bg_request = (snapshot, pending, drained_labeled, topo)
                self._bg_event.set()
        return ready

    def close(self) -> None:
        if self._bg_thread is not None:
            with self._bg_lock:
                self._bg_stop = True
            self._bg_event.set()
            self._bg_thread.join(timeout=5.0)
            self._bg_thread = None

    # -- the tick -----------------------------------------------------------

    # shape: (self: obj, snapshot: obj, pending: obj, topo: obj, burn: float,
    #   breaker_mode: obj, drained_labeled: int, unbind: obj, now: float) -> int
    def tick(
        self,
        snapshot,
        pending,
        *,
        topo=None,
        burn: float = 0.0,
        breaker_mode: str = "closed",
        drained_labeled: int = 0,
        unbind=None,
        now: float = 0.0,
    ) -> int:
        """One elastic-capacity step (see the module docstring's protocol).
        ``pending`` is the unplaced backlog AFTER this cycle's placements;
        ``drained_labeled`` counts the rebalancer's parked reserve nodes.
        Returns scale actions issued this tick (requests + deletes)."""
        self._tick += 1
        with span("pump"):
            pumped = self.provider.pump(now)
        self.reclaim_notices_seen += pumped["reclaim_notices"]
        self.reclaim_kills_seen += pumped["reclaim_kills"]
        if self.metrics is not None:
            if pumped["reclaim_notices"]:
                self.metrics.inc("scheduler_autoscale_reclaims_total", pumped["reclaim_notices"])
            self.metrics.set_gauge(
                "scheduler_autoscale_pending_provisions", float(self.provider.pending_provisions())
            )
        if self.config.every > 1 and (self._tick % self.config.every) != 0:
            return 0
        reason = throttle_reason(breaker_mode, self._cooldown_left)
        if reason == "cooldown":
            self._cooldown_left -= 1
        if reason is not None:
            self._skip(reason)
            return 0
        with span("plan"):
            plan = self._plan(snapshot, pending, drained_labeled, topo)
        if plan is None:
            return 0  # background plan pending — neither work nor a skip
        with span("scale"):
            demand = plan.get("sku_plan") or {}
            if (demand or plan.get("pending_unplaceable", 0)) and burn >= self.config.burn_trigger:
                return self._scale_up(demand, now)
            return self._scale_down(snapshot, drained_labeled, unbind, now)

    def _scale_up(self, demand: dict, now: float) -> int:
        """Issue the planned provision requests (bounded, quota/stockout
        tolerant) — or stand down while earlier ones are still landing."""
        if self.provider.pending_provisions():
            self._skip("inflight")
            return 0
        if not demand:
            # Overflow exists but the quota-aware plan found nothing to
            # buy — confirm against the provider (the quota authority)
            # with one probe of the cheapest SKU; a freed quota turns the
            # probe into a real scale-up.
            sku = min(self.provider.catalog, key=lambda s: (s.hourly_cost, s.name)).name
            try:
                self.provider.request(sku, now)
            except QuotaExceeded:
                self._skip("quota")
                return 0
            except Stockout:
                self._skip("stockout")
                return 0
            except ProviderError:
                self._skip("api-error")
                return 0
            self.scale_ups[sku] = self.scale_ups.get(sku, 0) + 1
            self._cooldown_left = self.config.cooldown
            self._decision("scale-up", requested=1, plan={sku: 1})
            if self.metrics is not None:
                self.metrics.inc("scheduler_autoscale_scale_ups_total", labels={"sku": sku})
            return 1
        issued = 0
        failed: dict[str, str] = {}
        for sku, count in sorted(demand.items()):
            for _ in range(count):
                if issued >= self.config.max_per_tick:
                    break
                try:
                    self.provider.request(sku, now)
                except QuotaExceeded:
                    failed[sku] = "quota"
                    break  # this SKU is capped for now; try the next one
                except Stockout:
                    failed[sku] = "stockout"
                    break
                except ProviderError:
                    self._skip("api-error")
                    return issued
                self.scale_ups[sku] = self.scale_ups.get(sku, 0) + 1
                issued += 1
                if self.metrics is not None:
                    self.metrics.inc("scheduler_autoscale_scale_ups_total", labels={"sku": sku})
            if issued >= self.config.max_per_tick:
                break
        if issued:
            self._cooldown_left = self.config.cooldown
            self._decision("scale-up", requested=issued, plan=dict(sorted(demand.items())))
        elif failed:
            # Every attempted SKU bounced — surface the dominant refusal.
            self._skip("quota" if "quota" in failed.values() else "stockout")
            self._decision("refused", errors=dict(sorted(failed.items())))
        return issued

    def _scale_down(self, snapshot, drained_labeled: int, unbind, now: float) -> int:
        """Retire elastic capacity: delete empty provider nodes beyond the
        warm reserve, else drain the least-loaded candidate through the
        unbind→cordon→delete protocol when its pods fit elsewhere."""
        ready = self.provider.ready_nodes()
        if not ready:
            self._skip("no-demand")
            return 0
        pods_by_node: dict[str, list] = {name: [] for name in ready}
        for name in ready:
            pods_by_node[name] = sorted(
                self.provider.api.list_pods(f"spec.nodeName={name}"), key=lambda p: p.metadata.name
            )
        empties = sorted(name for name in ready if not pods_by_node[name])
        if empties:
            removable = min(len(empties), max(0, drained_labeled + len(empties) - self.config.reserve))
            if removable <= 0:
                self._skip("reserve")
                return 0
            deleted = 0
            for name in empties[: min(removable, self.config.max_per_tick)]:
                if self.provider.delete(name, now):
                    sku = ready[name]
                    self.scale_downs[sku] = self.scale_downs.get(sku, 0) + 1
                    deleted += 1
                    if self.metrics is not None:
                        self.metrics.inc("scheduler_autoscale_scale_downs_total", labels={"sku": sku})
            if deleted:
                self._cooldown_left = self.config.cooldown
                self._decision("scale-down", deleted=deleted)
            return deleted
        # No empties: the reserve must already be parked elsewhere before a
        # live node is worth draining at all (hysteresis, again).
        if drained_labeled < self.config.reserve:
            self._skip("reserve")
            return 0
        name = min(ready, key=lambda n: (len(pods_by_node[n]), n))
        victims = pods_by_node[name]
        if len(victims) > self.config.drain_max_pods or not self._fits_elsewhere(snapshot, name, victims):
            self._skip("not-empty")
            return 0
        from ..api.objects import full_name

        for pod in victims:
            if unbind is None or not unbind(full_name(pod), name):
                self._skip("unbind-failed")
                return 0
            self.drain_unbound.append(full_name(pod))
        self.provider._cordon(name)  # the drain protocol's cordon step
        if not self.provider.delete(name, now):
            self._skip("api-error")  # a bind raced the drain; keep the node
            return 0
        sku = ready[name]
        self.scale_downs[sku] = self.scale_downs.get(sku, 0) + 1
        self._cooldown_left = self.config.cooldown
        self._decision("scale-down", deleted=1, drained=len(victims))
        if self.metrics is not None:
            self.metrics.inc("scheduler_autoscale_scale_downs_total", labels={"sku": sku})
        return 1

    def _fits_elsewhere(self, snapshot, candidate: str, victims) -> bool:
        """FFD the candidate's pods into the rest of the fleet's free,
        schedulable capacity — the no-stranded-demand precondition."""
        from ..api.objects import total_pod_resources
        from ..core.snapshot import node_allocatable, node_used_resources

        free = []
        for node in snapshot.nodes:
            if node.name == candidate:
                continue
            if node.spec is not None and node.spec.unschedulable:
                continue
            alloc = node_allocatable(node)
            used = node_used_resources(snapshot, node.name)
            free.append([int(alloc.cpu - used.cpu), int(alloc.memory - used.memory)])
        free.sort(key=lambda f: -f[0])
        reqs = []
        for pod in victims:
            r = total_pod_resources(pod)
            reqs.append((int(r.cpu), int(r.memory)))
        reqs.sort(key=lambda r: (-max(r[0], r[1]), r))
        for cpu, mem in reqs:
            placed = False
            for f in free:
                if f[0] >= cpu and f[1] >= mem:
                    f[0] -= cpu
                    f[1] -= mem
                    placed = True
                    break
            if not placed:
                return False
        return True

    # -- introspection ------------------------------------------------------

    def stats(self) -> dict:
        """Lifetime stats — strictly counts (deterministic control flow; no
        wall clock), consumed by the sim scorecard, /debug/autoscale,
        bench, and tests."""
        return {
            "enabled": True,
            "ticks": self._tick,
            "scale_ups": dict(sorted(self.scale_ups.items())),
            "scale_downs": dict(sorted(self.scale_downs.items())),
            "reclaim_notices": self.reclaim_notices_seen,
            "reclaim_kills": self.reclaim_kills_seen,
            "drain_unbound": len(self.drain_unbound),
            "skips": dict(sorted(self.skips.items())),
            "last_decision": dict(self.last_decision),
        }
