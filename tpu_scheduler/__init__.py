"""tpu_scheduler — a TPU-native scheduling framework.

Capability parity with acrlabs/kube-scheduler-rs-reference (a Rust Kubernetes
pod scheduler; see SURVEY.md), rebuilt TPU-first: the entire predicate filter
plus priority scoring for all pending pods × all nodes runs as batched tensor
ops (JAX/XLA, Pallas kernels, pjit/shard_map over device meshes) instead of a
per-pod random-sample loop with per-candidate API round-trips.

Layout:
  api/       Kubernetes-shaped object model + quantity arithmetic  (ref L1)
  core/      ClusterSnapshot + pure scalar predicates              (ref L2)
  ops/       tensorization, masks, scoring, commit kernels         (the TPU hot path)
  backends/  native (NumPy) and tpu (JAX) batched scheduling backends
  parallel/  mesh / shard_map / ring-blockwise distribution
  models/    scheduling policy profiles (score weights, chains)
  runtime/   fake API server, reflector, controller loop           (ref L4)
  utils/     tracing spans, metrics, checkpointing
"""

__version__ = "0.4.0"

from .api.objects import (  # noqa: F401
    Binding,
    Node,
    ObjectMeta,
    Pod,
    PodAffinityTerm,
    PodAntiAffinityTerm,
    PodDisruptionBudget,
    PodResources,
    Taint,
    Toleration,
    TopologySpreadConstraint,
    WeightedPodAffinityTerm,
    full_name,
    is_pod_bound,
    total_pod_resources,
)
from .core.predicates import InvalidNodeReason, check_node_validity  # noqa: F401
from .core.snapshot import ClusterSnapshot  # noqa: F401
from .runtime.kubeconfig import client_from_kubeconfig  # noqa: F401  (real-cluster edge, main.rs:130)
