"""Tensorized inter-pod anti-affinity, POSITIVE pod affinity + topology
spread (BASELINE config 5).

The scalar predicates (core/predicates.py: anti_affinity_ok /
topology_spread_ok) are pods×pods×nodes relations — the memory wall SURVEY.md
§2b SP/CP warns about.  This module never materializes that 3-tensor.  The
key observation: both predicates only consult *topology domains* (the set of
nodes sharing a value of the term's topology key), so the device state is
domain-granular:

  AA term vocab T:  distinct (namespace, topology_key, selector) terms among
                    pending + placed pods.
  PA term vocab Ta: positive (requiredDuringScheduling podAffinity) terms
                    among PENDING pods only — affinity constrains just the
                    declarer, so placed pods' terms need no columns.  The
                    blocked mask is the inverted matched-domain mask, gated
                    by the bootstrap waiver (a term matching nothing
                    anywhere is waived for self-matching declarers); the
                    within-round filter keeps only the first accepted match
                    per waived term (see constraint_filter).
  Spread vocab S:   distinct (namespace, key, max_skew, selector) constraints
                    among pending pods.
  Coarse domains D: (key, value) pairs over the referenced topology keys —
                    node_dom_c[N, D] is each node's one-hot domain membership
                    (one column per key it carries).
  Fine domains:     keys whose values are unique per node (hostname-like) and
                    nodes lacking a coarse key degrade to per-node singleton
                    domains — state at node granularity [T, N], exactly as
                    the scalar ``("~node", name)`` rule.

Per auction round (ops/assign.py), the blocked pods×nodes mask is three
matmuls — pod_carries[B,T] @ aa_matched_node[T,N] etc. — so constrained pods
ride the same MXU path as everything else; per-round state updates are
[T,P]@[P,D] matmuls plus O(P·T) scatters.

Within-round conflicts (two mutually-anti-affine pods accepted into one
domain in the same round; a domain over-filling past max_skew) are resolved
by rank (the auction's priority order):
  • AA: in each (term, domain) cell, a matched pod survives only if it
    out-ranks every accepted carrier in the cell and vice versa (exact
    min-rank rule; at worst it defers a pod the greedy oracle would accept
    by one round — never admits a violation).
  • Spread: per (constraint, domain) cell, a *water-filling* quota is
    computed (8-step fixpoint of q = max_skew + lo − counts with lo the
    rising min across the key's domains) and the cell keeps its quota's
    worth of lowest-rank claimants — mass spread workloads commit whole
    waves per round instead of one pod per domain.  The quota denominator
    deliberately overcounts (all capacity-accepted matched mass) while the
    water line lo counts only mass *certain* to commit this round; see the
    inline soundness note in constraint_filter.
Deferred pods stay active and retry next round against the committed state;
the round-start choose mask already blocks saturated domains, so every kept
set is violation-free and the loop strictly progresses.

Validity is *order-witnessed*: each round's kept set admits a sequential
order in which every placement passes the scalar chain — rank order for
anti-affinity (no conflicting pair survives the filter at all), ascending
fill-height (c0 + position-in-cell) for spread waves: a height-h placement
sees min-fill ≥ min(h, lo_fixpoint), so ``count+1−min ≤ max_skew`` holds at
its turn (tests/test_constraints_tensor.py replays this certificate through
core/predicates.py).  Caveat: a pod declaring *multiple* spread constraints
joins each constraint's witness order; the per-constraint quotas are each
respected but a single interleaving witnessing all of them simultaneously is
not constructed — multi-constraint pods are conservative-safe per
constraint, and the certificate test covers the (dominant) one-constraint
shape.

Everything is written against an ``xp`` namespace (numpy | jax.numpy) so the
native and TPU backends share one expression tree — the same bit-parity
contract as ops/masks.py.

Scale guards: clusters whose constraint structure exceeds the static budgets
(too many distinct terms, or a many-valued non-unique topology key) raise
:class:`UntensorizableConstraints`; the controller then falls back to the
exact host-side sequential phase (runtime/controller.py), so the tensor path
is an accelerator, never a semantics change.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..api.objects import Pod
from ..core.predicates import term_matches
from .pack import round_up

__all__ = [
    "ConstraintSet",
    "UntensorizableConstraints",
    "pack_constraints",
    "prune_match_memo",
    "round_blocked_masks",
    "blocked_block",
    "constraint_filter",
    "constraint_commit",
    "RANK_INF",
]

RANK_INF = np.float32(3.0e38)

# Default budgets (padded): sized so the per-term state ([T,N]/[S,D], ~10 MB
# at 256×10k) and the pod-side bitmaps ([P,T] etc., ~110 MB each at 100k×256)
# stay well under HBM at north-star scale while admitting realistic
# vocabularies — per-app selectors (one term per deployment) are the common
# shape, and a 50-deployment cluster with two skew levels already needs ~100
# spread terms.  History: the original 128/64 budgets silently routed the
# CLI's own mixed workload to the exact-but-glacial host sequential phase
# (UntensorizableConstraints fallback — measured 482 s for ONE 10k×1k cycle
# vs ~1 s on the tensor path), so the defaults now match what the hardware
# comfortably holds, and the controller exposes them as knobs.
MAX_AA_TERMS = 256
MAX_SPREAD = 256
MAX_COARSE_DOMAINS = 256

# Fast-path budget for the within-round filter/commit: below this terms×D
# product, "who came earlier into my cell" is computed DENSELY — a [P,T,D]
# exclusive cumsum along the (rank-ordered) pod axis — instead of the
# sort/scatter formulation.  On TPU through the tunnel the difference is
# stark (measured at 53k pods: scalar scatter_min ~43 ms and the [S·P]
# stable sort ~47 ms per round, vs ~2-3 ms for the cumsum 3-tensor and
# ~free [N,·] row scatters), because XLA lowers arbitrary-index scalar
# scatters near-serially while cumsums ride the parallel prefix path.
# Above the budget the 3-tensor would dominate HBM traffic, so the
# sort/scatter path takes over (bit-identical results either way — counts
# are small exact f32 integers and array order IS rank order).
DENSE_CELLS = 1024


class UntensorizableConstraints(Exception):
    """Constraint structure exceeds the tensor budgets — use the host path."""


# Sentinel key under which a match_memo stores the term-vocabulary signature
# it is valid for.  Key spaces (owned HERE, with prune_match_memo and
# _sig_independent — callers must not hand-filter by key type):
#   _MEMO_SIG            — the signature sentinel
#   id(pod) ints         — matched-term ids (vocab-DEPENDENT)
#   ("dk", id(pod))      — declared canonical keys (vocab-independent)
_MEMO_SIG = "sig"
_MEMO_DK = "dk"


def _sig_independent(k) -> bool:
    """Memo keys that survive a vocabulary-signature change."""
    return isinstance(k, tuple) and len(k) == 2 and k[0] == _MEMO_DK


def prune_match_memo(memo: dict, live_ids: set) -> dict:
    """Drop memo entries for dead pod objects, preserving the signature
    sentinel (see the key-space table above)."""
    return {
        k: v
        for k, v in memo.items()
        if k == _MEMO_SIG or k in live_ids or (isinstance(k, tuple) and k[1] in live_ids)
    }




def _term_probe_index(term_list):
    """(indexed, residual) over ``[(key, (ns, term)), ...]`` — the matched-
    bitmap hot loops are O(pods × terms) naively (13M term_matches calls at
    50k pods × ~260 terms, ~15 s host-side); a term with match_labels can
    only match a pod carrying its first sorted (k, v) pair, so pods probe
    the index with their own labels and run the full matcher on the few
    candidates (the same near-linear trick as the controller's
    _split_affinity_pending).  Terms without match_labels land in the
    per-namespace residual."""
    indexed: dict[tuple, list[int]] = {}
    residual: dict[str | None, list[int]] = {}
    for ti, (_key, (t_ns, term)) in enumerate(term_list):
        ml = term.match_labels
        if ml:
            k, v = sorted(ml.items())[0]
            indexed.setdefault((t_ns, k, v), []).append(ti)
        else:
            residual.setdefault(t_ns, []).append(ti)
    return indexed, residual


def _matched_term_ids(term_list, indexed, residual, ns, labels):
    """Term indices of ``term_list`` whose selector matches ``labels`` in
    namespace ``ns`` — candidates from the probe index, verified exactly."""
    cand: set[int] = set(residual.get(ns, ()))
    if labels:
        for kv in labels.items():
            cand.update(indexed.get((ns, kv[0], kv[1]), ()))
    return [ti for ti in cand if term_matches(term_list[ti][1][1], labels)]


def _canon_selector(match_labels, match_expressions) -> tuple:
    ml = tuple(sorted((match_labels or {}).items()))
    mx = tuple(
        sorted(
            (r.key, r.operator, tuple(sorted(r.values or ())) if r.operator in ("In", "NotIn") else tuple(r.values or ()))
            for r in (match_expressions or [])
        )
    )
    return (ml, mx)


def _aa_key(ns, term) -> tuple:
    return (ns, term.topology_key, _canon_selector(term.match_labels, term.match_expressions))


def _sp_key(ns, c) -> tuple:
    return (ns, c.topology_key, int(c.max_skew), _canon_selector(c.match_labels, c.match_expressions))


@dataclass(frozen=True)
class ConstraintSet:
    """Device tensors for AA + spread over one packed cycle.

    Pod rows align with PackedCluster's pending-pod order (padded to P).
    State arrays are the *round-start* state (from placed pods); the auction
    threads them through its while-loop carry.
    """

    # Pod side [P, T] / [P, Ta] / [P, S] / [P, Ss] float32
    pod_aa_carries: np.ndarray
    pod_aa_matched: np.ndarray
    pod_pa_declares: np.ndarray  # positive affinity: the pod declares term
    pod_pa_matched: np.ndarray  # the pod's labels satisfy the term's selector
    pod_sp_declares: np.ndarray
    pod_sp_matched: np.ndarray
    pod_sps_declares: np.ndarray  # soft (ScheduleAnyway) spread declarations
    pod_sps_matched: np.ndarray
    pod_ppa_w: np.ndarray  # [P, Tp] SIGNED preferred-(anti-)affinity weights
    pod_ppa_matched: np.ndarray  # [P, Tp] pod matches the preferred term
    # Node side
    node_dom_c: np.ndarray  # [N, D] float32 one-hot (one col per carried key)
    # Term metadata
    term_uses_dom: np.ndarray  # [T, D] float32 — domains of the term's key
    pa_uses_dom: np.ndarray  # [Ta, D] float32 — positive-affinity term keys
    ppa_uses_dom: np.ndarray  # [Tp, D] float32 — preferred-term keys
    sp_uses_dom: np.ndarray  # [S, D] float32
    sp_skew: np.ndarray  # [S] float32
    sps_uses_dom: np.ndarray  # [Ss, D] float32 — soft-spread constraint keys
    # Initial state (from placed pods)
    aa_dom_m: np.ndarray  # [T, D] 0/1 — domain holds a pod matched by term
    aa_dom_c: np.ndarray  # [T, D] 0/1 — domain holds a carrier of term
    aa_node_m: np.ndarray  # [T, N] 0/1 — fine-granularity (singleton) twin
    aa_node_c: np.ndarray  # [T, N] 0/1
    pa_dom_m: np.ndarray  # [Ta, D] 0/1 — domain holds a pod matched by PA term
    pa_node_m: np.ndarray  # [Ta, N] 0/1 — fine-granularity twin
    ppa_dom_cnt: np.ndarray  # [Tp, D] float32 — preferred-term match counts
    ppa_node_cnt: np.ndarray  # [Tp, N] float32 — fine-granularity twin
    sp_counts: np.ndarray  # [S, D] float32 — matching placed pods per domain
    sps_counts: np.ndarray  # [Ss, D] float32 — soft-spread matching counts

    n_terms: int
    n_pa_terms: int
    n_ppa_terms: int
    n_spread: int
    n_spread_soft: int

    def pod_arrays(self) -> dict:
        return {
            "pod_aa_carries": self.pod_aa_carries,
            "pod_aa_matched": self.pod_aa_matched,
            "pod_pa_declares": self.pod_pa_declares,
            "pod_pa_matched": self.pod_pa_matched,
            "pod_sp_declares": self.pod_sp_declares,
            "pod_sp_matched": self.pod_sp_matched,
            "pod_sps_declares": self.pod_sps_declares,
            "pod_sps_matched": self.pod_sps_matched,
            "pod_ppa_w": self.pod_ppa_w,
            "pod_ppa_matched": self.pod_ppa_matched,
        }

    def meta_arrays(self) -> dict:
        return {
            "node_dom_c": self.node_dom_c,
            "term_uses_dom": self.term_uses_dom,
            "pa_uses_dom": self.pa_uses_dom,
            "ppa_uses_dom": self.ppa_uses_dom,
            "sp_uses_dom": self.sp_uses_dom,
            "sp_skew": self.sp_skew,
            "sps_uses_dom": self.sps_uses_dom,
        }

    def state_arrays(self) -> dict:
        return {
            "aa_dom_m": self.aa_dom_m,
            "aa_dom_c": self.aa_dom_c,
            "aa_node_m": self.aa_node_m,
            "aa_node_c": self.aa_node_c,
            "pa_dom_m": self.pa_dom_m,
            "pa_node_m": self.pa_node_m,
            "ppa_dom_cnt": self.ppa_dom_cnt,
            "ppa_node_cnt": self.ppa_node_cnt,
            "sp_counts": self.sp_counts,
            "sps_counts": self.sps_counts,
        }


def pack_constraints(
    snapshot,
    pending: list[Pod],
    padded_pods: int,
    node_names: tuple[str, ...],
    padded_nodes: int,
    max_aa_terms: int = MAX_AA_TERMS,
    max_spread: int = MAX_SPREAD,
    max_coarse_domains: int = MAX_COARSE_DOMAINS,
    label_block: int = 8,
    match_memo: dict | None = None,
) -> ConstraintSet | None:
    """Build constraint tensors for one cycle; None if nothing constrained.

    Raises :class:`UntensorizableConstraints` when the structure exceeds the
    budgets (the controller's cue to run the host sequential phase instead).

    ``match_memo`` (same contract as ops/pack.py ``res_memo``: object-
    identity keyed, ``id(pod) -> (pod, matched-id tuples)``, caller-owned
    and caller-pruned) memoizes the five selector-match queries per pod —
    the dominant host cost of a constrained cycle (the matched-bitmap and
    placed-state loops are O(pods × terms) term_matches calls without it;
    PERF.md "known remaining headroom").  The memo is only valid for one
    term-vocabulary signature: it self-clears whenever the vocab changes
    (a new app's term appearing is a full-rematch event, steady-state
    cycles hit ~100%).  The API layer replaces pod objects on every
    modification, so identity hits are exactly the unchanged pods."""
    nodes = list(snapshot.nodes)
    assert tuple(n.name for n in nodes) == tuple(node_names)

    def _declared(pod):
        """The pod's declared canonical keys, memoized by object identity:
        (aa [(key, term)], pa [(key, term)], ppa [(key, term, signed_w)],
        sp [(key, c)], sps [(key, c)]).  Valid independent of the term
        vocabulary (derived from the pod object alone), so cached under a
        ("dk", id) key that survives vocab changes only incidentally — a
        sig-triggered clear recomputes it for the price of one pass."""
        mk = (_MEMO_DK, id(pod))
        if match_memo is not None:
            hit = match_memo.get(mk)
            if hit is not None and hit[0] is pod:
                return hit[1]
        ns, spec = pod.metadata.namespace, pod.spec
        aa = [(_aa_key(ns, t), t) for t in (spec.anti_affinity or ())] if spec is not None else []
        pa = [(_aa_key(ns, t), t) for t in (spec.pod_affinity or ())] if spec is not None else []
        ppa = []
        sp: list = []
        sps: list = []
        if spec is not None:
            for w in spec.preferred_pod_affinity or ():
                ppa.append((_aa_key(ns, w.term), w.term, float(w.weight)))
            for w in spec.preferred_pod_anti_affinity or ():
                ppa.append((_aa_key(ns, w.term), w.term, -float(w.weight)))
            for c in spec.topology_spread or ():
                (sp if c.is_hard else sps).append((_sp_key(ns, c), c))
        data = (aa, pa, ppa, sp, sps)
        # Unconstrained pods: recomputing the five empty lists is cheaper
        # than a memo entry per pod (the memo would double in size).
        if match_memo is not None and (aa or pa or ppa or sp or sps):
            match_memo[mk] = (pod, data)
        return data

    # --- vocabularies -----------------------------------------------------
    aa_vocab: dict[tuple, tuple] = {}  # key -> (ns, term)
    pa_vocab: dict[tuple, tuple] = {}
    ppa_vocab: dict[tuple, tuple] = {}  # preferred (soft, signed) — scoring only
    sp_vocab: dict[tuple, tuple] = {}  # hard (DoNotSchedule) — blocking
    sps_vocab: dict[tuple, tuple] = {}  # soft (ScheduleAnyway) — scoring only
    for p in pending:
        ns = p.metadata.namespace
        aa, pa, ppa, sp, sps = _declared(p)
        for key, t in aa:
            aa_vocab.setdefault(key, (ns, t))
        # Positive affinity: only PENDING pods' terms constrain anyone (no
        # symmetric direction — a placed pod's affinity is already satisfied).
        for key, t in pa:
            pa_vocab.setdefault(key, (ns, t))
        for key, t, _w in ppa:
            ppa_vocab.setdefault(key, (ns, t))
        for key, c in sp:
            sp_vocab.setdefault(key, (ns, c))
        for key, c in sps:
            sps_vocab.setdefault(key, (ns, c))
    # One _declared pass per placed carrier: the (key, term) pairs feed both
    # the vocab walk here and the carrier-mark loop at the bottom.
    placed_carrier_keys = [(q, qn, _declared(q)[0]) for q, qn in snapshot.placed_pods_with_terms()]
    for q, _qn, aa_d in placed_carrier_keys:
        ns = q.metadata.namespace
        for key, t in aa_d:
            aa_vocab.setdefault(key, (ns, t))

    if not aa_vocab and not pa_vocab and not ppa_vocab and not sp_vocab and not sps_vocab:
        return None
    if len(aa_vocab) > max_aa_terms:
        raise UntensorizableConstraints(f"{len(aa_vocab)} anti-affinity terms > budget {max_aa_terms}")
    if len(pa_vocab) > max_aa_terms:
        raise UntensorizableConstraints(f"{len(pa_vocab)} pod-affinity terms > budget {max_aa_terms}")
    if len(ppa_vocab) > max_aa_terms:
        raise UntensorizableConstraints(f"{len(ppa_vocab)} preferred pod-affinity terms > budget {max_aa_terms}")
    if len(sp_vocab) > max_spread:
        raise UntensorizableConstraints(f"{len(sp_vocab)} spread constraints > budget {max_spread}")
    if len(sps_vocab) > max_spread:
        raise UntensorizableConstraints(f"{len(sps_vocab)} soft spread constraints > budget {max_spread}")

    # --- topology keys → coarse domains or fine (per-node) ----------------
    keys = (
        {k for (_ns, k, _sel) in aa_vocab}
        | {k for (_ns, k, _sel) in pa_vocab}
        | {k for (_ns, k, _sel) in ppa_vocab}
        | {k for (_ns, k, _sk, _sel) in sp_vocab}
        | {k for (_ns, k, _sk, _sel) in sps_vocab}
    )
    spread_keys = {k for (_ns, k, _sk, _sel) in sp_vocab} | {k for (_ns, k, _sk, _sel) in sps_vocab}
    key_values: dict[str, dict[str, list[int]]] = {k: {} for k in keys}
    for i, n in enumerate(nodes):
        labels = n.metadata.labels or {}
        for k in keys:
            v = labels.get(k)
            if v is not None:
                key_values[k].setdefault(v, []).append(i)

    dom_vocab: dict[tuple[str, str], int] = {}  # (key, value) -> column
    fine_keys: set[str] = set()
    budget = max_coarse_domains
    for k in sorted(keys):
        vals = key_values[k]
        if len(vals) <= budget - len(dom_vocab):
            for v in sorted(vals):
                dom_vocab[(k, v)] = len(dom_vocab)
        elif all(len(nids) == 1 for nids in vals.values()):
            # Hostname-like: unique value per node ⇒ domain ≡ node, exact at
            # fine granularity with zero coarse columns.
            fine_keys.add(k)
            if k in spread_keys:
                raise UntensorizableConstraints(f"spread key {k!r} is per-node-granular ({len(vals)} values)")
        else:
            raise UntensorizableConstraints(f"topology key {k!r} has {len(vals)} shared-value domains > budget")

    d_pad = round_up(max(len(dom_vocab), 1), label_block)
    t_pad = round_up(max(len(aa_vocab), 1), label_block)
    ta_pad = round_up(max(len(pa_vocab), 1), label_block)
    tp_pad = round_up(max(len(ppa_vocab), 1), label_block)
    s_pad = round_up(max(len(sp_vocab), 1), label_block)
    ss_pad = round_up(max(len(sps_vocab), 1), label_block)
    n_pad = padded_nodes

    node_dom_c = np.zeros((n_pad, d_pad), dtype=np.float32)
    for (k, v), j in dom_vocab.items():
        for i in key_values[k][v]:
            node_dom_c[i, j] = 1.0

    aa_terms = list(aa_vocab.items())  # [(key, (ns, term))]
    pa_terms = list(pa_vocab.items())
    ppa_terms = list(ppa_vocab.items())
    sp_terms = list(sp_vocab.items())
    sps_terms = list(sps_vocab.items())

    term_uses_dom = np.zeros((t_pad, d_pad), dtype=np.float32)
    for ti, (key, (_ns, term)) in enumerate(aa_terms):
        if term.topology_key not in fine_keys:
            for v in key_values.get(term.topology_key, ()):  # noqa: B007
                term_uses_dom[ti, dom_vocab[(term.topology_key, v)]] = 1.0
    pa_uses_dom = np.zeros((ta_pad, d_pad), dtype=np.float32)
    for ti, (key, (_ns, term)) in enumerate(pa_terms):
        if term.topology_key not in fine_keys:
            for v in key_values.get(term.topology_key, ()):  # noqa: B007
                pa_uses_dom[ti, dom_vocab[(term.topology_key, v)]] = 1.0
    ppa_uses_dom = np.zeros((tp_pad, d_pad), dtype=np.float32)
    for ti, (key, (_ns, term)) in enumerate(ppa_terms):
        if term.topology_key not in fine_keys:
            for v in key_values.get(term.topology_key, ()):  # noqa: B007
                ppa_uses_dom[ti, dom_vocab[(term.topology_key, v)]] = 1.0
    sp_uses_dom = np.zeros((s_pad, d_pad), dtype=np.float32)
    sp_skew = np.zeros((s_pad,), dtype=np.float32)
    for si, (key, (_ns, c)) in enumerate(sp_terms):
        sp_skew[si] = float(c.max_skew)
        for v in key_values.get(c.topology_key, ()):
            sp_uses_dom[si, dom_vocab[(c.topology_key, v)]] = 1.0
    sps_uses_dom = np.zeros((ss_pad, d_pad), dtype=np.float32)
    for si, (key, (_ns, c)) in enumerate(sps_terms):
        for v in key_values.get(c.topology_key, ()):
            sps_uses_dom[si, dom_vocab[(c.topology_key, v)]] = 1.0

    # --- pod-side bitmaps -------------------------------------------------
    pod_aa_carries = np.zeros((padded_pods, t_pad), dtype=np.float32)
    pod_aa_matched = np.zeros((padded_pods, t_pad), dtype=np.float32)
    pod_pa_declares = np.zeros((padded_pods, ta_pad), dtype=np.float32)
    pod_pa_matched = np.zeros((padded_pods, ta_pad), dtype=np.float32)
    pod_sp_declares = np.zeros((padded_pods, s_pad), dtype=np.float32)
    pod_sp_matched = np.zeros((padded_pods, s_pad), dtype=np.float32)
    pod_sps_declares = np.zeros((padded_pods, ss_pad), dtype=np.float32)
    pod_sps_matched = np.zeros((padded_pods, ss_pad), dtype=np.float32)
    pod_ppa_w = np.zeros((padded_pods, tp_pad), dtype=np.float32)
    pod_ppa_matched = np.zeros((padded_pods, tp_pad), dtype=np.float32)
    aa_index = {key: i for i, (key, _) in enumerate(aa_terms)}
    pa_index = {key: i for i, (key, _) in enumerate(pa_terms)}
    ppa_index = {key: i for i, (key, _) in enumerate(ppa_terms)}
    sp_index = {key: i for i, (key, _) in enumerate(sp_terms)}
    sps_index = {key: i for i, (key, _) in enumerate(sps_terms)}
    aa_probe, aa_res = _term_probe_index(aa_terms)
    pa_probe, pa_res = _term_probe_index(pa_terms)
    ppa_probe, ppa_res = _term_probe_index(ppa_terms)
    sp_probe, sp_res = _term_probe_index(sp_terms)
    sps_probe, sps_res = _term_probe_index(sps_terms)

    if match_memo is not None:
        sig = (
            tuple(k for k, _ in aa_terms),
            tuple(k for k, _ in pa_terms),
            tuple(k for k, _ in ppa_terms),
            tuple(k for k, _ in sp_terms),
            tuple(k for k, _ in sps_terms),
        )
        if match_memo.get(_MEMO_SIG) != sig:
            # Matched-id entries are vocab-dependent — drop them; declared-
            # keys entries derive from the pod object alone and survive
            # (_sig_independent owns that distinction).
            keep = {k: v for k, v in match_memo.items() if _sig_independent(k)}
            match_memo.clear()
            match_memo.update(keep)
            match_memo[_MEMO_SIG] = sig

    def _matched_all(pod):
        """(aa, pa, ppa, sp, sps) matched-id lists for one pod, memoized."""
        if match_memo is not None:
            hit = match_memo.get(id(pod))
            if hit is not None and hit[0] is pod:
                return hit[1]
        ns, labels = pod.metadata.namespace, pod.metadata.labels
        ids = (
            _matched_term_ids(aa_terms, aa_probe, aa_res, ns, labels),
            _matched_term_ids(pa_terms, pa_probe, pa_res, ns, labels),
            _matched_term_ids(ppa_terms, ppa_probe, ppa_res, ns, labels),
            _matched_term_ids(sp_terms, sp_probe, sp_res, ns, labels),
            _matched_term_ids(sps_terms, sps_probe, sps_res, ns, labels),
        )
        if match_memo is not None:
            match_memo[id(pod)] = (pod, ids)
        return ids

    for pi, p in enumerate(pending):
        aa_d, pa_d, ppa_d, sp_d, sps_d = _declared(p)
        for key, _t in aa_d:
            pod_aa_carries[pi, aa_index[key]] = 1.0
        for key, _t in pa_d:
            pod_pa_declares[pi, pa_index[key]] = 1.0
        for key, _t, w in ppa_d:
            pod_ppa_w[pi, ppa_index[key]] += w
        for key, _c in sp_d:
            pod_sp_declares[pi, sp_index[key]] = 1.0
        for key, _c in sps_d:
            pod_sps_declares[pi, sps_index[key]] = 1.0
        aa_m, pa_m, ppa_m, sp_m, sps_m = _matched_all(p)
        for ti in aa_m:
            pod_aa_matched[pi, ti] = 1.0
        for ti in pa_m:
            pod_pa_matched[pi, ti] = 1.0
        for ti in ppa_m:
            pod_ppa_matched[pi, ti] = 1.0
        for si in sp_m:
            pod_sp_matched[pi, si] = 1.0
        for si in sps_m:
            pod_sps_matched[pi, si] = 1.0

    # --- initial state from placed pods -----------------------------------
    aa_dom_m = np.zeros((t_pad, d_pad), dtype=np.float32)
    aa_dom_c = np.zeros((t_pad, d_pad), dtype=np.float32)
    aa_node_m = np.zeros((t_pad, n_pad), dtype=np.float32)
    aa_node_c = np.zeros((t_pad, n_pad), dtype=np.float32)
    pa_dom_m = np.zeros((ta_pad, d_pad), dtype=np.float32)
    pa_node_m = np.zeros((ta_pad, n_pad), dtype=np.float32)
    ppa_dom_cnt = np.zeros((tp_pad, d_pad), dtype=np.float32)
    ppa_node_cnt = np.zeros((tp_pad, n_pad), dtype=np.float32)
    sp_counts = np.zeros((s_pad, d_pad), dtype=np.float32)
    sps_counts = np.zeros((ss_pad, d_pad), dtype=np.float32)
    node_index = {n.name: i for i, n in enumerate(nodes)}

    def _mark(arr_dom, arr_node, ti, term, qnode_name):
        ni = node_index[qnode_name]
        k = term.topology_key
        v = (nodes[ni].metadata.labels or {}).get(k)
        if k not in fine_keys and v is not None:
            arr_dom[ti, dom_vocab[(k, v)]] = 1.0
        else:
            arr_node[ti, ni] = 1.0

    def _count(arr_dom, arr_node, ti, term, qnode_name):
        """+= twin of _mark for the count-valued preferred-term state."""
        ni = node_index[qnode_name]
        k = term.topology_key
        v = (nodes[ni].metadata.labels or {}).get(k)
        if k not in fine_keys and v is not None:
            arr_dom[ti, dom_vocab[(k, v)]] += 1.0
        else:
            arr_node[ti, ni] += 1.0

    if aa_terms or pa_terms or ppa_terms or sp_terms or sps_terms:
        want_sp = bool(sp_terms or sps_terms)
        for q, qnode in snapshot.placed_pods():
            aa_m, pa_m, ppa_m, sp_m, sps_m = _matched_all(q)
            for ti in aa_m:
                _mark(aa_dom_m, aa_node_m, ti, aa_terms[ti][1][1], qnode.name)
            for ti in pa_m:
                _mark(pa_dom_m, pa_node_m, ti, pa_terms[ti][1][1], qnode.name)
            for ti in ppa_m:
                _count(ppa_dom_cnt, ppa_node_cnt, ti, ppa_terms[ti][1][1], qnode.name)
            if want_sp and (sp_m or sps_m):
                nlabels = (nodes[node_index[qnode.name]].metadata.labels) or {}
                for si in sp_m:
                    c = sp_terms[si][1][1]
                    v = nlabels.get(c.topology_key)
                    if v is not None:
                        sp_counts[si, dom_vocab[(c.topology_key, v)]] += 1.0
                for si in sps_m:
                    c = sps_terms[si][1][1]
                    v = nlabels.get(c.topology_key)
                    if v is not None:
                        sps_counts[si, dom_vocab[(c.topology_key, v)]] += 1.0
        for _q, qnode, aa_d in placed_carrier_keys:
            for key, t in aa_d:
                _mark(aa_dom_c, aa_node_c, aa_index[key], t, qnode.name)

    return ConstraintSet(
        pod_aa_carries=pod_aa_carries,
        pod_aa_matched=pod_aa_matched,
        pod_pa_declares=pod_pa_declares,
        pod_pa_matched=pod_pa_matched,
        pod_sp_declares=pod_sp_declares,
        pod_sp_matched=pod_sp_matched,
        pod_sps_declares=pod_sps_declares,
        pod_sps_matched=pod_sps_matched,
        pod_ppa_w=pod_ppa_w,
        pod_ppa_matched=pod_ppa_matched,
        node_dom_c=node_dom_c,
        term_uses_dom=term_uses_dom,
        pa_uses_dom=pa_uses_dom,
        ppa_uses_dom=ppa_uses_dom,
        sp_uses_dom=sp_uses_dom,
        sp_skew=sp_skew,
        sps_uses_dom=sps_uses_dom,
        aa_dom_m=aa_dom_m,
        aa_dom_c=aa_dom_c,
        aa_node_m=aa_node_m,
        aa_node_c=aa_node_c,
        pa_dom_m=pa_dom_m,
        pa_node_m=pa_node_m,
        ppa_dom_cnt=ppa_dom_cnt,
        ppa_node_cnt=ppa_node_cnt,
        sp_counts=sp_counts,
        sps_counts=sps_counts,
        n_terms=len(aa_terms),
        n_pa_terms=len(pa_terms),
        n_ppa_terms=len(ppa_terms),
        n_spread=len(sp_terms),
        n_spread_soft=len(sps_terms),
    )


# ---------------------------------------------------------------------------
# xp-generic round engine (shared by ops/assign.py and backends/native.py)
# ---------------------------------------------------------------------------


def _clip01(xp, a):
    return xp.minimum(a, 1.0)


def round_blocked_masks(
    xp, state: dict, meta: dict, soft_spread: bool = False, soft_pa: bool = False, hard_pa: bool = True
) -> dict:
    """Per-round [·, N] blocked-node masks from the current domain state.

    aa_m_node[T,N]: node's domain (under term t's key) holds a matched pod —
    blocks *carriers* of t.  aa_c_node[T,N]: holds a carrier — blocks
    *matched* pods.  sp_node[S,N]: placing a matching pod there would exceed
    ``max_skew + min(counts)`` — blocks *declarers* of s.

    sp_penalty_node[Ss,N] (soft/ScheduleAnyway — scoring, never blocking;
    built only with ``soft_spread=True``, a trace-time constant, so clusters
    without ScheduleAnyway constraints skip the matmuls entirely): the count
    of matching placed pods in the node's domain under soft constraint s,
    the tensor twin of core/predicates.make_soft_spread_scorer; score_block
    subtracts ``topology_weight · (pod_sps_declares @ sp_penalty_node)``.
    """
    ndc_t = meta["node_dom_c"].T
    aa_m_node = _clip01(xp, state["aa_dom_m"] @ ndc_t + state["aa_node_m"])
    aa_c_node = _clip01(xp, state["aa_dom_c"] @ ndc_t + state["aa_node_c"])
    # Positive affinity: a declarer is blocked wherever its term has NO match
    # in the node's domain — the inverted twin of aa_m_node — except while
    # the term is globally inactive (no match anywhere) AND the pod matches
    # its own term (the bootstrap waiver; blocked_block applies the pod-side
    # gate from pa_inactive).
    if hard_pa:
        pa_m_node = _clip01(xp, state["pa_dom_m"] @ ndc_t + state["pa_node_m"])
        pa_unmatched_node = 1.0 - pa_m_node
        pa_inactive = (state["pa_dom_m"].sum(axis=1) + state["pa_node_m"].sum(axis=1)) == 0  # [Ta]
    uses = meta["sp_uses_dom"]
    counts = state["sp_counts"]
    lo = xp.min(xp.where(uses > 0, counts, RANK_INF), axis=1)
    lo = xp.where(lo >= RANK_INF, 0.0, lo)
    blockcell = uses * (counts >= (meta["sp_skew"] + lo)[:, None])
    sp_node = _clip01(xp, blockcell @ ndc_t)
    masks = {"aa_m_node": aa_m_node, "aa_c_node": aa_c_node, "sp_node": sp_node}
    if hard_pa:
        masks["pa_unmatched_node"] = pa_unmatched_node
        masks["pa_inactive"] = pa_inactive.astype(xp.float32)
    if soft_spread:
        masks["sp_penalty_node"] = state["sps_counts"] @ ndc_t
    if soft_pa:
        # Preferred inter-pod terms: per-term match COUNT at each node's
        # domain; score_block adds pod_ppa_w (signed weights) @ this.
        masks["ppa_cnt_node"] = state["ppa_dom_cnt"] @ ndc_t + state["ppa_node_cnt"]
    return masks


def blocked_block(xp, blk: dict, masks: dict):
    """[B, N] constraint-blocked mask for one pod block (four matmuls)."""
    b = blk["pod_aa_carries"] @ masks["aa_m_node"]
    b = b + blk["pod_aa_matched"] @ masks["aa_c_node"]
    b = b + blk["pod_sp_declares"] @ masks["sp_node"]
    # Positive affinity with the bootstrap waiver: a declared term that is
    # globally inactive AND self-matched drops out of the pod's requirement
    # set for this round; every remaining declared term blocks its unmatched
    # nodes (terms AND — any unmet term blocks).  A non-self-matching pod
    # with an inactive term keeps it → unmatched everywhere → unschedulable
    # this round, exactly the scalar checker's "unmatchable" rule.
    if "pa_unmatched_node" in masks:
        gated = blk["pod_pa_declares"] * (1.0 - blk["pod_pa_matched"] * masks["pa_inactive"][None, :])
        b = b + gated @ masks["pa_unmatched_node"]
    return b > 0


def _scatter_min(xp, size: int, idx, vals):
    if xp is np:
        out = np.full((size,), RANK_INF, dtype=np.float32)
        np.minimum.at(out, idx, vals)
        return out
    return xp.full((size,), RANK_INF, dtype=xp.float32).at[idx].min(vals)


def _row_scatter_min(xp, n_rows: int, idx, vals):
    """out[r, c] = min over {p : idx[p] == r} of vals[p, c]  (RANK_INF fill).

    Row-granular scatters (one [C]-wide update per pod) lower to fast
    windowed scatters on TPU, unlike the near-serial scalar form."""
    if xp is np:
        out = np.full((n_rows, vals.shape[1]), RANK_INF, dtype=np.float32)
        np.minimum.at(out, idx, vals)
        return out
    return xp.full((n_rows, vals.shape[1]), RANK_INF, dtype=xp.float32).at[idx].min(vals)


def _row_scatter_max_t(xp, state_tn, idx, vals):
    """[T,N] state with state[c, idx[p]] = max(state, vals[p, c]) folded in —
    the row-scatter twin of the flattened t·n scalar scatter (transposed
    round-trip is two [T,N] relayouts, a rounding error next to the
    near-serial scalar form it replaces)."""
    if xp is np:
        out = state_tn.T.copy()  # always copy — callers may hold the old state
        np.maximum.at(out, idx, vals)
        return out.T
    return state_tn.T.at[idx].max(vals).T


def _row_scatter_add_t(xp, state_tn, idx, vals):
    """+= twin of :func:`_row_scatter_max_t` for count-valued state."""
    if xp is np:
        out = state_tn.T.copy()  # always copy — callers may hold the old state
        np.add.at(out, idx, vals)
        return out.T
    return state_tn.T.at[idx].add(vals).T


def _argsort_stable(xp, a):
    if xp is np:
        return np.argsort(a, kind="stable")
    return xp.argsort(a, stable=True)


def _cummax(xp, a):
    if xp is np:
        return np.maximum.accumulate(a)
    from jax import lax

    return lax.cummax(a, axis=0)


def constraint_filter(xp, accepted, choice, ranks, ps: dict, state: dict, meta: dict, hard_pa: bool = True) -> object:
    """Within-round conflict resolution — returns the surviving subset of
    ``accepted`` (see module docstring for the rank rules)."""
    ndc = meta["node_dom_c"]
    d = ndc.shape[1]
    n = ndc.shape[0]
    nd = ndc[choice]  # [P, D] one-hot domains of each pod's chosen node
    accf = accepted.astype(xp.float32)
    rank_f = ranks.astype(xp.float32)

    # ---- anti-affinity ----------------------------------------------------
    # Rule: in each (term, cell) — cell = coarse domain when the chosen node
    # carries the term's key, else the node itself — a matched pod survives
    # only if no earlier-rank accepted carrier shares the cell, and vice
    # versa.  "Earlier rank" ≡ earlier array index (pods are compacted in
    # priority-rank order), so existence-of-a-predecessor is an exclusive
    # cumsum along the pod axis on the dense path, and a min-rank reduction
    # on the fallback path — identical outcomes by construction.
    uses = meta["term_uses_dom"]  # [T, D]
    t = uses.shape[0]
    has_c = nd @ uses.T  # [P, T] 1 if the chosen node has the term's coarse key
    carr = ps["pod_aa_carries"] * accf[:, None]
    matc = ps["pod_aa_matched"] * accf[:, None]
    if t * d <= DENSE_CELLS:
        m3 = nd[:, None, :] * uses[None, :, :]  # [P,T,D] one-hot coarse cell under t

        def _earlier_in_cell(v):  # [P,T] 0/1 → [P,T] "an earlier v-pod shares my coarse cell"
            v3 = v[:, :, None] * m3
            ec = xp.cumsum(v3, axis=0) - v3  # exclusive
            return (ec * m3).sum(axis=2) > 0

        fine = has_c == 0
        carr_c, matc_c = carr * has_c, matc * has_c
        # Fine cells: min accepted rank per (node, term) via one row scatter.
        min_c_fine = _row_scatter_min(xp, n, choice, xp.where((carr * fine) > 0, rank_f[:, None], RANK_INF))
        min_m_fine = _row_scatter_min(xp, n, choice, xp.where((matc * fine) > 0, rank_f[:, None], RANK_INF))
        earlier_c = _earlier_in_cell(carr_c) | (fine & (rank_f[:, None] > min_c_fine[choice]))
        earlier_m = _earlier_in_cell(matc_c) | (fine & (rank_f[:, None] > min_m_fine[choice]))
        bad_aa = ((matc > 0) & earlier_c) | ((carr > 0) & earlier_m)
    else:
        cells = d + n
        dom_ids = xp.arange(d, dtype=xp.float32)
        cc = nd @ (uses * dom_ids[None, :]).T  # [P, T] coarse cell id (sum of ≤1 one-hot)
        cell = xp.where(has_c > 0, cc, d + choice[:, None].astype(xp.float32))
        g = (xp.arange(t, dtype=xp.float32)[None, :] * cells + cell).astype(xp.int32)  # [P, T]
        gf = g.reshape(-1)
        min_carrier = _scatter_min(xp, t * cells, gf, xp.where(carr > 0, rank_f[:, None], RANK_INF).reshape(-1))
        min_matched = _scatter_min(xp, t * cells, gf, xp.where(matc > 0, rank_f[:, None], RANK_INF).reshape(-1))
        min_c_at = min_carrier[g]  # [P, T]
        min_m_at = min_matched[g]
        bad_aa = ((matc > 0) & (rank_f[:, None] > min_c_at)) | ((carr > 0) & (rank_f[:, None] > min_m_at))
    keep = accepted & ~bad_aa.any(axis=1)

    # ---- positive affinity bootstrap (within-round) -----------------------
    # A term inactive at round start was waived for self-matching declarers
    # (blocked_block let them choose freely).  Sequentially, only the FIRST
    # accepted pod matching the term may rely on the waiver: any earlier-rank
    # accepted match re-activates the term before a later pod's turn in the
    # witness order, and the later pod's free placement would then violate
    # it.  Keep the min-rank accepted match; defer other waived declarers
    # one round (the term is then active and the round-start mask routes
    # them to its domain).  Over-inclusive min (it counts matches a later
    # filter may drop) only defers more — never admits a violation.
    if hard_pa:
        pa_inactive_f = ((state["pa_dom_m"].sum(axis=1) + state["pa_node_m"].sum(axis=1)) == 0).astype(xp.float32)
        keep_pa_f = keep.astype(xp.float32)
        pa_m_acc = ps["pod_pa_matched"] * keep_pa_f[:, None]  # [P, Ta]
        min_match_rank = xp.min(xp.where(pa_m_acc > 0, rank_f[:, None], RANK_INF), axis=0)  # [Ta]
        waived = ps["pod_pa_declares"] * ps["pod_pa_matched"] * pa_inactive_f[None, :]  # [P, Ta]
        bad_pa = (waived > 0) & keep[:, None] & (rank_f[:, None] > min_match_rank[None, :])
        keep = keep & ~bad_pa.any(axis=1)

    # ---- topology spread (vectorized over S) ------------------------------
    uses_sp = meta["sp_uses_dom"]  # [S, D]
    s_axis = uses_sp.shape[0]
    skew = meta["sp_skew"]  # [S]
    declares, matched = ps["pod_sp_declares"], ps["pod_sp_matched"]
    in_cell = nd @ uses_sp.T  # [P, S] 1 iff chosen node carries the key
    # Claimant mass (dm/dn) is based on ``keep`` — the survivors of the
    # anti-affinity and positive-affinity filters above — NOT on the raw
    # capacity accept: a pod those filters already dropped can never commit
    # this round, so counting it would (a) waste quota slots in the rank
    # prefix (a dead claimant at prefix 0 steals the slot from a live one,
    # deferring it a round for nothing) and (b) taint its cell's certainty
    # mass below, freezing the water line at one level per round — measured
    # as the 64-round tail at 50k x 5k with 10% AA/spread overlap
    # (scripts/bench_constrained.py).
    keep_f = keep.astype(xp.float32)
    dm = keep_f[:, None] * declares * matched * in_cell  # declaring+matching
    mo = accf[:, None] * (1.0 - declares) * matched  # matching-only (keyless→0 via matmul)
    dn = keep_f[:, None] * declares * (1.0 - matched) * in_cell  # declaring-only
    # Two count bases, deliberately different (soundness, not sloppiness):
    #   c0 — the quota DENOMINATOR — overcounts matching-only mass: every
    #     capacity-accepted NON-declaring matched pod is in, even ones a
    #     later constraint's quota drops.  Overcount only shrinks quota
    #     (conservative), and it is *required* for cross-constraint
    #     soundness: a pod kept by its own constraint's quota may land in
    #     this constraint's domain, so its mass must be assumed present at
    #     the declarer's turn in the witness order.  (Declaring claimants of
    #     THIS constraint need no such caution: their fate is decided by
    #     this constraint's own quota below.)
    #   c0_cert — the water-line (lo) base — counts only mass CERTAIN to
    #     place this round: round-start state plus post-anti-affinity
    #     survivors that declare no spread constraint (nothing after this
    #     filter can drop those).  Deriving lo from uncertain mass admitted
    #     real violations: pods capacity-accepted into other domains but
    #     deferred by their own skew quota inflated the min, opening quota
    #     here (caught by the replay certificate at synth seed 4).
    declares_n = declares.sum(axis=1)  # [P]
    declares_any = xp.minimum(declares_n, 1.0)
    certain = keep_f[:, None] * (1.0 - declares_any)[:, None] * matched
    c0 = state["sp_counts"] + (mo.T @ nd) * uses_sp  # [S, D]
    c0_cert = state["sp_counts"] + (certain.T @ nd) * uses_sp
    dem = (dm.T @ nd) * uses_sp  # [S, D]
    # A quota-kept claimant is certain iff nothing later can drop it: it
    # survived the filters above and this is its only spread constraint.
    # Cells containing any uncertain claimant contribute no fill to the
    # water line (an uncertain pod can hold a quota slot and then drop).
    dm_cert = dm * (declares_n == 1.0).astype(xp.float32)[:, None]
    dem_unc = dem - (dm_cert.T @ nd) * uses_sp  # [S, D] uncertain demand

    def _masked_lo(c):
        lo = xp.min(xp.where(uses_sp > 0, c, RANK_INF), axis=1)
        return xp.where(lo >= RANK_INF, 0.0, lo)

    def _fills(q):
        return xp.where(dem_unc == 0, xp.minimum(dem, q), 0.0)

    lo = _masked_lo(c0_cert)
    for _ in range(8):  # water-filling fixpoint (lo is nondecreasing)
        q = xp.maximum(0.0, (skew + lo)[:, None] - c0) * uses_sp
        lo = _masked_lo(c0_cert + _fills(q))
    q_final = xp.maximum(0.0, (skew + lo)[:, None] - c0) * uses_sp  # [S, D]

    # Rank-prefix of each declaring+matching pod within its (s, domain) cell
    # (array order == rank order among this round's claimants).  Dense path:
    # exclusive cumsum of the [P,S,D] claimant one-hot along the pod axis,
    # gathered at each pod's own cell — exact small-integer f32 counts.
    # Fallback for huge S·D: flatten (s, p) s-major so a stable sort by cell
    # id groups cells while preserving rank order, then position-in-segment
    # via a cummax of segment starts.
    if s_axis * d <= DENSE_CELLS:
        m3_sp = nd[:, None, :] * uses_sp[None, :, :]  # [P,S,D] claimant cell one-hot
        c3 = dm[:, :, None] * m3_sp
        ec3 = xp.cumsum(c3, axis=0) - c3  # exclusive
        prefix = (ec3 * m3_sp).sum(axis=2)  # [P, S]
    else:
        p_axis = nd.shape[0]
        dom_ids = xp.arange(d, dtype=xp.float32)
        cc_sp = nd @ (uses_sp * dom_ids[None, :]).T  # [P, S] coarse cell id
        cells_sp = d + 1
        sentinel = xp.float32(d)
        cell_sp = xp.where(dm > 0, cc_sp, sentinel)  # non-claimants → shared sentinel cell
        g_sp = (xp.arange(s_axis, dtype=xp.float32)[None, :] * cells_sp + cell_sp).T.reshape(-1)  # [S*P]
        order = _argsort_stable(xp, g_sp)
        g_sorted = g_sp[order]
        idx = xp.arange(s_axis * p_axis, dtype=xp.float32)
        is_start = xp.concatenate([xp.ones((1,), dtype=bool), g_sorted[1:] != g_sorted[:-1]])
        seg_start = _cummax(xp, xp.where(is_start, idx, 0.0))
        pos_sorted = idx - seg_start
        if xp is np:
            pos_flat = np.empty_like(pos_sorted)
            pos_flat[order] = pos_sorted
        else:
            pos_flat = xp.zeros_like(pos_sorted).at[order].set(pos_sorted)
        prefix = pos_flat.reshape(s_axis, p_axis).T  # [P, S]

    q_at = nd @ q_final.T  # [P, S] quota of own cell (0 where keyless)
    keep_dm = prefix < q_at
    c_final = c0 + xp.minimum(dem, q_final)  # inflated (conservative) counts
    lo_final = _masked_lo(c0_cert + _fills(q_final))  # certain water line
    c_at = nd @ c_final.T  # [P, S]
    keep_dn = (c_at + 1.0) <= (skew + lo_final)[None, :]
    bad_sp = ((dm > 0) & ~keep_dm) | ((dn > 0) & ~keep_dn)
    return keep & ~bad_sp.any(axis=1)


def constraint_commit(
    xp,
    accepted,
    choice,
    ps: dict,
    state: dict,
    meta: dict,
    soft_spread: bool = False,
    soft_pa: bool = False,
    hard_pa: bool = True,
) -> dict:
    """Fold the round's final accepted placements into the domain state."""
    ndc = meta["node_dom_c"]
    nd = ndc[choice]
    accf = accepted.astype(xp.float32)
    matc = ps["pod_aa_matched"] * accf[:, None]  # [P, T]
    carr = ps["pod_aa_carries"] * accf[:, None]
    uses = meta["term_uses_dom"]
    aa_dom_m = _clip01(xp, state["aa_dom_m"] + (matc.T @ nd) * uses)
    aa_dom_c = _clip01(xp, state["aa_dom_c"] + (carr.T @ nd) * uses)
    # Fine-granularity: chosen node lacks the term's coarse key (or the key
    # itself is fine) → the node is its own domain.  Row scatters (one
    # [T]-wide update per pod, see _row_scatter_max_t) replace the flattened
    # t·n scalar form — bit-identical, ~free vs ~14 ms each on TPU.
    has_c = nd @ uses.T  # [P, T]
    aa_node_m = _row_scatter_max_t(xp, state["aa_node_m"], choice, matc * (has_c == 0))
    aa_node_c = _row_scatter_max_t(xp, state["aa_node_c"], choice, carr * (has_c == 0))
    if hard_pa:
        # Positive affinity: every accepted pod matching a PA term activates
        # its landing domain (declaring or not — matches are matches).
        uses_pa = meta["pa_uses_dom"]
        matc_pa = ps["pod_pa_matched"] * accf[:, None]  # [P, Ta]
        pa_dom_m = _clip01(xp, state["pa_dom_m"] + (matc_pa.T @ nd) * uses_pa)
        has_c_pa = nd @ uses_pa.T  # [P, Ta]
        pa_node_m = _row_scatter_max_t(xp, state["pa_node_m"], choice, matc_pa * (has_c_pa == 0))
    else:
        pa_dom_m = state["pa_dom_m"]
        pa_node_m = state["pa_node_m"]
    if soft_pa:
        # Preferred terms: accepted matched pods bump their landing domain's
        # count (coarse) or node's count (fine/keyless) — same split as PA.
        uses_ppa = meta["ppa_uses_dom"]
        matc_ppa = ps["pod_ppa_matched"] * accf[:, None]  # [P, Tp]
        ppa_dom_cnt = state["ppa_dom_cnt"] + (matc_ppa.T @ nd) * uses_ppa
        has_c_ppa = nd @ uses_ppa.T  # [P, Tp]
        ppa_node_cnt = _row_scatter_add_t(xp, state["ppa_node_cnt"], choice, matc_ppa * (has_c_ppa == 0))
    else:
        ppa_dom_cnt = state["ppa_dom_cnt"]
        ppa_node_cnt = state["ppa_node_cnt"]
    sp_m = ps["pod_sp_matched"] * accf[:, None]  # [P, S]
    sp_counts = state["sp_counts"] + (sp_m.T @ nd) * meta["sp_uses_dom"]
    if soft_spread:
        sps_m = ps["pod_sps_matched"] * accf[:, None]  # [P, Ss]
        sps_counts = state["sps_counts"] + (sps_m.T @ nd) * meta["sps_uses_dom"]
    else:
        sps_counts = state["sps_counts"]
    return {
        "aa_dom_m": aa_dom_m,
        "aa_dom_c": aa_dom_c,
        "aa_node_m": aa_node_m,
        "aa_node_c": aa_node_c,
        "pa_dom_m": pa_dom_m,
        "pa_node_m": pa_node_m,
        "ppa_dom_cnt": ppa_dom_cnt,
        "ppa_node_cnt": ppa_node_cnt,
        "sp_counts": sp_counts,
        "sps_counts": sps_counts,
    }
