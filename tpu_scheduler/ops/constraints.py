"""Tensorized inter-pod anti-affinity, POSITIVE pod affinity + topology
spread (BASELINE config 5).

The scalar predicates (core/predicates.py: anti_affinity_ok /
topology_spread_ok) are pods×pods×nodes relations — the memory wall SURVEY.md
§2b SP/CP warns about.  This module never materializes that 3-tensor.  The
key observation: both predicates only consult *topology domains* (the set of
nodes sharing a value of the term's topology key), so the device state is
domain-granular:

  AA term vocab T:  distinct (namespace, topology_key, selector) terms among
                    pending + placed pods.
  PA term vocab Ta: positive (requiredDuringScheduling podAffinity) terms
                    among PENDING pods only — affinity constrains just the
                    declarer, so placed pods' terms need no columns.  The
                    blocked mask is the inverted matched-domain mask, gated
                    by the bootstrap waiver (a term matching nothing
                    anywhere is waived for self-matching declarers); the
                    within-round filter keeps only the first accepted match
                    per waived term (see constraint_filter).
  Spread vocab S:   distinct (namespace, key, max_skew, selector) constraints
                    among pending pods.
  Coarse domains D: (key, value) pairs over the referenced topology keys —
                    node_dom_c[N, D] is each node's one-hot domain membership
                    (one column per key it carries).
  Fine domains:     keys whose values are unique per node (hostname-like) and
                    nodes lacking a coarse key degrade to per-node singleton
                    domains — state at node granularity [T, N], exactly as
                    the scalar ``("~node", name)`` rule.

Per auction round (ops/assign.py), the blocked pods×nodes mask is three
matmuls — pod_carries[B,T] @ aa_matched_node[T,N] etc. — so constrained pods
ride the same MXU path as everything else; per-round state updates are
[T,P]@[P,D] matmuls plus O(P·T) scatters.

Within-round conflicts (two mutually-anti-affine pods accepted into one
domain in the same round; a domain over-filling past max_skew) are resolved
by rank (the auction's priority order):
  • AA: in each (term, domain) cell, a matched pod survives only if it
    out-ranks every accepted carrier in the cell and vice versa (exact
    min-rank rule; at worst it defers a pod the greedy oracle would accept
    by one round — never admits a violation).
  • Spread: rank-prefix admission.  A declarer on a keyed node is kept iff
    ``count(cell) + prefix(p) + 1 ≤ max_skew + lo_p`` where ``prefix(p)``
    is the matched CANDIDATE mass of lower rank in its cell and ``lo_p``
    the per-pod water line — the round-start minimum lifted by the
    COMMITTED lower-rank fills of SPREAD_CASCADE in-round sweeps.  All
    in-round matched mass rides the rank prefix (not a static denominator),
    so two same-selector constraints can no longer mutually freeze each
    other's quotas, and whole multi-level waves admit per round.
Deferred pods stay active and retry next round against the committed state;
the round-start choose mask blocks domains beyond the cascade's reach, so
claimants target cells the filter can actually admit.

Filter cost model (round 7): only ACCEPTED claimants can conflict, so
``constraint_filter`` gathers them into a compact [A] workspace before any
cell machinery runs (exact rows in NumPy; a stable accepted-first partition
whose scans stop after ``ceil(A / ACTIVE_CHUNK)`` tiles under jit) and
scatters survivors back — per-round filter cost tracks the accepted count,
not the padded pod axis.  Per-pod cell lookups ride one banded gather
matmul, the AA carrier/matched predecessor checks one fused segment
scatter-min over a unified (term, coarse-domain ∪ node) cell space, and the
spread water line / PA bootstrap flags are ROUND-CARRIED state
(``augment_round_state``) updated incrementally by ``constraint_commit``
instead of re-derived from the domain history every round.  All of it is
bitwise-neutral: masses are exact small-integer f32, so dropping zero rows,
banding independent matmul columns, and re-chunking prefix sums cannot
change a single admission.

Validity is *order-witnessed*: each round's kept set admits a sequential
order in which every placement passes the scalar chain — ASCENDING RANK for
both predicates: no conflicting AA pair survives at all, and a kept spread
declarer at its turn sees cell count ≤ count0 + prefix(p) (kept ⊆
candidates) and min ≥ lo_p (its cascade fills are lower-rank commits,
placed before it), so ``count+1−min ≤ max_skew`` holds at its turn
(tests/test_constraints_tensor.py replays this certificate through
core/predicates.py).  This holds uniformly for multi-constraint declarers —
admission requires every declared constraint's bound at the same rank turn.

Everything is written against an ``xp`` namespace (numpy | jax.numpy) so the
native and TPU backends share one expression tree — the same bit-parity
contract as ops/masks.py.

Scale guards: clusters whose constraint structure exceeds the static budgets
(too many distinct terms, or a many-valued non-unique topology key) raise
:class:`UntensorizableConstraints`; the controller then falls back to the
exact host-side sequential phase (runtime/controller.py), so the tensor path
is an accelerator, never a semantics change.
"""

from __future__ import annotations

import contextlib
from dataclasses import dataclass

import numpy as np

from ..api.objects import Pod
from ..core.predicates import term_matches
from .pack import round_up

__all__ = [
    "ConstraintSet",
    "UntensorizableConstraints",
    "pack_constraints",
    "prune_match_memo",
    "augment_round_state",
    "round_blocked_masks",
    "blocked_block",
    "constraint_filter",
    "constraint_commit",
    "RANK_INF",
]

RANK_INF = np.float32(3.0e38)

# Default budgets (padded): sized so the per-term state ([T,N]/[S,D], ~10 MB
# at 256×10k) and the pod-side bitmaps ([P,T] etc., ~110 MB each at 100k×256)
# stay well under HBM at north-star scale while admitting realistic
# vocabularies — per-app selectors (one term per deployment) are the common
# shape, and a 50-deployment cluster with two skew levels already needs ~100
# spread terms.  History: the original 128/64 budgets silently routed the
# CLI's own mixed workload to the exact-but-glacial host sequential phase
# (UntensorizableConstraints fallback — measured 482 s for ONE 10k×1k cycle
# vs ~1 s on the tensor path), so the defaults now match what the hardware
# comfortably holds, and the controller exposes them as knobs.
MAX_AA_TERMS = 256
MAX_SPREAD = 256
MAX_COARSE_DOMAINS = 256

# Fast-path budget for the ANTI-AFFINITY within-round filter: below this
# terms×D product, "who came earlier into my cell" is computed DENSELY — a
# [A,T,D] exclusive cumsum along the (rank-ordered) pod axis of the ACTIVE
# workspace (see constraint_filter) — instead of the fused scatter-min
# formulation.  On TPU through the tunnel the difference is stark (measured
# at 53k pods: scalar scatter_min ~43 ms per round vs ~2-3 ms for the cumsum
# 3-tensor), because XLA lowers arbitrary-index scalar scatters near-serially
# while cumsums ride the parallel prefix path; since the round-7 active-set
# compaction the scatter index count tracks the accepted workspace, so the
# fused segment path is the default at every real vocabulary and the dense
# path survives for sub-budget term structures.  Bit-identical results
# either way — counts are small exact f32 integers and array order IS rank
# order (tests/test_constraints_tensor.py pins parity exactly at this
# threshold).  (The SPREAD filter has no such split: its rank-prefix
# admission always uses the cell formulation, chunked along the pod axis —
# see _cell_rank_prefix.)
DENSE_CELLS = 1024
# The cells product alone does not bound the 3-tensor: its bytes scale with
# the POD axis too (round-4 advisor finding — at 128k padded pods a
# threshold-sized [P,T,D] is ~0.5 GB, and several temporaries live inside
# the jit round body at once).  The dense path therefore also requires
# p·t·d·4 ≤ this per-tensor byte budget; the flagship constrained shape
# (106k × 832 spread cells ≈ 354 MB, measured fast and well inside v5e-1's
# 16 GB HBM) stays dense, while larger pod axes degrade to the sort/scatter
# formulation — same results, bounded memory.  The in-jit size chain
# (ops/assign.py) re-evaluates the predicate per stage, so shrunk tail
# stages can re-enter the dense path even when the full-size stage could not.
DENSE_TENSOR_BYTES = 400 * 1024 * 1024


# shape: (p: int, cells: int) -> bool
def _dense_ok(p: int, cells: int) -> bool:
    return cells <= DENSE_CELLS and p * cells * 4 <= DENSE_TENSOR_BYTES


# Within-round water-line sweeps of the spread admission filter
# (constraint_filter) — each sweep can lift a constraint's certain minimum
# one level, so a round admits up to this many fill levels at once; the
# choose-time mask (round_blocked_masks) offers declarers domains within the
# same reach.  4 sweeps measured best on the flagship constrained row: each
# sweep only lifts levels whose fills come from LOWER-RANK commits, and
# cross-cell rank interleaving caps the useful depth — 8 sweeps bound no
# more pods and cost ~0.3 s/cycle more ([P,S,D] cumsum per sweep).  MUST be
# a global constant: a size-dependent sweep count would make admission
# depend on the stage shape and break native↔TPU bit-parity.
SPREAD_CASCADE = 4


class UntensorizableConstraints(Exception):
    """Constraint structure exceeds the tensor budgets — use the host path."""


# Sentinel key under which a match_memo stores the term-vocabulary signature
# it is valid for.  Key spaces (owned HERE, with prune_match_memo and
# _sig_independent — callers must not hand-filter by key type):
#   _MEMO_SIG            — the signature sentinel
#   id(pod) ints         — matched-term ids (vocab-DEPENDENT)
#   ("dk", id(pod))      — declared canonical keys (vocab-independent)
_MEMO_SIG = "sig"
_MEMO_DK = "dk"


def _sig_independent(k) -> bool:
    """Memo keys that survive a vocabulary-signature change."""
    return isinstance(k, tuple) and len(k) == 2 and k[0] == _MEMO_DK


# shape: (memo: dict, live_ids: obj) -> dict
def prune_match_memo(memo: dict, live_ids: set) -> dict:
    """Drop memo entries for dead pod objects, preserving the signature
    sentinel (see the key-space table above)."""
    return {
        k: v
        for k, v in memo.items()
        if k == _MEMO_SIG or k in live_ids or (isinstance(k, tuple) and k[1] in live_ids)
    }




def _term_probe_index(term_list):
    """(indexed, residual) over ``[(key, (ns, term)), ...]`` — the matched-
    bitmap hot loops are O(pods × terms) naively (13M term_matches calls at
    50k pods × ~260 terms, ~15 s host-side); a term with match_labels can
    only match a pod carrying its first sorted (k, v) pair, so pods probe
    the index with their own labels and run the full matcher on the few
    candidates (the same near-linear trick as the controller's
    _split_affinity_pending).  Terms without match_labels land in the
    per-namespace residual."""
    indexed: dict[tuple, list[int]] = {}
    residual: dict[str | None, list[int]] = {}
    for ti, (_key, (t_ns, term)) in enumerate(term_list):
        ml = term.match_labels
        if ml:
            k, v = sorted(ml.items())[0]
            indexed.setdefault((t_ns, k, v), []).append(ti)
        else:
            residual.setdefault(t_ns, []).append(ti)
    return indexed, residual


def _matched_term_ids(term_list, indexed, residual, ns, labels):
    """Term indices of ``term_list`` whose selector matches ``labels`` in
    namespace ``ns`` — candidates from the probe index, verified exactly."""
    cand: set[int] = set(residual.get(ns, ()))
    if labels:
        for kv in labels.items():
            cand.update(indexed.get((ns, kv[0], kv[1]), ()))
    return [ti for ti in cand if term_matches(term_list[ti][1][1], labels)]


def _canon_selector(match_labels, match_expressions) -> tuple:
    ml = tuple(sorted((match_labels or {}).items()))
    mx = tuple(
        sorted(
            (r.key, r.operator, tuple(sorted(r.values or ())) if r.operator in ("In", "NotIn") else tuple(r.values or ()))
            for r in (match_expressions or [])
        )
    )
    return (ml, mx)


def _aa_key(ns, term) -> tuple:
    return (ns, term.topology_key, _canon_selector(term.match_labels, term.match_expressions))


def _sp_key(ns, c) -> tuple:
    return (ns, c.topology_key, int(c.max_skew), _canon_selector(c.match_labels, c.match_expressions))


@dataclass(frozen=True)
class ConstraintSet:
    """Device tensors for AA + spread over one packed cycle.

    Pod rows align with PackedCluster's pending-pod order (padded to P).
    State arrays are the *round-start* state (from placed pods); the auction
    threads them through its while-loop carry.
    """

    # Pod side [P, T] / [P, Ta] / [P, S] / [P, Ss] float32
    pod_aa_carries: np.ndarray
    pod_aa_matched: np.ndarray
    pod_pa_declares: np.ndarray  # positive affinity: the pod declares term
    pod_pa_matched: np.ndarray  # the pod's labels satisfy the term's selector
    pod_sp_declares: np.ndarray
    pod_sp_matched: np.ndarray
    pod_sps_declares: np.ndarray  # soft (ScheduleAnyway) spread declarations
    pod_sps_matched: np.ndarray
    pod_ppa_w: np.ndarray  # [P, Tp] SIGNED preferred-(anti-)affinity weights
    pod_ppa_matched: np.ndarray  # [P, Tp] pod matches the preferred term
    # Node side
    node_dom_c: np.ndarray  # [N, D] float32 one-hot (one col per carried key)
    # Term metadata
    term_uses_dom: np.ndarray  # [T, D] float32 — domains of the term's key
    pa_uses_dom: np.ndarray  # [Ta, D] float32 — positive-affinity term keys
    ppa_uses_dom: np.ndarray  # [Tp, D] float32 — preferred-term keys
    sp_uses_dom: np.ndarray  # [S, D] float32
    sp_skew: np.ndarray  # [S] float32
    sps_uses_dom: np.ndarray  # [Ss, D] float32 — soft-spread constraint keys
    # Spread-domain selection [D, Ds] one-hot: the Ds ≤ D coarse domains any
    # HARD spread constraint references.  The filter's [·,S,D] cell passes
    # project through it so their domain axis carries only spread-relevant
    # columns (a zone-keyed cluster runs them at Ds=8 instead of the full
    # padded vocabulary) — dropped columns have sp_uses_dom ≡ 0, so every
    # product/min they fed was identically zero/INF and admissions are
    # bitwise unchanged.
    sp_dom_sel: np.ndarray
    # Initial state (from placed pods)
    aa_dom_m: np.ndarray  # [T, D] 0/1 — domain holds a pod matched by term
    aa_dom_c: np.ndarray  # [T, D] 0/1 — domain holds a carrier of term
    aa_node_m: np.ndarray  # [T, N] 0/1 — fine-granularity (singleton) twin
    aa_node_c: np.ndarray  # [T, N] 0/1
    pa_dom_m: np.ndarray  # [Ta, D] 0/1 — domain holds a pod matched by PA term
    pa_node_m: np.ndarray  # [Ta, N] 0/1 — fine-granularity twin
    ppa_dom_cnt: np.ndarray  # [Tp, D] float32 — preferred-term match counts
    ppa_node_cnt: np.ndarray  # [Tp, N] float32 — fine-granularity twin
    sp_counts: np.ndarray  # [S, D] float32 — matching placed pods per domain
    sps_counts: np.ndarray  # [Ss, D] float32 — soft-spread matching counts

    n_terms: int
    n_pa_terms: int
    n_ppa_terms: int
    n_spread: int
    n_spread_soft: int

    def pod_arrays(self) -> dict:
        return {
            "pod_aa_carries": self.pod_aa_carries,
            "pod_aa_matched": self.pod_aa_matched,
            "pod_pa_declares": self.pod_pa_declares,
            "pod_pa_matched": self.pod_pa_matched,
            "pod_sp_declares": self.pod_sp_declares,
            "pod_sp_matched": self.pod_sp_matched,
            "pod_sps_declares": self.pod_sps_declares,
            "pod_sps_matched": self.pod_sps_matched,
            "pod_ppa_w": self.pod_ppa_w,
            "pod_ppa_matched": self.pod_ppa_matched,
        }

    def meta_arrays(self) -> dict:
        return {
            "node_dom_c": self.node_dom_c,
            "term_uses_dom": self.term_uses_dom,
            "pa_uses_dom": self.pa_uses_dom,
            "ppa_uses_dom": self.ppa_uses_dom,
            "sp_uses_dom": self.sp_uses_dom,
            "sp_skew": self.sp_skew,
            "sps_uses_dom": self.sps_uses_dom,
            "sp_dom_sel": self.sp_dom_sel,
        }

    def state_arrays(self) -> dict:
        return {
            "aa_dom_m": self.aa_dom_m,
            "aa_dom_c": self.aa_dom_c,
            "aa_node_m": self.aa_node_m,
            "aa_node_c": self.aa_node_c,
            "pa_dom_m": self.pa_dom_m,
            "pa_node_m": self.pa_node_m,
            "ppa_dom_cnt": self.ppa_dom_cnt,
            "ppa_node_cnt": self.ppa_node_cnt,
            "sp_counts": self.sp_counts,
            "sps_counts": self.sps_counts,
        }


def pack_constraints(
    snapshot,
    pending: list[Pod],
    padded_pods: int,
    node_names: tuple[str, ...],
    padded_nodes: int,
    max_aa_terms: int = MAX_AA_TERMS,
    max_spread: int = MAX_SPREAD,
    max_coarse_domains: int = MAX_COARSE_DOMAINS,
    label_block: int = 8,
    match_memo: dict | None = None,
) -> ConstraintSet | None:
    """Build constraint tensors for one cycle; None if nothing constrained.

    Raises :class:`UntensorizableConstraints` when the structure exceeds the
    budgets (the controller's cue to run the host sequential phase instead).

    ``match_memo`` (same contract as ops/pack.py ``res_memo``: object-
    identity keyed, ``id(pod) -> (pod, matched-id tuples)``, caller-owned
    and caller-pruned) memoizes the five selector-match queries per pod —
    the dominant host cost of a constrained cycle (the matched-bitmap and
    placed-state loops are O(pods × terms) term_matches calls without it;
    PERF.md "known remaining headroom").  The memo is only valid for one
    term-vocabulary signature: it self-clears whenever the vocab changes
    (a new app's term appearing is a full-rematch event, steady-state
    cycles hit ~100%).  The API layer replaces pod objects on every
    modification, so identity hits are exactly the unchanged pods."""
    nodes = list(snapshot.nodes)
    assert tuple(n.name for n in nodes) == tuple(node_names)

    def _declared(pod):
        """The pod's declared canonical keys, memoized by object identity:
        (aa [(key, term)], pa [(key, term)], ppa [(key, term, signed_w)],
        sp [(key, c)], sps [(key, c)]).  Valid independent of the term
        vocabulary (derived from the pod object alone), so cached under a
        ("dk", id) key that survives vocab changes only incidentally — a
        sig-triggered clear recomputes it for the price of one pass."""
        mk = (_MEMO_DK, id(pod))
        if match_memo is not None:
            hit = match_memo.get(mk)
            if hit is not None and hit[0] is pod:
                return hit[1]
        ns, spec = pod.metadata.namespace, pod.spec
        aa = [(_aa_key(ns, t), t) for t in (spec.anti_affinity or ())] if spec is not None else []
        pa = [(_aa_key(ns, t), t) for t in (spec.pod_affinity or ())] if spec is not None else []
        ppa = []
        sp: list = []
        sps: list = []
        if spec is not None:
            for w in spec.preferred_pod_affinity or ():
                ppa.append((_aa_key(ns, w.term), w.term, float(w.weight)))
            for w in spec.preferred_pod_anti_affinity or ():
                ppa.append((_aa_key(ns, w.term), w.term, -float(w.weight)))
            for c in spec.topology_spread or ():
                (sp if c.is_hard else sps).append((_sp_key(ns, c), c))
        data = (aa, pa, ppa, sp, sps)
        # Unconstrained pods: recomputing the five empty lists is cheaper
        # than a memo entry per pod (the memo would double in size).
        if match_memo is not None and (aa or pa or ppa or sp or sps):
            match_memo[mk] = (pod, data)
        return data

    # --- vocabularies -----------------------------------------------------
    aa_vocab: dict[tuple, tuple] = {}  # key -> (ns, term)
    pa_vocab: dict[tuple, tuple] = {}
    ppa_vocab: dict[tuple, tuple] = {}  # preferred (soft, signed) — scoring only
    sp_vocab: dict[tuple, tuple] = {}  # hard (DoNotSchedule) — blocking
    sps_vocab: dict[tuple, tuple] = {}  # soft (ScheduleAnyway) — scoring only
    for p in pending:
        ns = p.metadata.namespace
        aa, pa, ppa, sp, sps = _declared(p)
        for key, t in aa:
            aa_vocab.setdefault(key, (ns, t))
        # Positive affinity: only PENDING pods' terms constrain anyone (no
        # symmetric direction — a placed pod's affinity is already satisfied).
        for key, t in pa:
            pa_vocab.setdefault(key, (ns, t))
        for key, t, _w in ppa:
            ppa_vocab.setdefault(key, (ns, t))
        for key, c in sp:
            sp_vocab.setdefault(key, (ns, c))
        for key, c in sps:
            sps_vocab.setdefault(key, (ns, c))
    # One _declared pass per placed carrier: the (key, term) pairs feed both
    # the vocab walk here and the carrier-mark loop at the bottom.
    placed_carrier_keys = [(q, qn, _declared(q)[0]) for q, qn in snapshot.placed_pods_with_terms()]
    for q, _qn, aa_d in placed_carrier_keys:
        ns = q.metadata.namespace
        for key, t in aa_d:
            aa_vocab.setdefault(key, (ns, t))

    if not aa_vocab and not pa_vocab and not ppa_vocab and not sp_vocab and not sps_vocab:
        return None
    if len(aa_vocab) > max_aa_terms:
        raise UntensorizableConstraints(f"{len(aa_vocab)} anti-affinity terms > budget {max_aa_terms}")
    if len(pa_vocab) > max_aa_terms:
        raise UntensorizableConstraints(f"{len(pa_vocab)} pod-affinity terms > budget {max_aa_terms}")
    if len(ppa_vocab) > max_aa_terms:
        raise UntensorizableConstraints(f"{len(ppa_vocab)} preferred pod-affinity terms > budget {max_aa_terms}")
    if len(sp_vocab) > max_spread:
        raise UntensorizableConstraints(f"{len(sp_vocab)} spread constraints > budget {max_spread}")
    if len(sps_vocab) > max_spread:
        raise UntensorizableConstraints(f"{len(sps_vocab)} soft spread constraints > budget {max_spread}")

    # --- topology keys → coarse domains or fine (per-node) ----------------
    keys = (
        {k for (_ns, k, _sel) in aa_vocab}
        | {k for (_ns, k, _sel) in pa_vocab}
        | {k for (_ns, k, _sel) in ppa_vocab}
        | {k for (_ns, k, _sk, _sel) in sp_vocab}
        | {k for (_ns, k, _sk, _sel) in sps_vocab}
    )
    spread_keys = {k for (_ns, k, _sk, _sel) in sp_vocab} | {k for (_ns, k, _sk, _sel) in sps_vocab}
    key_values: dict[str, dict[str, list[int]]] = {k: {} for k in keys}
    for i, n in enumerate(nodes):
        labels = n.metadata.labels or {}
        for k in keys:
            v = labels.get(k)
            if v is not None:
                key_values[k].setdefault(v, []).append(i)

    dom_vocab: dict[tuple[str, str], int] = {}  # (key, value) -> column
    fine_keys: set[str] = set()
    budget = max_coarse_domains
    for k in sorted(keys):
        vals = key_values[k]
        if len(vals) <= budget - len(dom_vocab):
            for v in sorted(vals):
                dom_vocab[(k, v)] = len(dom_vocab)
        elif all(len(nids) == 1 for nids in vals.values()):
            # Hostname-like: unique value per node ⇒ domain ≡ node, exact at
            # fine granularity with zero coarse columns.
            fine_keys.add(k)
            if k in spread_keys:
                raise UntensorizableConstraints(f"spread key {k!r} is per-node-granular ({len(vals)} values)")
        else:
            raise UntensorizableConstraints(f"topology key {k!r} has {len(vals)} shared-value domains > budget")

    d_pad = round_up(max(len(dom_vocab), 1), label_block)
    t_pad = round_up(max(len(aa_vocab), 1), label_block)
    ta_pad = round_up(max(len(pa_vocab), 1), label_block)
    tp_pad = round_up(max(len(ppa_vocab), 1), label_block)
    s_pad = round_up(max(len(sp_vocab), 1), label_block)
    ss_pad = round_up(max(len(sps_vocab), 1), label_block)
    n_pad = padded_nodes

    node_dom_c = np.zeros((n_pad, d_pad), dtype=np.float32)
    for (k, v), j in dom_vocab.items():
        for i in key_values[k][v]:
            node_dom_c[i, j] = 1.0

    aa_terms = list(aa_vocab.items())  # [(key, (ns, term))]
    pa_terms = list(pa_vocab.items())
    ppa_terms = list(ppa_vocab.items())
    sp_terms = list(sp_vocab.items())
    sps_terms = list(sps_vocab.items())

    term_uses_dom = np.zeros((t_pad, d_pad), dtype=np.float32)
    for ti, (key, (_ns, term)) in enumerate(aa_terms):
        if term.topology_key not in fine_keys:
            for v in key_values.get(term.topology_key, ()):  # noqa: B007
                term_uses_dom[ti, dom_vocab[(term.topology_key, v)]] = 1.0
    pa_uses_dom = np.zeros((ta_pad, d_pad), dtype=np.float32)
    for ti, (key, (_ns, term)) in enumerate(pa_terms):
        if term.topology_key not in fine_keys:
            for v in key_values.get(term.topology_key, ()):  # noqa: B007
                pa_uses_dom[ti, dom_vocab[(term.topology_key, v)]] = 1.0
    ppa_uses_dom = np.zeros((tp_pad, d_pad), dtype=np.float32)
    for ti, (key, (_ns, term)) in enumerate(ppa_terms):
        if term.topology_key not in fine_keys:
            for v in key_values.get(term.topology_key, ()):  # noqa: B007
                ppa_uses_dom[ti, dom_vocab[(term.topology_key, v)]] = 1.0
    sp_uses_dom = np.zeros((s_pad, d_pad), dtype=np.float32)
    sp_skew = np.zeros((s_pad,), dtype=np.float32)
    for si, (key, (_ns, c)) in enumerate(sp_terms):
        sp_skew[si] = float(c.max_skew)
        for v in key_values.get(c.topology_key, ()):
            sp_uses_dom[si, dom_vocab[(c.topology_key, v)]] = 1.0
    sps_uses_dom = np.zeros((ss_pad, d_pad), dtype=np.float32)
    for si, (key, (_ns, c)) in enumerate(sps_terms):
        for v in key_values.get(c.topology_key, ()):
            sps_uses_dom[si, dom_vocab[(c.topology_key, v)]] = 1.0
    # Spread-domain selection (see the ConstraintSet field comment): one-hot
    # columns for the domains any hard spread constraint references, padded
    # to the label block so the filter's cell passes stay tile-aligned.
    sp_cols = np.flatnonzero((sp_uses_dom > 0).any(axis=0))
    ds_pad = round_up(max(len(sp_cols), 1), label_block)
    sp_dom_sel = np.zeros((d_pad, ds_pad), dtype=np.float32)
    sp_dom_sel[sp_cols, np.arange(len(sp_cols))] = 1.0

    # --- pod-side bitmaps -------------------------------------------------
    pod_aa_carries = np.zeros((padded_pods, t_pad), dtype=np.float32)
    pod_aa_matched = np.zeros((padded_pods, t_pad), dtype=np.float32)
    pod_pa_declares = np.zeros((padded_pods, ta_pad), dtype=np.float32)
    pod_pa_matched = np.zeros((padded_pods, ta_pad), dtype=np.float32)
    pod_sp_declares = np.zeros((padded_pods, s_pad), dtype=np.float32)
    pod_sp_matched = np.zeros((padded_pods, s_pad), dtype=np.float32)
    pod_sps_declares = np.zeros((padded_pods, ss_pad), dtype=np.float32)
    pod_sps_matched = np.zeros((padded_pods, ss_pad), dtype=np.float32)
    pod_ppa_w = np.zeros((padded_pods, tp_pad), dtype=np.float32)
    pod_ppa_matched = np.zeros((padded_pods, tp_pad), dtype=np.float32)
    aa_index = {key: i for i, (key, _) in enumerate(aa_terms)}
    pa_index = {key: i for i, (key, _) in enumerate(pa_terms)}
    ppa_index = {key: i for i, (key, _) in enumerate(ppa_terms)}
    sp_index = {key: i for i, (key, _) in enumerate(sp_terms)}
    sps_index = {key: i for i, (key, _) in enumerate(sps_terms)}
    aa_probe, aa_res = _term_probe_index(aa_terms)
    pa_probe, pa_res = _term_probe_index(pa_terms)
    ppa_probe, ppa_res = _term_probe_index(ppa_terms)
    sp_probe, sp_res = _term_probe_index(sp_terms)
    sps_probe, sps_res = _term_probe_index(sps_terms)

    if match_memo is not None:
        sig = (
            tuple(k for k, _ in aa_terms),
            tuple(k for k, _ in pa_terms),
            tuple(k for k, _ in ppa_terms),
            tuple(k for k, _ in sp_terms),
            tuple(k for k, _ in sps_terms),
        )
        if match_memo.get(_MEMO_SIG) != sig:
            # Matched-id entries are vocab-dependent — drop them; declared-
            # keys entries derive from the pod object alone and survive
            # (_sig_independent owns that distinction).
            keep = {k: v for k, v in match_memo.items() if _sig_independent(k)}
            match_memo.clear()
            match_memo.update(keep)
            match_memo[_MEMO_SIG] = sig

    def _matched_all(pod):
        """(aa, pa, ppa, sp, sps) matched-id lists for one pod, memoized."""
        if match_memo is not None:
            hit = match_memo.get(id(pod))
            if hit is not None and hit[0] is pod:
                return hit[1]
        ns, labels = pod.metadata.namespace, pod.metadata.labels
        ids = (
            _matched_term_ids(aa_terms, aa_probe, aa_res, ns, labels),
            _matched_term_ids(pa_terms, pa_probe, pa_res, ns, labels),
            _matched_term_ids(ppa_terms, ppa_probe, ppa_res, ns, labels),
            _matched_term_ids(sp_terms, sp_probe, sp_res, ns, labels),
            _matched_term_ids(sps_terms, sps_probe, sps_res, ns, labels),
        )
        if match_memo is not None:
            match_memo[id(pod)] = (pod, ids)
        return ids

    for pi, p in enumerate(pending):
        aa_d, pa_d, ppa_d, sp_d, sps_d = _declared(p)
        for key, _t in aa_d:
            pod_aa_carries[pi, aa_index[key]] = 1.0
        for key, _t in pa_d:
            pod_pa_declares[pi, pa_index[key]] = 1.0
        for key, _t, w in ppa_d:
            pod_ppa_w[pi, ppa_index[key]] += w
        for key, _c in sp_d:
            pod_sp_declares[pi, sp_index[key]] = 1.0
        for key, _c in sps_d:
            pod_sps_declares[pi, sps_index[key]] = 1.0
        aa_m, pa_m, ppa_m, sp_m, sps_m = _matched_all(p)
        for ti in aa_m:
            pod_aa_matched[pi, ti] = 1.0
        for ti in pa_m:
            pod_pa_matched[pi, ti] = 1.0
        for ti in ppa_m:
            pod_ppa_matched[pi, ti] = 1.0
        for si in sp_m:
            pod_sp_matched[pi, si] = 1.0
        for si in sps_m:
            pod_sps_matched[pi, si] = 1.0

    # --- initial state from placed pods -----------------------------------
    aa_dom_m = np.zeros((t_pad, d_pad), dtype=np.float32)
    aa_dom_c = np.zeros((t_pad, d_pad), dtype=np.float32)
    aa_node_m = np.zeros((t_pad, n_pad), dtype=np.float32)
    aa_node_c = np.zeros((t_pad, n_pad), dtype=np.float32)
    pa_dom_m = np.zeros((ta_pad, d_pad), dtype=np.float32)
    pa_node_m = np.zeros((ta_pad, n_pad), dtype=np.float32)
    ppa_dom_cnt = np.zeros((tp_pad, d_pad), dtype=np.float32)
    ppa_node_cnt = np.zeros((tp_pad, n_pad), dtype=np.float32)
    sp_counts = np.zeros((s_pad, d_pad), dtype=np.float32)
    sps_counts = np.zeros((ss_pad, d_pad), dtype=np.float32)
    node_index = {n.name: i for i, n in enumerate(nodes)}

    def _mark(arr_dom, arr_node, ti, term, qnode_name):
        ni = node_index[qnode_name]
        k = term.topology_key
        v = (nodes[ni].metadata.labels or {}).get(k)
        if k not in fine_keys and v is not None:
            arr_dom[ti, dom_vocab[(k, v)]] = 1.0
        else:
            arr_node[ti, ni] = 1.0

    def _count(arr_dom, arr_node, ti, term, qnode_name):
        """+= twin of _mark for the count-valued preferred-term state."""
        ni = node_index[qnode_name]
        k = term.topology_key
        v = (nodes[ni].metadata.labels or {}).get(k)
        if k not in fine_keys and v is not None:
            arr_dom[ti, dom_vocab[(k, v)]] += 1.0
        else:
            arr_node[ti, ni] += 1.0

    if aa_terms or pa_terms or ppa_terms or sp_terms or sps_terms:
        want_sp = bool(sp_terms or sps_terms)
        for q, qnode in snapshot.placed_pods():
            aa_m, pa_m, ppa_m, sp_m, sps_m = _matched_all(q)
            for ti in aa_m:
                _mark(aa_dom_m, aa_node_m, ti, aa_terms[ti][1][1], qnode.name)
            for ti in pa_m:
                _mark(pa_dom_m, pa_node_m, ti, pa_terms[ti][1][1], qnode.name)
            for ti in ppa_m:
                _count(ppa_dom_cnt, ppa_node_cnt, ti, ppa_terms[ti][1][1], qnode.name)
            if want_sp and (sp_m or sps_m):
                nlabels = (nodes[node_index[qnode.name]].metadata.labels) or {}
                for si in sp_m:
                    c = sp_terms[si][1][1]
                    v = nlabels.get(c.topology_key)
                    if v is not None:
                        sp_counts[si, dom_vocab[(c.topology_key, v)]] += 1.0
                for si in sps_m:
                    c = sps_terms[si][1][1]
                    v = nlabels.get(c.topology_key)
                    if v is not None:
                        sps_counts[si, dom_vocab[(c.topology_key, v)]] += 1.0
        for _q, qnode, aa_d in placed_carrier_keys:
            for key, t in aa_d:
                _mark(aa_dom_c, aa_node_c, aa_index[key], t, qnode.name)

    return ConstraintSet(
        pod_aa_carries=pod_aa_carries,
        pod_aa_matched=pod_aa_matched,
        pod_pa_declares=pod_pa_declares,
        pod_pa_matched=pod_pa_matched,
        pod_sp_declares=pod_sp_declares,
        pod_sp_matched=pod_sp_matched,
        pod_sps_declares=pod_sps_declares,
        pod_sps_matched=pod_sps_matched,
        pod_ppa_w=pod_ppa_w,
        pod_ppa_matched=pod_ppa_matched,
        node_dom_c=node_dom_c,
        term_uses_dom=term_uses_dom,
        pa_uses_dom=pa_uses_dom,
        ppa_uses_dom=ppa_uses_dom,
        sp_uses_dom=sp_uses_dom,
        sp_skew=sp_skew,
        sps_uses_dom=sps_uses_dom,
        sp_dom_sel=sp_dom_sel,
        aa_dom_m=aa_dom_m,
        aa_dom_c=aa_dom_c,
        aa_node_m=aa_node_m,
        aa_node_c=aa_node_c,
        pa_dom_m=pa_dom_m,
        pa_node_m=pa_node_m,
        ppa_dom_cnt=ppa_dom_cnt,
        ppa_node_cnt=ppa_node_cnt,
        sp_counts=sp_counts,
        sps_counts=sps_counts,
        n_terms=len(aa_terms),
        n_pa_terms=len(pa_terms),
        n_ppa_terms=len(ppa_terms),
        n_spread=len(sp_terms),
        n_spread_soft=len(sps_terms),
    )


# ---------------------------------------------------------------------------
# xp-generic round engine (shared by ops/assign.py and backends/native.py)
# ---------------------------------------------------------------------------


# shape: (a: any) -> any
def _clip01(xp, a):
    return xp.minimum(a, 1.0)


# shape: (state: dict, meta: dict, soft_spread: bool, soft_pa: bool, hard_pa: bool) -> dict
def round_blocked_masks(
    xp, state: dict, meta: dict, soft_spread: bool = False, soft_pa: bool = False, hard_pa: bool = True
) -> dict:
    """Per-round [·, N] blocked-node masks from the current domain state.

    aa_m_node[T,N]: node's domain (under term t's key) holds a matched pod —
    blocks *carriers* of t.  aa_c_node[T,N]: holds a carrier — blocks
    *matched* pods.  sp_node[S,N]: placing a matching pod there would exceed
    ``max_skew + min(counts)`` — blocks *declarers* of s.

    sp_penalty_node[Ss,N] (soft/ScheduleAnyway — scoring, never blocking;
    built only with ``soft_spread=True``, a trace-time constant, so clusters
    without ScheduleAnyway constraints skip the matmuls entirely): the count
    of matching placed pods in the node's domain under soft constraint s,
    the tensor twin of core/predicates.make_soft_spread_scorer; score_block
    subtracts ``topology_weight · (pod_sps_declares @ sp_penalty_node)``.
    """
    ndc_t = meta["node_dom_c"].T
    aa_m_node = _clip01(xp, state["aa_dom_m"] @ ndc_t + state["aa_node_m"])
    aa_c_node = _clip01(xp, state["aa_dom_c"] @ ndc_t + state["aa_node_c"])
    # Positive affinity: a declarer is blocked wherever its term has NO match
    # in the node's domain — the inverted twin of aa_m_node — except while
    # the term is globally inactive (no match anywhere) AND the pod matches
    # its own term (the bootstrap waiver; blocked_block applies the pod-side
    # gate from pa_inactive).
    if hard_pa:
        pa_m_node = _clip01(xp, state["pa_dom_m"] @ ndc_t + state["pa_node_m"])
        pa_unmatched_node = 1.0 - pa_m_node
        # Round-carried bootstrap flags when the auction threads them
        # (augment_round_state / constraint_commit); recompute otherwise.
        pa_inactive = state.get("pa_inactive")
        if pa_inactive is None:
            pa_inactive = ((state["pa_dom_m"].sum(axis=1) + state["pa_node_m"].sum(axis=1)) == 0).astype(xp.float32)
    uses = meta["sp_uses_dom"]
    counts = state["sp_counts"]
    # Round-carried water line when present — bitwise what this recompute
    # yields (counts are exact integers), just not re-reduced every round.
    lo = state.get("sp_lo")
    if lo is None:
        lo = xp.min(xp.where(uses > 0, counts, RANK_INF), axis=1)
        lo = xp.where(lo >= RANK_INF, 0.0, lo)
    # Choose-time slack of CASCADE levels: the within-round admission filter
    # (constraint_filter) can raise the water line by up to CASCADE levels,
    # so domains within that reach are offered to declarers — otherwise the
    # whole herd targets only the min-count domains (few nodes), starving
    # the capacity prefix.  The filter remains the exact gate; the mask is
    # only a targeting hint.
    blockcell = uses * (counts >= (meta["sp_skew"] + lo + SPREAD_CASCADE)[:, None])
    sp_node = _clip01(xp, blockcell @ ndc_t)
    # Per-level steering for hard-spread DECLARERS (score side): each node's
    # domain height above the constraint's water line.  score_block charges
    # 2x the tie-break amplitude per level, so a declarer prefers min-count
    # domains outright (a lone straggler goes where admission will accept
    # it) while same-level domains stay jitter-spread — the slack mask above
    # offers the reachable levels, the steering orders them.
    sp_level_node = ((counts - lo[:, None]) * uses) @ ndc_t
    masks = {
        "aa_m_node": aa_m_node,
        "aa_c_node": aa_c_node,
        "sp_node": sp_node,
        "sp_level_node": sp_level_node,
    }
    if hard_pa:
        masks["pa_unmatched_node"] = pa_unmatched_node
        masks["pa_inactive"] = pa_inactive
    if soft_spread:
        masks["sp_penalty_node"] = state["sps_counts"] @ ndc_t
    if soft_pa:
        # Preferred inter-pod terms: per-term match COUNT at each node's
        # domain; score_block adds pod_ppa_w (signed weights) @ this.
        masks["ppa_cnt_node"] = state["ppa_dom_cnt"] @ ndc_t + state["ppa_node_cnt"]
    return masks


# shape: (blk: dict, masks: dict) -> any
def blocked_block(xp, blk: dict, masks: dict):
    """[B, N] constraint-blocked mask for one pod block (four matmuls)."""
    b = blk["pod_aa_carries"] @ masks["aa_m_node"]
    b = b + blk["pod_aa_matched"] @ masks["aa_c_node"]
    b = b + blk["pod_sp_declares"] @ masks["sp_node"]
    # Positive affinity with the bootstrap waiver: a declared term that is
    # globally inactive AND self-matched drops out of the pod's requirement
    # set for this round; every remaining declared term blocks its unmatched
    # nodes (terms AND — any unmet term blocks).  A non-self-matching pod
    # with an inactive term keeps it → unmatched everywhere → unschedulable
    # this round, exactly the scalar checker's "unmatchable" rule.
    if "pa_unmatched_node" in masks:
        gated = blk["pod_pa_declares"] * (1.0 - blk["pod_pa_matched"] * masks["pa_inactive"][None, :])
        b = b + gated @ masks["pa_unmatched_node"]
    return b > 0


# shape: (size: int, idx: [P] i32, vals: [P] f32) -> [size] f32
def _scatter_min(xp, size: int, idx, vals):
    if xp is np:
        out = np.full((size,), RANK_INF, dtype=np.float32)
        np.minimum.at(out, idx, vals)
        return out
    return xp.full((size,), RANK_INF, dtype=xp.float32).at[idx].min(vals)


# shape: (n_rows: int, idx: [P] i32, vals: [P, C] f32) -> [n_rows, C] f32
def _row_scatter_min(xp, n_rows: int, idx, vals):
    """out[r, c] = min over {p : idx[p] == r} of vals[p, c]  (RANK_INF fill).

    Row-granular scatters (one [C]-wide update per pod) lower to fast
    windowed scatters on TPU, unlike the near-serial scalar form."""
    if xp is np:
        out = np.full((n_rows, vals.shape[1]), RANK_INF, dtype=np.float32)
        np.minimum.at(out, idx, vals)
        return out
    return xp.full((n_rows, vals.shape[1]), RANK_INF, dtype=xp.float32).at[idx].min(vals)


# shape: (state_tn: [T, N] f32, idx: [P] i32, vals: [P, T] f32) -> [T, N] f32
def _row_scatter_max_t(xp, state_tn, idx, vals):
    """[T,N] state with state[c, idx[p]] = max(state, vals[p, c]) folded in —
    the row-scatter twin of the flattened t·n scalar scatter (transposed
    round-trip is two [T,N] relayouts, a rounding error next to the
    near-serial scalar form it replaces)."""
    if xp is np:
        out = state_tn.T.copy()  # always copy — callers may hold the old state
        np.maximum.at(out, idx, vals)
        return out.T
    return state_tn.T.at[idx].max(vals).T


# shape: (state_tn: [T, N] f32, idx: [P] i32, vals: [P, T] f32) -> [T, N] f32
def _row_scatter_add_t(xp, state_tn, idx, vals):
    """+= twin of :func:`_row_scatter_max_t` for count-valued state."""
    if xp is np:
        out = state_tn.T.copy()  # always copy — callers may hold the old state
        np.add.at(out, idx, vals)
        return out.T
    return state_tn.T.at[idx].add(vals).T


# shape: (p: int, cells: int) -> int
def _cell_chunk(p: int, cells: int) -> int:
    """Pod-axis chunk length keeping one [chunk, S, D] tile inside the byte
    budget (0 = no chunking needed — the full tensor fits)."""
    if p * cells * 4 <= DENSE_TENSOR_BYTES:
        return 0
    return max(256, DENSE_TENSOR_BYTES // (cells * 4))


# Static pod-axis tile for the ACTIVE-SET cell passes under jit: the fused
# filter compacts the round's accepted claimants into a workspace prefix and
# the jnp cell scans run ``ceil(A / ACTIVE_CHUNK)`` tiles under a
# dynamic-bound while_loop, so per-round filter cost tracks the accepted
# count the way the size chain tracks actives (the NumPy oracle gathers the
# exact [A] rows instead and needs no tiling).  Chunked and one-shot results
# are bitwise equal (exact small-integer sums — pinned by
# test_cell_rank_scan_chunked_equals_oneshot), so the constant is perf-only:
# any value yields identical placements.
ACTIVE_CHUNK = 256


# shape: (mass: [P, S] f32, nd: [P, D] f32, uses: [S, D] f32, out_fn: fn,
#   n_live: any) -> [P, S] f32
def _cell_rank_scan(xp, mass, nd, uses, out_fn, n_live=None):
    """Shared chunked driver for the spread filter's exclusive-by-rank cell
    passes: feeds ``out_fn(ec3, m3)`` — ``ec3`` the [·,S,D] exclusive
    cumulative cell mass including all lower-rank pods, ``m3`` the same
    rows' own-cell one-hots — per pod-axis chunk and concatenates the [·,S]
    outputs.  Exact small-integer sums, so chunked and one-shot results are
    bitwise equal — cross-backend/stage parity depends on that.

    Without ``n_live``: one-shot when [P,S,D] fits the byte budget, else
    chunks with an [S,D] carry (``lax.scan`` under jit, a plain loop in
    numpy — the budget applies to BOTH backends, round-5 review finding).

    With ``n_live`` (jit active-set path — rows beyond it must carry zero
    mass): a while_loop over ``ceil(n_live / ACTIVE_CHUNK)`` tiles, leaving
    later tiles' outputs at zero — their rows are exactly the non-accepted
    workspace tail the filter masks out anyway, so cost tracks the live
    count without a shape-dependent semantic."""
    p, s = mass.shape
    d = nd.shape[1]

    def step(carry, mch, ndch):
        m3 = ndch[:, None, :] * uses[None, :, :]  # [·,S,D]
        c3 = mch[:, :, None] * m3
        ec3 = carry[None, :, :] + xp.cumsum(c3, axis=0) - c3
        return carry + c3.sum(axis=0), out_fn(ec3, m3)

    if xp is np or n_live is None:
        chunk = _cell_chunk(p, s * d)
        if chunk == 0:
            return step(xp.zeros((s, d), xp.float32), mass, nd)[1]
        pad = (-p) % chunk
        mass_c = xp.pad(mass, ((0, pad), (0, 0))).reshape(-1, chunk, s)
        nd_c = xp.pad(nd, ((0, pad), (0, 0))).reshape(-1, chunk, d)
        if xp is np:
            carry = np.zeros((s, d), np.float32)
            outs = []
            for k in range(mass_c.shape[0]):
                carry, out = step(carry, mass_c[k], nd_c[k])
                outs.append(out)
            return np.concatenate(outs, axis=0)[:p]
        from jax import lax

        _, outs = lax.scan(lambda c, inp: step(c, *inp), xp.zeros((s, d), xp.float32), (mass_c, nd_c))
        return outs.reshape(-1, s)[:p]

    chunk = min(p, ACTIVE_CHUNK)
    if chunk >= p:
        return step(xp.zeros((s, d), xp.float32), mass, nd)[1]
    from jax import lax

    pad = (-p) % chunk
    mass_c = xp.pad(mass, ((0, pad), (0, 0))).reshape(-1, chunk, s)
    nd_c = xp.pad(nd, ((0, pad), (0, 0))).reshape(-1, chunk, d)
    k_live = (n_live.astype(xp.int32) + chunk - 1) // chunk

    def cond(st):
        return st[0] < k_live

    def body(st):
        k, carry, outs = st
        carry, out = step(carry, mass_c[k], nd_c[k])
        return k + 1, carry, outs.at[k].set(out)

    _, _, outs = lax.while_loop(
        cond, body, (xp.int32(0), xp.zeros((s, d), xp.float32), xp.zeros(mass_c.shape, xp.float32))
    )
    return outs.reshape(-1, s)[:p]


# shape: (mass: [P, S] f32, nd: [P, D] f32, uses: [S, D] f32, n_live: any) -> [P, S] f32
def _cell_rank_prefix(xp, mass, nd, uses, n_live=None):
    """[P,S] exclusive-by-rank (array order) mass before each pod in its own
    (s, domain) cell — the quota prefix."""
    return _cell_rank_scan(xp, mass, nd, uses, lambda ec3, m3: (ec3 * m3).sum(axis=2), n_live=n_live)


# shape: (mass: [P, S] f32, nd: [P, D] f32, uses: [S, D] f32, base: [S, D] f32,
#   n_live: any) -> [P, S] f32
def _cell_rank_min_level(xp, mass, nd, uses, base, n_live=None):
    """[P,S] per-pod water line: min over the constraint's used domains of
    ``base`` plus the exclusive-by-rank fill of ``mass`` — the cascade's
    lower bound on the minimum count at each pod's witness-order turn."""

    def out_fn(ec3, m3):
        lvl = xp.where(uses[None, :, :] > 0, base[None, :, :] + ec3, RANK_INF)
        lo = xp.min(lvl, axis=2)
        return xp.where(lo >= RANK_INF, 0.0, lo)

    return _cell_rank_scan(xp, mass, nd, uses, out_fn, n_live=n_live)


# shape: (nd: [A, D] f32, uses_sp: [S, D] f32, sp0: [S, D] f32, sel: [D, C] f32)
#   -> ([A, C] f32, [S, C] f32, [S, C] f32)
def _project_spread_domains(xp, nd, uses_sp, sp0, sel):
    """Project the spread cell operands onto the pack-time spread-domain
    selection (``ConstraintSet.sp_dom_sel``): the [·,S,D] cell passes then
    carry only the C ≤ D domains a hard spread constraint references.
    One-hot selection of exact small-integer columns — bitwise-neutral."""
    return nd @ sel, uses_sp @ sel, sp0 @ sel


# Stateless reusable no-op span context for the fused filter's family
# sub-phases: the jit path (and any caller without a tracer) pays nothing,
# while backends/native.py passes utils.tracing.span so the NumPy oracle's
# attribution profile splits ``choose/filter`` into filter/aa|pa|spread.
_NULL_SPAN_CTX = contextlib.nullcontext()


# shape: (name: str) -> obj
def _null_span(name):
    return _NULL_SPAN_CTX


# shape: (state: dict, meta: dict, hard_pa: bool) -> dict
def augment_round_state(xp, state: dict, meta: dict, hard_pa: bool = True) -> dict:
    """Derive the ROUND-CARRIED conflict-state entries from a cycle-start
    constraint state: ``sp_cell`` ([S,D] per-cell counts masked to used
    domains), ``sp_lo`` ([S] spread water line) and ``pa_inactive`` ([Ta]
    positive-affinity bootstrap flags).  The auction threads them through
    its while-loop carry and :func:`constraint_commit` updates them
    INCREMENTALLY from each round's commits, so neither the choose-mask
    build nor the conflict filter re-derives them from the accumulated
    domain history every round.  Values are bitwise what the per-round
    recompute produced (counts are exact small-integer f32), so carried and
    recomputed cycles place identically — the fallback recompute survives in
    the consumers for legacy callers handing in a bare state dict."""
    uses = meta["sp_uses_dom"]
    sp_cell = state["sp_counts"] * uses
    lo = xp.min(xp.where(uses > 0, sp_cell, RANK_INF), axis=1)
    lo = xp.where(lo >= RANK_INF, 0.0, lo)
    out = {**state, "sp_cell": sp_cell, "sp_lo": lo}
    out["pa_inactive"] = ((state["pa_dom_m"].sum(axis=1) + state["pa_node_m"].sum(axis=1)) == 0).astype(xp.float32)
    return out


# shape: (accepted: [P] bool, choice: [P] i32, ranks: [P] u32, ps: dict,
#   state: dict, meta: dict, hard_pa: bool, spans: fn) -> [P] bool
def constraint_filter(
    xp, accepted, choice, ranks, ps: dict, state: dict, meta: dict, hard_pa: bool = True, spans=None
) -> object:
    """Within-round conflict resolution — returns the surviving subset of
    ``accepted`` (see module docstring for the rank rules).

    ACTIVE-SET COMPACTION (round 7): only the round's accepted claimants can
    conflict — every mass the filter consumes is ``accepted``-gated and a
    non-accepted row's verdict is discarded — so the filter gathers accepted
    rows into a compact workspace before any cell machinery runs, and
    scatters survivors back at the end.  NumPy gathers the exact [A] rows;
    under jit the workspace is a stable accepted-first permutation of the
    (static-size) pod arrays whose cell scans stop after
    ``ceil(A / ACTIVE_CHUNK)`` tiles, so both backends' per-round filter
    cost tracks the accepted count instead of the padded pod axis.  Sums
    and mins over the dropped all-zero rows are exact no-ops, so compacted
    and full-width filtering are bitwise identical.

    ``spans`` (optional ``name -> context-manager``, e.g.
    utils.tracing.span) opens the ``aa`` / ``pa`` / ``spread`` sub-spans
    around the three constraint families so an attribution profile names
    WHICH family dominates; the default is a shared no-op context.
    """
    sp_span = spans if spans is not None else _null_span
    p = accepted.shape[0]
    ndc = meta["node_dom_c"]
    d = ndc.shape[1]
    n = ndc.shape[0]

    # ---- active-set workspace --------------------------------------------
    ws_keys = ["pod_aa_carries", "pod_aa_matched", "pod_sp_declares", "pod_sp_matched"]
    if hard_pa:
        ws_keys += ["pod_pa_declares", "pod_pa_matched"]
    if xp is np:
        gperm = np.flatnonzero(accepted)
        if gperm.size == 0:
            return accepted.copy()
        n_live = None  # exact [A] rows — the scans need no tile bound
        acc_ws = np.ones((gperm.size,), dtype=bool)
    else:
        # Stable accepted-first partition (the _compact cumsum trick): the
        # gather permutation keeps relative order, so workspace array order
        # is still rank order and every prefix/min below is unchanged.
        acc_i = accepted.astype(xp.int32)
        n_acc = acc_i.sum()
        pos_acc = xp.cumsum(acc_i) - acc_i
        pos_rej = xp.cumsum(1 - acc_i) - (1 - acc_i)
        dest = xp.where(accepted, pos_acc, n_acc + pos_rej)
        gperm = xp.zeros((p,), xp.int32).at[dest].set(xp.arange(p, dtype=xp.int32))
        n_live = n_acc
        acc_ws = accepted[gperm]
    choice_ws = choice[gperm]
    rank_f = ranks[gperm].astype(xp.float32)
    pw = {k: ps[k][gperm] for k in ws_keys}
    nd = ndc[choice_ws]  # [A, D] one-hot domains of each accepted pod's node
    accf = acc_ws.astype(xp.float32)

    uses = meta["term_uses_dom"]  # [T, D]
    uses_sp = meta["sp_uses_dom"]  # [S, D]
    t = uses.shape[0]
    sp0 = state.get("sp_cell")
    if sp0 is None:  # legacy caller without the round-carried state
        sp0 = state["sp_counts"] * uses_sp
    # ONE fused gather matmul for every per-pod cell lookup: AA coarse-key
    # flags + coarse cell ids, spread key flags + own-cell round-start
    # counts ride a single banded [A,D] @ [D, 2T+2S] dispatch instead of
    # four.  Each output column is an independent exact small-integer dot,
    # so banding is bitwise-neutral.
    dom_ids = xp.arange(d, dtype=xp.float32)
    band = xp.concatenate([uses, uses * dom_ids[None, :], uses_sp, sp0], axis=0)  # [2T+2S, D]
    g_all = nd @ band.T  # [A, 2T+2S]
    has_c = g_all[:, :t]  # [A, T] 1 if the chosen node has the term's coarse key
    cc = g_all[:, t : 2 * t]  # [A, T] coarse cell id (sum of ≤1 one-hot)
    s_sp = uses_sp.shape[0]
    in_cell = g_all[:, 2 * t : 2 * t + s_sp]  # [A, S] 1 iff node carries the key
    c_at = g_all[:, 2 * t + s_sp :]  # [A, S] own-cell round-start count

    # ---- anti-affinity ----------------------------------------------------
    # Rule: in each (term, cell) — cell = coarse domain when the chosen node
    # carries the term's key, else the node itself — a matched pod survives
    # only if no earlier-rank accepted carrier shares the cell, and vice
    # versa.  "Earlier rank" ≡ earlier array index (pods are compacted in
    # priority-rank order), so existence-of-a-predecessor is ONE fused
    # min-rank segment scatter over the unified (term, cell) id space —
    # coarse domains and fine (per-node) cells share the space, and the
    # carrier/matched tables ride a single offset dispatch — with a dense
    # [A,T,D] exclusive-cumsum path below the DENSE_CELLS budget; identical
    # outcomes by construction (pinned at the threshold by
    # test_dense_boundary_parity).
    with sp_span("aa"):
        carr = pw["pod_aa_carries"] * accf[:, None]
        matc = pw["pod_aa_matched"] * accf[:, None]
        if _dense_ok(nd.shape[0], t * d):
            m3 = nd[:, None, :] * uses[None, :, :]  # [A,T,D] one-hot coarse cell under t

            def _earlier_in_cell(v):  # [A,T] 0/1 → [A,T] "an earlier v-pod shares my coarse cell"
                v3 = v[:, :, None] * m3
                ec = xp.cumsum(v3, axis=0) - v3  # exclusive
                return (ec * m3).sum(axis=2) > 0

            fine = has_c == 0
            carr_c, matc_c = carr * has_c, matc * has_c
            # Fine cells: min accepted rank per (node, term) via one row scatter.
            min_c_fine = _row_scatter_min(xp, n, choice_ws, xp.where((carr * fine) > 0, rank_f[:, None], RANK_INF))
            min_m_fine = _row_scatter_min(xp, n, choice_ws, xp.where((matc * fine) > 0, rank_f[:, None], RANK_INF))
            earlier_c = _earlier_in_cell(carr_c) | (fine & (rank_f[:, None] > min_c_fine[choice_ws]))
            earlier_m = _earlier_in_cell(matc_c) | (fine & (rank_f[:, None] > min_m_fine[choice_ws]))
            bad_aa = ((matc > 0) & earlier_c) | ((carr > 0) & earlier_m)
        else:
            cells = d + n
            cell = xp.where(has_c > 0, cc, d + choice_ws[:, None].astype(xp.float32))
            g = (xp.arange(t, dtype=xp.float32)[None, :] * cells + cell).astype(xp.int32)  # [A, T]
            # Fused dispatch: carrier mins in [0, t·cells), matched mins
            # offset by t·cells — ONE segment scatter-min, two gathers.
            gf2 = xp.concatenate([g.reshape(-1), (g + t * cells).reshape(-1)])
            vals2 = xp.concatenate(
                [
                    xp.where(carr > 0, rank_f[:, None], RANK_INF).reshape(-1),
                    xp.where(matc > 0, rank_f[:, None], RANK_INF).reshape(-1),
                ]
            )
            mins = _scatter_min(xp, 2 * t * cells, gf2, vals2)
            min_c_at = mins[g]  # [A, T]
            min_m_at = mins[g + t * cells]
            bad_aa = ((matc > 0) & (rank_f[:, None] > min_c_at)) | ((carr > 0) & (rank_f[:, None] > min_m_at))
        keep = acc_ws & ~bad_aa.any(axis=1)

    # ---- positive affinity bootstrap (within-round) -----------------------
    # A term inactive at round start was waived for self-matching declarers
    # (blocked_block let them choose freely).  Sequentially, only the FIRST
    # accepted pod matching the term may rely on the waiver: any earlier-rank
    # accepted match re-activates the term before a later pod's turn in the
    # witness order, and the later pod's free placement would then violate
    # it.  Keep the min-rank accepted match; defer other waived declarers
    # one round (the term is then active and the round-start mask routes
    # them to its domain).  Over-inclusive min (it counts matches a later
    # filter may drop) only defers more — never admits a violation.  This
    # family cannot ride the AA segment scatter: its min is over the
    # POST-AA keep set, a sequential dependency.
    if hard_pa:
        with sp_span("pa"):
            pa_inactive_f = state.get("pa_inactive")
            if pa_inactive_f is None:  # legacy caller without the carry
                pa_inactive_f = (
                    (state["pa_dom_m"].sum(axis=1) + state["pa_node_m"].sum(axis=1)) == 0
                ).astype(xp.float32)
            keep_pa_f = keep.astype(xp.float32)
            pa_m_acc = pw["pod_pa_matched"] * keep_pa_f[:, None]  # [A, Ta]
            min_match_rank = xp.min(xp.where(pa_m_acc > 0, rank_f[:, None], RANK_INF), axis=0)  # [Ta]
            waived = pw["pod_pa_declares"] * pw["pod_pa_matched"] * pa_inactive_f[None, :]  # [A, Ta]
            bad_pa = (waived > 0) & keep[:, None] & (rank_f[:, None] > min_match_rank[None, :])
            keep = keep & ~bad_pa.any(axis=1)

    # ---- topology spread (rank-prefix admission + in-round cascade) -------
    # The scalar rule (core/predicates.make_spread_checker): placing a
    # DECLARER on a keyed node requires count(domain) + 1 − min(counts) ≤
    # max_skew at its turn.  The witness order for this round's kept set is
    # simply ASCENDING RANK, so for pod p the domain count at its turn is
    # bounded by the round-start count plus the matched CANDIDATE mass of
    # lower rank in its cell (kept ⊆ candidates), and the min is bounded
    # below by the round-start min plus lower-rank COMMITTED fills.
    # Admission is therefore
    #     c_at(p) + pre_all(p) + 1 ≤ skew + lo_p
    # with pre_all the exclusive-by-rank candidate-mass prefix in p's cell
    # and lo_p the per-pod water line.  History: round 4 charged all
    # capacity-accepted matching-only mass to a STATIC denominator instead
    # of the rank prefix; two same-selector constraints (e.g. the mixed
    # workload's two skew levels per app) then mutually inflated each
    # other's minimum cells every round, pinning every quota at zero and
    # serializing the tail to ~1 accept/constraint/round (the measured
    # 64-round cap: scripts/diag_round_kills.py printed "quota sum=0, open
    # cells=0" for all eight fixpoint iterations).  The rank prefix breaks
    # the deadlock structurally: the lowest-rank candidate of an open cell
    # always admits.
    with sp_span("spread"):
        skew = meta["sp_skew"]  # [S]
        declares, matched = pw["pod_sp_declares"], pw["pod_sp_matched"]
        keep_f = keep.astype(xp.float32)
        # Candidate matched mass: every post-AA/PA survivor whose chosen node
        # carries the key and whose labels match the selector — non-declarers
        # (they commit unconditionally; nothing after this filter drops them)
        # and declarers (they commit iff admitted below) ride ONE prefix.
        cand_m = keep_f[:, None] * matched * in_cell  # [A, S]
        decl_cell = keep_f[:, None] * declares * in_cell  # declarers on keyed nodes

        lo0 = state.get("sp_lo")
        if lo0 is None:  # legacy caller without the round-carried state
            lo0 = xp.min(xp.where(uses_sp > 0, sp0, RANK_INF), axis=1)
            lo0 = xp.where(lo0 >= RANK_INF, 0.0, lo0)  # [S] round-start water line

        # Domain-axis projection: the cell passes only ever touch domains a
        # spread constraint references, so they run on the [D, Ds] pack-time
        # selection (sp_dom_sel) — dropped columns were identically zero in
        # every product and RANK_INF in every min, so admissions are bitwise
        # unchanged while a zone-keyed cluster's passes shrink ~D/Ds-fold.
        sel = meta.get("sp_dom_sel")
        if sel is None:  # legacy caller without the selection tensor
            nd_sp, uses_spc, sp0c = nd, uses_sp, sp0
        else:
            nd_sp, uses_spc, sp0c = _project_spread_domains(xp, nd, uses_sp, sp0, sel)
        # ONE spread formulation for every size: the [A,S,Ds] cell passes
        # run one-shot when they fit the byte budget and pod-axis CHUNKED
        # otherwise (exact small-integer sums — bitwise identical either
        # way).  No pod-count- or backend-dependent SEMANTIC: the jit size
        # chain runs this filter at several static pod sizes and the native
        # backend at one, so admission must never depend on the stage shape.
        pre_all = _cell_rank_prefix(xp, cand_m, nd_sp, uses_spc, n_live=n_live)  # [A,S] mass before p in own cell

        bound = c_at + pre_all + 1.0  # [A, S] count-after-placement upper bound
        lo_p = xp.zeros_like(c_at) + lo0[None, :]
        admit = bound <= (skew[None, :] + lo_p)
        # In-round water-line cascade.  Each sweep recomputes, per pod, the
        # min over the constraint's domains of round-start counts plus the
        # COMMITTED fills of lower rank — commits from the previous sweep's
        # admissions, which only grow (admit is OR-accumulated), so every
        # sweep is sound: a kept pod's witness-order turn really does see
        # those lower-rank commits placed.  One sweep lifts the line one
        # level; SPREAD_CASCADE sweeps admit a whole multi-level wave per
        # round instead of one level per ROUND.
        for _ in range(SPREAD_CASCADE):
            rejected = ((decl_cell > 0) & ~admit).any(axis=1)
            committed_pod = keep_f * (1.0 - rejected.astype(xp.float32))  # [A]
            lo_p = _cell_rank_min_level(xp, cand_m * committed_pod[:, None], nd_sp, uses_spc, sp0c, n_live=n_live)
            admit = admit | (bound <= (skew[None, :] + lo_p))
        bad_sp = (decl_cell > 0) & ~admit
        keep = keep & ~bad_sp.any(axis=1)

    # ---- scatter survivors back ------------------------------------------
    if xp is np:
        out = np.zeros_like(accepted)
        out[gperm] = keep
        return out
    full = xp.zeros_like(accepted).at[gperm].set(keep)
    return accepted & full


# shape: (accepted: [P] bool, choice: [P] i32, ps: dict, state: dict,
#   meta: dict, soft_spread: bool, soft_pa: bool, hard_pa: bool) -> dict
def constraint_commit(
    xp,
    accepted,
    choice,
    ps: dict,
    state: dict,
    meta: dict,
    soft_spread: bool = False,
    soft_pa: bool = False,
    hard_pa: bool = True,
) -> dict:
    """Fold the round's final accepted placements into the domain state."""
    ndc = meta["node_dom_c"]
    nd = ndc[choice]
    accf = accepted.astype(xp.float32)
    matc = ps["pod_aa_matched"] * accf[:, None]  # [P, T]
    carr = ps["pod_aa_carries"] * accf[:, None]
    uses = meta["term_uses_dom"]
    aa_dom_m = _clip01(xp, state["aa_dom_m"] + (matc.T @ nd) * uses)
    aa_dom_c = _clip01(xp, state["aa_dom_c"] + (carr.T @ nd) * uses)
    # Fine-granularity: chosen node lacks the term's coarse key (or the key
    # itself is fine) → the node is its own domain.  Row scatters (one
    # [T]-wide update per pod, see _row_scatter_max_t) replace the flattened
    # t·n scalar form — bit-identical, ~free vs ~14 ms each on TPU.
    has_c = nd @ uses.T  # [P, T]
    aa_node_m = _row_scatter_max_t(xp, state["aa_node_m"], choice, matc * (has_c == 0))
    aa_node_c = _row_scatter_max_t(xp, state["aa_node_c"], choice, carr * (has_c == 0))
    if hard_pa:
        # Positive affinity: every accepted pod matching a PA term activates
        # its landing domain (declaring or not — matches are matches).
        uses_pa = meta["pa_uses_dom"]
        matc_pa = ps["pod_pa_matched"] * accf[:, None]  # [P, Ta]
        pa_dom_m = _clip01(xp, state["pa_dom_m"] + (matc_pa.T @ nd) * uses_pa)
        has_c_pa = nd @ uses_pa.T  # [P, Ta]
        pa_node_m = _row_scatter_max_t(xp, state["pa_node_m"], choice, matc_pa * (has_c_pa == 0))
    else:
        pa_dom_m = state["pa_dom_m"]
        pa_node_m = state["pa_node_m"]
    if soft_pa:
        # Preferred terms: accepted matched pods bump their landing domain's
        # count (coarse) or node's count (fine/keyless) — same split as PA.
        uses_ppa = meta["ppa_uses_dom"]
        matc_ppa = ps["pod_ppa_matched"] * accf[:, None]  # [P, Tp]
        ppa_dom_cnt = state["ppa_dom_cnt"] + (matc_ppa.T @ nd) * uses_ppa
        has_c_ppa = nd @ uses_ppa.T  # [P, Tp]
        ppa_node_cnt = _row_scatter_add_t(xp, state["ppa_node_cnt"], choice, matc_ppa * (has_c_ppa == 0))
    else:
        ppa_dom_cnt = state["ppa_dom_cnt"]
        ppa_node_cnt = state["ppa_node_cnt"]
    sp_m = ps["pod_sp_matched"] * accf[:, None]  # [P, S]
    sp_counts = state["sp_counts"] + (sp_m.T @ nd) * meta["sp_uses_dom"]
    if soft_spread:
        sps_m = ps["pod_sps_matched"] * accf[:, None]  # [P, Ss]
        sps_counts = state["sps_counts"] + (sps_m.T @ nd) * meta["sps_uses_dom"]
    else:
        sps_counts = state["sps_counts"]
    out = {
        "aa_dom_m": aa_dom_m,
        "aa_dom_c": aa_dom_c,
        "aa_node_m": aa_node_m,
        "aa_node_c": aa_node_c,
        "pa_dom_m": pa_dom_m,
        "pa_node_m": pa_node_m,
        "ppa_dom_cnt": ppa_dom_cnt,
        "ppa_node_cnt": ppa_node_cnt,
        "sp_counts": sp_counts,
        "sps_counts": sps_counts,
    }
    # Round-carried conflict state (augment_round_state): updated HERE from
    # the round's commits instead of re-derived from the accumulated domain
    # history next round.  ``sp_cell``/``sp_lo`` re-reduce the just-updated
    # [S,D] counts (domain-granular — a rounding error next to the pod
    # tensors); ``pa_inactive`` flips per-term the moment any accepted match
    # commits (exactly when the pa_dom_m/pa_node_m sums leave zero: a
    # matched accepted pod lands in its domain when the node carries the
    # key, in its node row otherwise — either way the sum grows).  Dict
    # membership gates the update so legacy callers handing in a bare
    # state_arrays() dict keep the old contract.
    if "sp_cell" in state:
        uses_sp = meta["sp_uses_dom"]
        sp_cell = sp_counts * uses_sp
        lo = xp.min(xp.where(uses_sp > 0, sp_cell, RANK_INF), axis=1)
        out["sp_cell"] = sp_cell
        out["sp_lo"] = xp.where(lo >= RANK_INF, 0.0, lo)
    if "pa_inactive" in state:
        if hard_pa:
            newly_matched = (matc_pa.sum(axis=0) > 0).astype(xp.float32)  # [Ta]
            out["pa_inactive"] = state["pa_inactive"] * (1.0 - newly_matched)
        else:
            out["pa_inactive"] = state["pa_inactive"]
    return out
