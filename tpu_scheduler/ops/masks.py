"""Batched feasibility mask — the tensor form of the predicate chain.

Replaces the reference's per-(pod, node) checks (``src/predicates.rs:20-61``)
with one [pods × nodes] boolean mask:

  fit[p,n]   = all_r( pod_req[p,r] <= node_avail[n,r] )          (PodFitsResources)
  sel[p,n]   = (pod_sel[p] · node_labels[n]) == pod_sel_count[p] (nodeSelector)
  taint[p,n] = (pod_ntol[p] · node_taints[n]) == 0               (taints/tolerations)
  aff[p,n]   = no-affinity or (pod_aff[p] · node_aff[n]) > 0     (node affinity, ORed terms)
  mask       = fit & sel & taint & aff & pod_active & node_valid

node_valid carries both padding and cordoned (spec.unschedulable) nodes.
Written against an ``xp`` array namespace (numpy or jax.numpy) so the native
and TPU backends share one expression tree — bit-identical semantics by
construction (tests/test_backends_parity.py).
"""

from __future__ import annotations

__all__ = ["feasibility_block", "feasibility_breakdown", "reason_rejection_counts"]


# shape: (pod_req: [B, R] i32, pod_sel: [B, L] f32, pod_sel_count: [B] f32,
#   node_avail: [N, R] i32, node_labels: [N, L] f32, pod_ntol: [B, T] f32,
#   node_taints: [N, T] f32, pod_aff: [B, A] f32, pod_has_aff: [B] f32,
#   node_aff: [N, A] f32) -> dict
def feasibility_breakdown(
    xp,
    pod_req,
    pod_sel,
    pod_sel_count,
    node_avail,
    node_labels,
    pod_ntol=None,
    node_taints=None,
    pod_aff=None,
    pod_has_aff=None,
    node_aff=None,
):
    """The predicate masks feasibility_block ANDs together, EXPOSED per
    reason: ``{InvalidNodeReason value -> [B, N] pass-mask}`` (True = the
    node passes that predicate for that pod).  These intermediates were
    always computed — surfacing them named is what the flight recorder's
    per-reason candidate counts and the why-pending debug route build on
    (utils/events.py; ISSUE: per-reason mask counts already computed).
    Keys follow ``core.predicates.InvalidNodeReason`` values so tensor and
    scalar breakdowns are interchangeable downstream.
    """
    out = {}
    out["NotEnoughResources"] = (pod_req[:, None, :] <= node_avail[None, :, :]).all(-1)
    # Selector-pair counting: matches iff the node carries every selector pair.
    # Counts are tiny integers — exact even through a bf16 MXU pass.
    counts = pod_sel @ node_labels.T
    out["NodeSelectorMismatch"] = counts == pod_sel_count[:, None]
    if pod_ntol is not None and node_taints is not None:
        # Untolerated-taint counting: schedulable iff zero of the node's hard
        # taints land in the pod's not-tolerated set.
        untol = pod_ntol @ node_taints.T
        out["TaintNotTolerated"] = untol == 0
    if pod_aff is not None and node_aff is not None and pod_has_aff is not None:
        # Node affinity: terms are ORed — eligible iff the pod has no
        # affinity, or the node satisfies at least one of its terms.
        aff_hits = pod_aff @ node_aff.T
        out["NodeAffinityMismatch"] = (aff_hits > 0) | (pod_has_aff[:, None] == 0)
    return out


# shape: (pod_req: [B, R] i32, pod_sel: [B, L] f32, pod_sel_count: [B] f32,
#   pod_active: [B] bool, node_avail: [N, R] i32, node_labels: [N, L] f32,
#   node_valid: [N] bool, pod_ntol: [B, T] f32, node_taints: [N, T] f32,
#   pod_aff: [B, A] f32, pod_has_aff: [B] f32, node_aff: [N, A] f32) -> [B, N] bool
def feasibility_block(
    xp,
    pod_req,
    pod_sel,
    pod_sel_count,
    pod_active,
    node_avail,
    node_labels,
    node_valid,
    pod_ntol=None,
    node_taints=None,
    pod_aff=None,
    pod_has_aff=None,
    node_aff=None,
):
    """[B, N] feasibility of a block of pods against all nodes.

    pod_req [B,2] int32, pod_sel [B,L] f32, pod_sel_count [B] f32,
    pod_active [B] bool, node_avail [N,2] int32, node_labels [N,L] f32,
    node_valid [N] bool, pod_ntol [B,T] f32 / node_taints [N,T] f32
    (optional together — omitted means no taints in the cluster).
    """
    parts = feasibility_breakdown(
        xp, pod_req, pod_sel, pod_sel_count, node_avail, node_labels,
        pod_ntol, node_taints, pod_aff, pod_has_aff, node_aff,
    )
    mask = node_valid[None, :] & pod_active[:, None]
    for part in parts.values():
        mask = mask & part
    return mask


# shape: (breakdown: dict, node_valid: [N] bool) -> dict
def reason_rejection_counts(xp, breakdown, node_valid):
    """Per-pod candidate-node rejection counts from a breakdown:
    ``{reason -> [B] number of otherwise-valid nodes failing that
    predicate}`` (non-exclusive — a node can fail several predicates; the
    scalar first-fail attribution lives in
    ``core.predicates.unschedulable_reason_counts``)."""
    return {
        reason: (node_valid[None, :] & ~part).sum(-1)
        for reason, part in breakdown.items()
    }
