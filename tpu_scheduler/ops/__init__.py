"""Tensor ops: the pods×nodes hot path (masks → score → pack → assign →
constraints), shared xp-generically by the NumPy and JAX backends.

Every public function in this package declares a machine-checked tensor
contract in a ``# shape:`` comment directly above its ``def`` — symbolic
dims (``[P, N]``, ``[B, R]``, …) plus dtypes — which the ``SHPE`` pass of
``scripts/analyze`` abstract-interprets on every ``make check``: transposed
operands, illegal broadcasts, wrong reduction axes, and bool/int/float
promotion drift fail the build instead of surfacing as a wrong placement
deep inside a jit trace.  The contract grammar and authoring guide live in
the README "Shape contracts (the SHPE annotation language)" section; run
``python -m scripts.analyze --rule SHPE`` to check this package alone.
"""
