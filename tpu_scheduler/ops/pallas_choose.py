"""Pallas TPU kernel for the auction's hot op: fused feasibility + score +
masked argmax ("choose") for a block of pods against all nodes.

The jnp path (ops/masks.py + ops/score.py + argmax in ops/assign.py)
materialises ~8 [B, N] f32/i32 intermediates per block in HBM unless XLA
fuses them all; this kernel keeps every intermediate in VMEM, streaming node
tiles through a running (max, argmax) scratch — one HBM read of the node
tensors and one [B] write per block, the minimum possible traffic.

Bitwise parity with the jnp/NumPy expression tree is preserved by computing
the *same* f32 operations in the same order (ops/score.py), the same exact
int32 arithmetic for resource fit (ops/masks.py), and the same uint32
Knuth-multiplicative jitter hash; ties resolve to the lowest node index,
exactly like ``jnp.argmax`` over the full row, via TWO guarantees: within a
tile an explicit max + masked min-reduction over the lane iota (Mosaic's
own argmax lowering is NOT first-index at every lane width — a two-node
score tie at tn=1024 returned the higher index on real hardware), and
across tiles a strict ``>`` running max that keeps the earlier tile
(tests/test_pallas_choose.py asserts equality).

Node-side layout: resources ride in one ``[8, N] int32`` array (rows: avail
cpu/mem, alloc cpu/mem, valid, 3× pad) so the int32 (8, 128) min-tile is hit
exactly; labels ride transposed ``[L, N]`` so the selector-count matmul
``sel @ labelsT`` feeds the MXU directly.

Banded hard predicates (PERF.md "known remaining headroom", landed): the
three hard count matmuls (selector pairs, untolerated taints, affinity
hits) ride ONE banded matmul — pod side ``[sel | 256·ntol | 65536·aff]``,
node side ``[labelsT; taintsT; affT]`` — and the kernel recovers the three
exact counts by power-of-2 base decomposition.  Each count is bounded by
its (static) vocab width ≤ 255, so the packed value is < 2²⁴ and every
intermediate is an exact f32 integer: decomposition returns bitwise the
same counts the separate matmuls would, preserving the parity contract.
(The soft score matmuls stay separate: their weighted sums are not exact
integers, so folding them into one accumulation would change float
rounding order.)  Constrained cycles band the four blocked-domain matmuls
the same way WITHOUT decomposition — only their sum feeds ``blocked > 0``,
and sums of exact small ints are order-independent.

Reference capability anchor: this is the batched form of the predicate chain
``check_node_validity`` (reference ``src/predicates.rs:63-77``) plus scoring
the reference lacks (it takes the first feasible random candidate,
``src/main.rs:51-71``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = [
    "choose_block_pallas",
    "build_node_info",
    "constrained_kernel_node_operands",
    "constrained_kernel_pod_operands",
]

# Row indices of the packed [8, N] node-resource array.
ROW_AVAIL_CPU, ROW_AVAIL_MEM, ROW_ALLOC_CPU, ROW_ALLOC_MEM, ROW_VALID = 0, 1, 2, 3, 4

NEG_INF = float("-inf")

# Base separation for the banded hard matmul: each packed count group must
# stay < its base for exact decomposition, so the (static) vocab widths must
# each be ≤ MAX_BAND_WIDTH.  Wider vocabs fall back to the jnp path (callers
# check pallas_band_widths_ok); 255·65536 + 255·256 + 255 == 2²⁴ − 1, the
# largest exactly-representable packing.
BAND_TAINT = 256.0
BAND_AFF = 65536.0
MAX_BAND_WIDTH = 255


def pallas_band_widths_ok(sel_width: int, ntol_width: int, aff_width: int) -> bool:
    """Static guard for the banded hard matmul's exactness bounds."""
    return max(sel_width, ntol_width, aff_width) <= MAX_BAND_WIDTH


def pallas_kernel_supported(pods: dict, nodes: dict) -> bool:
    """THE static can-this-cluster-ride-the-kernel predicate, for every
    use_pallas entry point (ops/assign._choose, ShardedBackend.assign,
    sharded_assign_multihost): >3 extended resources exceed the [8, N] info
    rows (build_node_info), and vocab widths beyond MAX_BAND_WIDTH break the
    banded matmul's exact decomposition.  Unsupported clusters ride the
    bit-identical jnp path."""
    return nodes["node_avail"].shape[1] <= 5 and pallas_band_widths_ok(
        pods["pod_sel"].shape[1], pods["pod_ntol"].shape[1], pods["pod_aff"].shape[1]
    )


def build_node_info(node_avail, node_alloc, node_valid):
    """Pack node resources into the kernel's [8, N] int32 layout.

    Rows 0-1: available cpu/mem; 2-3: allocatable cpu/mem (scoring); 4:
    valid; 5-7: available EXTENDED resources (res_vocab columns 2..4 —
    up to three; wider clusters bypass the kernel, see assign._choose)."""
    n = node_avail.shape[0]
    r = node_avail.shape[1]
    assert r <= 5, "pallas choose supports at most 3 extended resources"
    rows = [
        node_avail[:, 0],
        node_avail[:, 1],
        node_alloc[:, 0],
        node_alloc[:, 1],
        node_valid.astype(jnp.int32),
    ]
    for j in range(2, r):
        rows.append(node_avail[:, j])
    while len(rows) < 8:
        rows.append(jnp.zeros((n,), jnp.int32))
    return jnp.stack(rows, axis=0)


def constrained_kernel_node_operands(pods: dict, masks: dict, n_nodes: int):
    """(six node-side kernel operands, pa_inactive) from one round's
    blocked/penalty masks (ops/constraints.round_blocked_masks, node axis
    already sliced to this shard where applicable).  Since round 7 the
    masks derive from the ROUND-CARRIED conflict state (spread water line,
    PA bootstrap flags threaded through the auction carry and updated by
    constraint_commit) rather than per-round re-reductions — bitwise the
    same operand values, so the constrained kernel variant needs no new
    refs and its parity contract is untouched; the fused active-set filter
    itself is an ACCEPT-phase rewrite and stays outside the choose kernel
    by design.

    THE one source of truth for the zero-fill convention: features absent
    from the cycle (no hard PA / soft spread / preferred terms) become
    exact-zero operands whose matmuls add an exact 0.0 — bitwise-neutral —
    so a single constrained kernel variant serves every constraint mix.
    ``pods`` supplies the feature widths (any dict holding the constraint
    pod bitmaps: the full pod dict or a sliced block)."""
    f32 = jnp.float32
    paun = masks.get("pa_unmatched_node")
    pa_inactive = masks.get("pa_inactive")
    if paun is None:
        paun = jnp.zeros((pods["pod_pa_declares"].shape[1], n_nodes), f32)
        pa_inactive = jnp.zeros((pods["pod_pa_declares"].shape[1],), f32)
    spspen = masks.get("sp_penalty_node")
    if spspen is None:
        spspen = jnp.zeros((pods["pod_sps_declares"].shape[1], n_nodes), f32)
    splevel = masks.get("sp_level_node")
    if splevel is None:
        splevel = jnp.zeros((pods["pod_sp_declares"].shape[1], n_nodes), f32)
    ppacnt = masks.get("ppa_cnt_node")
    if ppacnt is None:
        ppacnt = jnp.zeros((pods["pod_ppa_w"].shape[1], n_nodes), f32)
    return (masks["aa_m_node"], masks["aa_c_node"], masks["sp_node"], paun, spspen, splevel, ppacnt), pa_inactive


def constrained_kernel_pod_operands(blk: dict, pa_inactive):
    """Seven pod-side kernel operands for one pod block.  The positive-
    affinity bootstrap gate (a self-matching declarer of a globally-inactive
    term drops the term for this round — ops/constraints.blocked_block) is
    applied HERE, pod-side, so the kernel's matmul sees the gated bitmap.
    ``pod_sp_declares`` appears twice: once in the blocked band, once
    unbanded for the hard-spread level-steering score matmul."""
    gated = blk["pod_pa_declares"] * (1.0 - blk["pod_pa_matched"] * pa_inactive[None, :])
    return (
        blk["pod_aa_carries"],
        blk["pod_aa_matched"],
        blk["pod_sp_declares"],
        gated,
        blk["pod_sps_declares"],
        blk["pod_sp_declares"],
        blk["pod_ppa_w"],
    )


def _make_choose_kernel(constrained: bool):
    """Kernel body factory.  ``constrained=True`` adds THREE pod-side and
    THREE node-side refs carrying the per-round constraint operands
    (ops/constraints.round_blocked_masks): the four hard blocked-node
    bitmaps (anti-affinity matched/carrier, spread saturation, gated
    positive affinity) banded into ONE matmul pair, plus the two soft score
    matmuls (ScheduleAnyway spread penalty, preferred inter-pod counts).
    Absent features ride as exact-zero operands, so results stay bitwise
    equal to the jnp expression tree."""

    def kernel(*refs):
        # Single slice-based unpack — the group order here is the ONE place
        # that must mirror the in_specs/operands construction in
        # choose_block_pallas (grouped identically there).
        (
            weights_ref,  # [1, 8] f32 SMEM (w_lr, w_ba, w_jitter, w_pref, w_soft_taint, w_topo, round_salt, node_offset)
            req_ref,  # [BP, R] i32
            hard_ref,  # [BP, L+T+A] f32  banded [sel | 256·ntol | 65536·aff]
            selc_ref,  # [BP, 1] f32
            hasaff_ref,  # [BP, 1] f32  (1 if the pod declares node affinity)
            prefw_ref,  # [BP, A2] f32  (pod's weight per preferred-affinity term)
            ntols_ref,  # [BP, Ts] f32  (1 where soft vocab taint NOT tolerated)
        ) = refs[:7]
        k = 7
        if constrained:
            (
                blk_ref,  # [BP, 2Tc+S+Ta] f32  banded [aa_carries | aa_matched | sp_declares | gated_pa]
                sps_ref,  # [BP, Ss] f32  (pod declares soft spread constraint)
                spd_ref,  # [BP, S] f32  (pod declares HARD spread — level steering)
                ppaw_ref,  # [BP, Tp] f32  (signed preferred inter-pod weights)
            ) = refs[k : k + 4]
            k += 4
        (
            act_ref,  # [BP, 1] i32
            idx_ref,  # [BP, 1] u32  (priority ranks, jitter hash input)
            info_ref,  # [8, TN] i32  (node resources, see ROW_*)
            hard_t_ref,  # [L+T+A, TN] f32  banded [labelsT; taintsT; affT]
            pref_t_ref,  # [A2, TN] f32
            taints_soft_t_ref,  # [Ts, TN] f32
        ) = refs[k : k + 6]
        k += 6
        if constrained:
            (
                blk_t_ref,  # [2Tc+S+Ta, TN] f32  banded [aa_m_node; aa_c_node; sp_node; pa_unmatched]
                spspen_ref,  # [Ss, TN] f32  (soft-spread penalty counts)
                splevel_ref,  # [S, TN] f32  (hard-spread domain height above water line)
                ppacnt_ref,  # [Tp, TN] f32  (preferred inter-pod match counts)
            ) = refs[k : k + 4]
            k += 4
        (
            choice_ref,  # [BP, 1] i32 out
            has_ref,  # [BP, 1] i32 out
            bestout_ref,  # [BP, 1] f32 out (best score — tp-merge operand)
            best_ref,  # [BP, 1] f32 scratch
            bestidx_ref,  # [BP, 1] i32 scratch
        ) = refs[k : k + 5]

        j = pl.program_id(1)
        nb = pl.num_programs(1)
        tn = info_ref.shape[1]
        f32 = jnp.float32

        @pl.when(j == 0)
        def _():
            best_ref[:] = jnp.full_like(best_ref, NEG_INF)
            bestidx_ref[:] = jnp.zeros_like(bestidx_ref)

        avail = info_ref[0:2, :]  # [2, TN] i32
        alloc = info_ref[2:4, :]
        valid = info_ref[ROW_VALID : ROW_VALID + 1, :]  # [1, TN] i32

        req_cpu = req_ref[:, 0:1]  # [BP, 1] i32
        req_mem = req_ref[:, 1:2]

        # PodFitsResources — exact int32, identical to ops/masks.py; extended
        # resources (req columns 2+, info rows 5+) join the same AND.
        fit = (req_cpu <= avail[0:1, :]) & (req_mem <= avail[1:2, :])  # [BP, TN]
        for e in range(req_ref.shape[1] - 2):
            fit = fit & (req_ref[:, 2 + e : 3 + e] <= info_ref[5 + e : 6 + e, :])

        # ONE banded matmul for all three hard count predicates, then exact
        # base decomposition (module docstring): counts = c mod 256,
        # untol = (c mod 65536) div 256, aff_hits = c div 65536 — every
        # value an exact f32 integer, bitwise what three matmuls would give.
        c = jnp.dot(hard_ref[:], hard_t_ref[:], preferred_element_type=f32)  # [BP, TN]
        aff_hits = jnp.floor(c / BAND_AFF)
        rem = c - aff_hits * BAND_AFF
        untol = jnp.floor(rem / BAND_TAINT)
        counts = rem - untol * BAND_TAINT
        sel_ok = counts == selc_ref[:]  # nodeSelector pair counting
        taint_ok = untol == f32(0.0)  # untolerated-taint counting
        # node affinity — ORed terms: eligible iff no affinity or >=1 hit.
        aff_ok = (aff_hits > f32(0.0)) | (hasaff_ref[:] == f32(0.0))

        mask = fit & sel_ok & taint_ok & aff_ok & (valid > 0) & (act_ref[:] > 0)

        if constrained:
            # Constraint-blocked domains — the four matmuls of
            # ops/constraints.blocked_block as ONE band: only the sum feeds
            # the > 0 test, and sums of exact small ints are
            # order-independent, so no decomposition is needed.
            blocked = jnp.dot(blk_ref[:], blk_t_ref[:], preferred_element_type=f32)
            mask = mask & ~(blocked > f32(0.0))

        # LeastRequested + BalancedAllocation — same op order as ops/score.py.
        used_cpu = (alloc[0:1, :] - avail[0:1, :]) + req_cpu  # [BP, TN] i32
        used_mem = (alloc[1:2, :] - avail[1:2, :]) + req_mem
        safe_cpu = alloc[0:1, :] > 0
        safe_mem = alloc[1:2, :] > 0
        denom_cpu = jnp.where(safe_cpu, alloc[0:1, :].astype(f32), f32(1.0))
        denom_mem = jnp.where(safe_mem, alloc[1:2, :].astype(f32), f32(1.0))
        frac_cpu = jnp.where(safe_cpu, used_cpu.astype(f32) / denom_cpu, f32(1.0))
        frac_mem = jnp.where(safe_mem, used_mem.astype(f32) / denom_mem, f32(1.0))
        least_requested = ((f32(1.0) - frac_cpu) + (f32(1.0) - frac_mem)) * f32(50.0)
        balanced = (f32(1.0) - jnp.abs(frac_cpu - frac_mem)) * f32(100.0)
        score = weights_ref[0, 0] * least_requested + weights_ref[0, 1] * balanced

        # Soft terms, same op order as ops/score.py: preferred node affinity
        # (+w₃ · matching-term weights), then PreferNoSchedule taints (−w₄ per
        # untolerated soft taint).  Both are exact small-int matmuls in f32.
        pref = jnp.dot(prefw_ref[:], pref_t_ref[:], preferred_element_type=f32)  # [BP, TN]
        score = score + weights_ref[0, 3] * pref
        untol_soft = jnp.dot(ntols_ref[:], taints_soft_t_ref[:], preferred_element_type=f32)
        score = score - weights_ref[0, 4] * untol_soft

        # Deterministic tie-break jitter — same uint32 hash as ops/score.py,
        # including the auction-round salt (rides SMEM weights slot 6) and
        # the node-index offset (slot 7 — nonzero only under a sharded mesh,
        # where this shard's nodes start at a global base; < 2^24 so the f32
        # round-trip is exact).
        u32 = jnp.uint32
        off = weights_ref[0, 7].astype(jnp.int32)
        node_idx = (off + j * tn + jax.lax.broadcasted_iota(jnp.int32, (1, tn), 1)).astype(u32)
        salt = weights_ref[0, 6].astype(jnp.int32).astype(u32)
        h = idx_ref[:].astype(u32) * u32(2654435761) + node_idx * u32(2246822519) + salt * u32(3266489917)
        h = (h ^ (h >> u32(15))) & u32(0xFFFF)
        # Mosaic lacks a direct uint32→f32 cast; h < 2^16 so int32 is exact.
        # Bucket-quantized tie-break — identical op order to ops/score.py.
        jw = weights_ref[0, 2]
        safe = jnp.where(jw > 0, jw, f32(1.0))
        score = jnp.where(jw > 0, jnp.floor(score / safe) * safe, score) + jw * (
            h.astype(jnp.int32).astype(f32) / f32(65536.0)
        )

        if constrained:
            # Soft constraint scores AFTER the jitter — ops/score.py order:
            # −w₅ · ScheduleAnyway penalty, then −2·w₂ per hard-spread level
            # above the water line (declarer steering), then +signed
            # preferred counts.
            spspen = jnp.dot(sps_ref[:], spspen_ref[:], preferred_element_type=f32)
            score = score - weights_ref[0, 5] * spspen
            splevel = jnp.dot(spd_ref[:], splevel_ref[:], preferred_element_type=f32)
            score = score - (f32(2.0) * weights_ref[0, 2]) * splevel
            score = score + jnp.dot(ppaw_ref[:], ppacnt_ref[:], preferred_element_type=f32)

        sc = jnp.where(mask, score.astype(f32), NEG_INF)

        tile_best = jnp.max(sc, axis=1, keepdims=True)  # [BP, 1]
        # Exact lowest-index tie-break: Mosaic's argmax lowering does NOT
        # guarantee first-index on ties at every lane width (observed on
        # chip at tn=1024: a two-node score tie returned the higher index,
        # breaking bit-parity with the jnp path — jnp.argmax IS
        # first-index).  A max + masked min-reduction over the lane iota is
        # exact at any width; the cross-tile merge below keeps the earlier
        # tile on ties (strict >), so the global result is always the
        # lowest-index maximum.
        lane = jax.lax.broadcasted_iota(jnp.int32, sc.shape, 1)
        tile_arg = jnp.min(jnp.where(sc == tile_best, lane, jnp.int32(tn)), axis=1).reshape(-1, 1) + j * tn

        improve = tile_best > best_ref[:]
        bestidx_ref[:] = jnp.where(improve, tile_arg, bestidx_ref[:])
        best_ref[:] = jnp.where(improve, tile_best, best_ref[:])

        @pl.when(j == nb - 1)
        def _():
            choice_ref[:] = bestidx_ref[:]
            has_ref[:] = (best_ref[:] > NEG_INF).astype(jnp.int32)
            bestout_ref[:] = best_ref[:]

    return kernel


# bucket: bp pb nbt b_pad n_pad
@functools.partial(jax.jit, static_argnames=("pod_tile", "node_tile", "interpret", "return_best"))
def choose_block_pallas(
    req,  # [B, 2] i32
    sel,  # [B, L] f32
    selc,  # [B] f32
    ntol,  # [B, T] f32
    aff,  # [B, A] f32
    has_aff,  # [B] f32
    pref_w,  # [B, A2] f32
    ntol_soft,  # [B, Ts] f32
    act,  # [B] bool
    ranks,  # [B] u32
    node_info,  # [8, N] i32 (build_node_info)
    labels_t,  # [L, N] f32
    taints_t,  # [T, N] f32
    aff_t,  # [A, N] f32
    pref_t,  # [A2, N] f32
    taints_soft_t,  # [Ts, N] f32
    weights,  # [6] f32 (SchedulingProfile.weights())
    salt=None,  # auction round (int32 scalar) — jitter re-roll per round
    cons_pod=None,  # (aa_carries [B,Tc], aa_matched [B,Tc], sp_declares [B,S],
    #                pa_gated [B,Ta], sps_declares [B,Ss], ppa_w [B,Tp]) f32
    cons_node=None,  # (aa_m_node [Tc,N], aa_c_node [Tc,N], sp_node [S,N],
    #                 pa_unmatched [Ta,N], sp_penalty [Ss,N], ppa_cnt [Tp,N]) f32
    node_offset=None,  # global index of node 0 (sharded meshes; jitter hash)
    pod_tile: int = 256,
    node_tile: int = 1024,
    interpret: bool = False,
    return_best: bool = False,
):
    """Fused choose over a block of pods: returns (choice [B] i32, has [B]
    bool), plus the per-pod best score ([B] f32, −inf where infeasible) when
    ``return_best`` — the cross-shard merge operand of parallel/sharded.py.
    ``node_offset`` shifts the jitter hash's node indices to global space
    when the node tensors are one shard of a mesh-sharded cluster.

    Pads pods/nodes up to tile multiples internally; padded pods are
    inactive, padded nodes invalid, so results are unaffected.

    ``cons_pod``/``cons_node`` (six arrays each, given together) switch on
    the constrained kernel: the wrapper bands the four blocked bitmaps of
    each side into ONE operand pair and passes the two soft operands
    separately — three extra pod-side and three extra node-side kernel refs
    ([·, N]-shaped, VMEM-cheap) — while the accept/commit phases stay in
    jnp (ops/assign.py).  Features absent from a cycle are exact-zero
    operands, keeping results bitwise equal to the jnp path.
    """
    constrained = cons_pod is not None
    b, n = req.shape[0], node_info.shape[1]
    r = req.shape[1]
    l = sel.shape[1]
    t = ntol.shape[1]
    a_dim = aff.shape[1]
    a2_dim = pref_w.shape[1]
    ts_dim = ntol_soft.shape[1]
    assert pallas_band_widths_ok(l, t, a_dim), (
        f"vocab widths ({l}, {t}, {a_dim}) exceed the banded-matmul bound "
        f"{MAX_BAND_WIDTH} — callers must route this cluster to the jnp path"
    )
    bp = min(pod_tile, max(8, b))
    pb = -(-b // bp)
    nbt = -(-n // node_tile)
    b_pad, n_pad = pb * bp, nbt * node_tile

    if b_pad != b:
        req = jnp.pad(req, ((0, b_pad - b), (0, 0)))
        sel = jnp.pad(sel, ((0, b_pad - b), (0, 0)))
        selc = jnp.pad(selc, ((0, b_pad - b),))
        ntol = jnp.pad(ntol, ((0, b_pad - b), (0, 0)))
        aff = jnp.pad(aff, ((0, b_pad - b), (0, 0)))
        has_aff = jnp.pad(has_aff, ((0, b_pad - b),))
        pref_w = jnp.pad(pref_w, ((0, b_pad - b), (0, 0)))
        ntol_soft = jnp.pad(ntol_soft, ((0, b_pad - b), (0, 0)))
        act = jnp.pad(act, ((0, b_pad - b),))
        ranks = jnp.pad(ranks, ((0, b_pad - b),))
        if constrained:
            cons_pod = tuple(jnp.pad(v, ((0, b_pad - b), (0, 0))) for v in cons_pod)
    if n_pad != n:
        node_info = jnp.pad(node_info, ((0, 0), (0, n_pad - n)))
        labels_t = jnp.pad(labels_t, ((0, 0), (0, n_pad - n)))
        taints_t = jnp.pad(taints_t, ((0, 0), (0, n_pad - n)))
        aff_t = jnp.pad(aff_t, ((0, 0), (0, n_pad - n)))
        pref_t = jnp.pad(pref_t, ((0, 0), (0, n_pad - n)))
        taints_soft_t = jnp.pad(taints_soft_t, ((0, 0), (0, n_pad - n)))
        if constrained:
            cons_node = tuple(jnp.pad(v, ((0, 0), (0, n_pad - n))) for v in cons_node)

    # The kernel consumes the first 6 profile weights only; slots 6-7 are
    # the round salt and node offset.  weights may be longer (index 6 is
    # gang_locality_weight — consumed upstream by topology/locality.py, and
    # topology cycles never reach the kernel), so slice before padding.
    w6 = weights.astype(jnp.float32)[:6]
    w = jnp.pad(w6, (0, 8 - w6.shape[0])).reshape(1, 8)
    if salt is not None:
        w = w.at[0, 6].set(jnp.asarray(salt).astype(jnp.float32))
    if node_offset is not None:
        w = w.at[0, 7].set(jnp.asarray(node_offset).astype(jnp.float32))

    pod_row = lambda width: pl.BlockSpec((bp, width), lambda i, j: (i, 0))  # noqa: E731
    node_row = lambda rows: pl.BlockSpec((rows, node_tile), lambda i, j: (0, j))  # noqa: E731

    # Banded hard operands (see module docstring): scale pod-side so the one
    # matmul packs the three counts into disjoint power-of-2 bands.
    f32 = jnp.float32
    hard_band = jnp.concatenate(
        [sel.astype(f32), ntol.astype(f32) * f32(BAND_TAINT), aff.astype(f32) * f32(BAND_AFF)], axis=1
    )
    hard_band_t = jnp.concatenate([labels_t.astype(f32), taints_t.astype(f32), aff_t.astype(f32)], axis=0)

    in_specs = [
        pl.BlockSpec((1, 8), lambda i, j: (0, 0), memory_space=pltpu.SMEM),
        pod_row(r),
        pod_row(l + t + a_dim),
        pod_row(1),
        pod_row(1),
        pod_row(a2_dim),
        pod_row(ts_dim),
    ]
    operands = [
        w,
        req,
        hard_band,
        selc.reshape(-1, 1),
        has_aff.astype(f32).reshape(-1, 1),
        pref_w,
        ntol_soft,
    ]
    if constrained:
        # The four blocked bitmaps band into one matmul (sum-only — no
        # decomposition, no scaling); soft operands stay separate.
        blk_band = jnp.concatenate([v.astype(f32) for v in cons_pod[:4]], axis=1)
        blk_band_t = jnp.concatenate([v.astype(f32) for v in cons_node[:4]], axis=0)
        in_specs += [
            pod_row(blk_band.shape[1]),
            pod_row(cons_pod[4].shape[1]),
            pod_row(cons_pod[5].shape[1]),
            pod_row(cons_pod[6].shape[1]),
        ]
        operands += [blk_band, cons_pod[4].astype(f32), cons_pod[5].astype(f32), cons_pod[6].astype(f32)]
    in_specs += [
        pod_row(1),
        pod_row(1),
        node_row(8),
        node_row(l + t + a_dim),
        node_row(a2_dim),
        node_row(ts_dim),
    ]
    operands += [
        act.astype(jnp.int32).reshape(-1, 1),
        ranks.astype(jnp.uint32).reshape(-1, 1),
        node_info,
        hard_band_t,
        pref_t,
        taints_soft_t,
    ]
    if constrained:
        in_specs += [
            node_row(blk_band_t.shape[0]),
            node_row(cons_node[4].shape[0]),
            node_row(cons_node[5].shape[0]),
            node_row(cons_node[6].shape[0]),
        ]
        operands += [blk_band_t, cons_node[4].astype(f32), cons_node[5].astype(f32), cons_node[6].astype(f32)]

    grid = (pb, nbt)
    choice, has, best = pl.pallas_call(
        _make_choose_kernel(constrained),
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((bp, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((bp, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((bp, 1), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b_pad, 1), jnp.int32),
            jax.ShapeDtypeStruct((b_pad, 1), jnp.int32),
            jax.ShapeDtypeStruct((b_pad, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bp, 1), jnp.float32),
            pltpu.VMEM((bp, 1), jnp.int32),
        ],
        interpret=interpret,
    )(*operands)
    if return_best:
        return choice[:b, 0], has[:b, 0].astype(bool), best[:b, 0]
    return choice[:b, 0], has[:b, 0].astype(bool)
