"""ctypes loader for the native packing shim (native/pack.cpp →
libtpusched.so).

The shim is the C++ equivalent of the reference's native kube_quantity
arithmetic (``src/util.rs:17-36``): batch quantity parsing and request-row
packing.  Python (api/quantity.py) remains the semantic oracle — the shim is
an accelerator, optional at runtime: every caller falls back to the Python
path when the library isn't built (``make -C native``).
"""

from __future__ import annotations

import ctypes
import os
from functools import lru_cache

import numpy as np

__all__ = ["available", "batch_parse", "pack_requests", "MODE_CPU_MILLIS", "MODE_MEM_BYTES"]

MODE_CPU_MILLIS = 0
MODE_MEM_BYTES = 1

_DEFAULT_LIB = os.path.join(os.path.dirname(__file__), "..", "..", "native", "build", "libtpusched.so")


@lru_cache(maxsize=1)
def _lib():
    # Env override wins over the default build path; read lazily so setting
    # it before first use works.  (Changing it after first use requires
    # _lib.cache_clear() — the handle is cached.)
    for path in (os.environ.get("TPUSCHED_NATIVE_LIB", ""), _DEFAULT_LIB):
        if path and os.path.exists(path):
            try:
                lib = ctypes.CDLL(os.path.abspath(path))
            except OSError:
                continue
            lib.tpusched_parse.restype = ctypes.c_int
            lib.tpusched_parse.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.POINTER(ctypes.c_int64)]
            lib.tpusched_batch_parse.restype = ctypes.c_int64
            lib.tpusched_batch_parse.argtypes = [
                ctypes.POINTER(ctypes.c_char_p),
                ctypes.c_int64,
                ctypes.c_int,
                ctypes.POINTER(ctypes.c_int64),
            ]
            lib.tpusched_pack_requests.restype = ctypes.c_int64
            lib.tpusched_pack_requests.argtypes = [
                ctypes.POINTER(ctypes.c_char_p),
                ctypes.POINTER(ctypes.c_char_p),
                ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int32),
            ]
            return lib
    return None


def available() -> bool:
    return _lib() is not None


def _to_char_pp(strs: list[str | None]):
    arr = (ctypes.c_char_p * len(strs))()
    for i, s in enumerate(strs):
        arr[i] = None if s is None else str(s).encode()
    return arr


def batch_parse(strs: list[str], mode: int) -> np.ndarray:
    """Parse quantities to int64 base units (millicores / bytes).

    Raises ValueError naming the first invalid quantity, matching the Python
    parser's behaviour.
    """
    lib = _lib()
    if lib is None:
        raise RuntimeError("native shim not built (make -C native)")
    out = np.zeros(len(strs), dtype=np.int64)
    bad = lib.tpusched_batch_parse(
        _to_char_pp(strs), len(strs), mode, out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
    )
    if bad >= 0:
        raise ValueError(f"invalid quantity: {strs[bad]!r}")
    return out


def pack_requests(cpu_strs: list[str | None], mem_strs: list[str | None]) -> np.ndarray:
    """[n,2] int32 (millicores, KiB-ceil) request rows — the ops/pack.py
    unit/rounding convention, computed natively."""
    lib = _lib()
    if lib is None:
        raise RuntimeError("native shim not built (make -C native)")
    assert len(cpu_strs) == len(mem_strs)
    out = np.zeros((len(cpu_strs), 2), dtype=np.int32)
    bad = lib.tpusched_pack_requests(
        _to_char_pp(cpu_strs),
        _to_char_pp(mem_strs),
        len(cpu_strs),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    if bad >= 0:
        raise ValueError(f"invalid quantity in row {bad}: cpu={cpu_strs[bad]!r} mem={mem_strs[bad]!r}")
    return out
