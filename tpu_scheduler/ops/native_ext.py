"""ctypes loader for the native packing shim (native/pack.cpp →
libtpusched.so).

The shim is the C++ equivalent of the reference's native kube_quantity
arithmetic (``src/util.rs:17-36``): batch quantity parsing and request-row
packing.  Python (api/quantity.py) remains the semantic oracle — the shim is
an accelerator, optional at runtime: every caller falls back to the Python
path when the library isn't built (``make -C native``).
"""

from __future__ import annotations

import ctypes
import os
from functools import lru_cache

import numpy as np

__all__ = ["available", "batch_parse", "pack_requests", "MODE_CPU_MILLIS", "MODE_MEM_BYTES"]

MODE_CPU_MILLIS = 0
MODE_MEM_BYTES = 1

_DEFAULT_LIB = os.path.join(os.path.dirname(__file__), "..", "..", "native", "build", "libtpusched.so")


@lru_cache(maxsize=1)
def _lib():
    # Env override wins over the default build path; read lazily so setting
    # it before first use works.  (Changing it after first use requires
    # _lib.cache_clear() — the handle is cached.)
    for path in (os.environ.get("TPUSCHED_NATIVE_LIB", ""), _DEFAULT_LIB):
        if path and os.path.exists(path):
            try:
                lib = ctypes.CDLL(os.path.abspath(path))
            except OSError:
                continue
            lib.tpusched_parse.restype = ctypes.c_int
            lib.tpusched_parse.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.POINTER(ctypes.c_int64)]
            lib.tpusched_batch_parse.restype = ctypes.c_int64
            lib.tpusched_batch_parse.argtypes = [
                ctypes.POINTER(ctypes.c_char_p),
                ctypes.c_int64,
                ctypes.c_int,
                ctypes.POINTER(ctypes.c_int64),
            ]
            lib.tpusched_pack_requests.restype = ctypes.c_int64
            lib.tpusched_pack_requests.argtypes = [
                ctypes.POINTER(ctypes.c_char_p),
                ctypes.POINTER(ctypes.c_char_p),
                ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int32),
            ]
            if hasattr(lib, "tpusched_batch_parse_ex"):
                lib.tpusched_batch_parse_ex.restype = ctypes.c_int64
                lib.tpusched_batch_parse_ex.argtypes = [
                    ctypes.POINTER(ctypes.c_char_p),
                    ctypes.c_int64,
                    ctypes.c_int,
                    ctypes.POINTER(ctypes.c_int64),
                    ctypes.POINTER(ctypes.c_uint8),
                ]
                lib.tpusched_pack_requests_ex.restype = ctypes.c_int64
                lib.tpusched_pack_requests_ex.argtypes = [
                    ctypes.POINTER(ctypes.c_char_p),
                    ctypes.POINTER(ctypes.c_char_p),
                    ctypes.c_int64,
                    ctypes.POINTER(ctypes.c_int32),
                    ctypes.POINTER(ctypes.c_uint8),
                ]
            return lib
    return None


def available() -> bool:
    return _lib() is not None


def _to_char_pp(strs: list[str | None]):
    arr = (ctypes.c_char_p * len(strs))()
    for i, s in enumerate(strs):
        arr[i] = None if s is None else str(s).encode()
    return arr


_I64_MAX = 2**63 - 1


def _clamp64(v: int) -> int:
    return max(-_I64_MAX, min(_I64_MAX, v))


def _oracle(s: str, mode: int) -> int:
    """Exact Python parse in shim units (int64-clamped)."""
    from ..api.quantity import cpu_to_millis, memory_to_bytes

    return _clamp64(cpu_to_millis(s) if mode == MODE_CPU_MILLIS else memory_to_bytes(s))


def batch_parse(strs: list[str], mode: int) -> np.ndarray:
    """Parse quantities to int64 base units (millicores / bytes).

    Raises ValueError naming the first invalid quantity, matching the Python
    parser's behaviour.  Entries whose >38-digit mantissas saturate the
    shim's 128-bit arithmetic are flagged by the C side and recomputed here
    through the exact Python oracle, so agreement is exact for every input.
    """
    lib = _lib()
    if lib is None:
        raise RuntimeError("native shim not built (make -C native)")
    out = np.zeros(len(strs), dtype=np.int64)
    if hasattr(lib, "tpusched_batch_parse_ex"):
        inexact = np.zeros(len(strs), dtype=np.uint8)
        bad = lib.tpusched_batch_parse_ex(
            _to_char_pp(strs),
            len(strs),
            mode,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            inexact.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        )
        if bad >= 0:
            raise ValueError(f"invalid quantity: {strs[bad]!r}")
        for i in np.flatnonzero(inexact):
            out[i] = _oracle(strs[i], mode)
        return out
    bad = lib.tpusched_batch_parse(
        _to_char_pp(strs), len(strs), mode, out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
    )
    if bad >= 0:
        raise ValueError(f"invalid quantity: {strs[bad]!r}")
    return out


_I32_MAX = 2**31 - 1


def pack_requests(cpu_strs: list[str | None], mem_strs: list[str | None]) -> np.ndarray:
    """[n,2] int32 (millicores, KiB-ceil) request rows — the ops/pack.py
    unit/rounding convention, computed natively.  Saturation-flagged rows are
    recomputed via the exact Python oracle (see batch_parse)."""
    lib = _lib()
    if lib is None:
        raise RuntimeError("native shim not built (make -C native)")
    assert len(cpu_strs) == len(mem_strs)
    out = np.zeros((len(cpu_strs), 2), dtype=np.int32)
    if hasattr(lib, "tpusched_pack_requests_ex"):
        inexact = np.zeros(len(cpu_strs), dtype=np.uint8)
        bad = lib.tpusched_pack_requests_ex(
            _to_char_pp(cpu_strs),
            _to_char_pp(mem_strs),
            len(cpu_strs),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            inexact.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        )
        if bad >= 0:
            raise ValueError(f"invalid quantity in row {bad}: cpu={cpu_strs[bad]!r} mem={mem_strs[bad]!r}")
        for i in np.flatnonzero(inexact):
            cpu = _oracle(cpu_strs[i], MODE_CPU_MILLIS) if cpu_strs[i] is not None else 0
            mem = _oracle(mem_strs[i], MODE_MEM_BYTES) if mem_strs[i] is not None else 0
            # Matches the C row convention: ceil for non-negative, C-style
            # truncation toward zero for negative.
            kib = (mem + 1023) // 1024 if mem >= 0 else -((-mem) // 1024)
            out[i, 0] = max(-_I32_MAX, min(_I32_MAX, cpu))
            out[i, 1] = max(-_I32_MAX, min(_I32_MAX, kib))
        return out
    bad = lib.tpusched_pack_requests(
        _to_char_pp(cpu_strs),
        _to_char_pp(mem_strs),
        len(cpu_strs),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    if bad >= 0:
        raise ValueError(f"invalid quantity in row {bad}: cpu={cpu_strs[bad]!r} mem={mem_strs[bad]!r}")
    return out
