"""Tensorization: ClusterSnapshot → packed device tensors.

This is the boundary between the object world (api/, core/) and the tensor
world (ops/, backends/).  It replaces the reference's per-candidate live
API-server list + quantity subtraction loop (``src/predicates.rs:21-38``)
with a one-shot pack of the whole cluster:

  node_alloc[N,R]  int32   total allocatable  (cpu millis, memory KiB, then
                           extended device resources — res_vocab/res_scales)
  node_avail[N,R]  int32   remaining = allocatable − Σ bound-pod requests
  node_labels[N,L] float32 bitmap over the selector-pair vocabulary
  node_taints[N,T] float32 bitmap over the hard-taint vocabulary
  node_aff[N,A]    float32 bitmap: node satisfies affinity-term vocab entry
  pod_req[P,R]     int32   pending-pod requests (millis, KiB ceil, counts)
  pod_sel[P,L]     float32 selector bitmap; pod_sel_count[P] = #selector keys
  pod_ntol[P,T]    float32 1 where the pod does NOT tolerate vocab taint t
  pod_aff[P,A]     float32 bitmap of the pod's node-affinity terms
  pod_has_aff[P]   float32 1 if the pod declares required node affinity
  pod_prio[P]      int32   pod priority (commit order tie-break)

Node affinity tensorizes through a *term vocabulary*: each distinct
nodeSelectorTerm (canonical form, NodeSelectorTerm.key()) among the pending
pods becomes a column; the full operator semantics (In/NotIn/Exists/
DoesNotExist/Gt/Lt, core/predicates.py) are evaluated host-side once per
(term, node) — O(A·N) per node-set change, amortised across cycles — so the
device check is one matmul: eligible iff no affinity, or
(pod_aff · node_aff[n]) > 0 (terms are ORed).

Taints tensorize dually to selectors: the vocabulary is the set of hard
(NoSchedule/NoExecute) taint triples present on nodes; toleration semantics
(Exists/Equal, empty-key, empty-effect — api/objects.py Toleration) are
evaluated host-side into the pod_ntol bitmap, so the device check is one
matmul: a node is tolerable iff (pod_ntol · node_taints[n]) == 0.  Cordoned
nodes (spec.unschedulable) fold into node_valid.

Unit choice: memory is KiB (not bytes) so everything fits int32 without
enabling jax_enable_x64 (int64 on TPU is emulated and slow).  Rounding is
conservative — allocatable floors, requests ceil, and values clamp to
[INT32_MIN, INT32_MAX] (a >2 TiB node appears as 2 TiB; a >2 TiB request is
effectively unschedulable) — so a fit decision made on packed tensors is
always valid under the exact scalar predicates (core/predicates.py); see
tests/test_pack.py.

Label vocabulary: only (key, value) pairs that appear in some pending pod's
nodeSelector can affect a decision, so the vocab is built from selectors, not
from the (unbounded) node label space.  A selector matches a node iff the
node carries every one of its pairs:  (pod_sel @ node_labels^T) == count.
Vocabularies are dynamic per cycle; shapes are padded to static buckets so
XLA recompiles only when a bucket grows (SURVEY.md §7 hard part (b)).

Shapes are padded to multiples of (pod_block, node_block) with validity
masks; padding rows have zero requests / zero capacity and are masked out of
every decision.
"""

from __future__ import annotations

from dataclasses import dataclass, replace

import numpy as np

from ..api.objects import Pod, is_extended_resource, total_pod_resources
from ..api.quantity import cpu_to_millis, memory_to_bytes
from ..core.snapshot import ClusterSnapshot
from ..errors import PackingError

__all__ = [
    "PackedCluster",
    "pack_snapshot",
    "repack_avail",
    "repack_incremental",
    "extend_node_vocabs",
    "build_selector_vocab",
    "build_taint_vocab",
    "build_affinity_vocab",
    "build_soft_taint_vocab",
    "build_pref_vocab",
    "resource_vocab",
    "round_up",
    "INT32_MAX",
    "STALL_ROUNDS",
]

CPU, MEM = 0, 1  # resource axis indices
INT32_MAX = 2**31 - 1
INT32_MIN = -(2**31)

# Constraint-cycle auctions stop after this many consecutive ZERO-acceptance
# rounds (shared by every backend so round counts stay bit-identical; see
# ops/assign.py for the rationale).  Lives here, not in assign.py, because
# the native recovery backend must import it without pulling in jax.
STALL_ROUNDS = 3


# shape: (x: int, multiple: int) -> int
# bucket: return
def round_up(x: int, multiple: int) -> int:
    if multiple <= 1:
        return max(x, 1)
    return max(((x + multiple - 1) // multiple) * multiple, multiple)


# shape: (x64: any) -> any
def _clamp_i32(x64: np.ndarray) -> np.ndarray:
    """int64 → int32 with saturation (never silent wraparound)."""
    return np.clip(x64, INT32_MIN, INT32_MAX).astype(np.int32)


@dataclass(frozen=True)
class PackedCluster:
    """Static-shape tensor view of one scheduling cycle's input."""

    # Nodes (padded to N)
    node_alloc: np.ndarray  # [N,R] int32 — total allocatable (see res_vocab)
    node_avail: np.ndarray  # [N,R] int32 — remaining after bound pods
    node_labels: np.ndarray  # [N,L] float32 — selector-pair bitmap
    node_taints: np.ndarray  # [N,T] float32 — hard-taint bitmap
    node_aff: np.ndarray  # [N,A] float32 — affinity-term satisfaction bitmap
    node_valid: np.ndarray  # [N]  bool (padding + cordoned nodes are False)
    node_names: tuple[str, ...]  # real nodes only (len = num_nodes)

    # Pending pods (padded to P)
    pod_req: np.ndarray  # [P,R] int32 — (millis, KiB ceil, counts)
    pod_sel: np.ndarray  # [P,L] float32
    pod_sel_count: np.ndarray  # [P] float32
    pod_ntol: np.ndarray  # [P,T] float32 — 1 where vocab taint NOT tolerated
    pod_aff: np.ndarray  # [P,A] float32 — the pod's affinity-term bitmap
    pod_has_aff: np.ndarray  # [P] float32 — 1 if pod declares node affinity
    pod_prio: np.ndarray  # [P] int32
    pod_valid: np.ndarray  # [P]  bool
    pod_names: tuple[str, ...]  # full names of real pending pods

    # Soft (scoring) terms — PreferNoSchedule taints and preferred node
    # affinity (ops/score.py); zero-filled when the cluster has none.
    node_taints_soft: np.ndarray  # [N,Ts] float32 — PreferNoSchedule bitmap
    pod_ntol_soft: np.ndarray  # [P,Ts] float32 — 1 where NOT tolerated
    node_pref: np.ndarray  # [N,A2] float32 — node satisfies pref-term
    pod_pref_w: np.ndarray  # [P,A2] float32 — pod's weight for pref-term

    vocab: dict[tuple[str, str], int]
    taint_vocab: dict[tuple[str, str, str], int]
    aff_vocab: dict[tuple, int]  # NodeSelectorTerm.key() -> column
    soft_taint_vocab: dict[tuple[str, str, str], int]
    pref_vocab: dict[tuple, int]  # preferred-term key -> column

    # Anti-affinity/topology-spread tensors for this cycle (ops/constraints
    # .ConstraintSet) — attached per-cycle by the controller (the domain
    # state depends on current placements, so it is never cached), None for
    # unconstrained cycles.
    constraints: object | None = None

    # Interconnect-topology tensors for this cycle (topology/locality
    # .TopologySet): gang membership + per-level domain masks feeding the
    # rank-aware co-placement score term.  Attached per-cycle by the
    # controller (gang membership changes every cycle); None for gangless
    # or topology-blind cycles.
    topology: object | None = None

    # Resource axis names for the [·, R] request/capacity tensors: always
    # ("cpu", "memory") first — millicores and ceil/floor-KiB, the exact
    # reference semantics — then any EXTENDED resources (device plugins:
    # google.com/tpu, nvidia.com/gpu, hugepages-*) requested by any pod in
    # the snapshot, as raw integer counts.  R == 2 for clusters without
    # extended requests, so the flagship path is unchanged.
    res_vocab: tuple[str, ...] = ("cpu", "memory")
    # Per-column unit divisor for the int32 tensors.  cpu is exact millis,
    # memory is fixed KiB (reference semantics); each EXTENDED column gets
    # the smallest power-of-1024 divisor under which every value in the
    # snapshot fits int32 — device counts stay exact at 1, byte-valued
    # quantities (hugepages, SGX EPC, ...) scale to KiB/MiB as needed, so
    # the fit comparison NEVER saturates into a false positive.  Rounding
    # stays conservative: requests ceil, capacities floor.
    res_scales: tuple[int, ...] = (1, 1024)

    # The pod OBJECTS behind the rows (same order as pod_names) — the
    # identity keys of the O(delta) row-reuse path in repack_incremental:
    # an unchanged object means unchanged spec (the API layer replaces
    # objects on modification), so its packed row can be gathered from the
    # previous cycle instead of re-derived in Python.  Host-only bookkeeping
    # (never shipped to device, never checkpointed).
    pod_objs: tuple = ()

    @property
    def num_nodes(self) -> int:
        return len(self.node_names)

    @property
    def num_pods(self) -> int:
        return len(self.pod_names)

    @property
    def padded_nodes(self) -> int:
        return self.node_alloc.shape[0]

    @property
    def padded_pods(self) -> int:
        return self.pod_req.shape[0]

    def device_arrays(self) -> dict[str, np.ndarray]:
        """The tensors that ship to the device (names → arrays)."""
        return {
            "node_alloc": self.node_alloc,
            "node_avail": self.node_avail,
            "node_labels": self.node_labels,
            "node_taints": self.node_taints,
            "node_aff": self.node_aff,
            "node_valid": self.node_valid,
            "pod_req": self.pod_req,
            "pod_sel": self.pod_sel,
            "pod_sel_count": self.pod_sel_count,
            "pod_ntol": self.pod_ntol,
            "pod_aff": self.pod_aff,
            "pod_has_aff": self.pod_has_aff,
            "pod_prio": self.pod_prio,
            "pod_valid": self.pod_valid,
            "node_taints_soft": self.node_taints_soft,
            "pod_ntol_soft": self.pod_ntol_soft,
            "node_pref": self.node_pref,
            "pod_pref_w": self.pod_pref_w,
        }


# shape: (pods: obj) -> dict
def build_selector_vocab(pods: list[Pod]) -> dict[tuple[str, str], int]:
    """Vocabulary of selector (key, value) pairs over the pending pods."""
    vocab: dict[tuple[str, str], int] = {}
    for p in pods:
        if p.spec is not None and p.spec.node_selector:
            for kv in p.spec.node_selector.items():
                if kv not in vocab:
                    vocab[kv] = len(vocab)
    return vocab


# shape: (pods: obj) -> dict
def build_affinity_vocab(pods: list[Pod]) -> dict[tuple, int]:
    """Vocabulary of canonical node-affinity terms over the pending pods."""
    vocab: dict[tuple, int] = {}
    for p in pods:
        if p.spec is not None and p.spec.node_affinity:
            for term in p.spec.node_affinity:
                k = term.key()
                if k not in vocab:
                    vocab[k] = len(vocab)
    return vocab


# shape: (key: obj) -> obj
def _term_from_key(key: tuple):
    from ..api.objects import LabelSelectorRequirement, NodeSelectorTerm

    return NodeSelectorTerm(
        match_expressions=[
            LabelSelectorRequirement(key=k, operator=op, values=list(vals) if vals else None) for k, op, vals in key
        ]
    )


# shape: (nodes: obj, aff_vocab: dict, n_pad: int, a_pad: int) -> [n_pad, a_pad] f32
def _pack_node_affinity(nodes, aff_vocab: dict, n_pad: int, a_pad: int) -> np.ndarray:
    """[N,A] node-satisfies-term bitmap, host-evaluated with the full scalar
    operator semantics (core/predicates.node_selector_term_matches)."""
    from ..core.predicates import node_selector_term_matches

    node_aff = np.zeros((n_pad, a_pad), dtype=np.float32)
    if not aff_vocab:
        return node_aff
    terms = [(idx, _term_from_key(key)) for key, idx in aff_vocab.items()]
    for i, node in enumerate(nodes):
        labels = node.metadata.labels
        for j, term in terms:
            if node_selector_term_matches(term, labels):
                node_aff[i, j] = 1.0
    return node_aff


# shape: (pending: obj, aff_vocab: dict, p_pad: int, a_pad: int) -> ([p_pad, a_pad] f32, [p_pad] f32)
def _pack_affinity(pending: list[Pod], aff_vocab: dict, p_pad: int, a_pad: int) -> tuple[np.ndarray, np.ndarray]:
    """Pod-side affinity bitmaps ([P,A] term membership, [P] has-affinity)."""
    pod_aff = np.zeros((p_pad, a_pad), dtype=np.float32)
    pod_has = np.zeros((p_pad,), dtype=np.float32)
    for i, pod in enumerate(pending):
        terms = (pod.spec.node_affinity or []) if pod.spec is not None else []
        if not terms:
            continue
        pod_has[i] = 1.0
        for term in terms:
            j = aff_vocab.get(term.key())
            if j is None:
                raise PackingError(f"affinity term {term.key()} missing from supplied aff_vocab")
            pod_aff[i, j] = 1.0
    return pod_aff, pod_has


# shape: (nodes: obj) -> dict
def build_taint_vocab(nodes) -> dict[tuple[str, str, str], int]:
    """Vocabulary of hard (key, value, effect) taint triples over the nodes."""
    from ..core.predicates import HARD_TAINT_EFFECTS

    vocab: dict[tuple[str, str, str], int] = {}
    for n in nodes:
        if n.spec is not None and n.spec.taints:
            for t in n.spec.taints:
                if t.effect in HARD_TAINT_EFFECTS:
                    triple = (t.key, t.value, t.effect)
                    if triple not in vocab:
                        vocab[triple] = len(vocab)
    return vocab


# shape: (nodes: obj) -> dict
def build_soft_taint_vocab(nodes) -> dict[tuple[str, str, str], int]:
    """Vocabulary of PreferNoSchedule taint triples — the soft (scoring)
    twin of :func:`build_taint_vocab`."""
    vocab: dict[tuple[str, str, str], int] = {}
    for n in nodes:
        if n.spec is not None and n.spec.taints:
            for t in n.spec.taints:
                if t.effect == "PreferNoSchedule":
                    triple = (t.key, t.value, t.effect)
                    if triple not in vocab:
                        vocab[triple] = len(vocab)
    return vocab


# shape: (pods: obj) -> dict
def build_pref_vocab(pods: list[Pod]) -> dict[tuple, int]:
    """Vocabulary of canonical preferred-affinity terms over pending pods."""
    vocab: dict[tuple, int] = {}
    for p in pods:
        if p.spec is not None and p.spec.preferred_node_affinity:
            for t in p.spec.preferred_node_affinity:
                k = t.term.key()
                if k not in vocab:
                    vocab[k] = len(vocab)
    return vocab


# shape: (nodes: obj, pref_vocab: dict, n_pad: int, a_pad: int) -> [n_pad, a_pad] f32
def _pack_node_pref(nodes, pref_vocab: dict, n_pad: int, a_pad: int) -> np.ndarray:
    """[N,A2] node-satisfies-preferred-term bitmap (full scalar operator
    semantics, same evaluator as the required-affinity pack)."""
    from ..core.predicates import node_selector_term_matches

    node_pref = np.zeros((n_pad, a_pad), dtype=np.float32)
    if not pref_vocab:
        return node_pref
    terms = [(idx, _term_from_key(key)) for key, idx in pref_vocab.items()]
    for i, node in enumerate(nodes):
        labels = node.metadata.labels
        for j, term in terms:
            if node_selector_term_matches(term, labels):
                node_pref[i, j] = 1.0
    return node_pref


# shape: (pending: obj, pref_vocab: dict, p_pad: int, a_pad: int) -> [p_pad, a_pad] f32
def _pack_pod_pref(pending: list[Pod], pref_vocab: dict, p_pad: int, a_pad: int) -> np.ndarray:
    """[P,A2] per-pod weight of each preferred term (duplicate declarations
    of the same canonical term sum their weights)."""
    pod_pref_w = np.zeros((p_pad, a_pad), dtype=np.float32)
    for i, pod in enumerate(pending):
        terms = (pod.spec.preferred_node_affinity or []) if pod.spec is not None else []
        for t in terms:
            j = pref_vocab.get(t.term.key())
            if j is None:
                raise PackingError(f"preferred term {t.term.key()} missing from supplied pref_vocab")
            pod_pref_w[i, j] += float(t.weight)
    return pod_pref_w


# shape: (pending: obj, taint_vocab: dict, p_pad: int, t_pad: int) -> [p_pad, t_pad] f32
def _pack_ntol(pending: list[Pod], taint_vocab: dict, p_pad: int, t_pad: int) -> np.ndarray:
    """[P,T] 1.0 where the pod does NOT tolerate vocab taint t (padding
    rows/columns are 0 = vacuously tolerated)."""
    from ..api.objects import Taint

    ntol = np.zeros((p_pad, t_pad), dtype=np.float32)
    if not taint_vocab:
        return ntol
    triples = [(idx, Taint(key=k, value=v, effect=e)) for (k, v, e), idx in taint_vocab.items()]

    # Most pods share a handful of toleration lists (or none at all, whose
    # row is all-ones over the vocab); cache rows by toleration content so
    # the per-cycle incremental repack stays O(P) instead of O(P·T) Python.
    default_row = np.zeros((t_pad,), dtype=np.float32)
    for j, _ in triples:
        default_row[j] = 1.0
    rows: dict[tuple, np.ndarray] = {}

    def row_for(tolerations) -> np.ndarray:
        key = tuple((t.key, t.operator, t.value, t.effect) for t in tolerations)
        row = rows.get(key)
        if row is None:
            row = np.zeros((t_pad,), dtype=np.float32)
            for j, taint in triples:
                if not any(t.tolerates(taint) for t in tolerations):
                    row[j] = 1.0
            rows[key] = row
        return row

    for i, pod in enumerate(pending):
        tolerations = (pod.spec.tolerations or []) if pod.spec is not None else []
        ntol[i] = row_for(tolerations) if tolerations else default_row
    return ntol


# shape: (snapshot: obj, res_memo: dict) -> obj
def resource_vocab(snapshot: ClusterSnapshot, res_memo: dict | None = None) -> tuple[str, ...]:
    """("cpu", "memory") plus every EXTENDED resource name
    (api/objects.is_extended_resource) any pod in the snapshot REQUESTS —
    bound pods too, since their usage must subtract from node capacity —
    sorted for a stable column order.  With ``res_memo`` (the same
    object-identity memo _alloc_and_used64 uses) the per-cycle cost is
    O(delta): unchanged pods answer from their cached PodResources."""
    names: set[str] = set()
    for pod in snapshot.pods:
        if pod.spec is None:
            continue
        if res_memo is not None:
            hit = res_memo.get(id(pod))
            if hit is not None and hit[0] is pod:
                res = hit[1]
            else:
                res = total_pod_resources(pod)
                res_memo[id(pod)] = (pod, res)
            if res.extended:
                names.update(res.extended)
            continue
        for c in pod.spec.containers:
            if c.resources is not None and c.resources.requests is not None:
                for k in c.resources.requests:
                    if k != "cpu" and k != "memory" and is_extended_resource(k):
                        names.add(k)
    return ("cpu", "memory", *sorted(names))


# shape: (snapshot: obj, n_pad: int, res_memo: dict, res_vocab: obj) -> ([n_pad, R] i64, [n_pad, R] i64, dict)
def _alloc_and_used64(
    snapshot: ClusterSnapshot, n_pad: int, res_memo: dict | None = None, res_vocab: tuple[str, ...] = ("cpu", "memory")
) -> tuple[np.ndarray, np.ndarray, dict[str, int]]:
    """Exact int64 (allocatable, bound-usage) per node — shared by pack and
    the incremental avail refresh."""
    r = len(res_vocab)
    alloc64 = np.zeros((n_pad, r), dtype=np.int64)
    used64 = np.zeros((n_pad, r), dtype=np.int64)
    node_index: dict[str, int] = {}
    for i, node in enumerate(snapshot.nodes):
        node_index[node.name] = i
        if node.status is not None and node.status.allocatable is not None:
            alloc = node.status.allocatable
            if "cpu" in alloc:
                alloc64[i, CPU] = cpu_to_millis(alloc["cpu"])
            if "memory" in alloc:
                alloc64[i, MEM] = memory_to_bytes(alloc["memory"])
            for j, name in enumerate(res_vocab[2:], start=2):
                if name in alloc:
                    alloc64[i, j] = memory_to_bytes(alloc[name])
    # Bound-pod usage, summed exactly in int64 bytes before the KiB floor.
    # ``res_memo`` (id(pod) -> (pod, PodResources), object-identity keyed
    # with the reference held so an id can never alias) amortizes the
    # request summation across cycles: bound pods dominate the cluster and
    # their objects only change on watch events.
    # Batched accumulation (round 5): per-pod scalar += ran ~8 µs/pod over
    # 200k+ bound pods per flagship e2e cycle; gather (node, res) pairs then
    # scatter-add whole columns in exact int64.
    idxs: list[int] = []
    reslist = []
    for pod in snapshot.pods:
        if pod.spec is not None and pod.spec.node_name is not None:
            i = node_index.get(pod.spec.node_name)
            if i is None:
                continue  # bound to an unknown node; consumes nothing we track
            if res_memo is not None:
                hit = res_memo.get(id(pod))
                if hit is not None and hit[0] is pod:
                    res = hit[1]
                else:
                    res = total_pod_resources(pod)
                    res_memo[id(pod)] = (pod, res)
            else:
                res = total_pod_resources(pod)
            idxs.append(i)
            reslist.append(res)
    if idxs:
        idx_arr = np.asarray(idxs, dtype=np.int64)
        m = len(idxs)
        np.add.at(used64[:, CPU], idx_arr, np.fromiter((r.cpu for r in reslist), np.int64, m))
        np.add.at(used64[:, MEM], idx_arr, np.fromiter((r.memory for r in reslist), np.int64, m))
        if len(res_vocab) > 2:
            ext_col = {name: j for j, name in enumerate(res_vocab[2:], start=2)}
            for i, res in zip(idxs, reslist):
                if res.extended:
                    for name, v in res.extended.items():
                        j = ext_col.get(name)
                        if j is not None and v:
                            used64[i, j] += v
    return alloc64, used64, node_index


# shape: (alloc64: [N, R] i64, req64: [P, R] i64) -> obj
def _fit_scales(alloc64: np.ndarray, req64: np.ndarray) -> tuple[int, ...]:
    """Per-column divisors (see PackedCluster.res_scales): columns 0-1 are
    fixed (millis, KiB); each extended column takes the smallest
    power-of-1024 under which every allocatable AND request value fits
    int32 — computed jointly over both sides so scaled comparisons are
    consistent and never saturate."""
    r = alloc64.shape[1]
    scales = [1, 1024]
    for j in range(2, r):
        m = 0
        if alloc64.shape[0]:
            m = max(m, int(np.abs(alloc64[:, j]).max()))
        if req64.shape[0]:
            m = max(m, int(np.abs(req64[:, j]).max()))
        scale = 1
        # Ceiled quotient — the same rounding _req_i32 applies — so a
        # request of exactly INT32_MAX*scale + r can never clamp into a
        # false fit.
        while -(-m // scale) > INT32_MAX:
            scale *= 1024
        scales.append(scale)
    return tuple(scales)


# shape: (req64: [P, R] i64, res_scales: obj) -> [P, R] i32
def _req_i32(req64: np.ndarray, res_scales: tuple[int, ...]) -> np.ndarray:
    """Requests CEIL under the column divisors (conservative dual of the
    capacity floor)."""
    sc = np.asarray(res_scales, dtype=np.int64)[None, :]
    return _clamp_i32(-(np.floor_divide(-req64, sc)))


# shape: (alloc64: [N, R] i64, used64: [N, R] i64, res_scales: obj) -> [N, R] i32
def _avail_i32(alloc64: np.ndarray, used64: np.ndarray, res_scales: tuple[int, ...] = (1, 1024)) -> np.ndarray:
    avail64 = alloc64 - used64
    # Floor capacities under the column divisors (conservative; a clamped
    # availability only ever UNDERestimates, which is safe).
    return _clamp_i32(np.floor_divide(avail64, np.asarray(res_scales, dtype=np.int64)[None, :]))


# shape: (snapshot: obj, pod_block: int, node_block: int, label_block: int,
#   vocab: dict, taint_vocab: dict, aff_vocab: dict, soft_taint_vocab: dict,
#   pref_vocab: dict, res_memo: dict) -> obj
# bucket: n_pad p_pad l_pad t_pad a_pad ts_pad a2_pad
def pack_snapshot(
    snapshot: ClusterSnapshot,
    pod_block: int = 128,
    node_block: int = 128,
    label_block: int = 8,
    vocab: dict[tuple[str, str], int] | None = None,
    taint_vocab: dict[tuple[str, str, str], int] | None = None,
    aff_vocab: dict[tuple, int] | None = None,
    soft_taint_vocab: dict[tuple[str, str, str], int] | None = None,
    pref_vocab: dict[tuple, int] | None = None,
    res_memo: dict | None = None,
) -> PackedCluster:
    """Pack a snapshot into static-shape tensors.

    ``vocab`` may be supplied (e.g. reused across cycles by the reflector) as
    long as it covers every selector pair among the pending pods; otherwise
    it is built fresh.
    """
    pending = snapshot.pending_pods()
    nodes = list(snapshot.nodes)
    if vocab is None:
        vocab = build_selector_vocab(pending)

    n_real, p_real = len(nodes), len(pending)
    n_pad = round_up(n_real, node_block)
    p_pad = round_up(p_real, pod_block)
    l_pad = round_up(len(vocab), label_block)

    if taint_vocab is None:
        taint_vocab = build_taint_vocab(nodes)
    t_pad = round_up(len(taint_vocab), label_block)
    if aff_vocab is None:
        aff_vocab = build_affinity_vocab(pending)
    a_pad = round_up(len(aff_vocab), label_block)
    if soft_taint_vocab is None:
        soft_taint_vocab = build_soft_taint_vocab(nodes)
    ts_pad = round_up(len(soft_taint_vocab), label_block)
    if pref_vocab is None:
        pref_vocab = build_pref_vocab(pending)
    a2_pad = round_up(len(pref_vocab), label_block)

    res_vocab = resource_vocab(snapshot, res_memo)
    alloc64, used64, _ = _alloc_and_used64(snapshot, n_pad, res_memo, res_vocab)
    node_labels = np.zeros((n_pad, l_pad), dtype=np.float32)
    node_taints = np.zeros((n_pad, t_pad), dtype=np.float32)
    node_taints_soft = np.zeros((n_pad, ts_pad), dtype=np.float32)
    node_aff = _pack_node_affinity(nodes, aff_vocab, n_pad, a_pad)
    node_pref = _pack_node_pref(nodes, pref_vocab, n_pad, a2_pad)
    node_valid = np.zeros((n_pad,), dtype=bool)
    from ..core.predicates import HARD_TAINT_EFFECTS

    for i, node in enumerate(nodes):
        node_valid[i] = not (node.spec is not None and node.spec.unschedulable)
        labels = node.metadata.labels
        if labels:
            for kv in labels.items():
                j = vocab.get(kv)
                if j is not None:
                    node_labels[i, j] = 1.0
        if node.spec is not None and node.spec.taints:
            for t in node.spec.taints:
                if t.effect in HARD_TAINT_EFFECTS:
                    j = taint_vocab.get((t.key, t.value, t.effect))
                    if j is None:
                        raise PackingError(f"taint {(t.key, t.value, t.effect)} missing from supplied taint_vocab")
                    node_taints[i, j] = 1.0
                elif t.effect == "PreferNoSchedule":
                    j = soft_taint_vocab.get((t.key, t.value, t.effect))
                    if j is None:
                        raise PackingError(f"taint {(t.key, t.value, t.effect)} missing from supplied soft_taint_vocab")
                    node_taints_soft[i, j] = 1.0

    pod_tensors = _pack_pods(pending, vocab, p_pad, l_pad, res_vocab, res_memo)
    pod_req64 = pod_tensors.pop("pod_req64")
    res_scales = _fit_scales(alloc64, pod_req64)
    pod_tensors["pod_req"] = _req_i32(pod_req64, res_scales)
    node_alloc = _clamp_i32(np.floor_divide(alloc64, np.asarray(res_scales, dtype=np.int64)[None, :]))
    node_avail = _avail_i32(alloc64, used64, res_scales)
    pod_ntol = _pack_ntol(pending, taint_vocab, p_pad, t_pad)
    pod_aff, pod_has_aff = _pack_affinity(pending, aff_vocab, p_pad, a_pad)
    pod_ntol_soft = _pack_ntol(pending, soft_taint_vocab, p_pad, ts_pad)
    pod_pref_w = _pack_pod_pref(pending, pref_vocab, p_pad, a2_pad)

    return PackedCluster(
        node_alloc=node_alloc,
        node_avail=node_avail,
        node_labels=node_labels,
        node_taints=node_taints,
        node_aff=node_aff,
        node_valid=node_valid,
        node_names=tuple(n.name for n in nodes),
        vocab=dict(vocab),
        taint_vocab=dict(taint_vocab),
        aff_vocab=dict(aff_vocab),
        soft_taint_vocab=dict(soft_taint_vocab),
        pref_vocab=dict(pref_vocab),
        res_vocab=res_vocab,
        res_scales=res_scales,
        pod_ntol=pod_ntol,
        pod_aff=pod_aff,
        pod_has_aff=pod_has_aff,
        node_taints_soft=node_taints_soft,
        pod_ntol_soft=pod_ntol_soft,
        node_pref=node_pref,
        pod_pref_w=pod_pref_w,
        **pod_tensors,
    )


# shape: (pending: obj, vocab: dict, p_pad: int, l_pad: int, res_vocab: obj, res_memo: dict) -> dict
def _pack_pods(
    pending: list[Pod], vocab: dict, p_pad: int, l_pad: int,
    res_vocab: tuple[str, ...] = ("cpu", "memory"), res_memo: dict | None = None,
) -> dict:
    """Pod-side tensors (the part that changes every cycle as pods bind).
    ``res_memo`` is the shared identity-keyed request-sum memo (same contract
    as resource_vocab's) — without it each cycle re-sums every pod's
    container requests a second time (measured ~1.3 s of a flagship e2e
    cycle's pack)."""
    from ..api.objects import full_name

    pod_req64 = np.zeros((p_pad, len(res_vocab)), dtype=np.int64)
    pod_sel = np.zeros((p_pad, l_pad), dtype=np.float32)
    pod_sel_count = np.zeros((p_pad,), dtype=np.float32)
    pod_prio = np.zeros((p_pad,), dtype=np.int32)
    pod_valid = np.zeros((p_pad,), dtype=bool)

    # Batched row fill (round 5): per-pod scalar numpy stores ran ~20 µs/pod
    # — ~2 s of a flagship e2e cycle's pack for 100k fresh rows.  Gather the
    # python-side values first, then store whole columns; COO-scatter the
    # sparse selector bitmap.  Raw bytes in MEM; caller ceils by res_scales.
    n = len(pending)
    reslist = []
    for pod in pending:
        if res_memo is not None:
            hit = res_memo.get(id(pod))
            if hit is not None and hit[0] is pod:
                reslist.append(hit[1])
                continue
            res = total_pod_resources(pod)
            res_memo[id(pod)] = (pod, res)
            reslist.append(res)
        else:
            reslist.append(total_pod_resources(pod))
    if n:
        pod_req64[:n, CPU] = np.fromiter((r.cpu for r in reslist), np.int64, n)
        pod_req64[:n, MEM] = np.fromiter((r.memory for r in reslist), np.int64, n)
        pod_prio[:n] = np.fromiter(
            ((p.spec.priority if p.spec is not None else 0) for p in pending), np.int32, n
        )
        pod_valid[:n] = True
    if len(res_vocab) > 2:
        ext_col = {name: j for j, name in enumerate(res_vocab[2:], start=2)}
        for i, res in enumerate(reslist):
            if res.extended:
                for name, v in res.extended.items():
                    j = ext_col.get(name)
                    if j is not None and v:
                        pod_req64[i, j] = v
    pod_names = [full_name(p) for p in pending]
    sel_i: list[int] = []
    sel_j: list[int] = []
    for i, pod in enumerate(pending):
        spec = pod.spec
        if spec is not None and spec.node_selector:
            for kv in spec.node_selector.items():
                j = vocab.get(kv)
                if j is None:
                    raise PackingError(f"selector pair {kv} missing from supplied vocab")
                sel_i.append(i)
                sel_j.append(j)
            pod_sel_count[i] = len(spec.node_selector)
    if sel_i:
        pod_sel[sel_i, sel_j] = 1.0

    return dict(
        pod_req64=pod_req64,
        pod_sel=pod_sel,
        pod_sel_count=pod_sel_count,
        pod_prio=pod_prio,
        pod_valid=pod_valid,
        pod_names=tuple(pod_names),
        pod_objs=tuple(pending),
    )


# shape: (alloc64: [N, R] i64, res_scales: obj) -> none
def _check_alloc_within_scales(alloc64: np.ndarray, res_scales: tuple[int, ...]) -> None:
    """Raise when an EXTENDED allocatable column outgrows the frozen
    per-column divisor (round-3 advisor): a full pack would re-derive the
    divisor and stay exact, so silently saturating capacity at INT32_MAX —
    conservative but imprecise — must instead force that full pack.
    Extended columns only, mirroring the request-side guard: cpu/memory
    scales are fixed by contract and keep the documented clamp behavior."""
    sc = np.asarray(res_scales, dtype=np.int64)
    if sc.shape[0] > 2 and alloc64.shape[1] > 2:
        # Capacity floors under the divisor (_avail_i32's rounding).
        if (np.floor_divide(alloc64[:, 2:], sc[None, 2:]) > INT32_MAX).any():
            raise ValueError("resource scales outgrown by node allocatable; run a full pack_snapshot instead")


# shape: (packed: obj, snapshot: obj) -> obj
def repack_avail(packed: PackedCluster, snapshot: ClusterSnapshot) -> PackedCluster:
    """Cheap refresh of ``node_avail`` from a new snapshot over the *same*
    node set — the incremental-update path the reflector uses between full
    packs (device-resident node tensor, SURVEY.md §3.3).  Only capacity
    bookkeeping is recomputed; pod tensors and label bitmaps are untouched.
    """
    fresh_names = tuple(n.name for n in snapshot.nodes)
    if fresh_names != packed.node_names:
        raise ValueError("repack_avail requires an identical node set/order; run a full pack_snapshot instead")
    if resource_vocab(snapshot) != packed.res_vocab:
        raise ValueError("resource vocabulary changed; run a full pack_snapshot instead")
    alloc64, used64, _ = _alloc_and_used64(snapshot, packed.padded_nodes, res_vocab=packed.res_vocab)
    _check_alloc_within_scales(alloc64, packed.res_scales)
    return replace(packed, node_avail=_avail_i32(alloc64, used64, packed.res_scales))


# shape: (arr: [N, L] f32, total: int, label_block: int) -> [N, ?] f32
# bucket: w_pad
def _grow_columns(arr: np.ndarray, total: int, label_block: int) -> np.ndarray:
    """Copy ``arr`` with its column count grown to cover ``total`` entries
    (padded to the block multiple).  Always copies — cached tensors may be
    aliased by checkpoints or in-flight device transfers."""
    width = arr.shape[1]
    if total > width:
        w_pad = round_up(total, label_block)
        return np.pad(arr, ((0, 0), (0, w_pad - width)))
    return arr.copy()


# shape: (packed: obj, snapshot: obj, label_block: int) -> obj
def extend_node_vocabs(packed: PackedCluster, snapshot: ClusterSnapshot, label_block: int = 8) -> PackedCluster:
    """Grow the cached node-side tensors to cover vocabulary entries newly
    introduced by the pending pods — the in-place alternative to a full
    repack when the node set is stable but a new deployment brings a
    selector pair, affinity term, or preferred term the cache has never
    seen (VERDICT r2 item 8).

    Only the *new* columns are evaluated against the nodes — O(N · new)
    host work instead of the full pack's O(N · (L + A + A2)).  Taint vocabs
    are node-driven: a taint change bumps the node's resourceVersion, which
    changes the node-set signature and forces a full pack anyway, so they
    are not extended here.  Column order of existing entries is preserved,
    so score/feasibility semantics are bit-identical to a fresh pack.
    """
    fresh_names = tuple(n.name for n in snapshot.nodes)
    if fresh_names != packed.node_names:
        raise ValueError("extend_node_vocabs requires an identical node set/order; run a full pack_snapshot instead")
    pending = snapshot.pending_pods()
    nodes = list(snapshot.nodes)

    # One pass over the pending pods: collect entries the cache lacks (new_*)
    # and the distinct entries actually in use (live_*, for the compaction
    # valve below).  Membership goes against the cached dicts directly — the
    # steady state allocates only these small live/new sets, never copies of
    # the (possibly large) vocabularies.
    new_sel: dict[tuple[str, str], None] = {}
    new_aff: dict[tuple, None] = {}
    new_pref: dict[tuple, None] = {}
    live_sel: set = set()
    live_aff: set = set()
    live_pref: set = set()
    for p in pending:
        if p.spec is None:
            continue
        if p.spec.node_selector:
            for kv in p.spec.node_selector.items():
                live_sel.add(kv)
                if kv not in packed.vocab:
                    new_sel[kv] = None
        for term in p.spec.node_affinity or []:
            k = term.key()
            live_aff.add(k)
            if k not in packed.aff_vocab:
                new_aff[k] = None
        for t in p.spec.preferred_node_affinity or []:
            k = t.term.key()
            live_pref.add(k)
            if k not in packed.pref_vocab:
                new_pref[k] = None
    if not (new_sel or new_aff or new_pref):
        return packed

    # Compaction valve: growth is monotone (dead deployments leave columns
    # behind), so once dead columns dominate the live entries, refuse —
    # the caller's full-pack fallback rebuilds minimal vocabularies from the
    # current pending set, shrinking the tensors.
    for vocab, live, new in (
        (packed.vocab, live_sel, new_sel),
        (packed.aff_vocab, live_aff, new_aff),
        (packed.pref_vocab, live_pref, new_pref),
    ):
        if len(vocab) + len(new) > max(16, 2 * len(live)):
            raise ValueError(
                f"vocabulary bloat: {len(vocab)} cached + {len(new)} new entries vs {len(live)} live; "
                "full repack compacts the dead columns"
            )

    out = {}
    if new_sel:
        vocab = dict(packed.vocab)
        node_labels = _grow_columns(packed.node_labels, len(vocab) + len(new_sel), label_block)
        for kv in new_sel:
            vocab[kv] = len(vocab)
        for ni, node in enumerate(nodes):
            labels = node.metadata.labels
            if labels:
                for k, v in new_sel:
                    if labels.get(k) == v:
                        node_labels[ni, vocab[(k, v)]] = 1.0
        out["vocab"] = vocab
        out["node_labels"] = node_labels
    if new_aff or new_pref:
        from ..core.predicates import node_selector_term_matches

        for keys, vocab_name, tensor_name in (
            (new_aff, "aff_vocab", "node_aff"),
            (new_pref, "pref_vocab", "node_pref"),
        ):
            if not keys:
                continue
            vocab = dict(getattr(packed, vocab_name))
            tensor = _grow_columns(getattr(packed, tensor_name), len(vocab) + len(keys), label_block)
            terms = []
            for key in keys:
                vocab[key] = len(vocab)
                terms.append((vocab[key], _term_from_key(key)))
            for ni, node in enumerate(nodes):
                labels = node.metadata.labels
                for j, term in terms:
                    if node_selector_term_matches(term, labels):
                        tensor[ni, j] = 1.0
            out[vocab_name] = vocab
            out[tensor_name] = tensor
    return replace(packed, **out)


# shape: (packed: obj, snapshot: obj, pod_block: int, res_memo: dict,
#   alloc_used64: obj) -> obj
# bucket: p_pad l_w t_w a_w ts_w a2_w
def repack_incremental(
    packed: PackedCluster,
    snapshot: ClusterSnapshot,
    pod_block: int = 128,
    res_memo: dict | None = None,
    alloc_used64: tuple[np.ndarray, np.ndarray] | None = None,
) -> PackedCluster:
    """Between-cycles repack: reuse the node-side tensors (labels, alloc,
    vocab — stable while the node set is stable) and rebuild only what a
    cycle changes — the pending-pod tensors and remaining capacity.

    The pod side is O(delta): a pending pod whose OBJECT is unchanged since
    the cached pack (same identity — the API layer replaces objects on every
    modification) has its rows gathered from the cached tensors with one
    vectorized scatter; only new/changed pods run the Python packing body.
    Reused rows are automatically correct under grown vocab columns
    (extend_node_vocabs preserves existing column indices, and an unchanged
    pod's entries all predate the growth, so its new columns are zero).

    Caller guarantees: identical node set/order (validated) and that
    ``packed.vocab`` covers every pending selector pair (KeyError otherwise).
    ``alloc_used64`` — the delta engine's carried exact-int64 capacity pair
    (tpu_scheduler/delta): when given, the O(bound-pods) usage sweep AND the
    O(pods) resource-vocabulary scan are skipped; the caller asserts both
    (the engine escalates to a full pack on any vocabulary drift).
    """
    from ..api.objects import full_name

    fresh_nodes = tuple(n.name for n in snapshot.nodes)
    if fresh_nodes != packed.node_names:
        raise ValueError("repack_incremental requires an identical node set/order; run a full pack_snapshot instead")
    if alloc_used64 is None:
        if resource_vocab(snapshot, res_memo) != packed.res_vocab:
            # A new extended-resource name widens every [·,R] tensor — that
            # is a full-pack event (the controller catches ValueError and
            # degrades).
            raise ValueError("resource vocabulary changed; run a full pack_snapshot instead")
        alloc64, used64, _ = _alloc_and_used64(snapshot, packed.padded_nodes, res_memo, packed.res_vocab)
    else:
        alloc64, used64 = alloc_used64
        if alloc64.shape != (packed.padded_nodes, len(packed.res_vocab)) or used64.shape != alloc64.shape:
            raise ValueError("carried capacity pair does not match the packed node axis; run a full pack_snapshot instead")
    _check_alloc_within_scales(alloc64, packed.res_scales)
    pending = snapshot.pending_pods()
    p_pad = max(packed.padded_pods, round_up(len(pending), pod_block))
    # Pod tensor widths come from the NODE side: extend_node_vocabs may have
    # grown label columns since the cached pod tensors were built.
    l_w = packed.node_labels.shape[1]
    t_w = packed.node_taints.shape[1]
    a_w = packed.node_aff.shape[1]
    ts_w = packed.node_taints_soft.shape[1]
    a2_w = packed.node_pref.shape[1]

    prev_row = {name: j for j, name in enumerate(packed.pod_names)} if packed.pod_objs else {}
    reuse_src: list[int] = []
    reuse_dst: list[int] = []
    fresh_idx: list[int] = []
    names: list[str] = []
    for i, pod in enumerate(pending):
        nm = full_name(pod)
        names.append(nm)
        j = prev_row.get(nm)
        if j is not None and packed.pod_objs[j] is pod:
            reuse_src.append(j)
            reuse_dst.append(i)
        else:
            fresh_idx.append(i)

    pod_req = np.zeros((p_pad, len(packed.res_vocab)), dtype=np.int32)
    pod_sel = np.zeros((p_pad, l_w), dtype=np.float32)
    pod_sel_count = np.zeros((p_pad,), dtype=np.float32)
    pod_prio = np.zeros((p_pad,), dtype=np.int32)
    pod_valid = np.zeros((p_pad,), dtype=bool)
    pod_ntol = np.zeros((p_pad, t_w), dtype=np.float32)
    pod_aff = np.zeros((p_pad, a_w), dtype=np.float32)
    pod_has_aff = np.zeros((p_pad,), dtype=np.float32)
    pod_ntol_soft = np.zeros((p_pad, ts_w), dtype=np.float32)
    pod_pref_w = np.zeros((p_pad, a2_w), dtype=np.float32)
    pod_valid[: len(pending)] = True

    if reuse_src:
        src = np.asarray(reuse_src, dtype=np.intp)
        dst = np.asarray(reuse_dst, dtype=np.intp)
        pod_req[dst] = packed.pod_req[src]
        pod_sel[dst, : packed.pod_sel.shape[1]] = packed.pod_sel[src]
        pod_sel_count[dst] = packed.pod_sel_count[src]
        pod_prio[dst] = packed.pod_prio[src]
        pod_ntol[dst, : packed.pod_ntol.shape[1]] = packed.pod_ntol[src]
        pod_aff[dst, : packed.pod_aff.shape[1]] = packed.pod_aff[src]
        pod_has_aff[dst] = packed.pod_has_aff[src]
        pod_ntol_soft[dst, : packed.pod_ntol_soft.shape[1]] = packed.pod_ntol_soft[src]
        pod_pref_w[dst, : packed.pod_pref_w.shape[1]] = packed.pod_pref_w[src]

    if fresh_idx:
        fp = [pending[i] for i in fresh_idx]
        fi = np.asarray(fresh_idx, dtype=np.intp)
        n_f = len(fp)
        sub = _pack_pods(fp, packed.vocab, n_f, l_w, packed.res_vocab, res_memo)
        sc = np.asarray(packed.res_scales, dtype=np.int64)
        # Extended columns only (a full pack re-derives those divisors and
        # cures the raise); cpu/memory scales are FIXED, so an oversized
        # value there keeps the documented clamp behavior (module header)
        # instead of degrading every future cycle to a full pack.  Ceiled
        # quotient to match _req_i32's rounding exactly.
        if sc.shape[0] > 2 and (-(np.floor_divide(-sub["pod_req64"][:, 2:], sc[None, 2:])) > INT32_MAX).any():
            raise ValueError("resource scales outgrown; run a full pack_snapshot instead")
        pod_req[fi] = _req_i32(sub["pod_req64"], packed.res_scales)
        pod_sel[fi] = sub["pod_sel"]
        pod_sel_count[fi] = sub["pod_sel_count"]
        pod_prio[fi] = sub["pod_prio"]
        pod_ntol[fi] = _pack_ntol(fp, packed.taint_vocab, n_f, t_w)
        f_aff, f_has = _pack_affinity(fp, packed.aff_vocab, n_f, a_w)
        pod_aff[fi] = f_aff
        pod_has_aff[fi] = f_has
        pod_ntol_soft[fi] = _pack_ntol(fp, packed.soft_taint_vocab, n_f, ts_w)
        pod_pref_w[fi] = _pack_pod_pref(fp, packed.pref_vocab, n_f, a2_w)

    return replace(
        packed,
        node_avail=_avail_i32(alloc64, used64, packed.res_scales),
        pod_req=pod_req,
        pod_sel=pod_sel,
        pod_sel_count=pod_sel_count,
        pod_prio=pod_prio,
        pod_valid=pod_valid,
        pod_names=tuple(names),
        pod_objs=tuple(pending),
        pod_ntol=pod_ntol,
        pod_aff=pod_aff,
        pod_has_aff=pod_has_aff,
        pod_ntol_soft=pod_ntol_soft,
        pod_pref_w=pod_pref_w,
    )
