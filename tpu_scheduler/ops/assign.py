"""Conflict-free batched assignment — the jitted scheduling cycle.

This is the TPU replacement for the reference's sequential reconcile loop
(``src/main.rs:51-71`` + the controller dispatch at ``main.rs:141-149``):
instead of one pod at a time × ≤5 random candidates × one RPC each, all
pending pods are assigned in a small number of *auction rounds*, entirely
on-device, with capacity commits that make oversubscription impossible —
closing the reference's by-design TOCTOU race (SURVEY.md §5: two concurrent
reconciles can both fit the same gap).

Round structure (all under ``lax.while_loop``; shapes static):
  1. choose:  blockwise over pods — feasibility mask + scores vs the
     *current* remaining capacity; per-pod masked argmax → choice[P].
  2. accept:  pods are pre-permuted into (priority desc, FIFO) order; a
     stable sort by chosen node groups each node's claimants in priority
     order; a segmented saturating prefix-sum of their requests accepts the
     longest prefix that fits remaining capacity.
  3. commit:  accepted requests scatter-subtract from remaining capacity;
     accepted pods leave the pool; pods with no feasible node drop out
     (capacity only shrinks within a cycle, so they can never become
     feasible again this cycle → they requeue, reference ``main.rs:122-125``).
  4. compact: a stable sort on ``~active`` packs the still-active pods to
     the front, so the next round's choose only touches
     ``ceil(n_active / block)`` blocks instead of all of them.  Measured on
     the north-star shape (100k×10k), active counts decay 100k → 76k → 53k
     → … → 8 over 32 rounds, so compaction cuts choose work ~4-5×.  The
     stable sort preserves relative order among active pods (= priority
     order), and each pod's original rank rides along for the score-jitter
     hash, so results are bit-identical to the uncompacted algorithm and to
     the native backend.

Every round with any claimant accepts at least the highest-priority claimant
of each contended node, so the loop strictly progresses; ``max_rounds`` is a
safety cap only.

Overflow note: within-segment demand prefix-sums can exceed int32 (100k pods
× multi-GiB requests in KiB), so the scan uses *saturating* int32 addition —
associative for non-negatives, yielding exactly ``min(true_sum, INT32_MAX)``,
which the native NumPy backend mirrors with exact int64 + clamp.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from .masks import feasibility_block
from .pack import INT32_MAX
from .score import score_block

__all__ = ["assign_cycle", "INT32_MAX"]


def _sat_add(a, b):
    """Saturating int32 add for non-negative operands: min(a+b, INT32_MAX)."""
    s = a + b
    return jnp.where(s < 0, INT32_MAX, s)


def _seg_scan_op(x, y):
    """Segmented saturating-sum operator for lax.associative_scan.

    Elements are (segment_start_flag [.,1] bool, value [.,2] int32).
    """
    fx, vx = x
    fy, vy = y
    return fx | fy, jnp.where(fy, vy, _sat_add(vx, vy))


def _choose_block(
    avail, node_alloc, node_labels, node_taints, node_valid, weights, breq, bsel, bselc, bntol, bact, bidx, pallas_pack=None
):
    """[B] best feasible node (+feasibility flag) for one block of pods.

    With ``pallas_pack`` (node_info, labels_t, taints_t, interpret) the fused
    Pallas kernel runs (ops/pallas_choose.py — bit-identical results, one
    VMEM pass); otherwise the xp-generic jnp expression tree.
    """
    if pallas_pack is not None:
        from .pallas_choose import choose_block_pallas

        node_info, labels_t, taints_t, interpret = pallas_pack
        return choose_block_pallas(
            breq, bsel, bselc, bntol, bact, bidx, node_info, labels_t, taints_t, weights, interpret=interpret
        )
    node_idx = jnp.arange(avail.shape[0], dtype=jnp.uint32)
    m = feasibility_block(jnp, breq, bsel, bselc, bact, avail, node_labels, node_valid, bntol, node_taints)
    sc = score_block(jnp, breq, node_alloc, avail, weights, bidx, node_idx)
    sc = jnp.where(m, sc, -jnp.inf)
    return jnp.argmax(sc, axis=1).astype(jnp.int32), m.any(axis=1)


def _choose(
    avail, active, req, sel, selc, ntol, ranks, n_active, node_alloc, node_labels, node_taints, node_valid, weights,
    block, use_pallas=False, pallas_interpret=False,
):
    """Per-pod best feasible node vs current capacity, blockwise over pods.

    Never materialises the full [P,N] score matrix: peak live memory is one
    [block, N] tile (HBM-bandwidth friendly; the pipeline analogue of
    SURVEY.md §2b PP).  Pods are compacted (active-first), so only the
    first ``ceil(n_active / block)`` blocks are evaluated — a dynamic bound
    on a ``lax.while_loop`` over blocks.  ``ranks`` carries each pod's
    original priority rank into the score-jitter hash.
    """
    p = req.shape[0]

    pallas_pack = None
    if use_pallas:
        from .pallas_choose import build_node_info

        # Rebuilt each round (avail changes); O(N) next to the O(B·N) choose.
        pallas_pack = (build_node_info(avail, node_alloc, node_valid), node_labels.T, node_taints.T, pallas_interpret)

    if block >= p:
        return _choose_block(
            avail, node_alloc, node_labels, node_taints, node_valid, weights, req, sel, selc, ntol, active, ranks,
            pallas_pack,
        )

    nb_occupied = (n_active + block - 1) // block  # traced; caller pads p % block == 0

    def cond(s):
        i = s[0]
        return i < nb_occupied

    def body(s):
        i, choice, has = s
        lo = i * block
        bc, bh = _choose_block(
            avail,
            node_alloc,
            node_labels,
            node_taints,
            node_valid,
            weights,
            lax.dynamic_slice_in_dim(req, lo, block),
            lax.dynamic_slice_in_dim(sel, lo, block),
            lax.dynamic_slice_in_dim(selc, lo, block),
            lax.dynamic_slice_in_dim(ntol, lo, block),
            lax.dynamic_slice_in_dim(active, lo, block),
            lax.dynamic_slice_in_dim(ranks, lo, block),
            pallas_pack,
        )
        choice = lax.dynamic_update_slice_in_dim(choice, bc, lo, axis=0)
        has = lax.dynamic_update_slice_in_dim(has, bh, lo, axis=0)
        return i + 1, choice, has

    _, choice, has = lax.while_loop(cond, body, (jnp.int32(0), jnp.zeros((p,), jnp.int32), jnp.zeros((p,), bool)))
    return choice, has


@partial(jax.jit, static_argnames=("max_rounds", "block", "use_pallas", "pallas_interpret"))
def assign_cycle(
    node_alloc,
    node_avail,
    node_labels,
    node_taints,
    node_valid,
    pod_req,
    pod_sel,
    pod_sel_count,
    pod_ntol,
    pod_prio,
    pod_valid,
    weights,
    max_rounds: int = 32,
    block: int = 4096,
    use_pallas: bool = False,
    pallas_interpret: bool = False,
):
    """Assign all pending pods to nodes in one on-device cycle.

    Returns (assigned [P] int32 — node index or −1, rounds int32,
    remaining node_avail [N,2] int32).
    """
    p_out = pod_req.shape[0]
    n = node_avail.shape[0]

    # Priority order (priority desc, FIFO index asc); stable sort keeps FIFO.
    # The permutation happens BEFORE any block padding: rank positions feed
    # the score-jitter hash and must equal the native backend's (which never
    # pads) for binding parity — padding first would shift ranks whenever a
    # pod has negative priority.
    perm = jnp.argsort(-pod_prio, stable=True)
    req = pod_req[perm]
    sel = pod_sel[perm]
    selc = pod_sel_count[perm]
    ntol = pod_ntol[perm]
    valid = pod_valid[perm]

    # Pad the pod axis to a block multiple so the blockwise choose path is
    # always exact — otherwise a remainder would silently materialise the
    # full [P,N] score matrix and blow HBM at target scale (100k × 10k).
    # Padding rows sit at ranks ≥ p_out (inactive), leaving real ranks intact.
    p = p_out
    if block < p and p % block != 0:
        extra = block - p % block
        req = jnp.pad(req, ((0, extra), (0, 0)))
        sel = jnp.pad(sel, ((0, extra), (0, 0)))
        selc = jnp.pad(selc, ((0, extra),))
        ntol = jnp.pad(ntol, ((0, extra), (0, 0)))
        valid = jnp.pad(valid, ((0, extra),))
        p = p + extra

    # Compaction state: pod arrays are kept active-first; ``ranks`` maps each
    # slot back to its original priority rank (for the jitter hash and the
    # final unpermute).  The initial order (rank order, actives scattered) is
    # handled by compacting once before the loop via n_active = p.
    ranks0 = jnp.arange(p, dtype=jnp.uint32)

    def compact(req, sel, selc, ntol, ranks, assigned, active):
        order = jnp.argsort(~active, stable=True)
        return req[order], sel[order], selc[order], ntol[order], ranks[order], assigned[order], active[order]

    req, sel, selc, ntol, ranks, assigned0, active0 = compact(
        req, sel, selc, ntol, ranks0, jnp.full((p,), -1, jnp.int32), valid
    )

    def cond(state):
        _, _, _, _, _, _, _, _, n_active, rounds = state
        return (rounds < max_rounds) & (n_active > 0)

    def body(state):
        avail, req, sel, selc, ntol, ranks, assigned, active, n_active, rounds = state
        choice, has = _choose(
            avail, active, req, sel, selc, ntol, ranks, n_active, node_alloc, node_labels, node_taints, node_valid,
            weights, block, use_pallas, pallas_interpret,
        )
        cand = active & has
        ch = jnp.where(cand, choice, n).astype(jnp.int32)  # sentinel segment n for non-claimants
        claim = jnp.where(cand[:, None], req, 0)

        # Group claimants per node; the stable sort preserves the compacted
        # (= priority) order among each node's claimants.
        order = jnp.argsort(ch, stable=True)
        ch_s = ch[order]
        claim_s = claim[order]
        is_start = jnp.concatenate([jnp.ones((1,), bool), ch_s[1:] != ch_s[:-1]])[:, None]
        _, within = lax.associative_scan(_seg_scan_op, (is_start, claim_s))

        avail_ext = jnp.concatenate([avail, jnp.zeros((1, 2), avail.dtype)], axis=0)
        fits_prefix = (within <= avail_ext[ch_s]).all(-1)
        acc_s = fits_prefix & (ch_s < n)
        accepted = jnp.zeros((p,), bool).at[order].set(acc_s)

        assigned = jnp.where(accepted, choice, assigned)
        dec = jnp.zeros((n + 1, 2), jnp.int32).at[ch].add(jnp.where(accepted[:, None], req, 0))
        avail = avail - dec[:n]
        active = cand & ~accepted
        req, sel, selc, ntol, ranks, assigned, active = compact(req, sel, selc, ntol, ranks, assigned, active)
        return avail, req, sel, selc, ntol, ranks, assigned, active, active.sum(dtype=jnp.int32), rounds + 1

    state0 = (node_avail, req, sel, selc, ntol, ranks, assigned0, active0, active0.sum(dtype=jnp.int32), jnp.int32(0))
    avail, _, _, _, _, ranks, assigned, _, _, rounds = lax.while_loop(cond, body, state0)

    # Undo compaction (rank space), then the priority permutation (original
    # pod order), dropping block padding.
    assigned_rank = jnp.zeros((p,), jnp.int32).at[ranks].set(assigned)
    out = jnp.full((p_out,), -1, jnp.int32).at[perm].set(assigned_rank[:p_out])
    return out, rounds, avail
