"""Conflict-free batched assignment — the jitted scheduling cycle.

This is the TPU replacement for the reference's sequential reconcile loop
(``src/main.rs:51-71`` + the controller dispatch at ``main.rs:141-149``):
instead of one pod at a time × ≤5 random candidates × one RPC each, all
pending pods are assigned in a small number of *auction rounds*, entirely
on-device, with capacity commits that make oversubscription impossible —
closing the reference's by-design TOCTOU race (SURVEY.md §5: two concurrent
reconciles can both fit the same gap).

Round structure (all under ``lax.while_loop``; shapes static):
  1. choose:  blockwise over pods — feasibility mask + scores vs the
     *current* remaining capacity; per-pod masked argmax → choice[P].
  2. accept:  pods are pre-permuted into (priority desc, FIFO) order; a
     stable sort by chosen node groups each node's claimants in priority
     order; a segmented saturating prefix-sum of their requests accepts the
     longest prefix that fits remaining capacity.
  3. commit:  accepted requests scatter-subtract from remaining capacity;
     accepted pods leave the pool; pods with no feasible node drop out
     (capacity only shrinks within a cycle, so they can never become
     feasible again this cycle → they requeue, reference ``main.rs:122-125``).
  4. compact: a stable sort on ``~active`` packs the still-active pods to
     the front, so the next round's choose only touches
     ``ceil(n_active / block)`` blocks instead of all of them.  Measured on
     the north-star shape (100k×10k), active counts decay 100k → 76k → 53k
     → … → 8 over 32 rounds, so compaction cuts choose work ~4-5×.  The
     stable sort preserves relative order among active pods (= priority
     order), and each pod's original rank rides along for the score-jitter
     hash, so results are bit-identical to the uncompacted algorithm and to
     the native backend.

Pod- and node-side tensors travel as dicts (the PackedCluster
``device_arrays`` names, split by prefix), so adding a predicate tensor is a
one-key change: the permutation, padding, compaction, and block slicing are
generic over the pod dict.

Every round with any claimant accepts at least the highest-priority claimant
of each contended node, so the loop strictly progresses; ``max_rounds`` is a
safety cap only.

Overflow note: within-segment demand prefix-sums can exceed int32 (100k pods
× multi-GiB requests in KiB), so the scan uses *saturating* int32 addition —
associative for non-negatives, yielding exactly ``min(true_sum, INT32_MAX)``,
which the native NumPy backend mirrors with exact int64 + clamp.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from .masks import feasibility_block
from .pack import INT32_MAX, STALL_ROUNDS
from .score import score_block
from ..topology.locality import gang_state_update, gang_topology_term
from ..utils.tracing import span

__all__ = ["assign_cycle", "assign_cycle_epochs", "split_device_arrays", "INT32_MAX"]

# Pod-side keys the choose step consumes (sliced per block); the rest of the
# pod state (assigned, active bookkeeping) never enters the score math.
_CHOOSE_KEYS = (
    "pod_req",
    "pod_sel",
    "pod_sel_count",
    "pod_ntol",
    "pod_aff",
    "pod_has_aff",
    "pod_ntol_soft",
    "pod_pref_w",
    "active",
    "ranks",
)
# Constraint pod-side keys (present only when the cycle carries anti-affinity
# or topology-spread tensors, ops/constraints.py).
_CONSTRAINT_KEYS = (
    "pod_aa_carries",
    "pod_aa_matched",
    "pod_pa_declares",
    "pod_pa_matched",
    "pod_sp_declares",
    "pod_sp_matched",
    "pod_sps_declares",
    "pod_sps_matched",
    "pod_ppa_w",
    "pod_ppa_matched",
)


# shape: (arrays: dict) -> (dict, dict)
def split_device_arrays(arrays: dict) -> tuple[dict, dict]:
    """Split a PackedCluster.device_arrays() dict into (node_side, pod_side)."""
    nodes = {k: v for k, v in arrays.items() if k.startswith("node_")}
    pods = {k: v for k, v in arrays.items() if k.startswith("pod_")}
    return nodes, pods


# shape: (a: [S, C] i32, b: [S, C] i32) -> [S, C] i32
def _sat_add(a, b):
    """Saturating int32 add for non-negative operands: min(a+b, INT32_MAX)."""
    s = a + b
    return jnp.where(s < 0, INT32_MAX, s)


# shape: (x: any, y: any) -> any
def _seg_scan_op(x, y):
    """Segmented saturating-sum operator for lax.associative_scan.

    Elements are (segment_start_flag [.,1] bool, value [.,2] int32).
    """
    fx, vx = x
    fy, vy = y
    return fx | fy, jnp.where(fy, vy, _sat_add(vx, vy))


# shape: (avail: [N, R] i32, nodes: dict, weights: [W] f32, blk: dict,
#   pallas_pack: obj, round_masks: dict, salt: scalar any,
#   topo_t: [G, N] f32) -> ([B] i32, [B] bool)
def _choose_block(avail, nodes, weights, blk, pallas_pack=None, round_masks=None, salt=None, topo_t=None):
    """[B] best feasible node (+feasibility flag) for one block of pods.

    ``blk`` is the pod-side dict sliced to one block.  With ``pallas_pack``
    (node_info, labels_t, taints_t, interpret) the fused Pallas kernel runs
    (ops/pallas_choose.py — bit-identical results, one VMEM pass); otherwise
    the xp-generic jnp expression tree.  ``round_masks`` (constraint cycles
    only) adds the anti-affinity/spread blocked-node matmuls.
    """
    if pallas_pack is not None:
        from .pallas_choose import choose_block_pallas, constrained_kernel_pod_operands

        node_info, labels_t, taints_t, aff_t, pref_t, taints_soft_t, interpret, cons_node = pallas_pack
        cons_pod = cons_node_args = None
        if cons_node is not None:
            cons_node_args, pa_inactive = cons_node
            cons_pod = constrained_kernel_pod_operands(blk, pa_inactive)
        return choose_block_pallas(
            blk["pod_req"],
            blk["pod_sel"],
            blk["pod_sel_count"],
            blk["pod_ntol"],
            blk["pod_aff"],
            blk["pod_has_aff"],
            blk["pod_pref_w"],
            blk["pod_ntol_soft"],
            blk["active"],
            blk["ranks"],
            node_info,
            labels_t,
            taints_t,
            aff_t,
            pref_t,
            taints_soft_t,
            weights,
            salt=salt,
            cons_pod=cons_pod,
            cons_node=cons_node_args,
            interpret=interpret,
        )
    node_idx = jnp.arange(avail.shape[0], dtype=jnp.uint32)
    m = feasibility_block(
        jnp,
        blk["pod_req"],
        blk["pod_sel"],
        blk["pod_sel_count"],
        blk["active"],
        avail,
        nodes["node_labels"],
        nodes["node_valid"],
        blk["pod_ntol"],
        nodes["node_taints"],
        blk["pod_aff"],
        blk["pod_has_aff"],
        nodes["node_aff"],
    )
    if round_masks is not None:
        from .constraints import blocked_block

        m = m & ~blocked_block(jnp, blk, round_masks)
    soft_sp = round_masks is not None and "sp_penalty_node" in round_masks
    soft_pa = round_masks is not None and "ppa_cnt_node" in round_masks
    steer_sp = round_masks is not None and "sp_level_node" in round_masks
    sc = score_block(
        jnp,
        blk["pod_req"],
        nodes["node_alloc"],
        avail,
        weights,
        blk["ranks"],
        node_idx,
        pod_pref_w=blk["pod_pref_w"],
        node_pref=nodes["node_pref"],
        pod_ntol_soft=blk["pod_ntol_soft"],
        node_taints_soft=nodes["node_taints_soft"],
        pod_sps_declares=blk["pod_sps_declares"] if soft_sp else None,
        sp_penalty_node=round_masks["sp_penalty_node"] if soft_sp else None,
        pod_sp_declares=blk["pod_sp_declares"] if steer_sp else None,
        sp_level_node=round_masks["sp_level_node"] if steer_sp else None,
        pod_ppa_w=blk["pod_ppa_w"] if soft_pa else None,
        ppa_cnt_node=round_masks["ppa_cnt_node"] if soft_pa else None,
        salt=salt,
        pod_gang_id=blk["pod_gang_id"] if topo_t is not None else None,
        topo_gang_node=topo_t,
    )
    sc = jnp.where(m, sc, -jnp.inf)
    return jnp.argmax(sc, axis=1).astype(jnp.int32), m.any(axis=1)


# shape: (avail: [N, R] i32, ps: dict, n_active: scalar i32, nodes: dict,
#   weights: [W] f32, block: int, use_pallas: bool, pallas_interpret: bool,
#   round_masks: dict, salt: scalar any, topo_t: [G, N] f32) -> ([P] i32, [P] bool)
def _choose(
    avail, ps, n_active, nodes, weights, block, use_pallas=False, pallas_interpret=False, round_masks=None, salt=None,
    topo_t=None,
):
    """Per-pod best feasible node vs current capacity, blockwise over pods.

    Never materialises the full [P,N] score matrix: peak live memory is one
    [block, N] tile (HBM-bandwidth friendly; the pipeline analogue of
    SURVEY.md §2b PP).  Pods are compacted (active-first), so only the
    first ``ceil(n_active / block)`` blocks are evaluated — a dynamic bound
    on a ``lax.while_loop`` over blocks.  ``ps["ranks"]`` carries each pod's
    original priority rank into the score-jitter hash.
    """
    p = ps["pod_req"].shape[0]

    if topo_t is not None:
        # The fused Pallas kernel has no gang-locality operand yet; topology
        # cycles run the jnp expression tree (bit-identical to native by
        # construction — the term is the same xp tree on both backends).
        use_pallas = False
    if use_pallas:
        from .pallas_choose import pallas_kernel_supported

        if not pallas_kernel_supported(ps, nodes):
            use_pallas = False
    pallas_pack = None
    if use_pallas:
        from .pallas_choose import build_node_info

        cons_node = None
        if round_masks is not None:
            # Constrained kernel operands: the per-round [·, N] masks ride
            # into the kernel directly (zero-fill convention documented on
            # the helper — one source of truth with parallel/sharded.py).
            from .pallas_choose import constrained_kernel_node_operands

            cons_node = constrained_kernel_node_operands(ps, round_masks, avail.shape[0])
        # Rebuilt each round (avail changes); O(N) next to the O(B·N) choose.
        pallas_pack = (
            build_node_info(avail, nodes["node_alloc"], nodes["node_valid"]),
            nodes["node_labels"].T,
            nodes["node_taints"].T,
            nodes["node_aff"].T,
            nodes["node_pref"].T,
            nodes["node_taints_soft"].T,
            pallas_interpret,
            cons_node,
        )

    choose_keys = _CHOOSE_KEYS + (_CONSTRAINT_KEYS if round_masks is not None else ())
    if topo_t is not None:
        choose_keys = choose_keys + ("pod_gang_id",)
    if block >= p:
        return _choose_block(
            avail, nodes, weights, {k: ps[k] for k in choose_keys}, pallas_pack, round_masks, salt, topo_t
        )

    nb_occupied = (n_active + block - 1) // block  # traced; caller pads p % block == 0

    def cond(s):
        i = s[0]
        return i < nb_occupied

    def body(s):
        i, choice, has = s
        lo = i * block
        blk = {k: lax.dynamic_slice_in_dim(ps[k], lo, block) for k in choose_keys}
        bc, bh = _choose_block(avail, nodes, weights, blk, pallas_pack, round_masks, salt, topo_t)
        choice = lax.dynamic_update_slice_in_dim(choice, bc, lo, axis=0)
        has = lax.dynamic_update_slice_in_dim(has, bh, lo, axis=0)
        return i + 1, choice, has

    _, choice, has = lax.while_loop(cond, body, (jnp.int32(0), jnp.zeros((p,), jnp.int32), jnp.zeros((p,), bool)))
    return choice, has


# shape: (v: any, extra: int) -> any
def _pad0(v, extra):
    return jnp.pad(v, ((0, extra),) + ((0, 0),) * (v.ndim - 1))


# Shrink-chain floor: below this the accept phase is negligible and further
# steps would only multiply compiled variants.
_MIN_EPOCH_SIZE = 256


# shape: (target: int, block: int) -> int
def _chain_size(target: int, block: int) -> int:
    """Align one shrinking-chain size — THE single rule for both drivers
    (assign_cycle's static in-jit chain and assign_cycle_epochs' host-driven
    halving): block multiples while above ``block`` (the blockwise choose
    requires it), floored at _MIN_EPOCH_SIZE."""
    if target > block:
        target = ((target + block - 1) // block) * block
    return max(_MIN_EPOCH_SIZE, target)


# shape: (ps: dict) -> dict
def _compact(ps):
    """Stable active-first packing — relative (priority) order preserved.

    Implemented as a cumsum PARTITION, not a sort (PERF.md headroom item,
    measured ~0.6 ms vs ~1.45 ms per round at the north-star shape): each
    row's destination is its rank within its class (actives first), which
    is exactly the permutation a stable argsort of ``~active`` yields — so
    results stay bit-identical while dropping the O(P log P) sort."""
    active = ps["active"]
    n_act = jnp.cumsum(active.astype(jnp.int32))
    n_inact = jnp.cumsum((~active).astype(jnp.int32))
    dest = jnp.where(active, n_act - 1, n_act[-1] + n_inact - 1)
    return {k: jnp.zeros_like(v).at[dest].set(v) for k, v in ps.items()}


# shape: (pods: dict, block: int) -> ([P] i64, dict)
def _prepare_pods(pods, block: int):
    """Shared cycle setup — permute to priority order, pad to a block
    multiple, init the auction bookkeeping, compact actives to the front.
    ONE implementation for assign_cycle and the epoch driver: the two are
    interchangeable by construction, so their setup must be too.

    Priority order (priority desc, FIFO index asc); stable sort keeps FIFO.
    The permutation happens BEFORE any block padding: rank positions feed
    the score-jitter hash and must equal the native backend's (which never
    pads) for binding parity — padding first would shift ranks whenever a
    pod has negative priority.  Padding rows sit at ranks ≥ p_out
    (inactive), leaving real ranks intact.
    """
    p_out = pods["pod_req"].shape[0]
    perm = jnp.argsort(-pods["pod_prio"], stable=True)
    ps = {k: v[perm] for k, v in pods.items() if k != "pod_prio"}
    p = p_out
    if block < p and p % block != 0:
        extra = block - p % block
        ps = {k: _pad0(v, extra) for k, v in ps.items()}
        p = p + extra
    ps["ranks"] = jnp.arange(p, dtype=jnp.uint32)
    ps["assigned"] = jnp.full((p,), -1, jnp.int32)
    ps["acc_round"] = jnp.full((p,), -1, jnp.int32)  # round each pod was accepted in
    ps["active"] = ps.pop("pod_valid")
    return perm, _compact(ps)


# shape: (nodes: dict, weights: [W] f32, block: int, use_pallas: bool,
#   pallas_interpret: bool, cmeta: dict, soft_spread: bool, soft_pa: bool,
#   hard_pa: bool, tmeta: dict) -> fn
def _make_round_body(nodes, weights, block, use_pallas, pallas_interpret, cmeta, soft_spread, soft_pa=False, hard_pa=True, tmeta=None):
    """One auction round as a while_loop body (shared by the monolithic
    assign_cycle and the size-shrinking epoch driver).

    ``tmeta`` (topology/locality.TopologySet.meta_arrays) switches on the
    rank-aware gang co-placement term: each round derives the per-(gang,
    node) score tensor from the loop-carried placement counts ``tst`` and
    the live capacity, and commit folds the round's accepted gang members
    back into those counts.  Gang-count state is [G, N] — NOT pod-indexed —
    so the size-chain slicing never loses placed-member information."""
    n = nodes["node_avail"].shape[0]

    def body(state):
        avail, ps, n_active, rounds, cst, tst = state
        p = ps["pod_req"].shape[0]
        round_masks = None
        if cmeta is not None:
            from .constraints import constraint_commit, constraint_filter, round_blocked_masks

            round_masks = round_blocked_masks(jnp, cst, cmeta, soft_spread=soft_spread, soft_pa=soft_pa, hard_pa=hard_pa)
        topo_t = None
        if tmeta is not None:
            topo_t = gang_topology_term(
                jnp, tst["gang_nodes"], tmeta, avail, ps["pod_gang_id"], ps["pod_req"], ps["active"], weights[6]
            )
        choice, has = _choose(
            avail, ps, n_active, nodes, weights, block, use_pallas, pallas_interpret, round_masks, salt=rounds,
            topo_t=topo_t,
        )
        cand = ps["active"] & has
        ch = jnp.where(cand, choice, n).astype(jnp.int32)  # sentinel segment n for non-claimants
        claim = jnp.where(cand[:, None], ps["pod_req"], 0)

        # Group claimants per node; the stable sort preserves the compacted
        # (= priority) order among each node's claimants.
        order = jnp.argsort(ch, stable=True)
        ch_s = ch[order]
        claim_s = claim[order]
        is_start = jnp.concatenate([jnp.ones((1,), bool), ch_s[1:] != ch_s[:-1]])[:, None]
        _, within = lax.associative_scan(_seg_scan_op, (is_start, claim_s))

        avail_ext = jnp.concatenate([avail, jnp.zeros((1, avail.shape[1]), avail.dtype)], axis=0)
        fits_prefix = (within <= avail_ext[ch_s]).all(-1)
        acc_s = fits_prefix & (ch_s < n)
        accepted = jnp.zeros((p,), bool).at[order].set(acc_s)

        if cmeta is not None:
            # Within-round conflict resolution + domain-state commit
            # (deferred pods stay active and retry next round).
            accepted = constraint_filter(jnp, accepted, choice, ps["ranks"], ps, cst, cmeta, hard_pa=hard_pa)
            stall = jnp.where(accepted.any(), jnp.int32(0), cst["stall"] + 1)
            cst = constraint_commit(jnp, accepted, choice, ps, cst, cmeta, soft_spread=soft_spread, soft_pa=soft_pa, hard_pa=hard_pa)
            cst["stall"] = stall

        ps["assigned"] = jnp.where(accepted, choice, ps["assigned"])
        ps["acc_round"] = jnp.where(accepted, rounds, ps["acc_round"])
        dec = jnp.zeros((n + 1, avail.shape[1]), jnp.int32).at[ch].add(jnp.where(accepted[:, None], ps["pod_req"], 0))
        avail = avail - dec[:n]
        was_active = ps["active"]
        ps["active"] = cand & ~accepted
        if cmeta is not None and hard_pa:
            # Positive affinity breaks the "feasibility only shrinks" rule
            # the no-feasible-node drop-out relies on: a pod placed THIS
            # round can activate a declarer's term and open nodes for it.
            # Keep blocked-everywhere PA declarers active while ANY pending
            # PA term gained a match this round: activations cascade (a
            # multi-hop chain A->B->C inside a GANG needs A alive until B
            # places — and the gang mop-up exclusion means a dropped gang
            # member livelocks, round-5 review finding), but a round where
            # NO term progressed cannot open anyone's nodes (AA masks only
            # grow, capacity only shrinks), so the hopeless stragglers that
            # round 4's any-pod-placed rule pinned through the whole
            # flagship tail (diag_constrained_tail: ~1.3k pods blocking the
            # size chain) drain as soon as PA progress stops.
            new_match = (ps["pod_pa_matched"] * accepted[:, None].astype(jnp.float32)).sum(axis=0) > 0  # [Ta]
            pa_hope = (ps["pod_pa_declares"].sum(axis=1) > 0) & new_match.any()
            ps["active"] = ps["active"] | (was_active & ~has & pa_hope)
        if tmeta is not None:
            # Commit accepted gang members into the [G, N] placement counts
            # (non-claimants carry the sentinel column, gangless pods row 0 —
            # neither is ever read back).
            tst = {"gang_nodes": gang_state_update(jnp, tst["gang_nodes"], accepted, ch, ps["pod_gang_id"])}
        ps = _compact(ps)
        return avail, ps, ps["active"].sum(dtype=jnp.int32), rounds + 1, cst, tst

    return body


# shape: (nodes: dict, pods: dict, weights: [W] f32, max_rounds: int,
#   block: int, use_pallas: bool, pallas_interpret: bool, cmeta: dict,
#   cstate: dict, soft_spread: bool, soft_pa: bool, hard_pa: bool,
#   tmeta: dict, tstate: dict)
#   -> ([P] i32, scalar i32, [N, R] i32, [P] i32, [P] i32)
@partial(jax.jit, static_argnames=("max_rounds", "block", "use_pallas", "pallas_interpret", "soft_spread", "soft_pa", "hard_pa"))
def assign_cycle(
    nodes: dict,
    pods: dict,
    weights,
    max_rounds: int = 32,
    block: int = 4096,
    use_pallas: bool = False,
    pallas_interpret: bool = False,
    cmeta: dict | None = None,
    cstate: dict | None = None,
    soft_spread: bool = False,
    soft_pa: bool = False,
    hard_pa: bool = True,
    tmeta: dict | None = None,
    tstate: dict | None = None,
):
    """Assign all pending pods to nodes in one on-device cycle.

    ``nodes``/``pods`` are the PackedCluster device arrays split by prefix
    (see :func:`split_device_arrays`).  Returns (assigned [P] int32 — node
    index or −1, rounds int32, remaining node_avail [N,2] int32).

    ``cmeta``/``cstate`` (ops/constraints.py meta_arrays/state_arrays) switch
    on the anti-affinity + topology-spread path: choose gains the blocked-
    domain matmuls, accept gains the within-round conflict filter, and the
    domain state threads through the loop carry.  ``pods`` must then also
    carry the constraint pod bitmaps (ConstraintSet.pod_arrays).  The fused
    Pallas kernel covers constraint cycles too: the per-round blocked/penalty
    node masks ride in as extra node-side kernel operands (choose_block_pallas
    ``cons_pod``/``cons_node``), while accept/commit stay in jnp.

    The auction runs as a STATIC SIZE CHAIN inside the one jit program: the
    same round body at shrinking pod-array sizes (quartering, block-aligned,
    floored at _MIN_EPOCH_SIZE), advancing to the next size once the active
    count fits it.  Compaction keeps actives in a prefix, so each stage
    transition folds the finished rows' results into full-size rank-space
    buffers and takes a static prefix slice — all on device, zero host
    syncs.  This is the epoch driver's halving idea without its per-epoch
    jit-boundary relayout (~200 ms at 100k pods) and host-sync (~70 ms)
    costs; results are bit-identical to a single full-size loop because
    dropped rows are exactly the inactive ones and padding rows never
    influence a round (sentinel cells, rank-keyed jitter).
    """
    p_out = pods["pod_req"].shape[0]
    perm, ps = _prepare_pods(pods, block)
    p = ps["pod_req"].shape[0]
    if cmeta is not None:
        from .constraints import augment_round_state

        # Round-carried conflict state (spread water line, per-cell counts,
        # PA bootstrap flags) derived once at cycle start and updated
        # incrementally by constraint_commit inside the round body.
        cstate = {**augment_round_state(jnp, cstate, cmeta, hard_pa=hard_pa), "stall": jnp.int32(0)}

    body = _make_round_body(
        nodes, weights, block, use_pallas, pallas_interpret, cmeta, soft_spread, soft_pa, hard_pa, tmeta
    )

    # Static size chain: p, p/4, p/16, … — ONE alignment/floor rule shared
    # with the epoch driver (_chain_size).  A stage is only appended when it
    # at least halves the previous one: a near-no-op tail stage (e.g. 300 →
    # 256) would pay a full extra while_loop + compiled round-body variant
    # for negligible savings.
    sizes = [p]
    while True:
        nxt = _chain_size(sizes[-1] // 4, block)
        if nxt > sizes[-1] // 2:
            break
        sizes.append(nxt)

    def make_cond(next_size, done):
        def cond(state):
            _, _, n_active, rounds, cst, _tst = state
            go = (rounds < max_rounds) & (n_active > 0) & ~done
            if cmeta is not None:
                go = go & (cst["stall"] < STALL_ROUNDS)
            if next_size:
                # Hand off to the next (smaller) stage once actives fit it.
                go = go & (n_active > next_size)
            return go

        return cond

    assigned_rank = jnp.zeros((p,), jnp.int32)
    acc_round_rank = jnp.zeros((p,), jnp.int32)
    avail = nodes["node_avail"]
    n_active = ps["active"].sum(dtype=jnp.int32)
    rounds = jnp.int32(0)
    cst = cstate
    tst = tstate
    # Terminal-exit latch: the stage-transition slice below is only safe
    # because a stage that exits via the round cap / stall / drained-pool
    # conditions (rather than the size handoff) guarantees every LATER stage
    # runs zero rounds — the slice may drop rows that are still active, and
    # the pre-slice fold preserves their unassigned state only if nothing
    # ever touches them again.  That used to be an implicit cross-stage
    # invariant riding on later conds re-checking the same rounds/stall
    # terms; ``done`` makes it explicit and robust against future per-stage
    # cond changes (e.g. resetting stall between stages).
    done = jnp.bool_(False)
    for i, size in enumerate(sizes):
        if i > 0:
            # Fold the rows about to be dropped (all inactive when the
            # previous stage exited via the size handoff — actives sit in
            # the compacted prefix and fit ``size``; on a terminal exit the
            # ``done`` latch keeps this stage at zero rounds), then slice.
            assigned_rank = assigned_rank.at[ps["ranks"]].set(ps["assigned"])
            acc_round_rank = acc_round_rank.at[ps["ranks"]].set(ps["acc_round"])
            ps = {k: v[:size] for k, v in ps.items()}
        next_size = sizes[i + 1] if i + 1 < len(sizes) else 0
        avail, ps, n_active, rounds, cst, tst = lax.while_loop(
            make_cond(next_size, done), body, (avail, ps, n_active, rounds, cst, tst)
        )
        terminal = (rounds >= max_rounds) | (n_active <= 0)
        if cmeta is not None:
            terminal = terminal | (cst["stall"] >= STALL_ROUNDS)
        done = done | terminal

    # Undo compaction (rank space), then the priority permutation (original
    # pod order), dropping block padding.
    assigned_rank = assigned_rank.at[ps["ranks"]].set(ps["assigned"])
    out = jnp.full((p_out,), -1, jnp.int32).at[perm].set(assigned_rank[:p_out])
    acc_round_rank = acc_round_rank.at[ps["ranks"]].set(ps["acc_round"])
    acc_round = jnp.full((p_out,), -1, jnp.int32).at[perm].set(acc_round_rank[:p_out])
    rank_of = jnp.zeros((p_out,), jnp.int32).at[perm].set(jnp.arange(p_out, dtype=jnp.int32))
    return out, rounds, avail, acc_round, rank_of


# Constraint cycles stop after STALL_ROUNDS consecutive ZERO-acceptance
# rounds (constant in ops/pack.py — jax-free for the native backend):
# unconstrained rounds always accept >=1 claimant (progress guarantee), but
# the within-round constraint filter can defer the same pods forever (e.g. a
# spread water line frozen by a capacity-full minimum domain) — measured 48
# wasted rounds to the cap at 5k pods.  Jitter re-rolls each round, so a few
# zero rounds may still unstick; after STALL_ROUNDS identical-state rounds
# the stragglers requeue to the next cycle instead (reference main.rs:122-125
# semantics — a retry later, never a crash or a spin).


# shape: (nodes: dict, pods: dict, block: int) -> ([P] i64, [N, R] i32, dict, scalar i32)
@partial(jax.jit, static_argnames=("block",))
def _epoch_prelude(nodes, pods, block: int):
    """Jitted wrapper of the shared cycle setup, returning the state the
    epoch loop drives (plus the permutation for the final unpermute)."""
    perm, ps = _prepare_pods(pods, block)
    return perm, nodes["node_avail"], ps, ps["active"].sum(dtype=jnp.int32)


# shape: (nodes: dict, ps: dict, avail: [N, R] i32, n_active: scalar i32,
#   rounds: scalar i32, cst: dict, weights: [W] f32, cmeta: dict,
#   tmeta: dict, tst: dict) -> any
@partial(jax.jit, static_argnames=("max_rounds", "block", "use_pallas", "pallas_interpret", "soft_spread", "soft_pa", "hard_pa", "floor"))
def _assign_epoch(
    nodes, ps, avail, n_active, rounds, cst, weights, cmeta,
    max_rounds: int, block: int, use_pallas: bool, pallas_interpret: bool, soft_spread: bool, soft_pa: bool, hard_pa: bool, floor: bool,
    tmeta=None, tst=None,
):
    """Run auction rounds until done — or, when not at the ``floor`` size,
    until the active count falls to half the (static) pod-array size, so the
    host driver can halve the arrays and re-enter at a cheaper size.

    ``cmeta`` is a traced pytree operand; its None-vs-dict structure is part
    of the jit cache key, which is what lets the body builder branch on it
    at trace time (same contract as assign_cycle)."""
    p = ps["pod_req"].shape[0]
    body = _make_round_body(
        nodes, weights, block, use_pallas, pallas_interpret, cmeta, soft_spread, soft_pa, hard_pa, tmeta
    )

    def cond(state):
        _, _, n_active, rounds, cst, _tst = state
        go = (rounds < max_rounds) & (n_active > 0)
        if cmeta is not None:
            go = go & (cst["stall"] < STALL_ROUNDS)
        if not floor:
            go = go & (2 * n_active > p)
        return go

    return lax.while_loop(cond, body, (avail, ps, n_active, rounds, cst, tst))


# shape: (nodes: dict, pods: dict, weights: [W] f32, max_rounds: int,
#   block: int, use_pallas: bool, pallas_interpret: bool, cmeta: dict,
#   cstate: dict, soft_spread: bool, soft_pa: bool, hard_pa: bool,
#   tmeta: dict, tstate: dict)
#   -> ([P] i32, scalar i32, [N, R] i32, [P] i32, [P] i32)
# hotpath: epochs-driver
def assign_cycle_epochs(
    nodes: dict,
    pods: dict,
    weights,
    max_rounds: int = 32,
    block: int = 4096,
    use_pallas: bool = False,
    pallas_interpret: bool = False,
    cmeta: dict | None = None,
    cstate: dict | None = None,
    soft_spread: bool = False,
    soft_pa: bool = False,
    hard_pa: bool = True,
    tmeta: dict | None = None,
    tstate: dict | None = None,
):
    """assign_cycle with host-driven SIZE SHRINKING — the backend's driver.

    Identical round-by-round math to :func:`assign_cycle` (same body fn),
    but the pod arrays are re-sliced to half along a fixed halving chain
    whenever the active count drops below half the current size: the accept
    phase's per-round sort/scan/scatter cost then tracks the live pod count
    instead of staying O(P_padded · log P) for all ~32 rounds.  Compaction
    keeps actives in a prefix, so slicing drops only finished rows (their
    results are folded into rank-space buffers first).  Each size on the
    chain compiles once and is cached by jit; one host sync per epoch
    (≤ log2(P/block) + 1 epochs).

    NOT jittable (host control flow) — jittable contexts (dryrun, graft
    entry) use :func:`assign_cycle`.
    """
    p_out = pods["pod_req"].shape[0]
    perm, avail, ps, n_active_dev = _epoch_prelude(nodes, pods, block)
    p_pad = ps["pod_req"].shape[0]
    # Enter the loop on the static upper bound instead of blocking on the
    # prelude's device count (an XFER finding: a whole extra device
    # round-trip per cycle before any epoch had even dispatched).  The true
    # active count rides home in epoch 0's single per-epoch fetch below; if
    # it is 0 the epoch's while_loop exits without running a round and the
    # results are identical.
    n_active = p_pad
    rounds = jnp.int32(0)
    if cmeta is not None:
        from .constraints import augment_round_state

        # Same round-carried conflict state as assign_cycle, derived once
        # (eagerly — the carry structure must be stable across epochs).
        cst = {**augment_round_state(jnp, cstate, cmeta, hard_pa=hard_pa), "stall": jnp.int32(0)}
    else:
        cst = cstate
    tst = tstate
    assigned_rank = jnp.full((p_pad,), -1, jnp.int32)
    acc_round_rank = jnp.full((p_pad,), -1, jnp.int32)

    p_cur = p_pad
    rounds_i = 0
    epoch_i = 0
    while rounds_i < max_rounds and n_active > 0:
        floor = p_cur <= _MIN_EPOCH_SIZE
        # Profiler attribution (utils/profiler.py): ``dispatch`` is the
        # Python/trace cost of launching the epoch (the jit call returns
        # before the device finishes — async dispatch), ``host-sync`` is the
        # ONE per-epoch blocking fetch where the device execute + transfer
        # time actually lands.  Both are host-side spans OUTSIDE the jit
        # boundary (JAXP-clean); together with the jax.monitoring compile
        # listener they decompose "solve" into compile / dispatch /
        # device-execute+sync.
        with span(f"epoch[{epoch_i}]"):
            with span("dispatch"):
                avail, ps, n_active_dev, rounds, cst, tst = _assign_epoch(
                    nodes, ps, avail, n_active_dev, rounds, cst, weights, cmeta,
                    max_rounds, block, use_pallas, pallas_interpret, soft_spread, soft_pa, hard_pa, floor,
                    tmeta, tst,
                )
            # ONE host sync per epoch: n_active, rounds, and the stall
            # counter ride home in a single fetch (~80 ms tunnel latency
            # each otherwise).
            with span("host-sync"):
                if cmeta is not None:
                    trio = jnp.stack([n_active_dev, rounds, cst["stall"]])
                    n_active, rounds_i, stall_i = (int(v) for v in trio)
                else:
                    duo = jnp.stack([n_active_dev, rounds])
                    n_active, rounds_i = (int(v) for v in duo)
                    stall_i = 0
        epoch_i += 1
        if stall_i >= STALL_ROUNDS:
            break
        if floor:
            break
        # Halving chain (alignment rule shared with assign_cycle's static
        # in-jit chain: _chain_size), so late rounds touch hundreds of rows,
        # not a full block.
        new_size = p_cur
        while new_size > _MIN_EPOCH_SIZE and n_active * 2 <= new_size:
            new_size = _chain_size(new_size // 2, block)
        if new_size < p_cur:
            # Fold the rows about to be dropped (all finished — actives sit
            # in the compacted prefix) into the rank-space result buffers.
            assigned_rank = assigned_rank.at[ps["ranks"]].set(ps["assigned"])
            acc_round_rank = acc_round_rank.at[ps["ranks"]].set(ps["acc_round"])
            ps = {k: v[:new_size] for k, v in ps.items()}
            p_cur = new_size

    assigned_rank = assigned_rank.at[ps["ranks"]].set(ps["assigned"])
    acc_round_rank = acc_round_rank.at[ps["ranks"]].set(ps["acc_round"])
    out = jnp.full((p_out,), -1, jnp.int32).at[perm].set(assigned_rank[:p_out])
    acc_round = jnp.full((p_out,), -1, jnp.int32).at[perm].set(acc_round_rank[:p_out])
    rank_of = jnp.zeros((p_out,), jnp.int32).at[perm].set(jnp.arange(p_out, dtype=jnp.int32))
    return out, rounds, avail, acc_round, rank_of
