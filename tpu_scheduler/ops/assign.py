"""Conflict-free batched assignment — the jitted scheduling cycle.

This is the TPU replacement for the reference's sequential reconcile loop
(``src/main.rs:51-71`` + the controller dispatch at ``main.rs:141-149``):
instead of one pod at a time × ≤5 random candidates × one RPC each, all
pending pods are assigned in a small number of *auction rounds*, entirely
on-device, with capacity commits that make oversubscription impossible —
closing the reference's by-design TOCTOU race (SURVEY.md §5: two concurrent
reconciles can both fit the same gap).

Round structure (all under ``lax.while_loop``; shapes static):
  1. choose:  blockwise over pods — feasibility mask + scores vs the
     *current* remaining capacity; per-pod masked argmax → choice[P].
  2. accept:  pods are pre-permuted into (priority desc, FIFO) order; a
     stable sort by chosen node groups each node's claimants in priority
     order; a segmented saturating prefix-sum of their requests accepts the
     longest prefix that fits remaining capacity.
  3. commit:  accepted requests scatter-subtract from remaining capacity;
     accepted pods leave the pool; pods with no feasible node drop out
     (capacity only shrinks within a cycle, so they can never become
     feasible again this cycle → they requeue, reference ``main.rs:122-125``).

Every round with any claimant accepts at least the highest-priority claimant
of each contended node, so the loop strictly progresses; ``max_rounds`` is a
safety cap only.

Overflow note: within-segment demand prefix-sums can exceed int32 (100k pods
× multi-GiB requests in KiB), so the scan uses *saturating* int32 addition —
associative for non-negatives, yielding exactly ``min(true_sum, INT32_MAX)``,
which the native NumPy backend mirrors with exact int64 + clamp.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from .masks import feasibility_block
from .pack import INT32_MAX
from .score import score_block

__all__ = ["assign_cycle", "INT32_MAX"]


def _sat_add(a, b):
    """Saturating int32 add for non-negative operands: min(a+b, INT32_MAX)."""
    s = a + b
    return jnp.where(s < 0, INT32_MAX, s)


def _seg_scan_op(x, y):
    """Segmented saturating-sum operator for lax.associative_scan.

    Elements are (segment_start_flag [.,1] bool, value [.,2] int32).
    """
    fx, vx = x
    fy, vy = y
    return fx | fy, jnp.where(fy, vy, _sat_add(vx, vy))


def _choose(avail, active, req, sel, selc, node_alloc, node_labels, node_valid, weights, block):
    """Per-pod best feasible node vs current capacity, blockwise over pods.

    Never materialises the full [P,N] score matrix: peak live memory is one
    [block, N] tile (HBM-bandwidth friendly; the pipeline analogue of
    SURVEY.md §2b PP).
    """
    p = req.shape[0]
    n = avail.shape[0]
    pod_idx = jnp.arange(p, dtype=jnp.uint32)
    node_idx = jnp.arange(n, dtype=jnp.uint32)

    def one(args):
        breq, bsel, bselc, bact, bidx = args
        m = feasibility_block(jnp, breq, bsel, bselc, bact, avail, node_labels, node_valid)
        sc = score_block(jnp, breq, node_alloc, avail, weights, bidx, node_idx)
        sc = jnp.where(m, sc, -jnp.inf)
        return jnp.argmax(sc, axis=1).astype(jnp.int32), m.any(axis=1)

    if block >= p:
        return one((req, sel, selc, active, pod_idx))
    nb = p // block  # caller guarantees p % block == 0 (assign_cycle pads)
    choice, has = lax.map(
        one,
        (
            req.reshape(nb, block, 2),
            sel.reshape(nb, block, -1),
            selc.reshape(nb, block),
            active.reshape(nb, block),
            pod_idx.reshape(nb, block),
        ),
    )
    return choice.reshape(p), has.reshape(p)


@partial(jax.jit, static_argnames=("max_rounds", "block"))
def assign_cycle(
    node_alloc,
    node_avail,
    node_labels,
    node_valid,
    pod_req,
    pod_sel,
    pod_sel_count,
    pod_prio,
    pod_valid,
    weights,
    max_rounds: int = 32,
    block: int = 4096,
):
    """Assign all pending pods to nodes in one on-device cycle.

    Returns (assigned [P] int32 — node index or −1, rounds int32,
    remaining node_avail [N,2] int32).
    """
    p_out = pod_req.shape[0]
    n = node_avail.shape[0]

    # Priority order (priority desc, FIFO index asc); stable sort keeps FIFO.
    # The permutation happens BEFORE any block padding: rank positions feed
    # the score-jitter hash and must equal the native backend's (which never
    # pads) for binding parity — padding first would shift ranks whenever a
    # pod has negative priority.
    perm = jnp.argsort(-pod_prio, stable=True)
    req = pod_req[perm]
    sel = pod_sel[perm]
    selc = pod_sel_count[perm]
    valid = pod_valid[perm]

    # Pad the pod axis to a block multiple so the blockwise choose path is
    # always exact — otherwise a remainder would silently materialise the
    # full [P,N] score matrix and blow HBM at target scale (100k × 10k).
    # Padding rows sit at ranks ≥ p_out (inactive), leaving real ranks intact.
    p = p_out
    if block < p and p % block != 0:
        extra = block - p % block
        req = jnp.pad(req, ((0, extra), (0, 0)))
        sel = jnp.pad(sel, ((0, extra), (0, 0)))
        selc = jnp.pad(selc, ((0, extra),))
        valid = jnp.pad(valid, ((0, extra),))
        p = p + extra

    def cond(state):
        _, _, active, rounds = state
        return (rounds < max_rounds) & active.any()

    def body(state):
        avail, assigned, active, rounds = state
        choice, has = _choose(avail, active, req, sel, selc, node_alloc, node_labels, node_valid, weights, block)
        cand = active & has
        ch = jnp.where(cand, choice, n).astype(jnp.int32)  # sentinel segment n for non-claimants
        claim = jnp.where(cand[:, None], req, 0)

        # Group claimants per node, priority order preserved by stable sort.
        order = jnp.argsort(ch, stable=True)
        ch_s = ch[order]
        claim_s = claim[order]
        is_start = jnp.concatenate([jnp.ones((1,), bool), ch_s[1:] != ch_s[:-1]])[:, None]
        _, within = lax.associative_scan(_seg_scan_op, (is_start, claim_s))

        avail_ext = jnp.concatenate([avail, jnp.zeros((1, 2), avail.dtype)], axis=0)
        fits_prefix = (within <= avail_ext[ch_s]).all(-1)
        acc_s = fits_prefix & (ch_s < n)
        accepted = jnp.zeros((p,), bool).at[order].set(acc_s)

        assigned = jnp.where(accepted, choice, assigned)
        dec = jnp.zeros((n + 1, 2), jnp.int32).at[ch].add(jnp.where(accepted[:, None], req, 0))
        avail = avail - dec[:n]
        active = cand & ~accepted
        return avail, assigned, active, rounds + 1

    state0 = (node_avail, jnp.full((p,), -1, jnp.int32), valid, jnp.int32(0))
    avail, assigned, _, rounds = lax.while_loop(cond, body, state0)

    # Back to original pod order (dropping block padding).
    out = jnp.full((p_out,), -1, jnp.int32).at[perm].set(assigned[:p_out])
    return out, rounds, avail
