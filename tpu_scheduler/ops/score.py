"""Priority scoring — LeastRequested + BalancedAllocation as dense tensor ops
(BASELINE.json config 4).

The reference has *no* scoring (first feasible random candidate wins,
``src/main.rs:51-71``); this implements the standard kube-scheduler pair the
north star mandates, over the packed tensors:

  used_after[p,n,r] = (alloc[n,r] − avail[n,r]) + req[p,r]
  frac              = used_after / alloc              (1.0 where alloc == 0)
  least_requested   = mean_r(1 − frac) · 100
  balanced          = (1 − |frac_cpu − frac_mem|) · 100
  score             = w_lr · least_requested + w_ba · balanced

A third term breaks score ties deterministically: pods with identical
requests see identical LeastRequested/Balanced rows, so a whole batch would
herd onto one argmax node per auction round (the reference never hits this
because it samples randomly, ``main.rs:56``).  A hash-based per-(pod, node)
jitter — uint32 Knuth-multiplicative, identical wraparound semantics in
NumPy and XLA — spreads near-ties across near-tied nodes while leaving
materially different scores ordered.  Deterministic, so native/TPU/sharded
parity is preserved bitwise.

xp-generic (numpy / jax.numpy): one expression tree for both backends, all
float32 elementwise, so native and TPU scores agree bitwise.
"""

from __future__ import annotations

__all__ = ["score_block"]


# shape: (pod_req: [B, R] i32, node_alloc: [N, R] i32, node_avail: [N, R] i32,
#   weights: [W] f32, pod_idx: [B] u32, node_idx: [N] u32,
#   pod_pref_w: [B, A2] f32, node_pref: [N, A2] f32,
#   pod_ntol_soft: [B, Ts] f32, node_taints_soft: [N, Ts] f32,
#   pod_sps_declares: [B, Ss] f32, sp_penalty_node: [Ss, N] f32,
#   pod_sp_declares: [B, S] f32, sp_level_node: [S, N] f32,
#   pod_ppa_w: [B, Tp] f32, ppa_cnt_node: [Tp, N] f32,
#   salt: scalar any, pod_gang_id: [B] i32, topo_gang_node: [G, N] f32) -> [B, N] f32
def score_block(
    xp,
    pod_req,
    node_alloc,
    node_avail,
    weights,
    pod_idx=None,
    node_idx=None,
    pod_pref_w=None,
    node_pref=None,
    pod_ntol_soft=None,
    node_taints_soft=None,
    pod_sps_declares=None,
    sp_penalty_node=None,
    pod_sp_declares=None,
    sp_level_node=None,
    pod_ppa_w=None,
    ppa_cnt_node=None,
    salt=None,
    pod_gang_id=None,
    topo_gang_node=None,
):
    """[B, N] combined priority score of a block of pods against all nodes.

    pod_req [B,2] int32; node_alloc, node_avail [N,2] int32;
    weights [7] f32 — (least_requested_w, balanced_allocation_w, jitter,
    preferred_affinity_w, soft_taint_w, topology_w, gang_locality_w —
    models/profiles.py ``weights()`` order; index 6 is consumed upstream by
    topology/locality.gang_topology_term, not here); pod_idx [B] /
    node_idx [N] uint32 — global indices for the jitter hash (optional;
    jitter term is skipped when either is None).

    Soft terms (each optional-together, zero-width tensors are no-ops):
      • preferred node affinity: +w₃ · Σ matching-term weights
        (pod_pref_w [B,A2] · node_pref [N,A2], kube NodeAffinity scoring);
      • PreferNoSchedule taints: −w₄ per untolerated soft taint
        (pod_ntol_soft [B,Ts] · node_taints_soft [N,Ts], kube
        TaintToleration scoring);
      • ScheduleAnyway topology spread: −w₅ per matching placed pod already
        in the node's domain, per declared soft constraint
        (pod_sps_declares [B,Ss] · sp_penalty_node [Ss,N],
        ops/constraints.round_blocked_masks) — emptier domains score higher;
      • preferred inter-pod (anti-)affinity: ± term-weight per matching pod
        in the node's domain (pod_ppa_w [B,Tp] SIGNED weights ·
        ppa_cnt_node [Tp,N] domain match counts, kube InterPodAffinity
        scoring; anti-preference rides the same matmul with negative
        weights, so no extra global knob — the 1-100 term weights rule).
    """
    f32 = xp.float32
    # Scoring reads cpu/mem only (columns 0-1) — slice BEFORE the [B,N,·]
    # broadcast so extended-resource columns (R > 2) never materialize in
    # the hot path; bit-identical at R == 2.
    pod_req = pod_req[:, :2]
    node_alloc = node_alloc[:, :2]
    node_avail = node_avail[:, :2]
    used_after = (node_alloc - node_avail)[None, :, :] + pod_req[:, None, :]  # [B,N,2] int32
    safe = (node_alloc > 0)[None, :, :]
    denom = xp.where(safe, node_alloc.astype(f32)[None, :, :], f32(1.0))
    frac = xp.where(safe, used_after.astype(f32) / denom, f32(1.0))
    least_requested = ((f32(1.0) - frac[..., 0]) + (f32(1.0) - frac[..., 1])) * f32(50.0)
    balanced = (f32(1.0) - xp.abs(frac[..., 0] - frac[..., 1])) * f32(100.0)
    score = weights[0] * least_requested + weights[1] * balanced
    if pod_pref_w is not None and node_pref is not None:
        score = score + weights[3] * (pod_pref_w @ node_pref.T)
    if pod_ntol_soft is not None and node_taints_soft is not None:
        score = score - weights[4] * (pod_ntol_soft @ node_taints_soft.T)
    if pod_idx is not None and node_idx is not None:
        u32 = xp.uint32
        h = pod_idx.astype(u32)[:, None] * u32(2654435761) + node_idx.astype(u32)[None, :] * u32(2246822519)
        if salt is not None:
            # Auction-round salt: deferred pods re-roll their tie-break each
            # round instead of re-herding onto the same near-tied nodes —
            # spreads retries, cutting rounds.  Same wraparound semantics in
            # NumPy and XLA (uint32), so cross-backend parity is preserved.
            h = h + xp.asarray(salt).astype(u32) * u32(3266489917)
        h = (h ^ (h >> u32(15))) & u32(0xFFFF)
        # BUCKET-QUANTIZED tie-break: scores within one jitter-amplitude
        # bucket are treated as exact ties and ordered by the hash alone, so
        # claimants spread UNIFORMLY across the whole near-tied band instead
        # of clustering around its additive-jitter-weighted top.  Measured
        # motivation (round 5, scripts/diag_round_kills.py): with additive
        # jitter the flagship constrained tail's ~16k claimants chose only
        # ~11 distinct nodes per term — a few leader nodes sat just above
        # the ±32-point band and the capacity prefix killed 15k claimants a
        # round.  Same floor/div ops in numpy and XLA → parity holds; w₂=0
        # keeps the raw score (jitter off).
        jw = weights[2]
        safe = xp.where(jw > 0, jw, f32(1.0))
        score = xp.where(jw > 0, xp.floor(score / safe) * safe, score) + jw * (h.astype(f32) / f32(65536.0))
    if pod_sps_declares is not None and sp_penalty_node is not None:
        score = score - weights[5] * (pod_sps_declares @ sp_penalty_node)
    if pod_sp_declares is not None and sp_level_node is not None:
        # HARD-spread declarer steering: −2·jitter-amplitude per level the
        # node's domain sits above the constraint's water line
        # (ops/constraints.round_blocked_masks ``sp_level_node``).  Levels
        # dominate the ±jitter tie-break, so declarers target the domains
        # the admission filter can actually accept; nodes within one level
        # stay jitter-spread.  Score-neutral for everyone else.
        score = score - (f32(2.0) * weights[2]) * (pod_sp_declares @ sp_level_node)
    if pod_ppa_w is not None and ppa_cnt_node is not None:
        score = score + pod_ppa_w @ ppa_cnt_node
    if pod_gang_id is not None and topo_gang_node is not None:
        # Rank-aware gang co-placement (topology/locality.py): the per-round
        # [G+1, N] anchor/fit/herd tensor is SHARED by every member of a
        # gang, so the whole batched all-ranks term is one row gather here.
        # Added after the jitter quantization, like the hard-spread steering:
        # its herd component is sized to dominate the per-pod tie-break so a
        # gang converges on one domain instead of scattering across
        # near-ties.  Row 0 is pinned to zero — score-neutral for gangless
        # pods (and block padding, which lands in gang 0).
        score = score + topo_gang_node[pod_gang_id]
    return score.astype(f32)
