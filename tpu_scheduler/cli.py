"""CLI entry point — the ``main()`` of the framework (reference
``src/main.rs:127-152``), with the north-star ``--backend={native,tpu}`` flag.

The reference connects to a real cluster via kubeconfig; this framework's
first-class cluster is the in-process fake API server loaded with a synthetic
workload (BASELINE.json config 3) — a real-cluster adapter is an edge module
by design (SURVEY.md §7 step 5).  Run:

    python -m tpu_scheduler.cli --backend=tpu --nodes 1000 --pods 10000

Prints one JSON metrics line per cycle and a final summary line.
"""

from __future__ import annotations

import argparse
import json
import random
import sys

from .backends.native import NativeBackend
from .models.profiles import PROFILES
from .runtime.controller import ATTEMPTS, REQUEUE_SECONDS, Scheduler
from .runtime.fake_api import FakeApiServer
from .testing import synth_cluster
from .utils.tracing import configure_logging


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="tpu-scheduler", description=__doc__)
    p.add_argument(
        "--backend",
        choices=["native", "tpu", "tpu-sharded"],
        default="tpu",
        help="scheduling backend (north-star flag); tpu-sharded runs the cycle over a dp×tp device mesh",
    )
    p.add_argument("--tp", type=int, default=None, help="tpu-sharded: tensor-parallel (nodes-axis) mesh width; dp gets the rest of the devices")
    p.add_argument(
        "--distributed",
        action="store_true",
        help="initialize jax.distributed at startup for multi-host meshes (reads SCHED_COORDINATOR / SCHED_NUM_PROCESSES / SCHED_PROCESS_ID, or auto-detects)",
    )
    p.add_argument("--policy", choices=["batch", "sample"], default="batch", help="batched cycle vs reference-style per-pod random sampling")
    p.add_argument("--profile", choices=sorted(PROFILES), default="default", help="scoring profile")
    p.add_argument(
        "--profile-file",
        default=None,
        metavar="PATH",
        help="load the scoring profile from a tuned-profile JSON artifact (learn/profiles schema; "
        "overrides --profile; --driver/--max-rounds/--pool-key/--preemption still apply on top)",
    )
    p.add_argument(
        "--driver",
        choices=["auto", "monolithic", "epochs"],
        default=None,
        help="auction driver override (profiles.SchedulingProfile.driver): auto/monolithic = one jit program with the in-jit size chain; epochs = host-driven size shrinking for boundary-cheap environments",
    )
    p.add_argument("--max-rounds", type=int, default=None, help="auction round cap override (profiles default: 32)")
    p.add_argument("--leader-elect", action="store_true", help="lease-based leader election: only the lease holder schedules; standbys keep caches warm and take over on leader loss")
    p.add_argument("--lease-name", default="tpu-scheduler", help="leader-election lease name")
    p.add_argument("--lease-duration", type=float, default=15.0, help="lease TTL (seconds) — the leader lease, or each shard lease with --shards")
    p.add_argument("--identity", default=None, help="leader-election holder identity (default: derived from pid)")
    p.add_argument(
        "--shards",
        type=int,
        default=1,
        help="active-active sharded control plane: partition the pending set into K stable-hash shards, each owned "
        "via its own tpu-scheduler-shard-<i> lease — run several replicas with the same K and they split the shards; "
        "supersedes --leader-elect (runtime/shards.py)",
    )
    p.add_argument(
        "--replica-id",
        default=None,
        help="this replica's identity for shard-lease ownership (default: --identity, then pid-derived)",
    )
    p.add_argument(
        "--preemption",
        action="store_true",
        help="evict strictly-lower-priority pods when a cycle leaves higher-priority pods resource-starved (kube PostFilter)",
    )
    p.add_argument(
        "--pool-key",
        default=None,
        help="node label partitioning the cluster into per-pool scheduling shards (expert-parallel routing; pods pinning the label route to their pool's shard)",
    )
    p.add_argument(
        "--topology-file",
        default=None,
        help="JSON interconnect-topology spec (levels + optional node->domain map, topology/model.py) for "
        "rank-aware gang co-placement; default: auto-detect from the topology.tpu-scheduler/{slice,rack} node labels",
    )
    p.add_argument(
        "--no-topology",
        action="store_true",
        help="disable topology-aware gang scoring even when nodes carry topology labels",
    )
    p.add_argument("--nodes", type=int, default=100, help="synthetic cluster: node count")
    p.add_argument("--pods", type=int, default=1000, help="synthetic cluster: pending pods")
    p.add_argument("--bound-pods", type=int, default=0, help="synthetic cluster: pre-bound pods")
    p.add_argument("--seed", type=int, default=0, help="synthetic cluster seed")
    p.add_argument(
        "--workload",
        default="plain",
        choices=["plain", "mixed"],
        help="synthetic workload shape: 'mixed' exercises the full feature surface "
        "(selectors, taints, node+pod affinity hard+soft, spread, gangs, extended TPU-chip requests)",
    )
    p.add_argument("--cycles", type=int, default=None, help="max scheduling cycles (default: run until settled)")
    p.add_argument("--daemon", action="store_true", help="serve forever: never exit on settle, idle between cycles (reference main.rs:146-149)")
    p.add_argument(
        "--pipeline",
        action="store_true",
        help="overlap binding POSTs with the next cycle's pack+solve via an assumed-bindings cache (host<->device pipelining; plain unconstrained cycles — routed/constrained cycles bind synchronously)",
    )
    p.add_argument("--interval", type=float, default=1.0, help="daemon mode: idle sleep between settled cycles (seconds)")
    p.add_argument("--attempts", type=int, default=ATTEMPTS, help="sample policy: candidates per pod (reference ATTEMPTS)")
    p.add_argument(
        "--requeue-seconds",
        type=float,
        default=REQUEUE_SECONDS,
        help="failed-pod backoff base: per-failure-class exponential delays scale on it (runtime/resilience.py); 0 retries immediately",
    )
    p.add_argument(
        "--breaker-open-seconds",
        type=float,
        default=5.0,
        help="circuit breaker: first open window after tripping (escalates x2 while probes fail, capped at 60s)",
    )
    p.add_argument(
        "--breaker-window",
        type=int,
        default=20,
        help="circuit breaker: rolling bind/watch outcome window the failure ratio trips on",
    )
    p.add_argument(
        "--no-breaker",
        action="store_true",
        help="disable the API circuit breaker (every bind POSTs immediately, brownout or not)",
    )
    p.add_argument(
        "--flush-capacity",
        type=int,
        default=4096,
        help="degraded mode: max binding POSTs deferred while the breaker is open (overflow requeues instead)",
    )
    p.add_argument("--no-fallback", action="store_true", help="disable tpu->native failure fallback")
    p.add_argument(
        "--no-delta",
        action="store_true",
        help="disable the incremental delta-scheduling engine: every cycle runs the classic full-wave pack+solve",
    )
    p.add_argument(
        "--rebalance",
        action="store_true",
        help="enable the background rebalancer (tpu_scheduler/rebalance): a cadence-gated packing solve on a "
        "worker thread proposing bounded defragmentation migration batches (unbind -> cordon -> delta re-place)",
    )
    p.add_argument(
        "--rebalance-every",
        type=int,
        default=8,
        metavar="CYCLES",
        help="rebalancer cadence: cycles between background ticks (with --rebalance)",
    )
    p.add_argument(
        "--rebalance-batch",
        type=int,
        default=8,
        metavar="N",
        help="max migrations issued per rebalancer tick (whole-node drain groups; with --rebalance)",
    )
    p.add_argument(
        "--autoscale",
        action="store_true",
        help="enable the closed-loop autoscaler (tpu_scheduler/autoscale) against the simulated cloud provider: "
        "cost-aware SKU packing on SLO burn, scale-down through the drain protocol (synthetic cluster only)",
    )
    p.add_argument(
        "--catalog-file",
        default=None,
        metavar="PATH",
        help="JSON SKU catalog for --autoscale (name/cpu/mem_gi/hourly_cost/quota/provision_s/...); default: built-in catalog",
    )
    p.add_argument("--log-level", default="INFO")
    p.add_argument(
        "--log-format",
        choices=["text", "json"],
        default="text",
        help="log line format: 'json' emits one machine-parseable JSON object per line (ts, level, logger, msg, cycle)",
    )
    p.add_argument(
        "--events-buffer",
        type=int,
        default=4096,
        help="flight recorder capacity (max pod timelines retained for the /debug routes); 0 disables recording",
    )
    p.add_argument("--profile-dir", default=None, help="write a jax.profiler trace of the cycles here")
    p.add_argument("--checkpoint-dir", default=None, help="restore scheduler state from here at startup, save at exit")
    p.add_argument("--http-port", type=int, default=None, help="serve /metrics, /healthz and the k8s REST surface on this port")
    p.add_argument("--api-server", default=None, help="schedule against a remote k8s-style REST endpoint (URL) instead of the synthetic in-process cluster")
    p.add_argument("--api-token", default=None, help="bearer token for --api-server")
    p.add_argument(
        "--kubeconfig",
        default=None,
        help="schedule against the cluster this kubeconfig points at (server/token/CA/client-cert resolution; "
        "default resolution when given without a value is $KUBECONFIG -> ~/.kube/config -> in-cluster)",
        nargs="?",
        const="",
    )
    p.add_argument("--kube-context", default=None, help="kubeconfig context to use (default: current-context)")
    p.add_argument(
        "--allow-exec-auth",
        action="store_true",
        help="allow kubeconfig exec: credential plugins (spawns the configured helper binary; off by default)",
    )
    return p


def main(argv: list[str] | None = None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "sim":
        # Deterministic cluster simulator + chaos harness (sim/):
        #   python -m tpu_scheduler.cli sim --scenario burst-storm --seed 3
        from .sim.cli import main as sim_main

        return sim_main(argv[1:])
    args = build_parser().parse_args(argv)
    configure_logging(args.log_level, args.log_format)

    from .utils.gc_tuning import enable_daemon_gc_tuning

    enable_daemon_gc_tuning()

    import os

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # Honor an explicit CPU pin even where a platform plugin's
        # sitecustomize force-registers itself ahead of the env var (the
        # axon TPU tunnel does): flipping jax.config before any device use
        # is the only reliable off-switch.  Without this, test-suite CLI
        # subprocesses quietly ran on the real chip — and hung for ~25 min
        # whenever the tunnel was wedged.
        import jax

        jax.config.update("jax_platforms", "cpu")

    if args.backend in ("tpu", "tpu-sharded"):
        from .utils.compile_cache import enable_compilation_cache

        enable_compilation_cache()

    if args.kubeconfig is not None:
        # Real-cluster path (reference main.rs:130 Client::try_default):
        # kubeconfig resolution gives server + auth + TLS in one step.
        from .runtime.http_api import RemoteApiAdapter
        from .runtime.kubeconfig import client_from_kubeconfig

        api = RemoteApiAdapter(
            client_from_kubeconfig(args.kubeconfig or None, context=args.kube_context, allow_exec=args.allow_exec_auth)
        )
    elif args.api_server:
        from .runtime.http_api import KubeApiClient, RemoteApiAdapter

        api = RemoteApiAdapter(KubeApiClient(args.api_server, token=args.api_token))
    else:
        api = FakeApiServer()
        mixed = (
            dict(
                selector_fraction=0.25,
                anti_affinity_fraction=0.1,
                spread_fraction=0.1,
                tainted_fraction=0.15,
                node_affinity_fraction=0.15,
                soft_taint_fraction=0.15,
                preferred_affinity_fraction=0.15,
                schedule_anyway_fraction=0.1,
                gang_fraction=0.1,
                pod_affinity_fraction=0.1,
                preferred_pod_affinity_fraction=0.15,
                extended_fraction=0.15,
            )
            if args.workload == "mixed"
            else {}
        )
        snap = synth_cluster(
            n_nodes=args.nodes, n_pending=args.pods, n_bound=args.bound_pods, seed=args.seed, **mixed
        )
        api.load(snap.nodes, snap.pods)

    if args.distributed or args.backend == "tpu-sharded":
        from .parallel.mesh import init_distributed

        # No-op in single-process runs; multi-host coordination comes from
        # the SCHED_* env (or cluster auto-detection with --distributed).
        init_distributed(auto=args.distributed)

    if args.backend == "native":
        backend = NativeBackend()
        fallback = None
    elif args.backend == "tpu-sharded":
        from .parallel.sharded import ShardedBackend

        backend = ShardedBackend(tp=args.tp)
        fallback = None if args.no_fallback else NativeBackend()
    else:
        from .backends.tpu import TpuBackend

        backend = TpuBackend()
        fallback = None if args.no_fallback else NativeBackend()

    if args.profile_file:
        # Distilled tuned weights (tpu_scheduler/learn): same dataclass,
        # same fused choose path — zero inference cost by construction.
        from .models.profiles import SchedulingProfile

        profile = SchedulingProfile.from_file(args.profile_file)
    else:
        profile = PROFILES[args.profile]
    if args.driver is not None:
        profile = profile.with_(driver=args.driver)
    if args.max_rounds is not None:
        profile = profile.with_(max_rounds=args.max_rounds)
    if args.pool_key:
        profile = profile.with_(pool_key=args.pool_key)
    if args.preemption:
        profile = profile.with_(preemption=True)
    from .runtime.resilience import BreakerConfig

    breaker_config = BreakerConfig(
        window=args.breaker_window,
        open_seconds=args.breaker_open_seconds,
        # A ratio above 1 can never be reached: --no-breaker keeps the
        # machinery (metrics, /debug/resilience) but never trips it.
        failure_ratio=2.0 if args.no_breaker else BreakerConfig.failure_ratio,
    )
    if args.no_topology:
        topology = None
    elif args.topology_file:
        from .topology.model import load_topology_file

        topology = load_topology_file(args.topology_file)
    else:
        topology = "auto"
    rebalance_cfg = None
    if args.rebalance:
        from .rebalance import RebalanceConfig

        # Daemon mode runs the packing solve on a worker thread so the
        # background tier stays off the cycle critical path.
        rebalance_cfg = RebalanceConfig(every=args.rebalance_every, batch=args.rebalance_batch, background=True)
    autoscale_cfg = None
    autoscale_provider = None
    if args.autoscale:
        if args.api_server or args.kubeconfig is not None:
            # The simulated provider joins nodes through the in-process
            # apiserver; a remote cluster owns its own node lifecycle.
            print(json.dumps({"autoscale": False, "reason": "remote cluster"}), file=sys.stderr)
        else:
            import time as _time

            from .autoscale import DEFAULT_CATALOG, AutoscaleConfig, SimCloudProvider, load_catalog

            catalog = load_catalog(args.catalog_file) if args.catalog_file else DEFAULT_CATALOG
            autoscale_provider = SimCloudProvider(
                api, clock=_time.monotonic, rng=random.Random(args.seed), catalog=catalog
            )
            # Daemon mode plans the catalog what-if on a worker thread so
            # the elastic tier stays off the cycle critical path.
            autoscale_cfg = AutoscaleConfig(background=True)
    sched = Scheduler(
        api,
        backend,
        profile=profile,
        policy=args.policy,
        topology=topology,
        attempts=args.attempts,
        requeue_seconds=args.requeue_seconds,
        fallback_backend=fallback,
        pipeline=args.pipeline,
        leader_elect=args.leader_elect,
        identity=args.replica_id or args.identity,
        lease_name=args.lease_name,
        lease_duration=args.lease_duration,
        shards=args.shards,
        events_buffer=args.events_buffer,
        breaker_config=breaker_config,
        flush_capacity=args.flush_capacity,
        delta=not args.no_delta,
        rebalance=rebalance_cfg,
        autoscale=autoscale_cfg,
        autoscale_provider=autoscale_provider,
    )
    if args.profile_dir:
        # Link the device trace from /debug/trace's Chrome-trace JSON so the
        # host and device timelines open side by side in Perfetto.
        sched.recorder.device_trace_dir = args.profile_dir

    if args.checkpoint_dir:
        from .runtime.checkpoint import restore_scheduler

        restore_scheduler(sched, args.checkpoint_dir)
    # Counters restored from a checkpoint are all-time totals; remember the
    # starting point so the summary line reports *this run's* work.
    counters_at_start = sched.metrics.snapshot()

    http_server = None
    if args.http_port is not None:
        from .runtime.http_api import HttpApiServer

        # Against a remote cluster we serve metrics/health only — the remote
        # API server owns the cluster state.
        local_api = None if (args.api_server or args.kubeconfig is not None) else api
        # /debug/profile serves through a replica registry so multi-replica
        # deployments can aggregate (?replica= selects); a single replica
        # registers just itself.
        from .utils.profiler import ReplicaLatencyRegistry, ReplicaProfileRegistry

        profile_registry = ReplicaProfileRegistry()
        profile_registry.register(sched.identity, sched.profile_snapshot)
        # /debug/latency aggregates the same way (time-to-bind waterfall).
        latency_registry = ReplicaLatencyRegistry()
        latency_registry.register(sched.identity, sched.latency_snapshot)
        http_server = HttpApiServer(
            local_api,
            metrics=sched.metrics,
            recorder=sched.recorder,
            resilience=sched.resilience_snapshot,
            shards=sched.shards_snapshot,
            profile=profile_registry.snapshot,
            pending_ages=sched.pending_age_debug,
            rebalance=sched.rebalance_snapshot if sched.rebalancer is not None else None,
            autoscale=sched.autoscale_snapshot if sched.autoscaler is not None else None,
            latency=latency_registry.snapshot,
            port=args.http_port,
        ).start()
        print(json.dumps({"http": True, "url": http_server.base_url}), file=sys.stderr)

    from .utils.tracing import device_profile

    try:
        with device_profile(args.profile_dir):
            if args.daemon:
                try:
                    metrics = sched.run(max_cycles=args.cycles, daemon_interval=args.interval)
                except KeyboardInterrupt:
                    metrics = []  # per-cycle history not kept in daemon mode; counters survive below
            else:
                metrics = sched.run(max_cycles=args.cycles, until_settled=args.cycles is None)
    finally:
        if args.checkpoint_dir:
            from .runtime.checkpoint import save_scheduler

            save_scheduler(sched, args.checkpoint_dir)
        if http_server is not None:
            http_server.stop()
        sched.close()  # drain in-flight pipelined binds, stop the worker

    for m in metrics:
        print(m.to_json())
    counters = sched.metrics.snapshot()
    # In daemon mode the per-cycle history is truncated (and empty after a
    # Ctrl-C), so this run's totals come from counter deltas vs startup
    # (checkpoint restore pre-loads all-time totals).
    run_total = lambda name: counters.get(name, 0) - counters_at_start.get(name, 0)  # noqa: E731
    summary = {
        "summary": True,
        "backend": args.backend,
        "policy": args.policy,
        "cycles": run_total("scheduler_cycles_total"),
        "bound_total": run_total("scheduler_pods_bound_total"),
        "unschedulable_last": metrics[-1].unschedulable if metrics else 0,
        "counters": counters,
    }
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
