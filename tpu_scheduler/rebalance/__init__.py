"""Background rebalancer — the placement-QUALITY tier of the two-tier solver.

The incremental delta engine (tpu_scheduler/delta) bought steady-state
latency by giving up global optimality: placements are greedy-incremental
and fragmentation accumulates unchecked over long horizons.  This package
is the second tier — a continuous background full-wave packing solve over a
consistent snapshot that proposes BOUNDED defragmentation migration
batches, executed as deschedule → breaker-gated unbind → delta-engine
re-place so every migration flows through the existing DeltaIndex
invalidation closure and SolveState ledger (commit-exactly-once, crash-safe
under replica kill and brownout).

Modules:
  snapshot.py  — RebalanceSnapshot: the consistent packing view (movable
                 victims, pinned mass, receiver eligibility)
  solver.py    — the packing solve: whole-node drains via first-fit-
                 decreasing, packing-efficiency / stranded-capacity math
  planner.py   — RebalanceConfig, the closed migration-reason and skip
                 taxonomies, batch selection (whole-node groups)
  executor.py  — Rebalancer: cadence + SLO-burn/backlog/breaker throttles,
                 the unbind-then-cordon drain protocol, the in-flight
                 ledger, inline and background-thread solve modes
  whatif.py    — autoscaler what-if: node-add / node-remove policies the
                 packing tier makes answerable
"""

from .executor import REBALANCE_CORDON_LABEL, Rebalancer
from .planner import MIGRATION_REASONS, SKIP_REASONS, RebalanceConfig
from .snapshot import RebalanceSnapshot
from .solver import Migration, PackingPlan, packing_stats, solve_packing
from .whatif import autoscaler_whatif

__all__ = [
    "MIGRATION_REASONS",
    "SKIP_REASONS",
    "REBALANCE_CORDON_LABEL",
    "Migration",
    "PackingPlan",
    "RebalanceConfig",
    "RebalanceSnapshot",
    "Rebalancer",
    "autoscaler_whatif",
    "packing_stats",
    "solve_packing",
]
