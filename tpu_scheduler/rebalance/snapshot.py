"""RebalanceSnapshot — the consistent packing view one background solve
reads.

Built once per solve from the cycle's (immutable) ClusterSnapshot, so the
background thread can hold it safely while the cycle loop moves on — the
shared-cache stance the delta engine's ``_reduced_view`` established.

Victim taxonomy (conservative by construction — a migration may only ever
move a pod whose placement is purely resource-driven):

  • **movable** — bound, not a gang member (gangs admit all-or-nothing and
    never migrate piecewise), no nodeSelector / required node affinity, no
    anti-affinity / pod-affinity / topology-spread (moving a constrained
    pod could invalidate a placement the solve cannot see), no extended
    resources (the two fixed axes are the packing vocabulary), not
    selected by any PodDisruptionBudget (migrations are voluntary
    disruptions; protected workloads are simply never victims), and not
    vetoed by the caller's ``victim_ok`` (deferred/assumed binds, shard
    ownership).  Soft preferences (preferred affinity, PreferNoSchedule)
    do not pin: they bias scores, never feasibility.
  • **pinned** — every other bound pod.  A node hosting any pinned mass
    can never be drained empty, so it is excluded from drain candidacy
    outright (a partial drain shrinks nothing).

Receiver eligibility (``dest_ok``): schedulable (not cordoned) and free of
NoSchedule/NoExecute taints — movable pods carry no tolerations
requirement, so any hard taint excludes the node for all of them.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..api.objects import Pod, full_name, total_pod_resources
from ..core.snapshot import ClusterSnapshot, node_allocatable, node_used_resources

__all__ = ["RebalanceSnapshot", "is_movable"]


# shape: (pod: obj) -> bool
def _spec_pins(pod: Pod) -> bool:
    """Does the pod's own spec pin it (constraint-driven placement)?"""
    s = pod.spec
    if s is None:
        return True
    return bool(
        s.gang
        or s.node_selector
        or s.node_affinity
        or s.anti_affinity
        or s.pod_affinity
        or s.topology_spread
    )


# shape: (pod: obj, pdbs: obj, victim_ok: obj) -> bool
def is_movable(pod: Pod, pdbs=(), victim_ok=None) -> bool:
    """The closed victim test (see the module docstring's taxonomy)."""
    if _spec_pins(pod):
        return False
    req = total_pod_resources(pod)
    if req.extended and any(v for v in req.extended.values()):
        return False
    if victim_ok is not None and not victim_ok(full_name(pod)):
        return False
    if pdbs:
        from ..runtime.controller import _pdb_matches

        if any(_pdb_matches(b, pod) for b in pdbs):
            return False
    return True


# shape: (node: obj) -> bool
def _dest_ok(node) -> bool:
    if node.spec is None:
        return True
    if node.spec.unschedulable:
        return False
    for t in node.spec.taints or ():
        if t.effect in ("NoSchedule", "NoExecute"):
            return False
    return True


@dataclass(frozen=True)
class RebalanceSnapshot:
    """One consistent packing view: exact-int capacity over two fixed axes
    (cpu millicores, memory bytes — the same scalars ``fits_in`` compares),
    the movable victim list, and per-node drain/receive eligibility."""

    node_names: tuple[str, ...]
    alloc: np.ndarray  # [N, 2] i64 — allocatable (cpu_m, mem_bytes)
    used: np.ndarray  # [N, 2] i64 — ALL bound demand (movable + pinned)
    pinned: np.ndarray  # [N] bool — node hosts non-movable bound mass
    dest_ok: np.ndarray  # [N] bool — schedulable receiver
    # (pod_full, node row, cpu_m, mem_bytes) per movable pod, sorted by
    # (node row, pod name) so every downstream order is deterministic.
    movable: tuple[tuple[str, int, int, int], ...]

    # shape: (snapshot: obj, pdbs: obj, victim_ok: obj) -> obj
    @staticmethod
    def build(snapshot: ClusterSnapshot, pdbs=(), victim_ok=None) -> "RebalanceSnapshot":
        nodes = snapshot.nodes
        names = tuple(n.name for n in nodes)
        row = {name: i for i, name in enumerate(names)}
        n = len(names)
        alloc = np.zeros((n, 2), dtype=np.int64)
        used = np.zeros((n, 2), dtype=np.int64)
        dest = np.zeros((n,), dtype=bool)
        for i, node in enumerate(nodes):
            a = node_allocatable(node, snapshot)
            u = node_used_resources(snapshot, node.name)
            alloc[i] = (a.cpu, a.memory)
            used[i] = (u.cpu, u.memory)
            dest[i] = _dest_ok(node)
        pinned = np.zeros((n,), dtype=bool)
        movable: list[tuple[str, int, int, int]] = []
        for pod, node in snapshot.placed_pods():
            i = row.get(node.name)
            if i is None:
                continue
            if is_movable(pod, pdbs, victim_ok):
                req = total_pod_resources(pod)
                movable.append((full_name(pod), i, int(req.cpu), int(req.memory)))
            else:
                pinned[i] = True
        movable.sort(key=lambda m: (m[1], m[0]))
        return RebalanceSnapshot(
            node_names=names, alloc=alloc, used=used, pinned=pinned, dest_ok=dest, movable=tuple(movable)
        )
