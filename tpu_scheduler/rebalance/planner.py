"""Rebalance policy: config knobs, the closed migration/skip taxonomies,
and batch selection.

The taxonomies are drift-gated against the README "Rebalancing &
defragmentation" catalogue by the REBL analyze rule (the METR pattern), so
a new reason cannot ship undocumented.

**Migration reasons** (why a pod is descheduled):
  defrag-drain — its node drains empty so the occupied set shrinks
  rack-defrag  — same, and the node was its coarsest topology domain's
                 LAST occupied node: the drain frees the whole rack

**Skip reasons** (why a tick did less than it could):
  breaker-open  — the API circuit breaker is not closed; migrations never
                  compete with a browned-out server
  slo-burn      — a priority tier's pending-age burn rate crossed the
                  limit; rebalancing yields to the backlog
  backlog       — the pending set exceeds ``max_pending``; same stance
  inflight      — a previous batch's pods are still awaiting re-placement
                  (bounded disruption: one batch in flight)
  budget        — the lifetime migration budget is spent
  api-error     — a control read (PDB list) failed; the tick stands down
  no-gain       — the solve found nothing worth draining
  victim-moved  — a planned victim's placement changed under the plan; its
                  node group is abandoned (the next solve sees the truth)
  unbind-failed — a deschedule POST failed; the group's drain is aborted
                  (the node is NOT cordoned with pods still on it)
"""

from __future__ import annotations

from dataclasses import dataclass

__all__ = ["MIGRATION_REASONS", "SKIP_REASONS", "RebalanceConfig", "select_batch", "throttle_reason"]

MIGRATION_REASONS = (
    "defrag-drain",
    "rack-defrag",
)

# protocol: taxonomy SKIP_REASONS producers=_skip,throttle_reason scope=tpu_scheduler/rebalance
SKIP_REASONS = (
    "breaker-open",
    "slo-burn",
    "backlog",
    "inflight",
    "budget",
    "api-error",
    "no-gain",
    "victim-moved",
    "unbind-failed",
)


@dataclass(frozen=True)
class RebalanceConfig:
    """The rebalancer's knobs (catalogued in the README section)."""

    every: int = 8  # cycles between background ticks (the cadence)
    batch: int = 8  # max migrations issued per tick (whole-node groups)
    burn_limit: float = 0.5  # max per-tier SLO burn rate before standing down
    max_pending: int = 8  # max pending backlog before standing down
    max_migrations: int = 0  # lifetime migration budget (0 = unbounded)
    max_plan: int = 256  # migrations per solve (bounds solver work)
    headroom: float = 0.9  # receiver fill cap the projection packs to
    stale_after: int = 32  # ticks before an unplaced migration counts stalled
    background: bool = False  # solve on a worker thread (daemon mode)


# shape: (breaker_mode: obj, burn: float, backlog: int, inflight: int,
#   executed: int, cfg: obj) -> obj
def throttle_reason(breaker_mode, burn: float, backlog: int, inflight: int, executed: int, cfg: RebalanceConfig):
    """The tick-level stand-down decision, most urgent reason first; None
    means the tick may solve and migrate."""
    if breaker_mode != "closed":
        return "breaker-open"
    if burn >= cfg.burn_limit:
        return "slo-burn"
    if backlog > cfg.max_pending:
        return "backlog"
    if inflight:
        return "inflight"
    if cfg.max_migrations and executed >= cfg.max_migrations:
        return "budget"
    return None


# shape: (plan: obj, batch: int, budget_left: int) -> obj
def select_batch(plan, batch: int, budget_left: int = 0) -> list:
    """Whole-node migration groups for one tick, in plan (drain) order.

    A node's drain is never split across ticks — an emptied node is the
    unit of progress — so groups are taken whole while they fit the batch;
    the FIRST group is taken even when it alone exceeds ``batch`` (a node
    needing more moves than the batch size must still be drainable).
    ``budget_left`` (0 = unbounded) additionally caps the total."""
    groups: dict[str, list[Migration]] = {}
    order: list[str] = []
    for m in plan.migrations:
        if m.src not in groups:
            groups[m.src] = []
            order.append(m.src)
        groups[m.src].append(m)
    out: list[list[Migration]] = []
    taken = 0
    for src in order:
        g = groups[src]
        if budget_left and taken + len(g) > budget_left:
            break
        if out and taken + len(g) > batch:
            break
        out.append(g)
        taken += len(g)
        if taken >= batch:
            break
    return out
