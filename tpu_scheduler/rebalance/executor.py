"""Rebalancer — the background tier's control loop.

One ``tick`` per scheduler cycle (cheap no-op off the cadence), run AFTER
the cycle's scheduling work so the tier never sits on the critical path,
and — in daemon mode (``RebalanceConfig.background``) — with the packing
solve itself on a worker thread against the immutable snapshot view.

The drain protocol (per planned node, within ONE tick so no scheduling can
interleave):

  1. verify the node still hosts exactly the planned pods (anything else
     moved under the plan → ``victim-moved``, group abandoned);
  2. breaker-gated UNBIND of each pod (a 5xx/transport failure aborts the
     group — ``unbind-failed`` — and the node is NOT cordoned with pods
     still on it); each descheduled pod becomes Pending and flows through
     the reflector → DeltaIndex invalidation closure → SolveState release
     → delta-engine re-place, exactly like any watch event;
  3. cordon the now-EMPTY node with the ``REBALANCE_CORDON_LABEL`` marker
     so the spreading score cannot scatter the re-placements straight back
     — the occupied set shrinks monotonically.  Labeled nodes are the
     autoscaler's scale-down candidates (whatif.py).

Crash safety: there is NO rebalancer-private durable state.  A crash
between unbinds leaves pods Pending (owned by the normal scheduling path —
never orphaned); a crash after cordon leaves a labeled empty node any
successor's rebalancer recognizes (and pressure-release uncordons).  The
commit-exactly-once story is the SolveState ledger's: the unbind is one
CAS-guarded API call, and re-placement is an ordinary delta-cycle commit.

Pressure release: when the SLO burn rate or the pending backlog crosses the
throttle, the tick UNCORDONS every labeled node before standing down —
reserve capacity returns to the cluster the moment demand needs it (the
node-remove half of the autoscaler loop, inverted on demand).
"""

from __future__ import annotations

import threading
import time

from ..utils.tracing import span
from .planner import SKIP_REASONS, RebalanceConfig, select_batch, throttle_reason
from .snapshot import RebalanceSnapshot
from .solver import solve_packing

__all__ = ["REBALANCE_CORDON_LABEL", "Rebalancer"]

# Node-label marker on rebalancer-drained (cordoned) nodes: distinguishes
# them from operator cordons, survives crashes, and names the scale-down
# candidate set the autoscaler what-if reads.
REBALANCE_CORDON_LABEL = "rebalance.tpu-scheduler/drained"


# protocol: machine drain-migration field=- init=verify
# protocol: states: verify | unbound | cordoned | replaced | aborted
# protocol: verify -> unbound | aborted
# protocol: unbound -> cordoned | replaced | aborted
# protocol: cordoned -> replaced | aborted
# protocol: var bound: 0..1 = 1
# protocol: var pending: 0..1 = 0
# protocol: action unbind: verify -> unbound requires bound == 1 effect bound = 0, pending = 1
# protocol: action skip: verify -> aborted
# protocol: action cordon: unbound -> cordoned
# protocol: action replace: unbound -> replaced requires pending == 1 effect pending = 0, bound = 1
# protocol: action replace-cordoned: cordoned -> replaced requires pending == 1 effect pending = 0, bound = 1
# protocol: env crash: verify -> aborted
# protocol: env crash-unbound: unbound -> aborted
# protocol: env crash-cordoned: cordoned -> aborted
# protocol: action rescue: aborted -> aborted requires pending == 1 effect pending = 0, bound = 1
# protocol: invariant never-orphaned: bound == 1 or pending == 1
# protocol: progress pending-replaced: pending == 1
class Rebalancer:
    """Owns the cadence, throttles, in-flight ledger, and lifetime stats.
    Written only by the owning scheduler's cycle loop; the HTTP debug
    thread reads GIL-atomic copies via ``stats()``.

    The ``# protocol:`` contract above models one victim pod through the
    verify→unbind→cordon→re-place drain (model-only: per-pod state lives
    in the ``inflight`` ledger rows, not a field).  The unbind CAS
    atomically turns a bound pod into a pending one (``effect bound = 0,
    pending = 1``), so MODL proves ``never-orphaned`` — at every reachable
    point, including a scheduler crash between any two steps, the pod is
    either still bound or pending for the normal scheduling path
    (``rescue``) to place.  ``pending-replaced`` proves a pending victim
    can never wedge."""

    def __init__(self, config: RebalanceConfig | None = None, metrics=None):
        self.config = config or RebalanceConfig()
        self.metrics = metrics
        # pod full name -> {"src", "reason", "tick"} per issued migration
        # awaiting re-placement (at most one batch outstanding).
        self.inflight: dict[str, dict] = {}
        self.solves = 0
        self.planned = 0
        self.executed = 0
        self.completed = 0
        self.vanished = 0
        self.stalled = 0
        self.nodes_drained = 0
        self.pressure_releases = 0
        self.skips: dict[str, int] = {}
        self.last_plan: dict = {}
        self._tick = 0
        # Wall-clock solve times (bench / debug evidence only — NEVER on
        # the scorecard, which must stay byte-identical).
        self.solve_walls: list[float] = []
        # Background mode: one worker, one (snapshot, topo, pdbs) request
        # slot, one finished plan slot.
        self._bg_lock = threading.Lock()
        self._bg_request = None  # guarded-by: _bg_lock
        self._bg_plan = None  # guarded-by: _bg_lock
        self._bg_event = threading.Event()
        self._bg_thread: threading.Thread | None = None
        self._bg_stop = False

    # -- bookkeeping --------------------------------------------------------

    def _skip(self, reason: str) -> None:
        assert reason in SKIP_REASONS, reason
        self.skips[reason] = self.skips.get(reason, 0) + 1
        if self.metrics is not None:
            self.metrics.inc("scheduler_rebalance_skips_total", labels={"reason": reason})

    # shape: (self: obj, snapshot: obj) -> int
    def reconcile(self, snapshot) -> int:
        """Resolve the in-flight ledger against the live snapshot: a pod
        bound again is a COMPLETED migration; a pod gone entirely counts
        vanished (the workload deleted it mid-flight — not an orphan, there
        is nothing left to place); a pod pending past ``stale_after`` ticks
        counts stalled and is dropped from the ledger (the normal
        scheduling path owns it either way).  Returns completions."""
        if not self.inflight:
            return 0
        from ..api.objects import full_name, is_pod_bound

        by_full = {full_name(p): p for p in snapshot.pods}
        done = 0
        for pf in list(self.inflight):
            p = by_full.get(pf)
            if p is not None and is_pod_bound(p):
                del self.inflight[pf]
                self.completed += 1
                done += 1
                if self.metrics is not None:
                    self.metrics.inc("scheduler_rebalance_migrations_completed_total")
            elif p is None:
                del self.inflight[pf]
                self.vanished += 1
            elif self._tick - self.inflight[pf]["tick"] >= self.config.stale_after:
                del self.inflight[pf]
                self.stalled += 1
        return done

    # -- the background solve seam -----------------------------------------

    def _bg_loop(self) -> None:
        while True:
            self._bg_event.wait()
            self._bg_event.clear()
            with self._bg_lock:
                if self._bg_stop:
                    return
                req, self._bg_request = self._bg_request, None
            if req is None:
                continue
            rs, topo = req
            t0 = time.perf_counter()
            plan = solve_packing(rs, topo, max_migrations=self.config.max_plan, headroom=self.config.headroom)
            wall = time.perf_counter() - t0
            with self._bg_lock:
                self._bg_plan = plan
                self.solve_walls.append(wall)

    def _solve(self, rs: RebalanceSnapshot, topo):
        """Inline mode: solve now.  Background mode: hand the request to
        the worker and return a previously finished plan if one is ready
        (None otherwise — this tick stands down and a later tick consumes
        the result)."""
        if not self.config.background:
            t0 = time.perf_counter()
            plan = solve_packing(rs, topo, max_migrations=self.config.max_plan, headroom=self.config.headroom)
            self.solve_walls.append(time.perf_counter() - t0)
            return plan
        if self._bg_thread is None:
            self._bg_thread = threading.Thread(target=self._bg_loop, daemon=True)
            self._bg_thread.start()
        with self._bg_lock:
            ready, self._bg_plan = self._bg_plan, None
            if ready is None and self._bg_request is None:
                self._bg_request = (rs, topo)
                self._bg_event.set()
        return ready

    def close(self) -> None:
        if self._bg_thread is not None:
            with self._bg_lock:
                self._bg_stop = True
            self._bg_event.set()
            self._bg_thread.join(timeout=5.0)
            self._bg_thread = None

    # -- the tick -----------------------------------------------------------

    # shape: (self: obj, snapshot: obj, topo: obj, pdbs: obj, burn: float,
    #   backlog: int, breaker_mode: obj, unbind: obj, cordon: obj,
    #   uncordon: obj, victim_ok: obj) -> int
    def tick(
        self,
        snapshot,
        *,
        topo=None,
        pdbs=(),
        burn: float = 0.0,
        backlog: int = 0,
        breaker_mode: str = "closed",
        unbind=None,
        cordon=None,
        uncordon=None,
        victim_ok=None,
    ) -> int:
        """One background-tier step (see the module docstring's protocol).
        ``pdbs=None`` means the PDB read failed — the tick stands down
        (``api-error``) rather than migrate a possibly protected pod.
        Returns the number of migrations issued this tick."""
        self._tick += 1
        self.reconcile(snapshot)
        on_cadence = self.config.every <= 1 or (self._tick % self.config.every) == 0
        if not on_cadence:
            return 0
        reason = throttle_reason(breaker_mode, burn, backlog, len(self.inflight), self.executed, self.config)
        if reason in ("slo-burn", "backlog") and uncordon is not None:
            released = 0
            for node in snapshot.nodes:
                if (node.metadata.labels or {}).get(REBALANCE_CORDON_LABEL) and uncordon(node):
                    released += 1
            if released:
                self.pressure_releases += released
                if self.metrics is not None:
                    self.metrics.inc("scheduler_rebalance_pressure_releases_total", released)
        if reason is not None:
            self._skip(reason)
            return 0
        if pdbs is None:
            self._skip("api-error")
            return 0
        with span("snapshot"):
            rs = RebalanceSnapshot.build(snapshot, pdbs, victim_ok)
        with span("solve"):
            plan = self._solve(rs, topo)
        if plan is None:
            return 0  # background solve pending — neither work nor a skip
        self.solves += 1
        self.planned += len(plan.migrations)
        self.last_plan = {
            "migrations": len(plan.migrations),
            "drained": len(plan.drained),
            "efficiency_before": plan.before["efficiency"],
            "efficiency_after": plan.after["efficiency"],
        }
        if self.metrics is not None:
            self.metrics.inc("scheduler_rebalance_solves_total")
        if not plan.migrations:
            self._skip("no-gain")
            return 0
        with span("plan"):
            budget_left = 0
            if self.config.max_migrations:
                budget_left = max(0, self.config.max_migrations - self.executed)
            groups = select_batch(plan, self.config.batch, budget_left)
        issued = 0
        with span("migrate"):
            from ..api.objects import full_name

            bound_by_node: dict[str, set[str]] = {}
            for p, node in snapshot.placed_pods():
                bound_by_node.setdefault(node.name, set()).add(full_name(p))
            for g in groups:
                src = g[0].src
                if bound_by_node.get(src, set()) != {m.pod_full for m in g}:
                    self._skip("victim-moved")
                    continue
                drained_clean = True
                for m in g:
                    if unbind is None or not unbind(m.pod_full, m.src):
                        self._skip("unbind-failed")
                        drained_clean = False
                        break
                    self.inflight[m.pod_full] = {"src": m.src, "reason": m.reason, "tick": self._tick}
                    self.executed += 1
                    issued += 1
                    if self.metrics is not None:
                        self.metrics.inc("scheduler_rebalance_migrations_total", labels={"reason": m.reason})
                if drained_clean and cordon is not None and cordon(src):
                    self.nodes_drained += 1
                    if self.metrics is not None:
                        self.metrics.inc("scheduler_rebalance_nodes_drained_total")
        return issued

    # -- introspection ------------------------------------------------------

    def stats(self) -> dict:
        """Lifetime stats — strictly counts and projected-efficiency floats
        (deterministic control flow; no wall clock), consumed by the sim
        scorecard, /debug/rebalance, bench, and tests."""
        return {
            "enabled": True,
            "ticks": self._tick,
            "solves": self.solves,
            "planned": self.planned,
            "executed": self.executed,
            "completed": self.completed,
            "vanished": self.vanished,
            "stalled": self.stalled,
            "inflight": len(self.inflight),
            "nodes_drained": self.nodes_drained,
            "pressure_releases": self.pressure_releases,
            "skips": dict(sorted(self.skips.items())),
            "last_plan": dict(self.last_plan),
        }
