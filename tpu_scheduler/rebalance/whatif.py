"""Autoscaler what-if — the questions the packing tier makes answerable.

Node-ADD policy (pending-backlog SLO driven): given the standing pending
backlog, how many nodes must join for every pending pod to fit?  The
backlog's requests first-fit-decreasing into the SCHEDULABLE fleet's free
capacity; whatever remains packs into hypothetical new nodes of the
fleet's largest shape — the count is the recommendation.

Node-REMOVE policy (defrag driven): how many nodes could leave today?  The
rebalancer's already-drained (labeled, empty) nodes plus the nodes the
packing solve projects drainable right now — the scale-down headroom.

Deterministic: exact ints, sorted orders, no rng — safe on the scorecard.
"""

from __future__ import annotations

import numpy as np

from .snapshot import RebalanceSnapshot
from .solver import solve_packing

__all__ = ["autoscaler_whatif"]


# shape: (snapshot: obj, pending: obj, drained_labeled: int, topo: obj,
#   catalog: obj, quota_left: obj) -> dict
def autoscaler_whatif(snapshot, pending, drained_labeled: int = 0, topo=None, catalog=None, quota_left=None) -> dict:
    """The what-if block: ``nodes_needed`` (node-add recommendation for the
    current backlog), ``nodes_removable`` (scale-down headroom), and the
    backlog accounting behind them.  ``pending`` is the pending Pod list;
    ``drained_labeled`` counts already-drained (cordoned, empty) nodes.

    With a heterogeneous ``catalog`` (InstanceSKU tuple, optionally bounded
    by ``quota_left``), the overflow additionally packs by cost-aware FFD
    over the catalog: ``sku_plan`` ({sku: count} — WHICH shapes to buy),
    ``plan_cost_per_hour``, and ``nodes_needed`` becomes the plan's node
    total so the autoscale policy never re-derives shape choice."""
    from ..api.objects import total_pod_resources

    rs = RebalanceSnapshot.build(snapshot)
    free = rs.alloc - rs.used
    np.maximum(free, 0, out=free)
    usable = [i for i in range(len(rs.node_names)) if rs.dest_ok[i]]
    usable.sort(key=lambda i: (-int(free[i, 0]), rs.node_names[i]))
    reqs = []
    for p in sorted(pending, key=lambda p: p.metadata.name or ""):
        r = total_pod_resources(p)
        reqs.append((int(r.cpu), int(r.memory)))
    reqs.sort(key=lambda r: (-max(r[0], r[1]), r))
    left = free.copy()
    overflow: list[tuple[int, int]] = []
    for cpu, mem in reqs:
        placed = False
        for i in usable:
            if int(left[i, 0]) >= cpu and int(left[i, 1]) >= mem:
                left[i, 0] -= cpu
                left[i, 1] -= mem
                placed = True
                break
        if not placed:
            overflow.append((cpu, mem))
    # Hypothetical new nodes: the fleet's largest shape per axis (a fleet
    # of zero nodes recommends one node per overflow pod — conservative).
    nodes_needed = 0
    if overflow:
        if len(rs.alloc):
            shape = (int(rs.alloc[:, 0].max()), int(rs.alloc[:, 1].max()))
        else:
            shape = (0, 0)
        if shape[0] <= 0 or shape[1] <= 0:
            nodes_needed = len(overflow)
        else:
            room = [0, 0]
            for cpu, mem in overflow:
                if room[0] < cpu or room[1] < mem:
                    nodes_needed += 1
                    room = [shape[0], shape[1]]
                room[0] -= cpu
                room[1] -= mem
    plan = solve_packing(rs, topo)
    out = {
        "pending_pods": len(reqs),
        "pending_unplaceable": len(overflow),
        "nodes_needed": nodes_needed,
        "nodes_removable": int(drained_labeled) + len(plan.drained),
        "drained_now": int(drained_labeled),
        "drainable_projected": len(plan.drained),
    }
    if catalog is not None:
        from ..autoscale.policy import pack_catalog

        sku_plan, unplaceable = pack_catalog(overflow, catalog, quota_left)
        by_name = {s.name: s for s in catalog}
        out["sku_plan"] = sku_plan
        out["plan_cost_per_hour"] = round(
            sum(by_name[sku].hourly_cost * n for sku, n in sku_plan.items()), 9
        )
        # Overflow the catalog cannot serve (quota-capped or oversized) —
        # the fleet-fit overflow itself stays in pending_unplaceable.
        out["plan_unplaceable"] = unplaceable
        out["nodes_needed"] = sum(sku_plan.values())
    return out
