"""The background packing solve — whole-node drains via first-fit-decreasing.

Objective (the constraint-based pod-packing framing): maximize
**packing efficiency** — demand over the allocatable of the nodes that
carry any demand — equivalently minimize **stranded capacity**, the free
room trapped on occupied nodes.  Because the re-placement side of a
migration belongs to the delta engine (whose spreading score would scatter
descheduled pods right back onto empty nodes), the solve's unit of progress
is the **whole-node drain**: a node is worth draining only if ALL of its
bound mass is movable and the remaining receivers can absorb it — then the
executor unbinds its pods and cordons the emptied node, so the occupied set
monotonically shrinks regardless of where the re-placement lands.

Topology preference (the PR-6 ``CompiledTopology`` distance machinery):
drain candidates are ordered emptiest-COARSEST-DOMAIN first — emptying the
last occupied node of a rack frees the whole rack (the ``rack-defrag``
migration reason, vs the plain ``defrag-drain``) — and receivers are
ordered fullest-domain-first, then by interconnect distance from the drain
source, so the projected packing consolidates into already-hot racks.

Everything is deterministic: sorted orders, exact int64 arithmetic, no rng.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .planner import MIGRATION_REASONS
from .snapshot import RebalanceSnapshot

__all__ = ["Migration", "PackingPlan", "packing_stats", "solve_packing"]


# shape: (alloc: [N, 2] i64, used: [N, 2] i64) -> dict
def packing_stats(alloc: np.ndarray, used: np.ndarray) -> dict:
    """Packing-efficiency / stranded-capacity verdict over one capacity
    view.  ``efficiency`` is the dominant-axis fill of the OCCUPIED node
    set (1.0 = every occupied node full on its binding axis; an empty
    cluster scores 1.0 — nothing is stranded); ``stranded_frac`` is the
    free share of occupied capacity on the same axis.  Exact integer sums;
    the single division is deterministic on a given platform."""
    occ = (used > 0).any(axis=1)
    occupied = int(occ.sum())
    out = {
        "occupied_nodes": occupied,
        "empty_nodes": int(len(used) - occupied),
        "efficiency": 1.0,
        "stranded_frac": 0.0,
    }
    if not occupied:
        return out
    a = alloc[occ].sum(axis=0)
    u = used[occ].sum(axis=0)
    fills = [int(u[k]) / int(a[k]) for k in range(2) if int(a[k]) > 0]
    if fills:
        eff = max(fills)
        out["efficiency"] = round(eff, 6)
        # Pre-oversubscribed state (synthetic round-robin binding) can push
        # the occupied-set fill past 1; stranded capacity floors at zero.
        out["stranded_frac"] = round(max(0.0, 1.0 - eff), 6)
    return out


@dataclass(frozen=True)
class Migration:
    """One planned deschedule: the pod, its source node, the receiver the
    PROJECTION packed it onto (a hint — the delta engine owns the real
    re-placement), and the closed migration reason."""

    pod_full: str
    src: str
    dst: str
    cpu: int
    mem: int
    reason: str


@dataclass(frozen=True)
class PackingPlan:
    """One solve's verdict: migrations in drain order (grouped by source
    node — the executor's whole-node batch unit), the drained node names,
    and the projected before/after packing stats."""

    migrations: tuple[Migration, ...]
    drained: tuple[str, ...]
    before: dict
    after: dict


# shape: (alloc: [N, 2] i64, used: [N, 2] i64, headroom: float) -> [N, 2] i64
def _receiver_budget(alloc: np.ndarray, used: np.ndarray, headroom: float) -> np.ndarray:
    """The migration-diff operand: how much projected mass each receiver
    may still absorb — ``headroom · alloc − used``, floored at zero (an
    already-over-full node absorbs nothing)."""
    budget = (alloc.astype(np.float64) * headroom).astype(np.int64) - used
    np.maximum(budget, 0, out=budget)
    return budget


# shape: (budget: [N, 2] i64, req_cpu: [M] i64, req_mem: [M] i64) -> [N, M] bool
def _fit_matrix(budget: np.ndarray, req_cpu: np.ndarray, req_mem: np.ndarray) -> np.ndarray:
    """The migration-diff feasibility operand: which receiver row can host
    which victim, per-axis outer compare — the whole-group fast abort
    (a victim no receiver fits sinks its node's drain before any FFD)."""
    return (budget[:, 0:1] >= req_cpu[None, :]) & (budget[:, 1:2] >= req_mem[None, :])


# shape: (rs: obj, topo: obj) -> obj
def _coarse_domains(rs: RebalanceSnapshot, topo):
    """[N] int32 coarsest-level domain ids aligned to ``rs.node_names``
    (compiled against a possibly different node order — map by name), or
    None when the cluster is topology-blind."""
    if topo is None or topo.n_levels == 0:
        return None
    by_name = {name: int(topo.dom_ids[-1][i]) for i, name in enumerate(topo.node_names)}
    if not all(name in by_name for name in rs.node_names):
        return None
    return np.asarray([by_name[name] for name in rs.node_names], dtype=np.int32)


# shape: (rs: obj, topo: obj, max_migrations: int, headroom: float) -> obj
def solve_packing(rs: RebalanceSnapshot, topo=None, max_migrations: int = 256, headroom: float = 0.9) -> PackingPlan:
    """Compute the bounded whole-node-drain plan (see module docstring).

    ``headroom`` caps how full the projection may pack a receiver (the
    delta engine's greedy re-placement is not the FFD projection, so the
    plan leaves slack for the difference); ``max_migrations`` bounds the
    plan size outright."""
    n = len(rs.node_names)
    before = packing_stats(rs.alloc, rs.used)
    used = rs.used.copy()
    budget = _receiver_budget(rs.alloc, used, headroom)
    by_node: dict[int, list[tuple[str, int, int]]] = {}
    for pod_full, i, cpu, mem in rs.movable:
        by_node.setdefault(i, []).append((pod_full, cpu, mem))
    occ = (used > 0).any(axis=1)
    doms = _coarse_domains(rs, topo)
    dist = topo.distance_matrix() if (topo is not None and doms is not None and n <= 4096) else None

    # shape: (i: int) -> float
    def node_fill(i: int) -> float:
        fills = [int(used[i, k]) / int(rs.alloc[i, k]) for k in range(2) if int(rs.alloc[i, k]) > 0]
        return max(fills) if fills else 1.0

    # shape: (d: int) -> float
    def dom_fill(d: int) -> float:
        rows = np.flatnonzero(doms == d)
        a = rs.alloc[rows].sum(axis=0)
        u = used[rows].sum(axis=0)
        fills = [int(u[k]) / int(a[k]) for k in range(2) if int(a[k]) > 0]
        return max(fills) if fills else 1.0

    # Drain candidates: occupied, unpinned, every gram of demand movable.
    cands = [
        i
        for i in range(n)
        if occ[i]
        and not rs.pinned[i]
        and i in by_node
        and sum(c for _p, c, _m in by_node[i]) == int(used[i, 0])
        and sum(m for _p, _c, m in by_node[i]) == int(used[i, 1])
    ]
    # Emptiest coarsest-domain first (free whole racks), then emptiest
    # node, then name — fully deterministic.
    cands.sort(
        key=lambda i: (
            dom_fill(int(doms[i])) if doms is not None else 0.0,
            node_fill(i),
            rs.node_names[i],
        )
    )
    drained: list[int] = []
    received: set[int] = set()  # nodes the projection already packed INTO
    migrations: list[Migration] = []
    for src in cands:
        if src in received:
            # A node that absorbed projected mass is a keep-node now —
            # draining it would re-migrate pods the plan just moved (chain
            # churn) and silently erase the received mass from the
            # projection's books.
            continue
        pods = sorted(by_node[src], key=lambda p: (-max(p[1], p[2]), p[0]))  # FFD by dominant axis
        if len(migrations) + len(pods) > max_migrations:
            continue
        # Receivers: occupied, schedulable, not the source, not drained —
        # fullest domain first, fullest node next, NEAREST to the source as
        # the final tie-break (the interconnect-distance preference).
        recv = [
            j
            for j in range(n)
            if j != src and occ[j] and rs.dest_ok[j] and j not in drained
        ]
        recv.sort(
            key=lambda j: (
                -dom_fill(int(doms[j])) if doms is not None else 0.0,
                -node_fill(j),
                float(dist[src, j]) if dist is not None else 0.0,
                rs.node_names[j],
            )
        )
        if recv:
            # Whole-group fast abort: a victim NO receiver could host even
            # with its full remaining budget sinks this drain outright.
            fits = _fit_matrix(
                budget[np.asarray(recv, dtype=np.int64)],
                np.asarray([c for _p, c, _m in pods], dtype=np.int64),
                np.asarray([m for _p, _c, m in pods], dtype=np.int64),
            )
            if not bool(fits.any(axis=0).all()):
                continue
        trial: list[tuple[str, int, int, int]] = []  # (pod_full, dst, cpu, mem)
        spent: dict[int, np.ndarray] = {}
        ok = True
        for pod_full, cpu, mem in pods:
            placed = False
            for j in recv:
                free = budget[j] - spent.get(j, 0)
                if int(free[0]) >= cpu and int(free[1]) >= mem:
                    spent[j] = spent.get(j, np.zeros(2, dtype=np.int64)) + np.asarray([cpu, mem], dtype=np.int64)
                    trial.append((pod_full, j, cpu, mem))
                    placed = True
                    break
            if not placed:
                ok = False
                break
        if not ok:
            continue
        # Commit the drain: move the projected mass, mark the node drained.
        reason = MIGRATION_REASONS[0]  # defrag-drain
        if doms is not None:
            others = np.flatnonzero((doms == doms[src]) & occ)
            if len(others) == 1 and int(others[0]) == src:
                reason = MIGRATION_REASONS[1]  # rack-defrag: the rack empties whole
        for pod_full, j, cpu, mem in trial:
            used[j] += (cpu, mem)
            budget[j] -= (cpu, mem)
            received.add(j)
            migrations.append(
                Migration(pod_full=pod_full, src=rs.node_names[src], dst=rs.node_names[j], cpu=cpu, mem=mem, reason=reason)
            )
        used[src] = 0
        occ[src] = False
        drained.append(src)
    after = packing_stats(rs.alloc, used)
    return PackingPlan(
        migrations=tuple(migrations),
        drained=tuple(rs.node_names[i] for i in drained),
        before=before,
        after=after,
    )
