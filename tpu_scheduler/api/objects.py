"""Kubernetes-shaped object model for the scheduler.

Capability-parity with the slices of ``k8s-openapi`` the reference consumes
(reference: ``src/util.rs``, ``src/predicates.rs``): Pod (metadata, spec
containers/resources/nodeSelector/nodeName, status.phase), Node (metadata
labels, status.allocatable), Binding (metadata + target ObjectReference).

Objects are plain dataclasses; the tensor path never touches them per-pod —
they exist for the control plane, the fake API server, and parity tests.
Construction from k8s-style dict manifests is supported via ``from_dict`` so
synthetic cluster generators and tests can speak YAML-shaped data.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Any, Mapping

from .quantity import cpu_to_millis, memory_to_bytes

__all__ = [
    "ObjectMeta",
    "ResourceRequirements",
    "Container",
    "LabelSelectorRequirement",
    "PodAntiAffinityTerm",
    "PodAffinityTerm",
    "WeightedPodAffinityTerm",
    "PodDisruptionBudget",
    "TopologySpreadConstraint",
    "NodeSelectorTerm",
    "PodSpec",
    "PodStatus",
    "Pod",
    "Taint",
    "Toleration",
    "NodeStatus",
    "NodeSpec",
    "Node",
    "ObjectReference",
    "Binding",
    "PodResources",
    "total_pod_resources",
    "is_pod_bound",
    "full_name",
    "pod_to_dict",
    "node_to_dict",
]

_uid_counter = itertools.count(1)


def _next_uid() -> str:
    return f"uid-{next(_uid_counter)}"


def _parse_resource_version(rv) -> "int | str":
    """Kubernetes resourceVersion is an opaque string; keep it numeric when
    it parses (the in-repo servers use ints) and opaque otherwise — every
    consumer (change detection, signatures) only needs equality."""
    if rv is None or rv == "":
        return 0
    try:
        return int(rv)
    except (TypeError, ValueError):
        return str(rv)


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str | None = None
    labels: dict[str, str] | None = None
    uid: str = field(default_factory=_next_uid)
    resource_version: int | str = 0


@dataclass
class ResourceRequirements:
    # Quantity strings ("500m", "2Gi") or numbers, keyed by resource name.
    requests: dict[str, Any] | None = None
    limits: dict[str, Any] | None = None


@dataclass
class Container:
    name: str = ""
    resources: ResourceRequirements | None = None


@dataclass
class LabelSelectorRequirement:
    """One ``matchExpressions`` entry of a Kubernetes label selector.

    Operators (k8s semantics): ``In`` — key present and value ∈ values;
    ``NotIn`` — key absent or value ∉ values; ``Exists`` — key present;
    ``DoesNotExist`` — key absent.
    """

    key: str
    operator: str
    values: list[str] | None = None


@dataclass
class PodAntiAffinityTerm:
    """Required inter-pod anti-affinity term (BASELINE.json config 5).

    The pod may not land in a topology domain (the set of nodes sharing the
    same value of ``topology_key``) that already holds a pod whose labels
    satisfy the term's selector (``match_labels`` pairs AND every
    ``match_expressions`` requirement) *and* whose namespace equals this
    pod's.  Semantics notes (deviations from full Kubernetes, by design):

      • an entirely empty selector (no pairs, no expressions) matches
        *nothing* (K8s: everything);
      • a node lacking ``topology_key`` is its own singleton domain, so the
        term degrades to per-node (hostname-like) anti-affinity there;
      • the term is enforced symmetrically: an already-placed pod's term also
        blocks an incoming pod that matches it (as kube-scheduler does).
    """

    match_labels: dict[str, str] | None = None
    topology_key: str = "kubernetes.io/hostname"
    match_expressions: list[LabelSelectorRequirement] | None = None


# Positive inter-pod affinity reuses the same term structure (as Kubernetes'
# PodAffinityTerm does for both lists): the pod may land ONLY in a topology
# domain that already holds a pod matched by the selector (every term must be
# satisfied — terms AND).  Bootstrap rule (kube InterPodAffinity): a term no
# existing pod matches anywhere is waived iff the incoming pod matches its
# own term — so the first pod of a self-affine group can place; without
# self-match the pod is unschedulable until a match appears.
PodAffinityTerm = PodAntiAffinityTerm


@dataclass
class WeightedPodAffinityTerm:
    """One ``preferredDuringSchedulingIgnoredDuringExecution`` entry of
    podAffinity / podAntiAffinity: a soft preference — every placed pod in a
    candidate node's topology domain that matches ``term`` adds (affinity) or
    subtracts (anti-affinity) ``weight`` (1-100, kube semantics) score
    points.  Deviation from full Kubernetes, by design: only the incoming
    pod's own preferred terms score; placed pods' preferred terms are not
    applied symmetrically."""

    weight: int
    term: PodAffinityTerm = field(default_factory=PodAffinityTerm)


@dataclass
class PodDisruptionBudget:
    """policy/v1 PodDisruptionBudget, the subset preemption consults:
    namespace-scoped label selector plus exactly one of ``min_available`` /
    ``max_unavailable`` (absolute counts; percentage strings are unsupported
    by design and fail CLOSED — zero disruptions allowed).  An empty/absent
    selector matches every pod in the namespace (policy/v1 semantics; note
    this differs from this codebase's affinity-term deviation where an
    empty selector matches nothing).  Semantics here are NEVER-VIOLATE: a
    victim whose eviction
    would take a matching budget below its floor is simply not eligible —
    preemption looks elsewhere (kube's PreemptLowerPriority instead
    *minimizes* violations; the conservative subset never disrupts a
    protected workload).  NoExecute taint evictions bypass PDBs, exactly as
    kube's taint manager does.
    """

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    match_labels: dict[str, str] | None = None
    match_expressions: list[LabelSelectorRequirement] | None = None
    min_available: int | None = None
    max_unavailable: int | None = None

    @staticmethod
    def from_dict(d: Mapping[str, Any]) -> "PodDisruptionBudget":
        meta = d.get("metadata", {})
        spec = d.get("spec", {})
        sel = spec.get("selector") or {}
        exprs = sel.get("matchExpressions") or []
        return PodDisruptionBudget(
            metadata=ObjectMeta(name=meta.get("name", ""), namespace=meta.get("namespace")),
            match_labels=sel.get("matchLabels"),
            match_expressions=[
                LabelSelectorRequirement(key=e.get("key", ""), operator=e.get("operator", ""), values=e.get("values"))
                for e in exprs
            ]
            or None,
            min_available=spec.get("minAvailable"),
            max_unavailable=spec.get("maxUnavailable"),
        )

    def to_dict(self) -> dict[str, Any]:
        sel: dict[str, Any] = {}
        if self.match_labels:
            sel["matchLabels"] = dict(self.match_labels)
        if self.match_expressions:
            sel["matchExpressions"] = [
                {"key": r.key, "operator": r.operator, **({"values": list(r.values)} if r.values else {})}
                for r in self.match_expressions
            ]
        spec: dict[str, Any] = {"selector": sel}
        if self.min_available is not None:
            spec["minAvailable"] = self.min_available
        if self.max_unavailable is not None:
            spec["maxUnavailable"] = self.max_unavailable
        meta: dict[str, Any] = {"name": self.metadata.name}
        if self.metadata.namespace is not None:
            meta["namespace"] = self.metadata.namespace
        return {"kind": "PodDisruptionBudget", "metadata": meta, "spec": spec}


@dataclass
class TopologySpreadConstraint:
    """Topology-spread constraint (config 5).

    Counts pods matching the selector in the pod's namespace per domain
    of ``topology_key``.  With ``when_unsatisfiable="DoNotSchedule"`` (hard,
    the default) placing the pod on a node must keep
    ``count(domain)+1 − min(count over the key's named domains) ≤ max_skew``;
    with ``"ScheduleAnyway"`` (soft) the skew is allowed but emptier domains
    score higher (weighted by the profile's ``topology_weight``).
    Nodes lacking the key are exempt from the constraint and excluded from
    the minimum (matching kube-scheduler's default node-exclusion).
    An empty selector matches nothing → the constraint is vacuous.
    """

    topology_key: str
    max_skew: int = 1
    match_labels: dict[str, str] | None = None
    match_expressions: list[LabelSelectorRequirement] | None = None
    when_unsatisfiable: str = "DoNotSchedule"

    @property
    def is_hard(self) -> bool:
        return self.when_unsatisfiable != "ScheduleAnyway"


@dataclass
class NodeSelectorTerm:
    """One nodeSelectorTerms entry of required node affinity: its
    ``match_expressions`` are ANDed; terms in a list are ORed.  Node-affinity
    expressions additionally support ``Gt``/``Lt`` (numeric label compare).
    A term with no expressions matches nothing (the empty-selector deviation,
    see PodAntiAffinityTerm)."""

    match_expressions: list[LabelSelectorRequirement] | None = None

    def key(self) -> tuple:
        """Canonical hashable form — the affinity-term vocabulary key.

        In/NotIn values are sets semantically, so their order is
        canonicalized too; Gt/Lt values stay positional (single value)."""
        def vals(r):
            v = tuple(r.values or ())
            return tuple(sorted(v)) if r.operator in ("In", "NotIn") else v

        return tuple(sorted((r.key, r.operator, vals(r)) for r in self.match_expressions or []))


@dataclass
class Taint:
    """Node taint.  NoSchedule and NoExecute are enforced as hard filters;
    PreferNoSchedule is soft — untolerated ones subtract score (ops/score.py,
    weighted by the profile's ``soft_taint_weight``)."""

    key: str
    value: str = ""
    effect: str = "NoSchedule"


@dataclass
class Toleration:
    """Pod toleration (k8s semantics): matches a taint iff
      • key matches (empty key + Exists tolerates everything), and
      • operator Exists, or Equal with equal value, and
      • effect matches (empty toleration effect matches any effect).
    """

    key: str = ""
    operator: str = "Equal"
    value: str = ""
    effect: str = ""
    # NoExecute only (k8s tolerationSeconds): how long the pod may keep
    # RUNNING on a node after a matching NoExecute taint appears; None =
    # tolerate forever.  Ignored at scheduling time.
    toleration_seconds: int | None = None

    def tolerates(self, taint: Taint) -> bool:
        if self.effect and self.effect != taint.effect:
            return False
        if not self.key:
            return self.operator == "Exists"
        if self.key != taint.key:
            return False
        if self.operator == "Exists":
            return True
        return self.operator == "Equal" and self.value == taint.value


@dataclass
class PreferredSchedulingTerm:
    """One ``preferredDuringSchedulingIgnoredDuringExecution`` entry of node
    affinity: a soft preference — nodes matching ``term`` gain ``weight``
    (1-100, kube semantics) score points, scaled by the profile's
    ``preferred_affinity_weight``."""

    weight: int
    term: NodeSelectorTerm = field(default_factory=NodeSelectorTerm)


@dataclass
class PodSpec:
    containers: list[Container] = field(default_factory=list)
    node_selector: dict[str, str] | None = None
    node_name: str | None = None
    priority: int = 0
    # Inter-pod anti-affinity / topology-spread surface (BASELINE.json
    # config 5) — the reference has neither (it stops at resources +
    # nodeSelector, src/predicates.rs:63-77).
    anti_affinity: list[PodAntiAffinityTerm] | None = None
    pod_affinity: list[PodAntiAffinityTerm] | None = None  # positive co-location twin
    preferred_pod_affinity: list[WeightedPodAffinityTerm] | None = None  # soft, weighted
    preferred_pod_anti_affinity: list[WeightedPodAffinityTerm] | None = None
    topology_spread: list[TopologySpreadConstraint] | None = None
    tolerations: list[Toleration] | None = None
    node_affinity: list[NodeSelectorTerm] | None = None  # required terms, ORed
    preferred_node_affinity: list[PreferredSchedulingTerm] | None = None  # soft, weighted
    # Gang (coscheduling) group: pods sharing a gang name bind all-or-
    # nothing within a cycle — the TPU-workload shape (a training job's
    # workers are useless until every one of them places).  Kube expresses
    # this via the scheduling-sigs PodGroup CRD; here it is a first-class
    # spec field, serialized as the pod-group label.
    gang: str | None = None


@dataclass
class PodStatus:
    phase: str = "Pending"


@dataclass
class Pod:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec | None = None
    status: PodStatus = field(default_factory=PodStatus)

    @property
    def name(self) -> str:
        return self.metadata.name

    @staticmethod
    def from_dict(d: Mapping[str, Any]) -> "Pod":
        meta = d.get("metadata", {})
        spec_d = d.get("spec")
        spec = None
        if spec_d is not None:
            containers = [
                Container(
                    name=c.get("name", ""),
                    resources=ResourceRequirements(
                        requests=(c.get("resources") or {}).get("requests"),
                        limits=(c.get("resources") or {}).get("limits"),
                    )
                    if c.get("resources") is not None
                    else None,
                )
                for c in spec_d.get("containers") or []
            ]
            def parse_expressions(selector: Mapping[str, Any] | None) -> list[LabelSelectorRequirement] | None:
                exprs = (selector or {}).get("matchExpressions")
                if not exprs:
                    return None
                return [
                    LabelSelectorRequirement(
                        key=e.get("key", ""),
                        operator=e.get("operator", ""),
                        values=e.get("values"),
                    )
                    for e in exprs
                ]

            def parse_term(t: Mapping[str, Any]) -> PodAntiAffinityTerm:
                return PodAntiAffinityTerm(
                    match_labels=(t.get("labelSelector") or {}).get("matchLabels"),
                    topology_key=t.get("topologyKey", "kubernetes.io/hostname"),
                    match_expressions=parse_expressions(t.get("labelSelector")),
                )

            def parse_weighted(entries) -> list[WeightedPodAffinityTerm] | None:
                if not entries:
                    return None
                return [
                    WeightedPodAffinityTerm(weight=int(e.get("weight", 1)), term=parse_term(e.get("podAffinityTerm") or {}))
                    for e in entries
                ]

            paa_d = (spec_d.get("affinity") or {}).get("podAntiAffinity") or {}
            anti_terms = paa_d.get("requiredDuringSchedulingIgnoredDuringExecution") or []
            anti = [parse_term(t) for t in anti_terms] or None
            pod_aff = None
            pa_d = (spec_d.get("affinity") or {}).get("podAffinity") or {}
            aff_terms = pa_d.get("requiredDuringSchedulingIgnoredDuringExecution") or []
            if aff_terms:
                pod_aff = [parse_term(t) for t in aff_terms]
            pref_pod_aff = parse_weighted(pa_d.get("preferredDuringSchedulingIgnoredDuringExecution"))
            pref_pod_anti = parse_weighted(paa_d.get("preferredDuringSchedulingIgnoredDuringExecution"))
            spread = None
            constraints = spec_d.get("topologySpreadConstraints") or []
            if constraints:  # hard (DoNotSchedule) and soft (ScheduleAnyway) alike
                spread = [
                    TopologySpreadConstraint(
                        topology_key=c.get("topologyKey", ""),
                        max_skew=c.get("maxSkew", 1),
                        match_labels=(c.get("labelSelector") or {}).get("matchLabels"),
                        match_expressions=parse_expressions(c.get("labelSelector")),
                        when_unsatisfiable=c.get("whenUnsatisfiable", "DoNotSchedule"),
                    )
                    for c in constraints
                ]
            node_aff = None
            node_affinity_d = (spec_d.get("affinity") or {}).get("nodeAffinity") or {}
            node_sel_terms = (
                node_affinity_d.get("requiredDuringSchedulingIgnoredDuringExecution") or {}
            ).get("nodeSelectorTerms") or []
            if node_sel_terms:
                node_aff = [NodeSelectorTerm(match_expressions=parse_expressions(t)) for t in node_sel_terms]
            pref_aff = None
            pref_terms = node_affinity_d.get("preferredDuringSchedulingIgnoredDuringExecution") or []
            if pref_terms:
                pref_aff = [
                    PreferredSchedulingTerm(
                        weight=int(t.get("weight", 1)),
                        term=NodeSelectorTerm(match_expressions=parse_expressions(t.get("preference"))),
                    )
                    for t in pref_terms
                ]
            tolerations = [
                Toleration(
                    key=t.get("key", ""),
                    operator=t.get("operator", "Equal"),
                    value=t.get("value", ""),
                    effect=t.get("effect", ""),
                    toleration_seconds=t.get("tolerationSeconds"),
                )
                for t in spec_d.get("tolerations") or []
            ] or None
            spec = PodSpec(
                containers=containers,
                node_selector=spec_d.get("nodeSelector"),
                node_name=spec_d.get("nodeName"),
                priority=spec_d.get("priority", 0),
                anti_affinity=anti,
                pod_affinity=pod_aff,
                preferred_pod_affinity=pref_pod_aff,
                preferred_pod_anti_affinity=pref_pod_anti,
                topology_spread=spread,
                tolerations=tolerations,
                node_affinity=node_aff,
                preferred_node_affinity=pref_aff,
                gang=(meta.get("labels") or {}).get("pod-group.scheduling.sigs.k8s.io") or spec_d.get("schedulingGang"),
            )
        status = PodStatus(phase=d.get("status", {}).get("phase", "Pending"))
        obj_meta = ObjectMeta(
            name=meta.get("name", ""),
            namespace=meta.get("namespace"),
            labels=meta.get("labels"),
            resource_version=_parse_resource_version(meta.get("resourceVersion")),
        )
        if "uid" in meta:
            obj_meta.uid = meta["uid"]
        return Pod(metadata=obj_meta, spec=spec, status=status)


def _selector_to_dict(match_labels, match_expressions) -> dict[str, Any] | None:
    sel: dict[str, Any] = {}
    if match_labels:
        sel["matchLabels"] = dict(match_labels)
    if match_expressions:
        sel["matchExpressions"] = [
            {"key": e.key, "operator": e.operator, **({"values": list(e.values)} if e.values is not None else {})}
            for e in match_expressions
        ]
    return sel or None


def pod_to_dict(pod: "Pod") -> dict[str, Any]:
    """Serialize to the k8s-manifest shape ``Pod.from_dict`` accepts (the
    REST wire format of runtime/kube_http.py).  Lossless round-trip for
    every field the scheduler reads."""
    meta: dict[str, Any] = {"name": pod.metadata.name, "uid": pod.metadata.uid}
    if pod.metadata.namespace is not None:
        meta["namespace"] = pod.metadata.namespace
    if pod.metadata.labels:
        meta["labels"] = dict(pod.metadata.labels)
    if pod.metadata.resource_version:
        meta["resourceVersion"] = str(pod.metadata.resource_version)
    out: dict[str, Any] = {"kind": "Pod", "metadata": meta, "status": {"phase": pod.status.phase}}
    if pod.spec is None:
        return out
    spec: dict[str, Any] = {
        "containers": [
            {
                "name": c.name,
                **(
                    {
                        "resources": {
                            k: v
                            for k, v in (
                                ("requests", c.resources.requests),
                                ("limits", c.resources.limits),
                            )
                            if v is not None
                        }
                    }
                    if c.resources is not None
                    else {}
                ),
            }
            for c in pod.spec.containers
        ]
    }
    if pod.spec.node_selector:
        spec["nodeSelector"] = dict(pod.spec.node_selector)
    if pod.spec.node_name is not None:
        spec["nodeName"] = pod.spec.node_name
    if pod.spec.priority:
        spec["priority"] = pod.spec.priority
    if pod.spec.gang:
        spec["schedulingGang"] = pod.spec.gang
    if pod.spec.tolerations:
        spec["tolerations"] = [
            {
                **({"key": t.key} if t.key else {}),
                "operator": t.operator,
                **({"value": t.value} if t.value else {}),
                **({"effect": t.effect} if t.effect else {}),
                **({"tolerationSeconds": t.toleration_seconds} if t.toleration_seconds is not None else {}),
            }
            for t in pod.spec.tolerations
        ]
    def _term_to_dict(t) -> dict[str, Any]:
        term: dict[str, Any] = {"topologyKey": t.topology_key}
        sel = _selector_to_dict(t.match_labels, t.match_expressions)
        if sel:
            term["labelSelector"] = sel
        return term

    affinity: dict[str, Any] = {}
    if pod.spec.anti_affinity:
        affinity["podAntiAffinity"] = {
            "requiredDuringSchedulingIgnoredDuringExecution": [_term_to_dict(t) for t in pod.spec.anti_affinity]
        }
    if pod.spec.preferred_pod_anti_affinity:
        affinity.setdefault("podAntiAffinity", {})["preferredDuringSchedulingIgnoredDuringExecution"] = [
            {"weight": w.weight, "podAffinityTerm": _term_to_dict(w.term)} for w in pod.spec.preferred_pod_anti_affinity
        ]
    if pod.spec.pod_affinity:
        affinity["podAffinity"] = {
            "requiredDuringSchedulingIgnoredDuringExecution": [_term_to_dict(t) for t in pod.spec.pod_affinity]
        }
    if pod.spec.preferred_pod_affinity:
        affinity.setdefault("podAffinity", {})["preferredDuringSchedulingIgnoredDuringExecution"] = [
            {"weight": w.weight, "podAffinityTerm": _term_to_dict(w.term)} for w in pod.spec.preferred_pod_affinity
        ]
    if pod.spec.node_affinity or pod.spec.preferred_node_affinity:
        node_affinity: dict[str, Any] = {}
        if pod.spec.node_affinity:
            node_affinity["requiredDuringSchedulingIgnoredDuringExecution"] = {
                "nodeSelectorTerms": [
                    _selector_to_dict(None, t.match_expressions) or {} for t in pod.spec.node_affinity
                ]
            }
        if pod.spec.preferred_node_affinity:
            node_affinity["preferredDuringSchedulingIgnoredDuringExecution"] = [
                {"weight": t.weight, "preference": _selector_to_dict(None, t.term.match_expressions) or {}}
                for t in pod.spec.preferred_node_affinity
            ]
        affinity["nodeAffinity"] = node_affinity
    if affinity:
        spec["affinity"] = affinity
    if pod.spec.topology_spread:
        constraints = []
        for c in pod.spec.topology_spread:
            constraint: dict[str, Any] = {
                "topologyKey": c.topology_key,
                "maxSkew": c.max_skew,
                "whenUnsatisfiable": c.when_unsatisfiable,
            }
            sel = _selector_to_dict(c.match_labels, c.match_expressions)
            if sel:
                constraint["labelSelector"] = sel
            constraints.append(constraint)
        spec["topologySpreadConstraints"] = constraints
    out["spec"] = spec
    return out


def node_to_dict(node: "Node") -> dict[str, Any]:
    """Serialize to the k8s-manifest shape ``Node.from_dict`` accepts."""
    meta: dict[str, Any] = {"name": node.metadata.name, "uid": node.metadata.uid}
    if node.metadata.labels:
        meta["labels"] = dict(node.metadata.labels)
    if node.metadata.resource_version:
        meta["resourceVersion"] = str(node.metadata.resource_version)
    out: dict[str, Any] = {"kind": "Node", "metadata": meta}
    if node.status is not None and node.status.allocatable is not None:
        out["status"] = {"allocatable": dict(node.status.allocatable)}
    if node.spec is not None:
        spec: dict[str, Any] = {}
        if node.spec.taints:
            spec["taints"] = [{"key": t.key, "value": t.value, "effect": t.effect} for t in node.spec.taints]
        if node.spec.unschedulable:
            spec["unschedulable"] = True
        if spec:
            out["spec"] = spec
    return out


@dataclass
class NodeStatus:
    # Quantity strings/numbers keyed by resource name ("cpu", "memory").
    allocatable: dict[str, Any] | None = None


@dataclass
class NodeSpec:
    taints: list[Taint] | None = None
    unschedulable: bool = False  # kubectl cordon


@dataclass
class Node:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    status: NodeStatus | None = None
    spec: NodeSpec | None = None

    @property
    def name(self) -> str:
        return self.metadata.name

    @staticmethod
    def from_dict(d: Mapping[str, Any]) -> "Node":
        meta = d.get("metadata", {})
        status_d = d.get("status")
        spec_d = d.get("spec")
        spec = None
        if spec_d is not None:
            taints = [
                Taint(key=t.get("key", ""), value=t.get("value", ""), effect=t.get("effect", "NoSchedule"))
                for t in spec_d.get("taints") or []
            ] or None
            spec = NodeSpec(taints=taints, unschedulable=bool(spec_d.get("unschedulable", False)))
        obj_meta = ObjectMeta(
            name=meta.get("name", ""),
            namespace=meta.get("namespace"),
            labels=meta.get("labels"),
            resource_version=_parse_resource_version(meta.get("resourceVersion")),
        )
        if "uid" in meta:
            obj_meta.uid = meta["uid"]
        return Node(
            metadata=obj_meta,
            status=NodeStatus(allocatable=status_d.get("allocatable")) if status_d else None,
            spec=spec,
        )


@dataclass
class ObjectReference:
    name: str | None = None
    kind: str = "Node"


@dataclass
class Binding:
    """Pod→node binding, mirroring the Binding subresource the reference
    POSTs at ``src/main.rs:83-115``."""

    metadata: ObjectMeta
    target: ObjectReference


@dataclass
class PodResources:
    """(cpu millicores, memory bytes) pair with the arithmetic the reference
    defines on ``PodResources`` (``src/util.rs:17-36``), extended with
    arbitrary countable EXTENDED resources (kube device-plugin semantics:
    ``google.com/tpu: 4``, ``nvidia.com/gpu: 8``, hugepages) — the resource
    class a TPU-native scheduler exists to place.  ``extended`` is None (not
    an empty dict) whenever no extended resource is present, so the
    cpu/mem-only fast paths carry zero overhead."""

    cpu: int = 0  # millicores
    memory: int = 0  # bytes
    extended: dict[str, int] | None = None  # resource name -> integer count

    def copy(self) -> "PodResources":
        """Independent copy — cached totals (core/snapshot.py memos) hand
        these out so callers can keep mutating with += / -=."""
        return PodResources(self.cpu, self.memory, dict(self.extended) if self.extended else None)

    def _ext_add(self, other: "PodResources", sign: int) -> None:
        if other.extended:
            if self.extended is None:
                self.extended = {}
            for k, v in other.extended.items():
                self.extended[k] = self.extended.get(k, 0) + sign * v

    def __isub__(self, other: "PodResources") -> "PodResources":
        self.cpu -= other.cpu
        self.memory -= other.memory
        self._ext_add(other, -1)
        return self

    def __iadd__(self, other: "PodResources") -> "PodResources":
        self.cpu += other.cpu
        self.memory += other.memory
        self._ext_add(other, +1)
        return self

    def fits_in(self, avail: "PodResources") -> bool:
        """request ≤ available on EVERY axis (cpu, memory, each extended
        resource; an extended request against a node lacking the resource
        fails — kube device-plugin semantics)."""
        if self.cpu > avail.cpu or self.memory > avail.memory:
            return False
        if self.extended:
            a = avail.extended or {}
            for k, v in self.extended.items():
                if v > a.get(k, 0):
                    return False
        return True

    def covers(self, need: "PodResources") -> bool:
        """self ≥ need on every axis where need is positive (preemption's
        freed-capacity test; negative/zero needs are already satisfied)."""
        if need.cpu > self.cpu and need.cpu > 0 or need.memory > self.memory and need.memory > 0:
            return False
        if need.extended:
            mine = self.extended or {}
            for k, v in need.extended.items():
                if v > 0 and v > mine.get(k, 0):
                    return False
        return True


def is_extended_resource(name: str) -> bool:
    """Kube's definition (IsExtendedResourceName): domain-qualified names
    OUTSIDE the kubernetes.io domain, plus hugepages-*.  Kube-native names
    this framework doesn't model (ephemeral-storage, pods,
    *.kubernetes.io/*) stay IGNORED, as the reference ignores everything
    but cpu/memory — a common manifest requesting them must not become
    unschedulable."""
    if name.startswith("hugepages-"):
        return True
    if "/" not in name:
        return False
    domain = name.split("/", 1)[0]
    return not (domain == "kubernetes.io" or domain.endswith(".kubernetes.io"))


def total_pod_resources(pod: Pod) -> PodResources:
    """Sum container *requests* — reference ``src/util.rs:54-75`` for
    cpu/memory, plus kube EXTENDED resources (``is_extended_resource``:
    domain-qualified device-plugin names and hugepages-*): each accumulates
    as an exact integer (device counts; hugepages sizes in bytes).  Other
    names are ignored, matching the reference."""
    out = PodResources()
    if pod.spec is None:
        return out
    for c in pod.spec.containers:
        if c.resources is None or c.resources.requests is None:
            continue
        req = c.resources.requests
        for name, q in req.items():
            if name == "cpu":
                out.cpu += cpu_to_millis(q)
            elif name == "memory":
                out.memory += memory_to_bytes(q)
            elif is_extended_resource(name):
                if out.extended is None:
                    out.extended = {}
                out.extended[name] = out.extended.get(name, 0) + memory_to_bytes(q)
    return out


def is_pod_bound(pod: Pod) -> bool:
    """True iff ``spec.nodeName`` is set — reference ``src/util.rs:38-45``."""
    return pod.spec is not None and pod.spec.node_name is not None


def full_name(obj: Pod | Node) -> str:
    """"namespace/name" or bare name — reference ``src/util.rs:47-52``."""
    if obj.metadata.namespace:
        return f"{obj.metadata.namespace}/{obj.metadata.name}"
    return obj.metadata.name
