"""Kubernetes-shaped object model for the scheduler.

Capability-parity with the slices of ``k8s-openapi`` the reference consumes
(reference: ``src/util.rs``, ``src/predicates.rs``): Pod (metadata, spec
containers/resources/nodeSelector/nodeName, status.phase), Node (metadata
labels, status.allocatable), Binding (metadata + target ObjectReference).

Objects are plain dataclasses; the tensor path never touches them per-pod —
they exist for the control plane, the fake API server, and parity tests.
Construction from k8s-style dict manifests is supported via ``from_dict`` so
synthetic cluster generators and tests can speak YAML-shaped data.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Any, Mapping

from .quantity import cpu_to_millis, memory_to_bytes

__all__ = [
    "ObjectMeta",
    "ResourceRequirements",
    "Container",
    "PodSpec",
    "PodStatus",
    "Pod",
    "NodeStatus",
    "Node",
    "ObjectReference",
    "Binding",
    "PodResources",
    "total_pod_resources",
    "is_pod_bound",
    "full_name",
]

_uid_counter = itertools.count(1)


def _next_uid() -> str:
    return f"uid-{next(_uid_counter)}"


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str | None = None
    labels: dict[str, str] | None = None
    uid: str = field(default_factory=_next_uid)
    resource_version: int = 0


@dataclass
class ResourceRequirements:
    # Quantity strings ("500m", "2Gi") or numbers, keyed by resource name.
    requests: dict[str, Any] | None = None
    limits: dict[str, Any] | None = None


@dataclass
class Container:
    name: str = ""
    resources: ResourceRequirements | None = None


@dataclass
class PodSpec:
    containers: list[Container] = field(default_factory=list)
    node_selector: dict[str, str] | None = None
    node_name: str | None = None
    priority: int = 0
    # Topology-spread / anti-affinity surface (BASELINE.json config 5):
    # topology key -> max skew; anti-affinity label selector terms.
    topology_spread: dict[str, int] | None = None
    anti_affinity_labels: dict[str, str] | None = None


@dataclass
class PodStatus:
    phase: str = "Pending"


@dataclass
class Pod:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec | None = None
    status: PodStatus = field(default_factory=PodStatus)

    @property
    def name(self) -> str:
        return self.metadata.name

    @staticmethod
    def from_dict(d: Mapping[str, Any]) -> "Pod":
        meta = d.get("metadata", {})
        spec_d = d.get("spec")
        spec = None
        if spec_d is not None:
            containers = [
                Container(
                    name=c.get("name", ""),
                    resources=ResourceRequirements(
                        requests=(c.get("resources") or {}).get("requests"),
                        limits=(c.get("resources") or {}).get("limits"),
                    )
                    if c.get("resources") is not None
                    else None,
                )
                for c in spec_d.get("containers", [])
            ]
            spec = PodSpec(
                containers=containers,
                node_selector=spec_d.get("nodeSelector"),
                node_name=spec_d.get("nodeName"),
                priority=spec_d.get("priority", 0),
                topology_spread=spec_d.get("topologySpread"),
                anti_affinity_labels=spec_d.get("antiAffinityLabels"),
            )
        status = PodStatus(phase=d.get("status", {}).get("phase", "Pending"))
        return Pod(
            metadata=ObjectMeta(
                name=meta.get("name", ""),
                namespace=meta.get("namespace"),
                labels=meta.get("labels"),
            ),
            spec=spec,
            status=status,
        )


@dataclass
class NodeStatus:
    # Quantity strings/numbers keyed by resource name ("cpu", "memory").
    allocatable: dict[str, Any] | None = None


@dataclass
class Node:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    status: NodeStatus | None = None

    @property
    def name(self) -> str:
        return self.metadata.name

    @staticmethod
    def from_dict(d: Mapping[str, Any]) -> "Node":
        meta = d.get("metadata", {})
        status_d = d.get("status")
        return Node(
            metadata=ObjectMeta(
                name=meta.get("name", ""),
                namespace=meta.get("namespace"),
                labels=meta.get("labels"),
            ),
            status=NodeStatus(allocatable=status_d.get("allocatable")) if status_d else None,
        )


@dataclass
class ObjectReference:
    name: str | None = None
    kind: str = "Node"


@dataclass
class Binding:
    """Pod→node binding, mirroring the Binding subresource the reference
    POSTs at ``src/main.rs:83-115``."""

    metadata: ObjectMeta
    target: ObjectReference


@dataclass
class PodResources:
    """(cpu millicores, memory bytes) pair with the arithmetic the reference
    defines on ``PodResources`` (``src/util.rs:17-36``)."""

    cpu: int = 0  # millicores
    memory: int = 0  # bytes

    def __isub__(self, other: "PodResources") -> "PodResources":
        self.cpu -= other.cpu
        self.memory -= other.memory
        return self

    def __iadd__(self, other: "PodResources") -> "PodResources":
        self.cpu += other.cpu
        self.memory += other.memory
        return self


def total_pod_resources(pod: Pod) -> PodResources:
    """Sum container *requests* (cpu, memory) — reference ``src/util.rs:54-75``.

    Containers without a resources/requests block contribute zero; resource
    names other than cpu/memory are ignored, matching the reference.
    """
    out = PodResources()
    if pod.spec is None:
        return out
    for c in pod.spec.containers:
        if c.resources is None or c.resources.requests is None:
            continue
        req = c.resources.requests
        if "cpu" in req:
            out.cpu += cpu_to_millis(req["cpu"])
        if "memory" in req:
            out.memory += memory_to_bytes(req["memory"])
    return out


def is_pod_bound(pod: Pod) -> bool:
    """True iff ``spec.nodeName`` is set — reference ``src/util.rs:38-45``."""
    return pod.spec is not None and pod.spec.node_name is not None


def full_name(obj: Pod | Node) -> str:
    """"namespace/name" or bare name — reference ``src/util.rs:47-52``."""
    if obj.metadata.namespace:
        return f"{obj.metadata.namespace}/{obj.metadata.name}"
    return obj.metadata.name
