"""Kubernetes resource-quantity parsing.

TPU-native replacement for the reference's ``kube_quantity::ParsedQuantity``
arithmetic (reference: ``src/util.rs:17-36``).  Instead of keeping quantities
as symbolic (value, suffix) pairs, we normalise eagerly to integers — cpu in
*millicores*, memory in *bytes* — because the whole point of this framework is
to pack resources into int64 tensors for TPU evaluation.  Exact arithmetic is
done with ``fractions.Fraction`` so "0.1" cpu or "1.5Gi" memory never lose
precision before the final ceil.

Grammar (Kubernetes apimachinery `Quantity`):

    quantity     := <sign>? <digits> ('.' <digits>)? <suffix>?
    suffix       := binarySI | decimalSI | decimalExponent
    binarySI     := Ki | Mi | Gi | Ti | Pi | Ei
    decimalSI    := n | u | m | '' | k | M | G | T | P | E
    decimalExponent := ('e'|'E') <sign>? <digits>
"""

from __future__ import annotations

import math
import re
from fractions import Fraction
from functools import lru_cache

__all__ = [
    "QuantityError",
    "parse_quantity",
    "cpu_to_millis",
    "memory_to_bytes",
    "millis_to_cpu_str",
    "bytes_to_memory_str",
]


class QuantityError(ValueError):
    """Raised for an unparseable Kubernetes quantity string."""


_SUFFIX_MULTIPLIERS: dict[str, Fraction] = {
    "n": Fraction(1, 10**9),
    "u": Fraction(1, 10**6),
    "m": Fraction(1, 10**3),
    "": Fraction(1),
    "k": Fraction(10**3),
    "M": Fraction(10**6),
    "G": Fraction(10**9),
    "T": Fraction(10**12),
    "P": Fraction(10**15),
    "E": Fraction(10**18),
    "Ki": Fraction(2**10),
    "Mi": Fraction(2**20),
    "Gi": Fraction(2**30),
    "Ti": Fraction(2**40),
    "Pi": Fraction(2**50),
    "Ei": Fraction(2**60),
}

_QUANTITY_RE = re.compile(
    r"^(?P<sign>[+-]?)"
    r"(?P<digits>\d+(?:\.\d*)?|\.\d+)"
    r"(?:"
    r"(?P<suffix>[numkMGTPE]|Ki|Mi|Gi|Ti|Pi|Ei)"
    r"|(?:[eE](?P<exp>[+-]?\d+))"
    r")?$"
)


def parse_quantity(s: str | int | float) -> Fraction:
    """Parse a Kubernetes quantity into an exact Fraction of base units.

    Accepts ints/floats for convenience (synthetic workload generators).
    """
    if isinstance(s, int):
        return Fraction(s)
    if isinstance(s, float):
        return Fraction(str(s))
    if not isinstance(s, str):
        raise QuantityError(f"quantity must be str/int/float, got {type(s)!r}")
    m = _QUANTITY_RE.match(s.strip())
    if not m:
        raise QuantityError(f"invalid quantity: {s!r}")
    value = Fraction(m.group("digits"))
    if m.group("sign") == "-":
        value = -value
    suffix = m.group("suffix")
    exp = m.group("exp")
    if suffix is not None:
        value *= _SUFFIX_MULTIPLIERS[suffix]
    elif exp is not None:
        e = int(exp)
        value *= Fraction(10) ** e
    return value


@lru_cache(maxsize=65536)
def cpu_to_millis(s: str | int | float) -> int:
    """Parse a cpu quantity to integer millicores, rounding up.

    "500m" -> 500, "2" -> 2000, "0.5" -> 500, "1n" -> 1 (ceil).
    Kubernetes canonicalises fractional requests upward; matching that keeps
    fit-decisions conservative (never admit a pod the reference would reject).
    """
    return math.ceil(parse_quantity(s) * 1000)


@lru_cache(maxsize=65536)
def memory_to_bytes(s: str | int | float) -> int:
    """Parse a memory quantity to integer bytes, rounding up.

    "2Gi" -> 2147483648, "1G" -> 1000000000, "129e6" -> 129000000.
    """
    return math.ceil(parse_quantity(s))


def millis_to_cpu_str(millis: int) -> str:
    """Render millicores back to a canonical cpu quantity string."""
    if millis % 1000 == 0:
        return str(millis // 1000)
    return f"{millis}m"


def bytes_to_memory_str(nbytes: int) -> str:
    """Render bytes back to a quantity string (binary suffix when exact)."""
    for suffix, mult in (("Ei", 2**60), ("Pi", 2**50), ("Ti", 2**40), ("Gi", 2**30), ("Mi", 2**20), ("Ki", 2**10)):
        if nbytes and nbytes % mult == 0:
            return f"{nbytes // mult}{suffix}"
    return str(nbytes)
