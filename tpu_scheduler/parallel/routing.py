"""Expert-parallel analogue: route pod classes to per-pool scheduling shards
(SURVEY.md §2b EP — "routing pod classes (GPU/TPU/CPU pools) to per-pool
scoring shards").

A cluster partitioned by a node label (``pool=compute``, ``pool=memory`` …)
decomposes: a pending pod whose nodeSelector PINS the partition key is only
feasible inside that pool, so the global P×N auction splits into independent
per-pool auctions of Σ Pᵢ×Nᵢ work — strictly less compute, smaller tiles,
and (the EP part) each pool shard dispatches to its own device: JAX's async
dispatch overlaps the pool solves exactly like expert shards overlap in an
MoE layer, with results gathered once at the end.

Pods that don't pin the key (and nodes lacking it) form the RESIDUAL, solved
after the pools against post-commit capacity via the controller's placed
overlay.  Semantics:

  • validity/capacity: exact — pools are disjoint node sets, a routed pod's
    selector makes off-pool nodes infeasible anyway, and the residual sees
    every pool placement as consumed capacity (same overlay the mixed
    priority-segment path uses, runtime/controller.py);
  • choice parity: NOT bit-identical to the unrouted auction (per-shard rank
    spaces change the tie-break jitter), matching the framework's parity
    contract for decomposed paths — binding validity, not identical choices
    (SURVEY.md §7 hard part (e));
  • priority: exact within a pool and within the residual; a residual pod
    competes only for post-pool capacity (the decomposition's documented
    trade — the same one the reference's random sampling makes globally,
    ``src/main.rs:49-71``).

Constrained cycles (anti-affinity / topology spread) bypass routing: domain
state spans pools, so the controller routes them through the constraint
tensor path instead.
"""

from __future__ import annotations

from ..api.objects import Pod
from ..core.snapshot import ClusterSnapshot

__all__ = ["partition_snapshot", "PoolPartition"]


class PoolPartition:
    """One partitioning of a cycle: per-pool sub-snapshots + residual."""

    def __init__(self, pools: dict[str, ClusterSnapshot], residual_pending: list[Pod]):
        self.pools = pools
        self.residual_pending = residual_pending

    @property
    def routed_pods(self) -> int:
        return sum(len(s.pending_pods()) for s in self.pools.values())


def _pinned_value(pod: Pod, key: str) -> str | None:
    if pod.spec is None or not pod.spec.node_selector:
        return None
    return pod.spec.node_selector.get(key)


def partition_snapshot(snapshot: ClusterSnapshot, pool_key: str) -> PoolPartition | None:
    """Split a cycle by ``pool_key``.

    Pool ``v`` gets: the nodes labeled ``pool_key=v``, every pod bound to
    one of them (capacity bookkeeping), and the pending pods whose selector
    pins ``pool_key=v``.  Pending pods that don't pin the key — and any pod
    pinning a value no node carries (it can never bind; it must surface as
    unschedulable through the residual) — stay in the residual.  Returns
    None when routing would not split anything (≤1 non-empty pool, or
    nothing routable) — the caller then takes the plain batch path.
    """
    # One pass each over nodes, pending, and bound pods — O(nodes + pods)
    # regardless of pool cardinality.
    node_pool: dict[str, str] = {}
    nodes_by_pool: dict[str, list] = {}
    for n in snapshot.nodes:
        v = (n.metadata.labels or {}).get(pool_key)
        if v is not None:
            node_pool[n.name] = v
            nodes_by_pool.setdefault(v, []).append(n)

    routable: dict[str, list[Pod]] = {}
    residual: list[Pod] = []
    for p in snapshot.pending_pods():
        v = _pinned_value(p, pool_key)
        if v is not None and v in nodes_by_pool:
            routable.setdefault(v, []).append(p)
        else:
            residual.append(p)
    if len(routable) <= 1:
        return None

    bound_by_pool: dict[str, list[Pod]] = {}
    for q in snapshot.pods:
        if q.spec is not None and q.spec.node_name is not None:
            v = node_pool.get(q.spec.node_name)
            if v in routable:
                bound_by_pool.setdefault(v, []).append(q)

    pools: dict[str, ClusterSnapshot] = {}
    for v, pending in routable.items():
        pools[v] = ClusterSnapshot.build(nodes_by_pool[v], bound_by_pool.get(v, []) + pending)
    return PoolPartition(pools, residual)
