"""Device meshes for the scheduler — the ICI/DCN scaling surface.

The reference's only "distribution" is HTTPS to the API server (SURVEY.md
§2b); here the scaling axes are a ``jax.sharding.Mesh``:

  dp — data parallelism over the *pods* axis (each device scores a pod shard)
  tp — tensor parallelism over the *nodes* axis (for node counts × label
       widths beyond one device's HBM)

Multi-host extends the same mesh over DCN via :func:`init_distributed`
(``jax.distributed``): after initialization ``jax.devices()`` is the global
device list and :func:`make_mesh` lays the mesh out **process-major**, so
the ``tp`` axis (the chatty one: per-round all_gather of node-shard argmaxes,
parallel/sharded.py) stays inside each host on ICI, while ``dp`` (one
all_gather of pod claims per round, O(P) int32s) crosses hosts on DCN.
Executed proof: tests/test_multihost.py runs the full sharded cycle across
two OS processes over a TCP coordinator and checks bit-parity with the
single-process oracle.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

import numpy as np

__all__ = ["make_mesh", "mesh_shape_for", "init_distributed", "MeshBinding", "mesh_binding", "node_sharding"]


def init_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    local_device_ids=None,
    auto: bool = False,
) -> bool:
    """Initialize ``jax.distributed`` for multi-host (DCN) operation.

    Arguments default from the ``SCHED_COORDINATOR`` / ``SCHED_NUM_PROCESSES``
    / ``SCHED_PROCESS_ID`` environment variables.  With ``auto=True`` and no
    explicit configuration, falls through to bare
    ``jax.distributed.initialize()`` (JAX's own cluster auto-detection on
    TPU pods / managed environments).  Returns True when a multi-process
    runtime was initialized, False for the single-process no-op — callers
    can invoke it unconditionally at startup."""
    coordinator_address = coordinator_address or os.environ.get("SCHED_COORDINATOR")
    if num_processes is None:
        num_processes = int(os.environ.get("SCHED_NUM_PROCESSES", "1"))
    if process_id is None:
        process_id = int(os.environ.get("SCHED_PROCESS_ID", "0"))
    if num_processes <= 1 or coordinator_address is None:
        if auto:
            import jax

            jax.distributed.initialize()  # env/cluster auto-detection
            return jax.process_count() > 1
        return False
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )
    return True


def mesh_shape_for(n_devices: int, tp: int | None = None) -> tuple[int, int]:
    """(dp, tp) factorisation: biggest power-of-two tp requested (default 2
    when it divides evenly, else 1) — pods are the long axis, so dp gets the
    bulk of the devices."""
    if tp is None:
        tp = 2 if n_devices % 2 == 0 and n_devices > 1 else 1
    if n_devices % tp != 0:
        raise ValueError(f"tp={tp} does not divide device count {n_devices}")
    return n_devices // tp, tp


def make_mesh(devices=None, tp: int | None = None):
    """Build a (dp, tp) Mesh over the given (default: all global) devices.

    Devices are ordered process-major, so with ``tp ≤ local_device_count``
    every tp row is intra-host (ICI) and dp crosses hosts (DCN)."""
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    devices = sorted(devices, key=lambda d: (d.process_index, d.id))
    dp, tp_ = mesh_shape_for(len(devices), tp)
    return Mesh(np.array(devices).reshape(dp, tp_), ("dp", "tp"))


@dataclass(frozen=True)
class MeshBinding:
    """One shard bound to one replica's device mesh (the fleet layer's
    mesh-per-replica unit): the shard id, the (dp, tp) Mesh over the
    shard's device slice, and the device ids for the /debug/shards view."""

    shard: int
    num_shards: int
    mesh: object
    device_ids: tuple
    dedicated: bool  # False = fewer devices than shards; the slice is the whole set


# shape: (shard: int, num_shards: int, devices: obj, tp: int) -> obj
def mesh_binding(shard: int, num_shards: int, devices=None, tp: int | None = None) -> MeshBinding:
    """Bind one shard to its contiguous slice of the device list.

    Devices order process-major (the make_mesh contract) and split into
    ``num_shards`` contiguous chunks; shard *i* gets chunk *i*, so peer
    shards' solves run on disjoint silicon and a takeover rebinds the
    absorbed shard onto the survivor's own chunk.  With fewer devices than
    shards (the CPU tests, a 1-chip dev box) every shard binds the WHOLE
    device set — correct, just not parallel across replicas."""
    import jax

    if devices is None:
        devices = jax.devices()
    devices = sorted(devices, key=lambda d: (d.process_index, d.id))
    n = len(devices)
    per = n // int(num_shards)
    if per < 1:
        chunk = devices
        dedicated = False
    else:
        lo = int(shard) * per
        # The last shard absorbs the remainder chunk.
        hi = n if int(shard) == int(num_shards) - 1 else lo + per
        chunk = devices[lo:hi]
        dedicated = True
    return MeshBinding(
        shard=int(shard),
        num_shards=int(num_shards),
        mesh=make_mesh(chunk, tp=tp if tp is not None and len(chunk) % tp == 0 else 1),
        device_ids=tuple(d.id for d in chunk),
        dedicated=dedicated,
    )


# shape: (binding: obj) -> obj
def node_sharding(binding: MeshBinding):
    """NamedSharding laying the NODE sub-axis of a [..., N] operand over the
    binding's ``tp`` mesh axis (the SNIPPETS.md NamedSharding idiom) — how a
    shard's packed node tensors land on its own device slice."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(binding.mesh, P("tp"))
