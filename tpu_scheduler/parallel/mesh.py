"""Device meshes for the scheduler — the ICI/DCN scaling surface.

The reference's only "distribution" is HTTPS to the API server (SURVEY.md
§2b); here the scaling axes are a ``jax.sharding.Mesh``:

  dp — data parallelism over the *pods* axis (each device scores a pod shard)
  tp — tensor parallelism over the *nodes* axis (for node counts × label
       widths beyond one device's HBM)

Multi-host extends the same mesh over DCN via ``jax.distributed`` — the mesh
abstraction is identical, so everything in parallel/sharded.py carries over.
"""

from __future__ import annotations

import numpy as np

__all__ = ["make_mesh", "mesh_shape_for"]


def mesh_shape_for(n_devices: int, tp: int | None = None) -> tuple[int, int]:
    """(dp, tp) factorisation: biggest power-of-two tp requested (default 2
    when it divides evenly, else 1) — pods are the long axis, so dp gets the
    bulk of the devices."""
    if tp is None:
        tp = 2 if n_devices % 2 == 0 and n_devices > 1 else 1
    if n_devices % tp != 0:
        raise ValueError(f"tp={tp} does not divide device count {n_devices}")
    return n_devices // tp, tp


def make_mesh(devices=None, tp: int | None = None):
    """Build a (dp, tp) Mesh over the given (default: all) devices."""
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    dp, tp_ = mesh_shape_for(len(devices), tp)
    return Mesh(np.array(devices).reshape(dp, tp_), ("dp", "tp"))
