"""Multi-chip scheduling cycle: the auction of ops/assign.py distributed over
a (dp, tp) mesh with jax.shard_map — pods sharded over ``dp``, nodes over
``tp``, XLA collectives over ICI (SURVEY.md §2b).

Identical results to the single-device path, by construction:

  choose   — each device scores its pod shard against its node shard; the
             per-pod best node is reduced across ``tp`` with all_gather +
             (score desc, node-index asc) tie-break, which equals the global
             first-max argmax.
  accept   — pod claims (choice, request) are all_gathered over ``dp`` in
             global priority order (pods are pre-permuted before sharding,
             so the tiled gather *is* rank order); each tp column runs the
             segmented saturating prefix acceptance for the nodes it owns;
             per-pod accepted flags come back via a tp psum (node shards are
             disjoint).
  commit   — each column scatter-subtracts its own nodes; every dp row in a
             column computes identically, keeping replicated state in sync
             without extra traffic.

Constrained cycles (anti-affinity / topology spread, ops/constraints.py)
ride the same mesh: the constraint tensors are [T,D]/[S,D]/[T,N]-shaped — a
rounding error next to the [P/dp × N/tp] choose tiles — so the domain state
and pod bitmaps are REPLICATED on every device, the round-start blocked
masks are computed redundantly (each device slices its node columns), and
the within-round filter + state commit run identically on every device over
the already-gathered global claims.  No collectives beyond the two the
unconstrained path already pays; determinism keeps the replicas in lockstep
(same inputs → same state), exactly like the replicated ``avail`` columns.

Per-round traffic: O(P) int32s over dp + O(P) over tp — a few MB at 100k
pods, ICI-trivial next to the [P/dp × N/tp] compute tiles.

The same code scales to multi-host (DCN) by building the mesh over
``jax.distributed`` processes; nothing below is aware of the difference.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ..models.profiles import SchedulingProfile
from ..ops.assign import _seg_scan_op
from ..ops.pack import STALL_ROUNDS
from ..ops.masks import feasibility_block
from ..ops.pack import PackedCluster, round_up
from ..ops.score import score_block
from ..backends.base import SchedulingBackend
from .mesh import make_mesh

__all__ = ["sharded_assign_cycle", "ShardedBackend", "IN_SPECS", "CONSTRAINT_KEYS", "constraint_operands"]


# shape: (avail: [N, R] i32, active: [B] bool, req: [B, R] i32,
#   sel: [B, L] f32, selc: [B] f32, ntol: [B, T] f32, aff: [B, A] f32,
#   has_aff: [B] f32, pref_w: [B, A2] f32, ntol_soft: [B, Ts] f32,
#   node_alloc: [N, R] i32, node_labels: [N, L] f32, node_taints: [N, T] f32,
#   node_aff: [N, A] f32, node_valid: [N] bool, node_pref: [N, A2] f32,
#   node_taints_soft: [N, Ts] f32, weights: [W] f32, pod_idx: [B] u32,
#   node_idx: [N] u32, blocked: [B, N] bool, sps_declares: [B, Ss] f32,
#   sp_penalty: [Ss, N] f32, spd_declares: [B, S] f32, sp_level: [S, N] f32,
#   ppa_w: [B, Tp] f32, ppa_cnt: [Tp, N] f32, salt: scalar any)
#   -> ([B] f32, [B] i32, [B] bool)
def _local_choose(
    avail, active, req, sel, selc, ntol, aff, has_aff, pref_w, ntol_soft, node_alloc, node_labels, node_taints,
    node_aff, node_valid, node_pref, node_taints_soft, weights, pod_idx, node_idx,
    blocked=None, sps_declares=None, sp_penalty=None, spd_declares=None, sp_level=None,
    ppa_w=None, ppa_cnt=None, salt=None,
):
    """Best local node per pod of this shard: (best_score, local idx, has).

    ``pod_idx``/``node_idx`` are *global* (rank-space) indices so the score
    jitter hash matches the single-device path exactly.  ``blocked`` is the
    constraint-blocked [p_local, n_local] mask (constrained cycles only);
    ``sps_declares``/``sp_penalty`` the ScheduleAnyway scoring operands;
    ``spd_declares``/``sp_level`` the hard-spread level-steering pair."""
    m = feasibility_block(
        jnp, req, sel, selc, active, avail, node_labels, node_valid, ntol, node_taints, aff, has_aff, node_aff
    )
    if blocked is not None:
        m = m & ~blocked
    sc = score_block(
        jnp, req, node_alloc, avail, weights, pod_idx, node_idx,
        pod_pref_w=pref_w, node_pref=node_pref, pod_ntol_soft=ntol_soft, node_taints_soft=node_taints_soft,
        pod_sps_declares=sps_declares, sp_penalty_node=sp_penalty,
        pod_sp_declares=spd_declares, sp_level_node=sp_level,
        pod_ppa_w=ppa_w, ppa_cnt_node=ppa_cnt, salt=salt,
    )
    sc = jnp.where(m, sc, -jnp.inf)
    return jnp.max(sc, axis=1), jnp.argmax(sc, axis=1).astype(jnp.int32), m.any(axis=1)


# Plain pod operand order — must match IN_SPECS positionally; shared by the
# single-process run wrapper and multihost.py so the three stay in lockstep.
POD_KEYS = (
    "pod_req",
    "pod_sel",
    "pod_sel_count",
    "pod_ntol",
    "pod_aff",
    "pod_has_aff",
    "pod_pref_w",
    "pod_ntol_soft",
    "pod_valid",
)

# Flat operand order for the constrained extension (all REPLICATED — specs
# P()): pod bitmaps in global rank order, then meta, then initial state.
CONSTRAINT_KEYS = (
    # pod side (ConstraintSet.pod_arrays, priority-permuted + dp-padded)
    "pod_aa_carries",
    "pod_aa_matched",
    "pod_pa_declares",
    "pod_pa_matched",
    "pod_sp_declares",
    "pod_sp_matched",
    "pod_sps_declares",
    "pod_sps_matched",
    "pod_ppa_w",
    "pod_ppa_matched",
    # meta (node_dom_c is [N,D] with N padded to the tp multiple)
    "node_dom_c",
    "term_uses_dom",
    "pa_uses_dom",
    "ppa_uses_dom",
    "sp_uses_dom",
    "sp_skew",
    "sps_uses_dom",
    "sp_dom_sel",
    # initial state (aa_node_* / pa_node_m are [·,N] padded to the tp multiple)
    "aa_dom_m",
    "aa_dom_c",
    "aa_node_m",
    "aa_node_c",
    "pa_dom_m",
    "pa_node_m",
    "ppa_dom_cnt",
    "ppa_node_cnt",
    "sp_counts",
    "sps_counts",
)
_N_PODKEYS = 10
_N_METAKEYS = 8


@lru_cache(maxsize=64)
def _build_shard_map(
    mesh,
    max_rounds: int,
    constrained: bool = False,
    soft_spread: bool = False,
    soft_pa: bool = False,
    hard_pa: bool = True,
    use_pallas: bool = False,
    pallas_interpret: bool = False,
):
    """The shard_map'd per-device cycle fn (not yet jitted/wrapped) — shared
    by the single-process run wrapper below and the multi-host path
    (parallel/multihost.py), so both execute the identical program.

    ``use_pallas`` routes each shard's choose through the fused kernel
    (ops/pallas_choose.py) — the per-shard best SCORE rides out as the
    kernel's third output for the cross-tp merge, and the jitter hash gets
    this shard's global node base via ``node_offset``, so results stay
    bit-identical to the jnp shard program.  ``pallas_interpret`` runs the
    kernel in interpreter mode (CPU meshes: tests, dryrun_multichip)."""
    dp = mesh.shape["dp"]
    tp = mesh.shape["tp"]

    def local_fn(
        node_alloc, node_avail, node_labels, node_taints, node_aff, node_valid, node_pref, node_taints_soft,
        req, sel, selc, ntol, aff, has_aff, pref_w, ntol_soft, valid, w, *cargs,
    ):
        p_local = req.shape[0]
        n_local = node_avail.shape[0]
        p_tot = p_local * dp
        n_tot = n_local * tp
        dp_idx = lax.axis_index("dp")
        tp_idx = lax.axis_index("tp")
        node_base = tp_idx * n_local
        g_pod_idx = (dp_idx * p_local + jnp.arange(p_local)).astype(jnp.uint32)
        g_node_idx = (node_base + jnp.arange(n_local)).astype(jnp.uint32)
        if use_pallas:
            # Loop-invariant transposed node operands (kernel layout).
            labels_t, taints_t, aff_t = node_labels.T, node_taints.T, node_aff.T
            pref_t, tsoft_t = node_pref.T, node_taints_soft.T

        if constrained:
            from ..ops.constraints import (
                augment_round_state,
                blocked_block,
                constraint_commit,
                constraint_filter,
                round_blocked_masks,
            )

            named = dict(zip(CONSTRAINT_KEYS, cargs))
            cpods = {k: named[k] for k in CONSTRAINT_KEYS[:_N_PODKEYS]}
            cmeta = {k: named[k] for k in CONSTRAINT_KEYS[_N_PODKEYS : _N_PODKEYS + _N_METAKEYS]}
            cst0 = {k: named[k] for k in CONSTRAINT_KEYS[_N_PODKEYS + _N_METAKEYS :]}
            # Round-carried conflict state, replicated like the rest of the
            # constraint carry (ops/assign.py twin).
            cst0 = augment_round_state(jnp, cst0, cmeta, hard_pa=hard_pa)
            cst0["stall"] = jnp.int32(0)
            # This device's dp rows of the (replicated) pod bitmaps.
            blk_l = {k: lax.dynamic_slice_in_dim(v, dp_idx * p_local, p_local) for k, v in cpods.items()}
            g_ranks = jnp.arange(p_tot, dtype=jnp.uint32)
        else:
            cst0 = {}

        def cond(state):
            _, _, _, go, rounds, cst = state
            keep = (rounds < max_rounds) & go
            if constrained:
                keep = keep & (cst["stall"] < STALL_ROUNDS)
            return keep

        def body(state):
            avail, assigned, active, _, rounds, cst = state

            # 1. choose: local tile (with the constraint-blocked columns of
            # this shard when constrained), then argmax across the tp axis.
            blocked_l = sps_dec_l = sp_pen_l = ppa_w_l = ppa_cnt_l = None
            spd_dec_l = sp_lvl_l = None
            cons_pod_l = cons_node_l = None
            if constrained:
                masks = round_blocked_masks(jnp, cst, cmeta, soft_spread=soft_spread, soft_pa=soft_pa, hard_pa=hard_pa)  # [·, n_tot]
                # Node-axis masks slice to this shard's columns; pa_inactive
                # is per-TERM ([Ta], no node axis) and stays whole.
                lm = {
                    k: (v if k == "pa_inactive" else lax.dynamic_slice_in_dim(v, node_base, n_local, axis=1))
                    for k, v in masks.items()
                }
                if use_pallas:
                    # Constrained kernel operands over this shard's sliced
                    # masks — the SAME helpers as ops/assign._choose, so the
                    # zero-fill and PA-gating conventions have one home.
                    from ..ops.pallas_choose import (
                        constrained_kernel_node_operands,
                        constrained_kernel_pod_operands,
                    )

                    cons_node_l, pa_inactive = constrained_kernel_node_operands(blk_l, lm, n_local)
                    cons_pod_l = constrained_kernel_pod_operands(blk_l, pa_inactive)
                else:
                    blocked_l = blocked_block(jnp, blk_l, lm)  # [p_local, n_local]
                    if soft_spread:
                        sps_dec_l = blk_l["pod_sps_declares"]
                        sp_pen_l = lm["sp_penalty_node"]
                    spd_dec_l = blk_l["pod_sp_declares"]
                    sp_lvl_l = lm["sp_level_node"]
                    if soft_pa:
                        ppa_w_l = blk_l["pod_ppa_w"]
                        ppa_cnt_l = lm["ppa_cnt_node"]
            if use_pallas:
                from ..ops.pallas_choose import build_node_info, choose_block_pallas

                idx_l, _has_l, best_l = choose_block_pallas(
                    req, sel, selc, ntol, aff, has_aff, pref_w, ntol_soft, active, g_pod_idx,
                    build_node_info(avail, node_alloc, node_valid),
                    labels_t, taints_t, aff_t, pref_t, tsoft_t, w,
                    salt=rounds, cons_pod=cons_pod_l, cons_node=cons_node_l,
                    node_offset=node_base, interpret=pallas_interpret, return_best=True,
                )
            else:
                best_l, idx_l, _ = _local_choose(
                    avail, active, req, sel, selc, ntol, aff, has_aff, pref_w, ntol_soft, node_alloc, node_labels,
                    node_taints, node_aff, node_valid, node_pref, node_taints_soft, w, g_pod_idx, g_node_idx,
                    blocked=blocked_l, sps_declares=sps_dec_l, sp_penalty=sp_pen_l,
                    spd_declares=spd_dec_l, sp_level=sp_lvl_l,
                    ppa_w=ppa_w_l, ppa_cnt=ppa_cnt_l, salt=rounds,
                )
            bests = lax.all_gather(best_l, "tp")  # [tp, p_local]
            idxs = lax.all_gather(idx_l + node_base, "tp")
            best, choice = bests[0], idxs[0]
            for k in range(1, tp):
                take = (bests[k] > best) | ((bests[k] == best) & (idxs[k] < choice))
                best = jnp.where(take, bests[k], best)
                choice = jnp.where(take, idxs[k], choice)
            has = jnp.isfinite(best)
            cand = active & has

            # 2. accept: gather all claims (already in global priority order).
            g_choice = lax.all_gather(jnp.where(cand, choice, n_tot), "dp", tiled=True)  # [P]
            g_req = lax.all_gather(jnp.where(cand[:, None], req, 0), "dp", tiled=True)  # [P,2]
            in_range = (g_choice >= node_base) & (g_choice < node_base + n_local)
            ch_local = jnp.where(in_range, g_choice - node_base, n_local).astype(jnp.int32)
            claim = jnp.where(in_range[:, None], g_req, 0)

            order = jnp.argsort(ch_local, stable=True)
            ch_s = ch_local[order]
            claim_s = claim[order]
            is_start = jnp.concatenate([jnp.ones((1,), bool), ch_s[1:] != ch_s[:-1]])[:, None]
            _, within = lax.associative_scan(_seg_scan_op, (is_start, claim_s))
            avail_ext = jnp.concatenate([avail, jnp.zeros((1, avail.shape[1]), avail.dtype)], axis=0)
            acc_s = (within <= avail_ext[ch_s]).all(-1) & (ch_s < n_local)
            accepted_rng = jnp.zeros((p_tot,), bool).at[order].set(acc_s)

            # Flags across node shards are disjoint → psum replicates the
            # global accepted set on every device.
            accepted = lax.psum(accepted_rng.astype(jnp.int32), "tp") > 0

            # 3. constraints: filter + state commit run REPLICATED — every
            # device holds the same global claims, bitmaps, and state, so
            # every device computes the identical result (no collective).
            if constrained:
                gi = jnp.minimum(g_choice, n_tot - 1).astype(jnp.int32)  # clamp the non-claimant sentinel
                accepted = constraint_filter(jnp, accepted, gi, g_ranks, cpods, cst, cmeta, hard_pa=hard_pa)
                stall = jnp.where(accepted.any(), jnp.int32(0), cst["stall"] + 1)
                cst = constraint_commit(jnp, accepted, gi, cpods, cst, cmeta, soft_spread=soft_spread, soft_pa=soft_pa, hard_pa=hard_pa)
                cst["stall"] = stall

            # 4. capacity commit from the FILTERED accepted set; each column
            # scatter-subtracts its own nodes.
            acc_here = accepted & in_range
            dec = jnp.zeros((n_local + 1, avail.shape[1]), jnp.int32).at[ch_local].add(jnp.where(acc_here[:, None], claim, 0))
            avail = avail - dec[:n_local]
            acc_local = lax.dynamic_slice(accepted, (dp_idx * p_local,), (p_local,))

            assigned = jnp.where(acc_local, choice, assigned)
            was_active = active  # round-start actives (not yet rebound)
            new_active = cand & ~acc_local
            if constrained and hard_pa:
                # PA declarers blocked everywhere stay active while ANY
                # pending PA term gained a match this round (see
                # ops/assign.py).  `accepted` and the pod bitmaps (cpods)
                # are global and replicated, so every device computes the
                # same flag; the per-pod gate uses this dp shard's rows.
                new_match = (cpods["pod_pa_matched"] * accepted[:, None].astype(jnp.float32)).sum(axis=0) > 0
                pa_hope = (blk_l["pod_pa_declares"].sum(axis=1) > 0) & new_match.any()
                new_active = new_active | (was_active & ~has & pa_hope)
            active = new_active
            n_active = lax.psum(active.sum(), "dp")
            return avail, assigned, active, n_active > 0, rounds + 1, cst

        state0 = (
            node_avail,
            jnp.full((p_local,), -1, jnp.int32),
            valid,
            lax.psum(valid.sum(), "dp") > 0,
            jnp.int32(0),
            cst0,
        )
        avail, assigned, _, _, rounds, _ = lax.while_loop(cond, body, state0)
        return assigned, rounds, avail

    extra_specs = (P(),) * len(CONSTRAINT_KEYS) if constrained else ()
    return jax.shard_map(
        local_fn,
        mesh=mesh,
        in_specs=IN_SPECS + extra_specs,
        out_specs=(P("dp"), P(), P("tp", None)),
        # The while-carry mixes tp-varying (avail) and dp-varying (assigned)
        # state that converges by construction; VMA inference can't see that.
        check_vma=False,
    )


# shard_map input layout, shared with parallel/multihost.py: node tensors
# over tp, pod tensors (pre-permuted to priority order) over dp, weights
# replicated; constrained cycles append CONSTRAINT_KEYS operands, all P().
IN_SPECS = (
    P("tp", None),  # node_alloc
    P("tp", None),  # node_avail
    P("tp", None),  # node_labels
    P("tp", None),  # node_taints
    P("tp", None),  # node_aff
    P("tp"),  # node_valid
    P("tp", None),  # node_pref
    P("tp", None),  # node_taints_soft
    P("dp", None),  # pod_req
    P("dp", None),  # pod_sel
    P("dp"),  # pod_sel_count
    P("dp", None),  # pod_ntol
    P("dp", None),  # pod_aff
    P("dp"),  # pod_has_aff
    P("dp", None),  # pod_pref_w
    P("dp", None),  # pod_ntol_soft
    P("dp"),  # pod_valid (already priority-permuted)
    P(),  # weights
)


# shape: (cons: obj, n_pad_from: int, n_pad_to: int) -> dict
def constraint_operands(cons, n_pad_from: int, n_pad_to: int) -> dict:
    """Numpy constraint operands in CONSTRAINT_KEYS order (as a dict), with
    the node axis padded from the pack's padding to the mesh's tp multiple.
    Pod bitmaps are returned in PACK order — the caller permutes + pads them
    alongside the pod tensors."""
    extra = n_pad_to - n_pad_from
    ops = {}
    ops.update(cons.pod_arrays())
    meta = cons.meta_arrays()
    state = cons.state_arrays()
    ops["node_dom_c"] = np.pad(meta["node_dom_c"], ((0, extra), (0, 0)))
    for k in ("term_uses_dom", "pa_uses_dom", "ppa_uses_dom", "sp_uses_dom", "sp_skew", "sps_uses_dom", "sp_dom_sel"):
        ops[k] = meta[k]
    for k in ("aa_dom_m", "aa_dom_c", "pa_dom_m", "ppa_dom_cnt", "sp_counts", "sps_counts"):
        ops[k] = state[k]
    ops["aa_node_m"] = np.pad(state["aa_node_m"], ((0, 0), (0, extra)))
    ops["aa_node_c"] = np.pad(state["aa_node_c"], ((0, 0), (0, extra)))
    ops["pa_node_m"] = np.pad(state["pa_node_m"], ((0, 0), (0, extra)))
    ops["ppa_node_cnt"] = np.pad(state["ppa_node_cnt"], ((0, 0), (0, extra)))
    return ops


@lru_cache(maxsize=64)
def _build_sharded_fn(
    mesh,
    max_rounds: int,
    constrained: bool = False,
    soft_spread: bool = False,
    soft_pa: bool = False,
    hard_pa: bool = True,
    use_pallas: bool = False,
    pallas_interpret: bool = False,
):
    """Jitted (mesh, max_rounds)-specialised cycle fn — cached so repeated
    cycles reuse the compiled executable (jit re-specialises per shape)."""
    dp = mesh.shape["dp"]
    sharded = _build_shard_map(mesh, max_rounds, constrained, soft_spread, soft_pa, hard_pa, use_pallas, pallas_interpret)

    @jax.jit
    def run(a, c):
        p_tot = a["pod_req"].shape[0]
        # Permute BEFORE dp padding: ranks feed the score-jitter hash and
        # must equal the unpadded native backend's (see ops/assign.py).
        perm = jnp.argsort(-a["pod_prio"], stable=True)
        pods = {k: a[k][perm] for k in POD_KEYS}
        cpods = {k: c[k][perm] for k in CONSTRAINT_KEYS[:_N_PODKEYS]} if constrained else {}
        extra = (-p_tot) % dp
        if extra:
            pad = lambda v: jnp.pad(v, ((0, extra),) + ((0, 0),) * (v.ndim - 1))  # noqa: E731
            pods = {k: pad(v) for k, v in pods.items()}
            cpods = {k: pad(v) for k, v in cpods.items()}
        cargs = tuple(cpods[k] if i < _N_PODKEYS else c[k] for i, k in enumerate(CONSTRAINT_KEYS)) if constrained else ()
        node_args = tuple(
            a[k]
            for k in (
                "node_alloc", "node_avail", "node_labels", "node_taints", "node_aff", "node_valid",
                "node_pref", "node_taints_soft",
            )
        )
        assigned_p, rounds, avail = sharded(
            *node_args,
            *(pods[k] for k in POD_KEYS),
            a["weights"],
            *cargs,
        )
        assigned = jnp.full((p_tot,), -1, jnp.int32).at[perm].set(assigned_p[:p_tot])
        return assigned, rounds, avail

    return run


# shape: (mesh: obj, arrays: dict, weights: [W] f32, max_rounds: int,
#   constraints: dict, soft_spread: bool, soft_pa: bool, hard_pa: bool,
#   use_pallas: bool, pallas_interpret: bool) -> ([P] i32, scalar i32, [N, R] i32)
def sharded_assign_cycle(
    mesh, arrays: dict, weights, max_rounds: int = 32, constraints: dict | None = None,
    soft_spread: bool = False, soft_pa: bool = False, hard_pa: bool = True,
    use_pallas: bool = False, pallas_interpret: bool = False,
):
    """Run one cycle over the mesh. ``arrays`` are the PackedCluster device
    arrays with N pre-padded to a tp multiple (pods pad internally, post-
    permute); ``constraints`` the :func:`constraint_operands` dict for
    constrained cycles.  Returns (assigned [P], rounds, avail [N_padded,2])."""
    assert arrays["node_avail"].shape[0] % mesh.shape["tp"] == 0
    a = dict(arrays)
    a["weights"] = np.asarray(weights, dtype=np.float32)
    run = _build_sharded_fn(
        mesh, max_rounds, constraints is not None, soft_spread, soft_pa, hard_pa, use_pallas, pallas_interpret
    )
    return run(a, constraints if constraints is not None else {})


class ShardedBackend(SchedulingBackend):
    """SchedulingBackend over a device mesh — DP×TP distribution of the
    cycle, including constrained (anti-affinity / topology-spread) cycles
    via replicated domain state.  Drop-in for TpuBackend; used by
    dryrun_multichip, the CLI ``--backend=tpu-sharded``, and the multi-chip
    benches."""

    name = "tpu-sharded"
    # One mesh program at a time: concurrent shard solves would interleave
    # collective launches, which deadlocks multi-controller runtimes (and
    # buys nothing on a single mesh — the devices are shared anyway).
    supports_concurrent_shards = False

    def __init__(self, mesh=None, tp: int | None = None, use_pallas: bool | None = None, pallas_interpret: bool = False):
        self.mesh = mesh if mesh is not None else make_mesh(tp=tp)
        # The fused kernel runs compiled on TPU meshes only; other platforms
        # need interpret mode (explicitly requested — tests, dryrun).
        platform = next(iter(self.mesh.devices.flat)).platform
        if use_pallas is None:
            use_pallas = platform == "tpu"
        self.use_pallas = use_pallas
        self.pallas_interpret = pallas_interpret or (use_pallas and platform != "tpu")
        # First-use proving guard, per kernel variant — the sharded twin of
        # TpuBackend's: until the pallas shard program survives one real
        # compile+run, a failure downgrades to the (bit-identical) jnp shard
        # program instead of killing the cycle.
        self._proven_variants: set[bool] = set()
        self._disabled_variants: set[bool] = set()
        self._pallas_strikes: dict[bool, int] = {False: 0, True: 0}

    def _dispatch(self, a, c, profile, soft_spread, soft_pa, hard_pa, use_pallas):
        if jax.process_count() > 1:
            # Multi-controller runtime: host-local numpy can't feed a jit
            # over non-addressable devices — route through the global-
            # array path (parallel/multihost.py; same shard_map program).
            from .multihost import sharded_assign_multihost

            assigned, rounds = sharded_assign_multihost(
                self.mesh, a, profile.weights(), profile.max_rounds, constraints=c,
                soft_spread=soft_spread, soft_pa=soft_pa, hard_pa=hard_pa,
                use_pallas=use_pallas, pallas_interpret=self.pallas_interpret,
            )
            return np.asarray(assigned), int(rounds)
        assigned, rounds, _avail = sharded_assign_cycle(
            self.mesh, a, profile.weights(), profile.max_rounds, constraints=c,
            soft_spread=soft_spread, soft_pa=soft_pa, hard_pa=hard_pa,
            use_pallas=use_pallas, pallas_interpret=self.pallas_interpret,
        )
        return np.asarray(jax.device_get(assigned)), int(rounds)

    # shape: (packed: obj, profile: obj) -> ([P] i32, scalar i32)
    # bucket: n_pad
    def assign(self, packed: PackedCluster, profile: SchedulingProfile) -> tuple[np.ndarray, int]:
        from ..errors import BackendUnavailable

        tp = self.mesh.shape["tp"]
        a = dict(packed.device_arrays())
        # Node padding to the tp multiple happens here; pod padding to the dp
        # multiple happens inside the jitted run, after the priority permute.
        n_pad = round_up(packed.padded_nodes, tp)
        for k in ("node_alloc", "node_avail", "node_labels", "node_taints", "node_aff", "node_pref", "node_taints_soft"):
            a[k] = np.pad(a[k], ((0, n_pad - packed.padded_nodes), (0, 0)))
        a["node_valid"] = np.pad(a["node_valid"], ((0, n_pad - packed.padded_nodes),))
        cons = packed.constraints
        c = constraint_operands(cons, packed.padded_nodes, n_pad) if cons is not None else None
        soft_spread = cons is not None and cons.n_spread_soft > 0
        soft_pa = cons is not None and cons.n_ppa_terms > 0
        hard_pa = cons is not None and cons.n_pa_terms > 0
        variant = cons is not None
        from ..ops.pallas_choose import pallas_kernel_supported

        use_pallas = self.use_pallas and pallas_kernel_supported(a, a) and variant not in self._disabled_variants
        if use_pallas and variant not in self._proven_variants:
            try:
                out = self._dispatch(a, c, profile, soft_spread, soft_pa, hard_pa, True)
                self._proven_variants.add(variant)
                return out
            except jax.errors.JaxRuntimeError as e:
                # Transient fault or Mosaic rejection — indistinguishable;
                # strike-based like TpuBackend: native fallback this cycle,
                # kernel variant disabled after two strikes.
                self._pallas_strikes[variant] += 1
                if self._pallas_strikes[variant] >= 2:
                    import logging

                    logging.getLogger("tpu_scheduler.backend").warning(
                        "sharded pallas %s kernel failed %d first-use attempts; disabling that variant",
                        "constrained" if variant else "plain",
                        self._pallas_strikes[variant],
                    )
                    self._disabled_variants.add(variant)
                raise BackendUnavailable(f"sharded backend runtime failure: {e}") from e
            except Exception as e:  # noqa: BLE001 — first-compile guard (see TpuBackend)
                import logging

                logging.getLogger("tpu_scheduler.backend").warning(
                    "sharded pallas %s kernel failed on first use (%s: %s); disabling that variant, retrying jnp path",
                    "constrained" if variant else "plain",
                    type(e).__name__,
                    e,
                )
                self._disabled_variants.add(variant)
                use_pallas = False
        try:
            return self._dispatch(a, c, profile, soft_spread, soft_pa, hard_pa, use_pallas)
        except jax.errors.JaxRuntimeError as e:
            # Same contract as TpuBackend: device-runtime failures become the
            # explicit unavailability signal the controller's fallback keys
            # on; programming errors propagate.
            raise BackendUnavailable(f"sharded backend runtime failure: {e}") from e
