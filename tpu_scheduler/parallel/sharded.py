"""Multi-chip scheduling cycle: the auction of ops/assign.py distributed over
a (dp, tp) mesh with jax.shard_map — pods sharded over ``dp``, nodes over
``tp``, XLA collectives over ICI (SURVEY.md §2b).

Identical results to the single-device path, by construction:

  choose   — each device scores its pod shard against its node shard; the
             per-pod best node is reduced across ``tp`` with all_gather +
             (score desc, node-index asc) tie-break, which equals the global
             first-max argmax.
  accept   — pod claims (choice, request) are all_gathered over ``dp`` in
             global priority order (pods are pre-permuted before sharding,
             so the tiled gather *is* rank order); each tp column runs the
             segmented saturating prefix acceptance for the nodes it owns;
             per-pod accepted flags come back via a tp psum (node shards are
             disjoint).
  commit   — each column scatter-subtracts its own nodes; every dp row in a
             column computes identically, keeping replicated state in sync
             without extra traffic.

Per-round traffic: O(P) int32s over dp + O(P) over tp — a few MB at 100k
pods, ICI-trivial next to the [P/dp × N/tp] compute tiles.

The same code scales to multi-host (DCN) by building the mesh over
``jax.distributed`` processes; nothing below is aware of the difference.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ..models.profiles import SchedulingProfile
from ..ops.assign import _seg_scan_op
from ..ops.masks import feasibility_block
from ..ops.pack import PackedCluster, round_up
from ..ops.score import score_block
from ..backends.base import SchedulingBackend
from .mesh import make_mesh

__all__ = ["sharded_assign_cycle", "ShardedBackend"]


def _local_choose(
    avail, active, req, sel, selc, ntol, aff, has_aff, pref_w, ntol_soft, node_alloc, node_labels, node_taints,
    node_aff, node_valid, node_pref, node_taints_soft, weights, pod_idx, node_idx,
):
    """Best local node per pod of this shard: (best_score, local idx, has).

    ``pod_idx``/``node_idx`` are *global* (rank-space) indices so the score
    jitter hash matches the single-device path exactly."""
    m = feasibility_block(
        jnp, req, sel, selc, active, avail, node_labels, node_valid, ntol, node_taints, aff, has_aff, node_aff
    )
    sc = score_block(
        jnp, req, node_alloc, avail, weights, pod_idx, node_idx,
        pod_pref_w=pref_w, node_pref=node_pref, pod_ntol_soft=ntol_soft, node_taints_soft=node_taints_soft,
    )
    sc = jnp.where(m, sc, -jnp.inf)
    return jnp.max(sc, axis=1), jnp.argmax(sc, axis=1).astype(jnp.int32), m.any(axis=1)


@lru_cache(maxsize=64)
def _build_shard_map(mesh, max_rounds: int):
    """The shard_map'd per-device cycle fn (not yet jitted/wrapped) — shared
    by the single-process run wrapper below and the multi-host path
    (parallel/multihost.py), so both execute the identical program."""
    dp = mesh.shape["dp"]
    tp = mesh.shape["tp"]

    def local_fn(
        node_alloc, node_avail, node_labels, node_taints, node_aff, node_valid, node_pref, node_taints_soft,
        req, sel, selc, ntol, aff, has_aff, pref_w, ntol_soft, valid, w,
    ):
        p_local = req.shape[0]
        n_local = node_avail.shape[0]
        p_tot = p_local * dp
        n_tot = n_local * tp
        dp_idx = lax.axis_index("dp")
        tp_idx = lax.axis_index("tp")
        node_base = tp_idx * n_local
        g_pod_idx = (dp_idx * p_local + jnp.arange(p_local)).astype(jnp.uint32)
        g_node_idx = (node_base + jnp.arange(n_local)).astype(jnp.uint32)

        def cond(state):
            _, _, _, go, rounds = state
            return (rounds < max_rounds) & go

        def body(state):
            avail, assigned, active, _, rounds = state

            # 1. choose: local tile, then argmax across the tp axis.
            best_l, idx_l, _ = _local_choose(
                avail, active, req, sel, selc, ntol, aff, has_aff, pref_w, ntol_soft, node_alloc, node_labels,
                node_taints, node_aff, node_valid, node_pref, node_taints_soft, w, g_pod_idx, g_node_idx,
            )
            bests = lax.all_gather(best_l, "tp")  # [tp, p_local]
            idxs = lax.all_gather(idx_l + node_base, "tp")
            best, choice = bests[0], idxs[0]
            for k in range(1, tp):
                take = (bests[k] > best) | ((bests[k] == best) & (idxs[k] < choice))
                best = jnp.where(take, bests[k], best)
                choice = jnp.where(take, idxs[k], choice)
            has = jnp.isfinite(best)
            cand = active & has

            # 2. accept: gather all claims (already in global priority order).
            g_choice = lax.all_gather(jnp.where(cand, choice, n_tot), "dp", tiled=True)  # [P]
            g_req = lax.all_gather(jnp.where(cand[:, None], req, 0), "dp", tiled=True)  # [P,2]
            in_range = (g_choice >= node_base) & (g_choice < node_base + n_local)
            ch_local = jnp.where(in_range, g_choice - node_base, n_local).astype(jnp.int32)
            claim = jnp.where(in_range[:, None], g_req, 0)

            order = jnp.argsort(ch_local, stable=True)
            ch_s = ch_local[order]
            claim_s = claim[order]
            is_start = jnp.concatenate([jnp.ones((1,), bool), ch_s[1:] != ch_s[:-1]])[:, None]
            _, within = lax.associative_scan(_seg_scan_op, (is_start, claim_s))
            avail_ext = jnp.concatenate([avail, jnp.zeros((1, 2), avail.dtype)], axis=0)
            acc_s = (within <= avail_ext[ch_s]).all(-1) & (ch_s < n_local)
            accepted_rng = jnp.zeros((p_tot,), bool).at[order].set(acc_s)

            # 3. commit locally; flags across node shards are disjoint → psum.
            dec = jnp.zeros((n_local + 1, 2), jnp.int32).at[ch_local].add(jnp.where(accepted_rng[:, None], claim, 0))
            avail = avail - dec[:n_local]
            accepted = lax.psum(accepted_rng.astype(jnp.int32), "tp") > 0
            acc_local = lax.dynamic_slice(accepted, (dp_idx * p_local,), (p_local,))

            assigned = jnp.where(acc_local, choice, assigned)
            active = cand & ~acc_local
            n_active = lax.psum(active.sum(), "dp")
            return avail, assigned, active, n_active > 0, rounds + 1

        state0 = (
            node_avail,
            jnp.full((p_local,), -1, jnp.int32),
            valid,
            lax.psum(valid.sum(), "dp") > 0,
            jnp.int32(0),
        )
        avail, assigned, _, _, rounds = lax.while_loop(cond, body, state0)
        return assigned, rounds, avail

    return jax.shard_map(
        local_fn,
        mesh=mesh,
        in_specs=IN_SPECS,
        out_specs=(P("dp"), P(), P("tp", None)),
        # The while-carry mixes tp-varying (avail) and dp-varying (assigned)
        # state that converges by construction; VMA inference can't see that.
        check_vma=False,
    )


# shard_map input layout, shared with parallel/multihost.py: node tensors
# over tp, pod tensors (pre-permuted to priority order) over dp, weights
# replicated.
IN_SPECS = (
    P("tp", None),  # node_alloc
    P("tp", None),  # node_avail
    P("tp", None),  # node_labels
    P("tp", None),  # node_taints
    P("tp", None),  # node_aff
    P("tp"),  # node_valid
    P("tp", None),  # node_pref
    P("tp", None),  # node_taints_soft
    P("dp", None),  # pod_req
    P("dp", None),  # pod_sel
    P("dp"),  # pod_sel_count
    P("dp", None),  # pod_ntol
    P("dp", None),  # pod_aff
    P("dp"),  # pod_has_aff
    P("dp", None),  # pod_pref_w
    P("dp", None),  # pod_ntol_soft
    P("dp"),  # pod_valid (already priority-permuted)
    P(),  # weights
)


@lru_cache(maxsize=64)
def _build_sharded_fn(mesh, max_rounds: int):
    """Jitted (mesh, max_rounds)-specialised cycle fn — cached so repeated
    cycles reuse the compiled executable (jit re-specialises per shape)."""
    dp = mesh.shape["dp"]
    sharded = _build_shard_map(mesh, max_rounds)

    @jax.jit
    def run(a, w):
        p_tot = a["pod_req"].shape[0]
        # Permute BEFORE dp padding: ranks feed the score-jitter hash and
        # must equal the unpadded native backend's (see ops/assign.py).
        perm = jnp.argsort(-a["pod_prio"], stable=True)
        req = a["pod_req"][perm]
        sel = a["pod_sel"][perm]
        selc = a["pod_sel_count"][perm]
        ntol = a["pod_ntol"][perm]
        aff = a["pod_aff"][perm]
        has_aff = a["pod_has_aff"][perm]
        pref_w = a["pod_pref_w"][perm]
        ntol_soft = a["pod_ntol_soft"][perm]
        valid = a["pod_valid"][perm]
        extra = (-p_tot) % dp
        if extra:
            req = jnp.pad(req, ((0, extra), (0, 0)))
            sel = jnp.pad(sel, ((0, extra), (0, 0)))
            selc = jnp.pad(selc, ((0, extra),))
            ntol = jnp.pad(ntol, ((0, extra), (0, 0)))
            aff = jnp.pad(aff, ((0, extra), (0, 0)))
            has_aff = jnp.pad(has_aff, ((0, extra),))
            pref_w = jnp.pad(pref_w, ((0, extra), (0, 0)))
            ntol_soft = jnp.pad(ntol_soft, ((0, extra), (0, 0)))
            valid = jnp.pad(valid, ((0, extra),))
        assigned_p, rounds, avail = sharded(
            a["node_alloc"],
            a["node_avail"],
            a["node_labels"],
            a["node_taints"],
            a["node_aff"],
            a["node_valid"],
            a["node_pref"],
            a["node_taints_soft"],
            req,
            sel,
            selc,
            ntol,
            aff,
            has_aff,
            pref_w,
            ntol_soft,
            valid,
            w,
        )
        assigned = jnp.full((p_tot,), -1, jnp.int32).at[perm].set(assigned_p[:p_tot])
        return assigned, rounds, avail

    return run


def sharded_assign_cycle(mesh, arrays: dict, weights, max_rounds: int = 32):
    """Run one cycle over the mesh. ``arrays`` are the PackedCluster device
    arrays with N pre-padded to a tp multiple (pods pad internally, post-
    permute).  Returns (assigned [P], rounds, avail [N_padded,2])."""
    assert arrays["node_avail"].shape[0] % mesh.shape["tp"] == 0
    return _build_sharded_fn(mesh, max_rounds)(arrays, weights)


class ShardedBackend(SchedulingBackend):
    """SchedulingBackend over a device mesh — DP×TP distribution of the
    cycle.  Drop-in for TpuBackend; used by dryrun_multichip and the
    multi-chip benches."""

    name = "tpu-sharded"

    def __init__(self, mesh=None, tp: int | None = None):
        self.mesh = mesh if mesh is not None else make_mesh(tp=tp)

    def assign(self, packed: PackedCluster, profile: SchedulingProfile) -> tuple[np.ndarray, int]:
        if packed.constraints is not None:
            # The sharded cycle doesn't evaluate the anti-affinity/spread
            # tensors yet; dropping them silently would bind violating
            # placements.  Raising the tensor-budget signal routes the
            # controller to its exact host-side constrained phase.
            from ..ops.constraints import UntensorizableConstraints

            raise UntensorizableConstraints("sharded backend does not evaluate constraint tensors yet")
        try:
            tp = self.mesh.shape["tp"]
            a = dict(packed.device_arrays())
            # Node padding to the tp multiple happens here; pod padding to the dp
            # multiple happens inside the jitted run, after the priority permute.
            n_pad = round_up(packed.padded_nodes, tp)
            for k in ("node_alloc", "node_avail", "node_labels", "node_taints", "node_aff", "node_pref", "node_taints_soft"):
                a[k] = np.pad(a[k], ((0, n_pad - packed.padded_nodes), (0, 0)))
            a["node_valid"] = np.pad(a["node_valid"], ((0, n_pad - packed.padded_nodes),))
            assigned, rounds, _avail = sharded_assign_cycle(self.mesh, a, packed_weights(profile), profile.max_rounds)
            return np.asarray(jax.device_get(assigned)), int(rounds)
        except jax.errors.JaxRuntimeError as e:
            # Same contract as TpuBackend: device-runtime failures become the
            # explicit unavailability signal the controller's fallback keys
            # on; programming errors propagate.
            from ..errors import BackendUnavailable

            raise BackendUnavailable(f"sharded backend runtime failure: {e}") from e


def packed_weights(profile: SchedulingProfile):
    return jnp.asarray(profile.weights())
