"""Multi-host (DCN) execution of the sharded scheduling cycle.

Single-process JAX can hand plain numpy arrays to a jitted shard_map and let
the runtime scatter them; across processes that is impossible — every
process owns only its addressable shards.  This module is the thin layer
that difference requires:

  • the priority permute + block padding run host-side in numpy (bit-
    identical to the jnp ops in parallel/sharded.py's single-process
    wrapper: both are stable argsorts on int32 + zero pads);
  • inputs become global ``jax.Array``s via ``make_array_from_callback``
    against the shard_map IN_SPECS, so each process materialises exactly its
    shards (node tensors split over tp, pod tensors over dp, weights
    replicated);
  • the *same* shard_map program as the single-process path executes
    (parallel/sharded.py::_build_shard_map — per-round all_gather over tp on
    ICI, one O(P) pod-claim all_gather over dp on DCN);
  • the dp-sharded result is re-replicated with
    ``multihost_utils.process_allgather`` so every host sees every binding.

Every process must call :func:`sharded_assign_multihost` with the same
arrays (each packs the same snapshot — packing is deterministic), mirroring
how every host of a TPU pod slice feeds the same program.

Proven by tests/test_multihost.py: two OS processes, a TCP coordinator
(``mesh.init_distributed``), 4 virtual CPU devices each → a dp=4×tp=2 mesh
spanning both, with bit-parity against the single-process native oracle.
"""

from __future__ import annotations

from functools import lru_cache

import numpy as np

from ..ops.pack import round_up
from .sharded import _N_PODKEYS, CONSTRAINT_KEYS, IN_SPECS, POD_KEYS, _build_shard_map

__all__ = ["sharded_assign_multihost", "make_global_array"]


@lru_cache(maxsize=64)
def _jitted_shard_map(
    mesh,
    max_rounds: int,
    constrained: bool = False,
    soft_spread: bool = False,
    soft_pa: bool = False,
    hard_pa: bool = True,
    use_pallas: bool = False,
    pallas_interpret: bool = False,
):
    """Cached jit of the shard_map program — without this every cycle would
    re-trace and re-compile (the single-process twin _build_sharded_fn is
    lru_cached for the same reason)."""
    import jax

    return jax.jit(_build_shard_map(mesh, max_rounds, constrained, soft_spread, soft_pa, hard_pa, use_pallas, pallas_interpret))


def make_global_array(mesh, spec, arr: np.ndarray):
    """Build a global jax.Array from a (process-replicated) numpy array —
    each process materialises only its addressable shards."""
    import jax
    from jax.sharding import NamedSharding

    return jax.make_array_from_callback(arr.shape, NamedSharding(mesh, spec), lambda idx: arr[idx])


# bucket: n_pad extra
def sharded_assign_multihost(
    mesh, arrays: dict, weights, max_rounds: int = 32, constraints: dict | None = None,
    soft_spread: bool = False, soft_pa: bool = False, hard_pa: bool = True,
    use_pallas: bool = False, pallas_interpret: bool = False,
):
    """Run one scheduling cycle over a (possibly multi-host) mesh.

    ``arrays`` is the PackedCluster ``device_arrays()`` dict (numpy, same on
    every process); ``constraints`` the sharded.constraint_operands dict
    (node axes already padded to this mesh's tp multiple) for constrained
    cycles — the constraint tensors are replicated, exactly as in the
    single-process path.  Returns (assigned [P] np.int32, rounds int)
    replicated to every process.
    """
    import jax
    from jax.experimental import multihost_utils

    from ..ops.pallas_choose import pallas_kernel_supported

    if use_pallas and not pallas_kernel_supported(arrays, arrays):
        # Unsupported cluster shapes (extended-resource or vocab widths)
        # ride the bit-identical jnp shard program — same guard as the
        # other two use_pallas entry points.
        use_pallas = False

    dp, tp = mesh.shape["dp"], mesh.shape["tp"]
    a = dict(arrays)

    # Node padding to the tp multiple (host-side twin of ShardedBackend.assign).
    n0 = a["node_avail"].shape[0]
    n_pad = round_up(n0, tp)
    for k in ("node_alloc", "node_avail", "node_labels", "node_taints", "node_aff", "node_pref", "node_taints_soft"):
        a[k] = np.pad(a[k], ((0, n_pad - n0), (0, 0)))
    a["node_valid"] = np.pad(a["node_valid"], ((0, n_pad - n0),))

    # Priority permute BEFORE dp padding (rank parity with the native path),
    # then pad pods to the dp multiple.
    p_tot = a["pod_req"].shape[0]
    perm = np.argsort(-a["pod_prio"], kind="stable")
    pods = {k: a[k][perm] for k in POD_KEYS}
    cpods = {k: constraints[k][perm] for k in CONSTRAINT_KEYS[:_N_PODKEYS]} if constraints is not None else {}
    extra = (-p_tot) % dp
    if extra:
        for k, v in pods.items():
            pods[k] = np.pad(v, ((0, extra),) + ((0, 0),) * (v.ndim - 1))
        for k, v in cpods.items():
            cpods[k] = np.pad(v, ((0, extra), (0, 0)))

    operands = (
        a["node_alloc"],
        a["node_avail"],
        a["node_labels"],
        a["node_taints"],
        a["node_aff"],
        a["node_valid"],
        a["node_pref"],
        a["node_taints_soft"],
        *(pods[k] for k in POD_KEYS),
        np.asarray(weights, dtype=np.float32),
    )
    specs = IN_SPECS
    if constraints is not None:
        from jax.sharding import PartitionSpec as P

        operands = operands + tuple(
            cpods[k] if i < _N_PODKEYS else constraints[k] for i, k in enumerate(CONSTRAINT_KEYS)
        )
        specs = specs + (P(),) * len(CONSTRAINT_KEYS)
    global_ins = [make_global_array(mesh, spec, arr) for spec, arr in zip(specs, operands)]

    fn = _jitted_shard_map(
        mesh, max_rounds, constraints is not None, soft_spread, soft_pa, hard_pa, use_pallas, pallas_interpret
    )
    assigned_p, rounds, _avail = fn(*global_ins)

    assigned_full = np.asarray(multihost_utils.process_allgather(assigned_p, tiled=True))
    out = np.full((p_tot,), -1, dtype=np.int32)
    out[perm] = assigned_full[:p_tot]
    # rounds comes out of the shard_map replicated (out_spec P()) — every
    # process can read it locally, no gather needed.
    rounds_val = int(np.asarray(rounds.addressable_data(0)))
    return out, rounds_val
