"""coordination.k8s.io/v1 Lease objects + the client-side leader-election
algorithm (the kube client-go ``leaderelection`` recipe), shared by every
lease backend:

  * :class:`~tpu_scheduler.runtime.fake_api.FakeApiServer` — in-process
    store with resourceVersion compare-and-swap;
  * :class:`~tpu_scheduler.runtime.http_api.KubeApiClient` — the SAME
    algorithm over spec-shaped HTTP requests only (GET/POST/PUT Lease
    objects; no invented verbs), so it works against a real kube-apiserver.

The reference has no leader election (SURVEY.md §5); the capability anchor
is kube's own: a Lease object whose ``spec.holderIdentity`` names the
leader, renewed by CAS on ``metadata.resourceVersion`` — acquisition races
resolve at the server as update conflicts, never by server-side verbs.

Timestamps are RFC3339 MicroTime strings (kube's ``renewTime`` wire shape);
expiry is judged on the CALLER's clock (client-go semantics — the server
never decides leadership).
"""

from __future__ import annotations

from datetime import datetime, timezone
from typing import Callable

__all__ = [
    "LEASE_NAMESPACE",
    "format_micro_time",
    "parse_micro_time",
    "make_lease",
    "try_acquire_or_renew",
    "release",
]

# Where the scheduler parks its election Lease — kube-system, like
# kube-scheduler's own ``kube-system/kube-scheduler`` lease.
LEASE_NAMESPACE = "kube-system"


def format_micro_time(epoch: float) -> str:
    """RFC3339 with microseconds — kube MicroTime (e.g. 2026-07-30T12:00:00.000000Z)."""
    return datetime.fromtimestamp(epoch, tz=timezone.utc).strftime("%Y-%m-%dT%H:%M:%S.%fZ")


def parse_micro_time(s: str | None) -> float | None:
    if not s:
        return None
    try:
        return datetime.strptime(s, "%Y-%m-%dT%H:%M:%S.%fZ").replace(tzinfo=timezone.utc).timestamp()
    except ValueError:
        try:  # plain RFC3339 seconds (kube Time rather than MicroTime)
            return datetime.strptime(s, "%Y-%m-%dT%H:%M:%SZ").replace(tzinfo=timezone.utc).timestamp()
        except ValueError:
            return None


def make_lease(namespace: str, name: str, holder: str, duration_seconds: float, now: float) -> dict:
    return {
        "apiVersion": "coordination.k8s.io/v1",
        "kind": "Lease",
        "metadata": {"name": name, "namespace": namespace},
        "spec": {
            "holderIdentity": holder,
            "leaseDurationSeconds": int(duration_seconds),
            "acquireTime": format_micro_time(now),
            "renewTime": format_micro_time(now),
            "leaseTransitions": 0,
        },
    }


def try_acquire_or_renew(
    get: Callable[[], dict | None],
    create: Callable[[dict], bool],
    update: Callable[[dict], bool],
    namespace: str,
    name: str,
    holder: str,
    duration_seconds: float,
    now: float,
) -> bool:
    """One election round (client-go ``tryAcquireOrRenew``): create the
    Lease if absent, renew it if held by us, take it over if expired or
    released — all through ``create``/``update`` primitives that return
    False on a conflict (409), which is how a lost race reads.  Returns
    True iff the caller holds the lease afterwards."""
    lease = get()
    if lease is None:
        return create(make_lease(namespace, name, holder, duration_seconds, now))
    spec = lease.get("spec") or {}
    current = spec.get("holderIdentity") or ""
    renew = parse_micro_time(spec.get("renewTime"))
    held_duration = float(spec.get("leaseDurationSeconds") or duration_seconds)
    if current and current != holder and renew is not None and now < renew + held_duration:
        return False  # held by a live leader
    takeover = current != holder
    new_spec = {
        "holderIdentity": holder,
        "leaseDurationSeconds": int(duration_seconds),
        "acquireTime": format_micro_time(now) if takeover else spec.get("acquireTime", format_micro_time(now)),
        "renewTime": format_micro_time(now),
        "leaseTransitions": int(spec.get("leaseTransitions") or 0) + (1 if takeover else 0),
    }
    return update({**lease, "spec": new_spec})


def release(
    get: Callable[[], dict | None],
    update: Callable[[dict], bool],
    holder: str,
    now: float,
) -> None:
    """Voluntary hand-off (client-go ``release``): clear ``holderIdentity``
    and shrink the duration so any standby's next round takes over
    immediately.  Only the holder releases; a CAS conflict means someone
    else already took the lease — nothing left to do either way."""
    lease = get()
    if lease is None or (lease.get("spec") or {}).get("holderIdentity") != holder:
        return
    spec = lease["spec"]
    new_spec = {
        **spec,
        "holderIdentity": "",
        "leaseDurationSeconds": 1,
        "renewTime": format_micro_time(now),
    }
    update({**lease, "spec": new_spec})
