"""Sharded control plane — lease-per-shard ownership for active-active
controller replicas.

The single-leader election (runtime/lease.py + ``--leader-elect``) serializes
the WHOLE pending set behind one process: a leader crash mid-cycle stalls all
scheduling for up to ``lease_duration``.  This module partitions the pending
set into K shards so any replica can own any subset of them:

  • ``shard_for_name`` — stable hash (crc32, PYTHONHASHSEED-proof) of the pod
    full name; ``shard_of_pod`` pins every member of a gang to the GANG
    name's shard, so all-or-nothing admission survives partitioning (a gang
    split across owners could never look complete to any one replica).  The
    fleet layer (tpu_scheduler/fleet) can swap this flat hash for a
    topology-keyed ``ShardKeyer`` via ``ShardSet.set_keyer`` — each shard's
    node columns then form a contiguous topology slice.
  • one ``coordination.k8s.io`` Lease per shard (``tpu-scheduler-shard-<i>``),
    acquired/renewed through the SAME CAS primitives as the leader lease
    (fake_api.acquire_lease → lease.try_acquire_or_renew) — acquisition races
    resolve at the server as resourceVersion conflicts, never by new verbs.
  • ``ShardSet.refresh`` — one ownership round per scheduling cycle: renew
    what we hold, take over expired/released shards while under a
    proportional target (ceil(K / live replicas)), and RELEASE the excess
    when new replicas join so ownership rebalances without operator action.
    A replica that crashes simply stops renewing; its shards expire and the
    survivors absorb them within one lease TTL + one cycle — the takeover
    bound the sim scorecard's ``availability`` block holds at
    ``2 × lease_duration``.

Everything here is main-thread state called from the controller's cycle loop
(no background renewal thread: the cycle cadence IS the renewal cadence, so
``cycle_interval`` must stay below ``lease_duration`` — the controller warns
when it cannot know, the sim enforces it by construction).  Clocks are
injected, so simulated replicas replay bit-identically.
"""

from __future__ import annotations

import zlib
from dataclasses import dataclass, field

__all__ = [
    "SHARD_LEASE_PREFIX",
    "REPLICA_LEASE_PREFIX",
    "shard_for_name",
    "shard_of_pod",
    "shard_lease_name",
    "ShardDelta",
    "ShardSet",
]

# Lease-name prefix: shard i is owned through ``tpu-scheduler-shard-<i>`` in
# kube-system (LEASE_NAMESPACE), beside the single-leader lease.
SHARD_LEASE_PREFIX = "tpu-scheduler-shard-"

# Presence lease per replica (``tpu-scheduler-replica-<identity>``): a
# replica holding ZERO shards is otherwise invisible to the proportional
# target (shard holders are the only evidence), so incumbents would never
# release toward it.  Renewed every refresh; expiry removes the replica from
# everyone's live count, which is what raises the survivors' targets after a
# crash.
REPLICA_LEASE_PREFIX = "tpu-scheduler-replica-"


def shard_for_name(key: str, num_shards: int) -> int:
    """Stable shard index for an identity string (pod full name or gang
    name).  crc32, not ``hash()``: the assignment must agree across replica
    processes and survive restarts (PYTHONHASHSEED)."""
    if num_shards <= 1:
        return 0
    return zlib.crc32(key.encode()) % num_shards


def shard_of_pod(pod, num_shards: int) -> int:
    """A pod's shard — the GANG name's shard when the pod belongs to one
    (gang members must share an owner for atomic admission), its own full
    name's otherwise."""
    spec = pod.spec
    if spec is not None and spec.gang:
        return shard_for_name(spec.gang, num_shards)
    ns = pod.metadata.namespace or "default"
    return shard_for_name(f"{ns}/{pod.metadata.name}", num_shards)


def shard_lease_name(shard: int) -> str:
    return f"{SHARD_LEASE_PREFIX}{shard}"


@dataclass
class ShardDelta:
    """One refresh round's ownership changes."""

    owned: frozenset = frozenset()  # shards held after the round
    gained: frozenset = frozenset()  # newly acquired this round (takeover/rebalance targets)
    lost: frozenset = frozenset()  # held last round, not renewable now
    released: frozenset = frozenset()  # voluntarily released (rebalance)
    holders: dict = field(default_factory=dict)  # shard -> live holder identity ("" = unheld)
    resized: bool = False  # a newer shard-map generation was adopted this round (fleet/resize.py)


# protocol: machine shard-lease field=- init=free
# protocol: states: free | held | expired
# protocol: free -> held
# protocol: held -> free | expired
# protocol: expired -> held
# protocol: var released: 0..1 = 0
# protocol: action acquire: free -> held requires released == 0
# protocol: action renew: held -> held requires released == 0
# protocol: action release: held -> free effect released = 1
# protocol: env crash-ttl: held -> expired
# protocol: action takeover: expired -> held effect released = 0
# protocol: env thread-renew: free -> held requires released == 0
# protocol: invariant release-is-final: released == 1 implies state == free
# protocol: progress reclaimable: state == expired
class ShardSet:
    """Per-replica shard-ownership ledger over the lease API.

    The ``# protocol:`` contract above models one shard's lease from this
    replica's point of view (model-only, ``field=-``: the state lives in
    the API server, not in a field here).  ``release-is-final`` is the
    PR-7 race, now proved instead of regression-sampled: after a voluntary
    ``release_all`` the stale renew thread (``thread-renew``) must never
    re-acquire — only a fresh ``takeover`` by a live replica clears the
    released latch.  ``reclaimable`` proves a crash-expired lease can
    always be taken over.

    ``api`` needs ``acquire_lease(name, holder, duration)``,
    ``release_lease(name, holder)``, and ``get_lease(name)`` — the surface
    FakeApiServer, RemoteApiAdapter, and the chaos proxy all serve.
    """

    def __init__(self, api, num_shards: int, identity: str, lease_duration: float, clock, keyer=None):
        self.api = api
        self.num_shards = int(num_shards)
        self.identity = identity
        self.lease_duration = float(lease_duration)
        self.clock = clock
        self.owned: frozenset = frozenset()
        # Pluggable pod→shard assignment (fleet/keyer.ShardKeyer): topology
        # mode keys pods to contiguous topology-domain slices; None keeps
        # the historic flat crc32 exactly.
        self.keyer = keyer
        # Highest shard-map generation adopted so far (fleet/resize.py).
        self.map_generation = 0

    # -- assignment ---------------------------------------------------------

    def set_keyer(self, keyer) -> None:
        """Install (or clear) the fleet ShardKeyer.  The caller owns the
        consequences: a keying change moves pods between shards, so it must
        revalidate its pending view exactly as a takeover does."""
        self.keyer = keyer

    def shard_of(self, pod) -> int:
        if self.keyer is not None:
            return self.keyer.shard_of_pod(pod)
        return shard_of_pod(pod, self.num_shards)

    def owns_pod(self, pod) -> bool:
        return self.shard_of(pod) in self.owned

    def owns_name(self, pod_full: str) -> bool:
        """Ownership by pod full name only — the ledger-prune filter.  Gang
        pods may hash elsewhere via their gang name, so this is used ONLY to
        scope prunes conservatively, never for scheduling eligibility."""
        if self.keyer is not None:
            return self.keyer.shard_for_key(pod_full) in self.owned
        return shard_for_name(pod_full, self.num_shards) in self.owned

    # -- hardened lease primitives ------------------------------------------
    # Lease-endpoint brownouts (sim/chaos.py lease faults, a flaky remote
    # apiserver) REFUSE, never raise into the cycle: a failed acquire is a
    # lost CAS, a failed release leaves the lease to expire within one TTL,
    # a failed read reads as unheld — the CAS still arbitrates takeover.

    def _acquire(self, name: str) -> bool:
        try:
            return bool(self.api.acquire_lease(name, self.identity, self.lease_duration))
        except Exception:
            return False

    def _release(self, name: str) -> None:
        try:
            self.api.release_lease(name, self.identity)
        except Exception:
            pass

    def _get(self, name: str) -> dict | None:
        try:
            return self.api.get_lease(name)
        except Exception:
            return None

    # -- one ownership round ------------------------------------------------

    def _live_holders(self, now: float) -> dict[int, str]:
        """shard -> holder identity for every shard whose lease is live
        (unexpired, non-empty holder); absent shards map to ""."""
        holders: dict[int, str] = {}
        for s in range(self.num_shards):
            info = self._get(shard_lease_name(s))
            if info is not None and info.get("holder") and now < float(info.get("expires", 0.0)):
                holders[s] = info["holder"]
            else:
                holders[s] = ""
        return holders

    def _live_replicas(self, now: float, holders: dict[int, str]) -> int:
        """Count of live replicas (self included) from the presence leases;
        degrades to distinct shard holders when the API cannot list leases
        (a remote server without the collection route) — a zero-shard
        replica then waits for a lease to free instead of being rebalanced
        toward, which is safe, just slower."""
        live = {self.identity}
        lister = getattr(self.api, "list_lease_summaries", None)
        if lister is not None:
            for info in lister():
                if (
                    info["name"].startswith(REPLICA_LEASE_PREFIX)
                    and info.get("holder")
                    and now < float(info.get("expires", 0.0))
                ):
                    live.add(info["holder"])
        else:
            for s in sorted(holders):
                if holders[s]:
                    live.add(holders[s])
        return len(live)

    def _adopt_shard_map(self) -> bool:
        """Fold a newer published shard map (fleet/resize.py) into this
        replica's view before the ownership round: a merge releases leases
        beyond the new range (their pods re-key into the survivors), a
        split leaves the new orphan shards for the absorb pass.  Returns
        True when the shard COUNT changed (the caller re-keys and rebinds)."""
        from ..fleet.resize import read_shard_map

        info = read_shard_map(self.api)
        if info is None:
            return False
        gen, count = info
        if gen <= self.map_generation:
            return False
        self.map_generation = gen
        if count == self.num_shards:
            return False
        for s in sorted(self.owned):
            if s >= count:
                self._release(shard_lease_name(s))
        self.owned = frozenset(s for s in self.owned if s < count)
        self.num_shards = count
        return True

    def publish_resize(self, count: int) -> bool:
        """Coordinator-side split/merge: publish ``generation+1:<count>``.
        Only the shard-0 owner may call this (the rebalancer's tie-break);
        the change lands fleet-wide on the next refresh cadence — including
        on this replica, through the same ``_adopt_shard_map`` path."""
        if 0 not in self.owned or int(count) < 1:
            return False
        from ..fleet.resize import publish_shard_map, read_shard_map

        current = read_shard_map(self.api)
        gen = max(self.map_generation, current[0] if current is not None else 0) + 1
        return publish_shard_map(self.api, gen, int(count), self.lease_duration)

    def refresh(self) -> ShardDelta:
        """Renew owned shards, absorb orphans up to the proportional target,
        release the excess.  Deterministic: shards are visited in a rotated
        order starting at this identity's own hash, so concurrent replicas
        prefer disjoint orphans and the CAS settles the rest."""
        now = self.clock()
        # Presence first: visible to every other replica's target math even
        # while we hold nothing.
        self._acquire(REPLICA_LEASE_PREFIX + self.identity)
        resized = self._adopt_shard_map()
        holders = self._live_holders(now)
        n_replicas = self._live_replicas(now, holders)
        target = -(-self.num_shards // n_replicas)  # ceil
        prev = self.owned
        owned: set[int] = set()
        gained: set[int] = set()
        released: set[int] = set()
        start = shard_for_name(self.identity, self.num_shards)
        order = [(start + i) % self.num_shards for i in range(self.num_shards)]
        # Pass 1: renew what we already hold (never drop involuntarily —
        # losing a renewal CAS means another replica took it, which pass 2's
        # bookkeeping reports as lost).
        for s in order:
            if s in prev and self._acquire(shard_lease_name(s)):
                owned.add(s)
        # Pass 2: rebalance — release the excess above target (freshly
        # joined replicas pick them up next round) from the END of the
        # rotated order, so the shards a replica keeps are the ones nearest
        # its own hash (stable across rounds).
        if len(owned) > target:
            for s in reversed(order):
                if len(owned) <= target:
                    break
                if s in owned:
                    owned.discard(s)
                    released.add(s)
                    self._release(shard_lease_name(s))
        # Pass 3: absorb orphans (expired/released/never-created shards)
        # while under target.
        for s in order:
            if len(owned) >= target:
                break
            if s in owned or holders[s] not in ("", self.identity):
                continue
            if self._acquire(shard_lease_name(s)):
                owned.add(s)
                if s not in prev:
                    gained.add(s)
        self.owned = frozenset(owned)
        return ShardDelta(
            owned=self.owned,
            gained=frozenset(gained),
            lost=frozenset(prev - owned - released),
            released=frozenset(released),
            holders=holders,
            resized=resized,
        )

    def release_all(self) -> None:
        """Clean shutdown: hand every owned shard (and the presence lease)
        back so survivors absorb them immediately instead of waiting out the
        TTL."""
        for s in sorted(self.owned):
            self._release(shard_lease_name(s))
        self._release(REPLICA_LEASE_PREFIX + self.identity)
        self.owned = frozenset()

    def debug(self, now: float) -> dict:
        """The /debug/shards payload (read from the HTTP thread: every read
        below is a GIL-atomic snapshot of main-thread state — the
        resilience_snapshot stance)."""
        leases = {}
        for s in range(self.num_shards):
            info = self._get(shard_lease_name(s))
            leases[shard_lease_name(s)] = (
                None
                if info is None
                else {"holder": info["holder"], "expires_in_s": round(float(info.get("expires", 0.0)) - now, 3)}
            )
        out = {
            "replica_id": self.identity,
            "num_shards": self.num_shards,
            "owned": sorted(self.owned),
            "lease_duration_seconds": self.lease_duration,
            "leases": leases,
            "keyer": self.keyer.mode if self.keyer is not None else "hash",
            "map_generation": self.map_generation,
        }
        dm = getattr(self.keyer, "domain_map", None)
        if dm is not None:
            # Per-shard topology-domain + node-slice info (the fleet view
            # of /debug/shards — which racks each shard's columns span).
            out["shard_domains"] = {
                str(s): {"domains": list(dm.domains_of_shard(s)), "nodes": len(dm.shard_nodes[s])}
                for s in range(dm.num_shards)
            }
        return out
