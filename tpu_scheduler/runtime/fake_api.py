"""In-memory Kubernetes-style API server.

The reference can only run against a real cluster (``src/main.rs:130``,
``README.md:27-28``) and its API-dependent predicate was therefore untestable
(SURVEY.md §4 — the unused mockall deps).  This fake server delivers what the
reference merely *intended*: full watch/list/bind semantics in-process, so the
whole control loop is exercised by unit tests and synthetic benchmarks.

Capabilities (matching what the reference consumes from kube):
  • typed stores of Nodes and Pods with resourceVersion bookkeeping
  • watch streams with ADDED/MODIFIED/DELETED events and field selectors
    (``status.phase=Pending`` — main.rs:141-142; ``spec.nodeName=X`` —
    predicates.rs:22-26)
  • list with the same field selectors
  • the Binding subresource (main.rs:94-109): sets ``spec.nodeName``, flips
    phase to Running (standing in for the kubelet), 409s on conflicts
  • fault injection for the error paths (CreateBindingFailed → requeue)
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass, replace
from typing import Callable, Iterable

from ..api.objects import Node, ObjectReference, Pod, is_pod_bound
from ..errors import CreateBindingFailed

__all__ = ["ApiError", "WatchEvent", "Watch", "FakeApiServer"]


class ApiError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(f"{code}: {message}")
        self.code = code


@dataclass(frozen=True)
class WatchEvent:
    type: str  # ADDED | MODIFIED | DELETED
    object: Pod | Node


def _field_selector_fn(selector: str | None) -> Callable[[Pod | Node], bool]:
    """Supports the two k8s field-selector shapes the reference uses."""
    if not selector:
        return lambda obj: True

    clauses = []
    for part in selector.split(","):
        path, _, want = part.partition("=")
        path = path.strip()
        want = want.strip()
        if path == "status.phase":
            clauses.append(lambda o, w=want: getattr(o.status, "phase", None) == w)
        elif path == "spec.nodeName":
            clauses.append(lambda o, w=want: o.spec is not None and o.spec.node_name == w)
        elif path == "metadata.name":
            clauses.append(lambda o, w=want: o.metadata.name == w)
        else:
            raise ApiError(400, f"unsupported field selector {path!r}")
    return lambda obj: all(c(obj) for c in clauses)


class Watch:
    """A subscription to a kind's event stream (the reflector's feed)."""

    def __init__(self, server: "FakeApiServer", kind: str, selector: str | None):
        self._server = server
        self._kind = kind
        self._match = _field_selector_fn(selector)
        self._queue: deque[WatchEvent] = deque()

    def _offer(self, event: WatchEvent) -> None:
        if self._match(event.object):
            self._queue.append(event)

    def poll(self) -> list[WatchEvent]:
        """Drain currently-queued events (non-blocking)."""
        with self._server._lock:
            out = list(self._queue)
            self._queue.clear()
        return out

    def close(self) -> None:
        with self._server._lock:
            self._server._watches[self._kind].discard(self)


class FakeApiServer:
    def __init__(self):
        self._lock = threading.RLock()
        self._nodes: dict[str, Node] = {}
        self._pods: dict[tuple[str, str], Pod] = {}  # (namespace, name)
        self._rv = 0
        self._watches: dict[str, set[Watch]] = {"Node": set(), "Pod": set()}
        # Fault injection: number of upcoming binding calls to fail with 500.
        self.fail_next_bindings = 0
        self.binding_count = 0

    # -- internals ---------------------------------------------------------

    def _emit(self, kind: str, event: WatchEvent) -> None:
        for w in self._watches[kind]:
            w._offer(event)

    def _bump(self, obj: Pod | Node) -> None:
        self._rv += 1
        obj.metadata.resource_version = self._rv

    @staticmethod
    def _pod_key(pod: Pod) -> tuple[str, str]:
        return (pod.metadata.namespace or "default", pod.metadata.name)

    # -- nodes -------------------------------------------------------------

    def create_node(self, node: Node) -> None:
        with self._lock:
            if node.name in self._nodes:
                raise ApiError(409, f"node {node.name} exists")
            self._bump(node)
            self._nodes[node.name] = node
            self._emit("Node", WatchEvent("ADDED", node))

    def update_node(self, node: Node) -> None:
        with self._lock:
            if node.name not in self._nodes:
                raise ApiError(404, f"node {node.name} not found")
            self._bump(node)
            self._nodes[node.name] = node
            self._emit("Node", WatchEvent("MODIFIED", node))

    def delete_node(self, name: str) -> None:
        with self._lock:
            node = self._nodes.pop(name, None)
            if node is None:
                raise ApiError(404, f"node {name} not found")
            self._emit("Node", WatchEvent("DELETED", node))

    def list_nodes(self) -> list[Node]:
        with self._lock:
            return list(self._nodes.values())

    def watch_nodes(self, field_selector: str | None = None, send_initial: bool = True) -> Watch:
        with self._lock:
            w = Watch(self, "Node", field_selector)
            self._watches["Node"].add(w)
            if send_initial:
                for node in self._nodes.values():
                    w._offer(WatchEvent("ADDED", node))
            return w

    # -- pods --------------------------------------------------------------

    def create_pod(self, pod: Pod) -> None:
        with self._lock:
            key = self._pod_key(pod)
            if key in self._pods:
                raise ApiError(409, f"pod {key} exists")
            self._bump(pod)
            self._pods[key] = pod
            self._emit("Pod", WatchEvent("ADDED", pod))

    def delete_pod(self, namespace: str, name: str) -> None:
        with self._lock:
            pod = self._pods.pop((namespace, name), None)
            if pod is None:
                raise ApiError(404, f"pod {namespace}/{name} not found")
            self._emit("Pod", WatchEvent("DELETED", pod))

    def list_pods(self, field_selector: str | None = None) -> list[Pod]:
        match = _field_selector_fn(field_selector)
        with self._lock:
            return [p for p in self._pods.values() if match(p)]

    def watch_pods(self, field_selector: str | None = None, send_initial: bool = True) -> Watch:
        with self._lock:
            w = Watch(self, "Pod", field_selector)
            self._watches["Pod"].add(w)
            if send_initial:
                for pod in self._pods.values():
                    w._offer(WatchEvent("ADDED", pod))
            return w

    # -- binding subresource (main.rs:94-109) ------------------------------

    def create_binding(self, namespace: str, pod_name: str, target: ObjectReference) -> None:
        """POST /api/v1/namespaces/{ns}/pods/{name}/binding."""
        with self._lock:
            self.binding_count += 1
            if self.fail_next_bindings > 0:
                self.fail_next_bindings -= 1
                raise CreateBindingFailed(f"injected API failure binding {namespace}/{pod_name}")
            pod = self._pods.get((namespace, pod_name))
            if pod is None:
                raise ApiError(404, f"pod {namespace}/{pod_name} not found")
            if is_pod_bound(pod):
                raise ApiError(409, f"pod {namespace}/{pod_name} already bound")
            if target.name not in self._nodes:
                raise ApiError(404, f"node {target.name} not found")
            new_spec = replace(pod.spec, node_name=target.name) if pod.spec is not None else None
            if new_spec is None:
                from ..api.objects import PodSpec

                new_spec = PodSpec(node_name=target.name)
            bound = replace(pod, spec=new_spec, status=replace(pod.status, phase="Running"))
            self._bump(bound)
            self._pods[(namespace, pod_name)] = bound
            self._emit("Pod", WatchEvent("MODIFIED", bound))

    # -- bulk helpers for synthetic clusters -------------------------------

    def load(self, nodes: Iterable[Node] = (), pods: Iterable[Pod] = ()) -> None:
        for n in nodes:
            self.create_node(n)
        for p in pods:
            self.create_pod(p)
