"""In-memory Kubernetes-style API server.

The reference can only run against a real cluster (``src/main.rs:130``,
``README.md:27-28``) and its API-dependent predicate was therefore untestable
(SURVEY.md §4 — the unused mockall deps).  This fake server delivers what the
reference merely *intended*: full watch/list/bind semantics in-process, so the
whole control loop is exercised by unit tests and synthetic benchmarks.

Capabilities (matching what the reference consumes from kube):
  • typed stores of Nodes and Pods with resourceVersion bookkeeping
  • watch streams with ADDED/MODIFIED/DELETED events and field selectors
    (``status.phase=Pending`` — main.rs:141-142; ``spec.nodeName=X`` —
    predicates.rs:22-26)
  • list with the same field selectors
  • the Binding subresource (main.rs:94-109): sets ``spec.nodeName``, flips
    phase to Running (standing in for the kubelet), 409s on conflicts
  • fault injection for the error paths (CreateBindingFailed → requeue)
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from dataclasses import dataclass, replace
from typing import Callable, Iterable

from ..api.objects import Node, ObjectReference, Pod, is_pod_bound
from ..errors import CreateBindingFailed

__all__ = ["ApiError", "WatchEvent", "Watch", "FakeApiServer"]


class ApiError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(f"{code}: {message}")
        self.code = code


@dataclass(frozen=True)
class WatchEvent:
    type: str  # ADDED | MODIFIED | DELETED
    object: Pod | Node


def _evolve(obj, **changes):
    """``dataclasses.replace`` for the binding hot path: a shallow
    ``__dict__`` copy plus the changed fields — same replace-don't-mutate
    result (a NEW object, the old one untouched) without re-walking every
    field through getattr/__init__.  Safe here because these API objects
    are plain dataclasses with no __post_init__/InitVar logic."""
    new = object.__new__(type(obj))
    new.__dict__.update(obj.__dict__)
    new.__dict__.update(changes)
    return new


def _field_selector_fn(selector: str | None) -> Callable[[Pod | Node], bool]:
    """Supports the two k8s field-selector shapes the reference uses."""
    if not selector:
        return lambda obj: True

    clauses = []
    for part in selector.split(","):
        path, _, want = part.partition("=")
        path = path.strip()
        want = want.strip()
        if path == "status.phase":
            clauses.append(lambda o, w=want: getattr(o.status, "phase", None) == w)
        elif path == "spec.nodeName":
            clauses.append(lambda o, w=want: o.spec is not None and o.spec.node_name == w)
        elif path == "metadata.name":
            clauses.append(lambda o, w=want: o.metadata.name == w)
        else:
            raise ApiError(400, f"unsupported field selector {path!r}")
    return lambda obj: all(c(obj) for c in clauses)


class Watch:
    """A subscription to a kind's event stream (the reflector's feed)."""

    def __init__(self, server: "FakeApiServer", kind: str, selector: str | None):
        self._server = server
        self._kind = kind
        self._match = _field_selector_fn(selector)
        self._queue: deque[WatchEvent] = deque()  # guarded-by: _server._lock

    def _offer(self, event: WatchEvent) -> None:  # holds-lock: _server._lock
        if self._match(event.object):
            self._queue.append(event)

    def poll(self) -> list[WatchEvent]:
        """Drain currently-queued events (non-blocking)."""
        with self._server._lock:
            out = list(self._queue)
            self._queue.clear()
        return out

    def close(self) -> None:
        with self._server._lock:
            self._server._watches[self._kind].discard(self)


class FakeApiServer:
    def __init__(self, watch_history: int = 1 << 18, clock=None):
        self._clock = clock or time.monotonic
        self._lock = threading.RLock()
        self._nodes: dict[str, Node] = {}  # guarded-by: _lock
        self._pods: dict[tuple[str, str], Pod] = {}  # guarded-by: _lock — (namespace, name)
        self._pdbs: dict[str, object] = {}  # guarded-by: _lock — "ns/name" -> PodDisruptionBudget
        self._rv = 0  # guarded-by: _lock
        self._watches: dict[str, set[Watch]] = {"Node": set(), "Pod": set()}  # guarded-by: _lock
        # Bounded event history for resourceVersion-based incremental watch
        # (the HTTP boundary's ``?watch=true&resourceVersion=N`` long-poll):
        # (rv, kind, event, prev_object), rv strictly increasing.  A list
        # (not a deque) so watch_since can bisect straight to the suffix
        # after rv — O(log n + delta) per poll, not O(history).  A client
        # whose rv has been evicted gets 410 Gone and relists — the kube
        # watch-cache contract.
        self._events_log: list[tuple[int, str, WatchEvent, Pod | Node | None]] = []  # guarded-by: _lock
        self._watch_history = watch_history
        self._events_cv = threading.Condition(self._lock)
        # Leader-election Leases (coordination.k8s.io/v1): (namespace, name)
        # -> kube-shaped Lease dict.  The server only stores and CASes on
        # metadata.resourceVersion; leadership is decided CLIENT-side from
        # spec.renewTime + leaseDurationSeconds (client-go semantics,
        # runtime/lease.py).
        self._leases: dict[tuple[str, str], dict] = {}  # guarded-by: _lock
        # Every lease WRITE in commit order: (name, holderIdentity-after) —
        # "" marks a release.  The renew-vs-release shutdown race regression
        # test reads this to prove no renewal lands after the release.
        self.lease_history: list[tuple[str, str]] = []  # guarded-by: _lock
        # Fault injection: number of upcoming binding calls to fail with 500.
        self.fail_next_bindings = 0
        self.binding_count = 0

    # -- internals ---------------------------------------------------------

    def _emit(self, kind: str, event: WatchEvent, prev: Pod | Node | None = None, rv: int | None = None) -> None:  # holds-lock: _lock
        if rv is None:
            rv = event.object.metadata.resource_version or self._rv
        self._events_log.append((rv, kind, event, prev))
        if len(self._events_log) >= 2 * self._watch_history:
            # Trim in halves — amortized O(1) per append.
            del self._events_log[: len(self._events_log) - self._watch_history]
        for w in self._watches[kind]:
            w._offer(event)
        self._events_cv.notify_all()

    def _bump(self, obj: Pod | Node) -> None:  # holds-lock: _lock
        self._rv += 1
        obj.metadata.resource_version = self._rv

    @property
    def latest_rv(self) -> int:
        with self._lock:
            return self._rv

    def watch_since(
        self, kind: str, rv: int, field_selector: str | None = None, timeout: float = 0.0
    ) -> tuple[list[WatchEvent], int]:
        """Events of ``kind`` with resourceVersion > ``rv`` (the incremental
        watch the reference's kube watcher provides, ``main.rs:135``).

        Long-polls up to ``timeout`` seconds when nothing is pending.  An
        object whose update leaves the field selector emits DELETED (kube
        semantics).  Raises ``ApiError(410)`` when ``rv`` predates the
        retained history — the client's cue to relist.
        """
        import bisect

        match = _field_selector_fn(field_selector)
        deadline = time.monotonic() + timeout
        with self._events_cv:
            while True:
                oldest = self._events_log[0][0] if self._events_log else self._rv + 1
                if rv < oldest - 1:
                    raise ApiError(410, f"resourceVersion {rv} too old (oldest retained {oldest - 1})")
                start = bisect.bisect_right(self._events_log, rv, key=lambda e: e[0])
                out: list[WatchEvent] = []
                for erv, k, ev, prev in self._events_log[start:]:
                    if k != kind:
                        continue
                    if match(ev.object):
                        out.append(ev)
                    elif prev is not None and match(prev):
                        out.append(WatchEvent("DELETED", ev.object))
                if out or timeout <= 0:
                    return out, self._rv
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return [], self._rv
                self._events_cv.wait(remaining)

    @staticmethod
    def _pod_key(pod: Pod) -> tuple[str, str]:
        return (pod.metadata.namespace or "default", pod.metadata.name)

    # -- nodes -------------------------------------------------------------

    def create_node(self, node: Node) -> None:
        with self._lock:
            if node.name in self._nodes:
                raise ApiError(409, f"node {node.name} exists")
            self._bump(node)
            self._nodes[node.name] = node
            self._emit("Node", WatchEvent("ADDED", node))

    def update_node(self, node: Node) -> None:
        with self._lock:
            prev = self._nodes.get(node.name)
            if prev is None:
                raise ApiError(404, f"node {node.name} not found")
            self._bump(node)
            self._nodes[node.name] = node
            self._emit("Node", WatchEvent("MODIFIED", node), prev=prev)

    def delete_node(self, name: str) -> None:
        with self._lock:
            node = self._nodes.pop(name, None)
            if node is None:
                raise ApiError(404, f"node {name} not found")
            self._rv += 1  # deletion is an rv-advancing event (kube semantics)
            self._emit("Node", WatchEvent("DELETED", node), rv=self._rv)

    def list_nodes(self) -> list[Node]:
        with self._lock:
            return list(self._nodes.values())

    def list_nodes_with_rv(self) -> tuple[list[Node], int]:
        """(nodes, resourceVersion) taken atomically — the watch-start token
        a lister needs: events after this rv are exactly what the list
        doesn't already reflect."""
        with self._lock:
            return list(self._nodes.values()), self._rv

    def watch_nodes(self, field_selector: str | None = None, send_initial: bool = True) -> Watch:
        with self._lock:
            w = Watch(self, "Node", field_selector)
            self._watches["Node"].add(w)
            if send_initial:
                for node in self._nodes.values():
                    w._offer(WatchEvent("ADDED", node))
            return w

    # -- pods --------------------------------------------------------------

    def create_pod(self, pod: Pod) -> None:
        with self._lock:
            key = self._pod_key(pod)
            if key in self._pods:
                raise ApiError(409, f"pod {key} exists")
            self._bump(pod)
            self._pods[key] = pod
            self._emit("Pod", WatchEvent("ADDED", pod))

    def delete_pod(self, namespace: str, name: str) -> None:
        with self._lock:
            pod = self._pods.pop((namespace, name), None)
            if pod is None:
                raise ApiError(404, f"pod {namespace}/{name} not found")
            self._rv += 1  # deletion is an rv-advancing event (kube semantics)
            self._emit("Pod", WatchEvent("DELETED", pod), rv=self._rv)

    def list_pods(self, field_selector: str | None = None) -> list[Pod]:
        match = _field_selector_fn(field_selector)
        with self._lock:
            return [p for p in self._pods.values() if match(p)]

    def list_pods_with_rv(self, field_selector: str | None = None) -> tuple[list[Pod], int]:
        """(pods, resourceVersion) taken atomically (see list_nodes_with_rv)."""
        match = _field_selector_fn(field_selector)
        with self._lock:
            return [p for p in self._pods.values() if match(p)], self._rv

    def watch_pods(self, field_selector: str | None = None, send_initial: bool = True) -> Watch:
        with self._lock:
            w = Watch(self, "Pod", field_selector)
            self._watches["Pod"].add(w)
            if send_initial:
                for pod in self._pods.values():
                    w._offer(WatchEvent("ADDED", pod))
            return w

    # -- binding subresource (main.rs:94-109) ------------------------------

    def create_binding(self, namespace: str, pod_name: str, target: ObjectReference) -> None:
        """POST /api/v1/namespaces/{ns}/pods/{name}/binding.

        Hot path of the e2e cycle: a 100k-pod wave issues 100k of these, so
        the object evolution uses ``_evolve`` (a ``__dict__``-copy twin of
        ``dataclasses.replace``, ~10x faster — replace re-walks every field
        via getattr) while keeping the replace-don't-mutate contract the
        identity-keyed pack memos rely on."""
        with self._lock:
            self.binding_count += 1
            if self.fail_next_bindings > 0:
                self.fail_next_bindings -= 1
                raise CreateBindingFailed(f"injected API failure binding {namespace}/{pod_name}")
            pod = self._pods.get((namespace, pod_name))
            if pod is None:
                raise ApiError(404, f"pod {namespace}/{pod_name} not found")
            if is_pod_bound(pod):
                raise ApiError(409, f"pod {namespace}/{pod_name} already bound")
            if target.name not in self._nodes:
                raise ApiError(404, f"node {target.name} not found")
            if pod.spec is not None:
                new_spec = _evolve(pod.spec, node_name=target.name)
            else:
                from ..api.objects import PodSpec

                new_spec = PodSpec(node_name=target.name)
            bound = _evolve(pod, spec=new_spec, status=_evolve(pod.status, phase="Running"))
            self._bump(bound)
            self._pods[(namespace, pod_name)] = bound
            self._emit("Pod", WatchEvent("MODIFIED", bound), prev=pod)

    def unbind_pod(self, namespace: str, pod_name: str, expect_node: str | None = None) -> None:
        """Deschedule: clear ``spec.nodeName`` and return the pod to
        Pending in ONE atomic call (the rebalancer's migration seam — a
        crash leaves the pod either bound or pending, never lost).

        ``expect_node`` is a CAS guard: when given, the pod must currently
        be bound to exactly that node or the call 409s — a stale migration
        plan can never deschedule a pod that already moved."""
        with self._lock:
            pod = self._pods.get((namespace, pod_name))
            if pod is None:
                raise ApiError(404, f"pod {namespace}/{pod_name} not found")
            if not is_pod_bound(pod):
                raise ApiError(409, f"pod {namespace}/{pod_name} is not bound")
            if expect_node is not None and pod.spec.node_name != expect_node:
                raise ApiError(
                    409, f"pod {namespace}/{pod_name} is bound to {pod.spec.node_name}, not {expect_node}"
                )
            unbound = _evolve(pod, spec=_evolve(pod.spec, node_name=None), status=_evolve(pod.status, phase="Pending"))
            self._bump(unbound)
            self._pods[(namespace, pod_name)] = unbound
            self._emit("Pod", WatchEvent("MODIFIED", unbound), prev=pod)

    # -- leader election (coordination.k8s.io/v1 Lease objects) ------------
    #
    # Spec-shaped primitives with resourceVersion compare-and-swap — the
    # contract a real kube-apiserver serves — plus acquire/release helpers
    # running the client-go election algorithm (runtime/lease.py) over them,
    # so the in-process path and the HTTP path execute the same recipe.

    def get_lease_object(self, namespace: str, name: str) -> dict | None:
        with self._lock:
            lease = self._leases.get((namespace, name))
            return json.loads(json.dumps(lease)) if lease is not None else None

    def create_lease_object(self, namespace: str, name: str, lease: dict) -> dict:
        with self._lock:
            if (namespace, name) in self._leases:
                raise ApiError(409, f"lease {namespace}/{name} already exists")
            self._rv += 1
            stored = {**lease, "metadata": {**lease.get("metadata", {}), "name": name, "namespace": namespace, "resourceVersion": str(self._rv)}}
            self._leases[(namespace, name)] = stored
            self.lease_history.append((name, (stored.get("spec") or {}).get("holderIdentity") or ""))
            return json.loads(json.dumps(stored))

    def update_lease_object(self, namespace: str, name: str, lease: dict) -> dict:
        """PUT with optimistic concurrency: the submitted
        metadata.resourceVersion must equal the stored one or 409 — the CAS
        every leader-election race resolves through."""
        with self._lock:
            cur = self._leases.get((namespace, name))
            if cur is None:
                raise ApiError(404, f"lease {namespace}/{name} not found")
            sent_rv = str((lease.get("metadata") or {}).get("resourceVersion") or "")
            if sent_rv != str(cur["metadata"]["resourceVersion"]):
                raise ApiError(409, f"lease {namespace}/{name} conflict: resourceVersion {sent_rv} is stale")
            self._rv += 1
            stored = {**lease, "metadata": {**lease["metadata"], "name": name, "namespace": namespace, "resourceVersion": str(self._rv)}}
            self._leases[(namespace, name)] = stored
            self.lease_history.append((name, (stored.get("spec") or {}).get("holderIdentity") or ""))
            return json.loads(json.dumps(stored))

    def acquire_lease(self, name: str, holder: str, duration_seconds: float) -> bool:
        """One election round per the client-go algorithm: create if absent,
        renew if ours, take over if expired/released; conflicts mean a lost
        race (kube leader-election semantics, server holds no verbs)."""
        from . import lease as lease_mod

        def _create(obj):
            try:
                self.create_lease_object(lease_mod.LEASE_NAMESPACE, name, obj)
                return True
            except ApiError:
                return False

        def _update(obj):
            try:
                self.update_lease_object(lease_mod.LEASE_NAMESPACE, name, obj)
                return True
            except ApiError:
                return False

        # The whole round runs under the store lock (re-entrant), so an
        # in-process renewal thread and main loop for the SAME holder never
        # read each other's CAS as a lost election; cross-process races
        # still resolve through the resourceVersion conflict.
        with self._lock:
            return lease_mod.try_acquire_or_renew(
                lambda: self.get_lease_object(lease_mod.LEASE_NAMESPACE, name),
                _create,
                _update,
                lease_mod.LEASE_NAMESPACE,
                name,
                holder,
                duration_seconds,
                self._clock(),
            )

    def release_lease(self, name: str, holder: str) -> None:
        """Voluntary hand-off (clean shutdown): only the holder may release."""
        from . import lease as lease_mod

        def _update(obj):
            try:
                self.update_lease_object(lease_mod.LEASE_NAMESPACE, name, obj)
                return True
            except ApiError:
                return False

        with self._lock:
            lease_mod.release(
                lambda: self.get_lease_object(lease_mod.LEASE_NAMESPACE, name), _update, holder, self._clock()
            )

    def get_lease(self, name: str) -> dict | None:
        """Back-compat summary view: {'holder', 'expires'} or None."""
        from . import lease as lease_mod

        obj = self.get_lease_object(lease_mod.LEASE_NAMESPACE, name)
        if obj is None:
            return None
        spec = obj.get("spec") or {}
        holder = spec.get("holderIdentity") or ""
        if not holder:
            return None
        renew = lease_mod.parse_micro_time(spec.get("renewTime")) or 0.0
        return {"holder": holder, "expires": renew + float(spec.get("leaseDurationSeconds") or 0)}

    def list_lease_summaries(self) -> list[dict]:
        """{'name', 'holder', 'expires'} per Lease in the election namespace,
        name-sorted — the sharded control plane's replica-presence scan
        (runtime/shards.py); '' holder entries (released leases) included so
        callers judge liveness themselves."""
        from . import lease as lease_mod

        with self._lock:
            keys = sorted(k for k in self._leases if k[0] == lease_mod.LEASE_NAMESPACE)
        out = []
        for _ns, name in keys:
            spec = (self.get_lease_object(lease_mod.LEASE_NAMESPACE, name) or {}).get("spec") or {}
            renew = lease_mod.parse_micro_time(spec.get("renewTime")) or 0.0
            out.append(
                {
                    "name": name,
                    "holder": spec.get("holderIdentity") or "",
                    "expires": renew + float(spec.get("leaseDurationSeconds") or 0),
                }
            )
        return out

    # -- PodDisruptionBudgets (policy/v1 subset; consulted by preemption) --

    def create_pdb(self, pdb) -> None:
        with self._lock:
            key = f"{pdb.metadata.namespace or 'default'}/{pdb.metadata.name}"
            self._pdbs[key] = pdb

    def delete_pdb(self, namespace: str, name: str) -> None:
        with self._lock:
            self._pdbs.pop(f"{namespace}/{name}", None)

    def list_pdbs(self) -> list:
        with self._lock:
            return list(self._pdbs.values())

    # -- bulk helpers for synthetic clusters -------------------------------

    def load(self, nodes: Iterable[Node] = (), pods: Iterable[Pod] = (), pdbs: Iterable = ()) -> None:
        for n in nodes:
            self.create_node(n)
        for p in pods:
            self.create_pod(p)
        for b in pdbs:
            self.create_pdb(b)
